#!/usr/bin/env python
"""Headline benchmark: the BASELINE.md north star.

50,000 pending pods vs 20,000 simulated nodes (heterogeneous capacities,
extended resources, taints/tolerations — BASELINE config-4 shape at north-star
scale), full filter+score+sequential-commit with exact one-pod-at-a-time
semantics.

Reported (stderr) and embedded in the JSON line:
  encode_s      cold full snapshot encode (host)
  delta_s       median warm-cycle re-encode through the resident
                DeltaEncoder; every cycle absorbs ~50k binds + ~50k
                completions (deletes), the sustainable steady state
  step_s        device step, steady state (best of 3)
  end_to_end_s  median over 3 warm cycles of (delta + step) — the
                north-star "<1 s wall-clock" metric; end_to_end_worst_s
                and the per-cycle list expose the variance

vs_baseline's denominator is THIS REPO'S OWN CPU MODE on the same workload
shape (heterogeneous, measured at a 1,000-pod x 2,000-node sample:
3.8 pods/s, p50 251 ms/pod — bench/harness.py --mode cpu), per the round-2
verdict: the folklore 300 pods/s was never measured here.  The reference-
folklore comparison is still embedded as vs_reference_folklore (value/300,
upstream scheduler_perf lore — BASELINE.md has no published fork table).
The honest end-to-end number is end_to_end_pods_per_sec, also embedded.

Prints exactly one JSON line on stdout.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
import traceback

# KTPU_BENCH_* override the scale for smoke runs only — the driver always
# runs the defaults (the artifact embeds the actual N/P via meta)
N_NODES = int(os.environ.get("KTPU_BENCH_NODES", 20_000))
N_PODS = int(os.environ.get("KTPU_BENCH_PODS", 50_000))
# this repo's own CPU-mode throughput on the heterogeneous shape (see above)
BASELINE_PODS_PER_SEC = 3.8
REFERENCE_FOLKLORE_PODS_PER_SEC = 300.0


def _probe_backend(timeout_s: float = 45.0, retries: int = 3,
                   wait_s: float = 15.0) -> str:
    """Name of the accelerator backend, or "" when only CPU is reachable.

    The probe runs in a SUBPROCESS with a hard timeout because a downed
    axon tunnel makes jax.devices() HANG indefinitely rather than raise
    (observed in rounds 3 and 4) — an in-process attempt would turn the
    driver's benchmark run into a wedged process instead of an artifact.
    Bounded retry (~3 tries over ~2 min) before falling back, per the
    round-3 verdict: the artifact must never be empty again."""
    for attempt in range(1, retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('BACKEND=' + jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            for line in r.stdout.splitlines():
                if line.startswith("BACKEND="):
                    backend = line.split("=", 1)[1].strip()
                    if backend == "cpu":
                        # a DEFINITIVE healthy answer: this machine simply
                        # has no accelerator.  The retry loop exists for
                        # the hang/timeout failure mode only.
                        return ""
                    if backend:
                        return backend
        except subprocess.TimeoutExpired:
            pass
        print(f"backend probe {attempt}/{retries}: no accelerator "
              f"(timeout {timeout_s}s)", file=sys.stderr)
        if attempt < retries:
            time.sleep(wait_s)
    return ""


def main() -> None:
    backend = _probe_backend()
    if not backend:
        # labeled CPU-sim fallback (the one shared sitecustomize-defeating
        # helper — bench/_cpu.py).  Also force the CHUNKED routing so the
        # fallback validates the PRODUCTION TPU route (compile + decisions
        # at full scale), not the plain scan that would never run on TPU
        # (round-4 verdict weak #3); read at trace time, so setting it
        # before the first jit call suffices.
        from kubernetes_tpu.bench._cpu import force_cpu_from_env

        force_cpu_from_env(always=True)
        os.environ.setdefault("KTPU_FORCE_CHUNKED", "1")
        platform = "cpu-sim-fallback"
    import jax

    if backend:
        platform = backend

    from kubernetes_tpu.api.delta import DeltaEncoder
    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.bench.workloads import heterogeneous
    from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config, schedule_batch

    print(f"platform: {platform}  devices: {jax.devices()}", file=sys.stderr)
    snap = heterogeneous(N_NODES, N_PODS, seed=0)
    enc = DeltaEncoder()

    t0 = time.perf_counter()
    arr, meta = enc.encode_device(snap)
    t_encode = time.perf_counter() - t0
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    print(f"encode (cold full): {t_encode:.3f}s  N={arr.N} P={arr.P} R={arr.R}",
          file=sys.stderr)

    import numpy as np

    # warmup / compile.  NOTE: block_until_ready can return early through the
    # axon TPU tunnel, so timing forces a (tiny) host transfer of the choices
    # vector — which is also what a real sidecar client would consume.
    t0 = time.perf_counter()
    choices = np.asarray(schedule_batch(arr, cfg)[0])
    print(f"compile+first run: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    t_step = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        choices = np.asarray(schedule_batch(arr, cfg)[0])
        t_step = min(t_step, time.perf_counter() - t0)

    # the pre-chunking per-pod scan, for the delta the chunked path buys
    # (ops/assign.py — schedule_scan_chunked vs schedule_scan).  Skipped on
    # the CPU fallback: the chunked path doesn't route there, so the
    # comparison is vacuous and costs three extra full-scale runs.
    t_plain = None
    if backend:
        from kubernetes_tpu.ops.assign import schedule_scan as _plain

        plain = jax.jit(_plain, static_argnames=("cfg",))
        t_plain = float("inf")
        np.asarray(plain(arr, cfg)[0])  # compile
        for _ in range(2):
            t0 = time.perf_counter()
            np.asarray(plain(arr, cfg)[0])
            t_plain = min(t_plain, time.perf_counter() - t0)
        print(f"per-pod (unchunked) scan step: {t_plain*1e3:.1f}ms",
              file=sys.stderr)

    # warm-cluster steady state, THREE full cycles: each cycle the previous
    # wave's pods are bound, the wave before THAT completes (its bound pods
    # leave the cluster — sustainable forever, like real churn), and a fresh
    # 50k wave arrives.  Every cycle therefore absorbs ~50k binds + ~50k
    # deletes through the resident encoder and re-runs the device step —
    # median over cycles is the honest steady-state number (the round-2
    # verdict flagged the previous single-sample measurement).
    def place(prev_snap, prev_meta, prev_choices):
        return [
            dataclasses.replace(p, node_name=prev_meta.node_names[int(c)])
            for p, c in zip(
                (prev_snap.pending_pods[i] for i in prev_meta.pod_perm),
                prev_choices[: prev_meta.n_pods],
            )
            if int(c) >= 0
        ]

    cycles = []
    prev = (snap, meta, choices)
    for w in range(2, 5):
        bound = place(*prev)  # previous wave bound; earlier waves completed
        wave = [
            dataclasses.replace(p, name=f"w{w}-{p.name}", uid="")
            for p in snap.pending_pods
        ]
        snapw = Snapshot(nodes=snap.nodes, pending_pods=wave, bound_pods=bound)
        t0 = time.perf_counter()
        arrw, metaw = enc.encode_device(snapw)
        t_delta = time.perf_counter() - t0
        assert enc.stats["delta"] >= w - 1, f"delta path did not engage: {enc.stats}"
        t0 = time.perf_counter()
        choicesw = np.asarray(schedule_batch(arrw, cfg)[0])
        t_stepw = time.perf_counter() - t0
        cycles.append((t_delta, t_stepw))
        prev = (snapw, metaw, choicesw)

    scheduled = int((choices[: meta.n_pods] >= 0).sum())
    e2es = sorted(d + s for d, s in cycles)
    end_to_end = e2es[len(e2es) // 2]  # median cycle
    t_delta = sorted(d for d, _ in cycles)[len(cycles) // 2]
    t_step2 = sorted(s for _, s in cycles)[len(cycles) // 2]
    pods_per_sec = meta.n_pods / t_step
    e2e_pods_per_sec = meta.n_pods / end_to_end
    print(
        f"step: {t_step*1e3:.1f}ms  scheduled {scheduled}/{meta.n_pods}\n"
        f"warm cycles (delta_s, step_s): "
        + ", ".join(f"({d:.3f}, {s:.3f})" for d, s in cycles)
        + f"\nsteady state (median): delta-encode {t_delta*1e3:.1f}ms + step "
        f"{t_step2*1e3:.1f}ms; end-to-end median {end_to_end*1e3:.1f}ms, "
        f"worst {e2es[-1]*1e3:.1f}ms "
        f"({'PASS' if end_to_end < 1.0 else 'FAIL'} <1s north star)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "north_star_50kpods_20knodes_throughput",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "n_nodes": N_NODES,
                "n_pods": N_PODS,
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
                "platform": platform,
                "baseline_pods_per_sec": BASELINE_PODS_PER_SEC,
                "baseline_source": "own cpu-mode, heterogeneous 1000x2000 sample",
                "vs_reference_folklore": round(
                    pods_per_sec / REFERENCE_FOLKLORE_PODS_PER_SEC, 2
                ),
                "encode_s": round(t_encode, 3),
                "delta_s": round(t_delta, 3),
                "step_s": round(t_step, 4),
                "step_unchunked_s": (
                    round(t_plain, 4) if t_plain is not None else None
                ),
                "end_to_end_s": round(end_to_end, 3),
                "end_to_end_worst_s": round(e2es[-1], 3),
                "cycles": [[round(d, 3), round(s, 3)] for d, s in cycles],
                "end_to_end_pods_per_sec": round(e2e_pods_per_sec, 1),
                "scheduled": scheduled,
                # which kernel the routed call actually compiled (trace-time
                # proof; the fallback must exercise the production route)
                "route_trace_counts": dict(_trace_counts()),
            }
        )
    )


def _trace_counts():
    from kubernetes_tpu.ops.assign import TRACE_COUNTS

    return TRACE_COUNTS


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — the driver artifact must
        # never be an empty rc!=0 run again (round-3 verdict missing #2):
        # whatever happens, emit ONE schema-shaped JSON line and exit 0.
        if isinstance(e, (KeyboardInterrupt, SystemExit)) and not (
            isinstance(e, SystemExit) and e.code
        ):
            raise
        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "north_star_50kpods_20knodes_throughput",
                    "value": 0.0,
                    "unit": "pods/s",
                    "vs_baseline": 0.0,
                    "platform": "error",
                    "error": repr(e),
                }
            )
        )
        sys.exit(0)
