#!/usr/bin/env python
"""Headline benchmark: the BASELINE.md north star.

50,000 pending pods vs 20,000 simulated nodes (heterogeneous capacities,
extended resources, taints/tolerations — BASELINE config-4 shape at north-star
scale), full filter+score+sequential-commit with exact one-pod-at-a-time
semantics.

Reported (stderr) and embedded in the JSON line:
  encode_s      cold full snapshot encode (host)
  delta_s       median warm-cycle re-encode through the resident
                DeltaEncoder; every cycle absorbs ~50k binds + ~50k
                completions (deletes), the sustainable steady state
  step_s        device step, steady state (best of 3)
  end_to_end_s  median over 3 warm cycles of (delta + step) — the
                north-star "<1 s wall-clock" metric; end_to_end_worst_s
                and the per-cycle list expose the variance

vs_baseline's denominator is THIS REPO'S OWN CPU MODE on the same workload
shape (heterogeneous, measured at a 1,000-pod x 2,000-node sample:
3.8 pods/s, p50 251 ms/pod — bench/harness.py --mode cpu), per the round-2
verdict: the folklore 300 pods/s was never measured here.  The reference-
folklore comparison is still embedded as vs_reference_folklore (value/300,
upstream scheduler_perf lore — BASELINE.md has no published fork table).
The honest end-to-end number is end_to_end_pods_per_sec, also embedded.

Prints exactly one JSON line on stdout.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
import traceback

# KTPU_BENCH_* override the scale for smoke runs only — the driver always
# runs the defaults (the artifact embeds the actual N/P via meta)
N_NODES = int(os.environ.get("KTPU_BENCH_NODES", 20_000))
N_PODS = int(os.environ.get("KTPU_BENCH_PODS", 50_000))
# this repo's own CPU-mode throughput on the heterogeneous shape (see above)
BASELINE_PODS_PER_SEC = 3.8
REFERENCE_FOLKLORE_PODS_PER_SEC = 300.0


def _probe_backend(timeout_s: float = 45.0, retries: int = 3,
                   wait_s: float = 15.0) -> str:
    """Name of the accelerator backend, or "" when only CPU is reachable.

    The probe runs in a SUBPROCESS with a hard timeout because a downed
    axon tunnel makes jax.devices() HANG indefinitely rather than raise
    (observed in rounds 3 and 4) — an in-process attempt would turn the
    driver's benchmark run into a wedged process instead of an artifact.
    Bounded retry (~3 tries over ~2 min) before falling back, per the
    round-3 verdict: the artifact must never be empty again."""
    for attempt in range(1, retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('BACKEND=' + jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            for line in r.stdout.splitlines():
                if line.startswith("BACKEND="):
                    backend = line.split("=", 1)[1].strip()
                    if backend == "cpu":
                        # a DEFINITIVE healthy answer: this machine simply
                        # has no accelerator.  The retry loop exists for
                        # the hang/timeout failure mode only.
                        return ""
                    if backend:
                        return backend
        except subprocess.TimeoutExpired:
            pass
        print(f"backend probe {attempt}/{retries}: no accelerator "
              f"(timeout {timeout_s}s)", file=sys.stderr)
        if attempt < retries:
            time.sleep(wait_s)
    return ""


def main() -> None:
    backend = _probe_backend()
    if not backend:
        # labeled CPU-sim fallback (the one shared sitecustomize-defeating
        # helper — bench/_cpu.py).  Also force the CHUNKED routing so the
        # fallback validates the PRODUCTION TPU route (compile + decisions
        # at full scale), not the plain scan that would never run on TPU
        # (round-4 verdict weak #3); read at trace time, so setting it
        # before the first jit call suffices.
        # KTPU_MESH / KTPU_MESH_PODS on the CPU fallback need that many
        # VIRTUAL host devices, and the flag must precede first backend
        # use.  meshreq is the ONE import-light kubernetes_tpu module: with
        # KTPU_COMPILE_CACHE_DIR set, importing almost anything else
        # (parallel.mesh included) initializes the backend as an import
        # side effect — before this flag could take hold
        from kubernetes_tpu.meshreq import (
            mesh_request_devices,
            parse_mesh_request,
        )

        try:
            mesh_req = mesh_request_devices(parse_mesh_request())
        except ValueError:
            mesh_req = 1
        if mesh_req > 1:
            parts = [
                f for f in os.environ.get("XLA_FLAGS", "").split()
                if "xla_force_host_platform_device_count" not in f
            ]
            parts.append(
                f"--xla_force_host_platform_device_count={mesh_req}"
            )
            os.environ["XLA_FLAGS"] = " ".join(parts)
        from kubernetes_tpu.bench._cpu import force_cpu_from_env

        force_cpu_from_env(always=True)
        os.environ.setdefault("KTPU_FORCE_CHUNKED", "1")
        platform = "cpu-sim-fallback"
    import jax

    if backend:
        platform = backend

    from kubernetes_tpu.api.delta import DeltaEncoder
    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.bench.workloads import heterogeneous
    from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from kubernetes_tpu.ops.aot import maybe_enable_compile_cache
    from kubernetes_tpu.ops.assign import (
        donation_supported,
        schedule_batch_routed,
    )
    from kubernetes_tpu.scheduler.metrics import Metrics, reset_run_state
    from kubernetes_tpu.scheduler.tracing import TraceCollector, Tracer

    # the run-start reset hook (route counters + metrics + collector in one
    # call): the artifact must describe THIS run even when bench runs
    # back-to-back in one process
    metrics = Metrics()
    collector = TraceCollector()
    reset_run_state(metrics=metrics, collector=collector)
    if os.environ.get("KTPU_METRICS"):
        # serve this run's registry for the duration (scheduler/apiserver.py)
        from kubernetes_tpu.scheduler.apiserver import MetricsServer

        try:
            _mport = int(os.environ["KTPU_METRICS"])
        except ValueError:
            _mport = 0
        srv = MetricsServer(metrics.expose_text, port=_mport)
        print(f"metrics: http://127.0.0.1:{srv.start()}/metrics",
              file=sys.stderr)

    # persistent XLA compile cache (KTPU_COMPILE_CACHE_DIR): the first
    # process pays the cold compile; every later one loads the executable
    cache_dir = maybe_enable_compile_cache()
    don = donation_supported()
    # KTPU_MESH=<n>: run the routed north-star step node-axis sharded over
    # n chips (parallel/sharded.py); the encoder places resident buffers
    # shard-wise so warm deltas update shards in place
    from kubernetes_tpu.parallel.mesh import (
        mesh_axis_shards,
        mesh_from_env,
        shard_hbm_estimate,
    )

    mesh = mesh_from_env()
    n_shards = int(mesh.size) if mesh is not None else 1
    pod_shards, node_shards = mesh_axis_shards(mesh)
    print(f"platform: {platform}  devices: {jax.devices()}", file=sys.stderr)
    if mesh is not None:
        print(f"mesh: {dict(mesh.shape)}", file=sys.stderr)
    if cache_dir:
        print(f"compile cache: {cache_dir}", file=sys.stderr)
    snap = heterogeneous(N_NODES, N_PODS, seed=0)
    enc = DeltaEncoder(mesh=mesh)

    t0 = time.perf_counter()
    arr, meta = enc.encode(snap)
    t_encode = time.perf_counter() - t0
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    print(f"encode (cold full): {t_encode:.3f}s  N={arr.N} P={arr.P} R={arr.R}",
          file=sys.stderr)

    import numpy as np

    # warmup / compile through the ROUTED kernel (donating where the backend
    # honors it — the same variant the pipelined loop runs, so only one
    # executable compiles in-process).  Inputs stay host numpy: the jit call
    # transfers fresh device buffers per step, which is what makes donation
    # safe here.  NOTE: block_until_ready can return early through the
    # axon TPU tunnel, so timing forces a (tiny) host transfer of the choices
    # vector — which is also what a real sidecar client would consume.
    t0 = time.perf_counter()
    choices = np.asarray(
        schedule_batch_routed(arr, cfg, donate=don, mesh=mesh)[0]
    )
    print(f"compile+first run: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    t_step_dense = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        choices = np.asarray(
            schedule_batch_routed(arr, cfg, donate=don, mesh=mesh)[0]
        )
        t_step_dense = min(t_step_dense, time.perf_counter() - t0)

    # the INCREMENTAL step — the production warm-cycle route (ops/
    # incremental.py; KTPU_INCREMENTAL=0 skips it and step_s reports the
    # dense kernel).  Same-box dense-vs-inc A/B lands in one artifact.
    from kubernetes_tpu.ops.incremental import HoistCache

    t_step = t_step_dense
    hoist_probe = HoistCache(mesh=mesh)
    inc = hoist_probe.ensure(arr, meta, cfg)
    if inc is not None:
        t0 = time.perf_counter()
        choices = np.asarray(
            schedule_batch_routed(arr, cfg, donate=don, mesh=mesh, inc=inc)[0]
        )
        print(f"inc compile+first run: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        t_step = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            choices = np.asarray(
                schedule_batch_routed(
                    arr, cfg, donate=don, mesh=mesh, inc=inc
                )[0]
            )
            t_step = min(t_step, time.perf_counter() - t0)
        print(
            f"step dense {t_step_dense:.2f}s -> incremental {t_step:.2f}s "
            f"({t_step_dense / max(t_step, 1e-9):.2f}x)",
            file=sys.stderr,
        )

    # the pre-chunking per-pod scan, for the delta the chunked path buys
    # (ops/assign.py — schedule_scan_chunked vs schedule_scan).  Skipped on
    # the CPU fallback: the chunked path doesn't route there, so the
    # comparison is vacuous and costs three extra full-scale runs.
    t_plain = None
    if backend:
        from kubernetes_tpu.ops.assign import schedule_scan as _plain

        plain = jax.jit(_plain, static_argnames=("cfg",))
        t_plain = float("inf")
        np.asarray(plain(arr, cfg)[0])  # compile
        for _ in range(2):
            t0 = time.perf_counter()
            np.asarray(plain(arr, cfg)[0])
            t_plain = min(t_plain, time.perf_counter() - t0)
        print(f"per-pod (unchunked) scan step: {t_plain*1e3:.1f}ms",
              file=sys.stderr)

    # warm-cluster steady state, PIPELINED (parallel/pipeline.py —
    # PipelinedBatchLoop): each cycle the previous fetched wave's pods are
    # bound, the wave before THAT completes (its bound pods leave the
    # cluster — sustainable forever, like real churn), and a fresh 50k wave
    # arrives.  Every cycle absorbs ~50k binds + ~50k deletes through the
    # resident encoder, and the delta-encode of wave w+1 runs WHILE wave
    # w's device step executes — the measured cycle wall is the step alone
    # once the pipeline fills.  The feedback runs with the pipeline's
    # one-wave lag (wave w binds wave w-2's placements); KTPU_PIPELINE=0
    # (the same switch the scheduler and harness --no-pipeline honor)
    # replays the IDENTICAL dataflow serially (depth 0) for comparison, so
    # decisions are bit-identical between the two (the parity tests pin
    # this at smoke scale).
    from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop

    pipeline = os.environ.get("KTPU_PIPELINE") != "0"
    # traced + metered warm loop: the captured spans feed the cycle
    # attribution report (scheduler/attribution.py) and the loop's SLI
    # series gives the headline arrival -> bind p50/p99 — span cost is
    # a handful per cycle, invisible next to the device step
    loop = PipelinedBatchLoop(
        encoder=enc, donate=don, depth=1 if pipeline else 0, mesh=mesh,
        tracer=Tracer(collector, component="pipeline"), metrics=metrics,
    )

    def mk_wave(w):
        return [
            dataclasses.replace(p, name=f"w{w}-{p.name}", uid="")
            for p in snap.pending_pods
        ]

    def place(pods, verdicts):
        return [
            dataclasses.replace(p, node_name=verdicts[p.name])
            for p in pods
            if verdicts.get(p.name)
        ]

    wave_pods = {1: snap.pending_pods}
    fetched = {
        1: {
            meta.pod_names[k]: (
                meta.node_names[int(choices[k])]
                if int(choices[k]) >= 0 else None
            )
            for k in range(meta.n_pods)
        }
    }
    walls = []
    last_w = 7
    t_mark = time.perf_counter()
    for w in range(2, last_w + 1):
        src = w - 2 if w - 2 in fetched else max(fetched)
        snapw = Snapshot(
            nodes=snap.nodes,
            pending_pods=mk_wave(w),
            bound_pods=place(wave_pods[src], fetched[src]),
        )
        wave_pods[w] = snapw.pending_pods
        v = loop.submit(snapw)
        # full cycle wall, mark to mark: the submit (encode + fetch of the
        # previous step) PLUS the caller-side feedback work — nothing is
        # excluded, so the median is an honest end-to-end number
        now = time.perf_counter()
        walls.append(now - t_mark)
        t_mark = now
        if v is not None:
            fetched[w - 1] = v
    fetched[last_w] = loop.drain()
    assert enc.stats["delta"] >= 3, f"delta path did not engage: {enc.stats}"

    # cycle attribution over the warm loop's spans: where the cycle wall
    # went, phase fractions summing to 1.0 (ROADMAP standing rule 1 —
    # attribute before optimizing; the report names the device kernel /
    # round loop as the dominant warm-cycle cost)
    from kubernetes_tpu.scheduler.attribution import (
        attribute_spans,
        render_attribution,
    )

    attribution = attribute_spans(collector)
    print(render_attribution(attribution), file=sys.stderr)
    from kubernetes_tpu.bench.harness import (
        commit_wave_fields,
        memwatch_fields,
        sli_fields,
    )

    sli = sli_fields(metrics)
    # commit-wave anatomy (ops/assign.py — class-batched commit waves):
    # rounds_executed is the sweep count the batching collapses (wave
    # blocks + stage-B rounds; regression-gated in ci.sh like step_s),
    # classes_committed_per_round the class-level batching factor.  One
    # untimed ordinal probe — decisions bit-identical to the timed runs.
    wave_anatomy = commit_wave_fields(arr, cfg, meta, inc=inc, mesh=mesh)
    # HBM telemetry (scheduler/memwatch.py): the loop's ledger sampled
    # every cycle boundary — measured peak / resident census stamped
    # top-level (hbm_peak_bytes is regression-gated like step_s) and the
    # sentinel verdict rides the memwatch block.  ONE stamping contract
    # shared with --stream (harness.memwatch_fields); bench sizes
    # per_shard_hbm_bytes exactly from the encoded arr dims below, so the
    # census-derived variant is dropped in favor of it.
    mem_fields = memwatch_fields(loop, metrics, n_shards,
                                 mesh_shape=(pod_shards, node_shards))
    mem_fields.pop("per_shard_hbm_bytes", None)
    per_shard_hbm = shard_hbm_estimate(
        arr.P, arr.N, node_shards, arr.R,
        n_terms=arr.term_counts0.shape[0],
        pod_shards=pod_shards,
    )["total"]
    # the PR-4 scale-out numbers as LIVE gauges, not just artifact fields
    # (unconditional — scale-out facts outlive a KTPU_MEMWATCH=0 run):
    # a /metrics scrape (KTPU_METRICS) sees the same story the JSON tells
    metrics.set("n_shards", n_shards)
    metrics.set("per_shard_hbm_bytes", per_shard_hbm)

    scheduled = int((choices[: meta.n_pods] >= 0).sum())
    # steady-state cycles: submit walls once the pipeline is full (each
    # spans one device step + any UNHIDDEN host work)
    steady = walls[2:]
    e2es = sorted(steady)
    end_to_end = e2es[len(e2es) // 2]  # median cycle
    overlap_fraction = loop.overlap_fraction()
    t_delta = loop.host_seconds["encode"][0] / max(1, len(walls))
    pods_per_sec = meta.n_pods / t_step
    e2e_pods_per_sec = meta.n_pods / end_to_end
    print(
        f"step: {t_step*1e3:.1f}ms  scheduled {scheduled}/{meta.n_pods}\n"
        f"warm cycle walls: "
        + ", ".join(f"{s:.3f}" for s in walls)
        + f"\nsteady state ({'pipelined' if pipeline else 'serial'}): "
        f"mean host encode+dispatch {t_delta*1e3:.1f}ms "
        f"(overlap fraction {overlap_fraction:.2f}); end-to-end median "
        f"{end_to_end*1e3:.1f}ms, worst {e2es[-1]*1e3:.1f}ms "
        f"({'PASS' if end_to_end < 1.0 else 'FAIL'} <1s north star)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "north_star_50kpods_20knodes_throughput",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "n_nodes": N_NODES,
                "n_pods": N_PODS,
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
                "platform": platform,
                "baseline_pods_per_sec": BASELINE_PODS_PER_SEC,
                "baseline_source": "own cpu-mode, heterogeneous 1000x2000 sample",
                "vs_reference_folklore": round(
                    pods_per_sec / REFERENCE_FOLKLORE_PODS_PER_SEC, 2
                ),
                "encode_s": round(t_encode, 3),
                "delta_s": round(t_delta, 3),
                "step_s": round(t_step, 4),
                # the dense (pre-PR-5) kernel on the same box, same run —
                # the incremental speedup's denominator
                "step_dense_s": round(t_step_dense, 4),
                # the unchunked leg only runs at small scale (the [P, N]
                # dense program OOMs at north-star shape) — when it never
                # ran, the key is OMITTED, not null: regression gates skip
                # absent metrics but would choke comparing against null
                **(
                    {"step_unchunked_s": round(t_plain, 4)}
                    if t_plain is not None else {}
                ),
                "end_to_end_s": round(end_to_end, 3),
                "end_to_end_worst_s": round(e2es[-1], 3),
                "cycles": [round(s, 3) for s in walls],
                "end_to_end_pods_per_sec": round(e2e_pods_per_sec, 1),
                "scheduled": scheduled,
                # the pipelined loop's self-report: fraction of host
                # encode/commit/decode hidden under in-flight device steps
                "pipeline": pipeline,
                "overlap_fraction": round(overlap_fraction, 3),
                "donated_waves": int(loop.stats["donated"]),
                "compile_cache_dir": cache_dir,
                # mesh scale-out: shard count, the 2-D (pods, nodes) grid,
                # and the per-shard HBM estimate of the kernel's dominant
                # blocks at this shape
                "n_shards": n_shards,
                "mesh_shape": [pod_shards, node_shards],
                "per_shard_hbm_bytes": per_shard_hbm,
                # measured HBM telemetry: hbm_peak_bytes /
                # hbm_resident_bytes + the memwatch sentinel block
                # (scheduler/memwatch.py; KTPU_MEMWATCH=0 omits)
                **mem_fields,
                # which kernel the routed call actually compiled (trace-time
                # proof; the fallback must exercise the production route)
                "route_trace_counts": dict(_trace_counts()),
                # incremental warm-cycle attribution (ops/incremental.py —
                # BENCH_r06): unique equivalence classes this wave, the
                # median dirty-node fraction the warm patches covered, and
                # resident-cache hit/full counts.  KTPU_INCREMENTAL=0 runs
                # the dense pre-PR-5 path for A/B comparison.
                "incremental": os.environ.get("KTPU_INCREMENTAL", "") != "0",
                # the headline SLI next to throughput: per-pod arrival ->
                # bind over the warm waves (streaming histogram p50/p99)
                **sli,
                # per-phase cycle attribution (machine-readable; the table
                # went to stderr above)
                "attribution": attribution,
                **loop.hoist.summary(),
                # commit-wave anatomy next to the hoist attribution:
                # rounds_executed / classes_committed_per_round
                **wave_anatomy,
            }
        )
    )


def _trace_counts():
    from kubernetes_tpu.ops.assign import TRACE_COUNTS

    return TRACE_COUNTS


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — the driver artifact must
        # never be an empty rc!=0 run again (round-3 verdict missing #2):
        # whatever happens, emit ONE schema-shaped JSON line and exit 0.
        if isinstance(e, (KeyboardInterrupt, SystemExit)) and not (
            isinstance(e, SystemExit) and e.code
        ):
            raise
        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": "north_star_50kpods_20knodes_throughput",
                    "value": 0.0,
                    "unit": "pods/s",
                    "vs_baseline": 0.0,
                    "platform": "error",
                    "error": repr(e),
                }
            )
        )
        sys.exit(0)
