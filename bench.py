#!/usr/bin/env python
"""Headline benchmark: the BASELINE.md north star.

50,000 pending pods vs 20,000 simulated nodes (heterogeneous capacities,
extended resources, taints/tolerations — BASELINE config-4 shape at north-star
scale), full filter+score+sequential-commit with exact one-pod-at-a-time
semantics.  Metric: pods scheduled per second, steady-state (post-compile),
best of 3.

vs_baseline: the reference default scheduler's scheduler_perf throughput on
simple profiles is O(100-300) pods/s (BASELINE.md; no published table exists
for the fork) — vs_baseline = pods_per_sec / 300 (the generous end).

Prints exactly one JSON line on stdout.
"""

import json
import sys
import time

N_NODES = 20_000
N_PODS = 50_000
BASELINE_PODS_PER_SEC = 300.0


def main() -> None:
    import jax

    from kubernetes_tpu.api.snapshot import encode_snapshot
    from kubernetes_tpu.bench.workloads import heterogeneous
    from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config, schedule_batch

    print(f"devices: {jax.devices()}", file=sys.stderr)
    snap = heterogeneous(N_NODES, N_PODS, seed=0)
    t0 = time.perf_counter()
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    arr = jax.device_put(arr)
    t_encode = time.perf_counter() - t0
    print(f"encode: {t_encode:.3f}s  N={arr.N} P={arr.P} R={arr.R}", file=sys.stderr)

    import numpy as np

    # warmup / compile.  NOTE: block_until_ready can return early through the
    # axon TPU tunnel, so timing forces a (tiny) host transfer of the choices
    # vector — which is also what a real sidecar client would consume.
    t0 = time.perf_counter()
    choices = np.asarray(schedule_batch(arr, cfg)[0])
    print(f"compile+first run: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        choices = np.asarray(schedule_batch(arr, cfg)[0])
        best = min(best, time.perf_counter() - t0)

    scheduled = int((choices[: meta.n_pods] >= 0).sum())
    pods_per_sec = meta.n_pods / best
    print(
        f"step: {best*1e3:.1f}ms  scheduled {scheduled}/{meta.n_pods}", file=sys.stderr
    )
    print(
        json.dumps(
            {
                "metric": "north_star_50kpods_20knodes_throughput",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
