"""Chaos parity: under any seeded fault plan, final placements are
bit-identical to the fault-free serial oracle, every recovery emits a
`recovery` span plus `framework_fault_recovery_total{site,action}`, and
every injected fault a `fault_injected` span (ISSUE 3 acceptance).

Tier-1 covers the three acceptance plans (sidecar drop, mid-wave device
exception, corrupt compile cache) at smoke scale across {pipeline on/off,
donation on/off}; the full seeded storms are marked `slow`."""

import copy
import os
import random
import time

import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop, run_serial
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import Profile, TPUScoreArgs
from kubernetes_tpu.scheduler.metrics import Metrics
from kubernetes_tpu.scheduler.tracing import TraceCollector, Tracer

from helpers import mk_node, mk_pod


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _wave(seed: int, n_nodes: int = 8, n_pods: int = 16) -> Snapshot:
    rng = np.random.default_rng(seed)
    nodes = [
        mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
        for i in range(n_nodes)
    ]
    pods = [
        mk_pod(f"w{seed}-p{j}", cpu=int(rng.integers(100, 1500)))
        for j in range(n_pods)
    ]
    return Snapshot(nodes=nodes, pending_pods=pods)


# --- plan mechanics ---
def test_fault_plan_is_deterministic_and_parses():
    a = chaos.FaultPlan.from_seed(11)
    b = chaos.FaultPlan.from_seed(11)
    assert a.describe() == b.describe()
    assert a.describe() != chaos.FaultPlan.from_seed(12).describe()
    p = chaos.FaultPlan.parse("scheduler.step:error@1;sidecar.rpc:hang@0:0.02")
    assert p.match("scheduler.step", 1).action == "error"
    assert p.match("scheduler.step", 0) is None
    assert p.match("sidecar.rpc", 0).param == 0.02
    star = chaos.FaultPlan.parse("pipeline.step:nan@*")
    assert star.match("pipeline.step", 999).action == "nan"
    with pytest.raises(ValueError):
        chaos.FaultPlan.parse("no.such.site:error@0")
    with pytest.raises(ValueError):
        chaos.FaultPlan.parse("kubelet.sync:nan@0")  # unsupported action


def test_poisoned_verdict_detection():
    assert not chaos.poisoned_verdicts(np.array([0, 3, -1], dtype=np.int32), 4)
    assert chaos.poisoned_verdicts(np.array([0.0, np.nan]), 4)
    assert chaos.poisoned_verdicts(np.array([0, 4], dtype=np.int64), 4)
    assert chaos.poisoned_verdicts(np.array([-7, 1], dtype=np.int64), 4)
    assert chaos.poisoned_verdicts(chaos.poison(np.array([1, 2, 3])), 4)


# --- pipelined loop: mid-wave death -> serial-oracle replay ---
@pytest.mark.parametrize("action", ["error", "nan"])
@pytest.mark.parametrize("donate", [False, True])
def test_pipeline_wave_death_recovers_to_serial_parity(action, donate):
    waves = [_wave(s) for s in range(4)]
    oracle = list(run_serial(waves, donate=donate))
    col = TraceCollector()
    metrics = Metrics()
    with chaos.chaos_plan(chaos.FaultPlan.single("pipeline.step", action, at=1)):
        loop = PipelinedBatchLoop(
            donate=donate, depth=1,
            tracer=Tracer(col, component="pipeline"), metrics=metrics,
        )
        got = list(loop.run(waves))
    assert got == oracle  # bit-identical placements, fault or no fault
    assert loop.stats["recovered"] == 1
    assert col.spans(name="fault_injected") and col.spans(name="recovery")
    assert metrics.labeled_counter_total("framework_fault_recovery_total") >= 1


def test_pipeline_host_stall_changes_nothing_but_wall():
    waves = [_wave(s) for s in range(3)]
    oracle = list(run_serial(waves))
    with chaos.chaos_plan(
        chaos.FaultPlan.single("host.stall", "stall", at=0, count=2, param=0.01)
    ):
        got = list(PipelinedBatchLoop(depth=1).run(waves))
    assert got == oracle


def test_pipeline_commit_exception_still_drains_inflight_wave():
    """An exception thrown by the caller's commit callback mid-wave must
    not leak the dispatched wave: drain() still fetches and commits it."""
    waves = [_wave(s) for s in range(2)]
    oracle = list(run_serial(waves))
    committed = []
    state = {"boomed": False}

    def commit(v):
        if not state["boomed"]:
            state["boomed"] = True
            raise RuntimeError("commit crash")
        committed.append(v)

    loop = PipelinedBatchLoop(depth=1, commit=commit)
    loop.submit(waves[0])
    with pytest.raises(RuntimeError):
        loop.submit(waves[1])  # wave 0's commit crashes mid-wave
    v = loop.drain()  # wave 1 was still tracked in-flight: flushes here
    assert v == oracle[1] and committed == [oracle[1]]


# --- scheduler batch path: acceptance plans x {pipeline, donation} ---
def _churn_run(pipeline: bool, plan=None, collector=None, donate_env=None):
    os.environ["KTPU_PIPELINE"] = "1" if pipeline else "0"
    if donate_env is not None:
        os.environ["KTPU_DONATE"] = donate_env
    try:
        ctx = (
            chaos.chaos_plan(plan) if plan is not None
            else __import__("contextlib").nullcontext()
        )
        with ctx:
            store = ClusterStore()
            for i in range(5):
                store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
            sched = Scheduler(
                store, SchedulerConfiguration(mode="tpu"), collector=collector
            )
            for i in range(20):
                store.add_pod(mk_pod(f"p{i}", cpu=250))
            sched.run_until_idle()
            rng = random.Random(5)
            for r in range(2):
                bound = sorted(
                    (p for p in store.pods.values() if p.node_name),
                    key=lambda p: p.uid,
                )
                for v in rng.sample(bound, 6):
                    store.delete_pod(v.uid)
                    q = copy.copy(v)
                    q.name = f"{v.name}-r{r}"
                    q.uid = ""
                    q.node_name = ""
                    q.__post_init__()
                    store.add_pod(q)
                sched.run_until_idle()
            placements = {p.name: p.node_name for p in store.pods.values()}
            return placements, sched
    finally:
        os.environ.pop("KTPU_PIPELINE", None)
        if donate_env is not None:
            os.environ.pop("KTPU_DONATE", None)


@pytest.mark.parametrize("pipeline", [True, False])
@pytest.mark.parametrize(
    "spec",
    ["scheduler.step:error@1", "scheduler.step:nan@0",
     "host.stall:stall@0+3:0.005"],
)
def test_scheduler_chaos_parity_on_churn(pipeline, spec):
    """Mid-wave device exception / NaN verdicts / slow-host stalls across
    {pipeline on, off}: placements bit-identical to the fault-free serial
    oracle, with recovery metrics + spans wherever a wave actually died."""
    oracle, _ = _churn_run(pipeline=False)
    col = TraceCollector()
    got, sched = _churn_run(
        pipeline=pipeline, plan=chaos.FaultPlan.parse(spec), collector=col
    )
    assert got == oracle
    assert all(v for v in got.values())
    if "stall" not in spec:
        assert (
            sched.metrics.labeled_counter_total(
                "framework_fault_recovery_total"
            ) > 0
        )
        assert col.spans(name="fault_injected") and col.spans(name="recovery")


def test_scheduler_chaos_parity_with_donation_disabled():
    oracle, _ = _churn_run(pipeline=False)
    got, sched = _churn_run(
        pipeline=True, plan=chaos.FaultPlan.parse("scheduler.step:error@0"),
        donate_env="0",
    )
    assert got == oracle
    assert sched.metrics.counters["scheduling_wave_recoveries_total"] >= 1


def test_commit_crash_releases_assumed_capacity():
    """A crash mid-commit (apiserver down during the bind fan-out) must
    release this cycle's assumptions and requeue the stranded pods — no
    phantom capacity, and a surviving caller's retry completes."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=8000, pods=64))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for i in range(6):
        store.add_pod(mk_pod(f"p{i}", cpu=100))
    orig_bind = store.bind
    calls = {"n": 0}

    def bad_bind(uid, node):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("apiserver down")
        return orig_bind(uid, node)

    store.bind = bad_bind
    with pytest.raises(RuntimeError):
        sched.schedule_batch()
    assert sched.cache.assumed == {}  # no leaked reservation
    assert sched._deferred_binds == []
    assert (
        sched.metrics.labeled_counter_total("framework_fault_recovery_total")
        >= 1
    )
    sched.run_until_idle()  # requeued pods retry and land
    assert all(p.node_name == "n0" for p in store.pods.values())


def test_commit_crash_requeues_unprocessed_and_keeps_committed_prefix():
    """A bind crash PART WAY through the fan-out: the already-published
    prefix stays bound, the failed pod and the unprocessed tail are
    requeued (not dropped, not double-parked), and no assume leaks."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=8000, pods=64))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for i in range(5):
        store.add_pod(mk_pod(f"p{i}", cpu=100))
    orig_bind = store.bind
    calls = {"n": 0}

    def bad_bind(uid, node):
        calls["n"] += 1
        if calls["n"] == 3:  # third publish dies; two pods already bound
            raise RuntimeError("apiserver down")
        return orig_bind(uid, node)

    store.bind = bad_bind
    with pytest.raises(RuntimeError):
        sched.schedule_batch()
    bound = [p for p in store.pods.values() if p.node_name]
    assert len(bound) == 2  # the committed prefix survived
    assert sched.cache.assumed == {}
    # the failed pod + unprocessed tail are back in the activeQ, once each
    assert len(sched.queue) == 3
    store.bind = orig_bind
    sched.run_until_idle()
    assert all(p.node_name == "n0" for p in store.pods.values())
    assert len(sched.events.by_reason("Scheduled")) == 5


def test_deferred_flush_crash_keeps_tail_for_retry():
    """A store.bind exception mid-flush must keep the failed bind and the
    unprocessed tail in _deferred_binds (assumes held) so a later flush
    publishes them — not silently drop them as phantom capacity."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=8000, pods=64))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    pods = [mk_pod(f"d{i}", cpu=100) for i in range(3)]
    for p in pods:
        store.add_pod(p)
        sched.cache.assume(p.uid, "n0")
        sched._deferred_binds.append((p, "n0"))
    orig_bind = store.bind
    calls = {"n": 0}

    def bad_bind(uid, node):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("apiserver down")
        return orig_bind(uid, node)

    store.bind = bad_bind
    with pytest.raises(RuntimeError):
        sched._flush_deferred_binds()
    # bind 1 published; binds 2+3 retained for retry, reservations held
    assert [p.name for p, _ in sched._deferred_binds] == ["d1", "d2"]
    assert set(sched.cache.assumed) == {pods[1].uid, pods[2].uid}
    store.bind = orig_bind
    sched._flush_deferred_binds()
    assert sched._deferred_binds == []
    assert all(p.node_name == "n0" for p in store.pods.values())


# --- sidecar: drop / hang / partial / budget ---
def _sidecar_rig(n_nodes=4, n_pods=8):
    from kubernetes_tpu.runtime import TPUScoreServer

    srv = TPUScoreServer()
    srv.start()
    snap = Snapshot(
        nodes=[mk_node(f"n{i}", cpu=4000) for i in range(n_nodes)],
        pending_pods=[mk_pod(f"p{j}", cpu=300) for j in range(n_pods)],
    )
    return srv, snap


@pytest.mark.parametrize("spec", [
    "sidecar.rpc:error@0",            # dropped connection on the first try
    "sidecar.rpc:hang@0:0.01",        # hang then drop
    "sidecar.rpc:partial@0",          # truncated response (must be DETECTED)
])
def test_sidecar_fault_retries_to_identical_verdicts(spec):
    from kubernetes_tpu.runtime import TPUScoreClient

    srv, snap = _sidecar_rig()
    try:
        clean = TPUScoreClient(f"127.0.0.1:{srv.port}")
        want = clean.schedule(snap, deadline_ms=60_000)
        clean.close()
        client = TPUScoreClient(
            f"127.0.0.1:{srv.port}", backoff_base_s=0.001
        )
        with chaos.chaos_plan(chaos.FaultPlan.parse(spec)):
            got = client.schedule(snap, deadline_ms=60_000)
        assert got == want
        assert client.stats["retries"] >= 1
        assert (
            client.metrics.labeled_counter_total(
                "framework_fault_recovery_total"
            ) >= 1
        )
        client.close()
    finally:
        srv.stop()


def test_sidecar_failure_budget_degrades_then_recovers():
    """failure_budget consecutive exhausted calls trip the circuit: the
    channel fails fast (no dial) until the cooldown, then one half-open
    probe restores it on success."""
    from kubernetes_tpu.runtime import SidecarUnavailable, TPUScoreClient

    srv, snap = _sidecar_rig()
    try:
        client = TPUScoreClient(
            f"127.0.0.1:{srv.port}", max_attempts=1, backoff_base_s=0.001,
            failure_budget=2, degraded_cooldown_s=0.05,
        )
        with chaos.chaos_plan(chaos.FaultPlan.parse("sidecar.rpc:error@0+2")):
            for _ in range(2):
                with pytest.raises(SidecarUnavailable):
                    client.schedule(snap, deadline_ms=60_000)
            assert client.degraded
            assert client.metrics.counters["sidecar_degraded_total"] == 1
            # fail-fast while degraded: no RPC attempted, so the chaos
            # site counter must not advance
            before = chaos.active().counts.get("sidecar.rpc", 0)
            with pytest.raises(SidecarUnavailable):
                client.schedule(snap, deadline_ms=60_000)
            assert chaos.active().counts.get("sidecar.rpc", 0) == before
            time.sleep(0.06)  # cooldown: half-open probe allowed (no fault now)
            got = client.schedule(snap, deadline_ms=60_000)
        assert not client.degraded
        assert client.metrics.counters["sidecar_degraded_recovered_total"] == 1
        assert sorted(got) == sorted(p.uid for p in snap.pending_pods)
        client.close()
    finally:
        srv.stop()


def test_half_open_probe_is_a_single_attempt():
    """After the degraded cooldown the probe call makes exactly ONE
    transport attempt — never the full retry ladder inside one cycle."""
    from kubernetes_tpu.runtime import SidecarUnavailable, TPUScoreClient

    srv, snap = _sidecar_rig()
    try:
        client = TPUScoreClient(
            f"127.0.0.1:{srv.port}", max_attempts=3, backoff_base_s=0.001,
            failure_budget=1, degraded_cooldown_s=0.01,
        )
        with chaos.chaos_plan(chaos.FaultPlan.parse("sidecar.rpc:error@*")):
            with pytest.raises(SidecarUnavailable):
                client.schedule(snap, deadline_ms=60_000)  # trips the budget
            assert client.degraded
            time.sleep(0.02)  # cooldown elapsed: next call is the probe
            before = chaos.active().counts.get("sidecar.rpc", 0)
            with pytest.raises(SidecarUnavailable):
                client.schedule(snap, deadline_ms=60_000)
            assert chaos.active().counts["sidecar.rpc"] == before + 1
        assert client.degraded  # failed probe re-armed the cooldown
        client.close()
    finally:
        srv.stop()


def test_scheduler_parity_through_sidecar_drop():
    """Acceptance plan 1: a sidecar-routed scheduler wave whose first RPC
    drops retries in-call and lands the SAME placements as the fault-free
    run."""
    from kubernetes_tpu.runtime import TPUScoreServer

    def run(plan):
        srv = TPUScoreServer()
        srv.start()
        try:
            store = ClusterStore()
            for i in range(4):
                store.add_node(mk_node(f"n{i}", cpu=4000))
            prof = Profile(tpu_score=TPUScoreArgs(
                sidecar_address=f"127.0.0.1:{srv.port}", deadline_ms=60_000,
            ))
            sched = Scheduler(
                store, SchedulerConfiguration(profiles=(prof,), mode="tpu")
            )
            sched._sidecars[f"127.0.0.1:{srv.port}"] = None
            for j in range(10):
                store.add_pod(mk_pod(f"p{j}", cpu=300))
            ctx = (
                chaos.chaos_plan(plan) if plan is not None
                else __import__("contextlib").nullcontext()
            )
            with ctx:
                sched.run_until_idle()
            return {p.name: p.node_name for p in store.pods.values()}, sched
        finally:
            srv.stop()

    want, _ = run(None)
    got, sched = run(chaos.FaultPlan.parse("sidecar.rpc:error@0"))
    assert got == want and all(v for v in got.values())
    assert sched.metrics.counters.get("sidecar_rpc_failures_total", 0) >= 1


def test_health_failure_marks_degraded_track_and_resyncs(monkeypatch):
    """Satellite regression: health() must never swallow a transport error
    silently — it increments sidecar_health_failures_total, counts toward
    the budget, and forces the next schedule() to full-resync (the
    reconnect-after-health-failure path)."""
    from kubernetes_tpu.runtime import SidecarUnavailable, TPUScoreClient

    srv, snap = _sidecar_rig()
    try:
        client = TPUScoreClient(f"127.0.0.1:{srv.port}")
        client.schedule(snap, deadline_ms=60_000)
        assert client.stats["full"] == 1 and client._synced
        with chaos.chaos_plan(chaos.FaultPlan.parse("sidecar.health:error@0")):
            with pytest.raises(SidecarUnavailable):
                client.health()
        assert client.metrics.counters["sidecar_health_failures_total"] == 1
        assert client._consecutive_failures == 1
        assert not client._synced  # the server may have restarted
        # reconnect: the next schedule re-sends the FULL snapshot
        got = client.schedule(snap, deadline_ms=60_000)
        assert client.stats["full"] == 2 and client.stats["delta"] == 0
        assert sorted(got) == sorted(p.uid for p in snap.pending_pods)
        assert client._consecutive_failures == 0  # success reset the budget
        client.close()
    finally:
        srv.stop()


# --- compile cache corruption ---
def test_scrub_compile_cache_drops_truncated_entries(tmp_path):
    from kubernetes_tpu.ops.aot import scrub_compile_cache

    (tmp_path / "a-cache").write_bytes(b"")          # zero-length
    (tmp_path / "b-cache").write_bytes(b"\x00ba")    # truncated-at-3
    (tmp_path / "c-cache").write_bytes(b"x" * 64)    # plausible entry
    assert scrub_compile_cache(str(tmp_path)) == 2
    assert sorted(f.name for f in tmp_path.iterdir()) == ["c-cache"]
    assert scrub_compile_cache(str(tmp_path), aggressive=True) == 1
    assert list(tmp_path.iterdir()) == []


def test_corrupt_compile_cache_entry_recompiles_not_raises(tmp_path):
    """Acceptance plan 3 (satellite 1): a truncated/corrupt entry in
    KTPU_COMPILE_CACHE_DIR falls back to a fresh compile that overwrites
    the bad entry — warmup never raises.  Subprocesses: the persistent
    cache only writes on a real in-process-cache miss."""
    import subprocess
    import sys

    cache = str(tmp_path / "cc")
    prog = (
        "from kubernetes_tpu.bench._cpu import force_cpu_from_env\n"
        "force_cpu_from_env()\n"
        "from kubernetes_tpu.ops import aot\n"
        "aot.maybe_enable_compile_cache()\n"
        "from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot\n"
        "from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config\n"
        "from helpers import mk_node, mk_pod\n"
        "snap = Snapshot(nodes=[mk_node('n%d' % i) for i in range(3)],\n"
        "                pending_pods=[mk_pod('p%d' % j) for j in range(4)])\n"
        "arr, _ = encode_snapshot(snap)\n"
        "cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)\n"
        "assert aot.warm_kernels(arr, cfg, batch=False) >= 1\n"
    )
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KTPU_COMPILE_CACHE_DIR=cache, PYTHONPATH=tests_dir)
    kw = dict(env=env, capture_output=True, text=True, timeout=300,
              cwd=os.path.dirname(tests_dir))
    r = subprocess.run([sys.executable, "-c", prog], **kw)
    assert r.returncode == 0, r.stderr[-2000:]
    entries = [f for f in os.listdir(cache)]
    assert entries, "warmup wrote no cache entries"
    # corrupt every entry the way a crash mid-write does
    for name in entries:
        with open(os.path.join(cache, name), "wb") as f:
            f.write(b"\x00bad")
    r = subprocess.run([sys.executable, "-c", prog], **kw)
    assert r.returncode == 0, (
        "warmup raised on a corrupt cache entry:\n" + r.stderr[-2000:]
    )
    # the bad 4-byte entries were dropped/overwritten by fresh compiles
    assert all(
        os.path.getsize(os.path.join(cache, f)) > 4 for f in os.listdir(cache)
    )


def test_genuine_compile_error_does_not_wipe_cache(tmp_path, monkeypatch):
    """A real compile error (not corruption) must escape with the shared
    cache dir untouched — other processes depend on its valid entries."""
    from kubernetes_tpu.ops import aot

    (tmp_path / "valid-entry-cache").write_bytes(b"x" * 64)
    monkeypatch.setattr(aot, "_enabled_dir", str(tmp_path))
    seen = []
    monkeypatch.setattr(
        "jax.config.update", lambda k, v: seen.append((k, v))
    )

    class BadKernel:
        def lower(self, arr, cfg):
            raise RuntimeError("genuine lowering bug")

    with pytest.raises(RuntimeError, match="genuine"):
        aot._compile_with_cache_recovery(BadKernel(), None, None)
    # the valid entry survived, and the cache was re-enabled on the way out
    assert (tmp_path / "valid-entry-cache").read_bytes() == b"x" * 64
    assert seen[-1] == ("jax_compilation_cache_dir", str(tmp_path))


def test_kubelet_sync_crash_rollback_leaves_no_orphan_sandbox():
    """A crash AFTER the sandbox was created rolls the admission back
    through the CRI teardown: no orphaned sandbox (or leaked pod IP), and
    the retry ends with exactly one sandbox."""
    from kubernetes_tpu.scheduler.kubelet import HollowKubelet
    from kubernetes_tpu.scheduler.leases import LeaseStore
    from kubernetes_tpu.scheduler.queue import FakeClock
    from kubernetes_tpu.api import types as t

    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    kubelet = HollowKubelet(store, LeaseStore(clock=clock), "n0", clock=clock)
    orig_create = kubelet.runtime.create_container
    calls = {"n": 0}

    def bad_create(sandbox_id, config):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("runtime hiccup after sandbox creation")
        return orig_create(sandbox_id, config)

    kubelet.runtime.create_container = bad_create
    store.add_pod(mk_pod("sandboxed", node_name="n0"))
    kubelet.tick()  # crash mid-sync, after run_pod_sandbox
    assert kubelet.sync_failures == 1
    assert kubelet.runtime.list_pod_sandboxes() == []  # rolled back via CRI
    kubelet.tick()  # retry succeeds
    assert store.pods["default/sandboxed"].phase == t.PHASE_RUNNING
    assert len(kubelet.runtime.list_pod_sandboxes()) == 1


# --- kubelet sync crash ---
def test_kubelet_sync_crash_is_contained_and_retried():
    from kubernetes_tpu.scheduler.kubelet import HollowKubelet
    from kubernetes_tpu.scheduler.leases import LeaseStore
    from kubernetes_tpu.scheduler.queue import FakeClock
    from kubernetes_tpu.api import types as t

    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    kubelet = HollowKubelet(store, LeaseStore(clock=clock), "n0", clock=clock)
    store.add_pod(mk_pod("crashy", node_name="n0"))
    with chaos.chaos_plan(chaos.FaultPlan.parse("kubelet.sync:crash@0")):
        kubelet.tick()  # injected crash: contained, nothing started
        assert kubelet.sync_failures == 1
        assert store.pods["default/crashy"].phase == ""
        assert not kubelet.workers["default/crashy"].admitted
        kubelet.tick()  # fault exhausted: the retry admits and starts
    assert store.pods["default/crashy"].phase == t.PHASE_RUNNING
    assert kubelet.sync_failures == 1


# --- queue backoff jitter (satellite) ---
def test_backoff_jitter_is_bounded_capped_and_seeded():
    from kubernetes_tpu.scheduler.queue import FakeClock, PriorityQueue

    def maturities(seed):
        clock = FakeClock()
        q = PriorityQueue(clock, backoff_jitter=0.25, jitter_seed=seed,
                          initial_backoff_s=1.0, max_backoff_s=10.0)
        out = []
        for i in range(32):
            p = mk_pod(f"j{i}")
            q._attempts[p.uid] = 6  # deep retry: base hits the 10 s cap
            with q._lock:
                q._push_backoff(p)
            out.append(q._backoff[-1][0])
        return out

    a, b, c = maturities(1), maturities(1), maturities(2)
    assert a == b  # seeded: reproducible
    assert a != c
    assert all(10.0 <= m < 10.0 * 1.25 for m in a)  # capped base + bounded jitter
    assert len(set(a)) > 16  # actually de-correlated, not one synchronized storm


def test_backoff_cap_and_jitter_flow_from_config():
    store = ClusterStore()
    cfg = SchedulerConfiguration(
        mode="tpu", pod_initial_backoff_seconds=0.5,
        pod_max_backoff_seconds=4.0, pod_backoff_jitter=0.2,
    )
    sched = Scheduler(store, cfg)
    q = sched.queue
    assert (q.initial_backoff_s, q.max_backoff_s, q.backoff_jitter) == (0.5, 4.0, 0.2)
    q._attempts["default/x"] = 10
    assert q.backoff_duration("default/x") == 4.0  # capped by config
    from kubernetes_tpu.scheduler.config import validate

    assert validate(SchedulerConfiguration(pod_backoff_jitter=-1.0))
    assert validate(SchedulerConfiguration(pod_max_backoff_seconds=0.1))


# --- seeded storms (full matrix is slow; tier-1 gets one smoke seed) ---
def _storm_run(seed):
    col = TraceCollector()
    got, sched = _churn_run(
        pipeline=True,
        plan=chaos.FaultPlan.from_seed(
            seed, sites=("scheduler.step", "host.stall"), n_faults=4
        ),
        collector=col,
    )
    return got, sched, col


def test_chaos_storm_smoke_seed0(monkeypatch):
    oracle, _ = _churn_run(pipeline=False)
    # the tier-1 storm smoke runs under the runtime lock-order checker
    # (ISSUE 8): every lock constructed below becomes a CheckedLock, and
    # any observed acquisition order that closes a cycle fails the smoke
    from kubernetes_tpu.analysis import lockcheck

    monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
    lockcheck.reset()
    try:
        got, sched, col = _storm_run(0)
        assert got == oracle
        lockcheck.assert_clean()
        assert lockcheck.order_graph()  # the checker observed real nesting
    finally:
        # the checker state is process-global: reset even on failure so a
        # later lock-check test doesn't inherit this storm's edges
        monkeypatch.delenv("KTPU_LOCK_CHECK")
        lockcheck.reset()


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_chaos_storm_full(seed):
    oracle, _ = _churn_run(pipeline=False)
    got, _, _ = _storm_run(seed)
    assert got == oracle
