"""cpumanager static policy + eviction manager (scheduler/cm.py) — the
kubelet's cm/ subsystems: exclusive-core pinning with fragmentation-driven
admission failure, and node-pressure eviction with the memory-pressure
taint surfaced to the scheduler."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore
from kubernetes_tpu.scheduler.cm import (
    MEMORY_PRESSURE_TAINT_KEY,
    CPUManagerStatic,
    EvictionManager,
    pod_qos,
)
from kubernetes_tpu.scheduler.kubelet import HollowKubelet
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import mk_node, mk_pod

GI = 1024**3


def _rig(cpu=4000, mem=8 * GI, pods=20):
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=cpu, mem=mem, pods=pods))
    kubelet = HollowKubelet(store, LeaseStore(clock=clock), "n0", clock=clock)
    return clock, store, kubelet


def test_cpumanager_pins_integer_requests_exclusively():
    clock, store, kubelet = _rig(cpu=4000)
    store.add_pod(mk_pod("g1", cpu=2000, node_name="n0"))  # integer: pinned
    store.add_pod(mk_pod("b1", cpu=500, node_name="n0"))  # fractional: shared
    kubelet.tick()
    assert kubelet.cpumanager.assignments["default/g1"] == (0, 1)
    assert "default/b1" not in kubelet.cpumanager.assignments
    # a second integer pod gets the NEXT free cores
    store.add_pod(mk_pod("g2", cpu=1000, node_name="n0"))
    kubelet.tick()
    assert kubelet.cpumanager.assignments["default/g2"] == (2,)


def test_cpumanager_fragmentation_fails_admission():
    """4-core node with 3 cores pinned: a 2-core pod cannot be admitted
    even though 1000m+shared capacity remains — UnexpectedAdmissionError,
    pod Failed (the reference kubelet's admission contract)."""
    clock, store, kubelet = _rig(cpu=4000)
    store.add_pod(mk_pod("g1", cpu=3000, node_name="n0"))
    kubelet.tick()
    store.add_pod(mk_pod("g2", cpu=2000, node_name="n0"))
    kubelet.tick()
    assert store.pods["default/g2"].phase == t.PHASE_FAILED
    # ...and its cores were never leaked
    assert "default/g2" not in kubelet.cpumanager.assignments
    # cores free once the pinned pod terminates
    store.delete_pod("default/g1")
    assert "default/g1" not in kubelet.cpumanager.assignments


def test_eviction_reclaims_overcommit_and_taints_node():
    """Direct binds bypassing the scheduler overcommit memory: the eviction
    manager evicts BestEffort first, then lowest-priority largest-request,
    until under the threshold, and the memory-pressure NoSchedule taint
    tracks the pressure state."""
    clock, store, kubelet = _rig(mem=8 * GI, pods=20)
    store.add_pod(mk_pod("be", cpu=0, mem=0, node_name="n0"))  # BestEffort
    assert pod_qos(store.pods["default/be"]) == "BestEffort"
    store.add_pod(mk_pod("low", cpu=100, mem=4 * GI, node_name="n0", priority=0))
    store.add_pod(mk_pod("hi", cpu=100, mem=3 * GI, node_name="n0", priority=100))
    kubelet.tick()
    assert not any(
        tn.key == MEMORY_PRESSURE_TAINT_KEY
        for tn in store.nodes["n0"].taints
    )
    # overcommit: another 4Gi lands directly (7+4 > 0.95 * 8)
    store.add_pod(mk_pod("ext", cpu=100, mem=4 * GI, node_name="n0", priority=50))
    evicted = kubelet.eviction.synchronize()
    # BestEffort evicts first but frees 0 bytes; then priority-0 "low"
    # (4Gi) brings usage to 7Gi <= 7.6Gi
    assert "default/be" in evicted and "default/low" in evicted
    assert store.pods["default/low"].phase == t.PHASE_FAILED
    assert store.pods["default/hi"].phase != t.PHASE_FAILED
    assert not any(
        tn.key == MEMORY_PRESSURE_TAINT_KEY
        for tn in store.nodes["n0"].taints
    )


def test_eviction_taints_while_pressure_persists():
    """A single unevictable-helpful... rather: when eviction cannot bring
    the node under threshold (one giant pod), the taint stays until it
    can."""
    clock, store, kubelet = _rig(mem=8 * GI)
    store.add_pod(mk_pod("giant", cpu=100, mem=9 * GI, node_name="n0"))
    evicted = kubelet.eviction.synchronize()
    # the giant itself is evicted (only candidate)
    assert evicted == ["default/giant"]
    assert not any(
        tn.key == MEMORY_PRESSURE_TAINT_KEY
        for tn in store.nodes["n0"].taints
    )


def test_cpumanager_checkpoint_survives_kubelet_restart(tmp_path):
    """cm/cpumanager/state: a restarted kubelet reloads core assignments
    from the checksummed checkpoint, so a still-running pod's cores are
    not double-assigned, and a pod that vanished while the kubelet was
    down frees its cores through housekeeping."""
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=4000))
    k1 = HollowKubelet(store, LeaseStore(clock=clock), "n0", clock=clock,
                       checkpoint_dir=str(tmp_path))
    store.add_pod(mk_pod("g1", cpu=2000, node_name="n0"))
    k1.tick()
    assert k1.cpumanager.assignments["default/g1"] == (0, 1)
    k1.close()
    # restart: the new kubelet sees the same assignment without re-allocating
    k2 = HollowKubelet(store, LeaseStore(clock=clock), "n0", clock=clock,
                       checkpoint_dir=str(tmp_path))
    assert k2.cpumanager.assignments["default/g1"] == (0, 1)
    # a new integer pod takes the NEXT cores (no double assignment)
    store.add_pod(mk_pod("g2", cpu=1000, node_name="n0"))
    k2.tick()
    assert k2.cpumanager.assignments["default/g2"] == (2,)
    k2.close()
