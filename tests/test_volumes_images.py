"""ImageLocality, volume family (VolumeZone/VolumeBinding-lite/
NodeVolumeLimits), DRA-lite resource claims — across all execution paths."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.native import schedule_batch_native
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config, schedule_batch
from kubernetes_tpu.oracle import oracle_schedule
from helpers import GI, mk_node, mk_pod


def run_all_paths(snap):
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    tpu = np.asarray(schedule_batch(arr, cfg)[0])
    native = schedule_batch_native(arr, cfg)[0]
    np.testing.assert_array_equal(native, tpu)
    got = [
        (meta.pod_names[k], meta.node_names[tpu[k]] if tpu[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule(snap)
    assert got == want, f"kernel={got} oracle={want}"
    return dict(got)


def test_image_locality_steers_to_cached_node():
    img = "registry.io/model-server:v3"
    nodes = [
        mk_node("cold"),
        mk_node("warm"),
    ]
    nodes[1].images[img] = 800 * 1024 * 1024  # 800 MB cached
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=[mk_pod("p", images=(img,))]))
    assert got["p"] == "warm"


def test_image_below_threshold_is_ignored():
    img = "tiny:latest"
    nodes = [mk_node("a"), mk_node("b")]
    nodes[1].images[img] = 10 * 1024 * 1024  # 10 MB < 23 MB threshold
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=[mk_pod("p", images=(img,))]))
    assert got["p"] == "a"  # tie -> lowest index


def test_bound_pvc_zone_restricts_nodes():
    pv = t.PersistentVolume(
        name="pv-a", capacity=100 * GI, storage_class="std",
        allowed_topology=((t.LABEL_ZONE, "a"),), claim_ref="default/data",
    )
    pvc = t.PersistentVolumeClaim(name="data", request=50 * GI, storage_class="std",
                                  volume_name="pv-a")
    nodes = [
        mk_node("n-b", labels={t.LABEL_ZONE: "b"}),
        mk_node("n-a", labels={t.LABEL_ZONE: "a"}),
    ]
    snap = Snapshot(nodes=nodes, pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvs=[pv], pvcs={pvc.key: pvc})
    got = run_all_paths(snap)
    assert got["p"] == "n-a"


def test_unbound_immediate_claim_without_pv_is_unschedulable():
    pvc = t.PersistentVolumeClaim(name="data", request=50 * GI, storage_class="fast")
    snap = Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvcs={pvc.key: pvc})
    got = run_all_paths(snap)
    assert got["p"] is None


def test_wait_for_first_consumer_claim_does_not_block():
    pvc = t.PersistentVolumeClaim(name="data", request=50 * GI, storage_class="fast",
                                  wait_for_first_consumer=True)
    snap = Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvcs={pvc.key: pvc})
    got = run_all_paths(snap)
    assert got["p"] == "n"


def test_volume_attach_limit_enforced():
    nodes = [mk_node("small"), mk_node("big")]
    nodes[0].volume_attach_limit = 1
    nodes[1].volume_attach_limit = 8
    pvcs = {}
    pods = []
    for i in range(3):
        pvc = t.PersistentVolumeClaim(name=f"d{i}", request=GI, storage_class="std",
                                      wait_for_first_consumer=True)
        pvcs[pvc.key] = pvc
        pods.append(mk_pod(f"p{i}", pvcs=(f"d{i}",)))
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=pods, pvcs=pvcs))
    # node "small" accepts at most 1 attached volume
    assert sum(1 for v in got.values() if v == "small") <= 1
    assert all(v is not None for v in got.values())


def test_resource_claims_consume_device_class():
    nodes = [mk_node("accel"), mk_node("plain")]
    nodes[0].allocatable["claim/tpu-v5e"] = 2
    pods = [
        mk_pod(f"p{i}", resource_claims=(t.ResourceClaimRef(device_class="tpu-v5e"),))
        for i in range(3)
    ]
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=pods))
    assert sum(1 for v in got.values() if v == "accel") == 2
    assert sum(1 for v in got.values() if v is None) == 1  # plain lacks the class


def test_missing_pvc_leaves_pod_pending():
    snap = Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p", pvcs=("ghost",))])
    got = run_all_paths(snap)
    assert got["p"] is None


# ------------------------- StorageClass dynamic provisioning (binder.go shape)


def _sc(name, provisioner="csi.example.com", mode="WaitForFirstConsumer", topo=()):
    from kubernetes_tpu.api import cluster as c

    return c.StorageClass(name=name, provisioner=provisioner,
                          volume_binding_mode=mode, allowed_topology=tuple(topo))


def test_wffc_provisioner_topology_constrains_nodes():
    """An unbound WaitForFirstConsumer claim whose class provisions only in
    zone a must steer the pod to zone a — on every execution path."""
    sc = _sc("zonal", topo=((t.LABEL_ZONE, "a"),))
    pvc = t.PersistentVolumeClaim(name="data", request=10 * GI, storage_class="zonal",
                                  wait_for_first_consumer=True)
    nodes = [
        mk_node("n-b", labels={t.LABEL_ZONE: "b"}),
        mk_node("n-a", labels={t.LABEL_ZONE: "a"}),
    ]
    snap = Snapshot(nodes=nodes, pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvcs={pvc.key: pvc}, storage_classes={"zonal": sc})
    assert run_all_paths(snap)["p"] == "n-a"


def test_immediate_unbound_claim_provisionable_schedules_anywhere():
    """No static PV exists, but the class provisions without topology limits:
    previously unschedulable, now feasible everywhere."""
    sc = _sc("fast", mode="Immediate")
    pvc = t.PersistentVolumeClaim(name="scratch", request=GI, storage_class="fast")
    snap = Snapshot(nodes=[mk_node("a"), mk_node("b")],
                    pending_pods=[mk_pod("p", pvcs=("scratch",))],
                    pvcs={pvc.key: pvc}, storage_classes={"fast": sc})
    assert run_all_paths(snap)["p"] == "a"


def test_unbound_claim_class_without_provisioner_unschedulable():
    sc = _sc("static-only", provisioner="")
    pvc = t.PersistentVolumeClaim(name="data", request=GI, storage_class="static-only",
                                  wait_for_first_consumer=True)
    snap = Snapshot(nodes=[mk_node("a")],
                    pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvcs={pvc.key: pvc}, storage_classes={"static-only": sc})
    assert run_all_paths(snap)["p"] is None


def test_static_candidates_or_with_provisioner_topology():
    """Options are ORed: a static PV in zone b OR provisioning in zone a."""
    sc = _sc("mixed", topo=((t.LABEL_ZONE, "a"),))
    pv = t.PersistentVolume(name="pv-b", capacity=100 * GI, storage_class="mixed",
                            allowed_topology=((t.LABEL_ZONE, "b"),))
    pvc = t.PersistentVolumeClaim(name="data", request=GI, storage_class="mixed",
                                  wait_for_first_consumer=True)
    nodes = [
        mk_node("n-c", labels={t.LABEL_ZONE: "c"}),
        mk_node("n-b", labels={t.LABEL_ZONE: "b"}),
        mk_node("n-a", labels={t.LABEL_ZONE: "a"}),
    ]
    snap = Snapshot(nodes=nodes, pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvs=[pv], pvcs={pvc.key: pvc}, storage_classes={"mixed": sc})
    got = run_all_paths(snap)
    assert got["p"] in ("n-a", "n-b")  # never zone c


# --------------------------------- DRA structured parameters (resource.k8s.io)


def _tpu_slices_and_class():
    from kubernetes_tpu.api import cluster as c

    dc = c.DeviceClass(name="tpu", selector=c.DeviceSelector(terms=(("type", "v5e"),)))
    slices = [
        c.ResourceSlice(
            name="n0-tpus", node_name="n0", driver="tpu.dev",
            devices=(
                c.DraDevice("d0", attributes=(("type", "v5e"),)),
                c.DraDevice("d1", attributes=(("type", "v5e"),)),
                c.DraDevice("d2", attributes=(("type", "v5e"),)),
                c.DraDevice("x0", attributes=(("type", "cpu"),)),  # not matched
            ),
        )
    ]
    return slices, {"tpu": dc}


def test_resource_slices_publish_per_node_device_counts():
    slices, classes = _tpu_slices_and_class()
    pod = mk_pod("p", resource_claims=(t.ResourceClaimRef("tpu", 2),))
    snap = Snapshot(nodes=[mk_node("n1"), mk_node("n0")], pending_pods=[pod],
                    resource_slices=slices, device_classes=classes)
    # only n0 publishes tpu devices (3 of 4 match the class selector)
    assert run_all_paths(snap)["p"] == "n0"


def test_device_claims_deplete_against_slice_inventory():
    slices, classes = _tpu_slices_and_class()
    first = mk_pod("a", resource_claims=(t.ResourceClaimRef("tpu", 2),))
    second = mk_pod("b", resource_claims=(t.ResourceClaimRef("tpu", 2),))
    snap = Snapshot(nodes=[mk_node("n0")], pending_pods=[first, second],
                    resource_slices=slices, device_classes=classes)
    got = run_all_paths(snap)
    assert got["a"] == "n0" and got["b"] is None  # 3 devices: 2 + 2 > 3


def test_oversized_claim_unschedulable():
    slices, classes = _tpu_slices_and_class()
    pod = mk_pod("p", resource_claims=(t.ResourceClaimRef("tpu", 5),))
    snap = Snapshot(nodes=[mk_node("n0")], pending_pods=[pod],
                    resource_slices=slices, device_classes=classes)
    assert run_all_paths(snap)["p"] is None


# ----------------------------------- PreBind volume binding + provisioning


def test_scheduler_binds_and_provisions_wffc_claim():
    """End-to-end through the CPU cycle: the WFFC claim is provisioned at
    PreBind — a PV appears, pinned to the chosen node's zone, and the PVC
    binds to it; a second pod sharing the claim must follow into the zone."""
    from kubernetes_tpu.api import cluster as c
    from kubernetes_tpu.scheduler.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.scheduler.store import ClusterStore

    store = ClusterStore()
    store.add_object("StorageClass", _sc("zonal"))
    for name, zone in (("n-a", "a"), ("n-b", "b")):
        store.add_node(t.Node(name=name, allocatable={t.CPU: 4000},
                              labels={t.LABEL_ZONE: zone}))
    store.add_pvc(t.PersistentVolumeClaim(name="data", request=10 * GI,
                                          storage_class="zonal",
                                          wait_for_first_consumer=True))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"))
    store.add_pod(t.Pod(name="writer", requests={t.CPU: 500}, pvcs=("data",)))
    sched.run_until_idle()
    writer = store.pods["default/writer"]
    assert writer.node_name in ("n-a", "n-b")
    zone = store.nodes[writer.node_name].labels[t.LABEL_ZONE]
    pvc = store.pvcs["default/data"]
    assert pvc.volume_name.startswith("pvc-default-data-")
    pv = store.pvs[pvc.volume_name]
    assert pv.claim_ref == "default/data"
    assert pv.allowed_topology == ((t.LABEL_ZONE, zone),)
    # a second consumer of the (now bound) claim must land in the same zone
    store.add_pod(t.Pod(name="reader", requests={t.CPU: 500}, pvcs=("data",)))
    sched.run_until_idle()
    reader = store.pods["default/reader"]
    assert store.nodes[reader.node_name].labels[t.LABEL_ZONE] == zone


def test_batch_mode_binds_volumes_and_keeps_pvc_constraints():
    """schedule_batch must carry PV/PVC/StorageClass state into its snapshot
    (regression: the rebuilt batch snapshot used to drop them) and run the
    PreBind volume commitment."""
    from kubernetes_tpu.scheduler.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.scheduler.store import ClusterStore

    store = ClusterStore()
    store.add_object("StorageClass", _sc("zonal", topo=((t.LABEL_ZONE, "a"),)))
    for name, zone in (("n-b", "b"), ("n-a", "a")):
        store.add_node(t.Node(name=name, allocatable={t.CPU: 4000},
                              labels={t.LABEL_ZONE: zone}))
    store.add_pvc(t.PersistentVolumeClaim(name="data", request=10 * GI,
                                          storage_class="zonal",
                                          wait_for_first_consumer=True))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(t.Pod(name="writer", requests={t.CPU: 500}, pvcs=("data",)))
    sched.run_until_idle()
    writer = store.pods["default/writer"]
    assert writer.node_name == "n-a"  # the class only provisions in zone a
    assert store.pvcs["default/data"].volume_name.startswith("pvc-default-data-")


def test_prebind_rejects_node_outside_provisioning_topology():
    """A same-batch sibling can consume the static PV a verdict relied on;
    PreBind must then refuse to provision outside the class topology instead
    of creating an unreachable volume."""
    from kubernetes_tpu.scheduler.store import ClusterStore
    from kubernetes_tpu.scheduler.volumebinder import bind_pod_volumes

    store = ClusterStore()
    store.add_object("StorageClass", _sc("mixed", topo=((t.LABEL_ZONE, "a"),)))
    store.add_node(t.Node(name="n-b", allocatable={t.CPU: 4000},
                          labels={t.LABEL_ZONE: "b"}))
    store.add_pvc(t.PersistentVolumeClaim(name="d", request=GI, storage_class="mixed",
                                          wait_for_first_consumer=True))
    err = bind_pod_volumes(store, t.Pod(name="p", pvcs=("d",)), "n-b")
    assert err is not None and "cannot provision" in err
    assert store.pvs == {}  # nothing was created


def test_prebind_rechecks_claim_bound_by_sibling():
    """A claim bound (by a sibling) after this pod's verdict must be
    topology-checked against the chosen node at PreBind."""
    from kubernetes_tpu.scheduler.store import ClusterStore
    from kubernetes_tpu.scheduler.volumebinder import bind_pod_volumes

    store = ClusterStore()
    store.add_node(t.Node(name="n-b", allocatable={t.CPU: 4000},
                          labels={t.LABEL_ZONE: "b"}))
    store.add_pv(t.PersistentVolume(name="pv-a", capacity=GI, storage_class="s",
                                    allowed_topology=((t.LABEL_ZONE, "a"),),
                                    claim_ref="default/d"))
    store.add_pvc(t.PersistentVolumeClaim(name="d", request=GI, storage_class="s",
                                          volume_name="pv-a"))
    err = bind_pod_volumes(store, t.Pod(name="p", pvcs=("d",)), "n-b")
    assert err is not None and "not reachable" in err


def test_multi_class_device_counts_are_exclusive():
    """One physical device matching two class selectors must satisfy only one
    class's capacity (exclusive allocation, first class in name order)."""
    from kubernetes_tpu.api import cluster as c
    from kubernetes_tpu.api.volumes import resolve_snapshot

    both = c.DraDevice("d0", attributes=(("type", "v5e"), ("fast", "yes")))
    slices = [c.ResourceSlice(name="s", node_name="n0", driver="d", devices=(both,))]
    classes = {
        "tpu": c.DeviceClass(name="tpu", selector=c.DeviceSelector(terms=(("type", "v5e"),))),
        "accel": c.DeviceClass(name="accel", selector=c.DeviceSelector(terms=(("fast", "yes"),))),
    }
    snap = resolve_snapshot(Snapshot(nodes=[mk_node("n0")], pending_pods=[mk_pod("p")],
                                     resource_slices=slices, device_classes=classes))
    alloc = snap.nodes[0].allocatable
    assert alloc.get("claim/accel", 0) == 1  # "accel" < "tpu" in name order
    assert alloc.get("claim/tpu", 0) == 0
