"""ImageLocality, volume family (VolumeZone/VolumeBinding-lite/
NodeVolumeLimits), DRA-lite resource claims — across all execution paths."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.native import schedule_batch_native
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config, schedule_batch
from kubernetes_tpu.oracle import oracle_schedule
from helpers import GI, mk_node, mk_pod


def run_all_paths(snap):
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    tpu = np.asarray(schedule_batch(arr, cfg)[0])
    native = schedule_batch_native(arr, cfg)[0]
    np.testing.assert_array_equal(native, tpu)
    got = [
        (meta.pod_names[k], meta.node_names[tpu[k]] if tpu[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule(snap)
    assert got == want, f"kernel={got} oracle={want}"
    return dict(got)


def test_image_locality_steers_to_cached_node():
    img = "registry.io/model-server:v3"
    nodes = [
        mk_node("cold"),
        mk_node("warm"),
    ]
    nodes[1].images[img] = 800 * 1024 * 1024  # 800 MB cached
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=[mk_pod("p", images=(img,))]))
    assert got["p"] == "warm"


def test_image_below_threshold_is_ignored():
    img = "tiny:latest"
    nodes = [mk_node("a"), mk_node("b")]
    nodes[1].images[img] = 10 * 1024 * 1024  # 10 MB < 23 MB threshold
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=[mk_pod("p", images=(img,))]))
    assert got["p"] == "a"  # tie -> lowest index


def test_bound_pvc_zone_restricts_nodes():
    pv = t.PersistentVolume(
        name="pv-a", capacity=100 * GI, storage_class="std",
        allowed_topology=((t.LABEL_ZONE, "a"),), claim_ref="default/data",
    )
    pvc = t.PersistentVolumeClaim(name="data", request=50 * GI, storage_class="std",
                                  volume_name="pv-a")
    nodes = [
        mk_node("n-b", labels={t.LABEL_ZONE: "b"}),
        mk_node("n-a", labels={t.LABEL_ZONE: "a"}),
    ]
    snap = Snapshot(nodes=nodes, pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvs=[pv], pvcs={pvc.key: pvc})
    got = run_all_paths(snap)
    assert got["p"] == "n-a"


def test_unbound_immediate_claim_without_pv_is_unschedulable():
    pvc = t.PersistentVolumeClaim(name="data", request=50 * GI, storage_class="fast")
    snap = Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvcs={pvc.key: pvc})
    got = run_all_paths(snap)
    assert got["p"] is None


def test_wait_for_first_consumer_claim_does_not_block():
    pvc = t.PersistentVolumeClaim(name="data", request=50 * GI, storage_class="fast",
                                  wait_for_first_consumer=True)
    snap = Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p", pvcs=("data",))],
                    pvcs={pvc.key: pvc})
    got = run_all_paths(snap)
    assert got["p"] == "n"


def test_volume_attach_limit_enforced():
    nodes = [mk_node("small"), mk_node("big")]
    nodes[0].volume_attach_limit = 1
    nodes[1].volume_attach_limit = 8
    pvcs = {}
    pods = []
    for i in range(3):
        pvc = t.PersistentVolumeClaim(name=f"d{i}", request=GI, storage_class="std",
                                      wait_for_first_consumer=True)
        pvcs[pvc.key] = pvc
        pods.append(mk_pod(f"p{i}", pvcs=(f"d{i}",)))
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=pods, pvcs=pvcs))
    # node "small" accepts at most 1 attached volume
    assert sum(1 for v in got.values() if v == "small") <= 1
    assert all(v is not None for v in got.values())


def test_resource_claims_consume_device_class():
    nodes = [mk_node("accel"), mk_node("plain")]
    nodes[0].allocatable["claim/tpu-v5e"] = 2
    pods = [
        mk_pod(f"p{i}", resource_claims=(t.ResourceClaimRef(device_class="tpu-v5e"),))
        for i in range(3)
    ]
    got = run_all_paths(Snapshot(nodes=nodes, pending_pods=pods))
    assert sum(1 for v in got.values() if v == "accel") == 2
    assert sum(1 for v in got.values() if v is None) == 1  # plain lacks the class


def test_missing_pvc_leaves_pod_pending():
    snap = Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p", pvcs=("ghost",))])
    got = run_all_paths(snap)
    assert got["p"] is None
