"""ktpu-verify shard pass (ISSUE 12): the declarative partition rule table
(parallel/partition_rules.py) + the KTPU014..018 sharding-flow gates
(analysis/shardcheck.py).

Ordering note (tier-1 runs -p no:randomly, so file order holds): the
acceptance gate runs first and pays this module's ONE full shard pass
(18-route trace, shared machinery with the device pass); every later test
reuses the cached report or builds synthetic RouteTraces."""

import json

import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu.analysis import shardcheck
from kubernetes_tpu.analysis.devicecheck import RouteTrace
from kubernetes_tpu.analysis.engine import Baseline, Report, analyze_source
from kubernetes_tpu.analysis.shardcheck import (
    SHARD_RULE_IDS,
    AxisConsistencyRule,
    CommReconcileRule,
    OutShardingDriftRule,
    ReplicatedGiantRule,
    ShardSpecLiteralRule,
    run_shard_pass,
)
from kubernetes_tpu.parallel import partition_rules as PR
from kubernetes_tpu.parallel.mesh import NODE_AXIS, shard_map

_PASS_CACHE = {}


def _full_pass() -> Report:
    """The one full shard pass this module pays for, over the 18-route
    trace shared with the device/mem modules (helpers.shared_route_traces)."""
    if "rep" not in _PASS_CACHE:
        from helpers import shared_route_traces

        from kubernetes_tpu.analysis.__main__ import default_baseline

        _PASS_CACHE["rep"] = run_shard_pass(
            baseline=Baseline.load(default_baseline()),
            pretraced=shared_route_traces())
    return _PASS_CACHE["rep"]


# ---- tentpole acceptance: the committed package is shard-pass clean ----

def test_committed_package_is_shard_pass_clean():
    """`python -m kubernetes_tpu.analysis --shard` exits 0 on the committed
    package under the committed baseline: all 18 routes traced (no silent
    skips — the 2-D pods x nodes grid tripled the matrix), KTPU014/016/017/
    018 clean, and the ROADMAP-3 replication debt is GONE: the pod axis
    shards every former KTPU015 giant, so the committed baseline is empty
    and zero KTPU015 findings fire at all."""
    rep = _full_pass()
    assert rep.errors == []
    assert rep.unbaselined == [], "\n".join(
        f.render() for f in rep.unbaselined)
    assert rep.exit_code == 0
    assert rep.device["n_traced"] == 18 and rep.device["n_skipped"] == 0
    assert [f for f in rep.findings if f.rule == "KTPU015"] == [], (
        "the pods axis must shard every scaling giant somewhere in the "
        "route matrix — a KTPU015 finding means replication debt returned")
    assert [f for f in rep.findings if f.baselined] == [], (
        "the 21-entry ROADMAP-3 baseline was burned to zero; nothing "
        "should need baselining now")


def test_committed_baseline_is_empty():
    """Satellite acceptance: analysis/baseline.json dropped its 21 KTPU015
    entries to 0 — the debt is paid by sharding, not waived by baseline."""
    from kubernetes_tpu.analysis.__main__ import default_baseline

    with open(default_baseline()) as f:
        doc = json.load(f)
    assert doc.get("findings") == []


def test_every_route_carries_a_shard_report():
    """Per-route shard block: resident-buffer fields resolved through the
    table, mesh routes carry a comm estimate + measured collective bytes
    and a compiled out-sharding report."""
    rep = _full_pass()
    for r in rep.device["routes"]:
        assert r["status"] == "traced"
        sh = r["shard"]
        assert sh["n_fields"] > 0
        if r["n_shards"] > 1:
            assert sh["comm_est"] and sh["comm_est"]["total"] > 0
            assert sh["comm_bytes_measured"] > 0
            if not r["donate"]:
                # out-shardings ride the donate-off compile the memory
                # stats already pay (the jit out specs are donate-invariant)
                assert sh["out_shardings"], r["name"]
                assert all(e["equivalent"] for e in sh["out_shardings"])


def test_ktpu017_committed_routes_reconcile_within_tolerance():
    """Acceptance: per-route measured collective bytes stay within the
    documented COMM_TOLERANCE of shard_comm_estimate on every mesh route."""
    rep = _full_pass()
    for r in rep.device["routes"]:
        if r["n_shards"] <= 1:
            continue
        measured = r["shard"]["comm_bytes_measured"]
        budget = r["shard"]["comm_est"]["total"]
        assert measured <= shardcheck.COMM_TOLERANCE * budget, r["name"]


# ---- the rule table ----

def test_table_resolves_every_resident_field_and_fails_closed():
    import dataclasses

    from kubernetes_tpu.api.snapshot import ClusterArrays
    from kubernetes_tpu.ops.incremental import IncState

    for f in dataclasses.fields(ClusterArrays):
        PR.spec_for(f"arr.{f.name}")
        assert f"arr.{f.name}" in PR.FIELD_DIMS, f.name
    for name in IncState._fields:
        PR.spec_for(f"inc.{name}")
        assert f"inc.{name}" in PR.FIELD_DIMS, name
    with pytest.raises(ValueError, match="no partition rule"):
        PR.spec_for("arr.some_future_field_nobody_added")


def test_node_axis_fields_derived_from_table():
    """mesh.NODE_AXIS_FIELDS is DERIVED (no parallel maintenance): node
    fields pad on exactly the axis the table shards, node_dom keeps the
    D-sentinel fill."""
    from kubernetes_tpu.parallel.mesh import NODE_AXIS_FIELDS

    assert NODE_AXIS_FIELDS == PR.node_axis_fields()
    assert NODE_AXIS_FIELDS["node_dom"] == (1, None)
    assert NODE_AXIS_FIELDS["node_valid"][0] == 0
    assert "image_score" not in NODE_AXIS_FIELDS
    assert set(NODE_AXIS_FIELDS) == {
        "node_valid", "node_alloc", "node_used", "node_unsched",
        "node_labels", "node_taint_ns", "node_taint_pref", "node_dom",
        "node_ports0",
    }


def test_sharded_wrappers_resolve_through_table(mesh8):
    """field_shardings == the table's NamedShardings, spec for spec — the
    refactored wrappers and the DeltaEncoder placement path read ONE
    authority (placements bit-identical is pinned by the existing
    test_sharded_routed / test_pipeline_parity suites)."""
    from kubernetes_tpu.parallel.sharded import field_shardings

    sh = field_shardings(mesh8, True)
    specs = PR.clusterarrays_specs(True)
    for name, ns in sh.items():
        assert tuple(ns.spec) == tuple(getattr(specs, name)), name
    assert tuple(sh["node_used"].spec) == (NODE_AXIS, None)
    assert tuple(sh["image_score"].spec) == (None, NODE_AXIS)
    assert tuple(field_shardings(mesh8, False)["image_score"].spec) == (
        None, None)


def test_shared_size_model_feeds_hbm_estimate():
    """The small-fix satellite: shard_hbm_estimate's resident_inputs term
    comes from the table-derived per-field model (the same one KTPU015
    thresholds), not a hand-listed sum."""
    from kubernetes_tpu.parallel.mesh import shard_hbm_estimate

    est = shard_hbm_estimate(1024, 256, 8, u_classes=32)
    assert est["resident_inputs"] == PR.resident_input_bytes(
        1024, 256, 8, u_classes=32)
    assert est["total"] >= est["resident_inputs"]


# ---- KTPU014 rule-table-resolution fixtures ----

def _lit_findings(src):
    return analyze_source(src, "kubernetes_tpu/scheduler/fx.py",
                          [ShardSpecLiteralRule()])


def test_ktpu014_namedsharding_literal_detected():
    src = (
        "from jax.sharding import NamedSharding, PartitionSpec\n"
        "s = NamedSharding(mesh, PartitionSpec('nodes'))\n"
    )
    fs = _lit_findings(src)
    assert len(fs) == 2  # the NamedSharding call AND the spec literal
    assert any("NamedSharding" in f.message for f in fs)


def test_ktpu014_aliased_partitionspec_literal_detected():
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "spec = P(None, 'nodes')\n"
    )
    fs = _lit_findings(src)
    assert fs and "P(...)" in fs[0].message


def test_ktpu014_device_put_sharding_kwarg_detected():
    src = (
        "import jax\n"
        "d = jax.device_put(x, sharding=s)\n"
    )
    fs = _lit_findings(src)
    assert fs and "device_put" in fs[0].message


def test_ktpu014_blessed_module_and_resolver_usage_pass():
    blessed = analyze_source(
        "from jax.sharding import PartitionSpec as P\nS = P('nodes')\n",
        shardcheck.TABLE_FILE, [ShardSpecLiteralRule()])
    assert blessed == []
    clean = _lit_findings(
        "from kubernetes_tpu.parallel.partition_rules import sharding_for\n"
        "import jax\n"
        "d = jax.device_put(x, sharding_for(mesh, 'arr.node_used'))\n"
    )
    assert clean == []


def test_ktpu014_package_has_single_spec_authority():
    """The refactor satellite held: no spec literal outside the table
    anywhere in the committed package."""
    from kubernetes_tpu.analysis.__main__ import default_root, resolve_root

    rep = run_shard_pass(rule_ids=["KTPU014"], baseline=Baseline([]),
                         root=resolve_root(default_root()))
    assert rep.errors == []
    assert rep.findings == [], "\n".join(f.render() for f in rep.findings)
    assert rep.device is None  # pure KTPU014: no route trace paid


# ---- KTPU015 replicated-giant fixtures ----

def _trace_with_fields(entries, n_shards=8):
    t = RouteTrace("fx/shard", kind="fixture", donate=False,
                   n_shards=n_shards)
    t.shard_fields = entries
    t.mesh_axes = {NODE_AXIS: n_shards} if n_shards > 1 else {}
    return t


def test_ktpu015_oversize_replicated_class_matrix_detected():
    """The ISSUE fixture: an oversize replicated [U, N] buffer (a class
    matrix someone forgot to shard) is a finding; the node-sharded twin and
    a bounded vocabulary table are not."""
    bad = {"qualname": "inc.base_u", "shape": (64, 128), "itemsize": 4,
           "spec": (None, None), "dims": ("U", "N")}
    ok_sharded = {"qualname": "inc.fit_u", "shape": (64, 128), "itemsize": 1,
                  "spec": (None, NODE_AXIS), "dims": ("U", "N")}
    ok_vocab = {"qualname": "arr.term_counts0", "shape": (8, 64),
                "itemsize": 4, "spec": (None, None), "dims": ("T2", "D1")}
    fs = ReplicatedGiantRule().check(
        [_trace_with_fields([bad, ok_sharded, ok_vocab])])
    assert len(fs) == 1 and "inc.base_u" in fs[0].message
    assert Report(findings=fs).exit_code == 1


def test_ktpu015_single_device_routes_not_judged():
    bad = {"qualname": "inc.base_u", "shape": (64, 128), "itemsize": 4,
           "spec": (None, None), "dims": ("U", "N")}
    assert ReplicatedGiantRule().check(
        [_trace_with_fields([bad], n_shards=1)]) == []


def test_ktpu015_finding_deduped_across_routes_and_fingerprint_stable():
    bad = {"qualname": "arr.pod_req", "shape": (128, 4), "itemsize": 4,
           "spec": (None, None), "dims": ("P", "R")}
    t1 = _trace_with_fields([bad])
    t2 = _trace_with_fields([dict(bad)])
    fs = ReplicatedGiantRule().check([t1, t2])
    assert len(fs) == 1  # one piece of debt, one baseline entry
    fs2 = ReplicatedGiantRule().check([t2])
    assert fs[0].fingerprint == fs2[0].fingerprint


# ---- KTPU016 axis-consistency fixtures ----

def test_ktpu016_unknown_axis_name_detected():
    e = {"qualname": "arr.node_used", "shape": (128, 4), "itemsize": 4,
         "spec": ("rows", None), "dims": ("N", "R")}
    fs = AxisConsistencyRule().check([_trace_with_fields([e])])
    assert fs and "does not exist in the mesh" in fs[0].message


def test_ktpu016_node_axis_on_wrong_dim_detected():
    e = {"qualname": "arr.node_used", "shape": (128, 4), "itemsize": 4,
         "spec": (None, NODE_AXIS), "dims": ("N", "R")}
    fs = AxisConsistencyRule().check([_trace_with_fields([e])])
    assert fs and "wrong-axis" in fs[0].message


def test_ktpu016_indivisible_padded_shape_detected_and_clean_passes():
    bad = {"qualname": "arr.node_used", "shape": (130, 4), "itemsize": 4,
           "spec": (NODE_AXIS, None), "dims": ("N", "R")}
    fs = AxisConsistencyRule().check([_trace_with_fields([bad])])
    assert fs and "does not divide" in fs[0].message
    ok = {"qualname": "arr.node_used", "shape": (128, 4), "itemsize": 4,
          "spec": (NODE_AXIS, None), "dims": ("N", "R")}
    assert AxisConsistencyRule().check([_trace_with_fields([ok])]) == []


# ---- KTPU017 comm-reconciliation fixtures ----

def test_ktpu017_injected_extra_all_gather_caught(mesh8):
    """A REAL traced program with an unbudgeted extra all-gather: measured
    bytes breach COMM_TOLERANCE x the analytic budget — exit 1."""
    from jax.sharding import PartitionSpec as P  # test fixture, not package

    def leaky(x):
        g = jax.lax.all_gather(x, NODE_AXIS)  # the accidental extra gather
        return jax.lax.psum(x, NODE_AXIS) + g.sum()

    fn = shard_map(leaky, mesh=mesh8, in_specs=(P(NODE_AXIS),),
                   out_specs=P(NODE_AXIS), check_rep=False)
    t = RouteTrace.from_callable("fx/leak", fn, jnp.ones(4096), n_shards=8)
    assert any(p == "all_gather" for p, _b in t.collective_bytes)
    measured = sum(b for _p, b in t.collective_bytes)
    t.comm_est = {"total": int(measured / (shardcheck.COMM_TOLERANCE * 2))}
    fs = CommReconcileRule().check([t])
    assert fs and "exceed" in fs[0].message
    assert Report(findings=fs).exit_code == 1


def test_ktpu017_within_tolerance_and_unestimated_pass():
    t = RouteTrace("fx/ok", kind="fixture", donate=False, n_shards=8)
    t.collective_bytes = [("all_gather", 1000)]
    t.comm_est = {"total": 900}
    assert CommReconcileRule().check([t]) == []
    t2 = RouteTrace("fx/noest", kind="fixture", donate=False, n_shards=8)
    t2.collective_bytes = [("all_gather", 10**9)]
    assert CommReconcileRule().check([t2]) == []  # no budget captured


def test_collective_bytes_walk_measures_output_sizes(mesh8):
    from jax.sharding import PartitionSpec as P  # test fixture

    fn = shard_map(lambda x: jax.lax.all_gather(x, NODE_AXIS), mesh=mesh8,
                   in_specs=(P(NODE_AXIS),), out_specs=P(NODE_AXIS, None),
                   check_rep=False)
    t = RouteTrace.from_callable(
        "fx/ag", fn, jnp.ones(64, jnp.float32), n_shards=8)
    ag = [(p, b) for p, b in t.collective_bytes if p == "all_gather"]
    assert ag == [("all_gather", 64 * 4)]  # [8, 8] f32 gathered per shard


# ---- KTPU018 out-sharding drift fixtures ----

def test_ktpu018_forced_replicated_output_detected():
    t = RouteTrace("fx/out", kind="fixture", donate=False, n_shards=8)
    t.out_sharding_report = [
        {"declared": "out.assignment", "compiled": "rep", "equivalent": True},
        {"declared": "out.node_used_scan", "compiled": "replicated!",
         "equivalent": False},
    ]
    fs = OutShardingDriftRule().check([t])
    assert len(fs) == 1 and "drifted" in fs[0].message
    assert "out.node_used_scan" in fs[0].message


def test_ktpu018_equivalent_and_uncaptured_pass():
    t = RouteTrace("fx/ok", kind="fixture", donate=False, n_shards=8)
    t.out_sharding_report = [
        {"declared": "out.assignment", "compiled": "rep", "equivalent": True},
    ]
    assert OutShardingDriftRule().check([t]) == []
    t2 = RouteTrace("fx/none", kind="fixture", donate=False, n_shards=8)
    assert OutShardingDriftRule().check([t2]) == []  # recorded, not guessed


# ---- CLI + harness wiring ----

def _canned_report():
    rep = Report(rules=list(SHARD_RULE_IDS))
    rep.device = {"routes": [], "n_traced": 0, "n_skipped": 0}
    return rep


def test_cli_shard_rule_subset_routes_to_shard_pass(monkeypatch, tmp_path):
    """--rules KTPU016 skips the AST walk and the device rules, runs ONLY
    the shard pass (canned — the real pass is paid once above)."""
    from kubernetes_tpu.analysis import __main__ as cli
    from kubernetes_tpu.analysis import devicecheck

    calls = {}

    def fake_shard(rule_ids=None, baseline=None, mesh_size=8,
                   pretraced=None, root=None):
        calls["rule_ids"] = list(rule_ids or [])
        calls["pretraced"] = pretraced
        return _canned_report()

    def fail_device(*a, **k):  # the device pass must NOT run
        raise AssertionError("device pass ran on a pure shard subset")

    monkeypatch.setattr(shardcheck, "run_shard_pass", fake_shard)
    monkeypatch.setattr(devicecheck, "run_device_pass", fail_device)
    out = tmp_path / "rep.json"
    rc = cli.main(["--rules", "KTPU016,KTPU018", "--format", "json",
                   "--output", str(out)])
    assert rc == 0
    assert calls["rule_ids"] == ["KTPU016", "KTPU018"]
    assert calls["pretraced"] is None
    doc = json.loads(out.read_text())
    assert "KTPU001" not in doc["rules"] and "KTPU007" not in doc["rules"]


def test_cli_device_and_shard_share_one_trace(monkeypatch, capsys):
    """--device --shard must collect the 12-route trace ONCE and hand it to
    both passes."""
    from kubernetes_tpu.analysis import __main__ as cli
    from kubernetes_tpu.analysis import devicecheck

    calls = {"collect": 0}
    sentinel = ([], [])

    def fake_collect(mesh_size=8):
        calls["collect"] += 1
        return sentinel

    def fake_device(rule_ids=None, baseline=None, mesh_size=8,
                    pretraced=None):
        calls["dev_pretraced"] = pretraced
        return _canned_report()

    def fake_shard(rule_ids=None, baseline=None, mesh_size=8,
                   pretraced=None, root=None):
        calls["shd_pretraced"] = pretraced
        return _canned_report()

    monkeypatch.setattr(devicecheck, "collect_traces", fake_collect)
    monkeypatch.setattr(devicecheck, "run_device_pass", fake_device)
    monkeypatch.setattr(shardcheck, "run_shard_pass", fake_shard)
    rc = cli.main(["--rules", "KTPU013", "--device", "--shard",
                   "--format", "json"])
    capsys.readouterr()
    assert rc == 0
    assert calls["collect"] == 1
    assert calls["dev_pretraced"] is sentinel
    assert calls["shd_pretraced"] is sentinel


def test_cli_typoed_shard_rule_id_refused():
    # KTPU099 does not exist (KTPU019 became the device cost observatory's
    # sub-phase ledger rule): a typoed id must refuse, never select zero
    # rules and exit 0
    from kubernetes_tpu.analysis import __main__ as cli

    with pytest.raises(SystemExit) as ei:
        cli.main(["--rules", "KTPU015,KTPU099"])
    assert ei.value.code == 2


def test_harness_verify_shard_embeds_report(monkeypatch, tmp_path):
    """--verify-shard implies --verify and ships the shard-pass device
    block in the artifact's verify report (canned pass — wiring only)."""
    from kubernetes_tpu.analysis import __main__ as cli
    from kubernetes_tpu.bench import harness

    seen = {}

    def fake_verify(root=None, baseline_path=None, device=False,
                    shard=False, mem=False):
        seen["device"] = device
        seen["shard"] = shard
        seen["mem"] = mem
        rep = _canned_report()
        rep.device = {
            "routes": [{
                "name": "chunked/nodonate/mesh8", "n_shards": 8,
                "shard": {"comm_bytes_measured": 8832},
            }],
            "n_traced": 1, "n_skipped": 0,
        }
        return rep

    monkeypatch.setattr(cli, "run_verify", fake_verify)
    yaml = tmp_path / "tiny.yaml"
    yaml.write_text(
        "name: Tiny\nops:\n"
        "  - {op: createCluster, generator: basic, nodes: 8, pods: 16}\n"
        "  - {op: measure}\n"
    )
    out = tmp_path / "out.json"
    harness.main(["--config", str(yaml), "--out", str(out),
                  "--verify-shard"])
    assert seen["shard"] is True and seen["device"] is False
    doc = json.loads(out.read_text())

    def find_key(d, key):
        if isinstance(d, dict):
            if key in d:
                return d[key]
            for v in d.values():
                r = find_key(v, key)
                if r is not None:
                    return r
        if isinstance(d, list):
            for v in d:
                r = find_key(v, key)
                if r is not None:
                    return r
        return None

    v = find_key(doc, "verify")
    assert v is not None and "device" in v
    # the regression-gate metric is stamped top-level next to step_s
    assert find_key(doc, "comm_bytes") == 8832


def test_regression_gate_learns_comm_bytes(tmp_path):
    """bench.regression --metric comm_bytes: an all-gather-budget blowup
    beyond threshold fails the gate exactly like a step-time regression."""
    from kubernetes_tpu.bench import regression

    good = {"platform": "cpu-sim", "comm_bytes": 9000, "step_s": 1.0}
    blown = {"platform": "cpu-sim", "comm_bytes": 20000, "step_s": 1.0}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(good))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(blown))
    rc = regression.main(["--dir", str(tmp_path), "--metric", "comm_bytes"])
    assert rc == 1  # 2.2x the budget is a regression
    blown["comm_bytes"] = 9100
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(blown))
    rc = regression.main(["--dir", str(tmp_path), "--metric", "comm_bytes"])
    assert rc == 0


# ---- finding identity ----

def test_field_finding_fingerprints_are_table_stable():
    from kubernetes_tpu.analysis.shardcheck import _field_finding

    a = _field_finding("KTPU015", "arr.pod_req", "msg one",
                       "replicated-giant:arr.pod_req:PxR")
    b = _field_finding("KTPU015", "arr.pod_req", "msg two (reworded)",
                       "replicated-giant:arr.pod_req:PxR")
    assert a.fingerprint == b.fingerprint
    assert a.file == shardcheck.TABLE_FILE
