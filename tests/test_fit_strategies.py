"""NodeResourcesFit scoring strategies (noderesources/least_allocated.go,
most_allocated.go, requested_to_capacity_ratio.go): LeastAllocated (default),
MostAllocated (bin-packing), RequestedToCapacityRatio (user shape) — decision-
identical across the XLA kernels, the C++ engine, the CPU plugin path, and
the oracle."""

import dataclasses
import random

import numpy as np
import pytest

from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch
from kubernetes_tpu.ops.scores import infer_score_config
from kubernetes_tpu.oracle import oracle_schedule
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import Profile, validate
from helpers import mk_node, mk_pod, random_cluster

STRATEGIES = [
    ("LeastAllocated", ((0.0, 0.0), (100.0, 10.0))),
    ("MostAllocated", ((0.0, 0.0), (100.0, 10.0))),
    ("RequestedToCapacityRatio", ((0.0, 10.0), (50.0, 2.0), (100.0, 0.0))),
]


def _cfg(strategy, shape):
    return dataclasses.replace(
        DEFAULT_SCORE_CONFIG, fit_strategy=strategy, rtcr_shape=shape
    )


@pytest.mark.parametrize("strategy,shape", STRATEGIES)
def test_kernel_oracle_parity(strategy, shape):
    rng = random.Random(hash(strategy) % 1000)
    snap = random_cluster(rng, n_nodes=12, n_pods=40, with_taints=True,
                          with_selectors=True)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, _cfg(strategy, shape))
    choices = np.asarray(schedule_batch(arr, cfg)[0])
    got = [(meta.pod_names[k],
            meta.node_names[int(choices[k])] if int(choices[k]) >= 0 else None)
           for k in range(meta.n_pods)]
    assert got == oracle_schedule(snap, cfg)


@pytest.mark.parametrize("strategy,shape", STRATEGIES)
def test_native_parity(strategy, shape):
    from kubernetes_tpu.native import schedule_batch_native

    rng = random.Random(1 + hash(strategy) % 1000)
    snap = random_cluster(rng, n_nodes=10, n_pods=30, with_taints=False,
                          with_selectors=True)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, _cfg(strategy, shape))
    kern = np.asarray(schedule_batch(arr, cfg)[0])[: meta.n_pods]
    nat = np.asarray(schedule_batch_native(arr, cfg)[0])[: meta.n_pods]
    np.testing.assert_array_equal(kern, nat)


def test_most_allocated_packs_instead_of_spreading():
    """The strategies must actually change placement: MostAllocated packs
    onto the busy node the default strategy avoids."""
    def run(strategy):
        store = ClusterStore()
        store.add_node(mk_node("busy", cpu=4000))
        store.add_node(mk_node("idle", cpu=4000))
        store.add_pod(mk_pod("filler", cpu=2000, node_name="busy"))
        cfg = SchedulerConfiguration(
            mode="tpu", profiles=(Profile(fit_strategy=strategy),)
        )
        assert not validate(cfg)
        sched = Scheduler(store, cfg)
        store.add_pod(mk_pod("p", cpu=500))
        sched.run_until_idle()
        return store.pods["default/p"].node_name

    assert run("LeastAllocated") == "idle"
    assert run("MostAllocated") == "busy"


def test_rtcr_shape_validation():
    bad = SchedulerConfiguration(
        profiles=(Profile(fit_strategy="RequestedToCapacityRatio",
                          rtcr_shape=((50.0, 1.0), (0.0, 0.0))),)
    )
    assert any("rtcr shape" in e for e in validate(bad))
    worse = SchedulerConfiguration(profiles=(Profile(fit_strategy="Sideways"),))
    assert any("scoringStrategy" in e for e in validate(worse))


def test_rtcr_exact_fit_parity_non_round_tripping_shape():
    """util == 100 exactly (pod request == allocatable) with a shape whose
    segment formula ys[n-2] + 1.0*(ys[n-1]-ys[n-2]) does NOT round-trip to
    ys[n-1] in float32 (y = 0.1/0.3): the C++ engine used to early-return
    ys[n-1] at util >= xs[n-1] while the kernel and oracle fall through to
    the segment formula, diverging at exact-fit utilization (round-3
    advisor, medium).  All engines must agree bit-for-bit."""
    from kubernetes_tpu.native import schedule_batch_native

    shape = ((0.0, 0.1), (100.0, 0.3))
    # two nodes scoring differently only through the RTCR shape; the pod
    # fills node n0 EXACTLY (util == 100 on both scored resources)
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=500, mem=512 * 1024**2),
               mk_node("n1", cpu=4000, mem=8 * 1024**3)],
        pending_pods=[mk_pod("exact", cpu=500, mem=512 * 1024**2)],
    )
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, _cfg("RequestedToCapacityRatio", shape))
    kern = np.asarray(schedule_batch(arr, cfg)[0])[: meta.n_pods]
    nat = np.asarray(schedule_batch_native(arr, cfg)[0])[: meta.n_pods]
    np.testing.assert_array_equal(kern, nat)
    got = [(meta.pod_names[k],
            meta.node_names[int(kern[k])] if int(kern[k]) >= 0 else None)
           for k in range(meta.n_pods)]
    assert got == oracle_schedule(snap, cfg)


def test_rtcr_zero_capacity_scores_as_max_utilization():
    """capacity == 0 scores as the shape value at 100% utilization — the
    reference's resourceScoringFunction returns rawScoringFunction(
    maxUtilization) for capacity 0, NOT 0 (round-3 advisor, low).  With a
    decreasing shape (high score at low utilization) a zero-memory node
    must therefore score LOW on that resource, steering the pod to the
    provisioned node; all engines agree."""
    from kubernetes_tpu.native import schedule_batch_native

    shape = ((0.0, 10.0), (100.0, 0.0))
    snap = Snapshot(
        # n0 has NO memory capacity; the pod requests none, so n0 is
        # feasible — but its memory axis scores at 100% utilization (0.0)
        nodes=[mk_node("n0", cpu=4000, mem=0), mk_node("n1", cpu=4000)],
        pending_pods=[mk_pod("memless", cpu=100, mem=0)],
    )
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, _cfg("RequestedToCapacityRatio", shape))
    kern = np.asarray(schedule_batch(arr, cfg)[0])[: meta.n_pods]
    nat = np.asarray(schedule_batch_native(arr, cfg)[0])[: meta.n_pods]
    np.testing.assert_array_equal(kern, nat)
    got = [(meta.pod_names[k],
            meta.node_names[int(kern[k])] if int(kern[k]) >= 0 else None)
           for k in range(meta.n_pods)]
    assert got == oracle_schedule(snap, cfg)
    # the zero-capacity node must NOT win: its memory score is 0, n1's ~10
    assert got[0][1] == "n1"
