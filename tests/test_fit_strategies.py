"""NodeResourcesFit scoring strategies (noderesources/least_allocated.go,
most_allocated.go, requested_to_capacity_ratio.go): LeastAllocated (default),
MostAllocated (bin-packing), RequestedToCapacityRatio (user shape) — decision-
identical across the XLA kernels, the C++ engine, the CPU plugin path, and
the oracle."""

import dataclasses
import random

import numpy as np
import pytest

from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch
from kubernetes_tpu.ops.scores import infer_score_config
from kubernetes_tpu.oracle import oracle_schedule
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import Profile, validate
from helpers import mk_node, mk_pod, random_cluster

STRATEGIES = [
    ("LeastAllocated", ((0.0, 0.0), (100.0, 10.0))),
    ("MostAllocated", ((0.0, 0.0), (100.0, 10.0))),
    ("RequestedToCapacityRatio", ((0.0, 10.0), (50.0, 2.0), (100.0, 0.0))),
]


def _cfg(strategy, shape):
    return dataclasses.replace(
        DEFAULT_SCORE_CONFIG, fit_strategy=strategy, rtcr_shape=shape
    )


@pytest.mark.parametrize("strategy,shape", STRATEGIES)
def test_kernel_oracle_parity(strategy, shape):
    rng = random.Random(hash(strategy) % 1000)
    snap = random_cluster(rng, n_nodes=12, n_pods=40, with_taints=True,
                          with_selectors=True)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, _cfg(strategy, shape))
    choices = np.asarray(schedule_batch(arr, cfg)[0])
    got = [(meta.pod_names[k],
            meta.node_names[int(choices[k])] if int(choices[k]) >= 0 else None)
           for k in range(meta.n_pods)]
    assert got == oracle_schedule(snap, cfg)


@pytest.mark.parametrize("strategy,shape", STRATEGIES)
def test_native_parity(strategy, shape):
    from kubernetes_tpu.native import schedule_batch_native

    rng = random.Random(1 + hash(strategy) % 1000)
    snap = random_cluster(rng, n_nodes=10, n_pods=30, with_taints=False,
                          with_selectors=True)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, _cfg(strategy, shape))
    kern = np.asarray(schedule_batch(arr, cfg)[0])[: meta.n_pods]
    nat = np.asarray(schedule_batch_native(arr, cfg)[0])[: meta.n_pods]
    np.testing.assert_array_equal(kern, nat)


def test_most_allocated_packs_instead_of_spreading():
    """The strategies must actually change placement: MostAllocated packs
    onto the busy node the default strategy avoids."""
    def run(strategy):
        store = ClusterStore()
        store.add_node(mk_node("busy", cpu=4000))
        store.add_node(mk_node("idle", cpu=4000))
        store.add_pod(mk_pod("filler", cpu=2000, node_name="busy"))
        cfg = SchedulerConfiguration(
            mode="tpu", profiles=(Profile(fit_strategy=strategy),)
        )
        assert not validate(cfg)
        sched = Scheduler(store, cfg)
        store.add_pod(mk_pod("p", cpu=500))
        sched.run_until_idle()
        return store.pods["default/p"].node_name

    assert run("LeastAllocated") == "idle"
    assert run("MostAllocated") == "busy"


def test_rtcr_shape_validation():
    bad = SchedulerConfiguration(
        profiles=(Profile(fit_strategy="RequestedToCapacityRatio",
                          rtcr_shape=((50.0, 1.0), (0.0, 0.0))),)
    )
    assert any("rtcr shape" in e for e in validate(bad))
    worse = SchedulerConfiguration(profiles=(Profile(fit_strategy="Sideways"),))
    assert any("scoringStrategy" in e for e in validate(worse))
