"""Multi-profile dispatch: pods select their scheduling profile by
spec.schedulerName (schedule_one.go — frameworkForPod); pods naming a
profile this scheduler does not serve are ignored entirely."""

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import PluginSpec, Profile
from helpers import mk_node, mk_pod


def _two_profile_cfg(mode):
    """default-scheduler = stock weights; busy-packer disables
    LeastAllocated+Balanced and prefers ALREADY-BUSY nodes via
    MostAllocated-like behavior... kept simple: it zeroes both usage
    scores, so among feasible nodes it picks the LOWEST INDEX regardless
    of load, while the default profile spreads to the idle node."""
    return SchedulerConfiguration(
        mode=mode,
        profiles=(
            Profile(),
            Profile(
                scheduler_name="busy-packer",
                plugins=(
                    PluginSpec(name="NodeResourcesFit", enabled=False),
                    PluginSpec(
                        name="NodeResourcesBalancedAllocation", enabled=False
                    ),
                ),
            ),
        ),
    )


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_pods_dispatch_to_their_profile(mode):
    store = ClusterStore()
    # n0 busy, n1 idle: default profile prefers n1 (least-allocated);
    # busy-packer scores both equally -> lowest index n0
    store.add_node(mk_node("n0", cpu=4000))
    store.add_node(mk_node("n1", cpu=4000))
    store.add_pod(mk_pod("filler", cpu=2000, node_name="n0"))
    sched = Scheduler(store, _two_profile_cfg(mode))
    store.add_pod(mk_pod("default-pod", cpu=500))
    p = mk_pod("packer-pod", cpu=500)
    p.scheduler_name = "busy-packer"
    store.add_pod(p)
    sched.run_until_idle()
    pods = {q.name: q.node_name for q in store.pods.values()}
    assert pods["default-pod"] == "n1"
    assert pods["packer-pod"] == "n0"


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_unknown_scheduler_name_ignored(mode):
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=4000))
    sched = Scheduler(store, SchedulerConfiguration(mode=mode))
    store.add_pod(mk_pod("ours", cpu=500))
    other = mk_pod("theirs", cpu=500)
    other.scheduler_name = "some-other-scheduler"
    store.add_pod(other)
    sched.run_until_idle()
    pods = {q.name: q for q in store.pods.values()}
    assert pods["ours"].node_name == "n0"
    # not scheduled, not failed — simply not ours
    assert pods["theirs"].node_name == ""
    assert not any(
        e.pod == other.uid for e in sched.events.by_reason("FailedScheduling")
    )


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_disabled_plugin_keeps_its_filter(mode):
    """PluginSpec(enabled=False) disables the SCORE point only — exactly the
    batch kernels' lowering (score weight 0, feasibility always enforced).
    Regression: the CPU path once dropped the whole plugin, letting a pod
    overcommit a node the kernels would reject."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=1000))
    cfg = SchedulerConfiguration(
        mode=mode,
        profiles=(
            Profile(plugins=(PluginSpec(name="NodeResourcesFit", enabled=False),)),
        ),
    )
    sched = Scheduler(store, cfg)
    store.add_pod(mk_pod("big", cpu=5000))
    sched.run_until_idle()
    assert store.pods[next(iter(store.pods))].node_name == ""  # stays pending


def test_other_profile_requeue_accrues_no_backoff():
    """A batch cycle drains the whole activeQ but schedules one profile per
    cycle; the other profiles' pods are handed back untouched and must not
    accrue exponential backoff for the phantom attempt (queue.pop_all bumps
    the attempt counter; the requeue forgives it)."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=64000, pods=200))
    cfg = _two_profile_cfg("tpu")
    sched = Scheduler(store, cfg)
    for i in range(6):
        p = mk_pod(f"a{i}", cpu=100)
        store.add_pod(p)
        q = mk_pod(f"b{i}", cpu=100)
        q.scheduler_name = "busy-packer"
        store.add_pod(q)
    sched.run_until_idle()
    assert all(p.node_name == "n0" for p in store.pods.values())
    # nobody failed scheduling, so nobody should carry attempt counts that
    # inflate a FUTURE failure's backoff beyond the initial step
    assert all(v <= 1 for v in sched.queue._attempts.values()), (
        sched.queue._attempts
    )


def test_custom_weight_profile_never_offloads_to_sidecar():
    """The wire protocol carries hardPodAffinityWeight but not arbitrary
    plugin weights, so a profile with customized score weights schedules
    in-process (kernels honor its ScoreConfig) instead of receiving
    default-weight verdicts from the sidecar.  With a dead sidecar address
    this only works if the offload is skipped entirely — no fallback
    metric, no connection attempt."""
    from kubernetes_tpu.scheduler.config import TPUScoreArgs

    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=4000))
    cfg = SchedulerConfiguration(
        mode="tpu",
        profiles=(
            Profile(
                plugins=(PluginSpec(name="TaintToleration", weight=9.0),),
                tpu_score=TPUScoreArgs(
                    sidecar_address="127.0.0.1:1"  # nothing listens here
                ),
            ),
        ),
    )
    sched = Scheduler(store, cfg)
    store.add_pod(mk_pod("p", cpu=500))
    sched.run_until_idle()
    assert store.pods["default/p"].node_name == "n0"
    assert sched.metrics.counters.get("tpuscore_fallback_total", 0) == 0


def test_batch_lead_profile_round_robins():
    """Continuous arrivals on one profile must not starve another: the
    batch cycle rotates its lead profile over the profiles present."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=64000, pods=500))
    sched = Scheduler(store, _two_profile_cfg("tpu"))
    for i in range(4):
        store.add_pod(mk_pod(f"a{i}", cpu=100))
        q = mk_pod(f"b{i}", cpu=100)
        q.scheduler_name = "busy-packer"
        store.add_pod(q)
    # first cycle serves one profile and requeues the other...
    first = sched.schedule_batch()
    served1 = {n for n, v in first.items() if v}
    assert served1 and len({n[0] for n in served1}) == 1  # ONE profile/cycle
    # ...the next cycle must serve the OTHER profile even though new pods
    # keep arriving on the first one
    lead1 = sched._last_profile_served
    for i in range(4, 8):
        p = mk_pod(f"a{i}", cpu=100)
        store.add_pod(p)
    sched.schedule_batch()
    assert sched._last_profile_served != lead1
    sched.run_until_idle()
    assert all(p.node_name for p in store.pods.values())
