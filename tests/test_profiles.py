"""Multi-profile dispatch: pods select their scheduling profile by
spec.schedulerName (schedule_one.go — frameworkForPod); pods naming a
profile this scheduler does not serve are ignored entirely."""

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import PluginSpec, Profile
from helpers import mk_node, mk_pod


def _two_profile_cfg(mode):
    """default-scheduler = stock weights; busy-packer disables
    LeastAllocated+Balanced and prefers ALREADY-BUSY nodes via
    MostAllocated-like behavior... kept simple: it zeroes both usage
    scores, so among feasible nodes it picks the LOWEST INDEX regardless
    of load, while the default profile spreads to the idle node."""
    return SchedulerConfiguration(
        mode=mode,
        profiles=(
            Profile(),
            Profile(
                scheduler_name="busy-packer",
                plugins=(
                    PluginSpec(name="NodeResourcesFit", enabled=False),
                    PluginSpec(
                        name="NodeResourcesBalancedAllocation", enabled=False
                    ),
                ),
            ),
        ),
    )


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_pods_dispatch_to_their_profile(mode):
    store = ClusterStore()
    # n0 busy, n1 idle: default profile prefers n1 (least-allocated);
    # busy-packer scores both equally -> lowest index n0
    store.add_node(mk_node("n0", cpu=4000))
    store.add_node(mk_node("n1", cpu=4000))
    store.add_pod(mk_pod("filler", cpu=2000, node_name="n0"))
    sched = Scheduler(store, _two_profile_cfg(mode))
    store.add_pod(mk_pod("default-pod", cpu=500))
    p = mk_pod("packer-pod", cpu=500)
    p.scheduler_name = "busy-packer"
    store.add_pod(p)
    sched.run_until_idle()
    pods = {q.name: q.node_name for q in store.pods.values()}
    assert pods["default-pod"] == "n1"
    assert pods["packer-pod"] == "n0"


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_unknown_scheduler_name_ignored(mode):
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=4000))
    sched = Scheduler(store, SchedulerConfiguration(mode=mode))
    store.add_pod(mk_pod("ours", cpu=500))
    other = mk_pod("theirs", cpu=500)
    other.scheduler_name = "some-other-scheduler"
    store.add_pod(other)
    sched.run_until_idle()
    pods = {q.name: q for q in store.pods.values()}
    assert pods["ours"].node_name == "n0"
    # not scheduled, not failed — simply not ours
    assert pods["theirs"].node_name == ""
    assert not any(
        e.pod == other.uid for e in sched.events.by_reason("FailedScheduling")
    )


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_disabled_plugin_keeps_its_filter(mode):
    """PluginSpec(enabled=False) disables the SCORE point only — exactly the
    batch kernels' lowering (score weight 0, feasibility always enforced).
    Regression: the CPU path once dropped the whole plugin, letting a pod
    overcommit a node the kernels would reject."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=1000))
    cfg = SchedulerConfiguration(
        mode=mode,
        profiles=(
            Profile(plugins=(PluginSpec(name="NodeResourcesFit", enabled=False),)),
        ),
    )
    sched = Scheduler(store, cfg)
    store.add_pod(mk_pod("big", cpu=5000))
    sched.run_until_idle()
    assert store.pods[next(iter(store.pods))].node_name == ""  # stays pending


def test_mixed_profile_batch_schedules_in_one_cycle():
    """A mixed-schedulerName batch runs its per-profile programs
    back-to-back within ONE cycle (round 3 requeued the non-lead profiles,
    serializing the stream), and nobody accrues backoff attempts for it."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=64000, pods=200))
    cfg = _two_profile_cfg("tpu")
    sched = Scheduler(store, cfg)
    for i in range(6):
        p = mk_pod(f"a{i}", cpu=100)
        store.add_pod(p)
        q = mk_pod(f"b{i}", cpu=100)
        q.scheduler_name = "busy-packer"
        store.add_pod(q)
    first = sched.schedule_batch()
    # every pod of BOTH profiles scheduled by the single cycle
    assert len(first) == 12 and all(v == "n0" for v in first.values())
    sched.run_until_idle()
    assert all(p.node_name == "n0" for p in store.pods.values())
    # nobody failed scheduling, so nobody should carry attempt counts that
    # inflate a FUTURE failure's backoff beyond the initial step
    assert all(v <= 1 for v in sched.queue._attempts.values()), (
        sched.queue._attempts
    )


def test_custom_weight_profile_never_offloads_to_sidecar():
    """The wire protocol carries hardPodAffinityWeight but not arbitrary
    plugin weights, so a profile with customized score weights schedules
    in-process (kernels honor its ScoreConfig) instead of receiving
    default-weight verdicts from the sidecar.  With a dead sidecar address
    this only works if the offload is skipped entirely — no fallback
    metric, no connection attempt."""
    from kubernetes_tpu.scheduler.config import TPUScoreArgs

    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=4000))
    cfg = SchedulerConfiguration(
        mode="tpu",
        profiles=(
            Profile(
                plugins=(PluginSpec(name="TaintToleration", weight=9.0),),
                tpu_score=TPUScoreArgs(
                    sidecar_address="127.0.0.1:1"  # nothing listens here
                ),
            ),
        ),
    )
    sched = Scheduler(store, cfg)
    store.add_pod(mk_pod("p", cpu=500))
    sched.run_until_idle()
    assert store.pods["default/p"].node_name == "n0"
    assert sched.metrics.counters.get("tpuscore_fallback_total", 0) == 0


def test_batch_lead_profile_round_robins():
    """The lead (the profile with FIRST claim on free capacity within the
    cycle) rotates across cycles, so continuous arrivals on one profile
    cannot always grab capacity first."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=64000, pods=500))
    sched = Scheduler(store, _two_profile_cfg("tpu"))
    for i in range(4):
        store.add_pod(mk_pod(f"a{i}", cpu=100))
        q = mk_pod(f"b{i}", cpu=100)
        q.scheduler_name = "busy-packer"
        store.add_pod(q)
    # one cycle serves BOTH profiles; the lead is recorded
    first = sched.schedule_batch()
    assert len([v for v in first.values() if v]) == 8
    lead1 = sched._last_profile_served
    # next mixed cycle leads with the OTHER profile
    for i in range(4, 8):
        store.add_pod(mk_pod(f"a{i}", cpu=100))
        q = mk_pod(f"b{i}", cpu=100)
        q.scheduler_name = "busy-packer"
        store.add_pod(q)
    sched.schedule_batch()
    assert sched._last_profile_served != lead1
    sched.run_until_idle()
    assert all(p.node_name for p in store.pods.values())


def test_cross_profile_gang_coalesces_to_one_program():
    """PodGroup members carrying different schedulerNames would deadlock if
    split across per-profile programs (no program ever sees min_member);
    the cycle coalesces the gang under its first-seen member's profile and
    records an event (round-3 advisor finding)."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=64000, pods=200))
    cfg = _two_profile_cfg("tpu")
    sched = Scheduler(store, cfg)
    sched.cache.pod_groups["job"] = t.PodGroup(name="job", min_member=4)
    for i in range(4):
        p = mk_pod(f"g{i}", cpu=100)
        p.pod_group = "job"
        p.labels = {"job": "job"}
        if i % 2:
            p.scheduler_name = "busy-packer"
        store.add_pod(p)
    res = sched.schedule_batch()
    assert len([v for v in res.values() if v]) == 4, res
    assert sched.events.by_reason("GangProfileCoalesced")
    assert all(p.node_name == "n0" for p in store.pods.values())
