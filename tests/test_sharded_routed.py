"""Sharded PRODUCTION kernels (chunked / rounds under shard_map) vs their
single-device counterparts — the routed north-star step across all chips.

Parity matrix per ISSUE 4: {chunked, rounds} x {donation on/off} x
{divisible, padded node count}, decisions (and node usage, up to the padded
tail) bit-identical.  Runs tier-1-safe on the conftest-forced 8-device CPU
platform (mesh8 fixture); the full-scale variant is @slow.  A seeded chaos
storm drives the whole Scheduler batch path with KTPU_MESH=8 armed — a
sharded trick that cannot survive the storm is not landable (ROADMAP).
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.snapshot import encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops.assign import (
    TRACE_COUNTS,
    schedule_batch_ordinals_routed,
    schedule_batch_routed,
)
from helpers import random_cluster


@pytest.fixture(autouse=True)
def _force_production_route(monkeypatch):
    """Route the chunked/rounds kernels on the CPU sim (read per call), so
    both the sharded run and its single-device comparator take the SAME
    production route the TPU backend would."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")


def _chunked_snap(divisible: bool):
    # fit-only workload (infer_score_config strips every other stage) with
    # P a multiple of the chunk size -> the chunked top-K kernel routes.
    rng = random.Random(42 + divisible)
    if divisible:
        # bucketed: N=32 (divides 8), P=128
        return random_cluster(rng, n_nodes=24, n_pods=120), True
    # unbucketed: N=27 pads to 32 inside the sharded wrapper, P=128 exact
    return random_cluster(rng, n_nodes=27, n_pods=128), False


def _rounds_snap(divisible: bool):
    # full stage set (taints/selectors/pairwise) -> the rounds kernel routes
    rng = random.Random(9 + divisible)
    if divisible:
        return random_cluster(
            rng, n_nodes=24, n_pods=50,
            with_taints=True, with_selectors=True, with_pairwise=True,
        ), True
    return random_cluster(
        rng, n_nodes=27, n_pods=48,
        with_taints=True, with_selectors=True, with_pairwise=True,
    ), False


def _assert_parity(mesh, snap, bucket, cfg=None, donate=False, route=None):
    arr, meta = encode_snapshot(snap, bucket=bucket)
    cfg = cfg if cfg is not None else infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    n = arr.N
    if route is not None:
        # force a fresh trace so the route proof below is STRICT — a warm
        # jit cache would otherwise make the counter check vacuous (the
        # TRACE_COUNTS caveat in ops/assign.py)
        import jax

        jax.clear_caches()
    before = dict(TRACE_COUNTS)
    want, want_used = schedule_batch_routed(arr, cfg, donate=False)
    got, got_used = schedule_batch_routed(arr, cfg, donate=donate, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # padded runs return the padded node axis; the tail rows are always zero
    gu = np.asarray(got_used)
    np.testing.assert_array_equal(gu[:n], np.asarray(want_used))
    assert not gu[n:].any()
    if route is not None:
        # the sharded program really compiled for this route
        assert TRACE_COUNTS[route] > before[route], (before, TRACE_COUNTS)
    return arr, cfg


@pytest.mark.parametrize("donate", [False, True])
@pytest.mark.parametrize("divisible", [True, False])
def test_sharded_chunked_parity(mesh8, donate, divisible, monkeypatch):
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap, bucket = _chunked_snap(divisible)
    arr, cfg = _assert_parity(
        mesh8, snap, bucket, donate=donate, route="sharded_chunked"
    )
    # prove the route: the config really is chunk-eligible
    from kubernetes_tpu.ops.assign import _chunkable

    assert _chunkable(arr, cfg)


@pytest.mark.parametrize("donate", [False, True])
@pytest.mark.parametrize("divisible", [True, False])
def test_sharded_rounds_parity(mesh8, donate, divisible, monkeypatch):
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap, bucket = _rounds_snap(divisible)
    _assert_parity(
        mesh8, snap, bucket, cfg=DEFAULT_SCORE_CONFIG, donate=donate,
        route="sharded_rounds",
    )


def test_sharded_ordinals_parity(mesh8):
    """The ordinal-reporting variant (the scheduler batch path's call) is
    sharded too: choices, per-pod commit ordinals and total sweeps all match
    the single-device kernel."""
    snap, bucket = _rounds_snap(True)
    arr, _ = encode_snapshot(snap, bucket=bucket)
    want_c, _, want_o, want_s = schedule_batch_ordinals_routed(
        arr, DEFAULT_SCORE_CONFIG, donate=False
    )
    got_c, _, got_o, got_s = schedule_batch_ordinals_routed(
        arr, DEFAULT_SCORE_CONFIG, donate=False, mesh=mesh8
    )
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
    np.testing.assert_array_equal(np.asarray(got_o), np.asarray(want_o))
    assert int(got_s) == int(want_s)


def test_pipelined_loop_with_mesh_matches_serial(mesh8):
    """The double-buffered loop against a SHARDED device step: verdicts
    bit-identical to the unsharded serial oracle, resident buffers placed
    with NamedSharding (no per-cycle re-transfer of unchanged fields)."""
    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop, run_serial
    from helpers import mk_node, mk_pod

    def wave(seed):
        rng = np.random.default_rng(seed)
        return Snapshot(
            nodes=[mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
                   for i in range(10)],
            pending_pods=[mk_pod(f"w{seed}-p{j}", cpu=int(rng.integers(100, 1500)))
                          for j in range(16)],
        )

    waves = [wave(s) for s in range(4)]
    oracle = list(run_serial(waves))  # single-device, serial
    loop = PipelinedBatchLoop(depth=1, mesh=mesh8)
    got = list(loop.run(waves))
    assert got == oracle
    assert loop.enc._dev, "resident device buffers should exist"
    from jax.sharding import NamedSharding

    shardings = {
        name: ent[1].sharding for name, ent in loop.enc._dev.items()
    }
    assert all(isinstance(s, NamedSharding) for s in shardings.values())
    # node-axis fields really live sharded (not fully replicated)
    assert not shardings["node_labels"].is_fully_replicated


def test_mesh_from_env_validates_and_clamps(monkeypatch):
    from kubernetes_tpu.parallel.mesh import mesh_from_env

    monkeypatch.delenv("KTPU_MESH", raising=False)
    assert mesh_from_env() is None
    monkeypatch.setenv("KTPU_MESH", "1")
    assert mesh_from_env() is None
    monkeypatch.setenv("KTPU_MESH", "banana")
    with pytest.raises(ValueError, match="KTPU_MESH"):
        mesh_from_env()
    monkeypatch.setenv("KTPU_MESH", "-3")
    with pytest.raises(ValueError, match="KTPU_MESH"):
        mesh_from_env()
    monkeypatch.setenv("KTPU_MESH", "4096")  # beyond available: clamps
    with pytest.warns(UserWarning, match="clamping"):
        mesh = mesh_from_env()
    assert mesh is not None and int(mesh.size) >= 8


def test_pad_nodes_semantics():
    """Padding adds permanently invalid nodes: valid False, zero capacity,
    sentinel domains — and is a no-op when already divisible."""
    from kubernetes_tpu.parallel.mesh import pad_nodes

    snap, _ = _rounds_snap(False)
    arr, _ = encode_snapshot(snap, bucket=False)
    assert arr.N == 27
    same, n0 = pad_nodes(arr, 1)
    assert same is arr and n0 == 27
    padded, n0 = pad_nodes(arr, 8)
    assert n0 == 27 and padded.N == 32
    assert not padded.node_valid[27:].any()
    assert not padded.node_alloc[27:].any()
    d_sentinel = arr.term_counts0.shape[1] - 1
    assert (padded.node_dom[:, 27:] == d_sentinel).all()
    np.testing.assert_array_equal(padded.node_labels[:27], arr.node_labels)


def test_chaos_storm_with_mesh(monkeypatch):
    """Seeded chaos storm through the Scheduler batch path with the mesh
    armed (KTPU_MESH=8): placements bit-identical to the fault-free,
    UNSHARDED serial oracle — the chaos parity invariant extended to the
    sharded production route."""
    from test_chaos import _churn_run
    from kubernetes_tpu import chaos

    monkeypatch.delenv("KTPU_MESH", raising=False)
    monkeypatch.delenv("KTPU_FORCE_CHUNKED", raising=False)
    oracle, _ = _churn_run(pipeline=False)
    monkeypatch.setenv("KTPU_MESH", "8")
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    import jax

    jax.clear_caches()  # strict route proof: the storm must RE-compile
    before = dict(TRACE_COUNTS)
    got, sched = _churn_run(
        pipeline=True,
        plan=chaos.FaultPlan.from_seed(
            0, sites=("scheduler.step", "host.stall"), n_faults=4
        ),
    )
    assert got == oracle
    assert sched.mesh is not None and int(sched.mesh.size) == 8
    # dense or incremental variant both prove the sharded production route
    # (the scheduler routes sharded_rounds_inc when the class cache applies)
    assert (
        TRACE_COUNTS["sharded_rounds"] > before["sharded_rounds"]
        or TRACE_COUNTS["sharded_rounds_inc"] > before["sharded_rounds_inc"]
    ), (before, TRACE_COUNTS)


@pytest.mark.slow
def test_sharded_chunked_full_scale_parity(mesh8, monkeypatch):
    """North-star-shaped (heterogeneous, chunk-routed) parity at a scale
    where multiple chunks and non-trivial shards are exercised."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    from kubernetes_tpu.bench.workloads import heterogeneous

    snap = heterogeneous(1000, 2560, seed=0)
    arr, _ = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want, want_used = schedule_batch_routed(arr, cfg, donate=False)
    got, got_used = schedule_batch_routed(arr, cfg, donate=False, mesh=mesh8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_used), np.asarray(want_used))
