"""Nominated-node reservation (reference: schedule_one.go —
RunFilterPluginsWithNominatedPods; scheduling_queue.go — nominator): after
preemption, the freed node is reserved against lower-priority competitors
while the preemptor waits out its backoff."""

import pytest

from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.queue import FakeClock

from helpers import mk_node, mk_pod


def _preempt_setup(mode):
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("only", cpu=1000))
    sched = Scheduler(store, SchedulerConfiguration(mode=mode), clock=clock)
    store.add_pod(mk_pod("victim", cpu=800, priority=0))
    sched.run_until_idle()
    store.add_pod(mk_pod("vip", cpu=800, priority=100))
    sched.run_until_idle()  # preempts victim; vip now in backoff, nominated
    assert "default/victim" not in store.pods
    assert sched.queue.nominated_pods_for_node("only")
    assert store.pods["default/vip"].nominated_node_name == "only"
    return clock, store, sched


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_lower_priority_pod_cannot_steal_nominated_capacity(mode):
    clock, store, sched = _preempt_setup(mode)
    # a lower-priority pod arrives while vip sits in backoff: the freed
    # capacity is reserved, so it must NOT bind
    store.add_pod(mk_pod("sneak", cpu=800, priority=0))
    sched.run_until_idle()
    assert store.pods["default/sneak"].node_name == ""
    # vip's backoff expires -> it takes the nominated node
    clock.step(2.0)
    sched.run_until_idle()
    assert store.pods["default/vip"].node_name == "only"
    assert store.pods["default/sneak"].node_name == ""
    assert not sched.queue.nominated_pods_for_node("only")  # cleared on bind


def test_higher_priority_pod_ignores_nomination_cpu():
    clock, store, sched = _preempt_setup("cpu")
    # an even-higher-priority pod may take the node despite the nomination
    # (the reservation only holds against priority <= the nominated pod's)
    store.add_pod(mk_pod("super", cpu=800, priority=200))
    sched.run_until_idle()
    assert store.pods["default/super"].node_name == "only"


def test_stale_nomination_cleared_on_failed_retry_cpu():
    clock, store, sched = _preempt_setup("cpu")
    # super steals the node before vip's backoff expires (priority 200 > 100
    # ignores the reservation); vip's retry then fails with no preemption
    # candidates -> its stale nomination must be cleared (clearNominatedNode)
    store.add_pod(mk_pod("super", cpu=800, priority=200))
    sched.run_until_idle()
    assert store.pods["default/super"].node_name == "only"
    clock.step(2.0)
    sched.run_until_idle()  # vip retries, cannot fit or preempt
    assert not sched.queue.nominated_pods_for_node("only")
    assert store.pods["default/vip"].nominated_node_name == ""
    # a small pod that fits beside super must not be blocked by a phantom
    # 800-cpu reservation
    store.add_pod(mk_pod("small", cpu=100, priority=0))
    sched.run_until_idle()
    assert store.pods["default/small"].node_name == "only"


def test_preemption_respects_other_pods_nomination_cpu():
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=1000))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"), clock=clock)
    store.add_pod(mk_pod("v1", cpu=200, priority=0))
    store.add_pod(mk_pod("filler", cpu=800, priority=0))
    sched.run_until_idle()
    # A (prio 100) preempts filler and is nominated to n0
    store.add_pod(mk_pod("A", cpu=800, priority=100))
    sched.run_until_idle()
    assert "default/filler" not in store.pods
    assert sched.queue.nominated_pods_for_node("n0")
    # B (prio 50, cpu 900) arrives: n0 is blocked by A's reservation, and
    # preemption's what-if must ALSO see the reservation -> evicting v1 would
    # be pointless, so v1 must survive and B gets no nomination
    store.add_pod(mk_pod("B", cpu=900, priority=50))
    sched.run_until_idle()
    assert "default/v1" in store.pods
    assert store.pods["default/B"].nominated_node_name == ""
    clock.step(2.0)
    sched.run_until_idle()
    assert store.pods["default/A"].node_name == "n0"


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_nomination_cleared_on_pod_delete(mode):
    clock, store, sched = _preempt_setup(mode)
    store.delete_pod("default/vip")
    assert not sched.queue.nominated_pods_for_node("only")
    # capacity is free again for anyone
    store.add_pod(mk_pod("sneak", cpu=800, priority=0))
    clock.step(2.0)
    sched.run_until_idle()
    assert store.pods["default/sneak"].node_name == "only"


def test_deleted_pod_does_not_resurrect_after_same_uid_readd():
    # delete-while-in-backoff then recreate with the same uid: the stale
    # backoff entry must drain silently, the fresh pod must survive
    from kubernetes_tpu.scheduler.queue import FakeClock, PriorityQueue

    clock = FakeClock()
    q = PriorityQueue(clock)
    old = mk_pod("p", cpu=100)
    q.add(old)
    assert q.pop() is old
    q.add_unschedulable(old, backoff=True)  # enters backoff
    q.delete(old.uid)  # deleted while in backoff
    new = mk_pod("p", cpu=200)  # recreated, same uid
    q.add(new)
    assert q.pop() is new
    clock.step(60.0)  # stale entry matures
    assert q.pop() is None  # the deleted pod must NOT come back
