"""L1 filter kernel tests — table-driven, mirroring the reference's plugin unit
tests (e.g. noderesources/fit_test.go, tainttoleration/taint_toleration_test.go)."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import filters
from helpers import mk_node, mk_pod


def feasible_matrix(snap):
    arr, meta = encode_snapshot(snap)
    sf = np.asarray(filters.static_feasible(arr))
    # fold in the capacity check at initial used for a full Filter answer
    fit = np.all(
        arr.node_used[None, :, :] + arr.pod_req[:, None, :] <= arr.node_alloc[None, :, :],
        axis=2,
    )
    return (sf & fit), meta


def test_fit_filters_oversized_pod():
    snap = Snapshot(
        nodes=[mk_node("small", cpu=1000), mk_node("big", cpu=8000)],
        pending_pods=[mk_pod("p", cpu=4000)],
    )
    f, _ = feasible_matrix(snap)
    assert not f[0, 0] and f[0, 1]


def test_taint_requires_toleration():
    taint = (t.Taint(key="dedicated", value="infra", effect=t.NO_SCHEDULE),)
    snap = Snapshot(
        nodes=[mk_node("tainted", taints=taint), mk_node("clean")],
        pending_pods=[
            mk_pod("no-tol"),
            mk_pod("tol", tolerations=(t.Toleration(key="dedicated", value="infra"),)),
            mk_pod("tol-exists", tolerations=(t.Toleration(key="dedicated", operator=t.OP_EXISTS),)),
        ],
    )
    f, meta = feasible_matrix(snap)
    by = {nm: i for i, nm in enumerate(meta.pod_names[:3])}
    assert not f[by["no-tol"], 0] and f[by["no-tol"], 1]
    assert f[by["tol"], 0] and f[by["tol-exists"], 0]


def test_prefer_no_schedule_does_not_filter():
    taint = (t.Taint(key="soft", effect=t.PREFER_NO_SCHEDULE),)
    snap = Snapshot(nodes=[mk_node("n", taints=taint)], pending_pods=[mk_pod("p")])
    f, _ = feasible_matrix(snap)
    assert f[0, 0]


def test_node_selector_equality():
    snap = Snapshot(
        nodes=[mk_node("ssd", labels={"disk": "ssd"}), mk_node("hdd", labels={"disk": "hdd"})],
        pending_pods=[mk_pod("p", node_selector={"disk": "ssd"})],
    )
    f, _ = feasible_matrix(snap)
    assert f[0, 0] and not f[0, 1]


def test_node_affinity_operators():
    nodes = [
        mk_node("a", labels={"tier": "gold", "gen": "7"}),
        mk_node("b", labels={"tier": "silver", "gen": "5"}),
        mk_node("c", labels={"gen": "9"}),
    ]

    def aff(op, key="tier", values=()):
        return t.Affinity(
            required_node_terms=(
                t.NodeSelectorTerm(
                    match_expressions=(
                        t.NodeSelectorRequirement(key=key, operator=op, values=values),
                    )
                ),
            )
        )

    snap = Snapshot(
        nodes=nodes,
        pending_pods=[
            mk_pod("in", affinity=aff(t.OP_IN, values=("gold",))),
            mk_pod("notin", affinity=aff(t.OP_NOT_IN, values=("gold",))),
            mk_pod("exists", affinity=aff(t.OP_EXISTS)),
            mk_pod("absent", affinity=aff(t.OP_DOES_NOT_EXIST)),
            mk_pod("gt", affinity=aff(t.OP_GT, key="gen", values=("6",))),
            mk_pod("lt", affinity=aff(t.OP_LT, key="gen", values=("6",))),
        ],
    )
    f, meta = feasible_matrix(snap)
    by = {nm: i for i, nm in enumerate(meta.pod_names[:6])}
    assert list(f[by["in"], :3]) == [True, False, False]
    assert list(f[by["notin"], :3]) == [False, True, True]  # absent key matches NotIn
    assert list(f[by["exists"], :3]) == [True, True, False]
    assert list(f[by["absent"], :3]) == [False, False, True]
    assert list(f[by["gt"], :3]) == [True, False, True]
    assert list(f[by["lt"], :3]) == [False, True, False]


def test_or_of_terms_and_nodeselector_conjunction():
    nodes = [
        mk_node("a", labels={"x": "1", "disk": "ssd"}),
        mk_node("b", labels={"y": "1", "disk": "ssd"}),
        mk_node("c", labels={"x": "1", "disk": "hdd"}),
    ]
    aff = t.Affinity(
        required_node_terms=(
            t.NodeSelectorTerm(
                match_expressions=(
                    t.NodeSelectorRequirement(key="x", operator=t.OP_IN, values=("1",)),
                )
            ),
            t.NodeSelectorTerm(
                match_expressions=(
                    t.NodeSelectorRequirement(key="y", operator=t.OP_IN, values=("1",)),
                )
            ),
        )
    )
    snap = Snapshot(
        nodes=nodes,
        pending_pods=[mk_pod("p", affinity=aff, node_selector={"disk": "ssd"})],
    )
    f, _ = feasible_matrix(snap)
    # (x=1 OR y=1) AND disk=ssd
    assert list(f[0, :3]) == [True, True, False]


def test_unknown_selector_value_unsatisfiable():
    snap = Snapshot(
        nodes=[mk_node("a", labels={"disk": "ssd"})],
        pending_pods=[mk_pod("p", node_selector={"disk": "nvme"})],
    )
    f, _ = feasible_matrix(snap)
    assert not f[0].any()


def test_unschedulable_node_filtered_unless_tolerated():
    snap = Snapshot(
        nodes=[mk_node("cordoned", unschedulable=True)],
        pending_pods=[
            mk_pod("p"),
            mk_pod(
                "tolerant",
                tolerations=(
                    t.Toleration(key="node.kubernetes.io/unschedulable", operator=t.OP_EXISTS),
                ),
            ),
        ],
    )
    f, meta = feasible_matrix(snap)
    by = {nm: i for i, nm in enumerate(meta.pod_names[:2])}
    assert not f[by["p"], 0]
    assert f[by["tolerant"], 0]
