"""APIServer handler chain: authn -> APF -> RBAC authz -> admission -> store;
CRD mechanism; generic GC over registered kinds.

Mirrors the reference's layering (apiserver/pkg/server/config.go —
DefaultBuildHandlerChain) and the admission/authz unit-test style."""

from dataclasses import dataclass

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler.admission import (
    AdmissionChain,
    AdmissionDenied,
    Attributes,
    PolicyPlugin,
    ValidatingPolicy,
)
from kubernetes_tpu.scheduler.apiserver import APIServer, Forbidden, Unauthenticated
from kubernetes_tpu.scheduler.auth import RBACAuthorizer, TokenAuthenticator, bind_cluster_role
from kubernetes_tpu.scheduler.controllers import ControllerManager
from kubernetes_tpu.scheduler.flowcontrol import (
    APFController,
    Request,
    RequestRejected,
)
from kubernetes_tpu.scheduler.store import ClusterStore


# ---------------------------------------------------------------- store / CRD


def test_register_kind_crd_roundtrip_and_watch():
    store = ClusterStore()
    events = []
    store.watch(events.append, replay=False)
    store.register_kind("PodGroupCRD")

    @dataclass
    class PodGroupObj:
        name: str
        namespace: str = "default"
        min_member: int = 2
        uid: str = "pg/1"

        @property
        def key(self):
            return f"{self.namespace}/{self.name}"

    store.add_object("PodGroupCRD", PodGroupObj("gang-a"))
    assert store.get_object("PodGroupCRD", "default/gang-a").min_member == 2
    assert [e.obj_type for e in events] == ["PodGroupCRD"]
    store.delete_object("PodGroupCRD", "default/gang-a")
    assert store.get_object("PodGroupCRD", "default/gang-a") is None
    assert events[-1].kind == "Deleted"


def test_unregistered_kind_rejected():
    store = ClusterStore()
    with pytest.raises(KeyError):
        store.add_object("NoSuchKind", object())


def test_gc_cascades_through_registered_kinds():
    """Deployment -> ReplicaSet -> Pod cascade still works through the generic
    tables; a CRD object with a vanished owner is collected too."""
    store = ClusterStore()
    cm = ControllerManager(store)
    d = t.Deployment(name="web", replicas=2,
                     template=t.Pod(name="w", labels={"app": "web"}),
                     selector=t.LabelSelector.of(app="web"))
    store.add_object("Deployment", d)
    cm.tick_until_quiescent()
    assert len(store.pods) == 2
    store.delete_object("Deployment", d.key)
    cm.tick_until_quiescent()
    assert len(store.pods) == 0 and not store.replicasets

    # CRD object owned by the deleted deployment
    store.register_kind("Widget")

    @dataclass
    class Widget:
        name: str
        owner_references: tuple = ()
        uid: str = "w/1"

        @property
        def key(self):
            return self.name

    store.add_object(
        "Widget",
        Widget("x", owner_references=(
            t.OwnerReference(kind="Deployment", name="web", uid=d.uid),)),
    )
    assert cm.gc.tick() == 1
    assert store.get_object("Widget", "x") is None


# -------------------------------------------------------------------- authn/z


def _mk_authz_store():
    store = ClusterStore()
    store.add_object("Role", c.Role(
        name="pod-reader", namespace="",
        rules=(c.PolicyRule(verbs=("get", "list"), resources=("pods",)),)))
    store.add_object("Role", c.Role(
        name="ns-admin", namespace="",
        rules=(c.PolicyRule(verbs=("*",), resources=("*",)),)))
    return store


def test_rbac_cluster_and_namespaced_bindings():
    store = _mk_authz_store()
    authz = RBACAuthorizer(store)
    alice = c.UserInfo("alice")
    bob = c.UserInfo("bob")
    root = c.UserInfo("root", groups=("system:masters",))

    bind_cluster_role(store, "read-all", "pod-reader", [("User", "alice")])
    # bob: admin only inside team-b (RoleBinding referencing a ClusterRole)
    store.add_object("RoleBinding", c.RoleBinding(
        name="bob-admin", namespace="team-b", role_name="ns-admin",
        subjects=(c.Subject("User", "bob"),)))

    assert authz.authorize(alice, "get", "pods", "any-ns")
    assert not authz.authorize(alice, "create", "pods", "any-ns")
    assert authz.authorize(bob, "create", "pods", "team-b")
    assert not authz.authorize(bob, "create", "pods", "team-a")
    assert authz.authorize(root, "delete", "nodes")  # system:masters bypass


def test_rbac_group_subject_and_resource_names():
    store = ClusterStore()
    store.add_object("Role", c.Role(
        name="cfg", namespace="",
        rules=(c.PolicyRule(verbs=("get",), resources=("services",),
                            resource_names=("frontend",)),)))
    bind_cluster_role(store, "b", "cfg", [("Group", "devs")])
    authz = RBACAuthorizer(store)
    dev = c.UserInfo("carol", groups=("devs",))
    assert authz.authorize(dev, "get", "services", "ns", "frontend")
    assert not authz.authorize(dev, "get", "services", "ns", "backend")


# ------------------------------------------------------------------ admission


def _pod(name="p", ns="default", **kw):
    return t.Pod(name=name, namespace=ns, **kw)


def test_admission_priority_class_resolution():
    store = ClusterStore()
    store.add_object("PriorityClass", c.PriorityClass(name="high", value=1000))
    store.add_object("PriorityClass",
                     c.PriorityClass(name="base", value=5, global_default=True))
    chain = AdmissionChain.default(store)

    out = chain.run(Attributes("create", "Pod", "default",
                               _pod(priority_class_name="high")))
    assert out.priority == 1000
    out = chain.run(Attributes("create", "Pod", "default", _pod()))
    assert out.priority == 5  # global default applied
    with pytest.raises(AdmissionDenied):
        chain.run(Attributes("create", "Pod", "default",
                             _pod(priority_class_name="nope")))


def test_admission_limitranger_defaults_and_max():
    store = ClusterStore()
    store.add_object("LimitRange", c.LimitRange(
        name="lr", namespace="default",
        default_request={t.CPU: 100, t.MEMORY: 1 << 20},
        max_per_pod={t.CPU: 4000}))
    chain = AdmissionChain.default(store)
    out = chain.run(Attributes("create", "Pod", "default", _pod()))
    assert out.requests == {t.CPU: 100, t.MEMORY: 1 << 20}
    # explicit request survives defaulting
    out = chain.run(Attributes("create", "Pod", "default",
                               _pod(requests={t.CPU: 200})))
    assert out.requests[t.CPU] == 200
    with pytest.raises(AdmissionDenied):
        chain.run(Attributes("create", "Pod", "default",
                             _pod(requests={t.CPU: 5000})))


def test_admission_resource_quota():
    store = ClusterStore()
    store.add_object("ResourceQuota", c.ResourceQuota(
        name="q", namespace="default", hard={"pods": 2, t.CPU: 1000}))
    chain = AdmissionChain.default(store)
    store.add_pod(_pod("a", requests={t.CPU: 600}))
    # cpu would exceed
    with pytest.raises(AdmissionDenied):
        chain.run(Attributes("create", "Pod", "default",
                             _pod("b", requests={t.CPU: 600})))
    chain.run(Attributes("create", "Pod", "default",
                         _pod("b", requests={t.CPU: 300})))
    store.add_pod(_pod("b", requests={t.CPU: 300}))
    # pod count would exceed
    with pytest.raises(AdmissionDenied):
        chain.run(Attributes("create", "Pod", "default", _pod("c")))


def test_admission_namespace_lifecycle():
    store = ClusterStore()
    store.add_object("Namespace", c.Namespace(name="live"))
    store.add_object("Namespace", c.Namespace(name="dying", phase="Terminating"))
    chain = AdmissionChain.default(store)
    chain.run(Attributes("create", "Pod", "live", _pod(ns="live")))
    chain.run(Attributes("create", "Pod", "default", _pod()))  # exempt implicit
    with pytest.raises(AdmissionDenied):
        chain.run(Attributes("create", "Pod", "dying", _pod(ns="dying")))
    with pytest.raises(AdmissionDenied):
        chain.run(Attributes("create", "Pod", "ghost", _pod(ns="ghost")))


def test_validating_policy_plugin():
    store = ClusterStore()
    pol = PolicyPlugin()
    pol.add(ValidatingPolicy(
        name="require-app-label",
        kinds=("Pod",),
        check=lambda a: "app" in a.obj.labels,
        message="pods must carry an app label"))
    chain = AdmissionChain.default(store, pol)
    chain.run(Attributes("create", "Pod", "default", _pod(labels={"app": "x"})))
    with pytest.raises(AdmissionDenied, match="app label"):
        chain.run(Attributes("create", "Pod", "default", _pod()))


# ------------------------------------------------------------------------ APF


def test_apf_fairness_elephant_vs_mouse():
    """An elephant flow with 20 queued requests and a mouse with 2 share a
    level: fair queuing must interleave, not FIFO-starve the mouse."""
    store = ClusterStore()
    # hand_size=1: every flow hashes to exactly one queue, making the fair
    # round-robin exact (larger hands trade this for hot-queue avoidance)
    store.add_object("PriorityLevelConfiguration", c.PriorityLevelConfiguration(
        name="fair", queues=32, hand_size=1, concurrency_shares=1000,
        queue_length_limit=200))
    store.add_object("FlowSchema", c.FlowSchema(
        name="fair-all", priority_level="fair", matching_precedence=1))
    apf = APFController(store, total_concurrency=64)
    apf.resync()
    reqs = [Request(user="elephant") for _ in range(20)]
    mouse = [Request(user="mouse"), Request(user="mouse")]
    # exhaust the level's seats first so everything queues
    seats = apf.queue_sets["fair"].concurrency
    blockers = [Request(user="blocker") for _ in range(seats)]
    for r in blockers:
        apf.admit(r)
    assert len(apf.dispatch()) == seats
    for r in reqs:
        apf.admit(r)
    for r in mouse:
        apf.admit(r)
    # distinct queues (otherwise the test can't distinguish fair queuing)
    assert reqs[0]._queue is not mouse[0]._queue
    # release one seat at a time; both mouse requests must be served within
    # the first 4 dispatches despite 20 queued elephant requests
    order = []
    for _ in range(6):
        apf.finish(blockers.pop())
        out = apf.dispatch()
        order.extend(r.user for r in out)
    assert order.count("mouse") == 2
    assert "mouse" in order[:4]


def test_apf_queue_length_limit_rejects():
    store = ClusterStore()
    store.add_object("PriorityLevelConfiguration", c.PriorityLevelConfiguration(
        name="tiny", queues=1, hand_size=1, queue_length_limit=2))
    store.add_object("FlowSchema", c.FlowSchema(
        name="tiny-all", priority_level="tiny", matching_precedence=1))
    apf = APFController(store, total_concurrency=1)
    apf.resync()
    first = Request(user="u")
    apf.admit(first)
    assert apf.dispatch() == [first]  # occupies the only seat
    for _ in range(2):
        apf.admit(Request(user="u"))
    with pytest.raises(RequestRejected):
        apf.admit(Request(user="u"))


def test_apf_shuffle_shard_spreads_flows():
    store = ClusterStore()
    apf = APFController(store)
    qs = apf.queue_sets["workload-low"]
    for i in range(200):
        apf.admit(Request(user=f"user-{i}"))
    occupied = sum(1 for q in qs.queues if q.requests)
    assert occupied > 10  # flows spread over many queues, not one


# ------------------------------------------------------------- the full chain


def test_apiserver_end_to_end_chain():
    store = ClusterStore()
    srv = APIServer(store)
    srv.authn.add_token("admin-tok", "admin", groups=("system:masters",))
    srv.authn.add_token("alice-tok", "alice")

    with pytest.raises(Unauthenticated):
        srv.handle(None, "list", "Pod")
    with pytest.raises(Unauthenticated):
        srv.handle("bogus", "list", "Pod")
    # alice has no bindings
    with pytest.raises(Forbidden):
        srv.handle("alice-tok", "list", "Pod", namespace="default")

    store.add_object("Role", c.Role(
        name="editor", namespace="",
        rules=(c.PolicyRule(verbs=("*",), resources=("pods", "services")),)))
    bind_cluster_role(store, "alice-edit", "editor", [("User", "alice")])

    srv.handle("alice-tok", "create", "Pod", obj=_pod("web-1"))
    assert "default/web-1" in store.pods
    pods = srv.handle("alice-tok", "list", "Pod", namespace="default")
    assert [p.name for p in pods] == ["web-1"]
    # admission still runs behind authz: quota denial surfaces
    store.add_object("ResourceQuota", c.ResourceQuota(
        name="q", namespace="default", hard={"pods": 1}))
    with pytest.raises(AdmissionDenied):
        srv.handle("alice-tok", "create", "Pod", obj=_pod("web-2"))
    # audit trail captured both outcomes
    assert any(e.allowed for e in srv.audit_log)
    assert any(not e.allowed and e.reason == "forbidden" for e in srv.audit_log)


def test_apiserver_service_ip_allocation():
    store = ClusterStore()
    srv = APIServer(store)
    srv.authn.add_token("tok", "admin", groups=("system:masters",))
    s1 = srv.handle("tok", "create", "Service",
                    obj=c.Service(name="a", ports=(c.ServicePort(80),)))
    s2 = srv.handle("tok", "create", "Service",
                    obj=c.Service(name="b", ports=(c.ServicePort(80),)))
    assert s1.cluster_ip != s2.cluster_ip
    assert s1.cluster_ip.startswith("10.96.")
    srv.handle("tok", "delete", "Service", namespace="default", name="a")
    s3 = srv.handle("tok", "create", "Service",
                    obj=c.Service(name="c", ports=(c.ServicePort(80),)))
    assert s3.cluster_ip == s1.cluster_ip  # freed IP reused


# ------------------------------------------------- review-fix regressions


def test_apiserver_exempt_level_and_explicit_uid_pod():
    store = ClusterStore()
    srv = APIServer(store)
    srv.authn.add_token("sched-tok", "system:kube-scheduler",
                        groups=("system:masters",))
    # exempt APF level must release immediately (no queueing) — was a crash
    srv.handle("sched-tok", "list", "Pod")
    # pod with an explicit (non-defaulted) uid is still addressable by name
    srv.handle("sched-tok", "create", "Pod",
               obj=t.Pod(name="p", uid="abc-123"))
    assert srv.handle("sched-tok", "get", "Pod",
                      namespace="default", name="p").uid == "abc-123"
    srv.handle("sched-tok", "delete", "Pod", namespace="default", name="p")
    assert not store.pods


def test_cluster_scoped_delete_via_api():
    """ClusterRole/ClusterRoleBinding (namespace='') round-trip through the
    API under their bare name — deleting a binding actually revokes it."""
    store = ClusterStore()
    srv = APIServer(store)
    srv.authn.add_token("root", "root", groups=("system:masters",))
    srv.authn.add_token("alice-tok", "alice")
    store.add_object("Role", c.Role(
        name="viewer", namespace="",
        rules=(c.PolicyRule(verbs=("list",), resources=("pods",)),)))
    bind_cluster_role(store, "alice-view", "viewer", [("User", "alice")])
    srv.handle("alice-tok", "list", "Pod", namespace="default")
    assert srv.handle("root", "get", "RoleBinding", name="alice-view") is not None
    srv.handle("root", "delete", "RoleBinding", name="alice-view")
    with pytest.raises(Forbidden):
        srv.handle("alice-tok", "list", "Pod", namespace="default")


def test_priority_admission_rejects_user_supplied_priority():
    store = ClusterStore()
    chain = AdmissionChain.default(store)
    with pytest.raises(AdmissionDenied, match="priority"):
        chain.run(Attributes("create", "Pod", "default", _pod(priority=1000)))


def test_gc_keeps_pod_owned_objects():
    store = ClusterStore()
    cm = ControllerManager(store)
    store.add_pod(_pod("web-0"))
    store.add_object("EndpointSlice", c.EndpointSlice(
        name="s1", owner_references=(
            t.OwnerReference(kind="Pod", name="web-0", uid="default/web-0"),)))
    assert cm.gc.tick() == 0
    store.delete_pod("default/web-0")
    assert cm.gc.tick() == 1


def test_hollow_kubelet_assigns_pod_ip_and_prunes_state():
    from kubernetes_tpu.scheduler.kubelet import HollowKubelet
    from kubernetes_tpu.scheduler.leases import LeaseStore
    from kubernetes_tpu.scheduler.queue import FakeClock

    store = ClusterStore()
    clock = FakeClock()
    leases = LeaseStore(clock=clock)
    store.add_node(t.Node(name="n0", allocatable={}))
    kubelet = HollowKubelet(store, leases, "n0", clock=clock)
    store.add_pod(_pod("p", node_name="n0", phase=t.PHASE_PENDING))
    kubelet.tick()
    pod = store.pods["default/p"]
    assert pod.phase == t.PHASE_RUNNING and pod.pod_ip.startswith("10.1")
    store.delete_pod("default/p")
    kubelet.tick()
    # no leak after deletion while Running: worker + runtime state pruned
    assert not kubelet.workers and not kubelet.runtime.containers


def test_impersonation_requires_rbac_and_swaps_identity():
    """DefaultBuildHandlerChain's impersonation filter: the authenticated
    user needs `impersonate` on `users`; the request then runs (and audits)
    as the impersonated identity."""
    store = ClusterStore()
    srv = APIServer(store)
    srv.authn.add_token("admin", "admin", groups=("system:masters",))
    srv.authn.add_token("eve", "eve")
    # eve may NOT impersonate
    with pytest.raises(Forbidden, match="impersonate"):
        srv.handle("eve", "list", "Pod", namespace="default",
                   impersonate_user="alice")
    # grant alice pod access; admin impersonates alice (masters may do anything)
    store.add_object("Role", c.Role(
        name="reader", namespace="",
        rules=(c.PolicyRule(verbs=("list",), resources=("pods",)),)))
    bind_cluster_role(store, "alice-read", "reader", [("User", "alice")])
    out = srv.handle("admin", "list", "Pod", namespace="default",
                     impersonate_user="alice")
    assert out == []
    # the audit row carries the impersonated identity
    assert srv.audit_log[-1].user == "alice"
    # impersonated identity is NOT a master: unauthorized resources refused
    with pytest.raises(Forbidden):
        srv.handle("admin", "list", "Node", impersonate_user="alice")
