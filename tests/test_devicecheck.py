"""ktpu-verify device pass (ISSUE 10): KTPU007..KTPU012 trace the compiled
placement kernels and gate their invariants — dtype flow, donation
aliasing, collective order, cache-key stability, transfer cleanliness, the
HBM budget — plus the KTPU013 knob-drift lint.

Ordering note: the parity test runs FIRST (tier-1 runs -p no:randomly, so
file order holds): it measures kernel decisions, triggers the one full
device pass this module pays for, and measures again — analyzed vs
unanalyzed runs must be bit-identical and the pass must restore env +
TRACE_COUNTS.  Every later test reuses the cached pass report."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.analysis import jaxrules
from kubernetes_tpu.analysis.devicecheck import (
    ROUTE_FILE,
    RouteTrace,
    enumerate_routes,
    run_device_pass,
)
from kubernetes_tpu.analysis.engine import Baseline, Report, analyze_source
from kubernetes_tpu.analysis.rules import KnobDriftRule
from kubernetes_tpu.bench import workloads
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops.assign import TRACE_COUNTS, schedule_batch_routed
from kubernetes_tpu.parallel.mesh import NODE_AXIS, make_mesh, shard_map

_PASS_CACHE = {}


def _full_pass() -> Report:
    """The one full device pass this module pays for, over the 18-route
    trace shared with the shard/mem modules (helpers.shared_route_traces)."""
    if "rep" not in _PASS_CACHE:
        from helpers import shared_route_traces

        _PASS_CACHE["rep"] = run_device_pass(
            baseline=Baseline([]), pretraced=shared_route_traces())
    return _PASS_CACHE["rep"]


def _decisions():
    """Chunked-route decisions on a fixed workload — the parity probe."""
    from kubernetes_tpu.api.delta import DeltaEncoder

    snap = workloads.heterogeneous(16, 120, seed=11)
    arr, meta = DeltaEncoder().encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    c, u = schedule_batch_routed(arr, cfg, donate=False)
    return np.asarray(c).copy(), np.asarray(u).copy()


# ---- tentpole acceptance: no-mutation parity + the tier-1 clean gate ----

def test_device_pass_never_mutates_kernel_behavior(monkeypatch):
    """Analyzed vs unanalyzed runs bit-identical, env + TRACE_COUNTS
    restored — the pass is a pure observer of the kernels."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    before_c, before_u = _decisions()
    env_before = {k: os.environ.get(k)
                  for k in ("KTPU_FORCE_CHUNKED", "KTPU_INCREMENTAL",
                            "KTPU_DONATE")}
    counts_before = dict(TRACE_COUNTS)
    rep = _full_pass()
    assert rep is not None
    assert {k: os.environ.get(k) for k in env_before} == env_before
    assert dict(TRACE_COUNTS) == counts_before
    after_c, after_u = _decisions()
    np.testing.assert_array_equal(after_c, before_c)
    np.testing.assert_array_equal(after_u, before_u)


def test_committed_package_is_device_pass_clean():
    """The tier-1 gate: every production route traces, the committed
    package is clean under the committed (empty) baseline — the acceptance
    criterion `--rules KTPU007,...,KTPU012` exits 0."""
    rep = _full_pass()
    assert rep.errors == []
    assert rep.unbaselined == [], "\n".join(
        f.render() for f in rep.unbaselined)
    assert rep.exit_code == 0


def test_every_route_listed_no_silent_skips():
    """The report lists EVERY enumerated route; on the tier-1 8-device CPU
    platform all 18 trace (a skip anywhere must carry a reason)."""
    rep = _full_pass()
    routes = {r["name"]: r for r in rep.device["routes"]}
    assert set(routes) == {s.name for s in enumerate_routes(8)}
    assert len(routes) == 18
    assert rep.device["n_traced"] == 18 and rep.device["n_skipped"] == 0
    for r in routes.values():
        assert r["status"] == "traced"
        assert r["warm"].get("cycles") == 3
    # donation marks on every donated route; collectives on every mesh route
    for r in routes.values():
        if r["donate"]:
            assert r["n_aliased"] or r["donor_args"], r["name"]
        if r["n_shards"] > 1:
            assert r["collectives"], r["name"]
        if not r["donate"]:
            assert r["memory"] is not None, r["name"]  # CPU exposes it


# ---- KTPU007 dtype-flow fixtures ----

def test_ktpu007_f64_promoting_kernel_detected():
    with jax.experimental.enable_x64():
        t = RouteTrace.from_callable(
            "fx/f64", lambda a: a * 2.0, np.ones(4, np.float64))
    fs = jaxrules.DtypeFlowRule().check([t])
    assert fs and "float64" in fs[0].message
    rep = Report(findings=fs)
    assert rep.exit_code == 1


def test_ktpu007_integer_lattice_bf16_narrowing_detected():
    t = RouteTrace.from_callable(
        "fx/bf16", lambda a: jnp.argmax(a.astype(jnp.bfloat16)),
        jnp.arange(8, dtype=jnp.int32))
    fs = jaxrules.DtypeFlowRule().check([t])
    assert fs and "bfloat16" in fs[0].message


def test_ktpu007_integer_output_demotion_detected():
    t = RouteTrace.from_callable(
        "fx/outf", lambda a: a.astype(jnp.float32),
        jnp.arange(4, dtype=jnp.int32), integer_out_indices=(0,))
    fs = jaxrules.DtypeFlowRule().check([t])
    assert fs and "declared integer-exact" in fs[0].message


def test_ktpu007_clean_fixture_passes():
    t = RouteTrace.from_callable(
        "fx/ok", lambda a: (jnp.argmax(a.astype(jnp.float32)), a + 1),
        jnp.arange(8, dtype=jnp.int32), integer_out_indices=(0, 1))
    assert jaxrules.DtypeFlowRule().check([t]) == []


def test_ktpu007_bf16_accumulation_detected():
    """An additive reduction whose accumulator is bf16 is a finding —
    bf16 is a STORAGE dtype; matmuls/sums must accumulate in f32.  (A
    bf16 matmul is the real-world shape: dot_general with
    preferred_element_type=bfloat16.  jnp.sum of bf16 auto-upcasts its
    accumulator at the jaxpr level, so matmul is the one that bites.)"""
    t = RouteTrace.from_callable(
        "fx/bf16acc",
        lambda a, b: a.astype(jnp.bfloat16) @ b.astype(jnp.bfloat16),
        jnp.ones((4, 4), dtype=jnp.float32),
        jnp.ones((4, 4), dtype=jnp.float32))
    fs = jaxrules.DtypeFlowRule().check([t])
    assert fs and "accumulates in bfloat16" in fs[0].message


def test_ktpu007_bf16_storage_f32_accumulate_passes():
    """The legal bf16 score path: compute in f32, quantize to bf16 for
    storage, upcast to f32 before every reduction — elementwise bf16 and
    bf16 max reductions draw no finding."""
    def fn(a):
        stored = (a * 2.0).astype(jnp.bfloat16)       # bf16 storage
        hi = jnp.max(stored)                           # exact in any width
        total = jnp.sum(stored.astype(jnp.float32))    # f32 accumulation
        return hi, total

    t = RouteTrace.from_callable(
        "fx/bf16ok", fn, jnp.ones(16, dtype=jnp.float32))
    assert jaxrules.DtypeFlowRule().check([t]) == []


# ---- KTPU008 donation fixtures ----

def test_ktpu008_dropped_donation_detected():
    """A donated input the compiler cannot alias to the declared output:
    the rule flags the silently-dropped donation (exit 1)."""
    t = RouteTrace.from_callable(
        "fx/drop", lambda a, b: b + 1.0, jnp.zeros(3), jnp.zeros(4),
        donate_argnums=(0,), alias_required_out=0)
    fs = jaxrules.DonationHonoredRule().check([t])
    assert fs and "dropped" in fs[0].message


def test_ktpu008_honored_donation_passes():
    t = RouteTrace.from_callable(
        "fx/ok", lambda a: a + 1.0, jnp.zeros((4, 4)),
        donate_argnums=(0,), alias_required_out=0)
    assert t.aliased == [(0, 0)]
    assert jaxrules.DonationHonoredRule().check([t]) == []


def test_ktpu008_nondonating_route_not_checked():
    t = RouteTrace.from_callable(
        "fx/nd", lambda a, b: b + 1.0, jnp.zeros(3), jnp.zeros(4),
        alias_required_out=0)
    assert jaxrules.DonationHonoredRule().check([t]) == []


# ---- KTPU009 collective-sequence fixtures ----

def test_ktpu009_shard_divergent_collective_detected(mesh8):
    from jax.sharding import PartitionSpec as P

    def divergent(x):
        i = jax.lax.axis_index(NODE_AXIS)
        return jax.lax.cond(
            i == 0,
            lambda v: jax.lax.psum(v, NODE_AXIS),
            lambda v: v * 2.0,
            x,
        )

    fn = shard_map(divergent, mesh=mesh8, in_specs=(P(NODE_AXIS),),
                   out_specs=P(NODE_AXIS), check_rep=False)
    t = RouteTrace.from_callable("fx/div", fn, jnp.ones(8), n_shards=8)
    assert t.cond_divergences
    fs = jaxrules.CollectiveSequenceRule().check([t])
    assert any("cond branches" in f.message for f in fs)


def test_ktpu009_uniform_collective_passes(mesh8):
    from jax.sharding import PartitionSpec as P

    fn = shard_map(lambda x: jax.lax.psum(x, NODE_AXIS), mesh=mesh8,
                   in_specs=(P(NODE_AXIS),), out_specs=P(),
                   check_rep=False)
    t = RouteTrace.from_callable("fx/ok", fn, jnp.ones(8), n_shards=8)
    assert t.collectives == ["psum"]
    assert jaxrules.CollectiveSequenceRule().check([t]) == []


def test_ktpu009_group_divergence_across_variants_detected(mesh8):
    """Two traces of one (kind, mesh) group with different collective
    sequences — trace-order nondeterminism the group check catches."""
    from jax.sharding import PartitionSpec as P

    def mk(seq_fn, name):
        fn = shard_map(seq_fn, mesh=mesh8, in_specs=(P(NODE_AXIS),),
                       out_specs=P(), check_rep=False)
        return RouteTrace.from_callable(name, fn, jnp.ones(8), n_shards=8,
                                        kind="grp")

    t1 = mk(lambda x: jax.lax.psum(x, NODE_AXIS), "grp/a")
    t2 = mk(lambda x: jax.lax.pmax(jax.lax.psum(x, NODE_AXIS), NODE_AXIS),
            "grp/b")
    fs = jaxrules.CollectiveSequenceRule().check([t1, t2])
    assert any("distinct collective sequences" in f.message for f in fs)


# ---- KTPU010 recompile-guard fixtures ----

def test_ktpu010_cache_key_churning_static_arg_detected():
    """A static arg whose value varies per warm cycle re-traces every
    call — measured off the real jit cache, fed to the rule."""
    f = jax.jit(lambda x, k: x + k, static_argnums=1)
    f(jnp.zeros(4), 1)
    s0 = f._cache_size()
    f(jnp.zeros(4), 2)  # churned static -> new cache entry
    s1 = f._cache_size()
    assert s1 > s0
    t = RouteTrace("fx/churn", kind="fixture", donate=False, n_shards=1)
    t.warm = {"cycles": 3, "retraces": 0, "cache_growth": s1 - s0,
              "lowered_stable": True}
    fs = jaxrules.RecompileGuardRule().check([t])
    assert fs and "recompile" in fs[0].message


def test_ktpu010_unstable_lowering_detected_and_clean_passes():
    t = RouteTrace("fx/unstable", kind="fixture", donate=False, n_shards=1)
    t.warm = {"cycles": 3, "retraces": 0, "cache_growth": 0,
              "lowered_stable": False}
    assert jaxrules.RecompileGuardRule().check([t])
    t2 = RouteTrace("fx/ok", kind="fixture", donate=False, n_shards=1)
    t2.warm = {"cycles": 3, "retraces": 0, "cache_growth": 0,
               "lowered_stable": True}
    assert jaxrules.RecompileGuardRule().check([t2]) == []


# ---- KTPU011 transfer-guard fixtures ----

def test_ktpu011_implicit_transfer_detected():
    violation = None
    try:
        with jax.transfer_guard_host_to_device("disallow"):
            _ = (jnp.asarray(np.ones(4)) + 1).block_until_ready()
    except Exception as e:  # noqa: BLE001
        violation = str(e)
    assert violation and "disallow" in violation.lower()
    t = RouteTrace("fx/transfer", kind="fixture", donate=False, n_shards=1)
    t.transfer_violation = violation
    fs = jaxrules.TransferGuardRule().check([t])
    assert fs and "implicit host<->device transfer" in fs[0].message
    t2 = RouteTrace("fx/ok", kind="fixture", donate=False, n_shards=1)
    assert jaxrules.TransferGuardRule().check([t2]) == []


# ---- KTPU012 hbm-estimate fixtures ----

def test_ktpu012_budget_overrun_detected_and_tolerance_passes():
    t = RouteTrace("fx/hbm", kind="fixture", donate=False, n_shards=1)
    t.est = {"total": 1000}
    t.memory = {"argument_bytes": 0, "output_bytes": 0,
                "temp_bytes": int(1000 * jaxrules.HBM_TOLERANCE * 2),
                "alias_bytes": 0}
    fs = jaxrules.HbmEstimateRule().check([t])
    assert fs and "exceeds" in fs[0].message
    t.memory["temp_bytes"] = int(1000 * jaxrules.HBM_TOLERANCE) - 1
    assert jaxrules.HbmEstimateRule().check([t]) == []
    t.memory = None  # backend without memory analysis: recorded, no guess
    assert jaxrules.HbmEstimateRule().check([t]) == []


# ---- KTPU013 knob-drift fixtures ----

def _knob_findings(source, known):
    return analyze_source(source, "kubernetes_tpu/scheduler/fx.py",
                          [KnobDriftRule(known_knobs=known)])


def test_ktpu013_undocumented_knob_read_detected():
    src = 'import os\nV = os.environ.get("KTPU_SECRET_KNOB", "1")\n'
    fs = _knob_findings(src, {"KTPU_DOCUMENTED"})
    assert fs and "KTPU_SECRET_KNOB" in fs[0].message
    # all three read forms flag
    for form in ('os.getenv("KTPU_SECRET_KNOB")',
                 'os.environ["KTPU_SECRET_KNOB"]'):
        fs = _knob_findings(f"import os\nV = {form}\n", set())
        assert fs, form


def test_ktpu013_documented_and_non_reads_pass():
    src = (
        "import os\n"
        'A = os.environ.get("KTPU_DOCUMENTED")\n'          # documented
        'os.environ["KTPU_SECRET_KNOB"] = "1"\n'           # write
        'os.environ.pop("KTPU_SECRET_KNOB", None)\n'       # pop
        "for var in KNOBS:\n    os.environ.get(var)\n"     # non-literal
    )
    assert _knob_findings(src, {"KTPU_DOCUMENTED"}) == []


def test_ktpu013_package_has_no_knob_drift():
    """Every KTPU_* env read in the committed package has a README row."""
    from kubernetes_tpu.analysis.__main__ import default_root, resolve_root
    from kubernetes_tpu.analysis.engine import analyze_package

    rep = analyze_package(resolve_root(default_root()),
                          rules=[KnobDriftRule()], lockorder=False)
    assert rep.errors == []
    assert rep.findings == [], "\n".join(f.render() for f in rep.findings)


# ---- CLI + harness wiring ----

def _canned_report():
    rep = Report(rules=list(jaxrules.DEVICE_RULE_IDS))
    rep.device = {"routes": [], "n_traced": 0, "n_skipped": 0}
    return rep


def test_cli_device_rule_subset_routes_to_device_pass(monkeypatch, capsys,
                                                      tmp_path):
    """--rules KTPU007 skips the AST walk and runs ONLY the device pass
    (canned here — the real pass is paid once above)."""
    from kubernetes_tpu.analysis import __main__ as cli
    from kubernetes_tpu.analysis import devicecheck

    calls = {}

    def fake_pass(rule_ids=None, baseline=None, mesh_size=8):
        calls["rule_ids"] = list(rule_ids or [])
        return _canned_report()

    monkeypatch.setattr(devicecheck, "run_device_pass", fake_pass)
    out = tmp_path / "rep.json"
    rc = cli.main(["--rules", "KTPU007,KTPU011", "--format", "json",
                   "--output", str(out)])
    assert rc == 0
    assert calls["rule_ids"] == ["KTPU007", "KTPU011"]
    import json

    doc = json.loads(out.read_text())
    assert "device" in doc and doc["exit_code"] == 0
    # the AST rules did NOT run on a pure device subset
    assert "KTPU001" not in doc["rules"]


def test_cli_device_flag_unions_with_ast_rules_subset(monkeypatch, capsys):
    """--device combined with an AST-only --rules subset must still run
    the device pass (all six device rules), not silently drop it."""
    from kubernetes_tpu.analysis import __main__ as cli
    from kubernetes_tpu.analysis import devicecheck

    calls = {}

    def fake_pass(rule_ids=None, baseline=None, mesh_size=8):
        calls["rule_ids"] = list(rule_ids or [])
        return _canned_report()

    monkeypatch.setattr(devicecheck, "run_device_pass", fake_pass)
    rc = cli.main(["--rules", "KTPU013", "--device", "--format", "json"])
    capsys.readouterr()
    assert rc == 0
    assert calls["rule_ids"] == list(jaxrules.DEVICE_RULE_IDS)


def test_ktpu013_missing_readme_section_fails_closed(monkeypatch):
    """A renamed/missing "Configuration knobs" heading must flag every
    read (empty documented set), never degrade to a whole-README scan
    where any prose mention passes."""
    rule = KnobDriftRule()
    monkeypatch.setattr(type(rule), "SECTION", "## No Such Heading XYZ")
    src = 'import os\nV = os.environ.get("KTPU_MESH")\n'  # prose-documented
    fs = analyze_source(src, "kubernetes_tpu/scheduler/fx.py", [rule])
    assert fs and "KTPU_MESH" in fs[0].message


def test_cli_unknown_device_rule_id_refused():
    from kubernetes_tpu.analysis import __main__ as cli

    with pytest.raises(SystemExit) as ei:
        cli.main(["--rules", "KTPU099"])
    assert ei.value.code == 2


def test_harness_verify_device_embeds_report(monkeypatch, tmp_path):
    """--verify-device implies --verify and ships the device block in the
    artifact's verify report (canned pass — wiring only)."""
    from kubernetes_tpu.analysis import __main__ as cli
    from kubernetes_tpu.bench import harness

    seen = {}

    def fake_verify(root=None, baseline_path=None, device=False,
                    shard=False, mem=False):
        seen["device"] = device
        seen["shard"] = shard
        seen["mem"] = mem
        return _canned_report()

    monkeypatch.setattr(cli, "run_verify", fake_verify)
    yaml = tmp_path / "tiny.yaml"
    yaml.write_text(
        "name: Tiny\nops:\n"
        "  - {op: createCluster, generator: basic, nodes: 8, pods: 16}\n"
        "  - {op: measure}\n"
    )
    out = tmp_path / "out.json"
    harness.main(["--config", str(yaml), "--out", str(out),
                  "--verify-device"])
    assert seen["device"] is True
    import json

    doc = json.loads(out.read_text())

    def find_verify(d):
        if isinstance(d, dict):
            if "verify" in d:
                return d["verify"]
            for v in d.values():
                r = find_verify(v)
                if r is not None:
                    return r
        if isinstance(d, list):
            for v in d:
                r = find_verify(v)
                if r is not None:
                    return r
        return None

    v = find_verify(doc)
    assert v is not None and "device" in v


# ---- finding identity ----

def test_device_finding_fingerprints_are_route_stable():
    """Two findings for the same (rule, route, detail) share a
    fingerprint regardless of construction order — baselines key on the
    violated property, not a source line."""
    from kubernetes_tpu.analysis.jaxrules import _finding

    t = RouteTrace("chunked/donate/single", kind="chunked", donate=True,
                   n_shards=1)
    a = _finding(t, "KTPU008", "msg one", "missing-alias-out1")
    b = _finding(t, "KTPU008", "msg two (reworded)", "missing-alias-out1")
    assert a.fingerprint == b.fingerprint
    assert a.file == ROUTE_FILE
