"""Incremental warm-cycle kernels (ISSUE 5): equivalence-class deduped
hoists + dirty-node rescoring (ops/incremental.py) must be BIT-IDENTICAL to
the dense kernels and the serial oracle across {chunked, rounds} x
{donate on/off} x {mesh8, single-device}, survive a seeded chaos storm with
the cache armed, fall back to the dense route on the degenerate
all-pods-unique wave (U == P — dedup is a provable no-op), and actually
patch O(changes) columns on warm cycles (the tier-1 trace-span regression
guarding against a silent full re-hoist)."""

import copy
import dataclasses
import os
import random

import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api.delta import DeltaEncoder
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops.assign import (
    TRACE_COUNTS,
    schedule_batch_ordinals_routed,
    schedule_batch_routed,
)
from kubernetes_tpu.ops.incremental import HoistCache, incremental_enabled
from kubernetes_tpu.oracle import oracle_schedule
from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.tracing import TraceCollector, Tracer

from helpers import mk_node, mk_pod, random_cluster


@pytest.fixture(autouse=True)
def _force_production_route(monkeypatch):
    """Route the chunked/rounds kernels on the CPU sim (read per call) so
    every case exercises the SAME production route a TPU backend would."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")


def _snap_for(kernel: str, seed: int = 42):
    rng = random.Random(seed)
    if kernel == "chunked":
        # fit-only (infer_score_config strips the rest), P % 128 == 0
        return random_cluster(rng, n_nodes=24, n_pods=120)
    return random_cluster(
        rng, n_nodes=24, n_pods=48,
        with_taints=True, with_selectors=True, with_pairwise=True,
    )


def _decode(choices, meta):
    ch = np.asarray(choices)
    return [
        (meta.pod_names[k],
         meta.node_names[int(ch[k])] if int(ch[k]) >= 0 else None)
        for k in range(meta.n_pods)
    ]


def _bind_some(snap, verdicts, k=4):
    """k placed pods become bound (spec objects shared — template stamping),
    the rest re-pend under fresh names: a small warm delta."""
    by_name = {p.name: p for p in snap.pending_pods}
    bound = []
    for nm, node in verdicts:
        if node is not None and len(bound) < k:
            bound.append(dataclasses.replace(by_name[nm], node_name=node))
    pend = [
        dataclasses.replace(p, name=f"w-{p.name}", uid="")
        for p in snap.pending_pods
    ]
    return Snapshot(nodes=snap.nodes, pending_pods=pend, bound_pods=bound)


@pytest.mark.parametrize("kernel", ["chunked", "rounds"])
@pytest.mark.parametrize("donate", [False, True])
def test_incremental_parity_single_device(kernel, donate, monkeypatch):
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap = _snap_for(kernel)
    enc = DeltaEncoder()
    cache = HoistCache()
    route = f"{kernel}_inc"
    for cycle in range(3):
        arr, meta = enc.encode(snap)
        cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
        inc = cache.ensure(arr, meta, cfg)
        assert inc is not None and inc.req_u.shape[0] < arr.P
        before = dict(TRACE_COUNTS)
        want_c, want_u = schedule_batch_routed(arr, cfg, donate=False)
        got_c, got_u = schedule_batch_routed(
            arr, cfg, donate=donate, inc=inc
        )
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
        np.testing.assert_array_equal(np.asarray(got_u), np.asarray(want_u))
        assert TRACE_COUNTS[route] >= before[route]  # warm jit cache ok
        got = _decode(got_c, meta)
        if cycle == 0:
            # decisions match the serial oracle, not just the dense kernel
            assert got == oracle_schedule(snap, cfg)
        # donation must never consume the resident cache (the aliasing rule)
        for buf in (inc.stat_u, inc.base_u, inc.fit_u, inc.cls, inc.req_u):
            assert not buf.is_deleted()
        snap = _bind_some(snap, got)
    # warm cycles really rode the resident cache (patched, not rebuilt)
    assert cache.stats["patched"] >= 1, cache.stats
    assert enc.stats["delta"] >= 1


@pytest.mark.parametrize("kernel", ["chunked", "rounds"])
@pytest.mark.parametrize("donate", [False, True])
def test_incremental_parity_mesh8(mesh8, kernel, donate, monkeypatch):
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap = _snap_for(kernel, seed=7)
    enc = DeltaEncoder()
    cache = HoistCache(mesh=mesh8)
    for cycle in range(2):
        arr, meta = enc.encode(snap)
        cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
        inc = cache.ensure(arr, meta, cfg)
        assert inc is not None
        want_c, want_u = schedule_batch_routed(arr, cfg, donate=False)
        got_c, got_u = schedule_batch_routed(
            arr, cfg, donate=donate, mesh=mesh8, inc=inc
        )
        n = arr.N
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
        gu = np.asarray(got_u)
        np.testing.assert_array_equal(gu[:n], np.asarray(want_u))
        assert not gu[n:].any()
        snap = _bind_some(snap, _decode(got_c, meta))
    assert cache.stats["patched"] >= 1, cache.stats


def test_incremental_ordinals_parity():
    snap = _snap_for("rounds", seed=3)
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    want = schedule_batch_ordinals_routed(arr, cfg, donate=False)
    got = schedule_batch_ordinals_routed(arr, cfg, donate=False, inc=inc)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[2]), np.asarray(want[2]))
    assert int(got[3]) == int(want[3])


def test_degenerate_all_unique_routes_dense():
    """U == P (every pod a distinct spec, no padding): the dedup is a
    provable no-op — ensure() refuses, the routed call takes the DENSE
    kernel, and decisions are unchanged."""
    nodes = [mk_node(f"n{i}", cpu=16_000, pods=256) for i in range(16)]
    pods = [mk_pod(f"p{i}", cpu=100 + i) for i in range(128)]  # P == p == 128
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    assert arr.P == 128 and meta.n_classes == 128  # no padding class
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    cache = HoistCache()
    inc = cache.ensure(arr, meta, cfg)
    assert inc is None and cache.last["action"] == "skipped_degenerate"
    before = dict(TRACE_COUNTS)
    got_c, _ = schedule_batch_routed(arr, cfg, donate=False, inc=inc)
    assert TRACE_COUNTS["chunked_inc"] == before["chunked_inc"]
    assert _decode(got_c, meta) == oracle_schedule(snap, cfg)


def test_chunked_many_classes_branch():
    """U1 > C exercises the gather-then-topk trace branch of the chunked
    kernel (U1 <= C tops the class matrix instead)."""
    nodes = [mk_node(f"n{i}", cpu=64_000, pods=512) for i in range(16)]
    # 200 unique specs + 56 repeats of the first: U1 = 201 > C = 128 < P
    pods = [mk_pod(f"p{i}", cpu=100 + (i % 200)) for i in range(256)]
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    assert inc is not None and inc.req_u.shape[0] > 128
    want_c, _ = schedule_batch_routed(arr, cfg, donate=False)
    got_c, _ = schedule_batch_routed(arr, cfg, donate=False, inc=inc)
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


def test_kill_switch_disables_incremental(monkeypatch):
    monkeypatch.setenv("KTPU_INCREMENTAL", "0")
    assert not incremental_enabled()
    snap = _snap_for("chunked")
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    cache = HoistCache()
    assert cache.ensure(arr, meta, cfg) is None
    assert cache.stats["disabled"] == 1


# --- the tier-1 warm-cycle regression: a 1-node delta must patch ~1
# column, NOT silently fall back to a full re-hoist ---
def test_warm_cycle_patches_few_columns_trace_guard():
    n_nodes = 32
    nodes = [mk_node(f"n{i}", cpu=32_000, pods=256) for i in range(n_nodes)]
    # 4 templates stamped 64x: U ≪ P, the steady production shape
    tmpl = [mk_pod(f"t{j}", cpu=200 + 100 * j) for j in range(4)]
    pods = [
        dataclasses.replace(tmpl[j % 4], name=f"c1-p{j}", uid="")
        for j in range(256)
    ]
    col = TraceCollector()
    tracer = Tracer(col, component="pipeline")
    loop = PipelinedBatchLoop(donate=False, depth=1, tracer=tracer)
    v1 = loop.submit(Snapshot(nodes=nodes, pending_pods=pods))
    assert v1 is None
    # cycle 2: the SAME wave template, one pod bound to one node — a
    # 1-node warm delta
    bound = [dataclasses.replace(tmpl[0], name="b0", uid="", node_name="n0")]
    pods2 = [
        dataclasses.replace(tmpl[j % 4], name=f"c2-p{j}", uid="")
        for j in range(256)
    ]
    loop.submit(Snapshot(nodes=nodes, pending_pods=pods2, bound_pods=bound))
    v2 = loop.drain()
    spans = col.spans("hoist.update")
    assert len(spans) == 2, [s.attributes for s in spans]
    first, second = (s.attributes for s in spans)
    assert first["action"] in ("static_rebuild", "full")
    # the regression guard: the warm cycle patched ≪ N columns
    assert second["action"] == "patch", second
    assert second["n_cols"] == 1 and second["n_cols"] < n_nodes // 4
    assert second["unique_classes"] <= 5
    assert 0 < second["dirty_node_fraction"] <= 1 / 16
    # and the patched decisions equal a fresh dense encode of cycle 2
    enc = DeltaEncoder()
    arr, meta = enc.encode(
        Snapshot(nodes=nodes, pending_pods=pods2, bound_pods=bound)
    )
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want_c, _ = schedule_batch_routed(arr, cfg, donate=False)
    want = {
        meta.pod_names[k]: (
            meta.node_names[int(np.asarray(want_c)[k])]
            if int(np.asarray(want_c)[k]) >= 0 else None
        )
        for k in range(meta.n_pods)
    }
    assert v2 == want


# --- chaos storm with the cache armed: placements must stay bit-identical
# to the fault-free serial oracle (the PR-3 landability bar) ---
def _churn(pipeline: bool, plan=None, incremental: bool = True):
    os.environ["KTPU_PIPELINE"] = "1" if pipeline else "0"
    os.environ["KTPU_INCREMENTAL"] = "" if incremental else "0"
    try:
        ctx = (
            chaos.chaos_plan(plan) if plan is not None
            else __import__("contextlib").nullcontext()
        )
        with ctx:
            store = ClusterStore()
            for i in range(5):
                store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
            sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
            for i in range(20):
                store.add_pod(mk_pod(f"p{i}", cpu=250))
            sched.run_until_idle()
            rng = random.Random(5)
            for r in range(2):
                bound = sorted(
                    (p for p in store.pods.values() if p.node_name),
                    key=lambda p: p.uid,
                )
                for v in rng.sample(bound, 6):
                    store.delete_pod(v.uid)
                    q = copy.copy(v)
                    q.name = f"{v.name}-r{r}"
                    q.uid = ""
                    q.node_name = ""
                    q.__post_init__()
                    store.add_pod(q)
                sched.run_until_idle()
            placements = {p.name: p.node_name for p in store.pods.values()}
            return placements, sched
    finally:
        os.environ.pop("KTPU_PIPELINE", None)
        os.environ.pop("KTPU_INCREMENTAL", None)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    chaos.uninstall()
    yield
    chaos.uninstall()


def test_chaos_storm_with_cache_armed():
    oracle, _ = _churn(pipeline=False, incremental=False)  # dense serial
    plan = chaos.FaultPlan.from_seed(
        0, sites=("scheduler.step", "host.stall"), n_faults=4
    )
    got, sched = _churn(pipeline=True, plan=plan, incremental=True)
    assert got == oracle
    # the storm really ran with the incremental cache engaged
    assert sched._hoist_cache is not None
    assert (
        sched._hoist_cache.stats["hits"] + sched._hoist_cache.stats["full"]
        + sched._hoist_cache.stats["static_rebuilds"] > 0
    ), sched._hoist_cache.stats


def test_scheduler_incremental_matches_dense_churn():
    """The scheduler batch path with the cache armed is placement-identical
    to the same churn with KTPU_INCREMENTAL=0 (dense kernels)."""
    dense, _ = _churn(pipeline=True, incremental=False)
    inc, sched = _churn(pipeline=True, incremental=True)
    assert inc == dense
