"""Preferred (soft) inter-pod affinity scoring — all paths (closes PARITY D6)."""

import random

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.native import schedule_batch_native
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config, schedule_batch
from kubernetes_tpu.oracle import oracle_schedule
from helpers import mk_node, mk_pod


def run_all_paths(snap):
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    tpu = np.asarray(schedule_batch(arr, cfg)[0])
    native = schedule_batch_native(arr, cfg)[0]
    np.testing.assert_array_equal(native, tpu)
    got = [
        (meta.pod_names[k], meta.node_names[tpu[k]] if tpu[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule(snap)
    assert got == want, f"kernel={got} oracle={want}"
    return dict(got)


def pref_aff(weight=50, anti=False, key=t.LABEL_ZONE, **sel):
    term = t.WeightedPodAffinityTerm(
        weight=weight,
        term=t.PodAffinityTerm(topology_key=key, label_selector=t.LabelSelector.of(**sel)),
    )
    return t.Affinity(
        preferred_pod_affinity=() if anti else (term,),
        preferred_pod_anti_affinity=(term,) if anti else (),
    )


def zone_nodes():
    return [
        mk_node("n-a", labels={t.LABEL_ZONE: "a"}),
        mk_node("n-b", labels={t.LABEL_ZONE: "b"}),
    ]


def test_preferred_affinity_pulls_toward_companion():
    bound = [mk_pod("db", labels={"app": "db"}, node_name="n-b")]
    pod = mk_pod("web", affinity=pref_aff(app="db"))
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=[pod], bound_pods=bound))
    assert got["web"] == "n-b"


def test_preferred_anti_pushes_away():
    bound = [mk_pod("noisy", labels={"app": "noisy"}, node_name="n-a")]
    pod = mk_pod("quiet", affinity=pref_aff(anti=True, app="noisy"))
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=[pod], bound_pods=bound))
    assert got["quiet"] == "n-b"


def test_symmetric_existing_preference_attracts():
    # the BOUND pod prefers app=web near it; incoming web pod feels the pull
    bound = [mk_pod("magnet", labels={"app": "db"}, node_name="n-b",
                    affinity=pref_aff(app="web"))]
    pod = mk_pod("web", labels={"app": "web"})
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=[pod], bound_pods=bound))
    assert got["web"] == "n-b"


def test_weight_tradeoff_between_terms():
    # strong pull to db (80) vs weak anti on cache (10): db wins
    bound = [
        mk_pod("db", labels={"app": "db"}, node_name="n-a"),
        mk_pod("cache", labels={"app": "cache"}, node_name="n-a"),
    ]
    aff = t.Affinity(
        preferred_pod_affinity=(
            t.WeightedPodAffinityTerm(
                weight=80,
                term=t.PodAffinityTerm(topology_key=t.LABEL_ZONE,
                                       label_selector=t.LabelSelector.of(app="db")),
            ),
        ),
        preferred_pod_anti_affinity=(
            t.WeightedPodAffinityTerm(
                weight=10,
                term=t.PodAffinityTerm(topology_key=t.LABEL_ZONE,
                                       label_selector=t.LabelSelector.of(app="cache")),
            ),
        ),
    )
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=[mk_pod("p", affinity=aff)],
                                 bound_pods=bound))
    assert got["p"] == "n-a"


def test_committed_pods_preferences_affect_later_pods():
    # first pod (with a preference for app=web) commits; the second (web) pod
    # should be pulled to wherever the first landed
    pods = [
        mk_pod("early", priority=10, affinity=pref_aff(app="web")),
        mk_pod("web", labels={"app": "web"}),
    ]
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=pods))
    assert got["web"] == got["early"]


def test_random_parity_with_preferred_interpod():
    rng = random.Random(8)
    nodes = zone_nodes() + [mk_node("n-c", labels={t.LABEL_ZONE: "c"})]
    pods = []
    apps = ["web", "db", "cache"]
    for i in range(40):
        app = rng.choice(apps)
        aff = None
        if rng.random() < 0.5:
            aff = pref_aff(weight=rng.choice([10, 50, 100]),
                           anti=rng.random() < 0.4, app=rng.choice(apps))
        pods.append(mk_pod(f"p{i}", labels={"app": app}, affinity=aff,
                           cpu=rng.choice([100, 200]), priority=rng.choice([0, 5])))
    run_all_paths(Snapshot(nodes=nodes, pending_pods=pods))


def req_aff(key=t.LABEL_ZONE, **sel):
    return t.Affinity(
        required_pod_affinity=(
            t.PodAffinityTerm(topology_key=key, label_selector=t.LabelSelector.of(**sel)),
        ),
    )


def test_hard_pod_affinity_weight_attracts():
    # BOUND pod carries REQUIRED affinity toward app=web; the incoming web pod
    # scores hardPodAffinityWeight (default 1) toward its zone — with all else
    # equal, it lands beside the requirer (scoring.go — processExistingPod)
    bound = [mk_pod("requirer", labels={"app": "db"}, node_name="n-b",
                    affinity=req_aff(app="web"))]
    pod = mk_pod("web", labels={"app": "web"})
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=[pod], bound_pods=bound))
    assert got["web"] == "n-b"


def test_hard_pod_affinity_from_committed_pod():
    # a pod whose required affinity is satisfied by the first-pod waiver
    # commits; its required term then pulls the matching pod to its zone
    pods = [
        mk_pod("early", priority=10, labels={"app": "web"}, affinity=req_aff(app="web")),
        mk_pod("web2", labels={"app": "web"}),
    ]
    got = run_all_paths(Snapshot(nodes=zone_nodes(), pending_pods=pods))
    assert got["web2"] == got["early"]


def test_random_parity_with_required_and_preferred_interpod():
    rng = random.Random(11)
    nodes = zone_nodes() + [mk_node("n-c", labels={t.LABEL_ZONE: "c"})]
    pods = []
    apps = ["web", "db", "cache"]
    for i in range(40):
        app = rng.choice(apps)
        aff = None
        r = rng.random()
        if r < 0.3:
            aff = pref_aff(weight=rng.choice([10, 50, 100]),
                           anti=rng.random() < 0.4, app=rng.choice(apps))
        elif r < 0.5:
            aff = req_aff(app=rng.choice(apps))
        pods.append(mk_pod(f"p{i}", labels={"app": app}, affinity=aff,
                           cpu=rng.choice([100, 200]), priority=rng.choice([0, 5])))
    run_all_paths(Snapshot(nodes=nodes, pending_pods=pods))


def test_hard_pod_affinity_weight_configurable():
    # weight 0 disables the hard contribution end-to-end (encoder + kernels +
    # oracle); weight 100 dominates. Exercises the cfg plumbing through
    # encode_snapshot(hard_pod_affinity_weight=...) and ScoreConfig.
    import dataclasses

    bound = [mk_pod("requirer", labels={"app": "db"}, node_name="n-b",
                    affinity=req_aff(app="web"))]
    pod = mk_pod("web", labels={"app": "web"})
    snap = Snapshot(nodes=zone_nodes(), pending_pods=[pod], bound_pods=bound)
    for hw in (0.0, 100.0):
        arr, meta = encode_snapshot(snap, hard_pod_affinity_weight=hw)
        cfg = infer_score_config(
            arr, dataclasses.replace(DEFAULT_SCORE_CONFIG, hard_pod_affinity_weight=hw)
        )
        tpu = np.asarray(schedule_batch(arr, cfg)[0])
        native = schedule_batch_native(arr, cfg)[0]
        np.testing.assert_array_equal(native, tpu)
        want = dict(oracle_schedule(snap, cfg))
        got = meta.node_names[tpu[0]] if tpu[0] >= 0 else None
        assert got == want["web"]
        if hw == 0.0:
            assert got == "n-a"  # no pull: ties break to the lowest index
        else:
            assert got == "n-b"
