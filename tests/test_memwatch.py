"""HBM telemetry plane (ISSUE 15): the live device-memory ledger, the
leak sentinel, and the KTPU020 measured-vs-analytic reconciliation.

Ordering note (tier-1 runs -p no:randomly, so file order holds): the
acceptance gate runs first and pays this module's ONE full mem pass
(an 18-route trace); every later trace-driven test reuses the cached
report.  Fixture tests build synthetic RouteTrace mem blocks."""

import os

import jax
import numpy as np
import pytest

from kubernetes_tpu.analysis.devicecheck import RouteTrace
from kubernetes_tpu.analysis.engine import Baseline
from kubernetes_tpu.analysis.memrules import (
    MEM_RULE_IDS,
    MEM_TOLERANCE,
    MemReconcileRule,
    run_mem_pass,
)
from kubernetes_tpu.api.delta import DeltaEncoder
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.bench import workloads
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops.incremental import HoistCache
from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop
from kubernetes_tpu.scheduler.memwatch import (
    SENTINEL_SLACK_BYTES,
    DeviceMemoryLedger,
    LeakSentinel,
    census_buffers,
    device_memory_stats,
    memwatch_enabled,
    model_bytes_for,
)
from kubernetes_tpu.scheduler.metrics import Metrics

from helpers import mk_node, mk_pod
from kubernetes_tpu import chaos

_PASS_CACHE = {}


def _full_pass():
    """The one full mem pass this module pays for, over the 18-route
    trace shared with the device/shard modules (helpers.shared_route_traces)."""
    if "rep" not in _PASS_CACHE:
        from helpers import shared_route_traces

        _PASS_CACHE["rep"] = run_mem_pass(
            baseline=Baseline([]), pretraced=shared_route_traces())
    return _PASS_CACHE["rep"]


def _wave(seed: int, n_nodes: int = 16, n_pods: int = 32) -> Snapshot:
    rng = np.random.default_rng(seed)
    nodes = [
        mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
        for i in range(n_nodes)
    ]
    pods = [
        mk_pod(f"w{seed}-p{j}", cpu=int(rng.integers(100, 1500)))
        for j in range(n_pods)
    ]
    return Snapshot(nodes=nodes, pending_pods=pods)


# ---- tentpole acceptance: the tier-1 clean gate over all eighteen routes ----


def test_committed_package_is_mem_pass_clean():
    """The acceptance criterion: `--rules KTPU020` exits 0 on the
    committed package — all eighteen routes traced, each carrying a
    reconciled memory block, no unbaselined findings."""
    rep = _full_pass()
    assert rep.errors == []
    assert rep.unbaselined == [], "\n".join(
        f.render() for f in rep.unbaselined)
    assert rep.device["n_traced"] == 18
    assert rep.exit_code == 0


def test_census_equals_field_dims_model_on_all_eighteen_routes():
    """census == FIELD_DIMS-model equality per route: every traced
    route's resident-buffer census resolved through the partition rule
    table's size model and MATCHED it buffer for buffer — the ledger and
    shard_hbm_estimate share one size model."""
    rep = _full_pass()
    for r in rep.device["routes"]:
        assert r["status"] == "traced"
        mem = r["mem"]
        assert mem is not None, f"{r['name']}: no memory block"
        census = mem["census"]
        assert census["matched"] is True, (
            f"{r['name']}: census drifted from the FIELD_DIMS model: "
            f"{census['entries']}"
        )
        assert census["n_buffers"] > 0
        assert census["entries"] == []  # only UNMATCHED entries ship


def test_measured_peak_reconciles_and_sentinel_clean_per_route():
    rep = _full_pass()
    for r in rep.device["routes"]:
        mem = r["mem"]
        assert mem["measured_peak_bytes"] > 0, f"{r['name']}: nothing metered"
        budget = mem["analytic_budget_bytes"]
        assert budget > 0
        assert mem["measured_peak_bytes"] <= MEM_TOLERANCE * budget, (
            f"{r['name']}: measured {mem['measured_peak_bytes']} > "
            f"{MEM_TOLERANCE}x budget {budget}"
        )
        assert mem["sentinel"]["leaking"] is False
        assert len(mem["samples"]) == 3  # cold + two warm cycles


def test_memory_stats_unavailable_recorded_not_passed():
    """KTPU012's discipline: the CPU sim exposes no memory_stats — every
    route RECORDS that (available False, source live_arrays) instead of
    silently passing it off as a device measurement; the reconciliation
    still ran on the live-array source (the clean gate above)."""
    rep = _full_pass()
    stats = device_memory_stats()
    for r in rep.device["routes"]:
        mem = r["mem"]
        assert mem["memory_stats_available"] == stats["available"]
        if not stats["available"]:
            assert mem["source"] == "live_arrays"


def test_device_memory_stats_graceful_on_statless_devices(monkeypatch):
    """A backend whose devices raise from (or lack) memory_stats() yields
    available=False per device and zero totals — never a crash, never a
    fabricated measurement."""

    class _NoStats:
        def memory_stats(self):
            raise RuntimeError("no stats on this backend")

        def __str__(self):
            return "FakeDevice(nostats)"

    monkeypatch.setattr(jax, "devices", lambda: [_NoStats(), _NoStats()])
    stats = device_memory_stats()
    assert stats["available"] is False
    assert stats["bytes_in_use"] == 0
    assert all(d["available"] is False for d in stats["devices"])


# ---- the census ----


def _encoded(mesh=None):
    snap = workloads.heterogeneous(16, 120, seed=5)
    enc = DeltaEncoder(mesh=mesh)
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    return snap, enc, arr, meta, cfg


@pytest.mark.parametrize("use_mesh", [False, True])
def test_census_covers_encoder_hoist_and_inc_without_double_count(
        use_mesh, mesh8):
    mesh = mesh8 if use_mesh else None
    n_shards = 8 if use_mesh else 1
    snap, enc, arr, meta, cfg = _encoded(mesh)
    cache = HoistCache(mesh=mesh)
    inc = cache.ensure(arr, meta, cfg)
    assert inc is not None
    enc.to_device(arr, meta)  # populate the resident device-buffer table
    c_all = census_buffers(encoder=enc, hoist=cache, inc=inc,
                           n_shards=n_shards)
    c_no_inc = census_buffers(encoder=enc, hoist=cache, n_shards=n_shards)
    # the IncState's leaves ARE the cache's device entries — adding inc
    # must not double-count a single buffer
    assert c_all["n_buffers"] == c_no_inc["n_buffers"]
    assert c_all["matched"] is True
    assert c_all["resident_bytes"] > 0
    qualnames = {e["qualname"] for e in c_all["entries"]}
    assert "arr.pod_req" in qualnames and "inc.base_u" in qualnames


def test_census_returns_to_baseline_on_invalidate_and_drop(mesh8):
    """The restore()/invalidate() invariant: a cache invalidation or a
    resident-buffer drop (what Scheduler.restore() forces) must return
    the census to baseline — nothing the framework owns stays resident."""
    snap, enc, arr, meta, cfg = _encoded(mesh8)
    cache = HoistCache(mesh=mesh8)
    cache.ensure(arr, meta, cfg)
    enc.to_device(arr, meta)
    assert census_buffers(encoder=enc, hoist=cache,
                          n_shards=8)["resident_bytes"] > 0
    cache.invalidate()
    enc.drop_device_buffers()
    after = census_buffers(encoder=enc, hoist=cache, n_shards=8)
    assert after["resident_bytes"] == 0 and after["n_buffers"] == 0


def test_census_skips_deleted_buffers():
    """Donation retiring a buffer removes it from the census (the sentinel
    invariant: retired buffers are not resident)."""
    snap, enc, arr, meta, cfg = _encoded()
    enc.to_device(arr, meta)
    before = census_buffers(encoder=enc)["n_buffers"]
    assert before > 0
    for _name, ent in enc._dev.items():
        ent[1].delete()
    assert census_buffers(encoder=enc)["n_buffers"] == 0


def test_model_bytes_detects_itemsize_drift():
    """A buffer whose dtype diverges from the table's declared itemsize is
    an UNMATCHED census entry — the drift KTPU020 flags."""
    a32 = jax.device_put(np.zeros((7, 4), np.int32))    # table: 4 bytes
    a8 = jax.device_put(np.zeros((7, 4), np.int8))      # drifted dtype
    ok = census_buffers(arr=None, inc=None)  # empty census baseline
    assert ok["n_buffers"] == 0
    assert model_bytes_for("arr.pod_req", (7, 4)) == 7 * 4 * 4
    from kubernetes_tpu.scheduler.memwatch import _census_entry

    assert _census_entry("arr.pod_req", a32, 1)["matched"] is True
    assert _census_entry("arr.pod_req", a8, 1)["matched"] is False
    assert model_bytes_for("not.a.field", (3,)) is None
    assert model_bytes_for("arr.pod_req", (3,)) is None  # rank mismatch


# ---- the leak sentinel ----


def test_sentinel_flags_monotone_growth_only():
    s = LeakSentinel(slack_bytes=1000)
    for v in (0, 2000, 4000, 6000):
        s.observe(v)
    assert s.verdict()["leaking"] is True
    noisy = LeakSentinel(slack_bytes=1000)
    for v in (0, 5000, 4000, 9000):  # one shrink breaks the monotone run
        noisy.observe(v)
    assert noisy.verdict()["leaking"] is False
    drift = LeakSentinel(slack_bytes=10_000)
    for v in (0, 200, 400, 600):  # sub-slack drift is allocator noise
        drift.observe(v)
    assert drift.verdict()["leaking"] is False
    short = LeakSentinel(slack_bytes=10)
    for v in (0, 50_000):  # one delta is not a trend
        short.observe(v)
    assert short.verdict()["leaking"] is False


def test_sentinel_window_is_bounded():
    """The leak detector must not itself leak: the sample history is a
    rolling window (SENTINEL_WINDOW); a leak outlasting it still flags
    because every delta inside the window stays positive."""
    s = LeakSentinel(slack_bytes=10, window=8)
    for i in range(1000):
        s.observe(i * 100)
    assert len(s.samples) == 8
    assert s.verdict()["leaking"] is True


def test_memwatch_false_override_disarms_one_loop():
    """The harness's untimed serial-reference pass disarms its ledger
    (memwatch=False) without touching the env default."""
    assert memwatch_enabled()
    off = PipelinedBatchLoop(donate=False, memwatch=False)
    assert off.memwatch is None
    on = PipelinedBatchLoop(donate=False)
    assert on.memwatch is not None


def test_ledger_accumulates_unmatched_entries_across_samples():
    """census_matched is an AND over all samples — the offending
    qualnames must accumulate with it, so a transient drift still names
    its buffer in the KTPU020 evidence."""
    ledger = DeviceMemoryLedger()
    ledger.baseline()
    bad = jax.device_put(np.zeros((7, 4), np.int8))  # table says 4-byte
    from kubernetes_tpu.api.snapshot import ClusterArrays  # noqa: F401

    class _Enc:  # a one-entry resident table with a drifted dtype
        _dev = {"pod_req": (None, bad)}

    ledger.cycle_sample(encoder=_Enc(), label="cold")
    ledger.cycle_sample(encoder=None, label="warm")  # drift gone
    assert ledger.census_matched is False
    assert "arr.pod_req" in ledger.census_unmatched


def test_ledger_catches_a_real_retained_buffer_leak():
    """The injected-leak scenario, live: each cycle a retired buffer is
    deliberately RETAINED outside every census — unaccounted live bytes
    rise monotonically past the slack and the sentinel trips."""
    ledger = DeviceMemoryLedger()
    ledger.baseline()
    retained = []
    for i in range(3):
        # 512 KiB per cycle, never released, never censused
        retained.append(jax.device_put(np.zeros((1 << 17,), np.float32)))
        retained[-1].block_until_ready()
        ledger.cycle_sample(label=f"cycle{i}")
    v = ledger.sentinel.verdict()
    assert v["leaking"] is True, v
    assert v["growth_bytes"] > SENTINEL_SLACK_BYTES
    del retained


# ---- KTPU020 fixtures (synthetic RouteTrace mem blocks) ----


def _mem_trace(name="fx/mem", mem=None, **overrides):
    t = RouteTrace(name, kind="fixture", donate=False, n_shards=1)
    base = {
        "measured_peak_bytes": 1000,
        "analytic_budget_bytes": 1000,
        "source": "live_arrays",
        "memory_stats_available": False,
        "census": {"matched": True, "resident_bytes": 500,
                   "per_shard_bytes": 500, "model_bytes": 500,
                   "n_buffers": 3, "entries": []},
        "sentinel": {"leaking": False, "samples": [0, 0, 0], "deltas": [0, 0],
                     "growth_bytes": 0, "slack_bytes": SENTINEL_SLACK_BYTES},
        "samples": [],
    }
    base.update(mem or {})
    base.update(overrides)
    t.mem = base
    return t


def test_ktpu020_injected_leak_fixture_is_exit_1():
    """The acceptance criterion: a route whose sentinel observed a
    monotone retained-buffer leak exits 1 through the full pass
    contract."""
    leak = _mem_trace("fx/leak", sentinel={
        "leaking": True, "samples": [0, 600_000, 1_200_000],
        "deltas": [600_000, 600_000], "growth_bytes": 1_200_000,
        "slack_bytes": SENTINEL_SLACK_BYTES,
    })
    rep = run_mem_pass(rule_ids=["KTPU020"], baseline=Baseline([]),
                       pretraced=([leak], []))
    assert rep.exit_code == 1
    assert any(f.snippet == "sentinel-leak" for f in rep.unbaselined)


def test_ktpu020_budget_breach_and_within_tolerance():
    over = _mem_trace("fx/over", measured_peak_bytes=int(
        MEM_TOLERANCE * 1000) + 1)
    ok = _mem_trace("fx/ok", measured_peak_bytes=int(MEM_TOLERANCE * 1000))
    findings = MemReconcileRule().check([over, ok])
    assert len(findings) == 1
    assert findings[0].snippet.startswith("mem:")
    assert findings[0].func == "fx/over"


def test_ktpu020_missing_mem_block_fails_closed():
    t = RouteTrace("fx/none", kind="fixture", donate=False, n_shards=1)
    findings = MemReconcileRule().check([t])
    assert [f.snippet for f in findings] == ["no-mem-block"]
    skipped = RouteTrace("fx/skip", kind="fixture", donate=False, n_shards=8)
    skipped.status = "skipped"
    assert MemReconcileRule().check([skipped]) == []


def test_ktpu020_census_model_drift_is_a_finding():
    drift = _mem_trace("fx/drift", census={
        "matched": False, "resident_bytes": 500, "per_shard_bytes": 500,
        "model_bytes": 900, "n_buffers": 3,
        "entries": [{"qualname": "arr.pod_req", "matched": False}],
    })
    findings = MemReconcileRule().check([drift])
    assert [f.snippet for f in findings] == ["census-model-drift"]
    assert "arr.pod_req" in findings[0].message


def test_ktpu020_zero_budget_skips_reconcile_not_sentinel():
    """A fixture without an analytic budget cannot reconcile (nothing to
    compare) but the sentinel still gates."""
    t = _mem_trace("fx/nobudget", analytic_budget_bytes=0,
                   measured_peak_bytes=10**9)
    assert MemReconcileRule().check([t]) == []


# ---- clean matrix: {donate} x {mesh} x {invalidate, restore, chaos} ----


@pytest.mark.parametrize("donate", [False, True])
@pytest.mark.parametrize("use_mesh", [False, True])
def test_sentinel_clean_through_warm_cycles_and_resets(donate, use_mesh,
                                                       mesh8):
    """The clean half of the sentinel contract: warm cycles with donation
    on/off, single-device and mesh8, a mid-stream invalidate() +
    drop_device_buffers() (the restore() analog) — unaccounted bytes must
    NOT grow monotonically and the census must stay model-matched."""
    mesh = mesh8 if use_mesh else None
    loop = PipelinedBatchLoop(donate=donate, mesh=mesh)
    assert loop.memwatch is not None
    waves = [_wave(s) for s in range(6)]
    for i, w in enumerate(waves):
        loop.submit(w)
        if i == 3:
            loop.hoist.invalidate()
            loop.enc.drop_device_buffers()
    loop.drain()
    assert loop.memwatch.samples == 6
    v = loop.memwatch.sentinel.verdict()
    assert v["leaking"] is False, v
    assert loop.memwatch.census_matched is True
    assert loop.memwatch.hbm_peak_bytes() >= 0


def test_sentinel_clean_through_chaos_wave_recovery():
    """A wave that dies mid-flight and recovers by serial replay must
    return the process to baseline — the recovery path leaks nothing."""
    waves = [_wave(s) for s in range(5)]
    with chaos.chaos_plan(chaos.FaultPlan.single("pipeline.step", "error",
                                                 at=1)):
        loop = PipelinedBatchLoop(donate=False, depth=1)
        list(loop.run(waves))
    assert loop.stats["recovered"] == 1
    v = loop.memwatch.sentinel.verdict()
    assert v["leaking"] is False, v
    assert loop.memwatch.census_matched is True


def test_memwatch_kill_switch():
    os.environ["KTPU_MEMWATCH"] = "0"
    try:
        assert not memwatch_enabled()
        loop = PipelinedBatchLoop(donate=False)
        assert loop.memwatch is None
        from kubernetes_tpu.bench.harness import memwatch_fields

        assert memwatch_fields(loop, None, 1) == {}
    finally:
        os.environ.pop("KTPU_MEMWATCH", None)
    assert memwatch_enabled()


# ---- gauges, artifacts, flight recorder ----


def test_cycle_sample_stamps_device_hbm_gauge_family():
    metrics = Metrics()
    snap, enc, arr, meta, cfg = _encoded()
    enc.to_device(arr, meta)
    ledger = DeviceMemoryLedger(metrics=metrics)
    ledger.cycle_sample(encoder=enc, label="cycle")
    _counters, gauges, _hists = metrics.snapshot()
    assert gauges["device_hbm_resident_bytes"] > 0
    for name in ("device_hbm_in_use_bytes", "device_hbm_peak_bytes",
                 "device_hbm_unaccounted_bytes"):
        assert name in gauges
    # /metrics exposition carries the family next to the queue gauges
    text = metrics.expose_text()
    assert "device_hbm_resident_bytes" in text


def test_summary_and_scale_out_fields_ride_the_stream_artifact():
    from kubernetes_tpu.bench.harness import run_streaming_workload

    waves = [_wave(s) for s in range(3)]
    out = run_streaming_workload("mw-smoke", waves, warmup=False)
    assert out["hbm_peak_bytes"] > 0
    assert out["hbm_resident_bytes"] > 0
    mw = out["memwatch"]
    assert mw["census_matched"] is True
    assert mw["sentinel"]["leaking"] is False
    assert mw["source"] in ("memory_stats", "live_arrays")
    # the PR-4 scale-out numbers: stamped in the artifact AND derivable
    # as gauges (memwatch_fields sets them on the run's registry)
    assert out["per_shard_hbm_bytes"] > 0


def test_per_shard_hbm_estimate_from_census(mesh8):
    snap, enc, arr, meta, cfg = _encoded()
    enc.to_device(arr, meta)
    ledger = DeviceMemoryLedger()
    ledger.cycle_sample(encoder=enc)
    est = ledger.per_shard_hbm_estimate()
    from kubernetes_tpu.ops import assign as A
    from kubernetes_tpu.parallel.mesh import shard_hbm_estimate

    want = shard_hbm_estimate(
        arr.P, arr.N, 1, n_res=arr.R,
        n_terms=arr.term_counts0.shape[0], chunk=A._CHUNK,
    )["total"]
    assert est == want
    empty = DeviceMemoryLedger()
    assert empty.per_shard_hbm_estimate() is None


def test_flight_record_memory_block_renders():
    from kubernetes_tpu.scheduler.flightrecorder import (
        FlightRecorder, render_flight,
    )

    ledger = DeviceMemoryLedger()
    ledger.cycle_sample(label="cycle")
    block = ledger.memory_block()
    assert set(block) == {"in_use", "peak", "resident", "unaccounted",
                          "source"}
    rec = FlightRecorder(directory=None, capacity=4)
    rec.record(profile="default", pods=3, scheduled=2, failed=1,
               verdict_crc="cafecafe", mem=block)
    text = render_flight({"version": 1, "reason": "test", "capacity": 4,
                          "records": rec.records()})
    assert "hbm[in_use=" in text and "src=" in text


def test_scheduler_samples_memory_at_cycle_boundaries(monkeypatch):
    from kubernetes_tpu.scheduler import (
        ClusterStore, Scheduler, SchedulerConfiguration,
    )

    monkeypatch.delenv("KTPU_MESH", raising=False)
    store = ClusterStore()
    for i in range(4):
        store.add_node(mk_node(f"n{i}", cpu=4000))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    assert sched._memwatch is not None
    for j in range(6):
        store.add_pod(mk_pod(f"p{j}", cpu=500))
    sched.run_until_idle()
    assert sched._memwatch.samples >= 2  # both cycle boundaries sampled
    _c, gauges, _h = sched.metrics.snapshot()
    assert "device_hbm_resident_bytes" in gauges
    assert sched._memwatch.sentinel.verdict()["leaking"] is False


# ---- CLI wiring ----


def test_cli_knows_ktpu020_and_refuses_typos(capsys):
    from kubernetes_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit):
        main(["--rules", "KTPU021"])
    err = capsys.readouterr().err
    assert "KTPU021" in err and "KTPU020" in err
    assert MEM_RULE_IDS == ("KTPU020",)


def test_mem_pass_reuses_pretraced_routes():
    """`--device --shard --mem` shares ONE 18-route trace: run_mem_pass
    over the cached pass's traces reports the same clean verdict without
    re-tracing (the shared-trace contract)."""
    rep = _full_pass()
    # rebuild RouteTraces from the cached report is not possible — instead
    # prove the pretraced path end to end with fixtures
    t = _mem_trace("fx/pretraced")
    rep2 = run_mem_pass(baseline=Baseline([]), pretraced=([t], []))
    assert rep2.exit_code == 0
    assert rep2.device["n_traced"] == 1
    assert rep.device["n_traced"] == 18
