"""Class-batched commit waves (ISSUE 17): the wave stage that collapses
the prefix-commit round loop must be BIT-IDENTICAL to the serial oracle
and the dense kernels across {chunked, rounds, inc} x {donate on/off} x
{single-device, mesh8} over warm churn, survive a seeded chaos storm with
batching armed, exercise the interference fallback (exact [N, R] rescore
+ epoch continuation) on an adversarial same-class contention wave, and
stay OFF the wave route for the degenerate U == P wave (trace guard:
the dedup is a no-op there, so the dense kernel routes)."""

import copy
import dataclasses
import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api.delta import DeltaEncoder
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops import assign
from kubernetes_tpu.ops.assign import (
    TRACE_COUNTS,
    schedule_batch_ordinals_routed,
    schedule_batch_routed,
)
from kubernetes_tpu.ops.incremental import HoistCache
from kubernetes_tpu.oracle import oracle_schedule
from kubernetes_tpu.scheduler import (
    ClusterStore,
    Scheduler,
    SchedulerConfiguration,
)

from helpers import mk_node, mk_pod, random_cluster


@pytest.fixture(autouse=True)
def _force_production_route(monkeypatch):
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _decode(choices, meta):
    ch = np.asarray(choices)
    return [
        (meta.pod_names[k],
         meta.node_names[int(ch[k])] if int(ch[k]) >= 0 else None)
        for k in range(meta.n_pods)
    ]


def _bind_some(snap, verdicts, k=4):
    by_name = {p.name: p for p in snap.pending_pods}
    bound = []
    for nm, node in verdicts:
        if node is not None and len(bound) < k:
            bound.append(dataclasses.replace(by_name[nm], node_name=node))
    pend = [
        dataclasses.replace(p, name=f"w-{p.name}", uid="")
        for p in snap.pending_pods
    ]
    return Snapshot(nodes=snap.nodes, pending_pods=pend, bound_pods=bound)


def _snap_for(kernel: str, seed: int = 42):
    rng = random.Random(seed)
    if kernel == "chunked":
        return random_cluster(rng, n_nodes=24, n_pods=120)
    return random_cluster(
        rng, n_nodes=24, n_pods=48,
        with_taints=True, with_selectors=True, with_pairwise=True,
    )


def test_wave_stage_traces_on_inc_chunked_route():
    """Trace guard: with batching armed (the default), the incremental
    chunked route compiles WITH the wave stage — class_waves bumps on a
    fresh trace, and decisions match the dense kernel AND the oracle."""
    assert assign._CLASS_WAVES  # armed by default
    snap = _snap_for("chunked")
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    assert inc is not None
    jax.clear_caches()  # strict: prove THIS call traces the wave
    before = dict(TRACE_COUNTS)
    got_c, got_u = schedule_batch_routed(arr, cfg, donate=False, inc=inc)
    assert TRACE_COUNTS["class_waves"] > before["class_waves"]
    assert TRACE_COUNTS["chunked_inc"] > before["chunked_inc"]
    want_c, want_u = schedule_batch_routed(arr, cfg, donate=False)
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
    np.testing.assert_array_equal(np.asarray(got_u), np.asarray(want_u))
    assert _decode(got_c, meta) == oracle_schedule(snap, cfg)


@pytest.mark.parametrize("kernel", ["chunked", "rounds"])
@pytest.mark.parametrize("donate", [False, True])
def test_wave_warm_churn_parity_single_device(kernel, donate, monkeypatch):
    """Warm churn with batching armed: every cycle's batched decisions are
    bit-identical to the dense kernel, the first to the serial oracle, and
    the resident class matrices survive donation (the aliasing rule the
    carried dirty list leans on — PARITY.md)."""
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap = _snap_for(kernel)
    enc = DeltaEncoder()
    cache = HoistCache()
    for cycle in range(3):
        arr, meta = enc.encode(snap)
        cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
        inc = cache.ensure(arr, meta, cfg)
        assert inc is not None
        want_c, want_u = schedule_batch_routed(arr, cfg, donate=False)
        got_c, got_u = schedule_batch_routed(arr, cfg, donate=donate,
                                             inc=inc)
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
        np.testing.assert_array_equal(np.asarray(got_u), np.asarray(want_u))
        got = _decode(got_c, meta)
        if cycle == 0:
            assert got == oracle_schedule(snap, cfg)
        for buf in (inc.stat_u, inc.base_u, inc.fit_u, inc.cls, inc.req_u):
            assert not buf.is_deleted()
        snap = _bind_some(snap, got)
    assert cache.stats["patched"] >= 1, cache.stats


@pytest.mark.parametrize("kernel", ["chunked", "rounds"])
@pytest.mark.parametrize("donate", [False, True])
def test_wave_warm_churn_parity_mesh8(mesh8, kernel, donate, monkeypatch):
    """Same matrix across the 8-way mesh: the wave stage runs on the
    post-gather replicated inputs, so the sharded collective sequence is
    unchanged (KTPU009) and decisions match the single-device kernel."""
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap = _snap_for(kernel, seed=7)
    enc = DeltaEncoder()
    cache = HoistCache(mesh=mesh8)
    for cycle in range(2):
        arr, meta = enc.encode(snap)
        cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
        inc = cache.ensure(arr, meta, cfg)
        assert inc is not None
        want_c, want_u = schedule_batch_routed(arr, cfg, donate=False)
        got_c, got_u = schedule_batch_routed(
            arr, cfg, donate=donate, mesh=mesh8, inc=inc
        )
        n = arr.N
        np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
        gu = np.asarray(got_u)
        np.testing.assert_array_equal(gu[:n], np.asarray(want_u))
        assert not gu[n:].any()
        snap = _bind_some(snap, _decode(got_c, meta))


def test_wave_ordinals_monotone_and_sweeps_counted():
    """The batched route's commit ordinals stay a valid per-pod latency
    decomposition: every scheduled pod's ordinal is in [0, sweeps), and
    the wave collapses sweeps well below the one-pod-per-round count."""
    snap = _snap_for("chunked", seed=5)
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    c, _, o, s = schedule_batch_ordinals_routed(arr, cfg, donate=False,
                                                inc=inc)
    c = np.asarray(c)[: meta.n_pods]
    o = np.asarray(o)[: meta.n_pods]
    s = int(s)
    m = c >= 0
    assert m.any()
    assert (o[m] >= 0).all() and (o[m] < s).all()
    # the batching bought something: fewer sweeps than scheduled pods
    assert s < int(m.sum()), (s, int(m.sum()))


def _interference_snap():
    # one dominant class hammering a handful of nearly-full nodes: almost
    # every commit moves the winning node's score, so wave blocks truncate
    # at the certification check and the exact fallback rescore + epoch
    # continuation must carry the frontier
    nodes = [mk_node(f"n{i}", cpu=4000, pods=40) for i in range(8)]
    pods = [
        dataclasses.replace(mk_pod("big", cpu=1000), name=f"p{i:03d}",
                            uid="")
        for i in range(240)
    ] + [mk_pod(f"q{i}", cpu=500) for i in range(16)]
    return Snapshot(nodes=nodes, pending_pods=pods)


def test_interference_heavy_wave_forces_fallback():
    """Adversarial same-class contention: the wave kernel's epoch counter
    must tick (fallback commits stacked onto claimed nodes / truncated
    blocks force continuation epochs), capacity must exhaust exactly where
    the serial semantics say, and decisions stay bit-identical to the
    dense kernel and the oracle."""
    from kubernetes_tpu.ops.scores import balanced_allocation, fit_score

    snap = _interference_snap()
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    assert inc is not None

    # white-box: drive the wave stage directly and read its epoch counter
    res = cfg.score_resources

    def score_flat(requested, alloc):
        return cfg.fit_weight * fit_score(requested, alloc, cfg) + \
            cfg.balanced_weight * balanced_allocation(requested, alloc, res)

    # the kernels' inc hoist: packed word planes AND together, then unpack
    # once at the dense-score frontier (ops/assign.py — schedule_scan_chunked)
    from kubernetes_tpu.ops import bitplane

    sfw = inc.stat_u & inc.fit_u
    sf = bitplane.unpack(sfw, arr.N) if bitplane.PACK_MASKS else sfw
    t0u_init = jnp.where(sf, inc.base_u, -jnp.inf)
    f = jax.jit(lambda c, pv, pr, ui, t0, st, na, ru:
                assign._wave_commit_stage(c, pv, pr, ui, t0, st, na, ru,
                                          score_flat))
    outs = f(inc.cls, arr.pod_valid, arr.pod_req, arr.node_used, t0u_init,
             inc.stat_u, arr.node_alloc, inc.req_u)
    committed, blocks, epochs = (np.asarray(outs[0]), int(outs[5]),
                                 int(outs[6]))
    assert committed.any()
    # interference really forced the fallback/continuation machinery
    assert epochs > 0, (blocks, epochs)

    # ... and the end-to-end routed decisions are still exact
    got_c, got_u = schedule_batch_routed(arr, cfg, donate=False, inc=inc)
    want_c, want_u = schedule_batch_routed(arr, cfg, donate=False)
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
    np.testing.assert_array_equal(np.asarray(got_u), np.asarray(want_u))
    assert _decode(got_c, meta) == oracle_schedule(snap, cfg)
    # capacity genuinely exhausted mid-wave (the adversarial regime)
    ch = np.asarray(got_c)[: meta.n_pods]
    assert (ch >= 0).any() and (ch < 0).any()


def test_degenerate_all_unique_never_traces_wave():
    """U == P: ensure() refuses the no-op dedup, the routed call takes the
    DENSE kernel, and the wave stage never traces (class_waves flat)."""
    nodes = [mk_node(f"n{i}", cpu=16_000, pods=256) for i in range(16)]
    pods = [mk_pod(f"p{i}", cpu=100 + i) for i in range(128)]
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    enc = DeltaEncoder()
    arr, meta = enc.encode(snap)
    assert arr.P == 128 and meta.n_classes == 128
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc = HoistCache().ensure(arr, meta, cfg)
    assert inc is None
    jax.clear_caches()  # strict: a warm cache would make the guard vacuous
    before = dict(TRACE_COUNTS)
    got_c, _ = schedule_batch_routed(arr, cfg, donate=False, inc=inc)
    assert TRACE_COUNTS["class_waves"] == before["class_waves"]
    assert TRACE_COUNTS["chunked_inc"] == before["chunked_inc"]
    assert _decode(got_c, meta) == oracle_schedule(snap, cfg)


# --- seeded chaos storm with batching armed: placements bit-identical to
# the fault-free dense serial churn (the landability bar) ---
def _churn(pipeline: bool, plan=None, incremental: bool = True):
    os.environ["KTPU_PIPELINE"] = "1" if pipeline else "0"
    os.environ["KTPU_INCREMENTAL"] = "" if incremental else "0"
    try:
        ctx = (
            chaos.chaos_plan(plan) if plan is not None
            else __import__("contextlib").nullcontext()
        )
        with ctx:
            store = ClusterStore()
            for i in range(6):
                store.add_node(mk_node(f"n{i}", cpu=4000, pods=24))
            sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
            for i in range(28):
                store.add_pod(mk_pod(f"p{i}", cpu=250 + 50 * (i % 3)))
            sched.run_until_idle()
            rng = random.Random(17)
            for r in range(2):
                bound = sorted(
                    (p for p in store.pods.values() if p.node_name),
                    key=lambda p: p.uid,
                )
                for v in rng.sample(bound, 8):
                    store.delete_pod(v.uid)
                    q = copy.copy(v)
                    q.name = f"{v.name}-r{r}"
                    q.uid = ""
                    q.node_name = ""
                    q.__post_init__()
                    store.add_pod(q)
                sched.run_until_idle()
            placements = {p.name: p.node_name for p in store.pods.values()}
            return placements, sched
    finally:
        os.environ.pop("KTPU_PIPELINE", None)
        os.environ.pop("KTPU_INCREMENTAL", None)


def test_chaos_storm_with_batching_armed():
    assert assign._CLASS_WAVES
    oracle, _ = _churn(pipeline=False, incremental=False)  # dense serial
    plan = chaos.FaultPlan.from_seed(
        3, sites=("scheduler.step", "host.stall"), n_faults=5
    )
    got, sched = _churn(pipeline=True, plan=plan, incremental=True)
    assert got == oracle
    # the storm really rode the class-hoisted (wave-armed) route
    assert sched._hoist_cache is not None
    assert (
        sched._hoist_cache.stats["hits"] + sched._hoist_cache.stats["full"]
        + sched._hoist_cache.stats["static_rebuilds"] > 0
    ), sched._hoist_cache.stats
