"""Volume family round-3 additions: ReadWriteOncePod serialization
(volumerestrictions/volume_restrictions.go — the one non-deprecated
restriction), resolved identically by every engine via api/volumes."""

from kubernetes_tpu.api import types as t
from helpers import mk_node, mk_pod

GI = 1024 ** 3



# ------------------------------------------------- ReadWriteOncePod (round 3)


def test_read_write_once_pod_serializes_users():
    """volumerestrictions — ReadWriteOncePod: one pod cluster-wide may use
    the claim; a live holder blocks new users; pending users serialize in
    arrival order; the holder finishing releases the claim."""
    import dataclasses

    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.oracle import oracle_schedule

    pvc = t.PersistentVolumeClaim(
        name="rwop", request=1, storage_class="", read_write_once_pod=True,
        wait_for_first_consumer=True,
    )
    nodes = [mk_node("n0"), mk_node("n1")]
    a = mk_pod("a", cpu=100)
    b = mk_pod("b", cpu=100)
    a = dataclasses.replace(a, pvcs=("rwop",))
    b = dataclasses.replace(b, pvcs=("rwop",))
    snap = Snapshot(nodes=nodes, pending_pods=[a, b], pvcs={pvc.key: pvc})
    got = dict(oracle_schedule(snap))
    assert got["a"] is not None and got["b"] is None  # arrival order wins
    # a live bound holder blocks every pending user
    holder = dataclasses.replace(a, name="holder", uid="", node_name="n0")
    snap2 = Snapshot(nodes=nodes, pending_pods=[dataclasses.replace(b)],
                     bound_pods=[holder], pvcs={pvc.key: pvc})
    got2 = dict(oracle_schedule(snap2))
    assert got2["b"] is None
    # ... until the holder reaches a terminal phase
    done = dataclasses.replace(holder, phase=t.PHASE_SUCCEEDED)
    snap3 = Snapshot(nodes=nodes, pending_pods=[dataclasses.replace(b)],
                     bound_pods=[done], pvcs={pvc.key: pvc})
    got3 = dict(oracle_schedule(snap3))
    assert got3["b"] is not None
    # a non-RWOP claim shared by two pods schedules both
    plain = t.PersistentVolumeClaim(name="shared", request=1,
                                    wait_for_first_consumer=True)
    c = dataclasses.replace(mk_pod("c", cpu=100), pvcs=("shared",))
    d = dataclasses.replace(mk_pod("d", cpu=100), pvcs=("shared",))
    snap4 = Snapshot(nodes=nodes, pending_pods=[c, d],
                     pvcs={plain.key: plain})
    got4 = dict(oracle_schedule(snap4))
    assert got4["c"] is not None and got4["d"] is not None


def test_read_write_once_pod_parity_through_batch_path():
    import dataclasses

    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration

    pvc = t.PersistentVolumeClaim(
        name="rwop", request=1, read_write_once_pod=True,
        wait_for_first_consumer=True,
    )
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    store.add_node(mk_node("n1"))
    store.add_pvc(pvc)
    store.add_pv(t.PersistentVolume(name="pv0", capacity=10))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(dataclasses.replace(mk_pod("a", cpu=100), pvcs=("rwop",)))
    store.add_pod(dataclasses.replace(mk_pod("b", cpu=100), pvcs=("rwop",)))
    sched.run_until_idle()
    bound = {p.name: bool(p.node_name) for p in store.pods.values()}
    assert bound == {"a": True, "b": False}, bound


def test_allowed_topology_values_or_within_key():
    """Repeated keys in allowed_topology OR their values (the reference's
    TopologySelectorTerm.matchLabelExpressions carries values[] per key);
    regression: they previously lowered to ANDed single-value expressions,
    which is unsatisfiable and marked every claimer unschedulable."""
    from kubernetes_tpu.api.cluster import StorageClass
    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.api.volumes import resolve_snapshot
    from kubernetes_tpu.oracle import oracle_schedule

    nodes = [
        t.Node(name=f"n{z}", allocatable={t.CPU: 4000, t.PODS: 10},
               labels={t.LABEL_ZONE: f"zone-{z}"})
        for z in range(3)
    ]
    sc = StorageClass(
        name="wffc",
        provisioner="csi.example.com",
        volume_binding_mode="WaitForFirstConsumer",
        allowed_topology=(
            (t.LABEL_ZONE, "zone-0"),
            (t.LABEL_ZONE, "zone-1"),
        ),
    )
    pod = t.Pod(name="p", requests={t.CPU: 100}, pvcs=("c",))
    snap = Snapshot(
        nodes=nodes,
        pending_pods=[pod],
        pvcs={"default/c": t.PersistentVolumeClaim(
            name="c", request=1 << 30, storage_class="wffc",
            wait_for_first_consumer=True)},
        storage_classes={"wffc": sc},
    )
    rs = resolve_snapshot(snap)
    (q,) = rs.pending_pods
    (term,) = q.affinity.required_node_terms
    (expr,) = term.match_expressions  # ONE expression, both values OR'd
    assert set(expr.values) == {"zone-0", "zone-1"}
    got = dict(oracle_schedule(snap))
    assert got["p"] in ("n0", "n1")  # schedulable, zone-2 excluded


def test_wffc_class_with_multiple_allowed_zones_provisions_in_any():
    """AllowedTopologies pairs sharing a key OR their values (the
    reference's TopologySelectorTerm.matchLabelExpressions.values[]): a
    class allowing zone-0 OR zone-1 must provision on a node in either
    zone.  Regression: _matches_node used to AND every pair, making any
    multi-zone class unprovisionable anywhere."""
    from kubernetes_tpu.api.cluster import StorageClass
    from kubernetes_tpu.scheduler.store import ClusterStore
    from kubernetes_tpu.scheduler.volumebinder import bind_pod_volumes

    store = ClusterStore()
    store.add_node(t.Node(name="n0", allocatable={t.CPU: 1000},
                          labels={t.LABEL_ZONE: "zone-1"}))
    store.add_object("StorageClass", StorageClass(
        name="wffc", provisioner="csi.example.com",
        volume_binding_mode="WaitForFirstConsumer",
        allowed_topology=((t.LABEL_ZONE, "zone-0"), (t.LABEL_ZONE, "zone-1")),
    ))
    store.add_pvc(t.PersistentVolumeClaim(
        name="data", request=GI, storage_class="wffc",
        wait_for_first_consumer=True,
    ))
    pod = t.Pod(name="p", pvcs=("data",))
    store.add_pod(pod)
    err = bind_pod_volumes(store, pod, "n0")
    assert err is None, err
    pvc = store.pvcs["default/data"]
    assert pvc.volume_name, "claim bound to a provisioned volume"
