"""Benchmark harness smoke tests (scheduler_perf analog, tiny scale)."""

from kubernetes_tpu.bench.harness import run_yaml


def test_harness_runs_tiny_configs():
    text = """
name: Tiny
ops:
  - {op: createCluster, generator: basic, nodes: 16, pods: 40}
  - {op: measure}
---
name: TinyGang
ops:
  - {op: createCluster, generator: gang, groups: 3, group_size: 4, nodes: 8}
  - {op: measure}
"""
    results = run_yaml(text)
    assert [r.name for r in results] == ["Tiny", "TinyGang"]
    basic = results[0]
    assert basic.scheduled == 40 and basic.unschedulable == 0
    assert basic.pods_per_sec > 0
    gang = results[1]
    assert gang.scheduled == 12


def test_harness_cpu_mode_matches_tpu():
    text = """
name: T
ops:
  - {op: createCluster, generator: heterogeneous, nodes: 12, pods: 24}
  - {op: measure}
"""
    tpu = run_yaml(text, "tpu")[0]
    cpu = run_yaml(text, "cpu")[0]
    assert tpu.scheduled == cpu.scheduled
    assert tpu.unschedulable == cpu.unschedulable


def test_churn_workload_keeps_scheduling_replacements():
    from kubernetes_tpu.bench.harness import run_churn_workload
    from kubernetes_tpu.bench.workloads import basic

    snap = basic(16, 32, seed=3)
    out = run_churn_workload("t", snap, rounds=3, churn_fraction=0.25, mode="cpu")
    # initial 32 + 3 rounds of replacements all found homes
    assert out.scheduled > 32 and out.unschedulable == 0
    assert out.pods_per_sec > 0


def test_batch_mode_reports_per_pod_latency_distribution():
    """Batch (tpu) mode must report a REAL per-pod latency distribution
    derived from commit ordinals — not one wave wall repeated three times
    (round-3 verdict missing #5).  With >= 2 pods scheduled sequentially,
    p50 < p99 strictly (later commit ordinals → later estimated
    availability)."""
    text = """
name: T
ops:
  - {op: createCluster, generator: basic, nodes: 20, pods: 60}
  - {op: measure}
"""
    out = run_yaml(text, "tpu")[0]
    assert out.latency_source == "per-pod-estimate", out
    assert out.scheduled == 60
    assert 0 < out.p50_ms < out.p90_ms <= out.p99_ms, out
    assert out.latency_mode == "closed-loop"


def test_perfdata_batch_walls_get_the_batch_latency_mode():
    """Satellite: an artifact whose only latency source is the per-wave
    batch wall (p50==p99 degenerate) is labeled latency_mode="batch", so
    bench/regression.py never gates a batch wall against a real closed-
    or open-loop latency distribution; estimate-backed runs keep
    "closed-loop"."""
    from kubernetes_tpu.bench.harness import _perfdata
    from kubernetes_tpu.bench.workloads import basic
    from kubernetes_tpu.scheduler.metrics import Metrics

    class _Events:
        def by_reason(self, reason):
            return []

    class _Sched:
        def __init__(self):
            self.metrics = Metrics()
            self.events = _Events()

    snap = basic(2, 2, seed=0)
    batch_only = _Sched()
    batch_only.metrics.observe("batch_scheduling_duration_seconds", 0.01)
    out = _perfdata("t", snap, batch_only, n_pods=2, wall=0.1)
    assert out.latency_source == "batch"
    assert out.latency_mode == "batch"

    estimated = _Sched()
    estimated.metrics.observe(
        "scheduling_attempt_duration_estimate_seconds", 0.01)
    out2 = _perfdata("t", snap, estimated, n_pods=2, wall=0.1)
    assert out2.latency_source == "per-pod-estimate"
    assert out2.latency_mode == "closed-loop"
