"""Test env: virtual 8-device CPU platform.

Mirrors the reference's determinism-first test posture (SURVEY.md §5 race
detection: CPU sim mode for deterministic tests); sharding tests get a real
8-device mesh without TPU hardware.

The sitecustomize workaround (env vars + post-import jax.config.update) lives
in __graft_entry__.force_cpu_platform, shared with the driver's multi-chip
dry run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

import jax  # noqa: E402

assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8, (
    "CPU sim platform not active — jax backend was initialized before "
    f"conftest ran (platform={jax.devices()[0].platform}, n={len(jax.devices())})"
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running chaos storms / full-scale runs (tier-1 runs "
        "-m 'not slow')",
    )


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    """The 8-device node-axis mesh over the forced CPU platform above — the
    tier-1-safe multichip fixture: every sharded parity test (test_sharded,
    test_sharded_routed) runs on ordinary CPU CI, no TPU required."""
    from kubernetes_tpu.parallel import make_mesh

    assert len(jax.devices()) >= 8, "conftest forces 8 virtual CPU devices"
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh2x4():
    """The 2-D (2 pods x 4 nodes) grid over the same 8 virtual devices —
    the tier-1-safe fixture for the pod-axis sharding tests
    (test_mesh_2d): resident pod-scaling buffers live split across the
    pods axis, kernels entry-gather them (ops/assign.py pod_unshard)."""
    from kubernetes_tpu.parallel import make_mesh

    assert len(jax.devices()) >= 8, "conftest forces 8 virtual CPU devices"
    return make_mesh(shape=(2, 4))
