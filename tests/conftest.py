"""Test env: virtual 8-device CPU platform.

Mirrors the reference's determinism-first test posture (SURVEY.md §5 race
detection: CPU sim mode for deterministic tests); sharding tests get a real
8-device mesh without TPU hardware.

Note: this machine's sitecustomize registers the axon TPU PJRT plugin and
overwrites jax.config.jax_platforms at interpreter start, so setting the
JAX_PLATFORMS env var is not enough — the config must be re-overridden after
jax import (before any backend initialization).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
