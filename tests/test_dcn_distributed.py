"""DCN multi-host smoke: two REAL processes join via jax.distributed.initialize
(the reference's multi-host story is plain gRPC between components; ours is the
jax distributed runtime carrying XLA collectives across hosts — SURVEY.md §2.4),
build one global mesh over both processes' CPU-sim devices, run the FULL
sharded scheduling step, and require decisions identical to the dense
single-process path.  Skips when the runtime can't form a multiprocess CPU
cluster (e.g. no cross-process collectives support in the installed jaxlib)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import sys
    rank, port, shape = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    sys.path.insert(0, {repo!r})
    from __graft_entry__ import force_cpu_platform
    force_cpu_platform(4)  # 4 local CPU devices per process -> 8 global
    import jax
    import numpy as np
    from kubernetes_tpu.parallel.mesh import init_distributed, global_arrays
    mesh_shape = tuple(int(v) for v in shape.split("x")) if "x" in shape else None
    mesh = init_distributed(f"127.0.0.1:{{port}}", 2, rank, mesh_shape=mesh_shape)
    assert len(jax.devices()) == 8, jax.devices()
    assert jax.process_count() == 2
    if mesh_shape is not None:
        assert tuple(mesh.shape.values()) == mesh_shape, dict(mesh.shape)
    from kubernetes_tpu.bench import workloads
    from kubernetes_tpu.api.snapshot import encode_snapshot
    from kubernetes_tpu.ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config
    from kubernetes_tpu.ops import schedule_batch
    from kubernetes_tpu.parallel.sharded import sharded_schedule_batch
    snap = workloads.spread_affinity(8, 16, seed=3)
    arr, meta = encode_snapshot(snap, bucket=False)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    dense = np.asarray(schedule_batch(arr, cfg)[0])  # local single-device oracle
    garr = global_arrays(mesh, arr)
    choices, _used = sharded_schedule_batch(garr, cfg, mesh)
    got = np.asarray(jax.device_get(choices))
    assert np.array_equal(got, dense), (got.tolist(), dense.tolist())
    print(f"RANK{{rank}} OK", flush=True)
    """
).format(repo=REPO)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("shape", ["1d", "2x4"])
def test_two_process_distributed_step_matches_dense(shape):
    """1d: the legacy node-axis mesh over both processes.  2x4: the 2-D
    pods x nodes grid spanning the DCN boundary — the pod axis falls across
    the two processes (2 pod rows x 4 node columns over 2x4 local devices),
    so the entry pod-gather is a REAL cross-process collective."""
    port = _free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(rank), str(port), shape],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=REPO,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers hung")
    joined = "\n---\n".join(outs)
    if any(p.returncode != 0 for p in procs):
        lowered = joined.lower()
        if (
            "multiprocess computations aren't implemented" in lowered
            # older jaxlibs word the same capability gap differently
            or (
                "distributed" in lowered
                and ("unimplemented" in lowered or "not supported" in lowered)
            )
        ):
            pytest.skip(f"multiprocess CPU collectives unavailable: {joined[-500:]}")
        pytest.fail(joined[-4000:])
    assert "RANK0 OK" in joined and "RANK1 OK" in joined, joined[-2000:]
