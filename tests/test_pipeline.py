"""Host↔device double-buffered pipelining (the PP analog, SURVEY §2.4)."""

import numpy as np

from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.parallel.pipeline import PipelinedRunner, run_serial
from helpers import mk_node, mk_pod


def _wave(seed: int, n_nodes: int = 12, n_pods: int = 24) -> Snapshot:
    rng = np.random.default_rng(seed)
    nodes = [
        mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
        for i in range(n_nodes)
    ]
    pods = [
        mk_pod(f"w{seed}-p{j}", cpu=int(rng.integers(100, 1500)))
        for j in range(n_pods)
    ]
    return Snapshot(nodes=nodes, pending_pods=pods)


def test_pipelined_results_identical_to_serial():
    waves = [_wave(s) for s in range(5)]
    pipelined = list(PipelinedRunner().run(waves))
    serial = list(run_serial(waves))
    assert pipelined == serial
    assert len(pipelined) == 5
    # every wave actually placed pods
    for verdicts in pipelined:
        assert sum(1 for v in verdicts.values() if v) > 0


def test_pipeline_handles_empty_and_single_streams():
    assert list(PipelinedRunner().run([])) == []
    [only] = list(PipelinedRunner().run([_wave(7)]))
    assert dict(only) == list(run_serial([_wave(7)]))[0]


def test_pipeline_preserves_wave_order():
    waves = [_wave(s, n_pods=8) for s in range(4)]
    out = list(PipelinedRunner().run(waves))
    for s, verdicts in enumerate(out):
        assert all(name.startswith(f"w{s}-") for name in verdicts)


def test_streaming_workload_harness_reports_gain_fields():
    from kubernetes_tpu.bench.harness import run_streaming_workload

    waves = [_wave(s, n_nodes=6, n_pods=10) for s in range(3)]
    out = run_streaming_workload("t", waves, warmup=False)
    assert out["waves"] == 3 and out["n_pods"] == 30
    assert out["pipelined_s"] > 0 and out["serial_s"] > 0
