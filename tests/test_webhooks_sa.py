"""Admission webhooks (the HTTP boundary member of the chain) and the
ServiceAccount + token controller with RBAC ServiceAccount subjects."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler.admission import AdmissionDenied, WebhookConfig
from kubernetes_tpu.scheduler.apiserver import APIServer, Forbidden
from kubernetes_tpu.scheduler.auth import TokenAuthenticator, bind_cluster_role
from kubernetes_tpu.scheduler.controllers import ServiceAccountController
from kubernetes_tpu.scheduler.store import ClusterStore


class _WebhookHandler(BaseHTTPRequestHandler):
    """Mutating endpoint /label: adds a label.  Validating endpoint /deny-big:
    rejects pods requesting >4000 cpu."""

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        obj = body["request"]["object"]
        if self.path == "/label":
            obj.setdefault("labels", {})["injected"] = "yes"
            out = {"response": {"allowed": True, "object": obj}}
        elif self.path == "/deny-big":
            big = obj.get("requests", {}).get("cpu", 0) > 4000
            out = {"response": {"allowed": not big,
                                "message": "cpu request too large"}}
        else:
            out = {"response": {"allowed": False, "message": "bad path"}}
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture(scope="module")
def webhook_server():
    srv = HTTPServer(("127.0.0.1", 0), _WebhookHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _api(webhooks=()):
    store = ClusterStore()
    authn = TokenAuthenticator()
    authn.add_token("admin", "admin", groups=("system:masters",))
    return APIServer(store, authenticator=authn, webhooks=webhooks), store


def test_mutating_webhook_rewrites_object(webhook_server):
    api, store = _api((WebhookConfig(url=f"{webhook_server}/label",
                                     mutating=True, kinds=("Pod",)),))
    api.handle("admin", "create", "Pod", t.Pod(name="p"))
    assert store.pods["default/p"].labels["injected"] == "yes"
    # non-matching kind untouched
    api.handle("admin", "create", "Node", t.Node(name="n"))
    assert "injected" not in store.nodes["n"].labels


def test_validating_webhook_rejects(webhook_server):
    api, store = _api((WebhookConfig(url=f"{webhook_server}/deny-big",
                                     kinds=("Pod",)),))
    api.handle("admin", "create", "Pod", t.Pod(name="ok", requests={"cpu": 100}))
    with pytest.raises(AdmissionDenied, match="too large"):
        api.handle("admin", "create", "Pod",
                   t.Pod(name="big", requests={"cpu": 9000}))
    assert "default/big" not in store.pods


def test_webhook_failure_policy():
    down = "http://127.0.0.1:9/x"
    api, _ = _api((WebhookConfig(url=down, kinds=("Pod",)),))
    with pytest.raises(AdmissionDenied):  # Fail (default)
        api.handle("admin", "create", "Pod", t.Pod(name="p"))
    api2, store2 = _api((WebhookConfig(url=down, kinds=("Pod",),
                                       failure_policy="Ignore"),))
    api2.handle("admin", "create", "Pod", t.Pod(name="p"))
    assert "default/p" in store2.pods


# ------------------------------------------------------- ServiceAccounts


def test_default_serviceaccount_and_token_minting():
    store = ClusterStore()
    authn = TokenAuthenticator()
    store.add_object("Namespace", c.Namespace(name="team-a"))
    ctrl = ServiceAccountController(store, authn)
    ctrl.tick()
    ctrl.tick()  # minting is a second pass over created SAs
    sas = {sa.key: sa for sa in store.list_objects("ServiceAccount")}
    assert "default/default" in sas and "team-a/default" in sas
    sa = sas["team-a/default"]
    assert sa.token
    user = authn.authenticate(sa.token)
    assert user.name == "system:serviceaccount:team-a:default"
    assert "system:serviceaccounts:team-a" in user.groups
    # idempotent: no re-mint
    before = sa.token
    ctrl.tick()
    assert store.get_object("ServiceAccount", "team-a/default").token == before


def test_serviceaccount_rbac_subject():
    store = ClusterStore()
    authn = TokenAuthenticator()
    ctrl = ServiceAccountController(store, authn)
    ctrl.tick()
    ctrl.tick()
    sa = store.get_object("ServiceAccount", "default/default")
    store.add_object(
        "Role",
        c.Role(name="pod-reader",
               rules=(c.PolicyRule(verbs=("get", "list"), resources=("pods",)),)),
    )
    store.add_object(
        "RoleBinding",
        c.RoleBinding(name="sa-read", role_name="pod-reader",
                      subjects=(c.Subject("ServiceAccount", "default:default"),)),
    )
    api = APIServer(store, authenticator=authn)
    assert api.handle(sa.token, "list", "Pod") == []
    with pytest.raises(Forbidden):
        api.handle(sa.token, "delete", "Pod", namespace="default", name="x")


def test_malformed_mutation_honors_failure_policy():
    """A webhook returning a garbage object is a webhook failure: Fail ->
    AdmissionDenied (not a raw DecodeError), Ignore -> original object kept."""
    class BadHandler(BaseHTTPRequestHandler):
        def do_POST(self):
            d = json.dumps({"response": {"allowed": True,
                                         "object": {"bogus": 1}}}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(d)))
            self.end_headers()
            self.wfile.write(d)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), BadHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}/m"
    api, _ = _api((WebhookConfig(url=url, mutating=True, kinds=("Pod",)),))
    with pytest.raises(AdmissionDenied, match="bad mutated object"):
        api.handle("admin", "create", "Pod", t.Pod(name="p"))
    api2, store2 = _api((WebhookConfig(url=url, mutating=True, kinds=("Pod",),
                                       failure_policy="Ignore"),))
    api2.handle("admin", "create", "Pod", t.Pod(name="p", labels={"keep": "me"}))
    assert store2.pods["default/p"].labels == {"keep": "me"}
    srv.shutdown()


def test_controller_manager_wires_sa_tokens():
    """The production wiring: ControllerManager(authenticator=...) mints
    tokens that actually authenticate."""
    store = ClusterStore()
    authn = TokenAuthenticator()
    from kubernetes_tpu.scheduler.controllers import ControllerManager

    ControllerManager(store, authenticator=authn).tick()
    sa = store.get_object("ServiceAccount", "default/default")
    assert authn.authenticate(sa.token).name == sa.username


def test_sa_token_revoked_on_deletion_and_fresh_on_recreate():
    store = ClusterStore()
    authn = TokenAuthenticator()
    ctrl = ServiceAccountController(store, authn)
    ctrl.tick()
    old = store.get_object("ServiceAccount", "default/default").token
    assert authn.authenticate(old) is not None
    store.delete_object("ServiceAccount", "default/default")
    ctrl.tick()  # recreates default SA, revokes the old credential
    assert authn.authenticate(old) is None
    new = store.get_object("ServiceAccount", "default/default").token
    assert new and new != old
    assert authn.authenticate(new) is not None


def test_missing_response_envelope_honors_failure_policy():
    class NoEnvelope(BaseHTTPRequestHandler):
        def do_POST(self):
            d = json.dumps({"allowed": True}).encode()  # missing "response"
            self.send_response(200)
            self.send_header("Content-Length", str(len(d)))
            self.end_headers()
            self.wfile.write(d)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), NoEnvelope)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}/v"
    api, _ = _api((WebhookConfig(url=url, kinds=("Pod",)),))
    with pytest.raises(AdmissionDenied, match="malformed"):
        api.handle("admin", "create", "Pod", t.Pod(name="p"))
    api2, store2 = _api((WebhookConfig(url=url, kinds=("Pod",),
                                       failure_policy="Ignore"),))
    api2.handle("admin", "create", "Pod", t.Pod(name="p"))
    assert "default/p" in store2.pods  # fail-open
    srv.shutdown()


def test_sa_recreated_between_ticks_revokes_old_token():
    """Delete + recreate in ONE controller interval: the predecessor's
    credential must still be revoked (identity checked by live token, not
    name presence)."""
    store = ClusterStore()
    authn = TokenAuthenticator()
    ctrl = ServiceAccountController(store, authn)
    ctrl.tick()
    old = store.get_object("ServiceAccount", "default/default").token
    store.delete_object("ServiceAccount", "default/default")
    store.add_object("ServiceAccount", c.ServiceAccount(name="default"))
    ctrl.tick()
    assert authn.authenticate(old) is None
    new = store.get_object("ServiceAccount", "default/default").token
    assert new != old and authn.authenticate(new) is not None
