"""Native C++ engine: decision parity with the jitted kernels and the oracle."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.snapshot import encode_snapshot
from kubernetes_tpu.native import schedule_batch_native, schedule_with_gangs_native
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config, schedule_batch
from kubernetes_tpu.ops.gang import schedule_with_gangs
from helpers import mk_node, mk_pod, random_cluster


@pytest.mark.parametrize("seed", range(6))
def test_native_matches_kernel(seed):
    rng = random.Random(4000 + seed)
    snap = random_cluster(rng, n_nodes=17, n_pods=43, with_taints=True,
                          with_selectors=True, with_pairwise=True)
    arr, _ = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want, want_used = schedule_batch(arr, cfg)
    got, got_used = schedule_batch_native(arr, cfg)
    np.testing.assert_array_equal(got, np.asarray(want))
    np.testing.assert_array_equal(got_used, np.asarray(want_used))


def test_native_medium_scale_matches_kernel():
    rng = random.Random(99)
    snap = random_cluster(rng, n_nodes=96, n_pods=400, with_taints=True,
                          with_selectors=True, with_pairwise=True)
    arr, _ = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want, _ = schedule_batch(arr, cfg)
    got, _ = schedule_batch_native(arr, cfg)
    np.testing.assert_array_equal(got, np.asarray(want))


def test_native_gang_matches_kernel_gang():
    pods = [mk_pod(f"g-{i}", cpu=600, pod_group="job") for i in range(3)]
    pods += [mk_pod(f"s-{i}", cpu=400, pod_group="small") for i in range(2)]
    from kubernetes_tpu.api.snapshot import Snapshot

    snap = Snapshot(nodes=[mk_node("n0", cpu=1000), mk_node("n1", cpu=1000)],
                    pending_pods=pods)
    arr, _ = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want, _ = schedule_with_gangs(arr, cfg)
    got, _ = schedule_with_gangs_native(arr, cfg)
    np.testing.assert_array_equal(got, want)
