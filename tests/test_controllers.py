"""Workload controllers + hollow kubelet + GC — the kube-controller-manager /
kubemark tier (reference: pkg/controller/replicaset — syncReplicaSet,
deployment rolling update, job_controller — syncJob, garbagecollector;
pkg/kubemark — hollow kubelet)."""

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.controllers import ControllerManager
from kubernetes_tpu.scheduler.kubelet import HollowCluster
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock

from helpers import mk_node, mk_pod


def mk_world(mode="tpu", n_nodes=3, cpu=4000):
    clock = FakeClock()
    store = ClusterStore()
    for i in range(n_nodes):
        store.add_node(mk_node(f"n{i}", cpu=cpu))
    sched = Scheduler(store, SchedulerConfiguration(mode=mode), clock=clock)
    cm = ControllerManager(store)
    leases = LeaseStore(clock)
    hollow = HollowCluster(store, leases)
    return clock, store, sched, cm, hollow


def converge(clock, sched, cm, hollow, rounds=10, dt=2.0):
    for _ in range(rounds):
        cm.tick()
        sched.run_until_idle()
        hollow.tick()
        clock.step(dt)


def rs_pods(store, rs_uid):
    return [
        p for p in store.pods.values()
        if any(r.uid == rs_uid for r in p.owner_references)
    ]


def test_replicaset_scales_up_and_down():
    clock, store, sched, cm, hollow = mk_world()
    rs = t.ReplicaSet(
        name="web", replicas=5,
        selector=t.LabelSelector.of(app="web"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "web"}),
    )
    store.add_workload("ReplicaSet", rs)
    converge(clock, sched, cm, hollow)
    pods = rs_pods(store, rs.uid)
    assert len(pods) == 5
    assert all(p.node_name for p in pods)  # all scheduled
    assert all(p.phase == t.PHASE_RUNNING for p in pods)  # kubelets ran them
    assert store.replicasets["default/web"].ready_replicas == 5
    # scale down to 2
    store.update_workload("ReplicaSet", t.ReplicaSet(
        name="web", replicas=2, selector=rs.selector, template=rs.template, uid=rs.uid,
    ))
    converge(clock, sched, cm, hollow)
    assert len(rs_pods(store, rs.uid)) == 2


def test_replicaset_replaces_deleted_pod():
    clock, store, sched, cm, hollow = mk_world()
    store.add_workload("ReplicaSet", t.ReplicaSet(
        name="web", replicas=3,
        selector=t.LabelSelector.of(app="web"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "web"}),
    ))
    converge(clock, sched, cm, hollow)
    victim = next(iter(p for p in store.pods.values() if p.owner_references))
    store.delete_pod(victim.uid)
    converge(clock, sched, cm, hollow)
    alive = [p for p in store.pods.values() if p.owner_references]
    assert len(alive) == 3
    assert all(p.node_name for p in alive)


def test_job_runs_to_completion():
    clock, store, sched, cm, hollow = mk_world()
    store.add_workload("Job", t.Job(
        name="batch", completions=6, parallelism=2,
        template=mk_pod("tmpl", cpu=100, labels={"app": "batch"}, run_seconds=1.0),
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    job = store.jobs["default/batch"]
    assert job.succeeded == 6
    assert job.complete
    done = [p for p in store.pods.values() if p.phase == t.PHASE_SUCCEEDED]
    assert len(done) == 6


def test_job_parallelism_cap():
    clock, store, sched, cm, hollow = mk_world()
    store.add_workload("Job", t.Job(
        name="batch", completions=8, parallelism=3,
        template=mk_pod("tmpl", cpu=100, run_seconds=5.0),
    ))
    cm.tick()
    active = [p for p in store.pods.values() if p.phase != t.PHASE_SUCCEEDED]
    assert len(active) == 3  # never more than parallelism in flight


def test_deployment_rollout_replaces_pods():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=4, max_surge=2, max_unavailable=1,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    v1_pods = [p for p in store.pods.values() if p.owner_references]
    assert len(v1_pods) == 4
    v1_rs = {rs.name for rs in store.replicasets.values()}
    assert len(v1_rs) == 1
    # roll out a new template (different resources -> different hash)
    store.update_workload("Deployment", t.Deployment(
        name="api", replicas=4, max_surge=2, max_unavailable=1,
        selector=d.selector,
        template=mk_pod("tmpl", cpu=200, labels={"app": "api"}),
        uid=d.uid,
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    # old RS drained and collected; 4 pods of the new template
    assert len(store.replicasets) == 1
    assert set(store.replicasets) != {f"default/{name}" for name in v1_rs}
    pods = [p for p in store.pods.values() if p.owner_references]
    assert len(pods) == 4
    assert all(p.requests[t.CPU] == 200 for p in pods)
    assert all(p.phase == t.PHASE_RUNNING for p in pods)


def test_gc_cascades_deployment_delete():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=3,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    assert len([p for p in store.pods.values() if p.owner_references]) == 3
    store.delete_workload("Deployment", d.key)
    converge(clock, sched, cm, hollow)
    assert not store.replicasets  # RS collected
    assert not [p for p in store.pods.values() if p.owner_references]  # pods too


def test_finished_pods_release_capacity():
    # one small node: a completed job pod must not block the next pod
    clock, store, sched, cm, hollow = mk_world(n_nodes=1, cpu=1000)
    store.add_workload("Job", t.Job(
        name="batch", completions=3, parallelism=1,
        template=mk_pod("tmpl", cpu=900, run_seconds=1.0),
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    assert store.jobs["default/batch"].succeeded == 3


def test_deployment_scale_down():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=4,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    assert len([p for p in store.pods.values() if p.owner_references]) == 4
    store.update_workload("Deployment", t.Deployment(
        name="api", replicas=2, selector=d.selector, template=d.template, uid=d.uid,
    ))
    converge(clock, sched, cm, hollow)
    assert len([p for p in store.pods.values() if p.owner_references]) == 2


def test_rollout_on_affinity_only_template_change():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=2,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    v1 = set(store.replicasets)
    aff = t.Affinity(required_node_terms=(t.NodeSelectorTerm(
        match_expressions=(t.NodeSelectorRequirement(
            key=t.LABEL_HOSTNAME, operator=t.OP_EXISTS),)),))
    store.update_workload("Deployment", t.Deployment(
        name="api", replicas=2, selector=d.selector,
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}, affinity=aff),
        uid=d.uid,
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    assert set(store.replicasets) != v1  # affinity-only change still rolls


def test_unschedulable_pod_wakes_when_bound_pod_completes():
    # AssignedPodDelete analog: a terminal phase releases capacity and must
    # requeue unschedulable waiters (scheduler._on_event ModifiedStatus path)
    clock, store, sched, cm, hollow = mk_world(n_nodes=1, cpu=1000)
    store.add_pod(mk_pod("runner", cpu=900, run_seconds=1.0))
    sched.run_until_idle()
    hollow.tick()  # runner: Pending -> Running
    store.add_pod(mk_pod("waiter", cpu=900))
    sched.run_until_idle()
    assert store.pods["default/waiter"].node_name == ""
    clock.step(30.0)
    hollow.tick()  # runner completes -> Succeeded (status write wakes waiter)
    assert store.pods["default/runner"].phase == t.PHASE_SUCCEEDED
    clock.step(30.0)  # clear waiter's backoff
    sched.run_until_idle()
    assert store.pods["default/waiter"].node_name == "n0"


def test_attach_detach_controller_reconciles_node_attachments():
    """AttachDetach: PVs used by bound pods appear in the node's
    volumes_attached; the last user leaving detaches; untouched nodes are
    not rewritten (identity-stable for the delta encoder)."""
    from kubernetes_tpu.scheduler.controllers import AttachDetachController

    store = ClusterStore()
    store.add_node(mk_node("n0"))
    store.add_node(mk_node("n1"))
    store.add_pv(t.PersistentVolume(name="pv-a", capacity=10,
                                    storage_class="std",
                                    claim_ref="default/claim-a"))
    store.add_pvc(t.PersistentVolumeClaim(name="claim-a", request=5,
                                          storage_class="std",
                                          volume_name="pv-a"))
    p = mk_pod("user", cpu=100, node_name="n0")
    p.pvcs = ("claim-a",)
    store.add_pod(p)
    ctrl = AttachDetachController(store)
    ctrl.tick()
    assert store.nodes["n0"].volumes_attached == ("pv-a",)
    assert store.nodes["n1"].volumes_attached == ()
    n1_obj = store.nodes["n1"]
    ctrl.tick()  # steady state: no node rewrites
    assert store.nodes["n1"] is n1_obj
    # the using pod finishes -> detach
    q = store.pods[p.uid]
    import copy
    q2 = copy.copy(q)
    q2.phase = t.PHASE_SUCCEEDED
    store.update_pod_status(q2)
    ctrl.tick()
    assert store.nodes["n0"].volumes_attached == ()


def test_resourceclaim_controller_lifecycle():
    """ResourceClaim: generated claim per pod template slot, reserved and
    allocated once bound, deleted when the owner finishes; standalone
    claims untouched."""
    from kubernetes_tpu.api import cluster as c
    from kubernetes_tpu.scheduler.controllers import ResourceClaimController

    store = ClusterStore()
    store.add_node(mk_node("n0"))
    store.add_object("ResourceClaim",
                     c.ResourceClaim(name="standalone", device_class="gpu"))
    p = mk_pod("dra", cpu=100)
    p.resource_claims = (t.ResourceClaimRef(device_class="gpu", count=2),)
    store.add_pod(p)
    ctrl = ResourceClaimController(store)
    ctrl.tick()
    claim = store.get_object("ResourceClaim", "default/dra-claim-0")
    assert claim is not None and claim.device_class == "gpu" and claim.count == 2
    assert not claim.allocated and claim.reserved_for == ()
    # pod binds -> reserved + allocated
    store.bind(p.uid, "n0")
    ctrl.tick()
    claim = store.get_object("ResourceClaim", "default/dra-claim-0")
    assert claim.allocated and claim.reserved_for == (p.uid,)
    # pod finishes -> generated claim GCed, standalone claim stays
    import copy
    q = copy.copy(store.pods[p.uid])
    q.phase = t.PHASE_SUCCEEDED
    store.update_pod_status(q)
    ctrl.tick()
    assert store.get_object("ResourceClaim", "default/dra-claim-0") is None
    assert store.get_object("ResourceClaim", "default/standalone") is not None


def test_certificates_controller_approves_signs_and_cleans():
    """Certificates: kubelet-serving CSRs from system:nodes auto-approve
    and get a certificate; foreign signers are denied; both age out after
    the cleaner TTL."""
    from kubernetes_tpu.api import cluster as c
    from kubernetes_tpu.scheduler.controllers import CertificatesController
    from kubernetes_tpu.scheduler.queue import FakeClock

    store = ClusterStore()
    clock = FakeClock()
    ctrl = CertificatesController(store, clock=clock)
    store.add_object("CertificateSigningRequest", c.CertificateSigningRequest(
        name="node-n0-serving", username="system:node:n0",
        groups=("system:nodes",)))
    store.add_object("CertificateSigningRequest", c.CertificateSigningRequest(
        name="rogue", username="mallory",
        signer_name="example.com/custom"))
    ctrl.tick()
    good = store.get_object("CertificateSigningRequest", "node-n0-serving")
    bad = store.get_object("CertificateSigningRequest", "rogue")
    assert good.status == "Approved" and "BEGIN CERTIFICATE" in good.certificate
    assert bad.status == "Denied" and not bad.certificate
    clock.step(CertificatesController.TTL_S + 1)
    ctrl.tick()
    assert store.get_object("CertificateSigningRequest", "node-n0-serving") is None
    assert store.get_object("CertificateSigningRequest", "rogue") is None


def test_expand_controller_resizes_bound_claims():
    """expand_controller.go: a bound claim whose request grew past its PV's
    capacity is resized iff the class allows expansion; shrink never."""
    from dataclasses import replace as dc_replace

    from kubernetes_tpu.api import cluster as c
    from kubernetes_tpu.scheduler.controllers import ExpandController

    store = ClusterStore()
    store.add_object("StorageClass", c.StorageClass(
        name="fast", provisioner="csi.x", allow_volume_expansion=True))
    store.add_object("StorageClass", c.StorageClass(
        name="rigid", provisioner="csi.x"))
    store.add_pv(t.PersistentVolume(name="pv-a", capacity=10,
                                    storage_class="fast",
                                    claim_ref="default/grow"))
    store.add_pv(t.PersistentVolume(name="pv-b", capacity=10,
                                    storage_class="rigid",
                                    claim_ref="default/stuck"))
    store.add_pvc(t.PersistentVolumeClaim(
        name="grow", request=25, storage_class="fast", volume_name="pv-a"))
    store.add_pvc(t.PersistentVolumeClaim(
        name="stuck", request=25, storage_class="rigid", volume_name="pv-b"))
    ctrl = ExpandController(store)
    ctrl.tick()
    assert store.pvs["pv-a"].capacity == 25  # expanded
    assert store.pvs["pv-b"].capacity == 10  # class forbids expansion
    # shrink request: never shrinks the volume
    store.update_pvc(dc_replace(
        store.pvcs["default/grow"], request=5))
    ctrl.tick()
    assert store.pvs["pv-a"].capacity == 25
