"""Workload controllers + hollow kubelet + GC — the kube-controller-manager /
kubemark tier (reference: pkg/controller/replicaset — syncReplicaSet,
deployment rolling update, job_controller — syncJob, garbagecollector;
pkg/kubemark — hollow kubelet)."""

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.controllers import ControllerManager
from kubernetes_tpu.scheduler.kubelet import HollowCluster
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock

from helpers import mk_node, mk_pod


def mk_world(mode="tpu", n_nodes=3, cpu=4000):
    clock = FakeClock()
    store = ClusterStore()
    for i in range(n_nodes):
        store.add_node(mk_node(f"n{i}", cpu=cpu))
    sched = Scheduler(store, SchedulerConfiguration(mode=mode), clock=clock)
    cm = ControllerManager(store)
    leases = LeaseStore(clock)
    hollow = HollowCluster(store, leases)
    return clock, store, sched, cm, hollow


def converge(clock, sched, cm, hollow, rounds=10, dt=2.0):
    for _ in range(rounds):
        cm.tick()
        sched.run_until_idle()
        hollow.tick()
        clock.step(dt)


def rs_pods(store, rs_uid):
    return [
        p for p in store.pods.values()
        if any(r.uid == rs_uid for r in p.owner_references)
    ]


def test_replicaset_scales_up_and_down():
    clock, store, sched, cm, hollow = mk_world()
    rs = t.ReplicaSet(
        name="web", replicas=5,
        selector=t.LabelSelector.of(app="web"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "web"}),
    )
    store.add_workload("ReplicaSet", rs)
    converge(clock, sched, cm, hollow)
    pods = rs_pods(store, rs.uid)
    assert len(pods) == 5
    assert all(p.node_name for p in pods)  # all scheduled
    assert all(p.phase == t.PHASE_RUNNING for p in pods)  # kubelets ran them
    assert store.replicasets["default/web"].ready_replicas == 5
    # scale down to 2
    store.update_workload("ReplicaSet", t.ReplicaSet(
        name="web", replicas=2, selector=rs.selector, template=rs.template, uid=rs.uid,
    ))
    converge(clock, sched, cm, hollow)
    assert len(rs_pods(store, rs.uid)) == 2


def test_replicaset_replaces_deleted_pod():
    clock, store, sched, cm, hollow = mk_world()
    store.add_workload("ReplicaSet", t.ReplicaSet(
        name="web", replicas=3,
        selector=t.LabelSelector.of(app="web"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "web"}),
    ))
    converge(clock, sched, cm, hollow)
    victim = next(iter(p for p in store.pods.values() if p.owner_references))
    store.delete_pod(victim.uid)
    converge(clock, sched, cm, hollow)
    alive = [p for p in store.pods.values() if p.owner_references]
    assert len(alive) == 3
    assert all(p.node_name for p in alive)


def test_job_runs_to_completion():
    clock, store, sched, cm, hollow = mk_world()
    store.add_workload("Job", t.Job(
        name="batch", completions=6, parallelism=2,
        template=mk_pod("tmpl", cpu=100, labels={"app": "batch"}, run_seconds=1.0),
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    job = store.jobs["default/batch"]
    assert job.succeeded == 6
    assert job.complete
    done = [p for p in store.pods.values() if p.phase == t.PHASE_SUCCEEDED]
    assert len(done) == 6


def test_job_parallelism_cap():
    clock, store, sched, cm, hollow = mk_world()
    store.add_workload("Job", t.Job(
        name="batch", completions=8, parallelism=3,
        template=mk_pod("tmpl", cpu=100, run_seconds=5.0),
    ))
    cm.tick()
    active = [p for p in store.pods.values() if p.phase != t.PHASE_SUCCEEDED]
    assert len(active) == 3  # never more than parallelism in flight


def test_deployment_rollout_replaces_pods():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=4, max_surge=2, max_unavailable=1,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    v1_pods = [p for p in store.pods.values() if p.owner_references]
    assert len(v1_pods) == 4
    v1_rs = {rs.name for rs in store.replicasets.values()}
    assert len(v1_rs) == 1
    # roll out a new template (different resources -> different hash)
    store.update_workload("Deployment", t.Deployment(
        name="api", replicas=4, max_surge=2, max_unavailable=1,
        selector=d.selector,
        template=mk_pod("tmpl", cpu=200, labels={"app": "api"}),
        uid=d.uid,
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    # old RS drained and collected; 4 pods of the new template
    assert len(store.replicasets) == 1
    assert set(store.replicasets) != {f"default/{name}" for name in v1_rs}
    pods = [p for p in store.pods.values() if p.owner_references]
    assert len(pods) == 4
    assert all(p.requests[t.CPU] == 200 for p in pods)
    assert all(p.phase == t.PHASE_RUNNING for p in pods)


def test_gc_cascades_deployment_delete():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=3,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    assert len([p for p in store.pods.values() if p.owner_references]) == 3
    store.delete_workload("Deployment", d.key)
    converge(clock, sched, cm, hollow)
    assert not store.replicasets  # RS collected
    assert not [p for p in store.pods.values() if p.owner_references]  # pods too


def test_finished_pods_release_capacity():
    # one small node: a completed job pod must not block the next pod
    clock, store, sched, cm, hollow = mk_world(n_nodes=1, cpu=1000)
    store.add_workload("Job", t.Job(
        name="batch", completions=3, parallelism=1,
        template=mk_pod("tmpl", cpu=900, run_seconds=1.0),
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    assert store.jobs["default/batch"].succeeded == 3


def test_deployment_scale_down():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=4,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    assert len([p for p in store.pods.values() if p.owner_references]) == 4
    store.update_workload("Deployment", t.Deployment(
        name="api", replicas=2, selector=d.selector, template=d.template, uid=d.uid,
    ))
    converge(clock, sched, cm, hollow)
    assert len([p for p in store.pods.values() if p.owner_references]) == 2


def test_rollout_on_affinity_only_template_change():
    clock, store, sched, cm, hollow = mk_world()
    d = t.Deployment(
        name="api", replicas=2,
        selector=t.LabelSelector.of(app="api"),
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}),
    )
    store.add_workload("Deployment", d)
    converge(clock, sched, cm, hollow)
    v1 = set(store.replicasets)
    aff = t.Affinity(required_node_terms=(t.NodeSelectorTerm(
        match_expressions=(t.NodeSelectorRequirement(
            key=t.LABEL_HOSTNAME, operator=t.OP_EXISTS),)),))
    store.update_workload("Deployment", t.Deployment(
        name="api", replicas=2, selector=d.selector,
        template=mk_pod("tmpl", cpu=100, labels={"app": "api"}, affinity=aff),
        uid=d.uid,
    ))
    converge(clock, sched, cm, hollow, rounds=20)
    assert set(store.replicasets) != v1  # affinity-only change still rolls


def test_unschedulable_pod_wakes_when_bound_pod_completes():
    # AssignedPodDelete analog: a terminal phase releases capacity and must
    # requeue unschedulable waiters (scheduler._on_event ModifiedStatus path)
    clock, store, sched, cm, hollow = mk_world(n_nodes=1, cpu=1000)
    store.add_pod(mk_pod("runner", cpu=900, run_seconds=1.0))
    sched.run_until_idle()
    hollow.tick()  # runner: Pending -> Running
    store.add_pod(mk_pod("waiter", cpu=900))
    sched.run_until_idle()
    assert store.pods["default/waiter"].node_name == ""
    clock.step(30.0)
    hollow.tick()  # runner completes -> Succeeded (status write wakes waiter)
    assert store.pods["default/runner"].phase == t.PHASE_SUCCEEDED
    clock.step(30.0)  # clear waiter's backoff
    sched.run_until_idle()
    assert store.pods["default/waiter"].node_name == "n0"
