"""2-D pods x nodes mesh (ISSUE 20): the pod axis sharded too.

Parity matrix: {chunked, rounds, incremental} x {donate on/off} on the
(2, 4) grid over the conftest-forced 8-device CPU platform, decisions
bit-identical to BOTH the single-device serial oracle AND the 1-D mesh8
route — the 2-D grid is a pure residency/HBM win, never a decision change.
Packed mask planes ride armed (their tier-1 default), so the bit-planes'
pod-axis padding and entry gather are exercised, not just the dense forms.

Plus the landability gates on the 2-D grid: pad_pods semantics, the
KTPU_MESH request grammar, a seeded chaos storm with KTPU_MESH=2x4 armed,
and a kill.post_assume crash-restart."""

import os

import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api.snapshot import encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops import bitplane
from kubernetes_tpu.ops.assign import (
    TRACE_COUNTS,
    schedule_batch_ordinals_routed,
    schedule_batch_routed,
)
from kubernetes_tpu.parallel.mesh import NODE_AXIS, PODS_AXIS

from test_sharded_routed import _chunked_snap, _rounds_snap


@pytest.fixture(autouse=True)
def _force_production_route(monkeypatch):
    """Chunked/rounds route on the CPU sim, same as test_sharded_routed."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")


def _parity_2d(mesh2x4, mesh8, snap, bucket, cfg=None, donate=False,
               route=None):
    """Serial oracle vs 1-D mesh8 vs 2-D (2, 4): all three bit-identical,
    with a strict TRACE_COUNTS proof that the 2-D run really compiled the
    claimed sharded route."""
    arr, meta = encode_snapshot(snap, bucket=bucket)
    cfg = cfg if cfg is not None else infer_score_config(
        arr, DEFAULT_SCORE_CONFIG)
    n = arr.N
    if route is not None:
        import jax

        jax.clear_caches()
    want, want_used = schedule_batch_routed(arr, cfg, donate=False)
    got_1d, _ = schedule_batch_routed(arr, cfg, donate=donate, mesh=mesh8)
    before = dict(TRACE_COUNTS)
    got_2d, got_used = schedule_batch_routed(
        arr, cfg, donate=donate, mesh=mesh2x4)
    np.testing.assert_array_equal(np.asarray(got_2d), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_2d), np.asarray(got_1d))
    gu = np.asarray(got_used)
    np.testing.assert_array_equal(gu[:n], np.asarray(want_used))
    assert not gu[n:].any()
    if route is not None:
        assert TRACE_COUNTS[route] > before[route], (before, TRACE_COUNTS)
    return arr, meta, cfg


@pytest.mark.parametrize("donate", [False, True])
def test_2d_chunked_parity(mesh2x4, mesh8, donate, monkeypatch):
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    assert bitplane.PACK_MASKS, "packed plane must ride armed on the grid"
    snap, bucket = _chunked_snap(False)  # N=27: node-axis padding too
    _parity_2d(mesh2x4, mesh8, snap, bucket, donate=donate,
               route="sharded_chunked")


@pytest.mark.parametrize("donate", [False, True])
def test_2d_rounds_parity(mesh2x4, mesh8, donate, monkeypatch):
    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap, bucket = _rounds_snap(False)
    _parity_2d(mesh2x4, mesh8, snap, bucket, cfg=DEFAULT_SCORE_CONFIG,
               donate=donate, route="sharded_rounds")


@pytest.mark.parametrize("donate", [False, True])
def test_2d_incremental_parity(mesh2x4, mesh8, donate, monkeypatch):
    """The warm-cycle incremental route on the 2-D grid: the hoist cache
    built against the 2-D mesh (inc.cls pod-sharded, inc.req_u replicated)
    schedules bit-identical to the serial inc oracle and the 1-D inc run."""
    from kubernetes_tpu.bench.workloads import heterogeneous
    from kubernetes_tpu.ops.incremental import HoistCache

    if donate:
        monkeypatch.setenv("KTPU_DONATE", "1")
    snap = heterogeneous(48, 256, seed=3)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    inc_ser = HoistCache(mesh=None).ensure(arr, meta, cfg)
    assert inc_ser is not None, "workload must be inc-applicable"
    want, _ = schedule_batch_routed(arr, cfg, donate=False, inc=inc_ser)
    inc_1d = HoistCache(mesh=mesh8).ensure(arr, meta, cfg)
    got_1d, _ = schedule_batch_routed(
        arr, cfg, donate=donate, mesh=mesh8, inc=inc_1d)
    inc_2d = HoistCache(mesh=mesh2x4).ensure(arr, meta, cfg)
    before = dict(TRACE_COUNTS)
    got_2d, _ = schedule_batch_routed(
        arr, cfg, donate=donate, mesh=mesh2x4, inc=inc_2d)
    np.testing.assert_array_equal(np.asarray(got_2d), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_2d), np.asarray(got_1d))
    assert (
        TRACE_COUNTS["sharded_chunked_inc"] > before["sharded_chunked_inc"]
        or TRACE_COUNTS["sharded_rounds_inc"] > before["sharded_rounds_inc"]
    ), (before, TRACE_COUNTS)


def test_2d_ordinals_parity(mesh2x4):
    """The ordinal-reporting scheduler-batch variant on the 2-D grid:
    choices, per-pod commit ordinals and total sweeps all match."""
    snap, bucket = _rounds_snap(True)
    arr, _ = encode_snapshot(snap, bucket=bucket)
    want_c, _, want_o, want_s = schedule_batch_ordinals_routed(
        arr, DEFAULT_SCORE_CONFIG, donate=False)
    got_c, _, got_o, got_s = schedule_batch_ordinals_routed(
        arr, DEFAULT_SCORE_CONFIG, donate=False, mesh=mesh2x4)
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))
    np.testing.assert_array_equal(np.asarray(got_o), np.asarray(want_o))
    assert int(got_s) == int(want_s)


def test_2d_pod_padding_parity(mesh2x4):
    """A pod count NOT divisible by the pod-shard count: the routed wrapper
    pod-pads before dispatch and slices the outputs back to the caller's P
    — decisions over the real pods bit-identical to the serial oracle."""
    import random

    from helpers import random_cluster

    rng = random.Random(77)
    snap = random_cluster(rng, n_nodes=24, n_pods=51)  # 51 odd: pod-pads
    arr, _ = encode_snapshot(snap, bucket=False)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want, _ = schedule_batch_routed(arr, cfg, donate=False)
    got, _ = schedule_batch_routed(arr, cfg, donate=False, mesh=mesh2x4)
    got = np.asarray(got)
    assert got.shape == np.asarray(want).shape  # sliced back, not padded
    np.testing.assert_array_equal(got, np.asarray(want))


def test_pad_pods_semantics():
    """pad_pods adds permanently invalid pods: pod_valid False on the tail
    (the master gate), zero requests — and is a no-op when divisible."""
    from kubernetes_tpu.parallel.mesh import pad_pods

    snap, _ = _rounds_snap(False)  # 48 pods
    arr, _ = encode_snapshot(snap, bucket=False)
    assert arr.P == 48
    same, p0 = pad_pods(arr, 2)
    assert same is arr and p0 == 48  # divisible: untouched
    padded, p0 = pad_pods(arr, 5)
    assert p0 == 48 and padded.P == 50
    assert not padded.pod_valid[48:].any()
    assert not padded.pod_req[48:].any()
    np.testing.assert_array_equal(padded.pod_req[:48], arr.pod_req)


def test_parse_mesh_request_grammar(monkeypatch):
    """The KTPU_MESH / KTPU_MESH_PODS / KTPU_MESH_NODES request grammar —
    jax-free (bench.py sizes the virtual platform with it pre-backend)."""
    from kubernetes_tpu.parallel.mesh import (
        mesh_request_devices,
        parse_mesh_request,
    )

    cases = [
        # (KTPU_MESH, KTPU_MESH_PODS, KTPU_MESH_NODES) -> expected
        ((None, None, None), None),
        (("8", None, None), 8),
        (("2x4", None, None), (2, 4)),
        (("1x4", None, None), 4),       # degenerate pod axis: plain 1-D
        ((None, "2", "4"), (2, 4)),
        (("8", "2", None), (2, 4)),     # pods divides the total
        ((None, "2", None), (2, 1)),    # pods alone: pod-only grid
        ((None, "1", None), None),      # degenerate pods alone: no mesh
        (("8", "1", None), 8),          # degenerate pods + total: 1-D
        (("2x4", "1", None), (2, 4)),   # explicit 2-D string still wins
        (("1", None, None), None),
        (("0", None, None), None),
    ]
    for (m, p, n), want in cases:
        for k, v in (("KTPU_MESH", m), ("KTPU_MESH_PODS", p),
                     ("KTPU_MESH_NODES", n)):
            if v is None:
                monkeypatch.delenv(k, raising=False)
            else:
                monkeypatch.setenv(k, v)
        assert parse_mesh_request() == want, (m, p, n)
    assert mesh_request_devices(None) == 1
    assert mesh_request_devices(8) == 8
    assert mesh_request_devices((2, 4)) == 8
    for m, p in [("banana", None), ("-3", None), ("2x4x2", None),
                 ("8", "3"), ("3x0", None)]:
        monkeypatch.setenv("KTPU_MESH", m)
        if p is None:
            monkeypatch.delenv("KTPU_MESH_PODS", raising=False)
        else:
            monkeypatch.setenv("KTPU_MESH_PODS", p)
        monkeypatch.delenv("KTPU_MESH_NODES", raising=False)
        with pytest.raises(ValueError):
            parse_mesh_request()


def test_pipelined_loop_with_2d_mesh_matches_serial(mesh2x4):
    """The double-buffered loop against the 2-D grid: verdicts
    bit-identical to the unsharded serial oracle, and the resident
    pod-scaling buffers really live SPLIT across the pods axis (the HBM
    win is residency, not a transient)."""
    from kubernetes_tpu.api.snapshot import Snapshot
    from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop, run_serial
    from helpers import mk_node, mk_pod

    def wave(seed):
        rng = np.random.default_rng(seed)
        return Snapshot(
            nodes=[mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
                   for i in range(10)],
            pending_pods=[mk_pod(f"w{seed}-p{j}",
                                 cpu=int(rng.integers(100, 1500)))
                          for j in range(16)],
        )

    waves = [wave(s) for s in range(4)]
    oracle = list(run_serial(waves))
    loop = PipelinedBatchLoop(depth=1, mesh=mesh2x4)
    got = list(loop.run(waves))
    assert got == oracle
    assert loop.enc._dev, "resident device buffers should exist"
    specs = {
        name: ent[1].sharding.spec for name, ent in loop.enc._dev.items()
    }
    assert PODS_AXIS in (specs["pod_req"] or ()), specs["pod_req"]
    assert NODE_AXIS in (specs["node_labels"] or ()), specs["node_labels"]


def test_chaos_storm_with_2d_mesh(monkeypatch):
    """Seeded chaos storm through the Scheduler batch path with the 2-D
    grid armed (KTPU_MESH=2x4): placements bit-identical to the fault-free,
    UNSHARDED serial oracle."""
    from test_chaos import _churn_run

    monkeypatch.delenv("KTPU_MESH", raising=False)
    monkeypatch.delenv("KTPU_FORCE_CHUNKED", raising=False)
    oracle, _ = _churn_run(pipeline=False)
    monkeypatch.setenv("KTPU_MESH", "2x4")
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    import jax

    jax.clear_caches()  # strict route proof: the storm must RE-compile
    before = dict(TRACE_COUNTS)
    got, sched = _churn_run(
        pipeline=True,
        plan=chaos.FaultPlan.from_seed(
            20, sites=("scheduler.step", "host.stall"), n_faults=4
        ),
    )
    assert got == oracle
    assert sched.mesh is not None
    assert dict(sched.mesh.shape) == {PODS_AXIS: 2, NODE_AXIS: 4}
    assert (
        TRACE_COUNTS["sharded_rounds"] > before["sharded_rounds"]
        or TRACE_COUNTS["sharded_rounds_inc"] > before["sharded_rounds_inc"]
    ), (before, TRACE_COUNTS)


def test_kill_post_assume_crash_restart_on_2d_mesh(tmp_path, monkeypatch):
    """kill -9 at post-assume/pre-checkpoint with the 2-D grid armed: the
    restarted incarnation rebuilds the pod-sharded resident buffers from
    the checkpoint + LIST and finishes bit-identical to the fault-free
    oracle — sharded residency is never trusted across the kill."""
    from test_crash_restart import _run

    monkeypatch.delenv("KTPU_MESH", raising=False)
    oracle, _, _ = _run(pipeline=False)
    monkeypatch.setenv("KTPU_MESH", "2x4")
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    got, sched, restarts = _run(
        chaos.FaultPlan.parse("kill.post_assume:kill@0"), ckpt_dir=tmp_path,
    )
    assert restarts >= 1
    assert got == oracle
    assert all(v for v in got.values())  # zero lost pods
    assert sched.metrics.counters["scheduler_restarts_total"] >= 1
