"""Failure detection / HA / checkpoint: node lifecycle, leader election,
assumed-pod checkpoint, crash-only recovery (SURVEY.md §5)."""

import os

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.checkpoint import CheckpointManager, load_assumed, save_assumed
from kubernetes_tpu.scheduler.leases import (
    LeaderElector,
    LeaseStore,
    NodeLifecycleController,
    UNREACHABLE_TAINT_KEY,
)
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import mk_node, mk_pod


def test_stale_lease_taints_then_evicts():
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    leases = LeaseStore(clock)
    ctl = NodeLifecycleController(store, leases, grace_s=40, eviction_s=300)
    leases.renew_node_heartbeat("n0")
    store.add_pod(mk_pod("p", node_name="n0"))

    clock.step(10)
    assert ctl.tick() == []
    assert store.nodes["n0"].taints == ()
    # heartbeat stops; grace passes
    clock.step(50)
    assert ctl.tick() == []  # tainted, not yet evicted
    assert any(tn.key == UNREACHABLE_TAINT_KEY for tn in store.nodes["n0"].taints)
    clock.step(299)
    assert ctl.tick() == []
    clock.step(2)
    assert ctl.tick() == ["default/p"]
    assert "default/p" not in store.pods


def test_toleration_seconds_respected_and_recovery_untaints():
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    leases = LeaseStore(clock)
    ctl = NodeLifecycleController(store, leases)
    tol = (t.Toleration(key=UNREACHABLE_TAINT_KEY, operator=t.OP_EXISTS,
                        effect=t.NO_EXECUTE, toleration_seconds=30),)
    store.add_pod(mk_pod("tolerant", node_name="n0", tolerations=tol))
    clock.step(100)  # no heartbeat at all
    ctl.tick()
    clock.step(20)
    assert ctl.tick() == []  # within 30s window
    # node comes back: taint removed, pod survives
    leases.renew_node_heartbeat("n0")
    assert ctl.tick() == []
    assert store.nodes["n0"].taints == ()
    clock.step(1000)
    leases.renew_node_heartbeat("n0")
    assert ctl.tick() == []


def test_scheduler_avoids_unreachable_node():
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("dead"))
    store.add_node(mk_node("alive"))
    leases = LeaseStore(clock)
    ctl = NodeLifecycleController(store, leases)
    clock.step(100)
    leases.renew_node_heartbeat("alive")  # alive keeps heartbeating; dead doesn't
    ctl.tick()  # "dead" gets the NoExecute taint
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"), clock=clock)
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    assert store.pods["default/p"].node_name == "alive"


def test_leader_election_single_active_and_failover():
    clock = FakeClock()
    leases = LeaseStore(clock)
    a = LeaderElector(leases, "sched-a")
    b = LeaderElector(leases, "sched-b")
    assert a.tick() and not b.tick()
    # a renews within the deadline: b stays passive
    clock.step(10)
    assert a.tick() and not b.tick()
    # a dies; lease expires after 15 s -> b takes over
    clock.step(16)
    assert b.tick()
    assert b.is_leader and not a.is_leader


def test_checkpoint_roundtrip_and_corruption(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    save_assumed(cm, {"default/p": "n0"})
    assert load_assumed(cm) == {"default/p": "n0"}
    # corruption -> discarded, crash-only rebuild
    path = os.path.join(str(tmp_path), "assumed_pods.json")
    with open(path, "w") as f:
        f.write('{"checksum": "bad", "data": {"assumed": {"x": "y"}}}')
    assert load_assumed(cm) == {}


def test_crash_only_recovery_from_watch():
    # a fresh scheduler on the same store rebuilds all state via LIST+WATCH
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    s1 = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(mk_pod("p0"))
    s1.run_until_idle()
    # s1 "crashes"; s2 attaches and schedules new work with full state
    s2 = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(mk_pod("p1", cpu=100))
    s2.run_until_idle()
    assert store.pods["default/p0"].node_name == "n0"
    assert store.pods["default/p1"].node_name == "n0"
    snap = s2.cache.update_snapshot()
    assert len(snap.bound_pods) == 2
