"""Property test: vectorized selector matching == per-pod object matching."""

import random

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.pairwise import TermKey, _match_matrix, _matches


def random_selector(rng):
    kind = rng.random()
    if kind < 0.15:
        return None
    if kind < 0.3:
        return t.LabelSelector()  # empty: matches everything
    exprs = []
    for _ in range(rng.randint(1, 3)):
        op = rng.choice([t.OP_IN, t.OP_NOT_IN, t.OP_EXISTS, t.OP_DOES_NOT_EXIST])
        key = rng.choice(["app", "tier", "env", "ghost"])
        vals = tuple(rng.sample(["a", "b", "c", "zz"], k=rng.randint(1, 2)))
        exprs.append(
            t.LabelSelectorRequirement(
                key=key, operator=op, values=() if op in (t.OP_EXISTS, t.OP_DOES_NOT_EXIST) else vals
            )
        )
    ml = ()
    if rng.random() < 0.5:
        ml = ((rng.choice(["app", "tier"]), rng.choice(["a", "b"])),)
    return t.LabelSelector(match_labels=ml, match_expressions=tuple(exprs))


def test_match_matrix_equals_object_matching():
    rng = random.Random(11)
    pods = [
        t.Pod(
            name=f"p{i}",
            namespace=rng.choice(["default", "prod", "dev"]),
            labels={
                k: rng.choice(["a", "b", "c"])
                for k in rng.sample(["app", "tier", "env"], k=rng.randint(0, 3))
            },
        )
        for i in range(60)
    ]
    terms = [
        TermKey(
            topology_key="zone",
            namespaces=tuple(rng.sample(["default", "prod", "dev"], k=rng.randint(1, 2))),
            selector=random_selector(rng),
        )
        for _ in range(40)
    ]
    M = _match_matrix(terms, pods)
    for ti, term in enumerate(terms):
        for pi, pod in enumerate(pods):
            assert bool(M[ti, pi]) == _matches(term, pod), (term, pod)
