"""EndpointSlice controller + kube-proxy analog; wave-2 controllers:
StatefulSet, DaemonSet, CronJob, HPA, Namespace, PodGC, TTLAfterFinished.

Test style mirrors the reference's controller unit tests (fake store + sync
loop assertions, e.g. pkg/controller/statefulset/stateful_set_control_test.go)."""

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler.controllers import (
    ControllerManager,
    CronJobController,
    DaemonSetController,
    HPAController,
    JobController,
    NamespaceController,
    PodGCController,
    StatefulSetController,
    TTLAfterFinishedController,
)
from kubernetes_tpu.scheduler.kubelet import HollowCluster
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.network import EndpointSliceController, Proxier
from kubernetes_tpu.scheduler.queue import FakeClock
from kubernetes_tpu.scheduler.store import ClusterStore


def _store_with_nodes(n=2):
    store = ClusterStore()
    for i in range(n):
        store.add_node(t.Node(name=f"n{i}", allocatable={t.CPU: 8000, t.PODS: 20}))
    return store


def _running_pod(name, node="n0", labels=None, ip=None):
    return t.Pod(name=name, node_name=node, phase=t.PHASE_RUNNING,
                 labels=dict(labels or {}), pod_ip=ip or f"10.244.0.{name[-1]}")


# ----------------------------------------------------- EndpointSlice + proxy


def test_endpointslice_sync_and_gc_ownership():
    store = _store_with_nodes()
    ctrl = EndpointSliceController(store)
    svc = c.Service(name="web", selector=(("app", "web"),),
                    ports=(c.ServicePort(80, target_port=8080),),
                    cluster_ip="10.96.0.1")
    store.add_object("Service", svc)
    store.add_pod(_running_pod("w1", labels={"app": "web"}))
    store.add_pod(_running_pod("w2", labels={"app": "web"}))
    store.add_pod(_running_pod("x1", labels={"app": "other"}))
    # pending pod: not an endpoint
    store.add_pod(t.Pod(name="w3", labels={"app": "web"}))
    ctrl.tick()
    slices = store.list_objects("EndpointSlice")
    assert len(slices) == 1
    eps = slices[0].endpoints
    assert {e.pod_uid for e in eps} == {"default/w1", "default/w2"}
    assert all(e.ready for e in eps)
    assert slices[0].owner_references[0].uid == svc.uid
    # service deleted -> GC collects the slice
    store.delete_object("Service", svc.key)
    cm = ControllerManager(store)
    cm.gc.tick()
    assert not store.list_objects("EndpointSlice")


def test_endpointslice_chunking_over_100():
    store = _store_with_nodes(1)
    ctrl = EndpointSliceController(store)
    store.add_object("Service", c.Service(
        name="big", selector=(("app", "big"),), ports=(c.ServicePort(80),),
        cluster_ip="10.96.0.2"))
    for i in range(250):
        store.add_pod(t.Pod(name=f"b{i}", node_name="n0", phase=t.PHASE_RUNNING,
                            labels={"app": "big"}, pod_ip=f"10.244.{i // 250}.{i % 250}"))
    ctrl.tick()
    slices = sorted(store.list_objects("EndpointSlice"), key=lambda s: s.name)
    assert [len(s.endpoints) for s in slices] == [100, 100, 50]
    # scale down -> shrink + drop empty trailing slices
    for i in range(120, 250):
        store.delete_pod(f"default/b{i}")
    ctrl.tick()
    slices = sorted(store.list_objects("EndpointSlice"), key=lambda s: s.name)
    assert [len(s.endpoints) for s in slices] == [100, 20]


def test_proxier_balances_and_session_affinity():
    store = _store_with_nodes()
    ctrl = EndpointSliceController(store)
    store.add_object("Service", c.Service(
        name="web", selector=(("app", "web"),),
        ports=(c.ServicePort(80, target_port=8080),), cluster_ip="10.96.0.1"))
    store.add_object("Service", c.Service(
        name="sticky", selector=(("app", "web"),),
        ports=(c.ServicePort(443),), cluster_ip="10.96.0.9",
        session_affinity="ClientIP"))
    for i in range(3):
        store.add_pod(_running_pod(f"w{i}", labels={"app": "web"},
                                   ip=f"10.244.0.{i}"))
    ctrl.tick()
    proxy = Proxier(store, seed=7)
    proxy.sync()
    # VIP lookup balances over all ready backends at the target port
    seen = {proxy.lookup(f"client-{i}", "10.96.0.1", 80) for i in range(60)}
    assert seen == {(f"10.244.0.{i}", 8080) for i in range(3)}
    # unknown VIP/port -> REJECT
    assert proxy.lookup("c", "10.96.0.1", 81) is None
    # ClientIP affinity is sticky per client
    first = proxy.lookup("alice", "10.96.0.9", 443)
    assert all(proxy.lookup("alice", "10.96.0.9", 443) == first for _ in range(20))
    # backend removal invalidates affinity and routing
    store.delete_pod("default/w0")
    store.delete_pod("default/w1")
    store.delete_pod("default/w2")
    ctrl.tick()
    proxy.sync()
    assert proxy.lookup("alice", "10.96.0.9", 443) is None


def test_proxier_skips_not_ready_endpoints():
    store = _store_with_nodes()
    ctrl = EndpointSliceController(store)
    store.add_object("Service", c.Service(
        name="web", selector=(("app", "web"),), ports=(c.ServicePort(80),),
        cluster_ip="10.96.0.1"))
    store.add_pod(_running_pod("w1", labels={"app": "web"}, ip="10.244.0.1"))
    # bound but no IP yet -> endpoint exists, not ready
    store.add_pod(t.Pod(name="w2", node_name="n0", phase=t.PHASE_PENDING,
                        labels={"app": "web"}))
    ctrl.tick()
    proxy = Proxier(store)
    proxy.sync()
    assert {proxy.lookup(f"c{i}", "10.96.0.1", 80) for i in range(20)} == {
        ("10.244.0.1", 80)
    }


# ------------------------------------------------------------- StatefulSet


def test_statefulset_ordered_creation_and_scale_down():
    store = _store_with_nodes()
    ctrl = StatefulSetController(store)
    sts = c.StatefulSet(name="db", replicas=3, template=t.Pod(name="x"))
    store.add_object("StatefulSet", sts)
    ctrl.tick()
    assert sorted(p.name for p in store.pods.values()) == ["db-0"]  # one at a time
    ctrl.tick()
    assert len(store.pods) == 1  # db-0 not ready yet: gate holds
    # mark ready (bound + running)
    p0 = store.pods["default/db-0"]
    p0.node_name, p0.phase = "n0", t.PHASE_RUNNING
    ctrl.tick()
    assert sorted(p.name for p in store.pods.values()) == ["db-0", "db-1"]
    p1 = store.pods["default/db-1"]
    p1.node_name, p1.phase = "n1", t.PHASE_RUNNING
    ctrl.tick()
    assert sorted(p.name for p in store.pods.values()) == ["db-0", "db-1", "db-2"]
    # scale down: highest ordinal first, one per round
    store.update_object("StatefulSet",
                        store.get_object("StatefulSet", "default/db").__class__(
                            **{**store.get_object("StatefulSet", "default/db").__dict__,
                               "replicas": 1}))
    ctrl.tick()
    assert sorted(p.name for p in store.pods.values()) == ["db-0", "db-1"]
    ctrl.tick()
    assert sorted(p.name for p in store.pods.values()) == ["db-0"]


def test_statefulset_parallel_policy():
    store = _store_with_nodes()
    ctrl = StatefulSetController(store)
    store.add_object("StatefulSet", c.StatefulSet(
        name="par", replicas=4, template=t.Pod(name="x"),
        pod_management_policy="Parallel"))
    ctrl.tick()
    assert len(store.pods) == 4


# --------------------------------------------------------------- DaemonSet


def test_daemonset_one_pod_per_eligible_node():
    store = _store_with_nodes(3)
    store.add_node(t.Node(name="tainted", allocatable={t.CPU: 8000},
                          taints=(t.Taint(key="gpu", effect=t.NO_SCHEDULE),)))
    store.add_node(t.Node(name="cordoned", allocatable={t.CPU: 8000},
                          unschedulable=True))
    ctrl = DaemonSetController(store)
    ds = c.DaemonSet(name="agent", template=t.Pod(name="x"))
    store.add_object("DaemonSet", ds)
    ctrl.tick()
    pods = list(store.pods.values())
    assert len(pods) == 3  # tainted + cordoned excluded
    # every pod pinned to a distinct node via hostname affinity
    from kubernetes_tpu.scheduler.controllers import _pinned_node
    assert {_pinned_node(p) for p in pods} == {"n0", "n1", "n2"}
    # node added -> next tick grows; node deleted -> pod removed
    store.add_node(t.Node(name="n3", allocatable={t.CPU: 8000}))
    ctrl.tick()
    assert len(store.pods) == 4
    store.delete_node("n1")
    ctrl.tick()
    assert {_pinned_node(p) for p in store.pods.values()} == {"n0", "n2", "n3"}


def test_daemonset_toleration_admits_tainted_node():
    store = ClusterStore()
    store.add_node(t.Node(name="gpu0", allocatable={t.CPU: 8000},
                          taints=(t.Taint(key="gpu", effect=t.NO_SCHEDULE),)))
    ctrl = DaemonSetController(store)
    store.add_object("DaemonSet", c.DaemonSet(
        name="gpu-agent",
        template=t.Pod(name="x", tolerations=(
            t.Toleration(key="gpu", operator=t.OP_EXISTS),))))
    ctrl.tick()
    assert len(store.pods) == 1


# ------------------------------------------------------------ CronJob + TTL


def test_cronjob_spawns_jobs_on_period():
    store = ClusterStore()
    clock = FakeClock(start=100.0)
    cron = CronJobController(store, clock=clock)
    jobs = JobController(store, clock=clock)
    store.add_object("CronJob", c.CronJob(
        name="tick", period_seconds=60, job_template=t.Pod(name="x", run_seconds=1)))
    cron.tick()
    assert len(store.jobs) == 1
    cron.tick()
    assert len(store.jobs) == 1  # within the period: no new job
    clock.step(61)
    cron.tick()
    assert len(store.jobs) == 2
    jobs.tick()
    assert len(store.pods) == 2  # one pod per spawned job
    # jobs carry the CronJob owner ref (GC edge)
    assert all(j.owner_references[0].kind == "CronJob" for j in store.jobs.values())


def test_cronjob_forbid_and_replace_policies():
    store = ClusterStore()
    clock = FakeClock(start=0.0)
    cron = CronJobController(store, clock=clock)
    store.add_object("CronJob", c.CronJob(
        name="fb", period_seconds=10, concurrency_policy="Forbid",
        job_template=t.Pod(name="x")))
    cron.tick()
    clock.step(11)
    cron.tick()  # previous job still active -> skipped
    assert len(store.jobs) == 1
    store.objects["CronJob"]["default/fb"].concurrency_policy = "Replace"
    clock.step(11)
    cron.tick()  # Replace: old active job deleted, new one spawned
    assert len(store.jobs) == 1
    assert list(store.jobs.values())[0].name.startswith("fb-")


def test_ttl_after_finished_deletes_job_and_cascades():
    store = ClusterStore()
    clock = FakeClock(start=0.0)
    cm = ControllerManager(store, clock=clock)
    store.add_object("Job", t.Job(
        name="once", completions=1, parallelism=1,
        template=t.Pod(name="x", run_seconds=1), ttl_seconds_after_finished=30))
    cm.tick()
    assert len(store.pods) == 1
    # finish the pod
    pod = next(iter(store.pods.values()))
    pod.phase = t.PHASE_SUCCEEDED
    cm.tick()
    job = store.jobs["default/once"]
    assert job.complete and job.completion_time == clock.now()
    clock.step(31)
    cm.tick()
    assert not store.jobs  # TTL elapsed
    cm.tick()
    assert not store.pods  # GC cascaded the pod


# -------------------------------------------------------------------- HPA


def test_hpa_scales_deployment_up_and_down_with_tolerance():
    store = _store_with_nodes()
    load = {"value": 1.0}
    hpa_ctrl = HPAController(store, metrics=lambda ns, pods: load["value"])
    d = t.Deployment(name="web", replicas=2, selector=t.LabelSelector.of(app="w"),
                     template=t.Pod(name="x", labels={"app": "w"}))
    store.add_object("Deployment", d)
    store.add_object("HorizontalPodAutoscaler", c.HorizontalPodAutoscaler(
        name="web", target_name="web", min_replicas=1, max_replicas=6,
        target_value=0.5, tolerance=0.1))
    for i in range(2):
        store.add_pod(_running_pod(f"w{i}", labels={"app": "w"}))
    hpa_ctrl.tick()
    assert store.deployments["default/web"].replicas == 4  # 2 * 1.0/0.5
    # inside tolerance: no change
    load["value"] = 0.52
    hpa_ctrl.tick()
    assert store.deployments["default/web"].replicas == 4
    # low load: scale down, clamped to min
    load["value"] = 0.01
    hpa_ctrl.tick()
    assert store.deployments["default/web"].replicas == 1
    hpa = store.get_object("HorizontalPodAutoscaler", "default/web")
    # status reflects the scale target's replicas at decision time
    assert hpa.current_replicas == 4 and hpa.desired_replicas == 1


# ------------------------------------------------- Namespace + PodGC sweeps


def test_namespace_termination_drains_all_kinds():
    store = _store_with_nodes()
    ctrl = NamespaceController(store)
    store.add_object("Namespace", c.Namespace(name="team-a"))
    store.add_pod(t.Pod(name="p1", namespace="team-a"))
    store.add_object("Service", c.Service(name="s1", namespace="team-a"))
    store.add_object("Deployment", t.Deployment(name="d1", namespace="team-a"))
    store.add_pdb(t.PodDisruptionBudget(name="pdb1", namespace="team-a"))
    ctrl.tick()
    assert store.pods  # Active: untouched
    store.objects["Namespace"]["team-a"].phase = "Terminating"
    ctrl.tick()
    assert not store.pods and not store.list_objects("Service")
    assert not store.deployments and not store.pdbs
    ctrl.tick()  # empty now -> namespace itself removed
    assert store.get_object("Namespace", "team-a") is None


def test_podgc_sweeps_orphans_and_terminated_overflow():
    store = _store_with_nodes(1)
    gc = PodGCController(store, terminated_threshold=2)
    store.add_pod(t.Pod(name="orphan", node_name="gone-node"))
    for i in range(5):
        store.add_pod(t.Pod(name=f"done{i}", node_name="n0",
                            phase=t.PHASE_SUCCEEDED))
    assert gc.tick() == 1 + 3  # orphan + (5 terminated - threshold 2)
    assert "default/orphan" not in store.pods
    assert sum(1 for p in store.pods.values()
               if p.phase == t.PHASE_SUCCEEDED) == 2


# ------------------------------------------------------- integration: fleet


def test_full_stack_daemonset_through_scheduler_and_kubelet():
    """DaemonSet -> controller stamps affinity-pinned pods -> real scheduler
    binds them -> hollow kubelet runs them -> endpoint slices see them."""
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    store = _store_with_nodes(3)
    cm = ControllerManager(store)
    sched = Scheduler(store)
    leases = LeaseStore()
    fleet = HollowCluster(store, leases)
    store.add_object("DaemonSet", c.DaemonSet(
        name="exporter", template=t.Pod(
            name="x", requests={t.CPU: 100, t.PODS: 1},
            labels={"app": "exporter"})))
    store.add_object("Service", c.Service(
        name="exporter", selector=(("app", "exporter"),),
        ports=(c.ServicePort(9100),), cluster_ip="10.96.0.5"))
    cm.tick()
    sched.run_until_idle()
    bound = [p for p in store.pods.values() if p.node_name]
    assert len(bound) == 3
    # each daemon pod landed exactly on its pinned node
    from kubernetes_tpu.scheduler.controllers import _pinned_node
    assert all(p.node_name == _pinned_node(p) for p in bound)
    fleet.tick()
    cm.tick()
    slices = store.list_objects("EndpointSlice")
    assert len(slices) == 1 and len(slices[0].endpoints) == 3
    proxy = Proxier(store)
    proxy.sync()
    assert proxy.lookup("client", "10.96.0.5", 9100) is not None


# ------------------------------------------- review regressions (wave 2 fixes)


def test_completed_job_never_reruns_after_podgc():
    """Job status is authoritative once complete: GC-deleting the succeeded
    pods must not respawn the workload (completion_time guard)."""
    store = _store_with_nodes()
    clock = FakeClock()
    ctrl = JobController(store, clock=clock)
    job = t.Job(name="batch", completions=2, parallelism=2,
                template=t.Pod(name="x", run_seconds=1.0))
    store.add_object("Job", job)
    ctrl.tick()
    for p in list(store.pods.values()):
        p.phase = t.PHASE_SUCCEEDED
    ctrl.tick()
    done = store.get_object("Job", "default/batch")
    assert done.complete and done.completion_time >= 0
    # PodGC wipes the succeeded pods
    for p in list(store.pods.values()):
        store.delete_pod(p.uid)
    ctrl.tick()
    assert store.pods == {}  # no respawn
    assert store.get_object("Job", "default/batch").complete


def test_daemonset_replaces_finished_daemon_pod():
    store = _store_with_nodes(1)
    ctrl = DaemonSetController(store)
    store.add_object("DaemonSet", c.DaemonSet(name="agent", template=t.Pod(name="x")))
    ctrl.tick()
    [pod] = store.pods.values()
    pod.phase = t.PHASE_FAILED
    ctrl.tick()
    pods = list(store.pods.values())
    assert len(pods) == 1 and pods[0].phase != t.PHASE_FAILED  # recreated fresh


def test_statefulset_recreates_finished_ordinal():
    store = _store_with_nodes()
    ctrl = StatefulSetController(store)
    store.add_object("StatefulSet", c.StatefulSet(name="db", replicas=2,
                                                  template=t.Pod(name="x")))
    ctrl.tick()
    p0 = store.pods["default/db-0"]
    p0.node_name, p0.phase = "n0", t.PHASE_FAILED
    ctrl.tick()  # db-0 deleted + recreated at the same ordinal, gate intact
    assert sorted(p.name for p in store.pods.values()) == ["db-0"]
    assert store.pods["default/db-0"].phase != t.PHASE_FAILED


def test_namespace_controller_drains_pvcs():
    store = ClusterStore()
    store.add_object("Namespace", c.Namespace(name="team-a", phase="Terminating"))
    store.add_pvc(t.PersistentVolumeClaim(name="data", namespace="team-a"))
    ctrl = NamespaceController(store)
    ctrl.tick()
    ctrl.tick()
    assert store.pvcs == {}
    assert store.get_object("Namespace", "team-a") is None


def test_podgc_terminated_sweep_oldest_finish_time_first():
    store = _store_with_nodes(1)
    gc = PodGCController(store, terminated_threshold=1)
    for name, at in (("late", 30.0), ("early", 10.0)):
        store.add_pod(t.Pod(name=name, node_name="n0",
                            phase=t.PHASE_SUCCEEDED, finished_at=at))
    gc.tick()
    assert [p.name for p in store.pods.values()] == ["late"]


def test_hollow_kubelets_share_store_get_disjoint_cidrs():
    """Two allocators over one store must hand out disjoint per-node /24s."""
    from kubernetes_tpu.scheduler.kubelet import HollowKubelet

    store = _store_with_nodes(2)
    leases = LeaseStore(FakeClock())
    cluster = HollowCluster(store, leases)
    store.add_pod(t.Pod(name="a", node_name="n0"))
    store.add_pod(t.Pod(name="b", node_name="n1"))
    cluster.tick()  # n0, n1 via the fleet
    direct = HollowKubelet(store, leases, "n1")  # standalone, same store
    store.add_pod(t.Pod(name="c", node_name="n1"))
    direct.tick()
    ips = {p.name: p.pod_ip for p in store.pods.values()}
    assert len(set(ips.values())) == 3, ips
    # same node -> same subnet regardless of which kubelet allocated
    assert ips["b"].rsplit(".", 1)[0] == ips["c"].rsplit(".", 1)[0]
    assert ips["a"].rsplit(".", 1)[0] != ips["b"].rsplit(".", 1)[0]


def test_job_completions_survive_podgc_between_waves():
    """Once-only accounting: completions counted into status must persist even
    when PodGC deletes the succeeded pods between controller syncs."""
    store = _store_with_nodes()
    clock = FakeClock()
    ctrl = JobController(store, clock=clock)
    store.add_object("Job", t.Job(name="waves", completions=4, parallelism=2,
                                  template=t.Pod(name="x", run_seconds=1.0)))
    ctrl.tick()
    for p in list(store.pods.values()):
        p.phase = t.PHASE_SUCCEEDED
    ctrl.tick()  # counts wave 1 (2 completions), spawns wave 2
    assert store.get_object("Job", "default/waves").succeeded == 2
    # PodGC wipes wave 1's succeeded pods before the next sync
    for p in list(store.pods.values()):
        if p.phase == t.PHASE_SUCCEEDED:
            store.delete_pod(p.uid)
    ctrl.tick()
    assert store.get_object("Job", "default/waves").succeeded == 2  # not lost
    for p in list(store.pods.values()):
        p.phase = t.PHASE_SUCCEEDED
    ctrl.tick()
    job = store.get_object("Job", "default/waves")
    assert job.succeeded == 4 and job.complete
    ctrl.tick()
    assert store.get_object("Job", "default/waves").succeeded == 4  # no double count
