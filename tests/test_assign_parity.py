"""L3+L5: end-to-end parity — jitted batch scheduler vs the sequential NumPy
oracle, the framework's conformance analog (SURVEY.md §4: "same snapshot ->
TPU verdicts == CPU-reference verdicts")."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch
from kubernetes_tpu.oracle import oracle_schedule
from kubernetes_tpu.api import types as t
from helpers import mk_node, mk_pod, random_cluster


def run_tpu(snap):
    arr, meta = encode_snapshot(snap)
    choices, _ = schedule_batch(arr, DEFAULT_SCORE_CONFIG)
    choices = np.asarray(choices)
    out = []
    for k in range(meta.n_pods):
        c = int(choices[k])
        out.append((meta.pod_names[k], meta.node_names[c] if c >= 0 else None))
    return out


def assert_parity(snap):
    got = run_tpu(snap)
    want = oracle_schedule(snap)
    assert got == want


def test_single_pod_single_node():
    assert_parity(Snapshot(nodes=[mk_node("n0")], pending_pods=[mk_pod("p0")]))


def test_prefers_least_allocated():
    snap = Snapshot(
        nodes=[mk_node("busy", cpu=4000), mk_node("idle", cpu=4000)],
        pending_pods=[mk_pod("p", cpu=1000)],
        bound_pods=[mk_pod("b", cpu=2000, node_name="busy")],
    )
    got = run_tpu(snap)
    assert got[0] == ("p", "idle")
    assert_parity(snap)


def test_sequential_capacity_semantics():
    # Two pods each needing >half a node: second must spill to the other node.
    snap = Snapshot(
        nodes=[mk_node("a", cpu=1000), mk_node("b", cpu=1000)],
        pending_pods=[mk_pod("p0", cpu=600), mk_pod("p1", cpu=600)],
    )
    got = dict(run_tpu(snap))
    assert {got["p0"], got["p1"]} == {"a", "b"}
    assert_parity(snap)


def test_unschedulable_reported():
    snap = Snapshot(
        nodes=[mk_node("tiny", cpu=100)],
        pending_pods=[mk_pod("p", cpu=200)],
    )
    assert run_tpu(snap)[0] == ("p", None)
    assert_parity(snap)


def test_priority_order_matters():
    # High-priority pod pops first and takes the last slot.
    snap = Snapshot(
        nodes=[mk_node("only", cpu=700)],
        pending_pods=[mk_pod("low", cpu=600), mk_pod("high", cpu=600, priority=100)],
    )
    got = dict(run_tpu(snap))
    assert got["high"] == "only" and got["low"] is None
    assert_parity(snap)


@pytest.mark.parametrize("seed", range(6))
def test_parity_random_small(seed):
    rng = random.Random(seed)
    assert_parity(random_cluster(rng, n_nodes=13, n_pods=29))


@pytest.mark.parametrize("seed", range(6))
def test_parity_random_taints_selectors(seed):
    rng = random.Random(1000 + seed)
    assert_parity(
        random_cluster(rng, n_nodes=17, n_pods=41, with_taints=True, with_selectors=True)
    )


def test_parity_random_medium():
    rng = random.Random(42)
    assert_parity(
        random_cluster(rng, n_nodes=64, n_pods=200, with_taints=True, with_selectors=True)
    )


@pytest.mark.parametrize("seed", range(8))
def test_parity_random_pairwise(seed):
    rng = random.Random(2000 + seed)
    assert_parity(
        random_cluster(
            rng, n_nodes=15, n_pods=37, with_taints=True, with_selectors=True, with_pairwise=True
        )
    )


def test_parity_random_pairwise_medium():
    rng = random.Random(77)
    assert_parity(
        random_cluster(
            rng, n_nodes=48, n_pods=150, with_taints=True, with_selectors=True, with_pairwise=True
        )
    )


def test_chunked_scan_engages_and_matches_plain():
    """>= 128 pods on a fit+balanced-only config take the CHUNKED commit scan
    (ops/assign.py — schedule_scan_chunked); its decisions must be
    bit-identical to the plain per-pod scan AND the oracle."""
    import jax

    from kubernetes_tpu.api.snapshot import encode_snapshot as _enc
    from kubernetes_tpu.ops.assign import (
        _chunkable,
        schedule_scan,
        schedule_scan_chunked,
    )
    from kubernetes_tpu.ops.scores import infer_score_config

    rng = random.Random(7)
    # few nodes + many pods: heavy intra-chunk contention (same nodes touched
    # repeatedly) — the correction slots, not just the hoisted row, decide
    # no PreferNoSchedule taints (they add a normalization stage and road
    # back to the plain scan); heterogeneous's HARD taints are covered below
    snap = random_cluster(rng, n_nodes=6, n_pods=200, with_taints=False,
                          with_selectors=True, with_pairwise=False)
    arr, meta = _enc(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg), (arr.P, cfg)
    plain = np.asarray(jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0])
    chunked, used = (
        np.asarray(x)
        for x in jax.jit(schedule_scan_chunked, static_argnames=("cfg",))(arr, cfg)
    )
    np.testing.assert_array_equal(chunked, plain)
    # ... and the shared entry point routes to it with oracle parity
    assert_parity(snap)
    # node_used output matches the plain scan's too
    plain_used = np.asarray(
        jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[1]
    )
    np.testing.assert_array_equal(used, plain_used)


@pytest.mark.parametrize("seed", range(4))
def test_chunked_scan_randomized_parity(seed):
    rng = random.Random(1000 + seed)
    snap = random_cluster(rng, n_nodes=rng.randint(3, 40),
                          n_pods=rng.randint(128, 300),
                          with_taints=False, with_selectors=True,
                          with_pairwise=False)
    assert_parity(snap)


def test_chunked_heterogeneous_with_hard_taints_matches_plain():
    """The north-star shape: NoSchedule taints + tolerations + extended
    resources stay on the chunked path (hard taints are static filters)."""
    import jax

    from kubernetes_tpu.bench import workloads
    from kubernetes_tpu.api.snapshot import encode_snapshot as _enc
    from kubernetes_tpu.ops.assign import _chunkable, schedule_scan
    from kubernetes_tpu.ops.scores import infer_score_config

    snap = workloads.heterogeneous(40, 256, seed=5)
    arr, meta = _enc(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg), cfg
    plain = np.asarray(jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0])
    routed = np.asarray(schedule_batch(arr, cfg)[0])
    np.testing.assert_array_equal(routed, plain)


def test_chunked_scan_tie_breaks_match_plain_on_identical_nodes():
    """Identical nodes + identical pods = a score TIE at every step, with the
    tying nodes alternating between touched (corrected) and untouched
    (hoisted) entries — the worst case for the chunked argmax/tie-break."""
    import jax

    from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
    from kubernetes_tpu.ops.assign import _chunkable, schedule_scan
    from kubernetes_tpu.ops.scores import infer_score_config

    nodes = [mk_node(f"n{i}", cpu=8000, pods=200) for i in range(4)]
    pods = [mk_pod(f"p{i}", cpu=100) for i in range(160)]
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg)
    plain = np.asarray(jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0])
    routed = np.asarray(schedule_batch(arr, cfg)[0])
    np.testing.assert_array_equal(routed, plain)
    assert_parity(snap)


def test_chunked_scan_with_rounds_diagnostic():
    """`with_rounds=True` (bound BEFORE jit, e.g. via functools.partial — it
    selects the return arity at trace time) reports the per-chunk round count
    of the prefix-commit speculation loop without changing decisions.  Every
    chunk commits >= 1 pod per round, so rounds are in [1, C]."""
    from functools import partial

    import jax

    from kubernetes_tpu.api.snapshot import encode_snapshot as _enc
    from kubernetes_tpu.ops.assign import (
        _CHUNK,
        _chunkable,
        schedule_scan_chunked,
    )
    from kubernetes_tpu.ops.scores import infer_score_config

    rng = random.Random(11)
    snap = random_cluster(rng, n_nodes=6, n_pods=256, with_taints=False,
                          with_selectors=True, with_pairwise=False)
    arr, meta = _enc(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg)
    f = jax.jit(
        partial(schedule_scan_chunked, with_rounds=True),
        static_argnames=("cfg",),
    )
    choices, used, rounds = (np.asarray(x) for x in f(arr, cfg))
    assert rounds.shape == (arr.P // _CHUNK,)
    assert (rounds >= 1).all() and (rounds <= _CHUNK).all()
    # decisions identical to the default (2-tuple) entry point
    two = np.asarray(
        jax.jit(schedule_scan_chunked, static_argnames=("cfg",))(arr, cfg)[0]
    )
    np.testing.assert_array_equal(choices, two)


@pytest.mark.parametrize("seed", range(3))
def test_chunked_scan_parity_when_topk_not_exhaustive(seed):
    """N > K = C+1: the top-K candidate list is a strict subset of the
    nodes, so the clean-head domination argument and the cleank staleness
    updates actually carry the result (with N <= K the list is trivially
    exhaustive and those paths are untested).  Decisions must stay
    bit-identical to the plain per-pod scan."""
    import jax

    from kubernetes_tpu.api.snapshot import encode_snapshot as _enc
    from kubernetes_tpu.ops.assign import (
        _CHUNK,
        _chunkable,
        schedule_scan,
        schedule_scan_chunked,
    )
    from kubernetes_tpu.ops.scores import infer_score_config

    rng = random.Random(3000 + seed)
    snap = random_cluster(rng, n_nodes=150 + 20 * seed, n_pods=256,
                          with_taints=False, with_selectors=True,
                          with_pairwise=False)
    arr, meta = _enc(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg)
    assert arr.N > _CHUNK + 1, arr.N  # the regime under test
    plain = np.asarray(
        jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0]
    )
    chunked = np.asarray(
        jax.jit(schedule_scan_chunked, static_argnames=("cfg",))(arr, cfg)[0]
    )
    np.testing.assert_array_equal(chunked, plain)
    assert_parity(snap)


def test_chunked_scan_plateau_wider_than_candidate_list():
    """More identical nodes than K = C+1: the tied-score plateau extends
    past every pod's candidate list, so correctness leans on the
    top_k lowest-index-ties ordering + the clean-head domination argument
    at its boundary.  Identical pods make every step a plateau pick."""
    import jax

    from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
    from kubernetes_tpu.ops.assign import _CHUNK, _chunkable, schedule_scan, schedule_scan_chunked
    from kubernetes_tpu.ops.scores import infer_score_config

    n_nodes = _CHUNK + 60  # > K = C+1, all identical
    nodes = [mk_node(f"n{i:04d}", cpu=4000, pods=300) for i in range(n_nodes)]
    pods = [mk_pod(f"p{i:05d}", cpu=50) for i in range(2 * _CHUNK)]
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg) and arr.N > _CHUNK + 1
    plain = np.asarray(jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0])
    chunked = np.asarray(
        jax.jit(schedule_scan_chunked, static_argnames=("cfg",))(arr, cfg)[0]
    )
    np.testing.assert_array_equal(chunked, plain)
    assert_parity(snap)


def test_chunked_scan_capacity_exhausts_mid_chunk():
    """Capacity runs out partway through a chunk: later pods must go
    unschedulable (-1) exactly where the per-pod scan says, exercising the
    t == c == -1 validity path and fit monotonicity mid-round."""
    import jax

    from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
    from kubernetes_tpu.ops.assign import _chunkable, schedule_scan, schedule_scan_chunked
    from kubernetes_tpu.ops.scores import infer_score_config

    nodes = [mk_node(f"n{i}", cpu=1000, pods=500) for i in range(140)]
    # 140 nodes x 1 pod of 900m each = exactly 140 fit; the rest starve
    pods = [mk_pod(f"p{i:05d}", cpu=900) for i in range(256)]
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg)
    plain = np.asarray(jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0])
    chunked = np.asarray(
        jax.jit(schedule_scan_chunked, static_argnames=("cfg",))(arr, cfg)[0]
    )
    np.testing.assert_array_equal(chunked, plain)
    assert (plain[: meta.n_pods] >= 0).sum() == 140
    assert_parity(snap)


# ---- schedule_scan_rounds: the generalized (pairwise/ports/taint/pref/
# image) chunked path ----

def _rounds_vs_plain(snap, cfg_base=DEFAULT_SCORE_CONFIG, check_oracle=True):
    """Route-independent ground truth: the rounds kernel must be
    bit-identical to the plain per-pod scan (choices AND final usage), and
    — for the default config — to the sequential oracle."""
    import jax

    from kubernetes_tpu.ops.assign import (
        _chunkable,
        _rounds_capable,
        schedule_scan,
        schedule_scan_rounds,
    )
    from kubernetes_tpu.ops.scores import infer_score_config

    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, cfg_base)
    assert _rounds_capable(arr, cfg), arr.P
    assert not _chunkable(arr, cfg), cfg  # the regime the rounds path exists for
    plain_c, plain_u = (
        np.asarray(x)
        for x in jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)
    )
    rounds_c, rounds_u = (
        np.asarray(x)
        for x in jax.jit(schedule_scan_rounds, static_argnames=("cfg",))(arr, cfg)
    )
    np.testing.assert_array_equal(rounds_c, plain_c)
    np.testing.assert_array_equal(rounds_u, plain_u)
    if check_oracle and cfg_base is DEFAULT_SCORE_CONFIG:
        got = [
            (meta.pod_names[k],
             meta.node_names[int(plain_c[k])] if int(plain_c[k]) >= 0 else None)
            for k in range(meta.n_pods)
        ]
        assert got == oracle_schedule(snap)
    return arr, cfg


@pytest.mark.parametrize("seed", range(4))
def test_rounds_scan_randomized_pairwise_parity(seed):
    """Random spread + (anti-)affinity + host ports + PreferNoSchedule
    taints (taint-score stage) + node selectors at >= 2 chunks."""
    rng = random.Random(4000 + seed)
    snap = random_cluster(rng, n_nodes=rng.randint(6, 40),
                          n_pods=rng.choice([128, 256]),
                          with_taints=True, with_selectors=True,
                          with_pairwise=True)
    _rounds_vs_plain(snap)


def test_rounds_scan_same_app_spread_worst_case():
    """EVERY pod shares one DoNotSchedule spread term (one app): maximal
    term-sharing interference — prefixes shrink toward one pod per round,
    the degenerate regime — while domain counts must stay exact across
    chunks."""
    nodes = [mk_node(f"n{i}", cpu=4000, pods=300,
                     labels={"topology.kubernetes.io/zone": f"zone-{i % 3}"})
             for i in range(9)]
    spread = (t.TopologySpreadConstraint(
        max_skew=1, topology_key="topology.kubernetes.io/zone",
        when_unsatisfiable=t.DO_NOT_SCHEDULE,
        label_selector=t.LabelSelector.of(app="one")),)
    pods = [mk_pod(f"p{i:04d}", cpu=50, labels={"app": "one"},
                   topology_spread=spread) for i in range(128)]
    _rounds_vs_plain(Snapshot(nodes=nodes, pending_pods=pods))


def test_rounds_scan_anti_affinity_one_per_node():
    """One-replica-per-node: every pod carries hostname-scoped required
    anti-affinity against its own app — each commit excludes a node for
    ALL later pods (anti_node writes ∩ every pod's match terms), the
    self-exclusion chain the round-3 verdict called out."""
    term = t.PodAffinityTerm(
        topology_key="kubernetes.io/hostname",
        label_selector=t.LabelSelector.of(app="solo"),
    )
    nodes = [mk_node(f"n{i:03d}", cpu=4000) for i in range(140)]
    pods = [mk_pod(f"p{i:04d}", cpu=100, labels={"app": "solo"},
                   affinity=t.Affinity(required_pod_anti_affinity=(term,)))
            for i in range(128)]
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    _rounds_vs_plain(snap)
    # semantic sanity: all 128 land on 128 DISTINCT nodes
    arr, meta = encode_snapshot(snap)
    from kubernetes_tpu.ops.scores import infer_score_config
    import jax
    from kubernetes_tpu.ops.assign import schedule_scan_rounds
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    ch = np.asarray(jax.jit(schedule_scan_rounds, static_argnames=("cfg",))(arr, cfg)[0])
    placed = ch[: meta.n_pods]
    assert (placed >= 0).all() and len(set(placed.tolist())) == 128


def test_rounds_scan_skew_boundary_and_exhaustion():
    """Tight maxSkew=1 over unbalanced zones + capacity that exhausts
    mid-chunk: spread feasibility flips back and forth as domains fill
    (min_match rises, RELAXING earlier-infeasible nodes) and late pods go
    -1 exactly where the plain scan says."""
    nodes = []
    for i in range(10):
        # zone-0 has 6 nodes, zone-1 has 3, zone-2 has 1 — skewed domains
        z = 0 if i < 6 else (1 if i < 9 else 2)
        nodes.append(mk_node(f"n{i}", cpu=1200, pods=8,
                             labels={"topology.kubernetes.io/zone": f"zone-{z}"}))
    spread = (t.TopologySpreadConstraint(
        max_skew=1, topology_key="topology.kubernetes.io/zone",
        when_unsatisfiable=t.DO_NOT_SCHEDULE,
        label_selector=t.LabelSelector.of(app="a")),)
    pods = [mk_pod(f"p{i:04d}", cpu=300, labels={"app": "a"},
                   topology_spread=spread) for i in range(128)]
    _rounds_vs_plain(Snapshot(nodes=nodes, pending_pods=pods))


def test_rounds_scan_all_stages_on():
    """Every optional stage at once: spread + required AND preferred
    (anti-)affinity (interpod score incl. hardPodAffinityWeight symmetric
    half) + host ports + PreferNoSchedule taints + preferred node affinity
    + ImageLocality — the full normalization-scalar surface the
    interference conditions must cover."""
    rng = random.Random(99)
    nodes = []
    for i in range(24):
        taints = ()
        if i % 4 == 0:
            taints = (t.Taint(key="soft", value="x",
                              effect=t.PREFER_NO_SCHEDULE),)
        nd = mk_node(
            f"n{i:02d}", cpu=8000, pods=64,
            labels={"topology.kubernetes.io/zone": f"zone-{i % 3}",
                    "tier": rng.choice(["a", "b"])},
            taints=taints,
        )
        if i % 3 == 0:
            nd.images = {"registry/app:v1": 500 * 1024**2}
        nodes.append(nd)
    apps = ["web", "db", "cache"]
    pods = []
    for i in range(256):
        app = rng.choice(apps)
        spread = ()
        aff_kw = {}
        if i % 3 == 0:
            spread = (t.TopologySpreadConstraint(
                max_skew=1, topology_key="topology.kubernetes.io/zone",
                when_unsatisfiable=t.DO_NOT_SCHEDULE if i % 6 else t.SCHEDULE_ANYWAY,
                label_selector=t.LabelSelector.of(app=app)),)
        if i % 4 == 1:
            aff_kw["required_pod_affinity"] = (t.PodAffinityTerm(
                topology_key="topology.kubernetes.io/zone",
                label_selector=t.LabelSelector.of(app=rng.choice(apps))),)
        if i % 4 == 2:
            aff_kw["preferred_pod_affinity"] = (t.WeightedPodAffinityTerm(
                weight=rng.choice([10, 50]),
                term=t.PodAffinityTerm(
                    topology_key="topology.kubernetes.io/zone",
                    label_selector=t.LabelSelector.of(app=rng.choice(apps)))),)
        if i % 5 == 0:
            aff_kw["preferred_pod_anti_affinity"] = (t.WeightedPodAffinityTerm(
                weight=20,
                term=t.PodAffinityTerm(
                    topology_key="kubernetes.io/hostname",
                    label_selector=t.LabelSelector.of(app=app))),)
        if i % 7 == 0:
            aff_kw["preferred_node_terms"] = (t.PreferredSchedulingTerm(
                weight=rng.choice([1, 5]),
                preference=t.NodeSelectorTerm(match_expressions=(
                    t.NodeSelectorRequirement(key="tier", operator=t.OP_IN,
                                              values=("a",)),))),)
        pod = mk_pod(
            f"p{i:04d}", cpu=rng.choice([100, 250]), labels={"app": app},
            topology_spread=spread,
            affinity=t.Affinity(**aff_kw) if aff_kw else None,
            host_ports=(("TCP", 9100),) if i % 11 == 0 else (),
        )
        if i % 6 == 0:
            pod.images = ("registry/app:v1",)
        pods.append(pod)
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    arr, cfg = _rounds_vs_plain(snap)
    # the test must actually be exercising every stage
    assert cfg.enable_pairwise and cfg.enable_ports and cfg.enable_taint_score
    assert cfg.enable_node_pref and cfg.enable_image and cfg.enable_interpod_score


def test_rounds_diagnostic_and_forced_routing(monkeypatch):
    """with_rounds reports per-chunk round counts in [1, C]; with
    KTPU_FORCE_CHUNKED=1 the PRODUCTION entry point (schedule_batch_impl)
    routes a pairwise config through the rounds kernel on the CPU sim
    (round-3 verdict: the routing predicate must be testable off-TPU)."""
    from functools import partial

    import jax

    from kubernetes_tpu.ops.assign import (
        _RCHUNK,
        _rounds_routed,
        schedule_batch_impl,
        schedule_scan,
        schedule_scan_rounds,
    )
    from kubernetes_tpu.ops.scores import infer_score_config

    rng = random.Random(31)
    snap = random_cluster(rng, n_nodes=11, n_pods=256, with_taints=True,
                          with_selectors=True, with_pairwise=True)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    f = jax.jit(
        partial(schedule_scan_rounds, with_rounds=True),
        static_argnames=("cfg",),
    )
    choices, used, rounds = (np.asarray(x) for x in f(arr, cfg))
    assert rounds.shape == (arr.P // _RCHUNK,)
    assert (rounds >= 1).all() and (rounds <= _RCHUNK).all()

    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    assert _rounds_routed(arr, cfg)
    routed = np.asarray(
        jax.jit(schedule_batch_impl, static_argnames=("cfg",))(arr, cfg)[0]
    )
    plain = np.asarray(
        jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)[0]
    )
    np.testing.assert_array_equal(routed, plain)
    np.testing.assert_array_equal(choices, plain)
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "0")
    assert not _rounds_routed(arr, cfg)


@pytest.mark.parametrize("strategy,shape", [
    ("MostAllocated", ((0.0, 0.0), (100.0, 10.0))),
    ("RequestedToCapacityRatio", ((0.0, 10.0), (50.0, 2.0), (100.0, 0.0))),
])
def test_rounds_scan_fit_strategies_parity(strategy, shape):
    """The rounds kernel's base hoist + column patches + point rescores all
    dispatch on the profile's scoringStrategy; MostAllocated inverts the
    usage preference (picked nodes IMPROVE for later pods — the repair's
    rescored-beats case), RTCR interpolates a custom shape."""
    import dataclasses

    import jax

    from kubernetes_tpu.ops.assign import schedule_scan, schedule_scan_rounds
    from kubernetes_tpu.ops.scores import infer_score_config

    rng = random.Random(hash(strategy) % 997)
    snap = random_cluster(rng, n_nodes=10, n_pods=128, with_taints=True,
                          with_selectors=True, with_pairwise=True)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, dataclasses.replace(
        DEFAULT_SCORE_CONFIG, fit_strategy=strategy, rtcr_shape=shape))
    plain_c, plain_u = (
        np.asarray(x)
        for x in jax.jit(schedule_scan, static_argnames=("cfg",))(arr, cfg)
    )
    rc, ru = (
        np.asarray(x)
        for x in jax.jit(schedule_scan_rounds, static_argnames=("cfg",))(arr, cfg)
    )
    np.testing.assert_array_equal(rc, plain_c)
    np.testing.assert_array_equal(ru, plain_u)


def test_rounds_scan_in_gang_fixpoint_matches_plain(monkeypatch):
    """Gang revocation re-runs the kernel with pod_valid masks; the rounds
    path must produce the same fixpoint as the plain scan (pairwise gangs:
    spread-constrained groups contending for skew headroom)."""
    import numpy as np

    from kubernetes_tpu.ops.gang import schedule_with_gangs
    from kubernetes_tpu.ops.scores import infer_score_config

    nodes = [mk_node(f"n{i}", cpu=2000, pods=6,
                     labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
             for i in range(9)]
    spread = (t.TopologySpreadConstraint(
        max_skew=1, topology_key="topology.kubernetes.io/zone",
        when_unsatisfiable=t.DO_NOT_SCHEDULE,
        label_selector=t.LabelSelector.of(app="gang")),)
    pods, groups = [], {}
    for g in range(16):
        name = f"job{g}"
        groups[name] = t.PodGroup(name=name, min_member=8)
        for m in range(8):
            pods.append(mk_pod(f"{name}-{m}", cpu=600, labels={"app": "gang"},
                               topology_spread=spread, pod_group=name))
    snap = Snapshot(nodes=nodes, pending_pods=pods, pod_groups=groups)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)

    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    forced, _ = schedule_with_gangs(arr, cfg)
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "0")
    plain, _ = schedule_with_gangs(arr, cfg)
    np.testing.assert_array_equal(forced, plain)
    # all-or-nothing held: bound members per group are 0 or >= 8
    pg = np.asarray(arr.pod_group)
    for g in range(16):
        n = int(((pg == g) & (forced >= 0)).sum())
        assert n == 0 or n >= 8, (g, n)


def test_rounds_scan_with_pinned_and_gated_pods():
    """spec.nodeName pins and scheduling-gated (pod_valid=False) pods
    interleave a chunk: pins restrict static feasibility to one node
    (forced same-node collisions for the repair), gates must stay -1."""
    nodes = [mk_node(f"n{i}", cpu=6000, pods=30,
                     labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
             for i in range(6)]
    pods = []
    for i in range(128):
        p = mk_pod(f"p{i:03d}", cpu=100, labels={"app": "w"},
                   topology_spread=(t.TopologySpreadConstraint(
                       max_skew=2,
                       topology_key="topology.kubernetes.io/zone",
                       when_unsatisfiable=t.SCHEDULE_ANYWAY,
                       label_selector=t.LabelSelector.of(app="w")),)
                   if i % 3 == 0 else ())
        if i % 7 == 0:
            # spec.nodeName pin on a PENDING pod: static feasibility
            # narrows to one node (forced same-node collisions)
            p.node_name = f"n{i % 6}"
        if i % 11 == 0:
            p.scheduling_gates = ("hold",)
        pods.append(p)
    snap = Snapshot(nodes=nodes, pending_pods=pods)
    arr, cfg = _rounds_vs_plain(snap, check_oracle=False)
    # gated pods stayed unscheduled on both paths (checked via the plain
    # equality inside _rounds_vs_plain); sanity: at least one gate existed
    assert (np.asarray(arr.pod_valid) == False).any()  # noqa: E712
