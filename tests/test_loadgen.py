"""Open-loop load observatory (ISSUE 16, bench/loadgen.py): arrival traces
are seed-deterministic and replayable JSON, the replay clock is
coordinated-omission-safe (SLI ages stamp from the TRACE arrival time, not
the injection instant), same trace + seed twice yields bit-identical
scheduling decisions, the CLI stamps the headline SLI + per-phase p99
attribution top-level, and the regression gate refuses to compare latency
distributions across driver modes.

Tier-1 replays the rollout ramp at reduced scale; the full scale-to-zero
storm (600-pod instantaneous burst) runs under the `slow` marker."""

import json
import math
import os

import pytest

from kubernetes_tpu.bench.loadgen import (
    SCENARIOS,
    ArrivalEvent,
    ArrivalTrace,
    drain_trace,
    load_or_build_trace,
    replay_trace,
    rollout_trace,
    storm_trace,
)
from kubernetes_tpu.bench.regression import LATENCY_METRICS, check_regression
from kubernetes_tpu.scheduler.metrics import SLI_PHASES


# ------------------------------------------------- trace generation


def test_traces_are_seed_deterministic():
    """Same (scenario, seed, scale) -> identical event sequences, byte for
    byte (the replayability contract); a different seed diverges."""
    for name, fn in SCENARIOS.items():
        a = fn(seed=3, scale=0.2)
        b = fn(seed=3, scale=0.2)
        assert [e.to_json() for e in a.events] == \
               [e.to_json() for e in b.events], name
        assert a.fingerprint() == b.fingerprint(), name
        c = fn(seed=4, scale=0.2)
        assert a.fingerprint() != c.fingerprint(), name
        # chronological, named in arrival order
        ts = [e.t for e in a.events]
        assert ts == sorted(ts), name
        assert a.events, name


def test_trace_save_load_roundtrip(tmp_path):
    t1 = rollout_trace(seed=1, scale=0.2)
    path = t1.save(str(tmp_path / "trace.json"))
    t2 = ArrivalTrace.load(path)
    assert t2.fingerprint() == t1.fingerprint()
    assert [e.to_json() for e in t2.events] == [e.to_json() for e in t1.events]
    assert (t2.name, t2.scenario, t2.seed, t2.nodes) == \
           (t1.name, t1.scenario, t1.seed, t1.nodes)
    # the CLI path resolves a file spec to the same trace
    t3 = load_or_build_trace(path)
    assert t3.fingerprint() == t1.fingerprint()


def test_load_or_build_trace_rejects_unknown_spec():
    with pytest.raises(ValueError, match="not a named scenario"):
        load_or_build_trace("no-such-scenario-or-file")


def test_scenarios_have_their_bursty_shapes():
    """The three shipped scenarios are genuinely bursty, not renamed
    Poisson: the storm has one instantaneous wake-the-fleet burst, the
    rollout ramp grows geometrically, and the drain wave re-arrives at
    elevated priority after t=4."""
    storm = storm_trace(seed=0, scale=0.2)
    at_six = sum(1 for e in storm.events if e.t == 6.0)
    assert at_six == round(600 * 0.2)  # simultaneous arrivals, one instant

    rollout = rollout_trace(seed=0, scale=0.5)
    half = rollout.duration_s / 2
    early = sum(1 for e in rollout.events if e.t < half)
    late = sum(1 for e in rollout.events if e.t >= half)
    assert late > 2 * early  # geometric ramp: the back half dwarfs the front

    drain = drain_trace(seed=0, scale=0.2)
    prios = {e.priority for e in drain.events}
    assert prios == {0, 100}
    assert all(e.t >= 4.0 for e in drain.events if e.priority == 100)


# ------------------------------------------------- open-loop replay


def _tiny_trace(events, nodes=2, duration=1.0):
    return ArrivalTrace(name="tiny", scenario="tiny", seed=0, nodes=nodes,
                        duration_s=duration, events=events)


def test_replay_clock_is_coordinated_omission_safe():
    """A pod due at trace t=0.2 that the replay only injects at the 1s
    cycle boundary must age from 0.2, not from injection: the measured SLI
    carries the >=0.8s the open-loop world already waited.  (A send-time
    clock — the coordinated-omission bug — would report ~ms here.)"""
    trace = _tiny_trace([ArrivalEvent(t=0.2, name="late", cpu_m=100,
                                      mem_mb=128)])
    art, _sched = replay_trace(trace, quantum_s=1.0)
    assert art["scheduled"] == 1 and art["sli_count"] == 1
    assert art["sli_p99_ms"] >= 800.0, art["sli_p99_ms"]
    assert art["latency_mode"] == "open-loop"


def test_replay_is_decision_deterministic():
    """Same trace, same seed, two replays: identical arrival sequences and
    bit-identical scheduling decisions (the virtual-pace FakeClock makes
    backoff maturation a pure function of the cycle count)."""
    trace = rollout_trace(seed=2, scale=0.15)
    a1, _ = replay_trace(trace)
    a2, _ = replay_trace(trace)
    assert a1["trace_crc"] == a2["trace_crc"] == trace.fingerprint()
    assert a1["decision_crc"] == a2["decision_crc"]
    assert a1["scheduled"] == a2["scheduled"] > 0
    assert a1["unschedulable"] == a2["unschedulable"]


def test_replay_artifact_attribution_block():
    """The replay artifact carries the full attribution plane: headline
    SLI stamped top-level, per-phase p99 shares summing to ~1.0, a named
    dominant phase, and worst-pod exemplars with complete phase vectors."""
    art, sched = replay_trace(rollout_trace(seed=0, scale=0.15))
    assert art["sli_count"] == art["scheduled"] > 0
    assert math.isfinite(art["sli_p50_ms"]) and art["sli_p50_ms"] >= 0
    assert math.isfinite(art["sli_p99_ms"])
    assert art["sli_p99_ms"] >= art["sli_p50_ms"]
    phases = art["sli_phases"]
    assert set(phases) == set(SLI_PHASES)
    share_sum = sum(st["p99_share"] for st in phases.values())
    assert abs(share_sum - 1.0) < 1e-3, phases
    att = art["sli_attribution"]
    assert att["dominant_phase"] in SLI_PHASES
    assert att["worst_pods"], "no exemplar pods recorded"
    for w in att["worst_pods"]:
        assert set(w["phases_ms"]) == set(SLI_PHASES)
        assert w["sli_ms"] >= 0


@pytest.mark.slow
def test_storm_replay_full_scale():
    """The full scale-to-zero storm: a 600-pod instantaneous burst against
    32 nodes still drains deterministically with a sane attribution."""
    trace = storm_trace(seed=0, scale=1.0)
    a1, _ = replay_trace(trace)
    a2, _ = replay_trace(trace)
    assert a1["decision_crc"] == a2["decision_crc"]
    assert a1["scheduled"] == a1["pods"] and a1["unschedulable"] == 0
    share_sum = sum(st["p99_share"] for st in a1["sli_phases"].values())
    assert abs(share_sum - 1.0) < 1e-3


# ------------------------------------------------- CLI acceptance


def test_cli_open_loop_stamps_artifact_and_exports(tmp_path, monkeypatch,
                                                   capsys):
    """THE acceptance path: `--open-loop rollout --sli-attribution` writes
    an artifact with the headline SLI top-level, shares summing to ~1.0,
    the replayable arrival trace next to it, and a Perfetto exemplar
    export of the worst pods' span timelines."""
    from kubernetes_tpu.bench import harness

    monkeypatch.setenv("KTPU_OPEN_LOOP_SCALE", "0.15")
    out_path = tmp_path / "OL.json"
    harness.main(["--open-loop", "rollout", "--sli-attribution",
                  "--out", str(out_path)])
    captured = capsys.readouterr()
    assert "dominant phase:" in captured.err  # the human table, on stderr

    art = json.loads(out_path.read_text())
    assert art["latency_mode"] == "open-loop"
    assert art["sli_count"] > 0
    assert math.isfinite(art["sli_p50_ms"]) and math.isfinite(art["sli_p99_ms"])
    share_sum = sum(st["p99_share"] for st in art["sli_phases"].values())
    assert abs(share_sum - 1.0) < 1e-3

    # the generated trace saved next to the artifact replays the EXACT run
    trace_path = art["trace_path"]
    assert os.path.dirname(trace_path) == str(tmp_path)
    assert ArrivalTrace.load(trace_path).fingerprint() == art["trace_crc"]

    # the exemplar export is a loadable chrome trace with real span events
    exemplar = art["sli_attribution"]["exemplar_export"]
    assert exemplar and os.path.exists(exemplar)
    doc = json.loads(open(exemplar).read())
    assert doc["otherData"]["exemplar_pods"]
    assert doc["otherData"]["exemplar_spans"] > 0
    assert any(ev.get("ph") != "M" for ev in doc["traceEvents"])


# ------------------------------------------------- regression gating


def _rec(latency_mode, **fields):
    rec = {"platform": "cpu-sim-fallback"}
    if latency_mode is not None:
        rec["latency_mode"] = latency_mode
    rec.update(fields)
    return rec


def test_regression_gate_never_compares_latency_across_driver_modes():
    """Satellite: a batch p99 (per-wave wall) must never gate an open-loop
    p99 — the gate skips cross-mode priors for latency metrics, still
    gates same-mode priors, and ignores latency_mode entirely for
    non-latency metrics like step_s."""
    assert "sli_p99_ms" in LATENCY_METRICS
    cur = ("r3.json", _rec("open-loop", sli_p99_ms=500.0))
    batch_prior = ("r1.json", _rec("batch", sli_p99_ms=5.0))
    ol_prior = ("r2.json", _rec("open-loop", sli_p99_ms=480.0))

    # batch prior skipped, open-loop prior gates: 500 vs 480 is within 10%
    v = check_regression([batch_prior, ol_prior, cur], cur,
                         metric="sli_p99_ms")
    assert v["status"] == "pass"
    assert v["best_prior"] == "r2.json"
    assert any("latency_mode" in s for s in v["skipped"])

    # only a cross-mode prior: no comparable prior at all -> pass
    v2 = check_regression([batch_prior, cur], cur, metric="sli_p99_ms")
    assert v2["status"] == "pass" and "no comparable" in v2["reason"]

    # a real same-mode regression still fails
    bad = ("r4.json", _rec("open-loop", sli_p99_ms=1000.0))
    v3 = check_regression([batch_prior, ol_prior, bad], bad,
                          metric="sli_p99_ms")
    assert v3["status"] == "regression"

    # non-latency metrics compare across modes (old artifacts predate the
    # latency_mode stamp and must keep gating step_s)
    old = ("r0.json", {"platform": "cpu-sim-fallback", "step_s": 1.0})
    cur_s = ("r5.json", _rec("open-loop", step_s=1.05))
    v4 = check_regression([old, cur_s], cur_s, metric="step_s")
    assert v4["status"] == "pass" and v4["best_prior"] == "r0.json"


def test_regression_gate_never_compares_across_shard_topologies():
    """An 8-device sim run timeshares one core (step_s ~8x a single-device
    run of the same kernel) and its per-shard metrics are divided by the
    grid — so n_shards joins platform in the comparability key, in BOTH
    directions.  Artifacts that predate the n_shards stamp were all
    single-device and must keep gating each other."""
    single = ("r8.json", _rec(None, step_s=2.6, n_shards=1,
                              per_shard_hbm_bytes=400_000_000))
    mesh = ("r9.json", _rec(None, step_s=29.0, n_shards=8,
                            mesh_shape=[2, 4],
                            per_shard_hbm_bytes=126_000_000))

    # the mesh run's 8x sim wall is a config change, not a regression
    v = check_regression([single, mesh], mesh, metric="step_s")
    assert v["status"] == "pass" and "no comparable" in v["reason"]
    assert any("n_shards" in s for s in v["skipped"])

    # and the mesh run's divided per-shard HBM never becomes the bar a
    # later single-device run is judged against
    nxt = ("r10.json", _rec(None, step_s=2.7, n_shards=1,
                            per_shard_hbm_bytes=401_000_000))
    v2 = check_regression([single, mesh, nxt], nxt,
                          metric="per_shard_hbm_bytes")
    assert v2["status"] == "pass" and v2["best_prior"] == "r8.json"

    # pre-mesh artifacts (no n_shards stamp) normalize to 1 and still gate
    old = ("r0.json", {"platform": "cpu-sim-fallback", "step_s": 1.0})
    v3 = check_regression([old, nxt], nxt, metric="step_s")
    assert v3["status"] == "regression" and v3["best_prior"] == "r0.json"

    # same-topology mesh runs gate each other
    worse = ("r11.json", _rec(None, step_s=40.0, n_shards=8))
    v4 = check_regression([mesh, worse], worse, metric="step_s")
    assert v4["status"] == "regression" and v4["best_prior"] == "r9.json"
