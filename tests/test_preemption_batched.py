"""Batched preemption (ops/preempt.py + scheduler/preemption.py) must be
DECISION-IDENTICAL to the CPU DefaultPreemption evaluator (the oracle) within
its gate: same Preempted nominations, same evicted victims, same surviving
pods — across randomized priority workloads with PDBs.
reference: framework/preemption/preemption.go — Evaluator;
defaultpreemption/default_preemption.go — SelectVictimsOnNode."""

import random

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from helpers import mk_node, mk_pod


def _run(seed: int, batched: bool, with_pdb: bool = False, pairwise: bool = False):
    """One preemption scenario; returns (preempted events, survivors,
    nominations, scheduled)."""
    rng = random.Random(seed)
    store = ClusterStore()
    n_nodes = rng.randint(3, 6)
    for i in range(n_nodes):
        store.add_node(mk_node(f"n{i}", cpu=2000, pods=8))
    gates = () if batched else (("BatchedPreemption", False),)
    sched = Scheduler(
        store, SchedulerConfiguration(mode="tpu", feature_gates=gates)
    )
    # fill with low-priority victims (bound)
    n_low = rng.randint(4, 10)
    for i in range(n_low):
        labels = {"app": rng.choice(["web", "db"])}
        store.add_pod(
            mk_pod(
                f"low{i}",
                cpu=rng.choice([300, 500, 800]),
                priority=rng.choice([0, 5]),
                node_name=f"n{rng.randrange(n_nodes)}",
                labels=labels,
            )
        )
    if with_pdb:
        pdb = t.PodDisruptionBudget(
            name="web-pdb",
            selector=t.LabelSelector.of(app="web"),
            disruptions_allowed=rng.choice([0, 1]),
        )
        store.pdbs[pdb.key] = pdb
    # high-priority preemptors that exceed free capacity
    n_hi = rng.randint(2, 4)
    for i in range(n_hi):
        kw = {}
        if pairwise:
            kw["affinity"] = t.Affinity(
                required_pod_anti_affinity=(
                    t.PodAffinityTerm(
                        topology_key=t.LABEL_HOSTNAME,
                        label_selector=t.LabelSelector.of(app="hi"),
                    ),
                ),
            )
        store.add_pod(
            mk_pod(f"hi{i}", cpu=1800, priority=100, labels={"app": "hi"}, **kw)
        )
    sched.run_until_idle()
    preempted = sorted((e.pod, e.node) for e in sched.events.by_reason("Preempted"))
    survivors = sorted(store.pods.keys())
    nominations = sorted(
        (uid, node) for uid, (_, node) in sched.queue.nominated.items()
    )
    scheduled = sorted(
        (e.pod, e.node) for e in sched.events.by_reason("Scheduled")
    )
    return preempted, survivors, nominations, scheduled


@pytest.mark.parametrize("seed", range(8))
def test_batched_preemption_matches_cpu_evaluator(seed):
    assert _run(seed, batched=True) == _run(seed, batched=False)


@pytest.mark.parametrize("seed", range(4))
def test_batched_preemption_matches_cpu_with_pdbs(seed):
    got = _run(seed, batched=True, with_pdb=True)
    want = _run(seed, batched=False, with_pdb=True)
    assert got == want


@pytest.mark.parametrize("seed", range(3))
def test_pairwise_preemptors_fall_back_and_still_match(seed):
    """Anti-affinity on the preemptor gates the batched path off — outcomes
    must still equal the CPU evaluator (same code path, by construction)."""
    got = _run(seed, batched=True, pairwise=True)
    want = _run(seed, batched=False, pairwise=True)
    assert got == want


def test_batched_preemption_actually_engages():
    """The batched path must really run (not silently fall back) for a plain
    priority workload: verify via the gate predicate itself."""
    from kubernetes_tpu.scheduler.preemption import BatchedPreemption

    store = ClusterStore()
    for i in range(3):
        store.add_node(mk_node(f"n{i}", cpu=2000))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for i in range(4):
        store.add_pod(mk_pod(f"low{i}", cpu=900, node_name=f"n{i % 3}"))
    hi = mk_pod("hi", cpu=1800, priority=50)
    store.add_pod(hi)
    sched.run_until_idle()
    assert sched.events.by_reason("Preempted"), "no preemption happened"
    # the preemption_victims counter is bumped ONLY by the batched branch:
    # proves the device path ran rather than silently falling back
    assert sched.metrics.counters["preemption_victims"] > 0
    # gate predicate holds for this pod shape
    from kubernetes_tpu.api.volumes import resolve_snapshot

    snap2 = resolve_snapshot(sched.cache.update_snapshot())
    arr, meta = sched._delta_enc.encode(snap2)
    bp = BatchedPreemption(arr, meta, snap2, store, sched.queue)
    probe = mk_pod("probe", cpu=1800, priority=50)
    snap2.pending_pods.append(probe)
    arr, meta = sched._delta_enc.encode(snap2)
    bp = BatchedPreemption(arr, meta, snap2, store, sched.queue)
    assert bp.applicable(probe)


def test_wave_path_serves_multiple_preemptors_and_repairs_dirty_nodes():
    """evaluate-many: several same-priority preemptors are served from ONE
    device wave; later members' decisions must account for earlier members'
    evictions + nominations (host repair of dirtied nodes), giving exactly
    the sequential single-eval decisions."""
    from kubernetes_tpu.scheduler import preemption as pre_mod

    instances = []
    orig_init = pre_mod.BatchedPreemption.__init__

    def spy_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        instances.append(self)

    pre_mod.BatchedPreemption.__init__ = spy_init
    try:
        store = ClusterStore()
        # 2 nodes, each full with one evictable low pod; 4 preemptors of
        # one priority -> two preempt (one per node), the other two find
        # nothing ONLY IF they see the earlier nominations (dirty repair)
        for i in range(2):
            store.add_node(mk_node(f"n{i}", cpu=2000, pods=8))
            store.add_pod(mk_pod(f"low{i}", cpu=1800, node_name=f"n{i}"))
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
        for k in range(4):
            store.add_pod(mk_pod(f"hi{k}", cpu=1800, priority=50))
        sched.run_until_idle()
    finally:
        pre_mod.BatchedPreemption.__init__ = orig_init
    preempted = sorted(e.pod for e in sched.events.by_reason("Preempted"))
    nominated = sorted(
        p.uid for p in store.pods.values() if p.nominated_node_name
    )
    survivors = sorted(u for u in store.pods if u.startswith("default/low"))
    assert len(preempted) == 2 and len(nominated) == 2
    assert survivors == []
    # the wave path really served every evaluation that ran (hi2/hi3
    # short-circuit before evaluate(): after both evictions no bound pod
    # outranks them, the loop's min_bound_prio gate); no silent fallback
    assert sum(b.wave_hits for b in instances) >= 2
    assert sum(b.single_hits for b in instances) == 0
