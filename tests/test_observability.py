"""Observability plane (ISSUE 6): streaming histograms + arrival->bind SLI,
cycle attribution engine, Prometheus exposition, regression gate, trace
completeness, and the run-start reset discipline."""

import json
import math
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.attribution import (
    attribute_spans,
    render_attribution,
)
from kubernetes_tpu.scheduler.metrics import (
    DEFAULT_BUCKET_BOUNDS,
    SLI_PHASES,
    Metrics,
    StreamingHist,
    reset_run_state,
)
from kubernetes_tpu.scheduler.tracing import Span, TraceCollector, Tracer

from helpers import mk_node, mk_pod


# ------------------------------------------------- streaming histograms


def test_streaming_hist_bounded_memory_at_1e6_samples():
    """O(buckets), not O(samples): a million observations must not grow the
    histogram's storage at all (the old _Hist kept every sample forever)."""
    h = StreamingHist()
    shape_before = (len(h.counts), len(h.bounds))
    assert not hasattr(h, "samples")  # the unbounded list is gone
    rng = np.random.default_rng(0)
    h.observe_many(rng.lognormal(mean=-3.0, sigma=2.0, size=1_000_000))
    assert h.count == 1_000_000
    assert (len(h.counts), len(h.bounds)) == shape_before
    # a further million changes nothing structural either
    h.observe_many(rng.lognormal(mean=-3.0, sigma=2.0, size=1_000_000))
    assert h.count == 2_000_000
    assert (len(h.counts), len(h.bounds)) == shape_before


def test_streaming_hist_quantiles_within_bucket_resolution():
    """p50/p99 within one factor-2 bucket of the exact sample quantile
    (PARITY.md error bound), exact clamp at the envelope."""
    rng = np.random.default_rng(7)
    vals = rng.uniform(1e-4, 2.0, size=20_000)
    h = StreamingHist()
    h.observe_many(vals)
    for q in (0.5, 0.9, 0.99):
        exact = float(np.quantile(vals, q))
        est = h.quantile(q)
        assert exact / 2.0 <= est <= exact * 2.0, (q, exact, est)
    assert h.quantile(1.0) == pytest.approx(vals.max())
    # single sample: every quantile is that sample (envelope clamp)
    h1 = StreamingHist()
    h1.observe(0.37)
    assert h1.quantile(0.5) == pytest.approx(0.37)
    assert h1.quantile(0.99) == pytest.approx(0.37)


def test_streaming_hist_observe_n_and_merge():
    a = StreamingHist()
    a.observe(0.1, n=500)  # a whole wave of identical per-pod samples
    b = StreamingHist()
    b.observe_many([0.2] * 100)
    a.merge(b)
    assert a.count == 600
    assert a.sum == pytest.approx(0.1 * 500 + 0.2 * 100)
    assert a.quantile(0.5) == pytest.approx(0.1, rel=0.5)
    assert a.max == pytest.approx(0.2)
    with pytest.raises(ValueError):
        a.merge(StreamingHist(bounds=DEFAULT_BUCKET_BOUNDS[:5]))


def test_snapshot_reads_hist_stats_atomically_under_concurrency():
    """Satellite: snapshot() must never tear (count vs quantiles) against a
    concurrent observe_many — the triple is read under the per-hist lock
    (StreamingHist.stats)."""
    m = Metrics()
    stop = threading.Event()
    errors = []

    def hammer():
        vals = np.full(1000, 0.25)
        while not stop.is_set():
            m.observe_many("h", vals)

    def scrape():
        last = 0
        try:
            while not stop.is_set():
                _, _, hists = m.snapshot()
                if "h" not in hists:
                    continue
                p50, p99, count = hists["h"]
                assert count % 1000 == 0, "torn count mid-observe_many"
                assert count >= last
                last = count
                if count:
                    assert p50 == pytest.approx(0.25) and p99 == pytest.approx(0.25)
        except Exception as e:  # noqa: BLE001 — surface to the main thread
            errors.append(e)

    threads = [threading.Thread(target=hammer), threading.Thread(target=scrape)]
    for th in threads:
        th.start()
    import time

    time.sleep(0.3)
    stop.set()
    for th in threads:
        th.join()
    assert not errors, errors


def test_streaming_hist_stats_never_tear_under_observe_many_hammer():
    """Satellite: hammer ONE StreamingHist's observe_many from several
    threads (the open-loop phase hists take concurrent waves from the
    binding-cycle pool) while the main thread reads stats() — every
    (p50, p99, count) triple must be internally consistent: count lands on
    a whole batch multiple, count is monotone, and once samples exist the
    quantiles straddle the bimodal input (a torn read — counts merged but
    not yet all buckets — would surface as an impossible triple)."""
    h = StreamingHist()
    stop = threading.Event()
    batch = [1e-3] * 600 + [4.0] * 400  # p50 in the ms mode, p99 in the s mode

    def hammer():
        while not stop.is_set():
            h.observe_many(batch)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for th in threads:
        th.start()
    import time

    last = 0
    reads = 0
    deadline = time.monotonic() + 0.3
    try:
        while time.monotonic() < deadline:
            p50, p99, count = h.stats()
            assert count % 1000 == 0, "torn count mid-observe_many merge"
            assert count >= last, "count went backwards"
            last = count
            if count:
                assert p50 <= 0.01, f"p50 {p50} escaped the 1ms mode"
                assert p99 >= 1.0, f"p99 {p99} lost the 4s mode"
                reads += 1
    finally:
        stop.set()
        for th in threads:
            th.join()
    assert reads > 0 and last > 0  # the race actually ran


# ------------------------------------------------- arrival -> bind SLI


def _cluster(mode="tpu", nodes=4, collector=None):
    store = ClusterStore()
    for i in range(nodes):
        # pods=1024: the default 110-pod node cap would strand most of the
        # 2000-pod consistency wave unbound (no bind -> no SLI sample)
        store.add_node(mk_node(f"n{i}", cpu=32000, mem=64 * 2**30, pods=1024))
    sched = Scheduler(store, SchedulerConfiguration(mode=mode),
                      collector=collector or TraceCollector(enabled=False))
    return store, sched


def test_sli_recorded_per_bound_pod_batch_mode():
    store, sched = _cluster("tpu")
    for i in range(30):
        store.add_pod(mk_pod(f"p{i}", cpu=100))
    sched.run_until_idle()
    h = sched.metrics.hists["pod_scheduling_sli_duration_seconds"]
    assert h.count == 30  # one TRUE arrival->bind sample per bound pod
    assert 0 < h.quantile(0.5) <= h.quantile(0.99)
    # consumed at publication: the arrival table does not leak
    assert sched.queue._arrival_at == {}


def test_sli_recorded_cpu_mode_and_appears_in_perfdata():
    from kubernetes_tpu.bench.harness import run_yaml

    text = """
name: T
ops:
  - {op: createCluster, generator: basic, nodes: 12, pods: 24}
  - {op: measure}
"""
    for mode in ("tpu", "cpu"):
        out = run_yaml(text, mode)[0]
        assert out.sli_count == 24, (mode, out)
        assert 0 < out.sli_p50_ms <= out.sli_p99_ms


def test_sli_consistency_with_kernel_ordinal_estimates():
    """Satellite: the host-measured arrival->bind SLI must be consistent
    with the kernel's per-pod finish-ordinal estimate (ops/assign.py
    ordinal path -> Metrics.observe_many): per pod the estimate (a fraction
    of the kernel wall) can never exceed the true SLI (which spans the
    whole kernel plus queue/encode/commit overheads), the big wave's pods
    own the tail of BOTH distributions, and hist-level p99s agree within
    the documented resolution."""
    col = TraceCollector()
    store, sched = _cluster("tpu", nodes=6, collector=col)

    # warm the jit cache on both bucketed shapes so compile time doesn't
    # distort the first wave's kernel wall
    wstore, wsched = _cluster("tpu", nodes=6)
    for i in range(20):
        wstore.add_pod(mk_pod(f"w{i}", cpu=10))
    wsched.run_until_idle()
    wstore2, wsched2 = _cluster("tpu", nodes=6)
    for i in range(2000):
        wstore2.add_pod(mk_pod(f"v{i}", cpu=10))
    wsched2.run_until_idle()

    est_all = {}
    sli_all = {}
    # ~100x work contrast between the waves: the ordering signal must be
    # STRUCTURAL (the big wave's kernel sweeps dwarf the small wave's), not
    # a wall-clock coin flip an OS scheduling hiccup could invert
    waves = {"small": 20, "big": 2000}
    for wname, n in waves.items():
        for i in range(n):
            store.add_pod(mk_pod(f"{wname}-{i}", cpu=10))
        sched.run_until_idle()
        # both dicts are per-wave (bounded): accumulate per run
        est_all.update(sched.last_wave_estimates)
        sli_all.update(sched.last_wave_sli)

    # same pods, both sources
    assert set(est_all) == set(sli_all)
    assert len(est_all) == sum(waves.values())
    # per-pod domination: ordinal estimate <= true arrival->bind
    for uid, est in est_all.items():
        assert est <= sli_all[uid] + 1e-6, (uid, est, sli_all[uid])
    # same pods in the tail: the top decile of EITHER ordering is made of
    # big-wave pods (>=95% — a rare host stall inside the small wave's
    # tiny kernel window may strand a couple of strays)
    k = len(est_all) // 10
    tail_est = sorted(est_all, key=est_all.get)[-k:]
    tail_sli = sorted(sli_all, key=sli_all.get)[-k:]
    big_est = sum(u.split("/")[-1].startswith("big") for u in tail_est)
    big_sli = sum(u.split("/")[-1].startswith("big") for u in tail_sli)
    assert big_est >= 0.95 * k, (big_est, k)
    assert big_sli >= 0.95 * k, (big_sli, k)
    # hist-level p99 consistency within the streaming-bucket resolution
    p99_est = sched.metrics.hists[
        "scheduling_attempt_duration_estimate_seconds"].quantile(0.99)
    p99_sli = sched.metrics.hists[
        "pod_scheduling_sli_duration_seconds"].quantile(0.99)
    assert p99_est <= p99_sli * 2.0 + 1e-6


def test_pipeline_loop_records_wave_sli():
    from kubernetes_tpu.bench.workloads import heterogeneous
    from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop

    m = Metrics()
    waves = [heterogeneous(8, 20, seed=s) for s in range(3)]
    loop = PipelinedBatchLoop(metrics=m)
    for _ in loop.run(waves):
        pass
    h = m.hists["pod_scheduling_sli_duration_seconds"]
    assert h.count == sum(len(w.pending_pods) for w in waves)


# ------------------------------------- per-pod SLI phase decomposition


def test_sli_phase_decomposition_telescopes_to_sli_batch_mode():
    """The four pod_sli_phase_duration_seconds components (queue_wait,
    wave_wait, device_kernel, bind) telescope EXACTLY to the arrival->bind
    SLI on the batch path: one sample per phase per bound pod, and the
    phase sums add up to the SLI sum — the monotone clamp redistributes
    time between phases but never invents or drops any."""
    col = TraceCollector()
    store, sched = _cluster("tpu", collector=col)
    for i in range(25):
        store.add_pod(mk_pod(f"ph{i}", cpu=100))
    sched.run_until_idle()
    sli = sched.metrics.hists["pod_scheduling_sli_duration_seconds"]
    assert sli.count == 25
    total = 0.0
    for ph in SLI_PHASES:
        h = sched.metrics.labeled_hist(
            "pod_sli_phase_duration_seconds", phase=ph)
        assert h.count == 25, ph
        total += h.sum
    assert total == pytest.approx(sli.sum, rel=1e-6, abs=1e-6)
    # consumed at publication like the arrival table: no leak
    assert sched.queue._popped_at == {}
    # the flight recorder's per-wave block saw the same pods
    worst = sched.worst_sli_pods()
    assert worst and all(set(w["phases_ms"]) == set(SLI_PHASES)
                         for w in worst)


def test_pipeline_loop_records_wave_phase_decomposition():
    """The pipelined loop publishes the same labeled phase hists with its
    wave-uniform decomposition: every bound pod contributes one sample per
    phase, and queue_wait is identically zero (a pipelined wave is
    dispatched whole — pods never sit in a per-pod queue)."""
    from kubernetes_tpu.bench.workloads import heterogeneous
    from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop

    m = Metrics()
    waves = [heterogeneous(8, 20, seed=s) for s in range(3)]
    loop = PipelinedBatchLoop(metrics=m)
    for _ in loop.run(waves):
        pass
    n = m.hists["pod_scheduling_sli_duration_seconds"].count
    assert n == sum(len(w.pending_pods) for w in waves)
    for ph in SLI_PHASES:
        h = m.labeled_hist("pod_sli_phase_duration_seconds", phase=ph)
        assert h.count == n, ph
    qw = m.labeled_hist("pod_sli_phase_duration_seconds", phase="queue_wait")
    assert qw.sum == 0.0


# ------------------------------------------------- cycle attribution


def _span(name, start, end, component="x", **attrs):
    s = Span(name, component=component, start=start, attributes=attrs or None)
    s.finish(end)
    return s


def test_attribution_fractions_sum_to_one_and_name_dominant_phase():
    # two synthetic pipelined cycles: encode hidden under the device step,
    # commit sticking out, a gap of idle wall
    spans = [
        _span("device.step", 0.0, 1.0, wave=0),
        _span("encode_overlap", 0.1, 0.4),   # fully hidden -> device owns it
        _span("commit_overlap", 1.0, 1.2),   # sticks out -> bind_commit
        _span("device.step", 1.5, 2.5, wave=1),  # 0.3 of idle gap before
        _span("hoist.update", 1.25, 1.35),
    ]
    rep = attribute_spans(spans, spans_dropped=0)
    assert rep["n_cycles"] == 2 and rep["complete"]
    total = sum(d["fraction"] for d in rep["phases"].values())
    assert total == pytest.approx(1.0, abs=0.01)
    ph = {p: d["seconds"] for p, d in rep["phases"].items()}
    assert ph["device_kernel"] == pytest.approx(2.0, abs=1e-6)
    assert ph["bind_commit"] == pytest.approx(0.2, abs=1e-6)
    assert ph["hoist_update"] == pytest.approx(0.1, abs=1e-6)
    assert ph["host_encode"] == 0.0  # hidden under the step: costs no wall
    assert ph["unattributed"] == pytest.approx(0.2, abs=1e-6)
    assert rep["dominant_phase"] == "device_kernel"
    table = render_attribution(rep)
    assert "device_kernel" in table and "dominant" in table


def test_attribution_flags_incomplete_traces():
    spans = [_span("device.step", 0.0, 1.0)]
    rep = attribute_spans(spans, spans_dropped=5)
    assert rep["complete"] is False and rep["spans_dropped"] == 5
    assert "INCOMPLETE" in render_attribution(rep)


def test_attribution_from_streaming_harness():
    """bench.harness --stream --attribution shape: report embedded next to
    route_trace_counts, fractions summing to ~1.0 of cycle wall, device
    kernel dominant (the acceptance criterion at smoke scale)."""
    from kubernetes_tpu.bench.harness import run_streaming_workload
    from kubernetes_tpu.bench.workloads import heterogeneous

    col = TraceCollector()
    waves = [heterogeneous(40, 300, seed=s) for s in range(3)]
    out = run_streaming_workload("t", waves, collector=col)
    rep = out["attribution"]
    assert rep["n_cycles"] == 3
    assert sum(d["fraction"] for d in rep["phases"].values()) == pytest.approx(
        1.0, abs=0.01
    )
    assert rep["dominant_phase"] == "device_kernel"
    assert out["sli_count"] == out["n_pods"]
    for c in rep["cycles"]:
        assert sum(d["fraction"] for d in c["phases"].values()) == pytest.approx(
            1.0, abs=0.01
        )


def test_attribution_no_pipeline_streaming():
    """--no-pipeline runs still emit the attribution report and SLI (the
    serial loop is the traced+metered run when there is no pipelined
    pass)."""
    from kubernetes_tpu.bench.harness import run_streaming_workload
    from kubernetes_tpu.bench.workloads import heterogeneous

    col = TraceCollector()
    waves = [heterogeneous(20, 100, seed=s) for s in range(2)]
    out = run_streaming_workload("t", waves, pipeline=False, collector=col)
    assert out["pipelined_s"] is None  # the serial-only escape hatch
    rep = out["attribution"]
    assert rep["n_cycles"] == 2
    ph = {p: d["seconds"] for p, d in rep["phases"].items()}
    # at toy scale the serial host encode may out-weigh the trivial kernel;
    # what matters is that BOTH phases were captured and fractions close
    assert ph["device_kernel"] > 0 and ph["host_encode"] > 0
    assert sum(d["fraction"] for d in rep["phases"].values()) == pytest.approx(
        1.0, abs=0.01
    )
    assert out["sli_count"] == out["n_pods"]


def test_attribution_scheduler_cycle_spans():
    """Scheduler-driven runs anchor on batch.cycle and attribute the
    encode/kernel/commit split."""
    col = TraceCollector()
    store, sched = _cluster("tpu", collector=col)
    for i in range(40):
        store.add_pod(mk_pod(f"p{i}", cpu=50))
    sched.run_until_idle()
    rep = attribute_spans(col)
    assert rep["n_cycles"] >= 1
    assert rep["cycles"][0]["anchor"] == "batch.cycle"
    ph = {p: d["seconds"] for p, d in rep["phases"].items()}
    assert ph["device_kernel"] > 0
    assert ph["host_encode"] > 0 or ph["bind_commit"] > 0


# ------------------------------------------------- trace completeness


def test_collector_counts_dropped_spans_and_reports_in_export():
    col = TraceCollector(capacity=4)
    tr = Tracer(col, component="t")
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert col.spans_dropped == 6
    doc = col.chrome_trace()
    assert doc["otherData"]["spans_dropped"] == 6
    assert doc["otherData"]["capacity"] == 4
    rep = attribute_spans(col)
    assert rep["complete"] is False
    col.clear()
    assert col.spans_dropped == 0


def test_chrome_trace_roundtrips_as_valid_perfetto_json(tmp_path):
    """CI guard: export_chrome_trace output must re-load as valid JSON with
    the required ph/ts/dur fields on every complete event."""
    col = TraceCollector()
    tr = Tracer(col, component="bench")
    with tr.span("outer", pods=3) as sp:
        sp.add_event("marker", k="v")
        with tr.span("inner"):
            pass
    path = col.export_chrome_trace(str(tmp_path / "t.trace.json"))
    doc = json.loads(open(path).read())
    events = doc["traceEvents"]
    assert events, "no events exported"
    for ev in events:
        assert "ph" in ev and "pid" in ev and "name" in ev
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert "tid" in ev
        elif ev["ph"] == "i":
            assert isinstance(ev["ts"], (int, float))
    assert {e["ph"] for e in events} >= {"X", "M"}
    assert doc["otherData"]["spans_dropped"] == 0


# ------------------------------------------------- /metrics exposition


def _parse_prom(text):
    """Minimal Prometheus text-format validator: returns {name: value} for
    samples; raises on malformed lines."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line.startswith("#"):
                assert line.startswith("# TYPE "), line
            continue
        name_part, _, value = line.rpartition(" ")
        assert name_part and value, line
        float("inf" if value == "+Inf" else value)  # numeric
        samples[name_part] = value
    return samples


def test_apiserver_metrics_route_serves_full_registry():
    m = Metrics()
    m.inc("queue_incoming_pods_total", 42)
    m.set("pending_pods", 7)
    m.observe("pod_scheduling_sli_duration_seconds", 0.012)
    m.observe("pod_scheduling_sli_duration_seconds", 0.5)
    m.observe_labeled(
        "framework_extension_point_duration_seconds", 0.001,
        extension_point="Filter", plugin="NodeResourcesFit",
    )
    m.inc_labeled("framework_fault_injected_total", site="sidecar.rpc",
                  action="drop")
    from kubernetes_tpu.scheduler.apiserver import APIServer

    api = APIServer(ClusterStore(), metrics=m)
    port = api.serve_metrics(0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
    finally:
        api.stop_metrics()
    samples = _parse_prom(body)
    # counters, gauges, labeled series, histogram buckets — all present
    assert samples["queue_incoming_pods_total"] == "42"
    assert samples["pending_pods"] == "7"
    assert samples[
        'framework_fault_injected_total{action="drop",site="sidecar.rpc"}'
    ] == "1"
    assert samples["pod_scheduling_sli_duration_seconds_count"] == "2"
    assert (
        'framework_extension_point_duration_seconds_bucket'
        '{extension_point="Filter",plugin="NodeResourcesFit",le="+Inf"}'
        in samples
    )
    # bucket series: cumulative, monotone, +Inf == count
    buckets = [
        (k, int(v)) for k, v in samples.items()
        if k.startswith("pod_scheduling_sli_duration_seconds_bucket")
    ]
    counts = [c for _, c in buckets]
    assert counts == sorted(counts) and counts[-1] == 2
    assert any('le="+Inf"' in k for k, _ in buckets)


# ------------------------------------------------- regression gate


def _bench_rec(step_s, platform="cpu-sim-fallback", wrapper=False, **extra):
    rec = {
        "metric": "north_star_50kpods_20knodes_throughput",
        "value": 1000.0, "unit": "pods/s", "platform": platform,
        "step_s": step_s, **extra,
    }
    return {"n": 1, "rc": 0, "parsed": rec} if wrapper else rec


def test_regression_gate_pass_regress_missing_field(tmp_path):
    d = tmp_path
    (d / "BENCH_r01.json").write_text(
        json.dumps(_bench_rec(2.0, platform="tpu-v5", wrapper=True))
    )
    (d / "BENCH_r02.json").write_text(json.dumps(_bench_rec(10.0)))
    (d / "BENCH_r03.json").write_text(json.dumps(_bench_rec(8.0, wrapper=True)))
    from kubernetes_tpu.bench import regression

    # improvement on the same box -> pass (the tpu-v5 run is another box
    # and must be skipped, not compared)
    (d / "BENCH_r04.json").write_text(json.dumps(_bench_rec(7.0)))
    assert regression.main(["--dir", str(d)]) == 0
    # injected 20% step-time regression vs best prior (7.0 -> 9.6) -> fail
    (d / "BENCH_r05.json").write_text(json.dumps(_bench_rec(8.4)))
    assert regression.main(["--dir", str(d)]) == 1
    # within threshold (7.0 -> 7.3 is < 10%) -> pass
    (d / "BENCH_r05.json").write_text(json.dumps(_bench_rec(7.3)))
    assert regression.main(["--dir", str(d)]) == 0
    # current run missing the metric -> distinct error exit
    rec = _bench_rec(7.0)
    del rec["step_s"]
    (d / "BENCH_r06.json").write_text(json.dumps(rec))
    assert regression.main(["--dir", str(d)]) == 2
    # PRIOR runs missing the metric are skipped, never failed on
    (d / "BENCH_r06.json").write_text(json.dumps(_bench_rec(6.9)))
    assert regression.main(["--dir", str(d)]) == 0
    # higher-is-better mode gates on throughput
    (d / "BENCH_r07.json").write_text(
        json.dumps(_bench_rec(6.9, value=100.0))
    )
    assert regression.main(
        ["--dir", str(d), "--metric", "value", "--higher-is-better"]
    ) == 1


def test_regression_gate_natural_trajectory_order(tmp_path):
    """Digit-aware ordering: r100 is newer than r99 (lexicographic sort
    would pick r99 as the gate's 'newest' candidate)."""
    d = tmp_path
    (d / "BENCH_r99.json").write_text(json.dumps(_bench_rec(5.0)))
    (d / "BENCH_r100.json").write_text(json.dumps(_bench_rec(9.0)))
    from kubernetes_tpu.bench import regression

    traj = regression.load_trajectory(str(d), "BENCH_r[0-9]*.json")
    assert [n for n, _ in traj] == ["BENCH_r99.json", "BENCH_r100.json"]
    # r100 (9.0) regressed 80% vs r99 (5.0): the gate must judge r100
    assert regression.main(["--dir", str(d)]) == 1


def test_regression_gate_real_trajectory_passes():
    """The repo's own BENCH_r01–r06 trajectory must gate green (r06 is the
    best cpu-sim step so far; the real-TPU rounds are another box)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    from kubernetes_tpu.bench import regression

    assert regression.main(["--dir", repo]) == 0


# ------------------------------------------------- reset discipline


def test_reset_run_state_clears_metrics_traces_and_counters():
    from kubernetes_tpu.ops.assign import TRACE_COUNTS

    m = Metrics()
    m.inc("c")
    m.observe("pod_scheduling_sli_duration_seconds", 0.1)
    m.observe_labeled("lh", 0.2, a="b")
    col = TraceCollector(capacity=1)
    tr = Tracer(col, "t")
    with tr.span("s1"):
        pass
    with tr.span("s2"):
        pass
    TRACE_COUNTS["plain"] += 3
    assert col.spans_dropped == 1
    # handle cached BEFORE the reset (the Scheduler._sli_hist pattern)
    cached = m.hist("pod_scheduling_sli_duration_seconds")
    reset_run_state(metrics=m, collector=col)
    assert dict(m.counters) == {}
    # histograms zero IN PLACE — not evicted — so cached handles stay live
    assert all(h.count == 0 and h.sum == 0.0 for h in m.hists.values())
    assert all(h.count == 0 for s in m.labeled_hists.values()
               for h in s.values())
    assert col.spans() == [] and col.spans_dropped == 0
    assert all(v == 0 for v in TRACE_COUNTS.values())
    # a post-reset observation through the pre-reset handle must be visible
    # in the registry (an orphaned hist here would silently drop the SLI)
    cached.observe(0.3)
    assert m.hist("pod_scheduling_sli_duration_seconds") is cached
    _, _, hists = m.snapshot()
    assert hists["pod_scheduling_sli_duration_seconds"][2] == 1


def test_streaming_runs_do_not_bleed_across_invocations():
    """Two back-to-back harness runs in one process: the second run's SLI
    sample count and route counts must describe only itself."""
    from kubernetes_tpu.bench.harness import run_streaming_workload
    from kubernetes_tpu.bench.workloads import heterogeneous

    waves = [heterogeneous(10, 30, seed=s) for s in range(2)]
    out1 = run_streaming_workload("a", waves, warmup=False)
    out2 = run_streaming_workload("b", waves, warmup=False)
    assert out1["sli_count"] == out2["sli_count"] == out1["n_pods"]
    # route counters bump at jit-TRACE time: run 1 compiled (the serial
    # reference traces the plain kernel and the metered pipelined pass
    # traces its ordinals twin, so the exact count is a kernel census, not
    # the property under test); run 2 hits the warm cache and must report
    # ZERO — a bleed would carry run 1's count forward instead
    assert out1["route_trace_counts"]["plain"] >= 1
    assert all(v == 0 for v in out2["route_trace_counts"].values())
