"""L0 encoder unit tests (analog of scheduler cache/snapshot tests)."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from helpers import GI, MILLI, mk_node, mk_pod


def test_shapes_padded_pow2():
    snap = Snapshot(nodes=[mk_node(f"n{i}") for i in range(5)], pending_pods=[mk_pod("p0")])
    arr, meta = encode_snapshot(snap)
    assert arr.N == 8 and arr.P == 8
    assert arr.node_valid.sum() == 5 and arr.pod_valid.sum() == 1
    assert meta.resources[:3] == [t.CPU, t.MEMORY, t.PODS]


def test_resource_scaling_exact():
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=4 * MILLI, mem=8 * GI)],
        pending_pods=[mk_pod("p0", cpu=250, mem=256 * 1024**2)],
    )
    arr, meta = encode_snapshot(snap)
    j_cpu = meta.resources.index(t.CPU)
    j_mem = meta.resources.index(t.MEMORY)
    # scaled values recover canonical quantities exactly
    assert arr.node_alloc[0, j_cpu] * meta.resource_scale[j_cpu] == 4 * MILLI
    assert arr.pod_req[0, j_mem] * meta.resource_scale[j_mem] == 256 * 1024**2


def test_activeq_order_priority_then_fifo():
    pods = [mk_pod("low"), mk_pod("high", priority=10), mk_pod("mid", priority=5)]
    snap = Snapshot(nodes=[mk_node("n0")], pending_pods=pods)
    _, meta = encode_snapshot(snap)
    assert meta.pod_names[:3] == ["high", "mid", "low"]


def test_pods_resource_synthetic():
    snap = Snapshot(nodes=[mk_node("n0", pods=7)], pending_pods=[mk_pod("p0")])
    arr, meta = encode_snapshot(snap)
    j = meta.resources.index(t.PODS)
    assert arr.node_alloc[0, j] == 7
    assert arr.pod_req[0, j] == 1


def test_bound_pods_accumulate_used():
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=4000)],
        pending_pods=[mk_pod("p")],
        bound_pods=[mk_pod("b1", cpu=500, node_name="n0"), mk_pod("b2", cpu=300, node_name="n0")],
    )
    arr, meta = encode_snapshot(snap)
    j = meta.resources.index(t.CPU)
    assert arr.node_used[0, j] * meta.resource_scale[j] == 800


def test_unschedulable_becomes_taint():
    snap = Snapshot(nodes=[mk_node("n0", unschedulable=True)], pending_pods=[mk_pod("p")])
    arr, meta = encode_snapshot(snap)
    assert ("node.kubernetes.io/unschedulable", "", t.NO_SCHEDULE) in meta.taint_vocab
    assert arr.node_taint_ns[0].any()
    # pod does not tolerate it
    assert not arr.pod_tol_ns[0, meta.taint_vocab.get(("node.kubernetes.io/unschedulable", "", t.NO_SCHEDULE))]


def test_nodename_pinning():
    snap = Snapshot(
        nodes=[mk_node("a"), mk_node("b")],
        pending_pods=[mk_pod("p0", node_name="b"), mk_pod("p1", node_name="ghost")],
    )
    arr, _ = encode_snapshot(snap)
    assert arr.pod_nodename[0] == 1
    assert arr.pod_nodename[1] == -2
