"""L0 encoder unit tests (analog of scheduler cache/snapshot tests)."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from helpers import GI, MILLI, mk_node, mk_pod


def test_shapes_padded_pow2():
    snap = Snapshot(nodes=[mk_node(f"n{i}") for i in range(5)], pending_pods=[mk_pod("p0")])
    arr, meta = encode_snapshot(snap)
    assert arr.N == 8 and arr.P == 8
    assert arr.node_valid.sum() == 5 and arr.pod_valid.sum() == 1
    assert meta.resources[:3] == [t.CPU, t.MEMORY, t.PODS]


def test_resource_scaling_exact():
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=4 * MILLI, mem=8 * GI)],
        pending_pods=[mk_pod("p0", cpu=250, mem=256 * 1024**2)],
    )
    arr, meta = encode_snapshot(snap)
    j_cpu = meta.resources.index(t.CPU)
    j_mem = meta.resources.index(t.MEMORY)
    # scaled values recover canonical quantities exactly
    assert arr.node_alloc[0, j_cpu] * meta.resource_scale[j_cpu] == 4 * MILLI
    assert arr.pod_req[0, j_mem] * meta.resource_scale[j_mem] == 256 * 1024**2


def test_activeq_order_priority_then_fifo():
    pods = [mk_pod("low"), mk_pod("high", priority=10), mk_pod("mid", priority=5)]
    snap = Snapshot(nodes=[mk_node("n0")], pending_pods=pods)
    _, meta = encode_snapshot(snap)
    assert meta.pod_names[:3] == ["high", "mid", "low"]


def test_pods_resource_synthetic():
    snap = Snapshot(nodes=[mk_node("n0", pods=7)], pending_pods=[mk_pod("p0")])
    arr, meta = encode_snapshot(snap)
    j = meta.resources.index(t.PODS)
    assert arr.node_alloc[0, j] == 7
    assert arr.pod_req[0, j] == 1


def test_bound_pods_accumulate_used():
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=4000)],
        pending_pods=[mk_pod("p")],
        bound_pods=[mk_pod("b1", cpu=500, node_name="n0"), mk_pod("b2", cpu=300, node_name="n0")],
    )
    arr, meta = encode_snapshot(snap)
    j = meta.resources.index(t.CPU)
    assert arr.node_used[0, j] * meta.resource_scale[j] == 800


def test_unschedulable_becomes_taint():
    snap = Snapshot(nodes=[mk_node("n0", unschedulable=True)], pending_pods=[mk_pod("p")])
    arr, meta = encode_snapshot(snap)
    assert ("node.kubernetes.io/unschedulable", "", t.NO_SCHEDULE) in meta.taint_vocab
    assert arr.node_taint_ns[0].any()
    # pod does not tolerate it
    assert not arr.pod_tol_ns[0, meta.taint_vocab.get(("node.kubernetes.io/unschedulable", "", t.NO_SCHEDULE))]


def test_nodename_pinning():
    snap = Snapshot(
        nodes=[mk_node("a"), mk_node("b")],
        pending_pods=[mk_pod("p0", node_name="b"), mk_pod("p1", node_name="ghost")],
    )
    arr, _ = encode_snapshot(snap)
    assert arr.pod_nodename[0] == 1
    assert arr.pod_nodename[1] == -2


def test_interner_native_matches_python():
    """The C identity-profile interner (native/interner.c) must group
    bit-identically to the pure-Python SpecInterner loop across cold and
    warm waves, template-shared and per-pod-distinct field objects, and a
    table clear.  Skips when the native helper cannot build."""
    import dataclasses
    import random

    from kubernetes_tpu.api import types as t
    from kubernetes_tpu.api.snapshot import SpecInterner
    from kubernetes_tpu.native import pyintern

    if pyintern.load() is None:
        import pytest

        pytest.skip("native interner unavailable")
    rng = random.Random(5)
    templates = [
        t.Pod(
            name=f"tmpl{i}",
            requests={"cpu": 100 * (i + 1), "memory": 1 << (10 + i % 4)},
            labels={"app": f"a{i % 5}"},
            priority=i % 3,
            tolerations=(
                (t.Toleration(key="k", operator="Exists"),) if i % 2 else ()
            ),
        )
        for i in range(12)
    ]
    nat = SpecInterner()
    assert nat._lib is not None
    py = SpecInterner()
    py._lib = None  # force the pure-Python path

    def check(pods):
        rn, invn, rkn = nat.group(pods)
        rp, invp, rkp = py.group(pods)
        assert [id(p) for p in rn] == [id(p) for p in rp]
        assert (invn == invp).all()
        assert rkn == rkp

    # wave 1: template-shared field objects (replace copies)
    w1 = [
        dataclasses.replace(rng.choice(templates), name=f"p{j}", uid="")
        for j in range(300)
    ]
    check(w1)
    # wave 2: per-pod DISTINCT field objects with equal values — the
    # identity level misses, the canonical level must still collapse them
    w2 = [
        t.Pod(
            name=f"q{j}",
            requests=dict(rng.choice(templates).requests),
            labels={"app": f"a{j % 5}"},
            priority=j % 3,
        )
        for j in range(300)
    ]
    check(w2)
    # wave 3: warm repeat of wave-1 objects (pure identity hits) + a few new
    check(w1[:100] + w2[:50])
    # wave 4: after a forced table clear, grouping must be unchanged
    nat._lib.interner_clear(nat._h)
    check(w1)
    # empty input
    check([])
