"""Regression tests for review-confirmed defects."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch
from kubernetes_tpu.oracle import oracle_schedule
from helpers import mk_node, mk_pod


def run_both(snap):
    arr, meta = encode_snapshot(snap)
    c = np.asarray(schedule_batch(arr, DEFAULT_SCORE_CONFIG)[0])
    got = [
        (meta.pod_names[k], meta.node_names[c[k]] if c[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule(snap)
    assert got == want
    return dict(got)


def test_int32_overflow_in_fit():
    # used + req would wrap negative in int32 and falsely pass
    big = 2**31 - 1
    snap = Snapshot(
        nodes=[t.Node("n0", allocatable={t.CPU: big, t.MEMORY: 1 << 40, t.PODS: 110})],
        pending_pods=[t.Pod("p", requests={t.CPU: big - 5})],
        bound_pods=[t.Pod("b", requests={t.CPU: big - 3}, node_name="n0")],
    )
    got = run_both(snap)
    assert got["p"] is None


def test_zero_request_resource_never_blocks():
    # node overcommitted on cpu by external binds still accepts a 0-cpu pod
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=1000)],
        pending_pods=[t.Pod("zero", requests={t.MEMORY: 1 << 20})],
        bound_pods=[t.Pod("hog", requests={t.CPU: 2000}, node_name="n0")],
    )
    got = run_both(snap)
    assert got["zero"] == "n0"


def test_empty_affinity_term_matches_nothing():
    aff = t.Affinity(required_node_terms=(t.NodeSelectorTerm(),))
    snap = Snapshot(nodes=[mk_node("n0")], pending_pods=[mk_pod("p", affinity=aff)])
    got = run_both(snap)
    assert got["p"] is None


def test_scheduling_gates_hold_pod():
    snap = Snapshot(
        nodes=[mk_node("n0")],
        pending_pods=[mk_pod("gated", scheduling_gates=("wait-for-quota",)), mk_pod("free")],
    )
    got = run_both(snap)
    assert got["gated"] is None and got["free"] == "n0"
