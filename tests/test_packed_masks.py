"""Packed data plane (bit-plane masks + bf16 scores) parity — the ISSUE 19
acceptance tests.

KTPU_PACK_MASKS / KTPU_SCORE_DTYPE are TRACE-TIME constants read once at
`ops.bitplane` import, so packed-vs-unpacked cannot flip inside one process:
the unpacked comparator runs in a FRESH subprocess with KTPU_PACK_MASKS=0
pinned (the autotune / rounds_proof discipline).  Both sides ride the SAME
bf16 score lattice, so packing is pure layout and every decision must be
bit-identical across {chunked, rounds, inc} x {donate on/off} x
{single-device, mesh8} warm churn.  Tier-1 runs a reduced leg set (each
kernel on each mesh, both donate values); the full 8-leg matrix is `slow`.

Plus the landability gates: a seeded chaos storm and a kill.post_assume
crash-restart with the packed plane armed (the default import state) —
a layout trick that cannot survive the storm is not landable (ROADMAP).
"""

import dataclasses
import json
import os
import random
import subprocess
import sys

import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.ops import bitplane

from helpers import mk_node, mk_pod, random_cluster  # noqa: F401 (mk_*: subproc)


@pytest.fixture(autouse=True)
def _packed_route(monkeypatch):
    """Production route on the CPU sim + the packed plane at its default
    (armed) import state; chaos injectors never leak across tests."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    chaos.uninstall()
    yield
    chaos.uninstall()


# --- the shared scenario: runs in THIS process (packed) and, via
# _unpacked_payload, in a subprocess with KTPU_PACK_MASKS=0 pinned ---

# tier-1 legs: each kernel on each mesh, both donate values exercised
_SMOKE_LEGS = (
    ("chunked", False, "single"),
    ("chunked", True, "mesh8"),
    ("rounds", True, "single"),
    ("rounds", False, "mesh8"),
)
_FULL_LEGS = tuple(
    (k, d, m)
    for k in ("chunked", "rounds")
    for d in (False, True)
    for m in ("single", "mesh8")
)


def _snap_for(kernel: str):
    rng = random.Random(42 if kernel == "chunked" else 9)
    if kernel == "chunked":
        # fit-only (infer_score_config strips the rest) -> chunked top-K
        return random_cluster(rng, n_nodes=24, n_pods=120)
    return random_cluster(
        rng, n_nodes=24, n_pods=48,
        with_taints=True, with_selectors=True, with_pairwise=True,
    )


def _decode(choices, meta):
    ch = np.asarray(choices)
    return [
        [meta.pod_names[k],
         meta.node_names[int(ch[k])] if int(ch[k]) >= 0 else None]
        for k in range(meta.n_pods)
    ]


def _bind_some(snap, verdicts, k=4):
    """k placed pods become bound, the rest re-pend under fresh names: a
    small warm delta so later cycles ride the patched resident cache."""
    by_name = {p.name: p for p in snap.pending_pods}
    bound = []
    for nm, node in verdicts:
        if node is not None and len(bound) < k:
            bound.append(dataclasses.replace(by_name[nm], node_name=node))
    pend = [
        dataclasses.replace(p, name=f"w-{p.name}", uid="")
        for p in snap.pending_pods
    ]
    return Snapshot(nodes=snap.nodes, pending_pods=pend, bound_pods=bound)


def _scenario_decisions(legs=_SMOKE_LEGS, cycles=3):
    """Every leg: warm churn over `cycles` encode->route->bind cycles,
    recording the dense route's decisions (cycle 0) and the incremental
    route's decisions (every cycle).  Pure function of the seeds + the
    trace-time packed-plane knobs — the payload is the parity artifact."""
    from kubernetes_tpu.api.delta import DeltaEncoder
    from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from kubernetes_tpu.ops.assign import schedule_batch_routed
    from kubernetes_tpu.ops.incremental import HoistCache
    from kubernetes_tpu.parallel import make_mesh

    out = {
        "pack": int(bitplane.PACK_MASKS),
        "sdtype": bitplane.SCORE_DTYPE,
        "decisions": {},
    }
    mesh8 = (make_mesh(8)
             if any(m == "mesh8" for _, _, m in legs) else None)
    try:
        for kernel, donate, mname in legs:
            if donate:
                os.environ["KTPU_DONATE"] = "1"
            else:
                os.environ.pop("KTPU_DONATE", None)
            mesh = mesh8 if mname == "mesh8" else None
            snap = _snap_for(kernel)
            enc = DeltaEncoder()
            if mesh is not None:
                enc.set_mesh(mesh)
            cache = HoistCache(mesh=mesh)
            key = f"{kernel}:{'donate' if donate else 'nodonate'}:{mname}"
            recorded = []
            for cycle in range(cycles):
                arr, meta = enc.encode(snap)
                cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
                if cycle == 0:
                    dense_c, _ = schedule_batch_routed(
                        arr, cfg, donate=False, mesh=mesh
                    )
                    recorded.append(["dense", _decode(dense_c, meta)])
                inc = cache.ensure(arr, meta, cfg)
                assert inc is not None, key
                got_c, _ = schedule_batch_routed(
                    arr, cfg, donate=donate, mesh=mesh, inc=inc
                )
                got = _decode(got_c, meta)
                recorded.append(["inc", got])
                snap = _bind_some(snap, [(nm, nd) for nm, nd in got])
            # warm cycles really rode the patched resident cache — the
            # packed fit plane was ASSIGNED in word space, not rebuilt
            assert cache.stats["patched"] >= 1, (key, cache.stats)
            out["decisions"][key] = recorded
    finally:
        os.environ.pop("KTPU_DONATE", None)
    return out


def _unpacked_payload(legs, cycles, timeout=840):
    """The SAME scenario in a fresh subprocess with dense (unpacked) masks:
    KTPU_PACK_MASKS=0, KTPU_SCORE_DTYPE=bf16 (identical score lattice —
    only the mask LAYOUT differs between the two payloads)."""
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(tests_dir)
    prog = (
        "import json, sys\n"
        f"sys.path.insert(0, {root!r})\n"
        f"sys.path.insert(0, {tests_dir!r})\n"
        "from __graft_entry__ import force_cpu_platform\n"
        "force_cpu_platform(8)\n"
        "import test_packed_masks as m\n"
        f"payload = m._scenario_decisions(legs={legs!r}, cycles={cycles})\n"
        "print('PAYLOAD::' + json.dumps(payload))\n"
    )
    env = dict(os.environ)
    env.pop("KTPU_DONATE", None)
    env.update({
        "KTPU_PACK_MASKS": "0",
        "KTPU_SCORE_DTYPE": "bf16",
        "KTPU_FORCE_CHUNKED": "1",
    })
    r = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=root,
    )
    assert r.returncode == 0, f"unpacked comparator died:\n{r.stderr[-2000:]}"
    for line in reversed(r.stdout.strip().splitlines()):
        if line.startswith("PAYLOAD::"):
            return json.loads(line[len("PAYLOAD::"):])
    raise AssertionError(f"no payload in comparator stdout: {r.stdout[-500:]}")


def _assert_bit_identity(legs, cycles):
    packed = json.loads(json.dumps(_scenario_decisions(legs, cycles)))
    unpacked = _unpacked_payload(legs, cycles)
    assert packed["pack"] == 1 and unpacked["pack"] == 0
    assert packed["sdtype"] == unpacked["sdtype"] == "bf16"
    assert packed["decisions"].keys() == unpacked["decisions"].keys()
    for key in packed["decisions"]:
        assert packed["decisions"][key] == unpacked["decisions"][key], (
            f"packed/unpacked decision divergence on leg {key}"
        )


def test_packed_vs_unpacked_bit_identity_smoke():
    """Packing is pure LAYOUT: flipping KTPU_PACK_MASKS must not move one
    decision on any route.  Reduced leg set (each kernel on each mesh,
    both donate values) — the full matrix is the slow variant below.
    Two cycles: cycle 0 is the full hoist, cycle 1 the warm word-space
    patch — enough to pin both paths while keeping tier-1 under its cap
    (the slow variant churns 3)."""
    if not bitplane.PACK_MASKS:
        pytest.skip("suite running with packing disabled via env")
    _assert_bit_identity(_SMOKE_LEGS, cycles=2)


@pytest.mark.slow
def test_packed_vs_unpacked_bit_identity_full_matrix():
    """The full {chunked, rounds} x {donate on/off} x {single, mesh8}
    matrix under warm churn (ISSUE 19 acceptance)."""
    if not bitplane.PACK_MASKS:
        pytest.skip("suite running with packing disabled via env")
    _assert_bit_identity(_FULL_LEGS, cycles=3)


# --- landability gates: the storm + the kill, packed plane armed ---

def test_chaos_storm_with_packing_armed(monkeypatch):
    """Seeded chaos storm through the Scheduler batch path with the packed
    plane at its default (armed) state: placements bit-identical to the
    fault-free serial oracle — the chaos parity invariant extended to the
    packed data plane."""
    from test_chaos import _churn_run

    assert bitplane.PACK_MASKS, "packed plane must be the default"
    assert bitplane.SCORE_DTYPE == "bf16"
    monkeypatch.delenv("KTPU_MESH", raising=False)
    oracle, _ = _churn_run(pipeline=False)
    got, sched = _churn_run(
        pipeline=True,
        plan=chaos.FaultPlan.from_seed(
            19, sites=("scheduler.step", "host.stall"), n_faults=4
        ),
    )
    assert got == oracle
    assert all(v for v in got.values())  # zero lost pods


def test_kill_post_assume_crash_restart_with_packing(tmp_path):
    """kill -9 at post-assume/pre-checkpoint with packing armed: the
    restarted incarnation replays and finishes bit-identical to the
    fault-free oracle — resident packed planes are rebuilt, never trusted
    across the kill."""
    from test_crash_restart import _run

    assert bitplane.PACK_MASKS, "packed plane must be the default"
    oracle, _, _ = _run(pipeline=False)
    got, sched, restarts = _run(
        chaos.FaultPlan.parse("kill.post_assume:kill@0"), ckpt_dir=tmp_path,
    )
    assert restarts >= 1
    assert got == oracle
    assert all(v for v in got.values())
    assert sched.metrics.counters["scheduler_restarts_total"] >= 1
