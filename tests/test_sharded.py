"""Sharded (8-device CPU mesh) vs single-device parity — the multi-chip path
must be bit-identical to the unsharded scan and hence to the oracle."""

import random

import numpy as np
import pytest

import jax

from kubernetes_tpu.api.snapshot import encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch
from kubernetes_tpu.parallel import make_mesh, sharded_schedule_batch
from helpers import random_cluster


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest should provide 8 virtual CPU devices"
    return make_mesh(8)


@pytest.mark.parametrize("seed", range(4))
def test_sharded_matches_unsharded(mesh, seed):
    rng = random.Random(7000 + seed)
    snap = random_cluster(
        rng, n_nodes=24, n_pods=50, with_taints=True, with_selectors=True, with_pairwise=True
    )
    arr, _ = encode_snapshot(snap)
    want, want_used = schedule_batch(arr, DEFAULT_SCORE_CONFIG)
    got, got_used = sharded_schedule_batch(arr, DEFAULT_SCORE_CONFIG, mesh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got_used), np.asarray(want_used))
