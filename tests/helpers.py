"""Shared test fixtures: builder-style Pod/Node constructors + random clusters.

Analog of the reference's fixture wrappers (pkg/scheduler/testing/wrappers.go —
st.MakePod().Req(...).Obj() builder pattern, SURVEY.md §4).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot

MILLI = 1000
GI = 1024**3


def mk_node(
    name: str,
    cpu: int = 4 * MILLI,
    mem: int = 8 * GI,
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Tuple[t.Taint, ...] = (),
    unschedulable: bool = False,
    extra: Optional[Dict[str, int]] = None,
) -> t.Node:
    alloc = {t.CPU: cpu, t.MEMORY: mem, t.PODS: pods}
    if extra:
        alloc.update(extra)
    return t.Node(
        name=name,
        allocatable=alloc,
        labels=dict(labels or {}),
        taints=taints,
        unschedulable=unschedulable,
    )


def mk_pod(
    name: str,
    cpu: int = 100,
    mem: int = 128 * 1024**2,
    node_name: str = "",
    priority: int = 0,
    labels: Optional[Dict[str, str]] = None,
    tolerations: Tuple[t.Toleration, ...] = (),
    node_selector: Optional[Dict[str, str]] = None,
    affinity: Optional[t.Affinity] = None,
    extra: Optional[Dict[str, int]] = None,
    **kw,
) -> t.Pod:
    req = {t.CPU: cpu, t.MEMORY: mem}
    if extra:
        req.update(extra)
    return t.Pod(
        name=name,
        requests=req,
        node_name=node_name,
        priority=priority,
        labels=dict(labels or {}),
        tolerations=tolerations,
        node_selector=tuple(sorted((node_selector or {}).items())),
        affinity=affinity,
        **kw,
    )


def random_cluster(
    rng: random.Random,
    n_nodes: int,
    n_pods: int,
    with_taints: bool = False,
    with_selectors: bool = False,
    with_pairwise: bool = False,
    n_zones: int = 3,
) -> Snapshot:
    nodes: List[t.Node] = []
    for i in range(n_nodes):
        labels = {
            t.LABEL_ZONE: f"zone-{i % n_zones}",
            "disktype": rng.choice(["ssd", "hdd"]),
            "tier": rng.choice(["a", "b", "c"]),
        }
        taints: Tuple[t.Taint, ...] = ()
        if with_taints and rng.random() < 0.3:
            taints = (
                t.Taint(
                    key="dedicated",
                    value=rng.choice(["infra", "batch"]),
                    effect=rng.choice([t.NO_SCHEDULE, t.PREFER_NO_SCHEDULE]),
                ),
            )
        nodes.append(
            mk_node(
                f"node-{i}",
                cpu=rng.choice([2, 4, 8, 16]) * MILLI,
                mem=rng.choice([4, 8, 16, 32]) * GI,
                pods=rng.choice([32, 64, 110]),
                labels=labels,
                taints=taints,
                unschedulable=rng.random() < 0.02,
            )
        )
    pods: List[t.Pod] = []
    for i in range(n_pods):
        tols: Tuple[t.Toleration, ...] = ()
        if with_taints and rng.random() < 0.5:
            tols = (
                t.Toleration(
                    key="dedicated",
                    operator=rng.choice(["Equal", "Exists"]),
                    value=rng.choice(["infra", "batch"]),
                ),
            )
        sel = None
        aff = None
        if with_selectors and rng.random() < 0.4:
            which = rng.random()
            if which < 0.5:
                sel = {"disktype": rng.choice(["ssd", "hdd"])}
            else:
                aff = t.Affinity(
                    required_node_terms=(
                        t.NodeSelectorTerm(
                            match_expressions=(
                                t.NodeSelectorRequirement(
                                    key="tier",
                                    operator=rng.choice([t.OP_IN, t.OP_NOT_IN, t.OP_EXISTS]),
                                    values=(rng.choice(["a", "b", "c"]),),
                                ),
                            )
                        ),
                    )
                )
        labels = {"app": rng.choice(["web", "db", "cache", "batch"]), "team": rng.choice(["x", "y"])}
        spread_cs = ()
        ports = ()
        if with_pairwise:
            r = rng.random()
            if r < 0.25:
                spread_cs = (
                    t.TopologySpreadConstraint(
                        max_skew=rng.choice([1, 2]),
                        topology_key=t.LABEL_ZONE,
                        when_unsatisfiable=rng.choice([t.DO_NOT_SCHEDULE, t.SCHEDULE_ANYWAY]),
                        label_selector=t.LabelSelector.of(app=labels["app"]),
                    ),
                )
            elif r < 0.4:
                kind = rng.random()
                term = t.PodAffinityTerm(
                    topology_key=t.LABEL_ZONE,
                    label_selector=t.LabelSelector.of(app=rng.choice(["web", "db", "cache"])),
                )
                pa = t.Affinity(
                    required_pod_affinity=(term,) if kind < 0.5 else (),
                    required_pod_anti_affinity=() if kind < 0.5 else (term,),
                )
                aff = t.Affinity(
                    required_node_terms=aff.required_node_terms if aff else (),
                    required_pod_affinity=pa.required_pod_affinity,
                    required_pod_anti_affinity=pa.required_pod_anti_affinity,
                )
            elif r < 0.5:
                ports = (("TCP", rng.choice([8080, 9090])),)
        pods.append(
            mk_pod(
                f"pod-{i}",
                cpu=rng.choice([50, 100, 250, 500, 1000]),
                mem=rng.choice([64, 128, 256, 512, 1024]) * 1024**2,
                priority=rng.choice([0, 0, 0, 10, 100]),
                tolerations=tols,
                node_selector=sel,
                affinity=aff,
                labels=labels,
                topology_spread=spread_cs,
                host_ports=ports,
            )
        )
    return Snapshot(nodes=nodes, pending_pods=pods)


_SHARED_TRACES = {}


def shared_route_traces():
    """ONE 18-route trace shared by the three full-pass test modules
    (test_devicecheck / test_shardcheck / test_memwatch) — the exact
    `--device --shard --mem` single-trace contract the CLI runs, and the
    single biggest CPU-sim cost in tier-1 (tracing the matrix three times
    would triple it)."""
    if "t" not in _SHARED_TRACES:
        from kubernetes_tpu.analysis.devicecheck import collect_traces

        _SHARED_TRACES["t"] = collect_traces()
    return _SHARED_TRACES["t"]
