"""Round-5 integration: the new subsystems working TOGETHER through the
real stack — scheduler places, the volume binder binds, the AttachDetach
controller attaches, the kubelet's volume manager gates SyncPod, the
prober drives readiness into EndpointSlice, a node-pressure preemption
wave evicts through the batched path, and the freed capacity serves the
preemptors — one cluster, one clock, every hop through the store's watch
fan-out."""

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.controllers import AttachDetachController
from kubernetes_tpu.scheduler.kubelet import HollowKubelet
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.network import EndpointSliceController
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import mk_node, mk_pod


def test_storage_probe_preemption_lifecycle():
    clock = FakeClock()
    store = ClusterStore()
    for i in range(4):
        store.add_node(mk_node(f"n{i}", cpu=4000, pods=16,
                               labels={t.LABEL_ZONE: f"z{i % 2}"}))
    # storage: one WFFC class restricted to z0 OR z1 (the round-5
    # multi-zone OR fix), an unbound claim a web pod will consume
    store.add_object("StorageClass", c.StorageClass(
        name="wffc", provisioner="csi.example.com",
        volume_binding_mode="WaitForFirstConsumer",
        allowed_topology=((t.LABEL_ZONE, "z0"), (t.LABEL_ZONE, "z1")),
    ))
    store.add_pvc(t.PersistentVolumeClaim(
        name="data", request=1 << 30, storage_class="wffc",
        wait_for_first_consumer=True,
    ))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    kubelets = [
        HollowKubelet(store, LeaseStore(clock=clock), f"n{i}", clock=clock)
        for i in range(4)
    ]
    ad = AttachDetachController(store)
    eps = EndpointSliceController(store)
    svc = c.Service(name="web", selector=(("app", "web"),),
                    ports=(c.ServicePort(80),))
    store.add_object("Service", svc)

    # the web pod: claims storage AND carries a readiness probe
    web = mk_pod("web-0", cpu=1000, labels={"app": "web"},
                 pvcs=("data",),
                 readiness_probe=t.Probe(period_seconds=1.0,
                                         success_threshold=2,
                                         failure_threshold=2,
                                         fail_after_seconds=0))
    store.add_pod(web)
    # low-priority filler saturating the cluster
    for i in range(4):
        store.add_pod(mk_pod(f"filler-{i}", cpu=2500, priority=0,
                             node_name=f"n{i}"))
    sched.run_until_idle()
    placed = store.pods["default/web-0"]
    assert placed.node_name, "web pod scheduled"
    assert store.pvcs["default/data"].volume_name, "WFFC claim provisioned"

    def tick_all():
        ad.tick()
        for k in kubelets:
            k.tick()
        eps.sync_service(svc)
        clock.step(1.0)

    # volume-manager gate: BEFORE attach the pod must not run
    home = next(k for k in kubelets if k.node_name == placed.node_name)
    home.tick()
    assert store.pods["default/web-0"].phase != t.PHASE_RUNNING
    tick_all()  # attach lands -> mount -> sandbox + container
    assert store.pods["default/web-0"].phase == t.PHASE_RUNNING
    assert store.pods["default/web-0"].ready is False  # probe not passed
    ready_eps = [
        e.ready for s in store.list_objects("EndpointSlice")
        for e in s.endpoints
    ]
    assert ready_eps == [False]
    tick_all()  # second consecutive probe success -> Ready -> serving
    assert store.pods["default/web-0"].ready is True
    ready_eps = [
        e.ready for s in store.list_objects("EndpointSlice")
        for e in s.endpoints
    ]
    assert ready_eps == [True]

    # a high-priority wave arrives on the saturated cluster: the batched
    # preemption path (waves + dirty repair) must evict fillers, and the
    # preemptors claim the freed capacity on retry
    for i in range(3):
        store.add_pod(mk_pod(f"hi-{i}", cpu=2500, priority=100))
    sched.run_until_idle()
    preempted = sched.events.by_reason("Preempted")
    assert len(preempted) == 3
    assert sched.metrics.counters["preemption_victims"] >= 3  # batched path
    # the web pod (priority 0 but small) survived on its node
    assert "default/web-0" in store.pods
    # kubelets reconcile the evictions through the watch: workers torn down
    for k in kubelets:
        k.tick()
    gone = [u for u in (f"default/filler-{i}" for i in range(4))
            if u not in store.pods]
    assert len(gone) == 3
    for k in kubelets:
        for u in gone:
            assert u not in k.workers
