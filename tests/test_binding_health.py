"""Async binding cycle (schedule_one.go's bindingCycle goroutine) and the
component-base health/metrics HTTP endpoints."""

import time
import urllib.request

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.runtime.sidecar import HealthServer
from kubernetes_tpu.scheduler.config import SchedulerConfiguration, validate
from kubernetes_tpu.scheduler.metrics import Metrics
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.store import ClusterStore
from helpers import mk_node, mk_pod


def test_async_binding_places_all_pods():
    store = ClusterStore()
    for i in range(4):
        store.add_node(mk_node(f"n{i}"))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu", binding_workers=4))
    for j in range(20):
        store.add_pod(mk_pod(f"p{j}", cpu=200))
    sched.run_until_idle(200)
    sched.wait_for_bindings()
    assert all(p.node_name for p in store.pods.values())
    assert len(sched.events.by_reason("Scheduled")) == 20


def test_async_binding_matches_sync_decisions():
    """The assume cache makes the pipelined cycle decision-identical to the
    synchronous one: same pods, same nodes -> same placements."""
    def run(workers):
        store = ClusterStore()
        for i in range(3):
            store.add_node(mk_node(f"n{i}", cpu=4000))
        sched = Scheduler(store, SchedulerConfiguration(
            mode="cpu", binding_workers=workers))
        for j in range(9):
            store.add_pod(mk_pod(f"p{j}", cpu=1100))
        sched.run_until_idle(100)
        sched.wait_for_bindings()
        return {p.name: p.node_name for p in store.pods.values()}

    assert run(0) == run(4)


def test_async_bind_failure_requeues():
    """A failing PreBind (missing PVC appears feasible? use volume binder
    failure) forgets the assumption and requeues the pod."""
    from kubernetes_tpu.api import cluster as c

    store = ClusterStore()
    store.add_node(mk_node("n0"))
    # unbound claim with an unknown class: feasibility lets it through
    # (pre-StorageClass legacy path) but PreBind cannot bind it
    store.add_pvc(t.PersistentVolumeClaim(name="d", storage_class="ghost",
                                          wait_for_first_consumer=True))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu", binding_workers=2))
    store.add_pod(mk_pod("p", pvcs=("d",)))
    sched.run_until_idle(5)
    sched.wait_for_bindings()
    assert store.pods["default/p"].node_name == ""
    assert sched.cache.assumed == {}


def test_binding_workers_validation():
    assert any("bindingWorkers" in e for e in validate(
        SchedulerConfiguration(binding_workers=-1)))


def test_health_and_metrics_endpoints():
    m = Metrics()
    m.inc("scheduling_attempts_scheduled", 7)
    m.observe("scheduling_attempt_duration_seconds", 0.01)
    ready = {"ok": False}
    hs = HealthServer(metrics=m, ready_check=lambda: ready["ok"])
    port = hs.start()

    def get(path):
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    import urllib.error

    assert get("/healthz") == (200, "ok")
    assert get("/livez") == (200, "ok")
    assert get("/readyz")[0] == 503  # not ready yet
    ready["ok"] = True
    assert get("/readyz") == (200, "ok")
    code, body = get("/metrics")
    assert code == 200
    assert "scheduling_attempts_scheduled 7" in body
    # streaming histograms expose cumulative le-buckets + _sum/_count
    assert 'scheduling_attempt_duration_seconds_bucket{le="+Inf"} 1' in body
    assert "scheduling_attempt_duration_seconds_count 1" in body
    hs.stop()


def test_async_binding_exception_requeues_instead_of_stranding():
    """A plugin bug in the binding cycle must forget the assumption and
    requeue — not vanish into an unobserved future."""
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu", binding_workers=2))
    boom = {"count": 0}
    orig = sched.framework.run_pre_bind

    def exploding(state, snap, pod, node_name):
        if boom["count"] == 0:
            boom["count"] += 1
            raise RuntimeError("plugin bug")
        return orig(state, snap, pod, node_name)

    sched.framework.run_pre_bind = exploding
    store.add_pod(mk_pod("p"))
    # the injected failure requeues the pod through backoff (~1 s): drive
    # cycles until the retry lands or the deadline proves it stranded
    import time

    deadline = time.time() + 10.0
    while time.time() < deadline:
        sched.run_until_idle(50)
        sched.wait_for_bindings()
        if store.pods["default/p"].node_name:
            break
        time.sleep(0.05)
    assert boom["count"] == 1  # the failure was actually injected
    assert store.pods["default/p"].node_name == "n0"  # retry succeeded
    assert sched.cache.assumed == {}  # no phantom capacity


def test_gated_pod_never_flushed_past_preenqueue():
    from kubernetes_tpu.scheduler.queue import FakeClock

    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"), clock=clock)
    store.add_pod(mk_pod("gated", scheduling_gates=("wait/for-it",)))
    sched.run_until_idle(5)
    clock.step(10_000.0)  # far past the leftover-flush window
    sched.run_until_idle(5)
    assert store.pods["default/gated"].node_name == ""  # still gated
