"""Host scheduler runtime: store/watch, queue, CPU-vs-TPU decision parity,
preemption, backoff — the integration tier (SURVEY.md §4: in-process cluster
state + real scheduling pipeline, no kubelet)."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import Profile, PluginSpec, from_yaml, validate
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import GI, MILLI, mk_node, mk_pod


def mk_cluster(mode="tpu", nodes=(), clock=None, config=None):
    store = ClusterStore()
    for nd in nodes:
        store.add_node(nd)
    sched = Scheduler(store, config or SchedulerConfiguration(mode=mode), clock=clock)
    return store, sched


def bound_map(store):
    return {p.name: (p.node_name or None) for p in store.pods.values()}


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_end_to_end_bind(mode):
    store, sched = mk_cluster(mode, nodes=[mk_node("n0"), mk_node("n1")])
    store.add_pod(mk_pod("p0"))
    store.add_pod(mk_pod("p1"))
    sched.run_until_idle()
    got = bound_map(store)
    assert got["p0"] and got["p1"]
    assert len(sched.events.by_reason("Scheduled")) == 2


def test_cpu_tpu_decision_parity():
    import random
    from helpers import random_cluster

    rng = random.Random(321)
    snap = random_cluster(rng, n_nodes=12, n_pods=30, with_taints=True,
                          with_selectors=True, with_pairwise=True)
    results = {}
    for mode in ("cpu", "tpu"):
        store, sched = mk_cluster(mode, nodes=[*map(_copy_node, snap.nodes)])
        for p in snap.pending_pods:
            store.add_pod(p)
        sched.run_until_idle()
        results[mode] = bound_map(store)
    assert results["cpu"] == results["tpu"]


def _copy_node(nd):
    import copy

    return copy.deepcopy(nd)


@pytest.mark.parametrize("mode", ["cpu", "tpu"])
def test_preemption_evicts_lower_priority(mode):
    clock = FakeClock()
    store, sched = mk_cluster(mode, nodes=[mk_node("only", cpu=1000)], clock=clock)
    store.add_pod(mk_pod("victim", cpu=800, priority=0))
    sched.run_until_idle()
    assert bound_map(store)["victim"] == "only"
    # high-priority pod arrives; must preempt
    store.add_pod(mk_pod("vip", cpu=800, priority=100))
    sched.run_until_idle()
    assert len(sched.events.by_reason("Preempted")) == 1
    assert "victim" not in bound_map(store)  # evicted (deleted)
    # retry after backoff
    clock.step(2.0)
    sched.run_until_idle()
    assert bound_map(store)["vip"] == "only"


def test_gated_pod_waits_for_update():
    store, sched = mk_cluster("tpu", nodes=[mk_node("n0")])
    store.add_pod(mk_pod("gated", scheduling_gates=("wait",)))
    sched.run_until_idle()
    assert bound_map(store)["gated"] is None
    # gate removed -> Pod/Update wakes it
    ungated = mk_pod("gated")
    store.update_pod(ungated)
    sched.run_until_idle()
    assert bound_map(store)["gated"] == "n0"


def test_unschedulable_wakes_on_node_add():
    clock = FakeClock()
    store, sched = mk_cluster("tpu", nodes=[mk_node("small", cpu=100)], clock=clock)
    store.add_pod(mk_pod("big", cpu=4000))
    sched.run_until_idle()
    assert bound_map(store)["big"] is None
    store.add_node(mk_node("large", cpu=8000))
    clock.step(3.0)  # clear backoff
    sched.run_until_idle()
    assert bound_map(store)["big"] == "large"


def test_backoff_is_exponential_and_capped():
    clock = FakeClock()
    store, sched = mk_cluster("tpu", clock=clock)  # no nodes: always fails
    store.add_pod(mk_pod("p", cpu=100))
    sched.run_until_idle()
    q = sched.queue
    assert q.backoff_duration("default/p") == 1.0
    for _ in range(6):
        clock.step(60)
        sched.run_until_idle()
    assert q.backoff_duration("default/p") == 10.0  # capped


def test_config_yaml_roundtrip_and_validation():
    cfg = from_yaml(
        """
profiles:
  - schedulerName: default-scheduler
    percentageOfNodesToScore: 100
    plugins:
      - {name: TaintToleration, weight: 3}
      - {name: PodTopologySpread, weight: 2}
      - {name: InterPodAffinity, enabled: false}
    tpuScore: {sidecarAddress: local, deadlineMs: 500}
mode: tpu
parallelism: 16
"""
    )
    assert cfg.profile().tpu_score.deadline_ms == 500
    sc = cfg.score_config()
    assert sc.interpod_weight == 0.0 and sc.taint_weight == 3.0
    assert validate(cfg) == []
    with pytest.raises(ValueError):
        from_yaml("mode: gpu")


def test_disabled_plugin_changes_decisions():
    # weight-0 taint score: PreferNoSchedule stops steering
    taint = (t.Taint(key="soft", effect=t.PREFER_NO_SCHEDULE),)
    nodes = [mk_node("soft-tainted", taints=taint), mk_node("clean")]
    prof = Profile(plugins=(PluginSpec(name="TaintToleration", enabled=False),))
    for mode in ("tpu",):
        store, sched = mk_cluster(
            mode, nodes=[_copy_node(n) for n in nodes],
            config=SchedulerConfiguration(mode=mode, profiles=(prof,)),
        )
        store.add_pod(mk_pod("p"))
        sched.run_until_idle()
        # without the taint score, both nodes tie -> lowest index (soft-tainted)
        assert bound_map(store)["p"] == "soft-tainted"


def test_feature_gate_validation():
    from kubernetes_tpu.scheduler.features import FeatureGates

    with pytest.raises(ValueError):
        FeatureGates((("NoSuchGate", True),))
    with pytest.raises(ValueError):
        FeatureGates((("DefaultPreemption", False),))  # GA gates are locked
    fg = FeatureGates((("GangScheduling", False),))
    assert not fg.enabled("GangScheduling")


def test_metrics_and_events_populate():
    store, sched = mk_cluster("tpu", nodes=[mk_node("n0")])
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    assert sched.metrics.counters["scheduling_attempts_scheduled"] == 1
    assert sched.metrics.hists["batch_scheduling_duration_seconds"].count
    assert sched.events.by_reason("Scheduled")[0].node == "n0"


# ----------------------------------------------------------- QueueingHints


def test_fit_failure_parks_until_node_event():
    """A pod rejected by NodeResourcesFit parks on that plugin's registered
    events: an unrelated assigned-pod event must NOT wake it; a node add
    must (QueueingHint registration, scheduling_queue.go)."""
    from kubernetes_tpu.scheduler.queue import EV_POD_ADD

    clock = FakeClock()
    store, sched = mk_cluster("cpu", nodes=[mk_node("small", cpu=500)], clock=clock)
    store.add_pod(mk_pod("big", cpu=2000))
    sched.run_until_idle(5)
    assert bound_map(store)["big"] is None
    assert "default/big" in sched.queue._unschedulable  # parked, not backoff
    # unrelated event kind: stays parked (Fit registers Node/*, Pod/Delete)
    sched.queue.move_all_to_active_or_backoff(EV_POD_ADD)
    clock.step(30.0)
    assert sched.queue.pop() is None
    # a node that fits arrives -> Node/Add moves it through backoff
    store.add_node(mk_node("roomy", cpu=4000))
    clock.step(30.0)
    sched.run_until_idle()
    assert bound_map(store)["big"] == "roomy"


def test_parked_pod_flushes_after_leftover_timeout():
    clock = FakeClock()
    store, sched = mk_cluster("cpu", nodes=[mk_node("small", cpu=500)], clock=clock)
    store.add_pod(mk_pod("big", cpu=2000))
    sched.run_until_idle(5)
    assert "default/big" in sched.queue._unschedulable
    clock.step(301.0)  # podMaxInUnschedulablePodsDuration leftover flush
    assert sched.queue.pop() is None  # moved to backoff, matures next step
    clock.step(30.0)
    pod = sched.queue.pop()
    assert pod is not None and pod.name == "big"


def test_run_until_idle_drains_past_100_cycles():
    """Regression: the old max_cycles=100 default silently returned with pods
    still queued; the fixpoint default must drain a 150-pod workload (CPU mode
    schedules one pod per cycle)."""
    store, sched = mk_cluster(
        "cpu", nodes=[mk_node("n0", cpu=200 * MILLI, mem=64 * GI, pods=200)]
    )
    for i in range(150):
        store.add_pod(mk_pod(f"p{i}", cpu=10, mem=1024**2))
    sched.run_until_idle()
    got = bound_map(store)
    assert sum(1 for v in got.values() if v == "n0") == 150
    assert sched.queue.pending_total == 0


def test_run_until_idle_raises_on_livelock():
    """A workload that never quiesces (every cycle pops a pod that fails and
    is immediately re-activated) must raise, not truncate silently."""
    store, sched = mk_cluster("cpu", nodes=[mk_node("n0", pods=0)])
    store.add_pod(mk_pod("p"))

    orig = sched.queue.add_unschedulable

    def ping_pong(pod, events=None, backoff=True, cycle_move_seq=None, **kw):
        orig(pod, events, backoff, cycle_move_seq, **kw)
        sched.queue.add(pod)  # a pathological event source re-activates it

    sched.queue.add_unschedulable = ping_pong
    with pytest.raises(RuntimeError, match="no scheduling progress"):
        sched.run_until_idle(stall_limit=50)


def test_run_until_idle_drains_large_unschedulable_backlog():
    """A big backlog of legitimately-unschedulable pods is normal quiescing
    (each cycle parks one pod), not livelock — must drain without raising."""
    store, sched = mk_cluster("cpu", nodes=[mk_node("n0", pods=0)])
    for i in range(60):
        store.add_pod(mk_pod(f"u{i}"))
    sched.run_until_idle(stall_limit=10)
    assert len(sched.queue) == 0
    assert all(v is None for v in bound_map(store).values())


def test_run_until_idle_raises_on_tpu_mode_livelock():
    """The batch path returns a verdict-per-pod dict even when every verdict
    is None; an all-failed batch whose pods are instantly re-activated must
    trip the stall guard, not loop forever."""
    store, sched = mk_cluster("tpu", nodes=[mk_node("n0", pods=0)])
    store.add_pod(mk_pod("p"))

    orig = sched.queue.add_unschedulable

    def ping_pong(pod, events=None, backoff=True, cycle_move_seq=None, **kw):
        orig(pod, events, backoff, cycle_move_seq, **kw)
        sched.queue.add(pod)

    sched.queue.add_unschedulable = ping_pong
    with pytest.raises(RuntimeError, match="no scheduling progress"):
        sched.run_until_idle(stall_limit=10)


def test_irrelevant_node_update_does_not_wake_fit_rejected():
    """QueueingHint callbacks (fit.go — isSchedulableAfterNodeChange): a
    label-only node update cannot free capacity, so a fit-rejected pod stays
    parked; an allocatable GROWTH wakes it."""
    clock = FakeClock()
    store, sched = mk_cluster("cpu", nodes=[mk_node("small", cpu=500)], clock=clock)
    store.add_pod(mk_pod("big", cpu=2000))
    sched.run_until_idle(5)
    assert "default/big" in sched.queue._unschedulable
    # label-only update: Skip — still parked
    store.update_node(mk_node("small", cpu=500, labels={"team": "a"}))
    assert "default/big" in sched.queue._unschedulable
    clock.step(30.0)
    assert sched.queue.pop() is None
    # allocatable grows: Queue — moves through backoff and schedules
    store.update_node(mk_node("small", cpu=4000, labels={"team": "a"}))
    assert "default/big" not in sched.queue._unschedulable
    clock.step(30.0)
    sched.run_until_idle()
    assert bound_map(store)["big"] == "small"


def test_irrelevant_assigned_pod_does_not_wake_spread_rejected():
    """An assigned-pod event wakes a spread-rejected pod only when the event
    pod matches one of its spread selectors (podtopologyspread hint)."""
    clock = FakeClock()
    z0 = [mk_node(f"z0-{i}", labels={t.LABEL_ZONE: "z0"}) for i in range(2)]
    tainted = mk_node(
        "z1-0", labels={t.LABEL_ZONE: "z1"},
        taints=(t.Taint(key="dedic", value="x", effect=t.NO_SCHEDULE),),
    )
    store, sched = mk_cluster("cpu", nodes=[*z0, tainted], clock=clock)
    for i in range(3):
        store.add_pod(
            mk_pod(f"web-{i}", labels={"app": "web"}, node_name=f"z0-{i % 2}")
        )
    spread = (
        t.TopologySpreadConstraint(
            max_skew=1, topology_key=t.LABEL_ZONE,
            when_unsatisfiable=t.DO_NOT_SCHEDULE,
            label_selector=t.LabelSelector.of(app="web"),
        ),
    )
    store.add_pod(mk_pod("w", labels={"app": "web"}, topology_spread=spread))
    sched.run_until_idle(8)
    assert bound_map(store)["w"] is None
    assert "default/w" in sched.queue._unschedulable
    # unrelated assigned pod (labels don't match the spread selector): Skip
    store.add_pod(mk_pod("db-0", labels={"app": "db"}, node_name="z0-0"))
    assert "default/w" in sched.queue._unschedulable
    # a matching assigned pod event: Queue (skew inputs changed)
    store.add_pod(mk_pod("web-new", labels={"app": "web"}, node_name="z0-1"))
    assert "default/w" not in sched.queue._unschedulable


@pytest.mark.parametrize("seed", range(3))
def test_queueing_hints_never_change_outcomes(seed):
    """QueueingHint callbacks may only SUPPRESS wakeups, never placements:
    the same event-driven workload converges to identical final placements
    with hints enabled and with hints disabled (leftover flush + backoff
    guarantee liveness either way)."""
    import random

    rng_master = random.Random(900 + seed)
    script = []  # replayable event script
    for step in range(12):
        r = rng_master.random()
        if r < 0.5:
            script.append(("pod", f"p{step}", rng_master.choice([200, 1500, 4500]),
                           rng_master.choice(["web", "db"])))
        elif r < 0.7:
            script.append(("node", f"extra{step}", rng_master.choice([2000, 6000])))
        elif r < 0.85:
            script.append(("grow", rng_master.choice([0, 1]),
                           rng_master.choice([4000, 8000])))
        else:
            script.append(("label", rng_master.choice([0, 1]), f"v{step}"))

    def run(hints_enabled: bool):
        clock = FakeClock()
        store, sched = mk_cluster(
            "cpu", nodes=[mk_node("n0", cpu=2000), mk_node("n1", cpu=500)],
            clock=clock,
        )
        if not hints_enabled:
            sched.framework.hints_for_plugins = lambda names: {}
        for ev in script:
            if ev[0] == "pod":
                store.add_pod(mk_pod(ev[1], cpu=ev[2], labels={"app": ev[3]}))
            elif ev[0] == "node":
                store.add_node(mk_node(ev[1], cpu=ev[2]))
            elif ev[0] == "grow":
                name = f"n{ev[1]}"
                nd = store.nodes[name]
                grown = mk_node(name, cpu=ev[2])
                grown.labels.update(nd.labels)
                store.update_node(grown)
            else:
                name = f"n{ev[1]}"
                nd = store.nodes[name]
                relabeled = mk_node(name, cpu=nd.allocatable[t.CPU])
                relabeled.labels = {**nd.labels, "team": ev[2]}
                store.update_node(relabeled)
            sched.run_until_idle(50)
            clock.step(2.0)
        for _ in range(6):  # drain through leftover flush + backoff
            clock.step(400.0)
            sched.run_until_idle(200)
        return bound_map(store)

    assert run(True) == run(False)
