"""Explainability plane (ISSUE 13): the device-derived unschedulable
diagnosis must be pure observation — explain-on vs explain-off decisions
bit-identical across kernels and meshes, reason counts equal to the host
oracle EXACTLY (parity is the feature), the production routes undisturbed
(KTPU010 zero retrace / KTPU011 transfer-guard clean with KTPU_EXPLAIN=1) —
and the decision flight recorder must leave a readable dump when a chaos
kill or a wave recovery fires."""

import json
import random

import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.api.delta import DeltaEncoder, class_groups
from kubernetes_tpu.ops import explain as ex
from kubernetes_tpu.ops.assign import TRACE_COUNTS, reset_trace_counts
from kubernetes_tpu.ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.scheduler import (
    ClusterStore,
    Scheduler,
    SchedulerConfiguration,
    run_restartable,
)
from kubernetes_tpu.scheduler.events import EventRecorder
from kubernetes_tpu.scheduler.flightrecorder import (
    FlightRecorder,
    load_flight,
    render_flight,
)
from kubernetes_tpu.scheduler.metrics import Metrics

from helpers import mk_node, mk_pod, random_cluster


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _mixed_nodes():
    return [
        mk_node("n0", cpu=1000, labels={"zone": "a"}),
        mk_node("n1", cpu=1000, labels={"zone": "b"},
                taints=(t.Taint(key="gpu", effect=t.NO_SCHEDULE),)),
        mk_node("n2", cpu=120, labels={"zone": "a"}),
        mk_node("n3", cpu=1000, unschedulable=True),
    ]


def _failing_pods():
    return [
        mk_pod("fit0", cpu=100),
        mk_pod("big0", cpu=5000),
        mk_pod("zoned0", cpu=50, node_selector={"zone": "nowhere"}),
        mk_pod("zoned1", cpu=50, node_selector={"zone": "nowhere"}),
    ]


# --- kernel == host oracle, exactly ---
@pytest.mark.parametrize("seed", [3, 11, 29])
def test_kernel_counts_equal_host_oracle(seed):
    """Randomized clusters with taints + selectors: the jitted reason
    counts equal the independent numpy recount bit-for-bit, and every
    class's counts sum to the valid-node total (one reason per node)."""
    rng = random.Random(seed)
    snap = random_cluster(rng, n_nodes=16, n_pods=48,
                          with_taints=True, with_selectors=True)
    arr, meta = DeltaEncoder().encode(snap)
    rows = list(range(meta.n_pods))
    reps, _ = class_groups(meta, rows)
    got = ex.explain_classes(arr, reps)
    want = ex.explain_oracle(arr, reps)
    np.testing.assert_array_equal(got, want)
    n_valid = int(np.asarray(arr.node_valid).sum())
    assert (got.sum(axis=1) == n_valid).all()


def test_kernel_counts_respect_supplied_usage():
    """Post-cycle usage flows through: filling a node flips its claim to
    Insufficient cpu in kernel and oracle alike."""
    snap = Snapshot(nodes=_mixed_nodes(), pending_pods=[mk_pod("p", cpu=500)])
    arr, meta = DeltaEncoder().encode(snap)
    used = np.array(arr.node_used, copy=True)
    used[0, meta.resources.index("cpu")] += 900  # n0 nearly full now
    got = ex.explain_classes(arr, np.array([0]), used)
    want = ex.explain_oracle(arr, [0], used)
    np.testing.assert_array_equal(got, want)
    labels = ex.reason_labels(meta.resources)
    counts = {labels[j]: int(got[0, j]) for j in range(len(labels))}
    assert counts["Insufficient cpu"] >= 1


def test_class_groups_dedupes_and_falls_back():
    snap = Snapshot(nodes=_mixed_nodes(), pending_pods=_failing_pods())
    arr, meta = DeltaEncoder().encode(snap)
    rows = list(range(meta.n_pods))
    reps, group_of = class_groups(meta, rows)
    # zoned0/zoned1 share a spec -> one rep serves both rows
    assert len(reps) < len(rows)
    assert len({group_of[r] for r in rows}) == len(reps)
    meta.pod_class = None  # plain-encode fallback: one class per row
    reps2, group_of2 = class_groups(meta, rows)
    assert list(reps2) == rows
    assert all(group_of2[r] == i for i, r in enumerate(rows))


# --- renderer + dominant reason ---
def test_render_unschedulable_is_upstream_shaped_and_deterministic():
    msg = ex.render_unschedulable(
        5, {"Insufficient cpu": 2, "node(s) were unschedulable": 3}
    )
    assert msg == ("0/5 nodes are available: 3 node(s) were unschedulable, "
                   "2 Insufficient cpu.")
    assert ex.render_unschedulable(7, {}) == "0/7 nodes are available."
    # count ties order by label; zero counts are dropped
    msg = ex.render_unschedulable(2, {"b reason": 1, "a reason": 1, "z": 0})
    assert msg == "0/2 nodes are available: 1 a reason, 1 b reason."


def test_dominant_reason_tie_breaks_to_higher_priority_entry():
    assert ex.dominant_reason({"first": 2, "second": 2, "third": 1}) == "first"
    assert ex.dominant_reason({"a": 1, "b": 3}) == "b"


# --- decisions bit-identical with explain on/off, routes undisturbed ---
def _run_batch_sched(explain: bool, monkeypatch, mesh_env=None,
                     force_chunked=None):
    monkeypatch.setenv("KTPU_EXPLAIN", "1" if explain else "0")
    if mesh_env is not None:
        monkeypatch.setenv("KTPU_MESH", mesh_env)
    else:
        monkeypatch.delenv("KTPU_MESH", raising=False)
    if force_chunked is not None:
        monkeypatch.setenv("KTPU_FORCE_CHUNKED", force_chunked)
    store = ClusterStore()
    for nd in _mixed_nodes():
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for p in _failing_pods():
        store.add_pod(p)
    sched.run_until_idle()
    # a warm delta: new arrivals after the first cycle (exercises the
    # resident encoder / hoist path explain must not perturb)
    store.add_pod(mk_pod("late0", cpu=100))
    store.add_pod(mk_pod("late-big", cpu=9000))
    sched.run_until_idle()
    placements = {p.name: p.node_name for p in store.pods.values()}
    return placements, sched


@pytest.mark.parametrize("mesh_env", [None, "8"])
@pytest.mark.parametrize("force_chunked", [None, "1"])
def test_decisions_bit_identical_explain_on_off(mesh_env, force_chunked,
                                                monkeypatch):
    """The acceptance gate: with KTPU_EXPLAIN=1 every placement is
    bit-identical to the explain-off run — across the plain and forced
    chunked/rounds routings, single-device and mesh8 — and the production
    kernels trace exactly as often (the explain kernel adds no retrace)."""
    _run_batch_sched(False, monkeypatch, mesh_env, force_chunked)  # warm jit
    reset_trace_counts()
    off, _ = _run_batch_sched(False, monkeypatch, mesh_env, force_chunked)
    routes_off = dict(TRACE_COUNTS)
    reset_trace_counts()
    on, sched = _run_batch_sched(True, monkeypatch, mesh_env, force_chunked)
    routes_on = dict(TRACE_COUNTS)
    assert on == off
    assert routes_on == routes_off
    # and the on-run really diagnosed: every FailedScheduling carries the
    # upstream-shaped message
    fails = sched.events.by_reason("FailedScheduling")
    assert fails and all(
        e.message.startswith("0/4 nodes are available:") for e in fails
    )


def test_incremental_route_decisions_unperturbed(monkeypatch):
    """{chunked_inc, rounds_inc} × explain: running the diagnosis between
    warm cycles changes neither the verdicts nor the inc-route trace
    counts (the ISSUE's {inc} × {single-device} cell; the scheduler-level
    test above covers inc under KTPU_MESH=8)."""
    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    from kubernetes_tpu.ops.assign import schedule_batch_routed
    from kubernetes_tpu.ops.incremental import HoistCache

    rng = random.Random(13)
    snap = random_cluster(rng, n_nodes=24, n_pods=120)

    def run(with_explain: bool):
        enc, cache = DeltaEncoder(), HoistCache()
        s = snap
        out = []
        reset_trace_counts()
        for _cycle in range(3):
            arr, meta = enc.encode(s)
            cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
            inc = cache.ensure(arr, meta, cfg)
            choices, _ = schedule_batch_routed(arr, cfg, donate=False, inc=inc)
            ch = np.asarray(choices)
            out.append(ch.tolist())
            if with_explain:
                failed = [k for k in range(meta.n_pods) if ch[k] < 0]
                msgs, dom, recs = ex.diagnose_failed(arr, meta, failed)
                assert set(msgs) == set(failed)
            bound = []
            for k in range(meta.n_pods):
                if ch[k] >= 0 and len(bound) < 4:
                    p = next(q for q in s.pending_pods
                             if q.name == meta.pod_names[k])
                    import dataclasses

                    bound.append(dataclasses.replace(
                        p, node_name=meta.node_names[int(ch[k])]))
            import dataclasses

            pend = [dataclasses.replace(p, name=f"w-{p.name}", uid="")
                    for p in s.pending_pods]
            s = Snapshot(nodes=s.nodes, pending_pods=pend, bound_pods=bound)
        return out, {k: v for k, v in TRACE_COUNTS.items() if v}

    _, routes_cold = run(False)  # cold run: proves the inc route engaged
    assert any(k.endswith("_inc") for k in routes_cold), routes_cold
    verdicts_off, routes_off = run(False)  # warm from here: clean A/B
    verdicts_on, routes_on = run(True)
    assert verdicts_on == verdicts_off
    assert routes_on == routes_off


# --- event messages equal a host-oracle recount exactly ---
def test_device_failure_events_match_host_oracle_recount(monkeypatch):
    monkeypatch.setenv("KTPU_EXPLAIN", "1")
    store = ClusterStore()
    nodes = _mixed_nodes()
    for nd in nodes:
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for p in _failing_pods():
        store.add_pod(p)
    sched.run_until_idle()
    failed_pods = [p for p in store.pods.values() if not p.node_name]
    bound_pods = [p for p in store.pods.values() if p.node_name]
    assert failed_pods and bound_pods
    # independent recount: fresh encode of the POST-CYCLE state (bound pods
    # fold into node_used exactly like the scheduler's post-commit usage)
    arr2, meta2 = DeltaEncoder().encode(Snapshot(
        nodes=nodes, pending_pods=failed_pods, bound_pods=bound_pods,
    ))
    labels = ex.reason_labels(meta2.resources)
    by_uid = {e.pod: e.message
              for e in sched.events.by_reason("FailedScheduling")}
    for p in failed_pods:
        row = meta2.pod_names.index(p.name)
        counts = ex.explain_oracle(arr2, [row])[0]
        want = ex.render_unschedulable(
            meta2.n_nodes,
            {labels[j]: int(counts[j]) for j in range(len(labels))},
        )
        assert by_uid[p.uid] == want
    # the labeled metric aggregated one dominant reason per failed pod
    series = sched.metrics.labeled_counter_series(
        "pod_unschedulable_reasons_total")
    assert sum(series.values()) == len(failed_pods)


def test_explain_off_keeps_device_events_silent(monkeypatch):
    monkeypatch.setenv("KTPU_EXPLAIN", "0")
    store = ClusterStore()
    for nd in _mixed_nodes():
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(mk_pod("big", cpu=5000))
    sched.run_until_idle()
    fails = sched.events.by_reason("FailedScheduling")
    assert fails and all(e.message == "" for e in fails)
    assert sched.metrics.labeled_counter_series(
        "pod_unschedulable_reasons_total") == {}


# --- CPU path shares the renderer ---
def test_cpu_path_message_renders_per_plugin_breakdown():
    store = ClusterStore()
    for nd in _mixed_nodes():
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"))
    store.add_pod(mk_pod("big", cpu=5000))
    sched.run_until_idle(max_cycles=2)
    [e] = sched.events.by_reason("FailedScheduling")[:1]
    assert e.message.startswith("0/4 nodes are available:")
    assert "Insufficient cpu" in e.message
    # per-node one-status counts sum to the cluster size
    total = sum(int(part.strip().split(" ", 1)[0])
                for part in e.message.split(":", 1)[1].rstrip(".").split(","))
    assert total == 4
    series = sched.metrics.labeled_counter_series(
        "pod_unschedulable_reasons_total")
    assert sum(series.values()) >= 1


# --- kubectl surfaces ---
def test_kubectl_describe_and_events_show_diagnosis(monkeypatch):
    monkeypatch.setenv("KTPU_EXPLAIN", "1")
    from kubernetes_tpu.kubectl import make_admin_kubectl

    store = ClusterStore()
    for nd in _mixed_nodes():
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(mk_pod("big", cpu=5000))
    sched.run_until_idle()
    kc = make_admin_kubectl(store=store, recorder=sched.events)
    out = kc.run("describe pod big")
    assert "FailedScheduling" in out
    assert "0/4 nodes are available:" in out
    ev = kc.run("get events")
    assert "0/4 nodes are available:" in ev


# --- KTPU010 / KTPU011 stay clean with the plane armed ---
def test_device_pass_retrace_and_transfer_rules_clean_with_explain(monkeypatch):
    """KTPU_EXPLAIN=1 while the ktpu-verify device pass traces all eighteen
    production routes: zero warm-cycle retraces (KTPU010) and a
    transfer-guard-clean warm loop (KTPU011) — the plane is additive."""
    monkeypatch.setenv("KTPU_EXPLAIN", "1")
    from kubernetes_tpu.analysis.devicecheck import run_device_pass

    rep = run_device_pass(rule_ids=["KTPU010", "KTPU011"])
    assert rep.errors == []
    assert rep.findings == [], [f.fingerprint for f in rep.findings]


# --- flight recorder ---
def test_flight_ring_is_bounded_and_ordered(tmp_path):
    fr = FlightRecorder(directory=str(tmp_path), capacity=4)
    for i in range(10):
        fr.record(profile="default", pods=i)
    recs = fr.records()
    assert len(recs) == 4
    assert [r["seq"] for r in recs] == [7, 8, 9, 10]
    path = fr.dump(reason="test")
    doc = load_flight(path)
    assert doc["reason"] == "test" and len(doc["records"]) == 4
    assert "pods=9" in render_flight(doc)


def test_flight_dump_absent_without_directory():
    fr = FlightRecorder(directory=None, capacity=2)
    fr.record(pods=1)
    assert fr.dump(reason="x") is None


def test_chaos_kill_leaves_readable_flight_dump(tmp_path, monkeypatch):
    """The acceptance path: a kill.post_assume chaos kill dumps the ring
    into the checkpoint dir; the dump parses, names the killing site, and
    the post-mortem CLI reads it (exit 0) — while the restarted run still
    converges."""
    monkeypatch.setenv("KTPU_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("KTPU_EXPLAIN", "1")
    with chaos.chaos_plan(chaos.FaultPlan.parse("kill.post_assume:kill@0")):
        store = ClusterStore()
        for nd in _mixed_nodes():
            store.add_node(nd)
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
        for p in _failing_pods():
            store.add_pod(p)
        sched, restarts = run_restartable(sched)
    assert restarts == 1
    dump = tmp_path / "flight.json"
    assert dump.exists()
    doc = load_flight(str(dump))
    assert doc["reason"] == "kill.post_assume"
    # the CLI contract: readable dump = exit 0; corrupt = exit 2
    from kubernetes_tpu.analysis.__main__ import main as verify_main

    assert verify_main(["--flight", str(dump)]) == 0
    dump.write_text("{not json")
    assert verify_main(["--flight", str(dump)]) == 2
    # structurally corrupt (valid JSON, wrong shape) is unusable too, not
    # a traceback / exit-1 misread as an analyzer finding
    dump.write_text('{"records": 5}')
    assert verify_main(["--flight", str(dump)]) == 2
    # ... and so is a list-of-dicts dump with wrong-TYPED fields
    dump.write_text('{"records": [{"seq": 1, "trace_id": 123}]}')
    assert verify_main(["--flight", str(dump)]) == 2
    assert verify_main(["--flight", str(tmp_path / "missing.json")]) == 2


def test_flight_k_knob_clamps_instead_of_crashing(monkeypatch):
    monkeypatch.setenv("KTPU_FLIGHT_K", "not-a-number")
    fr = FlightRecorder()
    assert fr.capacity == 64
    monkeypatch.setenv("KTPU_FLIGHT_K", "3")
    assert FlightRecorder().capacity == 3


def test_flight_records_capture_diagnosis_and_fingerprints(tmp_path,
                                                           monkeypatch):
    monkeypatch.setenv("KTPU_EXPLAIN", "1")
    store = ClusterStore()
    for nd in _mixed_nodes():
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"),
                      checkpoint_dir=str(tmp_path))
    for p in _failing_pods():
        store.add_pod(p)
    sched.run_until_idle()
    recs = sched._flight.records()
    assert recs
    first = recs[0]
    assert first["failed"] >= 3 and first["scheduled"] >= 1
    assert first["verdict_crc"] and first["class_crc"]
    assert first["diagnosis"]
    assert all("counts" in d and d["pods"] >= 1 for d in first["diagnosis"])
    # records are JSON-serializable as dumped (no numpy leakage)
    json.dumps(recs)


def test_unarmed_scheduler_skips_flight_recording(monkeypatch):
    """No checkpoint dir = nothing could ever dump the ring, so the warm
    cycle must not pay the per-cycle fingerprint passes either."""
    monkeypatch.delenv("KTPU_CHECKPOINT_DIR", raising=False)
    store = ClusterStore()
    for nd in _mixed_nodes():
        store.add_node(nd)
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    store.add_pod(mk_pod("p0", cpu=100))
    sched.run_until_idle()
    assert sched._flight.records() == []


# --- EventRecorder drop accounting ---
def test_events_publish_dropped_total_counts_token_bucket_refusals():
    store = ClusterStore()
    m = Metrics()
    rec = EventRecorder(store=store, publish_qps=0.0, publish_burst=1,
                        metrics=m)
    rec.record("FailedScheduling", "default/p0", message="m")
    rec.record("FailedScheduling", "default/p1", message="m")
    rec.record("FailedScheduling", "default/p2", message="m")
    assert m.counters["events_publish_dropped_total"] == 2
    # the in-memory decision log stays complete either way
    assert len(rec.by_reason("FailedScheduling")) == 3


def test_harness_event_fields_stamp_drops_and_top_reasons():
    from kubernetes_tpu.bench.harness import event_fields

    m = Metrics()
    assert event_fields(m) == {"events_publish_dropped": 0,
                               "unschedulable_reasons": None}
    m.inc("events_publish_dropped_total", 3)
    for _ in range(2):
        m.inc_labeled("pod_unschedulable_reasons_total",
                      reason="Insufficient cpu")
    m.inc_labeled("pod_unschedulable_reasons_total",
                  reason="node(s) were unschedulable")
    out = event_fields(m)
    assert out["events_publish_dropped"] == 3
    assert out["unschedulable_reasons"] == {
        "Insufficient cpu": 2, "node(s) were unschedulable": 1,
    }
