"""gRPC sidecar: loopback end-to-end, proto roundtrip, deadline fallback —
the integration analog of the extender tests + the north star's fallback
contract."""

import random

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.oracle import oracle_schedule
from kubernetes_tpu.runtime import SidecarUnavailable, TPUScoreClient, TPUScoreServer
from kubernetes_tpu.runtime.convert import snapshot_from_proto, snapshot_to_proto
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import Profile, TPUScoreArgs
from helpers import mk_node, mk_pod, random_cluster


@pytest.fixture(scope="module")
def server():
    srv = TPUScoreServer()
    srv.start()
    yield srv
    srv.stop()


def test_proto_roundtrip_preserves_snapshot():
    rng = random.Random(9)
    snap = random_cluster(rng, n_nodes=6, n_pods=12, with_taints=True,
                          with_selectors=True, with_pairwise=True)
    snap.pod_groups["g"] = t.PodGroup(name="g", min_member=2)
    back = snapshot_from_proto(snapshot_to_proto(snap))
    # decisions over the roundtripped snapshot must be identical
    assert oracle_schedule(back) == oracle_schedule(snap)
    assert back.pod_groups["g"].min_member == 2


def test_sidecar_schedules_over_loopback(server):
    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    h = client.health()
    assert h.ok and h.device_count >= 1
    snap = Snapshot(
        nodes=[mk_node("a"), mk_node("b")],
        pending_pods=[mk_pod("p0"), mk_pod("p1"), mk_pod("huge", cpu=10**6)],
    )
    verdicts = client.schedule(snap, deadline_ms=60_000)
    assert verdicts["default/p0"] in ("a", "b")
    assert verdicts["default/huge"] is None
    # parity with the oracle through the wire
    want = dict(oracle_schedule(snap))
    got = {uid.split("/")[1]: node for uid, node in verdicts.items()}
    assert got == want
    client.close()


def test_sidecar_matches_gang_semantics(server):
    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    pods = [mk_pod(f"g-{i}", cpu=600, pod_group="job") for i in range(3)]
    snap = Snapshot(nodes=[mk_node("n0", cpu=1000)], pending_pods=pods)
    verdicts = client.schedule(snap, deadline_ms=60_000, gang=True)
    assert all(v is None for v in verdicts.values())  # all-or-nothing revoked
    client.close()


def test_client_raises_on_dead_endpoint():
    client = TPUScoreClient("127.0.0.1:1")  # nothing listens here
    with pytest.raises(SidecarUnavailable):
        client.schedule(Snapshot(nodes=[mk_node("n")], pending_pods=[mk_pod("p")]),
                        deadline_ms=300)
    client.close()


def test_scheduler_offloads_to_sidecar(server):
    prof = Profile(tpu_score=TPUScoreArgs(sidecar_address=f"127.0.0.1:{server.port}",
                                          deadline_ms=60_000))
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu", profiles=(prof,)))
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    assert store.pods["default/p"].node_name == "n0"


def test_scheduler_falls_back_to_cpu_when_sidecar_down():
    prof = Profile(tpu_score=TPUScoreArgs(sidecar_address="127.0.0.1:1", deadline_ms=200))
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu", profiles=(prof,)))
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    # still scheduled — through the CPU plugin path
    assert store.pods["default/p"].node_name == "n0"
    assert sched.metrics.counters["tpuscore_fallback_total"] == 1


def test_sidecar_receives_resolved_volume_and_dra_constraints(server):
    """The wire format has no PV/PVC/StorageClass/slice schema: the scheduler
    resolves them into plain requests + affinity BEFORE transmitting, so
    sidecar verdicts honor storage topology and device capacity."""
    from kubernetes_tpu.api import cluster as c

    prof = Profile(tpu_score=TPUScoreArgs(sidecar_address=f"127.0.0.1:{server.port}",
                                          deadline_ms=60_000))
    store = ClusterStore()
    store.add_object("StorageClass", c.StorageClass(
        name="zonal", provisioner="csi", volume_binding_mode="WaitForFirstConsumer",
        allowed_topology=((t.LABEL_ZONE, "a"),)))
    store.add_object("DeviceClass", c.DeviceClass(
        name="tpu", selector=c.DeviceSelector(terms=(("type", "v5e"),))))
    store.add_object("ResourceSlice", c.ResourceSlice(
        name="s", node_name="n-a", driver="d",
        devices=(c.DraDevice("d0", attributes=(("type", "v5e"),)),)))
    for name, zone in (("n-b", "b"), ("n-a", "a")):
        store.add_node(mk_node(name, labels={t.LABEL_ZONE: zone}))
    store.add_pvc(t.PersistentVolumeClaim(name="data", request=1, storage_class="zonal",
                                          wait_for_first_consumer=True))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu", profiles=(prof,)))
    store.add_pod(mk_pod("vol-pod", pvcs=("data",)))
    store.add_pod(t.Pod(name="dra-pod", requests={t.CPU: 100},
                        resource_claims=(t.ResourceClaimRef("tpu", 1),)))
    sched.run_until_idle()
    # storage class only provisions in zone a; devices only exist on n-a
    assert store.pods["default/vol-pod"].node_name == "n-a"
    assert store.pods["default/dra-pod"].node_name == "n-a"
    assert store.pvcs["default/data"].volume_name  # PreBind bound it locally


def test_wire_carries_preferred_affinity_and_images(server):
    """Preferred (soft) inter-pod affinity and node image caches now survive
    the proto roundtrip, so sidecar verdicts score them identically (D10)."""
    img = "registry.io/model:v1"
    warm = mk_node("warm", labels={t.LABEL_ZONE: "z1"})
    warm.images[img] = 900 * 1024 * 1024
    anchor = mk_pod("anchor", labels={"app": "db"})
    anchor.node_name = "warm"
    follower = mk_pod("follower", images=(img,))
    follower.affinity = t.Affinity(
        preferred_pod_affinity=(
            t.WeightedPodAffinityTerm(
                weight=100,
                term=t.PodAffinityTerm(
                    topology_key=t.LABEL_ZONE,
                    label_selector=t.LabelSelector.of(app="db"),
                ),
            ),
        )
    )
    snap = Snapshot(
        nodes=[mk_node("cold"), warm],
        pending_pods=[follower],
        bound_pods=[anchor],
    )
    back = snapshot_from_proto(snapshot_to_proto(snap))
    assert back.pending_pods[0].affinity.preferred_pod_affinity[0].weight == 100
    assert back.nodes[1].images == warm.images
    assert oracle_schedule(back) == oracle_schedule(snap)
    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    verdicts = client.schedule(snap, deadline_ms=60_000)
    want = {f"default/{n}": node for n, node in oracle_schedule(snap)}
    assert verdicts == want and verdicts["default/follower"] == "warm"
    client.close()


def test_wire_preserves_zero_hard_pod_affinity_weight(server):
    """weight=0 (disable hard-affinity scoring) must survive proto3 —
    presence-tracked, not coerced to the server default of 1.0."""
    anchor = mk_pod("anchor", labels={"app": "db"})
    anchor.affinity = t.Affinity(
        required_pod_affinity=(
            t.PodAffinityTerm(topology_key=t.LABEL_ZONE,
                              label_selector=t.LabelSelector.of(app="db")),
        )
    )
    anchor.node_name = "n-z2"
    # follower matches the anchor's REQUIRED term -> scores toward n-z2 at
    # hardPodAffinityWeight; with weight 0 the pull disappears and the
    # lowest-index tie-break wins
    follower = mk_pod("follower", labels={"app": "db"})
    nodes = [mk_node("n-z1", labels={t.LABEL_ZONE: "z1"}),
             mk_node("n-z2", labels={t.LABEL_ZONE: "z2"})]
    snap = Snapshot(nodes=nodes, pending_pods=[follower], bound_pods=[anchor])
    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    pulled = client.schedule(snap, deadline_ms=60_000, hard_pod_affinity_weight=10.0)
    flat = client.schedule(snap, deadline_ms=60_000, hard_pod_affinity_weight=0.0)
    assert pulled["default/follower"] == "n-z2"
    assert flat["default/follower"] == "n-z1"
    client.close()


# ------------------------------------------------------- session/delta wire


def _wave(n, tag, cpu=100):
    return [mk_pod(f"{tag}-{i}", cpu=cpu, labels={"app": f"svc-{i % 3}"}) for i in range(n)]


def test_session_delta_stream_matches_stateless(server):
    """Cycle 2+ ships only the wave + bound diff; verdicts must equal a
    stateless full-snapshot request over the same cluster state."""
    import dataclasses

    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    stateless = TPUScoreClient(f"127.0.0.1:{server.port}", session=False)
    nodes = [mk_node(f"n{i}", cpu=4000) for i in range(8)]
    bound = []
    for cycle in range(4):
        wave = _wave(6, f"c{cycle}")
        snap = Snapshot(nodes=nodes, pending_pods=wave, bound_pods=list(bound))
        got = client.schedule(snap, deadline_ms=60_000)
        want = stateless.schedule(snap, deadline_ms=60_000)
        assert got == want, f"cycle {cycle}"
        for p in wave:
            node = got[p.uid]
            if node:
                bound.append(dataclasses.replace(p, node_name=node))
        if bound:
            bound.pop(0)  # churn: a bound pod departs each cycle
    assert client.stats["full"] == 1 and client.stats["delta"] == 3, client.stats
    client.close()
    stateless.close()


def test_session_resync_after_server_restart():
    """Kill-and-reconnect: a new server has no session state; the client must
    transparently resync with ONE full snapshot inside the same call."""
    srv1 = TPUScoreServer()
    srv1.start()
    client = TPUScoreClient(f"127.0.0.1:{srv1.port}")
    nodes = [mk_node(f"n{i}", cpu=4000) for i in range(4)]
    v1 = client.schedule(Snapshot(nodes=nodes, pending_pods=_wave(4, "a")),
                         deadline_ms=60_000)
    assert any(v1.values())
    port = srv1.port
    srv1.stop(grace=0)
    # restart on the SAME port: session gone, channel reconnects
    srv2 = TPUScoreServer(f"127.0.0.1:{port}")
    srv2.start()
    try:
        v2 = client.schedule(Snapshot(nodes=nodes, pending_pods=_wave(4, "b")),
                             deadline_ms=60_000)
        assert any(v2.values())
        assert client.stats["resync"] == 1, client.stats
    finally:
        srv2.stop()
        client.close()


def test_cold_large_session_not_ready_exactly_once():
    """A cold session above the warmup threshold answers not_ready (client
    falls back) exactly once; after background warmup the same shapes serve."""
    import time as _time

    from kubernetes_tpu.runtime.sidecar import _Engine

    srv = TPUScoreServer(engine=_Engine(warmup_threshold=1))  # everything is "large"
    srv.start()
    client = TPUScoreClient(f"127.0.0.1:{srv.port}")
    try:
        nodes = [mk_node(f"n{i}", cpu=4000) for i in range(4)]
        snap = Snapshot(nodes=nodes, pending_pods=_wave(4, "a"))
        assert not client.health().ready or not srv.engine._sessions
        with pytest.raises(SidecarUnavailable, match="not ready"):
            client.schedule(snap, deadline_ms=60_000)
        # wait for background warmup, as /readyz consumers would
        deadline = _time.monotonic() + 60
        while not client.health().ready and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert client.health().ready
        v = client.schedule(Snapshot(nodes=nodes, pending_pods=_wave(4, "b")),
                            deadline_ms=60_000)
        assert any(v.values())
        assert client.stats["not_ready"] == 1, client.stats
    finally:
        srv.stop()
        client.close()


def test_session_bind_with_label_drift_ships_object(server):
    """A bound copy whose labels drifted from the wave spec (label update
    racing the bind) must ship as added_bound, not a bare uid bind — verdicts
    stay identical to a stateless request over the true state."""
    import dataclasses

    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    stateless = TPUScoreClient(f"127.0.0.1:{server.port}", session=False)
    nodes = [mk_node(f"n{i}", cpu=4000) for i in range(4)]
    w1 = [
        mk_pod(
            "w1-0",
            cpu=100,
            labels={"app": "web"},
            affinity=t.Affinity(
                required_pod_anti_affinity=(
                    t.PodAffinityTerm(
                        topology_key=t.LABEL_HOSTNAME,
                        label_selector=t.LabelSelector.of(app="web"),
                    ),
                ),
            ),
        ),
        mk_pod("w1-1", cpu=100, labels={"app": "web"}),
    ]
    v1 = client.schedule(Snapshot(nodes=nodes, pending_pods=w1), deadline_ms=60_000)
    # the bind lands with CHANGED labels
    drifted = dataclasses.replace(w1[0], labels={"app": "db"}, node_name=v1[w1[0].uid])
    bound = [drifted, dataclasses.replace(w1[1], node_name=v1[w1[1].uid])]
    w2 = [dataclasses.replace(w1[0], name="w2-0", uid="")]
    w2[0].__post_init__()
    snap2 = Snapshot(nodes=nodes, pending_pods=w2, bound_pods=bound)
    got = client.schedule(snap2, deadline_ms=60_000)
    want = stateless.schedule(snap2, deadline_ms=60_000)
    assert got == want
    client.close()
    stateless.close()


def test_session_ships_bound_pod_updates(server):
    """A bound pod whose OBJECT is replaced between cycles (e.g. label update
    on a bound pod — legal metadata mutation) must reach the session; verdicts
    stay identical to stateless over the true state (round-3 review finding)."""
    import dataclasses

    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    stateless = TPUScoreClient(f"127.0.0.1:{server.port}", session=False)
    nodes = [mk_node(f"n{i}", cpu=4000) for i in range(4)]
    w1 = [mk_pod("b0", cpu=100, labels={"app": "web"})]
    v1 = client.schedule(Snapshot(nodes=nodes, pending_pods=w1), deadline_ms=60_000)
    bound = [dataclasses.replace(w1[0], node_name=v1[w1[0].uid])]
    # settle one delta cycle so the server holds the bound copy
    w2 = [mk_pod("w2", cpu=100)]
    client.schedule(Snapshot(nodes=nodes, pending_pods=w2, bound_pods=bound),
                    deadline_ms=60_000)
    # now the bound pod's labels change (new object, same uid)
    bound2 = [dataclasses.replace(bound[0], labels={"app": "db"})]
    w3 = [
        mk_pod(
            "anti-db",
            cpu=100,
            affinity=t.Affinity(
                required_pod_anti_affinity=(
                    t.PodAffinityTerm(
                        topology_key=t.LABEL_HOSTNAME,
                        label_selector=t.LabelSelector.of(app="db"),
                    ),
                ),
            ),
        )
    ]
    snap3 = Snapshot(nodes=nodes, pending_pods=w3, bound_pods=bound2)
    got = client.schedule(snap3, deadline_ms=60_000)
    want = stateless.schedule(snap3, deadline_ms=60_000)
    assert got == want
    # the anti-affinity pod must avoid the updated pod's node
    assert got[w3[0].uid] != bound2[0].node_name
    client.close()
    stateless.close()


def test_health_server_zpages():
    """component-base zpages: /statusz (component + uptime) and /flagz
    (effective config) alongside healthz/readyz/metrics."""
    import urllib.request

    from kubernetes_tpu.runtime.sidecar import HealthServer

    hs = HealthServer(component="test-sidecar",
                      flags={"listen": "127.0.0.1:0", "deadline_ms": 1000})
    port = hs.start()
    try:
        def get(path):
            with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
                return r.status, r.read().decode()
        st, body = get("/statusz")
        assert st == 200 and "test-sidecar" in body and "uptime_seconds" in body
        st, body = get("/flagz")
        assert st == 200 and "deadline_ms=1000" in body and "listen=" in body
        st, _ = get("/healthz")
        assert st == 200
    finally:
        hs.stop()


def test_session_node_change_forces_full_resync(server):
    """A node-set change invalidates the session's cluster state: the client
    transparently re-sends the FULL snapshot (nodes_fp conditioning) and
    verdicts still match stateless."""
    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    stateless = TPUScoreClient(f"127.0.0.1:{server.port}", session=False)
    nodes = [mk_node(f"n{i}", cpu=4000) for i in range(3)]
    client.schedule(Snapshot(nodes=nodes, pending_pods=_wave(3, "a")),
                    deadline_ms=60_000)
    client.schedule(Snapshot(nodes=nodes, pending_pods=_wave(3, "b")),
                    deadline_ms=60_000)
    assert client.stats["delta"] == 1
    nodes2 = nodes + [mk_node("n-new", cpu=9000)]
    snap = Snapshot(nodes=nodes2, pending_pods=_wave(3, "c", cpu=5000))
    got = client.schedule(snap, deadline_ms=60_000)
    want = stateless.schedule(snap, deadline_ms=60_000)
    assert got == want
    assert client.stats["full"] == 2  # the node change forced a full sync
    # big pods only fit the new node — proves the new node reached the session
    assert all(v == "n-new" for v in got.values() if v)
    client.close()
    stateless.close()


def test_session_deltas_survive_volume_state(server):
    """Volume clusters must keep session deltas: the client fingerprints the
    RAW node set + storage state (resolution rebuilds node objects per cycle),
    so stable PVC state stays on the delta path; a PVC change resyncs."""
    import dataclasses

    pvc = t.PersistentVolumeClaim(name="claim", request=1,
                                  wait_for_first_consumer=True)
    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    nodes = []
    for i in range(4):
        nd = mk_node(f"n{i}", cpu=4000)
        nd.volume_attach_limit = 8
        nodes.append(nd)
    for cycle in range(3):
        wave = _wave(3, f"v{cycle}")
        wave.append(dataclasses.replace(
            mk_pod(f"vol-{cycle}", cpu=100), pvcs=("claim",)))
        snap = Snapshot(nodes=nodes, pending_pods=wave,
                        pvcs={pvc.key: pvc})
        v = client.schedule(snap, deadline_ms=60_000)
        assert any(v.values())
    assert client.stats["full"] == 1 and client.stats["delta"] == 2, client.stats
    # PVC state change -> storage fingerprint mismatch -> full sync
    pvc2 = dataclasses.replace(pvc, request=2)
    snap = Snapshot(nodes=nodes, pending_pods=_wave(2, "after"),
                    pvcs={pvc2.key: pvc2})
    client.schedule(snap, deadline_ms=60_000)
    assert client.stats["full"] == 2, client.stats
    client.close()


def test_session_bind_compression_engages_and_matches(server):
    """Steady-state binds ride bind_prev_assignment — the server re-binds
    its own previous assignment minus an exception list instead of decoding
    N Bind messages — and the session must stay decision-identical to a
    stateless client, including a pod the client did NOT bind (exception)
    and a departed pod (delete after compressed bind)."""
    import dataclasses

    client = TPUScoreClient(f"127.0.0.1:{server.port}")
    stateless = TPUScoreClient(f"127.0.0.1:{server.port}", session=False)
    nodes = [mk_node(f"n{i}", cpu=4000) for i in range(6)]
    bound = []
    skipped_uid = None
    for cycle in range(4):
        wave = _wave(8, f"c{cycle}")
        snap = Snapshot(nodes=nodes, pending_pods=wave, bound_pods=list(bound))
        got = client.schedule(snap, deadline_ms=60_000)
        want = stateless.schedule(snap, deadline_ms=60_000)
        assert got == want, f"cycle {cycle}"
        for k, p in enumerate(wave):
            node = got[p.uid]
            if node is None:
                continue
            if k == 3:
                # the client declines one bind per wave (volume failure
                # analog): must land on the exception list, not the server
                skipped_uid = p.uid
                continue
            bound.append(dataclasses.replace(p, node_name=node))
        if bound:
            bound.pop(0)  # churn: a bound pod departs each cycle
    assert client.stats["binds_compressed"] > 0, client.stats
    # compression carried the steady state: almost no explicit Bind messages
    assert client.stats["binds_explicit"] == 0, client.stats
    # the server's session state does NOT contain the skipped pod
    sess = next(iter(server.engine._sessions.values()))
    assert skipped_uid is not None and skipped_uid not in sess.bound
    client.close()
    stateless.close()
