"""End-to-end span tracing: per-pod trace trees from apiserver admission
through queue wait, the scheduling/binding cycles and per-plugin extension
points, down to kubelet sync — plus the Perfetto export, klog correlation,
and the labeled per-extension-point histograms (ISSUE 1)."""

import json

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.apiserver import APIServer
from kubernetes_tpu.scheduler.auth import bind_cluster_role
from kubernetes_tpu.scheduler.klog import Logger
from kubernetes_tpu.scheduler.kubelet import HollowKubelet
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock, PriorityQueue
from kubernetes_tpu.scheduler.tracing import (
    Span,
    TraceCollector,
    Tracer,
    current_span,
    default_collector,
)
from helpers import mk_node, mk_pod


def _traced_cluster(collector, mode="cpu"):
    """Store + apiserver + scheduler + one kubelet sharing one collector."""
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=4000))
    sched = Scheduler(
        store,
        SchedulerConfiguration(mode=mode),
        logger=Logger(verbosity=4),
        collector=collector,
    )
    api = APIServer(store, tracer=Tracer(collector, component="apiserver"))
    api.authn.add_token("admin", "admin", groups=("system:masters",))
    kubelet = HollowKubelet(
        store, LeaseStore(clock=clock), "n0", clock=clock,
        tracer=Tracer(collector, component="kubelet"),
    )
    return store, api, sched, kubelet


def _schedule_web0(collector, mode="cpu"):
    store, api, sched, kubelet = _traced_cluster(collector, mode)
    api.handle("admin", "create", "Pod", obj=mk_pod("web-0", cpu=1000))
    sched.run_until_idle()
    kubelet.tick()
    return store, sched


# ------------------------------------------------------- (a) the trace tree


def test_pod_trace_is_one_connected_tree_across_four_components():
    col = TraceCollector()
    store, sched = _schedule_web0(col)
    assert store.pods["default/web-0"].node_name == "n0"

    ctx = col.pod_context("default/web-0")
    assert ctx is not None, "pod trace context attached"
    spans = col.spans(trace_id=ctx.trace_id)
    names = {s.name for s in spans}
    # the chain the issue mandates: queue-wait -> scheduling-cycle ->
    # per-plugin extension points -> bind -> kubelet sync
    assert {"apiserver.request", "queue.wait", "scheduling.cycle",
            "binding.cycle", "kubelet.sync"} <= names
    assert "Filter/NodeResourcesFit" in names  # extension-point child spans
    assert "Score/NodeResourcesFit" in names
    assert "Bind/DefaultBinder" in names
    # ≥ 4 components on ONE trace
    assert {"apiserver", "queue", "scheduler", "kubelet"} <= {
        s.component for s in spans
    }
    # connectedness: exactly one root (the apiserver request), every other
    # span's parent is a span of the same trace
    by_id = {s.span_id: s for s in spans}
    roots = [s for s in spans if not s.parent_id or s.parent_id not in by_id]
    assert len(roots) == 1 and roots[0].name == "apiserver.request"
    # parentage sanity along the mandated chain
    def one(name):
        (s,) = [s for s in spans if s.name == name]
        return s

    assert one("queue.wait").parent_id == one("apiserver.request").span_id
    assert one("scheduling.cycle").parent_id == one("queue.wait").span_id
    assert one("binding.cycle").parent_id == one("scheduling.cycle").span_id
    assert one("Filter/NodeResourcesFit").parent_id == one("scheduling.cycle").span_id
    assert one("Bind/DefaultBinder").parent_id == one("binding.cycle").span_id
    assert one("kubelet.sync").parent_id == one("binding.cycle").span_id
    # the text dump renders the same tree (smoke: every name present, root first)
    tree = col.tree_text(ctx.trace_id)
    assert tree.splitlines()[1].strip().startswith("- apiserver.request")
    for n in ("queue.wait", "scheduling.cycle", "kubelet.sync"):
        assert n in tree


# ------------------------------------------------- (b) Perfetto JSON export


def test_chrome_trace_export_roundtrips(tmp_path):
    col = TraceCollector()
    _schedule_web0(col)
    path = col.export_chrome_trace(str(tmp_path / "trace.json"))
    data = json.loads(open(path).read())  # Perfetto-loadable JSON
    events = data["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    spans = [s for s in col.spans() if s.end is not None]
    assert len(complete) == len(spans)
    # pid/tid/ts/dur field contract: pid = component, tid = trace, ts/dur in
    # non-negative microseconds matching the span's measured duration
    pid_names = {
        e["pid"]: e["args"]["name"] for e in events if e["ph"] == "M"
    }
    by_span_id = {s.span_id: s for s in spans}
    for e in complete:
        s = by_span_id[e["args"]["span_id"]]
        assert pid_names[e["pid"]] == s.component
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert e["dur"] == pytest.approx(s.duration_s * 1e6, abs=0.5)
        assert e["args"]["trace_id"] == s.trace_id
    # one tid per trace: all spans of the pod's trace share a row
    ctx = col.pod_context("default/web-0")
    tids = {e["tid"] for e in complete if e["args"]["trace_id"] == ctx.trace_id}
    assert len(tids) == 1


# --------------------------------------------- (c) klog <-> trace correlation


def test_klog_entries_carry_active_span_ids():
    col = TraceCollector()
    store, sched = _schedule_web0(col)
    ctx = col.pod_context("default/web-0")
    (entry,) = sched.log.entries("Scheduled pod")
    kv = dict(entry.kv)
    assert kv["trace_id"] == ctx.trace_id
    # the emitting site ran inside the binding.cycle span's subtree
    span_ids = {s.span_id for s in col.spans(trace_id=ctx.trace_id)}
    assert kv["span_id"] in span_ids
    # outside any span, entries carry no trace keys
    sched.log.info("bare entry")
    (bare,) = sched.log.entries("bare entry")
    assert "trace_id" not in dict(bare.kv)


# ------------------------- (d) labeled per-extension-point duration metrics


def test_labeled_extension_point_histograms_cover_every_plugin():
    col = TraceCollector()
    store, api, sched, kubelet = _traced_cluster(col)
    api.handle("admin", "create", "Pod", obj=mk_pod("web-0", cpu=1000))
    # an infeasible lower-priority pod drives PostFilter (DefaultPreemption)
    api.handle("admin", "create", "Pod", obj=mk_pod("huge", cpu=64000))
    sched.run_until_idle()

    _, _, hists = sched.metrics.snapshot()
    prefix = "framework_extension_point_duration_seconds{"
    series = {k: v for k, v in hists.items() if k.startswith(prefix)}
    assert series, "labeled histograms exposed through snapshot()"
    assert all(count > 0 for _, _, count in series.values())
    covered = {
        kv.split("=")[1].strip('"')
        for k in series
        for kv in k[len(prefix):-1].split(",")
        if kv.startswith("plugin=")
    }
    registered = {pw.plugin.name for pw in sched.framework.plugins}
    assert covered == registered, f"missing: {registered - covered}"
    # structured access: the raw series carry their label pairs
    raw = sched.metrics.labeled_hists[
        "framework_extension_point_duration_seconds"
    ]
    assert (("extension_point", "PostFilter"), ("plugin", "DefaultPreemption")) in raw


# ------------------------------------------------- opt-out + batch-path spans


def test_disabled_collector_allocates_no_spans():
    col = TraceCollector(enabled=False)
    store, sched = _schedule_web0(col)
    assert store.pods["default/web-0"].node_name == "n0"
    assert col.spans() == []
    assert col.pod_context("default/web-0") is None
    # the queue never even recorded enqueue timestamps (the cheap-gate
    # contract: no per-pod tracing state off the enabled path)
    assert sched.queue._enq_at == {}
    # labeled metrics still flow with tracing off (metrics-first posture)
    _, _, hists = sched.metrics.snapshot()
    assert any(
        k.startswith("framework_extension_point_duration_seconds{")
        for k in hists
    )


def test_batch_mode_emits_cycle_step_spans_and_pod_chain():
    col = TraceCollector()
    store, sched = _schedule_web0(col, mode="tpu")
    assert store.pods["default/web-0"].node_name == "n0"
    names = {s.name for s in col.spans()}
    assert {"batch.cycle", "batch.encode", "batch.kernel",
            "batch.commit"} <= names
    # the pod's own chain still crosses components: queue wait -> bind mark
    # -> kubelet sync on one trace
    ctx = col.pod_context("default/web-0")
    pod_names = {s.name for s in col.spans(trace_id=ctx.trace_id)}
    assert {"queue.wait", "bind", "kubelet.sync"} <= pod_names


def test_span_context_follows_pod_across_requeue():
    """A pod that fails and retries keeps ONE trace across attempts."""
    col = TraceCollector()
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=1000))
    sched = Scheduler(
        store, SchedulerConfiguration(mode="cpu"), clock=clock, collector=col
    )
    store.add_pod(mk_pod("blocked", cpu=900))
    store.add_pod(mk_pod("filler", cpu=400, node_name="n0"))
    sched.run_until_idle(max_cycles=3)
    # past the leftover flush: even event-parked pods retry by then
    clock.step(301.0)
    sched.run_until_idle(max_cycles=3)  # flush moves it into backoff
    clock.step(11.0)  # max backoff elapses
    sched.run_until_idle(max_cycles=3)
    ctx = col.pod_context("default/blocked")
    cycles = [
        s for s in col.spans(trace_id=ctx.trace_id)
        if s.name == "scheduling.cycle"
    ]
    assert len(cycles) >= 2, "retries chain onto the same trace"
