"""DeltaEncoder: incremental (watch-delta) encoding must be BIT-IDENTICAL to a
from-scratch encode of the same cluster state, across randomized churn streams
(SURVEY.md §7 hard part 4 — snapshot deltas, not full re-uploads; the analog of
storage/cacher/cacher.go keeping one incremental view that every snapshot reads).
"""

import dataclasses

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.delta import DeltaEncoder
from kubernetes_tpu.api.snapshot import ClusterArrays, Snapshot, encode_snapshot
from helpers import mk_node, mk_pod


def assert_arrays_equal(got: ClusterArrays, want: ClusterArrays):
    for f in dataclasses.fields(ClusterArrays):
        a, b = getattr(got, f.name), getattr(want, f.name)
        assert a.shape == b.shape, f"{f.name}: {a.shape} vs {b.shape}"
        np.testing.assert_array_equal(a, b, err_msg=f.name)


def mk_template_pod(name, kind, zone_pref=None):
    """Pods stamped from a small template family (the steady-state shape)."""
    if kind == 0:
        return mk_pod(name, cpu=250, mem=256 * 1024**2, labels={"app": "web"})
    if kind == 1:
        return mk_pod(
            name,
            cpu=500,
            labels={"app": "db"},
            topology_spread=(
                t.TopologySpreadConstraint(
                    max_skew=2,
                    topology_key=t.LABEL_ZONE,
                    when_unsatisfiable=t.DO_NOT_SCHEDULE,
                    label_selector=t.LabelSelector(match_labels=(("app", "db"),)),
                ),
            ),
        )
    if kind == 2:
        return mk_pod(
            name,
            cpu=100,
            labels={"app": "cache"},
            affinity=t.Affinity(
                required_pod_affinity=(
                    t.PodAffinityTerm(
                        topology_key=t.LABEL_ZONE,
                        label_selector=t.LabelSelector(match_labels=(("app", "web"),)),
                    ),
                ),
                preferred_pod_anti_affinity=(
                    t.WeightedPodAffinityTerm(
                        weight=3,
                        term=t.PodAffinityTerm(
                            topology_key=t.LABEL_ZONE,
                            label_selector=t.LabelSelector(
                                match_labels=(("app", "cache"),)
                            ),
                        ),
                    ),
                ),
            ),
        )
    return mk_pod(
        name,
        cpu=50,
        tolerations=(t.Toleration("gpu", "true", t.NO_SCHEDULE, "Equal"),),
        node_selector={t.LABEL_ZONE: "z0"},
        host_ports=(("TCP", 8080),),
    )


def mk_cluster_nodes(n):
    nodes = []
    for i in range(n):
        taints = (t.Taint("gpu", "true", t.NO_SCHEDULE),) if i % 5 == 0 else ()
        nodes.append(
            mk_node(
                f"n{i}",
                labels={t.LABEL_ZONE: f"z{i % 3}"},
                taints=taints,
            )
        )
    return nodes


def test_delta_equals_full_on_churn_stream():
    """Bind waves, delete some bound pods, new waves arrive — every cycle the
    resident encoder's DECISIONS must equal a fresh full encode's (subset-
    compatible waves reuse the richer cached vocab, so arrays may differ in
    inert columns while verdicts cannot)."""
    from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch

    rng = np.random.default_rng(0)
    nodes = mk_cluster_nodes(24)
    bound = []
    enc = DeltaEncoder()
    serial = 0
    for cycle in range(6):
        if cycle == 0:
            kinds = [0, 1, 2, 3, 0, 1, 2, 3]  # seed the vocab with all templates
        else:
            kinds = [int(rng.integers(0, 4)) for _ in range(int(rng.integers(4, 12)))]
        pending = [
            mk_template_pod(f"p{serial + i}", kind=k) for i, k in enumerate(kinds)
        ]
        serial += len(pending)
        snap = Snapshot(nodes=nodes, pending_pods=pending, bound_pods=list(bound))
        got, gm = enc.encode(snap)
        want, wm = encode_snapshot(snap)
        assert gm.pod_names == wm.pod_names
        g_choices = np.asarray(schedule_batch(got, DEFAULT_SCORE_CONFIG)[0])
        w_choices = np.asarray(schedule_batch(want, DEFAULT_SCORE_CONFIG)[0])
        np.testing.assert_array_equal(
            g_choices[: gm.n_pods], w_choices[: wm.n_pods], err_msg=f"cycle {cycle}"
        )
        # churn: bind a random subset of the wave, delete a random bound pod
        for pod in pending:
            if rng.random() < 0.7:
                ni = int(rng.integers(0, len(nodes)))
                bound.append(dataclasses.replace(pod, node_name=nodes[ni].name))
        if bound and rng.random() < 0.8:
            bound.pop(int(rng.integers(0, len(bound))))
    assert enc.stats["delta"] >= 4, enc.stats  # the fast path actually ran


def test_delta_falls_back_on_new_vocab():
    """A wave introducing a new pairwise term / referenced label key must
    rebuild (and still match full)."""
    nodes = mk_cluster_nodes(8)
    enc = DeltaEncoder()
    snap1 = Snapshot(
        nodes=nodes, pending_pods=[mk_template_pod("a", 0), mk_template_pod("b", 1)]
    )
    g1, _ = enc.encode(snap1)
    w1, _ = encode_snapshot(snap1)
    assert_arrays_equal(g1, w1)
    full_before = enc.stats["full"]
    # new spec family: references a new label key + new spread term
    snap2 = Snapshot(
        nodes=nodes,
        pending_pods=[
            mk_template_pod("c", 2),
            mk_pod("d", node_selector={"disk": "ssd"}),
        ],
        bound_pods=[dataclasses.replace(mk_template_pod("a", 0), node_name="n1")],
    )
    g2, _ = enc.encode(snap2)
    w2, _ = encode_snapshot(snap2)
    assert_arrays_equal(g2, w2)
    assert enc.stats["full"] == full_before + 1  # fingerprint mismatch -> rebuild


def test_delta_falls_back_on_node_change():
    nodes = mk_cluster_nodes(8)
    enc = DeltaEncoder()
    wave = lambda s: [mk_template_pod(f"p{s}", 0)]
    snap1 = Snapshot(nodes=list(nodes), pending_pods=wave(0))
    enc.encode(snap1)
    # node replaced (e.g. taint update through the store)
    nodes2 = list(nodes)
    nodes2[3] = mk_node("n3", labels={t.LABEL_ZONE: "z0"}, unschedulable=True)
    snap2 = Snapshot(nodes=nodes2, pending_pods=wave(1))
    g, _ = enc.encode(snap2)
    w, _ = encode_snapshot(snap2)
    assert_arrays_equal(g, w)
    assert enc.stats["full"] == 2


def test_delta_same_template_wave_hits_fast_path():
    """Steady state: same templates, growing bound set — no rebuilds after
    the first."""
    nodes = mk_cluster_nodes(12)
    enc = DeltaEncoder()
    bound = []
    for cycle in range(4):
        pending = [mk_template_pod(f"w{cycle}-{i}", kind=i % 4) for i in range(8)]
        snap = Snapshot(nodes=nodes, pending_pods=pending, bound_pods=list(bound))
        g, gm = enc.encode(snap)
        w, _ = encode_snapshot(snap)
        assert_arrays_equal(g, w)
        for i, pod in enumerate(pending):
            bound.append(dataclasses.replace(pod, node_name=f"n{(cycle + i) % 12}"))
    assert enc.stats["full"] == 1
    assert enc.stats["delta"] == 3


def test_bind_absorb_revalidates_mutated_labels():
    """Pod labels are mutable metadata: a label update racing the bind (the
    bound copy differs from the wave rep) must NOT reuse the rep's cached spec
    info — the bound contribution is recomputed from the actual object
    (advisor round-2 medium finding)."""
    nodes = mk_cluster_nodes(9)
    enc = DeltaEncoder()
    pod = mk_template_pod("mut", 2)  # labels {"app": "cache"}
    snap1 = Snapshot(nodes=nodes, pending_pods=[pod, mk_template_pod("w", 0)])
    enc.encode(snap1)
    # the bind lands with labels CHANGED to one the vocab's terms select
    bound_copy = dataclasses.replace(pod, labels={"app": "web"}, node_name="n1")
    snap2 = Snapshot(
        nodes=nodes, pending_pods=[mk_template_pod("w2", 2)], bound_pods=[bound_copy]
    )
    g, _ = enc.encode(snap2)
    w, _ = encode_snapshot(snap2)
    assert enc.stats["delta"] >= 1, enc.stats  # the delta path served the cycle
    assert_arrays_equal(g, w)


def test_debug_verify_catches_inplace_mutation():
    """debug_verify cross-checks the synced cluster side against a rebuild:
    clean churn passes; an in-place bound-pod mutation (defeating the
    identity fingerprint) raises."""
    nodes = mk_cluster_nodes(6)
    enc = DeltaEncoder(debug_verify=True)
    pod = mk_template_pod("a", 0)
    snap1 = Snapshot(nodes=nodes, pending_pods=[pod])
    enc.encode(snap1)
    bound = dataclasses.replace(pod, node_name="n1")
    snap2 = Snapshot(
        nodes=nodes, pending_pods=[mk_template_pod("b", 0)], bound_pods=[bound]
    )
    enc.encode(snap2)  # clean delta cycle: no raise
    assert enc.stats["delta"] == 1
    # in-place mutation: the record's `is` check cannot see it
    bound.requests = {t.CPU: bound.requests[t.CPU] * 10}
    snap3 = Snapshot(
        nodes=nodes, pending_pods=[mk_template_pod("c", 0)], bound_pods=[bound]
    )
    with pytest.raises(AssertionError, match="diverged from rebuild"):
        enc.encode(snap3)


def test_duplicate_bound_uid_rejected():
    """records dedups by uid while the batch arrays are per-pod — a duplicate
    uid would drift deltas from rebuilds, so the build rejects it outright."""
    nodes = mk_cluster_nodes(3)
    p = dataclasses.replace(mk_template_pod("dup", 0), node_name="n0")
    q = dataclasses.replace(p, node_name="n1")  # same uid, second entry
    q.uid = p.uid
    snap = Snapshot(nodes=nodes, pending_pods=[], bound_pods=[p, q])
    with pytest.raises(ValueError, match="duplicate bound pod uid"):
        DeltaEncoder().encode(snap)


def test_delta_survives_volume_state():
    """Round-3: a cluster WITH PV/PVC/DRA state must keep incremental encoding
    (pre-resolution identity + storage fingerprint conditioning) while the
    storage state is stable, rebuild exactly when it changes, and stay
    decision-identical to a fresh encode either way (round-2 verdict task 8)."""
    import dataclasses as dc

    from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch

    nodes = mk_cluster_nodes(12)
    pv = t.PersistentVolume(
        name="pv0", capacity=10 * 1024**3, storage_class="std",
        allowed_topology=((t.LABEL_ZONE, "z1"),),
    )
    pvc = t.PersistentVolumeClaim(
        name="claim0", request=5 * 1024**3, storage_class="std", volume_name="pv0"
    )
    enc = DeltaEncoder()
    bound = []
    serial = 0
    for cycle in range(4):
        pending = [mk_template_pod(f"p{serial + i}", kind=i % 4) for i in range(6)]
        # one pod per wave uses the claim (its resolution folds PV topology)
        pending.append(
            dataclasses.replace(
                mk_pod(f"vol{cycle}", cpu=100), pvcs=("claim0",)
            )
        )
        serial += 6
        snap = Snapshot(
            nodes=nodes, pending_pods=pending, bound_pods=list(bound),
            pvs=[pv], pvcs={pvc.key: pvc}, storage_classes={},
        )
        got, gm = enc.encode(snap)
        want, wm = encode_snapshot(snap)
        g = np.asarray(schedule_batch(got, DEFAULT_SCORE_CONFIG)[0])
        w = np.asarray(schedule_batch(want, DEFAULT_SCORE_CONFIG)[0])
        np.testing.assert_array_equal(g[: gm.n_pods], w[: wm.n_pods],
                                      err_msg=f"cycle {cycle}")
        for i, pod in enumerate(pending[:4]):
            bound.append(dataclasses.replace(pod, node_name=f"n{(cycle + i) % 12}"))
    assert enc.stats["delta"] >= 3, enc.stats  # incremental despite volumes
    full_before = enc.stats["full"]
    # a PVC state change (rebound to a new object) must force a rebuild...
    pvc2 = dc.replace(pvc, volume_name="")
    snap2 = Snapshot(
        nodes=nodes, pending_pods=[mk_template_pod("q", 0)],
        bound_pods=list(bound), pvs=[pv], pvcs={pvc2.key: pvc2},
    )
    g2, gm2 = enc.encode(snap2)
    w2, wm2 = encode_snapshot(snap2)
    assert enc.stats["full"] == full_before + 1
    g = np.asarray(schedule_batch(g2, DEFAULT_SCORE_CONFIG)[0])
    w = np.asarray(schedule_batch(w2, DEFAULT_SCORE_CONFIG)[0])
    np.testing.assert_array_equal(g[: gm2.n_pods], w[: wm2.n_pods])


def test_wave_store_bounded_on_stable_backlog():
    """The per-wave (pods, reps, inv) store must not accumulate across
    cycles: a stable backlog re-pends the same uids every cycle (wave_ix
    slots overwrite, never pop), and fully-bound waves must drain by
    refcount.  Regression for the round-3 review finding: one store entry
    leaked per encode cycle, unbounded over a long-running encoder."""
    import dataclasses

    from kubernetes_tpu.bench.workloads import basic

    snap = basic(30, 120)
    enc = DeltaEncoder()
    for _ in range(30):  # stable backlog: same pods re-encoded every cycle
        enc.encode_device(
            Snapshot(nodes=snap.nodes, pending_pods=snap.pending_pods)
        )
    assert len(enc._cs.wave_store) <= 9, len(enc._cs.wave_store)
    assert enc.stats["delta"] >= 25, enc.stats

    enc2 = DeltaEncoder()
    enc2.encode_device(snap)
    prev = snap.pending_pods
    for c in range(6):  # every wave fully binds: refcount drain
        bound = [
            dataclasses.replace(p, node_name=snap.nodes[0].name) for p in prev
        ]
        wave = [
            dataclasses.replace(p, name=f"c{c}-{p.name}", uid="")
            for p in snap.pending_pods
        ]
        enc2.encode_device(
            Snapshot(nodes=snap.nodes, pending_pods=wave, bound_pods=bound)
        )
        prev = wave
    assert len(enc2._cs.wave_store) <= 3, len(enc2._cs.wave_store)
