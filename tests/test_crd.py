"""CustomResourceDefinition machinery: per-version structural schemas,
served/storage flags, storage-version conversion, instance GC on CRD delete —
the apiextensions-apiserver analog (scheduler/crd.py) through the full
APIServer handler chain."""

import pytest

from kubernetes_tpu.scheduler import ClusterStore
from kubernetes_tpu.scheduler.admission import AdmissionDenied
from kubernetes_tpu.scheduler.apiserver import APIServer
from kubernetes_tpu.scheduler.crd import (
    CRDInvalid,
    CRDVersion,
    CustomResource,
    CustomResourceDefinition,
    validate_schema_value,
)


def _admin_server():
    store = ClusterStore()
    srv = APIServer(store)
    srv.authn.add_token("admin", "admin", groups=("system:masters",))
    return store, srv


def _crd():
    schema_v1a1 = {
        "type": "object",
        "required": ["minMember"],
        "properties": {
            "minMember": {"type": "integer", "minimum": 1},
            "queue": {"type": "string"},
        },
    }
    schema_v1 = {
        "type": "object",
        "required": ["minMember"],
        "properties": {
            "minMember": {"type": "integer", "minimum": 1},
            "queue": {"type": "string", "enum": ["default", "batch"]},
            "workers": {
                "type": "array",
                "items": {"type": "object", "properties": {"cpu": {"type": "integer"}},
                          "required": ["cpu"]},
            },
        },
    }
    return CustomResourceDefinition(
        group="scheduling.example.com",
        kind="TrainingJob",
        plural="trainingjobs",
        versions=(
            CRDVersion("v1alpha1", served=True, storage=False, schema=schema_v1a1),
            CRDVersion("v1", served=True, storage=True, schema=schema_v1),
        ),
    )


def test_schema_validator_subset():
    s = {"type": "object", "properties": {"n": {"type": "integer", "maximum": 5}},
         "required": ["n"]}
    assert validate_schema_value(s, {"n": 3}) == []
    assert any("required" in e for e in validate_schema_value(s, {}))
    assert any("expected integer" in e for e in validate_schema_value(s, {"n": "x"}))
    assert any("maximum" in e for e in validate_schema_value(s, {"n": 9}))
    assert any("unknown field" in e for e in validate_schema_value(s, {"n": 1, "z": 2}))
    # booleans are not integers (the classic Python trap)
    assert any("integer" in e for e in validate_schema_value(s, {"n": True}))


def test_crd_lifecycle_through_apiserver():
    store, srv = _admin_server()
    crd = srv.handle("admin", "create", "CustomResourceDefinition", obj=_crd())
    assert crd.established
    # valid create at the storage version
    ok = CustomResource(api_version="scheduling.example.com/v1", kind="TrainingJob",
                        name="job1", spec={"minMember": 4, "queue": "batch"})
    srv.handle("admin", "create", "TrainingJob", obj=ok)
    assert store.get_object("TrainingJob", "default/job1") is ok
    # invalid spec rejected with a schema path
    bad = CustomResource(api_version="scheduling.example.com/v1", kind="TrainingJob",
                         name="job2", spec={"minMember": 0})
    with pytest.raises(AdmissionDenied, match="minimum"):
        srv.handle("admin", "create", "TrainingJob", obj=bad)
    # enum enforcement + nested array items
    bad2 = CustomResource(api_version="scheduling.example.com/v1", kind="TrainingJob",
                          name="job3",
                          spec={"minMember": 1, "queue": "oops"})
    with pytest.raises(AdmissionDenied, match="enum"):
        srv.handle("admin", "create", "TrainingJob", obj=bad2)
    bad3 = CustomResource(api_version="scheduling.example.com/v1", kind="TrainingJob",
                          name="job4",
                          spec={"minMember": 1, "workers": [{"cpu": "a lot"}]})
    with pytest.raises(AdmissionDenied, match=r"workers\[0\].cpu"):
        srv.handle("admin", "create", "TrainingJob", obj=bad3)


def test_version_conversion_and_serving():
    store, srv = _admin_server()
    srv.handle("admin", "create", "CustomResourceDefinition", obj=_crd())
    # a write at a non-storage served version converts to the storage version
    old = CustomResource(api_version="scheduling.example.com/v1alpha1",
                         kind="TrainingJob", name="legacy",
                         spec={"minMember": 2, "queue": "anything"})
    srv.handle("admin", "create", "TrainingJob", obj=old)
    stored = store.get_object("TrainingJob", "default/legacy")
    assert stored.api_version == "scheduling.example.com/v1"
    # unknown / unserved versions rejected
    with pytest.raises(AdmissionDenied, match="unknown version"):
        srv.handle(
            "admin", "create", "TrainingJob",
            obj=CustomResource(api_version="scheduling.example.com/v9",
                               kind="TrainingJob", name="x", spec={"minMember": 1}),
        )


def test_crd_definition_validation_and_delete_gc():
    store, srv = _admin_server()
    with pytest.raises(AdmissionDenied, match="storage version"):
        srv.handle(
            "admin", "create", "CustomResourceDefinition",
            obj=CustomResourceDefinition(
                group="g.io", kind="Two", plural="twos",
                versions=(CRDVersion("v1", storage=True),
                          CRDVersion("v2", storage=True)),
            ),
        )
    with pytest.raises(AdmissionDenied, match="built-in"):
        srv.handle(
            "admin", "create", "CustomResourceDefinition",
            obj=CustomResourceDefinition(
                group="g.io", kind="Pod", plural="pods2",
                versions=(CRDVersion("v1", storage=True),),
            ),
        )
    srv.handle("admin", "create", "CustomResourceDefinition", obj=_crd())
    srv.handle(
        "admin", "create", "TrainingJob",
        obj=CustomResource(api_version="scheduling.example.com/v1",
                           kind="TrainingJob", name="gc-me",
                           spec={"minMember": 1}),
    )
    # deleting the CRD garbage-collects its instances
    srv.handle("admin", "delete", "CustomResourceDefinition",
               name="trainingjobs.scheduling.example.com")
    assert store.list_objects("TrainingJob") == []
    assert store.list_objects("CustomResourceDefinition") == []


def test_kubectl_discovers_custom_resources():
    """kubectl resolves CRD plurals/kinds dynamically (the RESTMapper-through-
    discovery behavior) and lists instances through the same handler chain."""
    from kubernetes_tpu.kubectl import Kubectl

    store, srv = _admin_server()
    kc = Kubectl(srv, token="admin")
    srv.handle("admin", "create", "CustomResourceDefinition", obj=_crd())
    srv.handle(
        "admin", "create", "TrainingJob",
        obj=CustomResource(api_version="scheduling.example.com/v1",
                           kind="TrainingJob", name="tj1",
                           spec={"minMember": 2}),
    )
    out = kc.run("get trainingjobs")
    assert "tj1" in out
    out2 = kc.run("get TrainingJob")
    assert "tj1" in out2
    # CRDs themselves list under their own words
    out3 = kc.run("get crds")
    assert "trainingjobs.scheduling.example.com" in out3
    # unknown plural still errors cleanly
    import pytest as _pytest

    from kubernetes_tpu.kubectl import KubectlError

    with _pytest.raises(KubectlError, match="resource type"):
        kc.run("get flurbs")


def test_crd_and_custom_resources_via_yaml_apply(tmp_path):
    """The full CRD story through manifests: apply a CRD (reference names
    block) then a custom resource from YAML; schema violations from YAML are
    rejected with the structural path."""
    from kubernetes_tpu.kubectl import Kubectl, KubectlError

    store, srv = _admin_server()
    kc = Kubectl(srv, token="admin")
    crd_yaml = tmp_path / "crd.yaml"
    crd_yaml.write_text(
        """
apiVersion: apiextensions.k8s.io/v1
kind: CustomResourceDefinition
group: scheduling.example.com
names: {kind: TrainingJob, plural: trainingjobs}
versions:
  - name: v1
    served: true
    storage: true
    schema:
      type: object
      required: [minMember]
      properties:
        minMember: {type: integer, minimum: 1}
---
apiVersion: scheduling.example.com/v1
kind: TrainingJob
name: tj-yaml
spec: {minMember: 2}
"""
    )
    kc.run(f"apply -f {crd_yaml}")
    assert "tj-yaml" in kc.run("get trainingjobs")
    bad = tmp_path / "bad.yaml"
    bad.write_text(
        """
apiVersion: scheduling.example.com/v1
kind: TrainingJob
name: broken
spec: {minMember: 0}
"""
    )
    with pytest.raises(KubectlError, match="minimum"):
        kc.run(f"apply -f {bad}")
