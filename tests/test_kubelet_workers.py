"""Pod workers + PLEG + restartPolicy on the hollow kubelet — the reference
kubelet's control structure (pod_workers.go serialized per-pod machines;
pleg/generic.go Relist; kuberuntime computePodActions restart rules) run
against the fake clock-driven runtime (the kubemark trade)."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore
from kubernetes_tpu.scheduler.kubelet import HollowKubelet
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import mk_node, mk_pod


def _rig():
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    kubelet = HollowKubelet(store, LeaseStore(clock=clock), "n0", clock=clock)
    return clock, store, kubelet


def test_workers_are_watch_driven_and_scoped_to_node():
    clock, store, kubelet = _rig()
    store.add_node(mk_node("other"))
    store.add_pod(mk_pod("mine", node_name="n0"))
    store.add_pod(mk_pod("elsewhere", node_name="other"))
    store.add_pod(mk_pod("pending"))  # unbound: not mine either
    assert set(kubelet.workers) == {"default/mine"}
    kubelet.tick()
    assert store.pods["default/mine"].phase == t.PHASE_RUNNING
    assert store.pods["default/elsewhere"].phase == ""
    # late bind arrives purely via watch
    store.bind("default/pending", "n0")
    assert "default/pending" in kubelet.workers


def test_pleg_emits_started_and_died():
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod("job", node_name="n0", run_seconds=5.0))
    kubelet.tick()
    kubelet.tick()  # relist observes RUNNING
    assert kubelet.pleg._last.get("default/job") is not None
    clock.step(6.0)
    kubelet.tick()  # runtime exits 0 -> PLEG ContainerDied -> Succeeded
    assert store.pods["default/job"].phase == t.PHASE_SUCCEEDED
    # teardown removed the container AND its sandbox through the CRI
    assert not [
        c for c in kubelet.runtime.list_containers()
        if c.pod_uid == "default/job"
    ]
    assert not [
        s for s in kubelet.runtime.list_pod_sandboxes()
        if s.pod_uid == "default/job"
    ]


def test_crash_restart_policy_always_bumps_restart_count():
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod("crashy", node_name="n0", crash_after_seconds=2.0))
    kubelet.tick()
    for i in range(3):
        clock.step(3.0)
        kubelet.tick()
    pod = store.pods["default/crashy"]
    assert pod.phase == t.PHASE_RUNNING  # still restarting (Always)
    assert pod.restart_count == 3


def test_crash_restart_policy_never_fails_pod():
    clock, store, kubelet = _rig()
    store.add_pod(
        mk_pod("once", node_name="n0", crash_after_seconds=1.0,
               restart_policy="Never")
    )
    kubelet.tick()
    clock.step(2.0)
    kubelet.tick()
    pod = store.pods["default/once"]
    assert pod.phase == t.PHASE_FAILED and pod.restart_count == 0


def test_on_failure_restarts_crashes_but_not_completions():
    clock, store, kubelet = _rig()
    store.add_pod(
        mk_pod("flaky-job", node_name="n0", run_seconds=10.0,
               crash_after_seconds=3.0, restart_policy="OnFailure")
    )
    kubelet.tick()
    clock.step(4.0)
    kubelet.tick()  # crashed at 3s -> restarted
    assert store.pods["default/flaky-job"].restart_count == 1
    # after restart the crash timer resets; let it crash once more
    clock.step(4.0)
    kubelet.tick()
    assert store.pods["default/flaky-job"].restart_count == 2


def test_cri_boundary_sandbox_container_lifecycle():
    """The kubelet speaks only the CRI: a running pod owns one READY
    sandbox (which carries the pod IP — the CNI result) and one RUNNING
    container; restarts create a NEW container id at the next attempt in
    the SAME sandbox; teardown is ordered and leaves nothing behind."""
    from kubernetes_tpu.scheduler import cri

    clock, store, kubelet = _rig()
    store.add_pod(mk_pod("svc", node_name="n0", crash_after_seconds=2.0))
    kubelet.tick()
    sbs = kubelet.runtime.list_pod_sandboxes()
    ctrs = kubelet.runtime.list_containers()
    assert len(sbs) == 1 and sbs[0].state == cri.SANDBOX_READY
    assert sbs[0].ip and store.pods["default/svc"].pod_ip == sbs[0].ip
    assert len(ctrs) == 1 and ctrs[0].state == cri.CONTAINER_RUNNING
    assert ctrs[0].attempt == 0 and ctrs[0].sandbox_id == sbs[0].id
    first_id = ctrs[0].id
    clock.step(3.0)
    kubelet.tick()  # crash -> restart: NEW container, same sandbox
    ctrs = kubelet.runtime.list_containers()
    assert len(ctrs) == 1 and ctrs[0].id != first_id
    assert ctrs[0].attempt == 1 and ctrs[0].sandbox_id == sbs[0].id
    # delete the pod: full CRI teardown
    store.delete_pod("default/svc")
    assert kubelet.runtime.list_containers() == []
    assert kubelet.runtime.list_pod_sandboxes() == []


def test_cri_image_pulls_publish_to_node_status():
    """EnsureImagesExist pulls through the ImageService and the kubelet
    publishes NodeStatus.Images — the matrix ImageLocality scores against
    — without rewriting the Node when nothing new landed."""
    clock, store, kubelet = _rig()
    p = mk_pod("imgpod", node_name="n0")
    p.images = ("registry/app:v2",)
    store.add_pod(p)
    kubelet.tick()
    node = store.nodes["n0"]
    assert "registry/app:v2" in node.images
    assert kubelet.images.list_images()["registry/app:v2"] == node.images["registry/app:v2"]
    # steady state: same images -> node object untouched
    q = mk_pod("imgpod2", node_name="n0")
    q.images = ("registry/app:v2",)
    store.add_pod(q)
    node_obj = store.nodes["n0"]
    kubelet.tick()
    assert store.nodes["n0"] is node_obj


def test_kubelet_tls_bootstrap_csr_flow():
    """The kubelet files a serving CSR on startup (pkg/kubelet/certificate
    bootstrap analog); the Certificates controller approves and signs it;
    serving_certificate() returns the issued cert and caches it across the
    CSR cleaner's GC."""
    from kubernetes_tpu.scheduler.controllers import CertificatesController

    clock, store, kubelet = _rig()
    csr = store.get_object("CertificateSigningRequest", "n0-serving")
    assert csr is not None and csr.username == "system:node:n0"
    assert kubelet.serving_certificate() == ""
    ctrl = CertificatesController(store, clock=clock)
    ctrl.tick()
    cert = kubelet.serving_certificate()
    assert "BEGIN CERTIFICATE" in cert
    # the cleaner GCs the issued CSR; the kubelet keeps its cert
    clock.step(CertificatesController.TTL_S + 1)
    ctrl.tick()
    assert store.get_object("CertificateSigningRequest", "n0-serving") is None
    assert kubelet.serving_certificate() == cert


def test_liveness_probe_failure_restarts_container():
    """prober_manager: liveness failure_threshold consecutive failures kill
    the container; the replacement goes through the standard restart path
    (restartCount++, a NEW container at attempt+1)."""
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod(
        "webapp", node_name="n0",
        liveness_probe=t.Probe(period_seconds=1.0, failure_threshold=3,
                               fail_after_seconds=5.0),
    ))
    kubelet.tick()
    w = kubelet.workers["default/webapp"]
    first = w.container_id
    for _ in range(4):  # healthy while runtime < fail_after
        clock.step(1.0)
        kubelet.tick()
    assert w.container_id == first and w.restarts == 0
    # probe now fails; 3 consecutive failures (period 1s) trigger the kill
    for _ in range(3):
        clock.step(1.0)
        kubelet.tick()
    assert w.restarts == 1
    assert w.container_id != first
    st = kubelet.runtime.container_status(w.container_id)
    assert st.attempt == 1
    assert store.pods["default/webapp"].restart_count == 1
    # ...and the cycle repeats on the replacement (fresh probe counters:
    # no kill until ITS runtime passes fail_after + 3 failed periods)
    clock.step(4.0)
    kubelet.tick()
    assert w.restarts == 1


def test_liveness_probe_respects_restart_policy_never():
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod(
        "once", node_name="n0", restart_policy="Never",
        liveness_probe=t.Probe(period_seconds=1.0, failure_threshold=1,
                               fail_after_seconds=2.0),
    ))
    kubelet.tick()
    clock.step(3.0)
    kubelet.tick()
    assert store.pods["default/once"].phase == t.PHASE_FAILED
    assert kubelet.workers["default/once"].terminated


def test_readiness_probe_gates_pod_ready_and_endpoints():
    """Readiness: the pod publishes Ready=False until the probe passes
    success_threshold times; EndpointSlice serves only ready pods; a
    failing probe flips Ready back off without restarting anything."""
    from kubernetes_tpu.api import cluster as c
    from kubernetes_tpu.scheduler.network import EndpointSliceController

    clock, store, kubelet = _rig()
    store.add_pod(mk_pod(
        "backend", node_name="n0", labels={"app": "web"},
        readiness_probe=t.Probe(period_seconds=1.0, success_threshold=2,
                                failure_threshold=2,
                                fail_after_seconds=10.0),
    ))
    svc = c.Service(name="web", selector=(("app", "web"),),
                    ports=(c.ServicePort(80, target_port=8080),))
    store.add_object("Service", svc)
    eps = EndpointSliceController(store)
    kubelet.tick()  # Running, but NOT ready (probe not passed yet)
    pod = store.pods["default/backend"]
    assert pod.phase == t.PHASE_RUNNING and pod.ready is False
    eps.sync_service(svc)
    slices = store.list_objects("EndpointSlice")
    assert all(not e.ready for s in slices for e in s.endpoints)
    clock.step(1.0)
    kubelet.tick()  # second consecutive success -> Ready
    assert store.pods["default/backend"].ready is True
    eps.sync_service(svc)
    slices = store.list_objects("EndpointSlice")
    assert [e.ready for s in slices for e in s.endpoints] == [True]
    # probe starts failing at t>=10s: 2 consecutive failures -> not ready,
    # container keeps running (readiness never restarts)
    w = kubelet.workers["default/backend"]
    cid = w.container_id
    clock.step(10.0)
    kubelet.tick()
    clock.step(1.0)
    kubelet.tick()
    assert store.pods["default/backend"].ready is False
    assert w.container_id == cid and w.restarts == 0


def test_pods_without_probes_are_ready_when_running():
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod("plain", node_name="n0"))
    kubelet.tick()
    pod = store.pods["default/plain"]
    assert pod.phase == t.PHASE_RUNNING and pod.ready is True


def test_teardown_missing_container_still_removes_sandbox():
    """A container already gone from the runtime must not orphan its
    sandbox (per-step CRIError handling in _teardown)."""
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod("gone", node_name="n0"))
    kubelet.tick()
    w = kubelet.workers["default/gone"]
    # the runtime loses the container out from under the kubelet (crash-only
    # world: a restarted runtime daemon with partial state)
    kubelet.runtime.stop_container(w.container_id)
    kubelet.runtime.remove_container(w.container_id)
    store.delete_pod("default/gone")
    assert not kubelet.runtime.list_pod_sandboxes()


def test_probe_thresholds_count_periods_not_ticks():
    """failure_threshold counts PROBE EXECUTIONS (period-spaced), not sync
    ticks: a period-10s liveness probe with threshold 3 on an
    always-failing target kills ~30s in, even when the kubelet ticks
    every second."""
    clock, store, kubelet = _rig()
    store.add_pod(mk_pod(
        "slowprobe", node_name="n0",
        liveness_probe=t.Probe(period_seconds=10.0, failure_threshold=3,
                               fail_after_seconds=0.5),
    ))
    kubelet.tick()  # starts the container; probe #1 at t=0 succeeds
    w = kubelet.workers["default/slowprobe"]
    first = w.container_id
    for _ in range(35):  # failures land at the period marks t=10, 20, 30
        clock.step(1.0)
        kubelet.tick()
        if w.restarts:
            break
    assert clock.now() >= 30.0, f"killed too early at t={clock.now()}"
    assert w.restarts == 1 and w.container_id != first


def test_volume_manager_gates_start_on_attach():
    """volumemanager WaitForAttachAndMount: a pod with a bound PVC does
    not start containers until the AttachDetach controller attaches the
    PV to this node; teardown unmounts and the controller then detaches."""
    from kubernetes_tpu.scheduler.controllers import AttachDetachController

    clock, store, kubelet = _rig()
    store.add_pv(t.PersistentVolume(name="pv-1", capacity=1024**3,
                                    storage_class="static",
                                    claim_ref="default/data"))
    store.add_pvc(t.PersistentVolumeClaim(name="data", request=1024**3,
                                          storage_class="static",
                                          volume_name="pv-1"))
    p = mk_pod("dbpod", node_name="n0")
    p.pvcs = ("data",)
    store.add_pod(p)
    ad = AttachDetachController(store)
    kubelet.tick()  # volume not attached yet -> no containers
    assert store.pods["default/dbpod"].phase != t.PHASE_RUNNING
    assert not kubelet.runtime.list_containers()
    ad.tick()  # controller attaches pv-1 to n0
    assert "pv-1" in store.nodes["n0"].volumes_attached
    kubelet.tick()  # gate passes: sandbox + container start, mount recorded
    assert store.pods["default/dbpod"].phase == t.PHASE_RUNNING
    assert kubelet.volumemanager.mounted["default/dbpod"] == ("pv-1",)
    store.delete_pod("default/dbpod")
    assert "default/dbpod" not in kubelet.volumemanager.mounted  # unmounted
    ad.tick()  # last user gone -> detach
    assert "pv-1" not in store.nodes["n0"].volumes_attached
