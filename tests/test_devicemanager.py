"""Device manager + topology manager analog: concrete allocation, NUMA
alignment, checkpoint/restore, admission failure.

reference: pkg/kubelet/cm/devicemanager (ManagerImpl.Allocate + checkpoint)
and cm/topologymanager (single-numa-node preference).
"""

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler.checkpoint import CheckpointManager
from kubernetes_tpu.scheduler.devicemanager import AllocationError, DeviceManager
from kubernetes_tpu.scheduler.kubelet import HollowKubelet
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock
from kubernetes_tpu.scheduler.store import ClusterStore


def _inventory():
    """n0: 4 tpus split over 2 NUMA nodes + one unrelated device."""
    devs = tuple(
        c.DraDevice(f"tpu{i}", attributes=(("type", "v5e"), ("numa", str(i // 2))))
        for i in range(4)
    ) + (c.DraDevice("nic0", attributes=(("type", "nic"),)),)
    slices = [c.ResourceSlice(name="n0-s", node_name="n0", driver="tpu.dev", devices=devs)]
    classes = {"tpu": c.DeviceClass(name="tpu",
                                    selector=c.DeviceSelector(terms=(("type", "v5e"),)))}
    return slices, classes


def _claim_pod(name, count):
    return t.Pod(name=name, resource_claims=(t.ResourceClaimRef("tpu", count),))


def test_allocate_prefers_single_numa_node():
    slices, classes = _inventory()
    dm = DeviceManager("n0")
    got = dm.allocate(_claim_pod("p", 2), slices, classes)
    assert got == {"tpu": ["tpu.dev/tpu0", "tpu.dev/tpu1"]}  # both numa 0
    assert dm.numa_aligned("default/p", slices)


def test_allocate_spans_numa_when_no_single_node_fits():
    slices, classes = _inventory()
    dm = DeviceManager("n0")
    got = dm.allocate(_claim_pod("big", 3), slices, classes)
    assert len(got["tpu"]) == 3
    assert not dm.numa_aligned("default/big", slices)


def test_devices_are_exclusive_and_freed():
    slices, classes = _inventory()
    dm = DeviceManager("n0")
    a = dm.allocate(_claim_pod("a", 2), slices, classes)["tpu"]
    b = dm.allocate(_claim_pod("b", 2), slices, classes)["tpu"]
    assert not set(a) & set(b)
    with pytest.raises(AllocationError):
        dm.allocate(_claim_pod("c", 1), slices, classes)
    dm.free("default/a")
    assert dm.allocate(_claim_pod("c", 1), slices, classes)["tpu"][0] in a


def test_allocation_idempotent_per_pod():
    slices, classes = _inventory()
    dm = DeviceManager("n0")
    first = dm.allocate(_claim_pod("p", 2), slices, classes)
    again = dm.allocate(_claim_pod("p", 2), slices, classes)
    assert first == again
    assert len(dm._in_use()) == 2


def test_checkpoint_survives_restart(tmp_path):
    slices, classes = _inventory()
    cm = CheckpointManager(str(tmp_path))
    dm = DeviceManager("n0", cm)
    got = dm.allocate(_claim_pod("p", 2), slices, classes)
    # "restarted kubelet": fresh manager over the same checkpoint dir
    dm2 = DeviceManager("n0", CheckpointManager(str(tmp_path)))
    assert dm2.allocations["default/p"] == got
    # the restored allocation still blocks double-hand-out
    b = dm2.allocate(_claim_pod("q", 2), slices, classes)["tpu"]
    assert not set(b) & set(got["tpu"])


def test_kubelet_admits_allocates_and_fails_oversized(tmp_path):
    slices, classes = _inventory()
    store = ClusterStore()
    store.add_node(t.Node(name="n0", allocatable={t.CPU: 8000}))
    for sl in slices:
        store.add_object("ResourceSlice", sl)
    for dc in classes.values():
        store.add_object("DeviceClass", dc)
    leases = LeaseStore(FakeClock())
    kubelet = HollowKubelet(store, leases, "n0", checkpoint_dir=str(tmp_path))

    ok = _claim_pod("fits", 2)
    ok.node_name = "n0"
    toobig = _claim_pod("toobig", 9)
    toobig.node_name = "n0"
    store.add_pod(ok)
    store.add_pod(toobig)
    kubelet.tick()
    assert store.pods["default/fits"].phase == t.PHASE_RUNNING
    assert kubelet.devices.allocations["default/fits"]["tpu"]
    # oversized claim -> UnexpectedAdmissionError path: pod Failed
    assert store.pods["default/toobig"].phase == t.PHASE_FAILED
    # deletion frees the devices on the next housekeeping pass
    store.delete_pod("default/fits")
    kubelet.tick()
    assert "default/fits" not in kubelet.devices.allocations


def test_duplicate_class_claims_accumulate():
    """Two claims for the same class on one pod commit ALL their devices
    (regression: the second claim used to overwrite the first's record)."""
    slices, classes = _inventory()
    dm = DeviceManager("n0")
    pod = t.Pod(name="dup", resource_claims=(
        t.ResourceClaimRef("tpu", 2), t.ResourceClaimRef("tpu", 2)))
    got = dm.allocate(pod, slices, classes)
    assert len(got["tpu"]) == 4 and len(set(got["tpu"])) == 4
    with pytest.raises(AllocationError):
        dm.allocate(_claim_pod("other", 1), slices, classes)
    dm.free(pod.uid)
    assert dm._in_use() == set()


def test_recreated_pod_with_different_claims_reallocates():
    """A pod recreated under the same name (= same uid) but with different
    claims must not inherit the predecessor's stale allocation."""
    slices, classes = _inventory()
    dm = DeviceManager("n0")
    dm.allocate(_claim_pod("p", 1), slices, classes)
    bigger = _claim_pod("p", 3)  # same uid default/p, larger claim
    got = dm.allocate(bigger, slices, classes)
    assert len(got["tpu"]) == 3
    assert len(dm._in_use()) == 3  # the stale 1-device record was released
