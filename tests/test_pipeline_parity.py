"""Pipelined-vs-serial parity + donation safety + compile-cache warmup.

The pipelined batch loop (parallel/pipeline.py — PipelinedBatchLoop) may
overlap host encode/commit with the device step and donate input buffers,
but it must never change a decision: depth=1 (pipelined) and depth=0
(serial, identical dataflow) must produce bit-identical assignments on
streaming AND churn-feedback workloads, with donation enabled and disabled.
The scheduler's deferred commit fan-out (scheduler.py —
_flush_deferred_binds) carries the same obligation against the fully
synchronous loop (KTPU_PIPELINE=0)."""

import os

import numpy as np
import pytest

from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.parallel.pipeline import PipelinedBatchLoop, run_serial
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration

from helpers import mk_node, mk_pod


def _wave(seed: int, n_nodes: int = 10, n_pods: int = 20) -> Snapshot:
    rng = np.random.default_rng(seed)
    nodes = [
        mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
        for i in range(n_nodes)
    ]
    pods = [
        mk_pod(f"w{seed}-p{j}", cpu=int(rng.integers(100, 1500)))
        for j in range(n_pods)
    ]
    return Snapshot(nodes=nodes, pending_pods=pods)


@pytest.mark.parametrize("donate", [False, True])
def test_streaming_parity_pipelined_vs_serial(donate):
    """Independent snapshot stream: identical verdict dicts, wave for wave."""
    waves = [_wave(s) for s in range(5)]
    pipelined = list(PipelinedBatchLoop(donate=donate, depth=1).run(waves))
    serial = list(run_serial(waves, donate=donate))
    assert pipelined == serial
    assert len(pipelined) == 5
    for verdicts in pipelined:
        assert sum(1 for v in verdicts.values() if v) > 0


def _feedback_stream(loop: PipelinedBatchLoop, n_waves: int = 6):
    """Churn-feedback workload with the pipeline's one-wave lag: wave w
    binds wave w-2's placements on a SHARED node set (capacity coupling
    across waves), wave w-3's pods complete.  Returns every wave's
    assignments in order."""
    nodes = [mk_node(f"n{i}", cpu=4000, pods=32) for i in range(8)]

    def mk(w):
        return [mk_pod(f"c{w}-p{j}", cpu=300 + 100 * (j % 5)) for j in range(16)]

    import dataclasses

    wave_pods = {}
    fetched = {}
    out = []
    for w in range(n_waves):
        if w - 2 in fetched:
            src = w - 2
            bound = [
                dataclasses.replace(p, node_name=fetched[src][p.name])
                for p in wave_pods[src]
                if fetched[src].get(p.name)
            ]
        else:
            bound = []
        wave_pods[w] = mk(w)
        v = loop.submit(
            Snapshot(nodes=nodes, pending_pods=wave_pods[w], bound_pods=bound)
        )
        if v is not None:
            fetched[w - 1] = v
            out.append(v)
    v = loop.drain()
    if v is not None:
        fetched[n_waves - 1] = v
        out.append(v)
    return out


@pytest.mark.parametrize("donate", [False, True])
def test_churn_feedback_parity_pipelined_vs_serial(donate):
    """Dependent (capacity-coupled) wave stream through the SAME lag-1
    dataflow at depth=1 and depth=0: assignments and scheduled counts are
    bit-identical — overlap and donation change wall time only."""
    pipelined = _feedback_stream(PipelinedBatchLoop(donate=donate, depth=1))
    serial = _feedback_stream(PipelinedBatchLoop(donate=donate, depth=0))
    assert pipelined == serial
    assert [sum(1 for v in w.values() if v) for w in pipelined] == [
        sum(1 for v in w.values() if v) for w in serial
    ]
    # the stream actually exercised contention (some pod ever unscheduled
    # would be too strong; assert capacity coupling moved placements)
    assert len(pipelined) == 6


def test_donation_enabled_and_disabled_agree():
    """Donation is a memory optimization, never a decision input."""
    waves = [_wave(s, n_nodes=6, n_pods=12) for s in range(3)]
    don = list(PipelinedBatchLoop(donate=True, depth=1).run(waves))
    plain = list(PipelinedBatchLoop(donate=False, depth=1).run(waves))
    assert don == plain


def test_donated_buffer_never_reread_by_host():
    """Donation safety: the loop transfers fresh device buffers per wave
    (the encoder's resident-reuse table stays empty, so no later cycle can
    re-read a donated buffer), and the donated input is actually consumed
    — on backends that honor donation the aliased node_used buffer is
    deleted after the step."""
    from kubernetes_tpu.ops.assign import donation_supported

    loop = PipelinedBatchLoop(donate=True, depth=1)
    list(loop.run([_wave(0, n_nodes=6, n_pods=12), _wave(1, n_nodes=6, n_pods=12)]))
    assert loop.stats["donated"] == 2
    # fresh-transfer mode: nothing recorded for reuse -> nothing to re-read
    assert loop.enc._dev == {}
    if donation_supported():
        probe = loop.last_donated_probe
        assert probe is not None and any(b.is_deleted() for b in probe), (
            "no donated input buffer was consumed by the step"
        )


def test_nondonating_fallback_routes_plain_kernel(monkeypatch):
    """KTPU_DONATE=0 (the rejecting-backend fallback) must route the plain
    kernel and keep resident-buffer reuse intact."""
    monkeypatch.setenv("KTPU_DONATE", "0")
    loop = PipelinedBatchLoop(donate=None, depth=1)
    assert loop.donate is False
    verdicts = list(loop.run([_wave(3, n_nodes=6, n_pods=12)]))
    assert len(verdicts) == 1 and loop.stats["donated"] == 0
    assert loop.last_donated_probe is None


def _churn_store_run(pipeline: bool):
    os.environ["KTPU_PIPELINE"] = "1" if pipeline else "0"
    try:
        store = ClusterStore()
        for i in range(6):
            store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
        import copy

        for i in range(24):
            store.add_pod(mk_pod(f"p{i}", cpu=250))
        sched.run_until_idle()
        # churn rounds: delete a third of the bound pods, re-add equivalents
        import random

        rng = random.Random(7)
        for r in range(3):
            bound = sorted(
                (p for p in store.pods.values() if p.node_name),
                key=lambda p: p.uid,
            )
            for v in rng.sample(bound, 8):
                store.delete_pod(v.uid)
                q = copy.copy(v)
                q.name = f"{v.name}-r{r}"
                q.uid = ""
                q.node_name = ""
                q.__post_init__()
                store.add_pod(q)
            sched.run_until_idle()
        placements = {
            p.name: p.node_name for p in store.pods.values()
        }
        events = len(sched.events.by_reason("Scheduled"))
        return placements, events
    finally:
        os.environ.pop("KTPU_PIPELINE", None)


def test_scheduler_deferred_commit_parity_on_churn():
    """run_until_idle with pipelined (deferred) commits vs the synchronous
    loop: identical placements and Scheduled-event counts across a
    streaming + churn workload; every deferred bind is store-visible by
    the time run_until_idle returns."""
    pipe_placements, pipe_events = _churn_store_run(pipeline=True)
    sync_placements, sync_events = _churn_store_run(pipeline=False)
    assert pipe_placements == sync_placements
    assert pipe_events == sync_events
    assert all(v for v in pipe_placements.values())


def test_scheduler_flushes_deferred_binds_at_drain():
    """No pod may linger assumed-but-unpublished after run_until_idle."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=8000, pods=64))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for i in range(10):
        store.add_pod(mk_pod(f"p{i}", cpu=100))
    sched.run_until_idle()
    assert sched._deferred_binds == []
    assert all(p.node_name == "n0" for p in store.pods.values())
    assert len(sched.events.by_reason("Scheduled")) == 10
    # capacity was reserved via assume during the cycle; after the flush
    # the assumptions are retired by the store's bind events
    assert sched.cache.assumed == {}


def test_compile_cache_and_aot_warmup(tmp_path):
    """maybe_enable_compile_cache + warm_kernels write serialized
    executables to the cache dir — the artifact a second process loads
    instead of re-paying the cold compile.  Runs in a SUBPROCESS: the
    persistent cache only writes on a real (in-process-cache-missing)
    compile, which a long pytest process cannot guarantee."""
    import subprocess
    import sys

    cache = str(tmp_path / "cc")
    prog = (
        "from kubernetes_tpu.bench._cpu import force_cpu_from_env\n"
        "force_cpu_from_env()\n"
        "from kubernetes_tpu.ops import aot\n"
        f"assert aot.maybe_enable_compile_cache() == {cache!r}\n"
        "from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot\n"
        "from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config\n"
        "from helpers import mk_node, mk_pod\n"
        "snap = Snapshot(nodes=[mk_node('n%d' % i) for i in range(4)],\n"
        "                pending_pods=[mk_pod('p%d' % j) for j in range(6)])\n"
        "arr, _ = encode_snapshot(snap)\n"
        "cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)\n"
        "assert aot.warm_kernels(arr, cfg) >= 2\n"
    )
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KTPU_COMPILE_CACHE_DIR=cache, PYTHONPATH=tests_dir)
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=os.path.dirname(tests_dir))
    assert r.returncode == 0, r.stderr[-2000:]
    entries = [f for f in os.listdir(cache) if f.endswith("-cache")]
    assert entries, "AOT warmup wrote no persistent-cache entries"


def test_sidecar_trace_context_crosses_the_wire():
    """The client stamps the active span's trace_id/span_id into gRPC
    metadata; the server rebuilds the context — the sidecar.schedule span
    lands in the SAME trace, parented under the client-side span (the
    ROADMAP open item: one connected Perfetto tree per sidecar-routed
    wave)."""
    from kubernetes_tpu.runtime import TPUScoreClient, TPUScoreServer
    from kubernetes_tpu.scheduler.tracing import TraceCollector, Tracer

    col = TraceCollector()
    srv = TPUScoreServer(collector=col)
    srv.start()
    try:
        client = TPUScoreClient(f"127.0.0.1:{srv.port}")
        snap = Snapshot(
            nodes=[mk_node("a"), mk_node("b")],
            pending_pods=[mk_pod("p0"), mk_pod("p1")],
        )
        tracer = Tracer(col, component="scheduler")
        with tracer.span("batch.cycle") as cycle:
            client.schedule(snap, deadline_ms=60_000)
        client.close()
    finally:
        srv.stop()
    [sc] = col.spans(name="sidecar.schedule")
    assert sc.trace_id == cycle.trace_id
    assert sc.parent_id == cycle.span_id
    assert sc.component == "sidecar"


def test_sidecar_without_active_span_starts_fresh_trace():
    """No active client span -> no metadata -> the server span roots its
    own trace (never crashes, never inherits a stale parent)."""
    from kubernetes_tpu.runtime import TPUScoreClient, TPUScoreServer
    from kubernetes_tpu.scheduler.tracing import TraceCollector

    col = TraceCollector()
    srv = TPUScoreServer(collector=col)
    srv.start()
    try:
        client = TPUScoreClient(f"127.0.0.1:{srv.port}")
        snap = Snapshot(nodes=[mk_node("a")], pending_pods=[mk_pod("p0")])
        client.schedule(snap, deadline_ms=60_000)
        client.close()
    finally:
        srv.stop()
    [sc] = col.spans(name="sidecar.schedule")
    assert sc.parent_id == ""


def test_pipeline_smoke_overlap_and_route():
    """CI smoke (satellite): a small streaming workload through the
    pipelined loop reports the kernel route taken and a NONZERO overlap
    fraction; --no-pipeline reports exactly zero.

    SCALE-AWARE assertion (the pre-existing flake fix): overlap is only
    observable when the device step is still running while a host phase
    samples it — at smoke scale on a loaded box the step can finish
    first and the fraction legitimately reads 0.0.  Rather than pinning
    one wave size (right for one box, flaky on another), the test walks
    an escalation ladder of wave sizes until overlap is observed; only a
    box where even the largest wave's device step is invisible fails —
    which would be a real accounting bug, not load noise."""
    from kubernetes_tpu.bench.harness import run_streaming_workload

    ladder = [(48, 96), (128, 512), (256, 2048)]
    out = None
    for n_nodes, n_pods in ladder:
        waves = [_wave(s, n_nodes=n_nodes, n_pods=n_pods) for s in range(4)]
        out = run_streaming_workload(
            f"smoke-{n_pods}", waves, warmup=True)
        assert out["waves"] == 4 and out["n_pods"] == 4 * n_pods
        assert sum(out["route_trace_counts"].values()) > 0
        if out["overlap_fraction"] > 0.0:
            break
    assert out["overlap_fraction"] > 0.0, (
        f"no overlap observed even at {ladder[-1]} waves — the overlap "
        "accounting lost the device step"
    )
    off_waves = [_wave(s, n_nodes=48, n_pods=96) for s in range(4)]
    off = run_streaming_workload("smoke-off", off_waves, warmup=False,
                                 pipeline=False)
    assert off["overlap_fraction"] == 0.0 and off["pipelined_s"] is None
