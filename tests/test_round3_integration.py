"""Round-3 cross-feature soak: QueueingHint parking, gang Permit-wait,
batched preemption, the volume-aware delta encoder, and kubelet pod workers
all running against one store through churn — asserting global invariants
the features could violate in combination (stranded assumptions, phantom
nominations, broken gang atomicity, delta-vs-full divergence)."""

import random

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.kubelet import HollowCluster
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import mk_node, mk_pod


import pytest


@pytest.mark.parametrize("seed", [42, 7])
def test_round3_churn_soak_invariants(seed):
    rng = random.Random(seed)
    clock = FakeClock()
    store = ClusterStore()
    for i in range(10):
        store.add_node(mk_node(f"n{i}", cpu=4000, pods=20,
                               labels={t.LABEL_ZONE: f"z{i % 3}"}))
    from kubernetes_tpu.scheduler.config import PluginSpec, Profile

    cfg = SchedulerConfiguration(
        mode="tpu",
        profiles=(
            Profile(),
            # a second profile with its own weights: profile dispatch rides
            # through the same churn (one profile per batch cycle, the other
            # requeued without backoff accrual)
            Profile(
                scheduler_name="packer",
                plugins=(
                    PluginSpec(name="NodeResourcesBalancedAllocation",
                               enabled=False),
                ),
            ),
        ),
    )
    sched = Scheduler(store, cfg, clock=clock)
    leases = LeaseStore(clock=clock)
    hollow = HollowCluster(store, leases)

    serial = 0
    for cycle in range(30):
        kind = rng.random()
        if kind < 0.45:  # plain pods, some short-lived, some on profile 2
            for _ in range(rng.randint(1, 6)):
                p = mk_pod(f"p{serial}", cpu=rng.choice([100, 400, 900]),
                           labels={"app": rng.choice(["web", "db"])},
                           run_seconds=rng.choice([0, 0, 2.0]))
                if rng.random() < 0.3:
                    p.scheduler_name = "packer"
                store.add_pod(p)
                serial += 1
        elif kind < 0.6:  # a gang wave (its own PodGroup: quorum is per wave)
            g = f"crew{serial}"
            sched.cache.pod_groups[g] = t.PodGroup(name=g, min_member=3)
            for m in range(3):
                # gangs outrank the preemptors: eviction tearing a gang apart
                # is expected reference semantics (coscheduling + preemption),
                # so keep it out of THIS invariant's way via priority
                store.add_pod(mk_pod(f"{g}-{m}", cpu=600, pod_group=g,
                                     priority=50))
            serial += 1
        elif kind < 0.75:  # preemptors that outrank plain pods ONLY
            store.add_pod(mk_pod(f"vip{serial}", cpu=3500, priority=30))
            serial += 1
        elif kind < 0.9:  # spread-constrained pod
            store.add_pod(
                mk_pod(
                    f"s{serial}", cpu=200, labels={"app": "web"},
                    topology_spread=(
                        t.TopologySpreadConstraint(
                            max_skew=2, topology_key=t.LABEL_ZONE,
                            when_unsatisfiable=t.DO_NOT_SCHEDULE,
                            label_selector=t.LabelSelector.of(app="web"),
                        ),
                    ),
                )
            )
            serial += 1
        else:  # delete a random bound non-gang pod (gang deletion is legal
            # but would make the per-wave atomicity count unobservable)
            bound = [p for p in store.pods.values()
                     if p.node_name and not p.pod_group]
            if bound:
                store.delete_pod(rng.choice(bound).uid)
        sched.run_until_idle()
        hollow.tick()
        clock.step(rng.choice([0.5, 1.5, 12.0]))
        sched.run_until_idle()

        # --- invariants, every cycle ---
        # 1. no stranded gang waiters at quiescence beyond live groups
        for g, ws in sched._gang_waiting.items():
            assert all(w[0].uid in store.pods for w in ws)
        # 2. gang atomicity: bound members of "crew" come in multiples the
        #    fixpoint produced (never 1 or 2 of a 3-gang)
        crew_by_wave = {}
        for p in store.pods.values():
            if p.pod_group and p.node_name:
                crew_by_wave.setdefault(p.pod_group, 0)
                crew_by_wave[p.pod_group] += 1
        assert all(c >= 3 for c in crew_by_wave.values()), crew_by_wave
        # 3. nominations only for live, still-pending pods
        for uid in sched.queue.nominated:
            cur = store.pods.get(uid)
            assert cur is None or not cur.node_name
        # 3b. no phantom backoff: pods that were merely requeued by
        #     another profile's batch cycle carry at most one attempt more
        #     than their real failures would explain (coarse bound: attempt
        #     counts stay small for pods that never failed)
        # 4. per-node capacity never exceeded by BOUND pods
        for nd in store.nodes.values():
            used = sum(
                p.requests.get(t.CPU, 0)
                for p in store.pods.values()
                if p.node_name == nd.name
                and p.phase not in (t.PHASE_SUCCEEDED, t.PHASE_FAILED)
            )
            assert used <= nd.allocatable[t.CPU], (nd.name, used)

    # settle: everything still pending is genuinely blocked, and the resident
    # delta encoder's decisions still match a from-scratch encoder's
    clock.step(30.0)
    sched.run_until_idle()
    import numpy as np

    from kubernetes_tpu.api.delta import DeltaEncoder
    from kubernetes_tpu.api.volumes import resolve_snapshot
    from kubernetes_tpu.ops import schedule_batch
    from kubernetes_tpu.ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config

    snap = sched.cache.update_snapshot()
    if sched._delta_enc is not None and snap.pending_pods:
        got_arr, gm = sched._delta_enc.encode(snap)
        want_arr, wm = DeltaEncoder().encode(snap)
        cfg = infer_score_config(want_arr, DEFAULT_SCORE_CONFIG)
        g = np.asarray(schedule_batch(got_arr, cfg)[0])[: gm.n_pods]
        w = np.asarray(schedule_batch(want_arr, cfg)[0])[: wm.n_pods]
        np.testing.assert_array_equal(g, w)
