"""Gang all-or-nothing on the CPU path: the coscheduling Permit-wait
(scheduler — _gang_waiting, the waiting_pods_map.go analog) must preserve
group atomicity exactly when the sidecar deadline forces the per-pod CPU
fallback — the round-2 verdict's behavior-preservation gap."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.config import Profile, TPUScoreArgs
from helpers import mk_node, mk_pod


def _gang_cluster(store):
    # 2 nodes x 2000 cpu: "fits" (3 x 1000) can place, "toobig" (3 x 1500)
    # can place at most 2 members — must bind NONE
    for i in range(2):
        store.add_node(mk_node(f"n{i}", cpu=2000, pods=10))
    for i in range(3):
        store.add_pod(mk_pod(f"fits-{i}", cpu=1000, pod_group="fits"))
    for i in range(3):
        store.add_pod(mk_pod(f"toobig-{i}", cpu=1500, pod_group="toobig"))


def _groups():
    return {
        "fits": t.PodGroup(name="fits", min_member=3),
        "toobig": t.PodGroup(name="toobig", min_member=3),
    }


def _bound_by_group(store):
    out = {"fits": 0, "toobig": 0}
    for p in store.pods.values():
        if p.node_name and p.pod_group:
            out[p.pod_group] += 1
    return out


def test_cpu_mode_gang_atomicity():
    store = ClusterStore()
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"))
    sched.cache.pod_groups.update(_groups())
    _gang_cluster(store)
    sched.run_until_idle()
    got = _bound_by_group(store)
    assert got == {"fits": 3, "toobig": 0}, got


def test_sidecar_down_fallback_preserves_gang_atomicity():
    """The mandated CPU fallback (sidecar deadline) must match the batch
    path's quorum outcome on the same snapshot."""
    # batch path outcome (tpu mode, no sidecar)
    store_b = ClusterStore()
    sched_b = Scheduler(store_b, SchedulerConfiguration(mode="tpu"))
    sched_b.cache.pod_groups.update(_groups())
    _gang_cluster(store_b)
    sched_b.run_until_idle()
    want = _bound_by_group(store_b)
    assert want == {"fits": 3, "toobig": 0}, want

    # fallback path: dead sidecar endpoint -> per-pod CPU loop
    prof = Profile(tpu_score=TPUScoreArgs(sidecar_address="127.0.0.1:1", deadline_ms=150))
    store_f = ClusterStore()
    sched_f = Scheduler(store_f, SchedulerConfiguration(mode="tpu", profiles=(prof,)))
    sched_f.cache.pod_groups.update(_groups())
    _gang_cluster(store_f)
    sched_f.run_until_idle()
    assert sched_f.metrics.counters["tpuscore_fallback_total"] >= 1
    got = _bound_by_group(store_f)
    assert got == want, (got, want)
    # no partial bind ever surfaced for the failed gang
    assert all(
        not (p.node_name and p.pod_group == "toobig") for p in store_f.pods.values()
    )


def test_fallback_gang_capacity_released_after_reject():
    """Rejected waiters must release their assumed capacity: a later plain
    pod fits where the incomplete gang was holding reservations."""
    prof = Profile(tpu_score=TPUScoreArgs(sidecar_address="127.0.0.1:1", deadline_ms=150))
    store = ClusterStore()
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu", profiles=(prof,)))
    sched.cache.pod_groups.update({"g": t.PodGroup(name="g", min_member=3)})
    store.add_node(mk_node("n0", cpu=2000, pods=10))
    for i in range(2):  # only 2 of 3 members exist
        store.add_pod(mk_pod(f"g-{i}", cpu=800, pod_group="g"))
    sched.run_until_idle()
    assert _bound_by_group(store).get("g", 0) == 0
    # both members took the Permit-reject path (waited, then rejected at drain)
    rejected = [
        e for e in sched.events.by_reason("FailedScheduling")
        if "below quorum" in e.message
    ]
    assert len(rejected) == 2 and all("g-" in e.pod for e in rejected)
    store.add_pod(mk_pod("plain", cpu=1800))
    sched.run_until_idle()
    assert store.pods["default/plain"].node_name == "n0"
