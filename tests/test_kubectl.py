"""kubectl analog (the CLI/UX layer) + the manifest codec behind it.

reference: staging/src/k8s.io/kubectl/pkg/cmd/ verbs over client-go, and
apimachinery's universal decoder (kind-dispatched strict decoding).
"""

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import serialize as ser
from kubernetes_tpu.api import types as t
from kubernetes_tpu.kubectl import Kubectl, KubectlError, make_admin_kubectl, resolve_kind
from kubernetes_tpu.scheduler.auth import TokenAuthenticator, bind_cluster_role
from kubernetes_tpu.scheduler.apiserver import APIServer
from kubernetes_tpu.scheduler.store import ClusterStore


# ------------------------------------------------------------- serializer


def test_serialize_roundtrip_pod_with_nested_constraints():
    p = t.Pod(
        name="web-1",
        requests={"cpu": 1000, "memory": 1 << 30},
        labels={"app": "web"},
        node_selector=(("disk", "ssd"),),
        tolerations=(t.Toleration(key="gpu", operator="Exists", effect="NoSchedule"),),
        affinity=t.Affinity(
            required_node_terms=(
                t.NodeSelectorTerm(
                    match_expressions=(
                        t.NodeSelectorRequirement("zone", "In", ("a", "b")),
                    )
                ),
            )
        ),
        topology_spread=(
            t.TopologySpreadConstraint(1, "zone", label_selector=t.LabelSelector.of(app="web")),
        ),
    )
    [p2] = ser.load_yaml(ser.dump_yaml(p))
    assert p2 == p


def test_serialize_mapping_sugar_for_pair_tuples():
    [p] = ser.load_yaml("kind: Pod\nname: x\nnode_selector: {disk: ssd}\n")
    assert p.node_selector == (("disk", "ssd"),)


def test_serialize_strict_unknown_field_and_kind():
    with pytest.raises(ser.DecodeError):
        ser.load_yaml("kind: Pod\nname: x\nbogus: 1\n")
    with pytest.raises(ser.DecodeError):
        ser.load_yaml("kind: Gadget\nname: x\n")


def test_serialize_list_document_flattens():
    objs = ser.load_yaml(
        "kind: List\nitems:\n- {kind: Node, name: n1}\n- {kind: Node, name: n2}\n"
    )
    assert [o.name for o in objs] == ["n1", "n2"]


# ------------------------------------------------------------- kubectl


@pytest.fixture
def kc():
    k = make_admin_kubectl()
    for name, cpu in (("n1", 4000), ("n2", 8000)):
        k.api.store.add_node(t.Node(name=name, allocatable={"cpu": cpu, "memory": 1 << 33}))
    return k


def test_get_nodes_table_and_yaml(kc):
    out = kc.run("get nodes")
    assert "n1" in out and "n2" in out and "NAME" in out
    out = kc.run("get node n1 -o yaml")
    [n] = ser.load_yaml(out)
    assert n.name == "n1" and n.allocatable["cpu"] == 4000


def test_apply_create_get_delete_pod(kc, tmp_path):
    f = tmp_path / "pod.yaml"
    f.write_text("kind: Pod\nname: web-0\nrequests: {cpu: 500}\nlabels: {app: web}\n")
    assert "created" in kc.run(f"apply -f {f}")
    assert "configured" in kc.run(f"apply -f {f}")  # idempotent update
    out = kc.run("get pods")
    assert "web-0" in out and "Pending" in out
    # selector filtering
    assert "web-0" in kc.run("get pods -l app=web")
    assert "No resources found" in kc.run("get pods -l app=nope")
    assert "deleted" in kc.run("delete pod web-0")
    with pytest.raises(KubectlError, match="NotFound"):
        kc.run("get pod web-0")


def test_create_rejects_duplicate(kc, tmp_path):
    f = tmp_path / "ns.yaml"
    f.write_text("kind: Namespace\nname: prod\n")
    kc.run(f"create -f {f}")
    with pytest.raises(KubectlError, match="AlreadyExists"):
        kc.run(f"create -f {f}")


def test_cordon_uncordon_and_taint(kc):
    assert "cordoned" in kc.run("cordon n1")
    assert kc.api.store.nodes["n1"].unschedulable
    assert "uncordoned" in kc.run("uncordon n1")
    assert not kc.api.store.nodes["n1"].unschedulable

    kc.run("taint nodes n1 dedicated=tpu:NoSchedule")
    assert kc.api.store.nodes["n1"].taints == (t.Taint("dedicated", "tpu", "NoSchedule"),)
    kc.run("taint nodes n1 dedicated:NoSchedule-")
    assert kc.api.store.nodes["n1"].taints == ()


def test_label_add_overwrite_remove(kc):
    kc.run("label node n1 tier=hot")
    assert kc.api.store.nodes["n1"].labels["tier"] == "hot"
    with pytest.raises(KubectlError, match="overwrite"):
        kc.run("label node n1 tier=cold")
    kc.run("label node n1 tier=cold --overwrite")
    assert kc.api.store.nodes["n1"].labels["tier"] == "cold"
    kc.run("label node n1 tier-")
    assert "tier" not in kc.api.store.nodes["n1"].labels


def test_scale_deployment(kc):
    kc.api.store.add_object("Deployment", t.Deployment(name="web", replicas=1))
    assert "scaled" in kc.run("scale deployment/web --replicas=5")
    assert kc.api.store.objects["Deployment"]["default/web"].replicas == 5


def test_top_nodes_uses_requests(kc):
    kc.api.store.add_pod(
        t.Pod(name="p", requests={"cpu": 2000, "memory": 1 << 32}, node_name="n1")
    )
    out = kc.run("top nodes")
    assert "50%" in out  # 2000/4000 cpu on n1


def test_drain_respects_pdb_then_force(kc):
    store = kc.api.store
    store.add_pod(t.Pod(name="a", labels={"app": "db"}, node_name="n1"))
    store.add_pdb(
        t.PodDisruptionBudget(
            name="db-pdb", selector=t.LabelSelector.of(app="db"), min_available=1
        )
    )
    with pytest.raises(KubectlError, match="PodDisruptionBudget"):
        kc.run("drain n1")
    # budget blocks eviction but the node is already cordoned
    assert store.nodes["n1"].unschedulable
    out = kc.run("drain n1 --disable-eviction")
    assert "drained" in out
    assert not any(p.node_name == "n1" for p in store.pods.values())


def test_drain_daemonset_pods_need_flag(kc):
    store = kc.api.store
    store.add_pod(
        t.Pod(
            name="ds-x",
            node_name="n2",
            owner_references=(t.OwnerReference("DaemonSet", "ds", "ds/default/ds"),),
        )
    )
    with pytest.raises(KubectlError, match="ignore-daemonsets"):
        kc.run("drain n2")
    assert "drained" in kc.run("drain n2 --ignore-daemonsets")
    # DaemonSet pod survives the drain
    assert any(p.name == "ds-x" for p in store.pods.values())


def test_rollout_status(kc):
    store = kc.api.store
    d = t.Deployment(name="web", replicas=2)
    store.add_object("Deployment", d)
    rs = t.ReplicaSet(
        name="web-abc",
        replicas=2,
        ready_replicas=0,
        owner_references=(t.OwnerReference("Deployment", "web", d.uid),),
    )
    store.add_object("ReplicaSet", rs)
    assert "Waiting" in kc.run("rollout status deployment/web")
    rs.ready_replicas = 2
    store.update_object("ReplicaSet", rs)
    assert "successfully rolled out" in kc.run("rollout status deployment/web")


def test_auth_can_i_respects_rbac():
    store = ClusterStore()
    authn = TokenAuthenticator()
    authn.add_token("admin-token", "admin", groups=("system:masters",))
    authn.add_token("viewer-token", "viewer")
    store.add_object(
        "Role",
        c.Role(name="view", rules=(c.PolicyRule(verbs=("get", "list"), resources=("pods",)),)),
    )
    bind_cluster_role(store, "view-binding", "view", [("User", "viewer")])
    api = APIServer(store, authenticator=authn)
    admin = Kubectl(api, "admin-token")
    viewer = Kubectl(api, "viewer-token")
    assert admin.run("auth can-i delete nodes").strip() == "yes"
    assert viewer.run("auth can-i list pods").strip() == "yes"
    assert viewer.run("auth can-i delete pods").strip() == "no"
    # and the verbs actually enforce it
    with pytest.raises(KubectlError, match="Forbidden|cannot"):
        viewer.run("cordon n1")


def test_pv_pvc_via_api_and_cli(kc, tmp_path):
    f = tmp_path / "vol.yaml"
    f.write_text(
        "kind: PersistentVolume\nname: pv-a\ncapacity: 100\nstorage_class: fast\n"
        "---\nkind: PersistentVolumeClaim\nname: claim-a\nrequest: 50\nstorage_class: fast\n"
    )
    kc.run(f"apply -f {f}")
    assert "pv-a" in kc.run("get pv")
    out = kc.run("get pvc")
    assert "claim-a" in out and "Pending" in out
    kc.run("delete pvc claim-a")
    assert "No resources found" in kc.run("get pvc")


def test_api_resources_and_version(kc):
    out = kc.run("api-resources")
    assert "pods" in out and "storageclasses" in out
    assert "kubectl" in kc.run("version")


def test_resolve_kind_rejects_unknown():
    with pytest.raises(KubectlError):
        resolve_kind("gadgets")


def test_get_selector_existence_and_bad_rollout_usage(kc):
    kc.api.store.add_pod(t.Pod(name="lbl", labels={"app": "x", "canary": ""}))
    assert "lbl" in kc.run("get pods -l canary")          # existence term
    assert "No resources found" in kc.run("get pods -l nope")
    with pytest.raises(KubectlError, match="usage"):
        kc.run("rollout status deployment")


def test_get_resourceclaims_and_csrs():
    """Round-4 kinds ride the same verb machinery: resourceclaims are
    namespaced, certificatesigningrequests cluster-scoped with the csr
    shortname."""
    from kubernetes_tpu.kubectl import make_admin_kubectl

    store = ClusterStore()
    store.add_object(
        "ResourceClaim", c.ResourceClaim(name="claim-a", device_class="gpu")
    )
    store.add_object(
        "CertificateSigningRequest",
        c.CertificateSigningRequest(name="n0-serving",
                                    username="system:node:n0"),
    )
    k = make_admin_kubectl(store)
    out = k.run(["get", "resourceclaims"])
    assert "claim-a" in out and "NAMESPACE" in out
    out = k.run(["get", "csr"])
    assert "n0-serving" in out and "NAMESPACE" not in out
    y = k.run(["get", "resourceclaim", "claim-a", "-o", "yaml"])
    assert "device_class: gpu" in y or "gpu" in y
