"""HTTP extender protocol (extender.go wire compat), Event API objects with
aggregation, and percentageOfNodesToScore adaptive sampling."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler.config import Profile, SchedulerConfiguration, validate
from kubernetes_tpu.scheduler.extender import ExtenderConfig
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.store import ClusterStore
from kubernetes_tpu.kubectl import make_admin_kubectl
from helpers import mk_node, mk_pod


class _ExtenderHandler(BaseHTTPRequestHandler):
    """A toy extender: filters out nodes named *-banned, prefers *-gold (score
    10), and records bind calls."""

    binds = []

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        if self.path.endswith("/filter"):
            names = [n for n in body["nodenames"] if not n.endswith("-banned")]
            failed = {n: "banned by extender" for n in body["nodenames"]
                      if n.endswith("-banned")}
            out = {"nodenames": names, "failedNodes": failed, "error": ""}
        elif self.path.endswith("/prioritize"):
            out = [{"host": n, "score": 10 if n.endswith("-gold") else 0}
                   for n in body["nodenames"]]
        elif self.path.endswith("/bind"):
            _ExtenderHandler.binds.append((body["podUID"], body["node"]))
            out = {"error": ""}
        else:
            out = {"error": f"unknown verb {self.path}"}
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture(scope="module")
def extender_server():
    srv = HTTPServer(("127.0.0.1", 0), _ExtenderHandler)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _sched(store, url="", **ext_kw):
    extenders = ()
    if url:
        extenders = (ExtenderConfig(url_prefix=url, **ext_kw),)
    return Scheduler(store, SchedulerConfiguration(mode="cpu", extenders=extenders))


def test_extender_filter_and_prioritize(extender_server):
    store = ClusterStore()
    store.add_node(mk_node("a-banned"))
    store.add_node(mk_node("b"))
    store.add_node(mk_node("c-gold"))
    sched = _sched(store, extender_server, filter_verb="filter",
                   prioritize_verb="prioritize")
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    # banned excluded; gold's +10 beats the index tie-break
    assert store.pods["default/p"].node_name == "c-gold"


def test_extender_bind_verb_takes_precedence(extender_server):
    _ExtenderHandler.binds.clear()
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    sched = _sched(store, extender_server, filter_verb="filter", bind_verb="bind")
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    assert _ExtenderHandler.binds == [("default/p", "n0")]
    assert store.pods["default/p"].node_name == "n0"


def test_nonignorable_extender_failure_requeues_pod():
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    # nothing listens here
    sched = _sched(store, "http://127.0.0.1:9", filter_verb="filter")
    store.add_pod(mk_pod("p"))
    sched.run_until_idle(5)
    assert store.pods["default/p"].node_name == ""  # cycle failed, requeued


def test_ignorable_extender_failure_is_skipped():
    store = ClusterStore()
    store.add_node(mk_node("n0"))
    sched = _sched(store, "http://127.0.0.1:9", filter_verb="filter",
                   ignorable=True)
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    assert store.pods["default/p"].node_name == "n0"


def test_extender_config_validation():
    errs = validate(SchedulerConfiguration(
        extenders=(ExtenderConfig(url_prefix="", bind_verb="bind"),)))
    assert any("urlPrefix" in e for e in errs)
    assert any("bindVerb requires filterVerb" in e for e in errs)


# ------------------------------------------------- percentageOfNodesToScore


def test_adaptive_sampling_stops_early_and_rotates():
    store = ClusterStore()
    for i in range(300):
        store.add_node(mk_node(f"n{i:03d}"))
    prof = Profile(percentage_of_nodes_to_score=40)  # want = max(100, 120)
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu", profiles=(prof,)))
    calls = []
    orig = sched._filter_with_nominated

    def counting(state, snap, pod, info, i):
        calls.append(info.node.name)
        return orig(state, snap, pod, info, i)

    sched._filter_with_nominated = counting
    store.add_pod(mk_pod("p0"))
    sched.run_until_idle()
    first = len(calls)
    assert first == 120  # stopped at numFeasibleNodesToFind, not 300
    cursor = sched._next_start_node_index
    assert cursor == 120  # rotating cursor advanced by processed count
    calls.clear()
    store.add_pod(mk_pod("p1"))
    sched.run_until_idle()
    assert calls[0] == f"n{cursor:03d}"  # next cycle starts where we left off


def test_default_percentage_scores_all_nodes():
    store = ClusterStore()
    for i in range(150):
        store.add_node(mk_node(f"n{i}"))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"))
    calls = []
    orig = sched._filter_with_nominated
    sched._filter_with_nominated = lambda *a: (calls.append(1), orig(*a))[1]
    store.add_pod(mk_pod("p"))
    sched.run_until_idle()
    assert len(calls) == 150


# --------------------------------------------------------- Event API objects


def test_scheduler_publishes_aggregated_events_and_kubectl_lists_them():
    kc = make_admin_kubectl()
    store = kc.api.store
    store.add_node(mk_node("n0", cpu=1000))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"))
    store.add_pod(mk_pod("ok", cpu=500))
    store.add_pod(mk_pod("huge", cpu=50_000))
    sched.run_until_idle(5)
    events = store.list_objects("Event")
    reasons = {e.reason for e in events}
    assert "Scheduled" in reasons and "FailedScheduling" in reasons
    # retries of the same failure aggregate into count, not new objects
    fails = [e for e in events if e.reason == "FailedScheduling"]
    assert len(fails) == 1
    out = kc.run("get events")
    assert "Scheduled" in out and "FailedScheduling" in out
    assert "Scheduled" in kc.run("events")  # the top-level alias works too


def test_events_attributed_to_pod_namespace_and_bounded():
    from kubernetes_tpu.scheduler.events import EventRecorder

    store = ClusterStore()
    rec = EventRecorder(store=store, publish_limit=3)
    rec.record("Scheduled", "prod/web", node="n1")
    rec.record("Scheduled", "default/web", node="n1")
    evs = store.list_objects("Event")
    assert {e.namespace for e in evs} == {"prod", "default"}  # no merging
    assert all(e.count == 1 for e in evs)
    # the cap evicts oldest objects
    for i in range(5):
        rec.record("Scheduled", f"default/p{i}", node="n1")
    assert len(store.list_objects("Event")) == 3


def test_extender_outage_does_not_trigger_preemption():
    """A dead non-ignorable extender must NOT evict victims — the retry hits
    the same dead extender, so preemption can never help."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=2000))
    victim = mk_pod("victim", cpu=800)
    victim.node_name = "n0"
    store.add_pod(victim)
    sched = _sched(store, "http://127.0.0.1:9", filter_verb="filter")
    high = mk_pod("high", cpu=800)  # fits WITHOUT eviction; only the
    high.priority = 100             # extender call fails
    store.add_pod(high)
    sched.run_until_idle(5)
    assert "default/victim" in store.pods  # not evicted
    assert store.pods["default/high"].node_name == ""


class _PreemptHandler(BaseHTTPRequestHandler):
    """A toy preemption-capable extender: rejects candidates on nodes whose
    name ends with -protected (extender.go — ProcessPreemption)."""

    calls = []

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        assert self.path.endswith("/preempt"), self.path
        _PreemptHandler.calls.append(body)
        kept = {
            node: meta
            for node, meta in body["nodeNameToMetaVictims"].items()
            if not node.endswith("-protected")
        }
        out = {"nodeNameToMetaVictims": kept, "error": ""}
        data = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


def test_extender_process_preemption_drops_protected_nodes():
    """Preemption offers the candidate victim map to preempt-verb extenders
    before picking a node; a node the extender rejects is never preempted
    even when it is otherwise the lexicographic best."""
    _PreemptHandler.calls = []
    srv = HTTPServer(("127.0.0.1", 0), _PreemptHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_port}"
        store = ClusterStore()
        # n0-protected would be the preferred candidate (lower index/equal
        # key); the extender forces n1
        store.add_node(mk_node("n0-protected", cpu=1000, pods=4))
        store.add_node(mk_node("n1", cpu=1000, pods=4))
        store.add_pod(mk_pod("v0", cpu=900, priority=0, node_name="n0-protected"))
        store.add_pod(mk_pod("v1", cpu=900, priority=0, node_name="n1"))
        cfg = SchedulerConfiguration(
            mode="cpu",
            extenders=(ExtenderConfig(url_prefix=url, preempt_verb="preempt"),),
        )
        from kubernetes_tpu.scheduler.queue import FakeClock

        clock = FakeClock()
        sched = Scheduler(store, cfg, clock=clock)
        store.add_pod(mk_pod("hi", cpu=900, priority=100))
        sched.run_until_idle()
        assert _PreemptHandler.calls, "extender was never offered candidates"
        offered = set(_PreemptHandler.calls[0]["nodeNameToMetaVictims"])
        assert offered == {"n0-protected", "n1"}
        clock.step(2.0)  # the preemptor retries after its backoff
        sched.run_until_idle()
        pods = {q.name: q.node_name for q in store.pods.values()}
        assert pods["hi"] == "n1"  # protected node never preempted
        assert "v0" in pods and pods["v0"] == "n0-protected"  # v0 survived
        assert "v1" not in pods  # v1 evicted
    finally:
        srv.shutdown()
