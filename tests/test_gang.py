"""Gang scheduling (all-or-nothing PodGroups) — BASELINE config 5 semantics."""

import random

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG
from kubernetes_tpu.ops.gang import schedule_with_gangs
from kubernetes_tpu.oracle.reference import oracle_schedule_with_gangs
from helpers import mk_node, mk_pod


def run_both(snap):
    arr, meta = encode_snapshot(snap)
    choices, _ = schedule_with_gangs(arr, DEFAULT_SCORE_CONFIG)
    got = [
        (meta.pod_names[k], meta.node_names[choices[k]] if choices[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule_with_gangs(snap)
    assert got == want, f"kernel={got} oracle={want}"
    return dict(got)


def test_gang_fits_entirely():
    pods = [mk_pod(f"g-{i}", cpu=500, pod_group="job") for i in range(4)]
    got = run_both(Snapshot(nodes=[mk_node("n0", cpu=4000)], pending_pods=pods))
    assert all(v == "n0" for v in got.values())


def test_gang_all_or_nothing_revoked():
    # group of 3 x 600m on a 1000m node: only 1 fits -> whole gang revoked
    pods = [mk_pod(f"g-{i}", cpu=600, pod_group="job") for i in range(3)]
    got = run_both(Snapshot(nodes=[mk_node("n0", cpu=1000)], pending_pods=pods))
    assert all(v is None for v in got.values())


def test_gang_revocation_frees_capacity_for_next_gang():
    # big gang (higher priority) cannot fully fit; once revoked, small gang fits
    big = [mk_pod(f"big-{i}", cpu=800, priority=10, pod_group="big") for i in range(3)]
    small = [mk_pod(f"small-{i}", cpu=500, pod_group="small") for i in range(2)]
    snap = Snapshot(nodes=[mk_node("n0", cpu=1000), mk_node("n1", cpu=1000)],
                    pending_pods=big + small)
    got = run_both(snap)
    assert all(got[f"big-{i}"] is None for i in range(3))
    assert all(got[f"small-{i}"] is not None for i in range(2))


def test_min_member_quorum():
    # minMember 2 of 3: gang sticks even though the third pod can't fit
    pods = [mk_pod(f"g-{i}", cpu=600, pod_group="job") for i in range(3)]
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=1000), mk_node("n1", cpu=700)],
        pending_pods=pods,
        pod_groups={"job": t.PodGroup(name="job", min_member=2)},
    )
    got = run_both(snap)
    assert sum(1 for v in got.values() if v is not None) == 2


def test_gangs_mixed_with_plain_pods():
    rng = random.Random(5)
    pods = [mk_pod(f"plain-{i}", cpu=rng.choice([100, 300])) for i in range(10)]
    pods += [mk_pod(f"gang-{i}", cpu=900, pod_group="heavy") for i in range(4)]
    snap = Snapshot(nodes=[mk_node(f"n{i}", cpu=2000) for i in range(3)], pending_pods=pods)
    run_both(snap)


def test_gang_fixpoint_on_chunked_scan_matches_plain():
    """Config-5 scale gangs (>=128 pods) route through the CHUNKED scan inside
    the gang revocation fixpoint; decisions must equal the plain per-pod scan
    driven through the same fixpoint."""
    import jax
    import numpy as np

    from kubernetes_tpu.api.snapshot import encode_snapshot
    from kubernetes_tpu.bench import workloads
    from kubernetes_tpu.ops.assign import _chunkable, schedule_scan
    from kubernetes_tpu.ops.gang import failed_groups, schedule_with_gangs
    from kubernetes_tpu.ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config

    snap = workloads.gang(n_groups=24, group_size=8, n_nodes=12, seed=11)
    arr, meta = encode_snapshot(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    assert _chunkable(arr, cfg), cfg
    chunked, _ = schedule_with_gangs(arr, cfg)

    # the same fixpoint over the plain scan
    import dataclasses

    plain_sb = jax.jit(schedule_scan, static_argnames=("cfg",))
    pod_valid = np.asarray(arr.pod_valid).copy()
    while True:
        arr_i = dataclasses.replace(arr, pod_valid=pod_valid)
        choices = np.asarray(plain_sb(arr_i, cfg)[0])
        bad = failed_groups(choices, np.asarray(arr.pod_group),
                            np.asarray(arr.group_min), active=pod_valid)
        if not bad.any():
            break
        pg = np.asarray(arr.pod_group)
        in_bad = bad[np.maximum(pg, 0)] & (pg >= 0) & pod_valid
        first_g = pg[int(np.argmax(in_bad))]
        pod_valid = pod_valid & ~((pg == first_g) & pod_valid)
    np.testing.assert_array_equal(chunked, choices)


def test_device_fixpoint_matches_host_loop():
    """gang_fixpoint_device (the lax.while_loop fixpoint, one async
    dispatch) must be bit-identical to the host revoke-one loop on
    randomized gang workloads — the sidecar's config-5 overlap rests on
    this parity."""
    from kubernetes_tpu.ops.gang import gang_fixpoint_device

    for seed in range(8):
        rng = random.Random(seed)
        nodes = [
            mk_node(f"n{i}", cpu=rng.choice([1000, 2000, 4000]))
            for i in range(rng.randint(2, 5))
        ]
        pods = []
        for g in range(rng.randint(1, 4)):
            size = rng.randint(2, 5)
            for i in range(size):
                pods.append(mk_pod(
                    f"g{g}-{i}", cpu=rng.choice([300, 600, 900]),
                    pod_group=f"grp{g}",
                ))
        for i in range(rng.randint(0, 3)):
            pods.append(mk_pod(f"solo{i}", cpu=rng.choice([200, 500])))
        snap = Snapshot(nodes=nodes, pending_pods=pods)
        arr, meta = encode_snapshot(snap)
        host_c, host_u = schedule_with_gangs(arr, DEFAULT_SCORE_CONFIG)
        dev_c, dev_u = (np.asarray(x) for x in gang_fixpoint_device(
            arr, DEFAULT_SCORE_CONFIG
        ))
        np.testing.assert_array_equal(host_c, dev_c, err_msg=f"seed {seed}")
        np.testing.assert_array_equal(host_u, dev_u, err_msg=f"seed {seed}")
