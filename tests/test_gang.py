"""Gang scheduling (all-or-nothing PodGroups) — BASELINE config 5 semantics."""

import random

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG
from kubernetes_tpu.ops.gang import schedule_with_gangs
from kubernetes_tpu.oracle.reference import oracle_schedule_with_gangs
from helpers import mk_node, mk_pod


def run_both(snap):
    arr, meta = encode_snapshot(snap)
    choices, _ = schedule_with_gangs(arr, DEFAULT_SCORE_CONFIG)
    got = [
        (meta.pod_names[k], meta.node_names[choices[k]] if choices[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule_with_gangs(snap)
    assert got == want, f"kernel={got} oracle={want}"
    return dict(got)


def test_gang_fits_entirely():
    pods = [mk_pod(f"g-{i}", cpu=500, pod_group="job") for i in range(4)]
    got = run_both(Snapshot(nodes=[mk_node("n0", cpu=4000)], pending_pods=pods))
    assert all(v == "n0" for v in got.values())


def test_gang_all_or_nothing_revoked():
    # group of 3 x 600m on a 1000m node: only 1 fits -> whole gang revoked
    pods = [mk_pod(f"g-{i}", cpu=600, pod_group="job") for i in range(3)]
    got = run_both(Snapshot(nodes=[mk_node("n0", cpu=1000)], pending_pods=pods))
    assert all(v is None for v in got.values())


def test_gang_revocation_frees_capacity_for_next_gang():
    # big gang (higher priority) cannot fully fit; once revoked, small gang fits
    big = [mk_pod(f"big-{i}", cpu=800, priority=10, pod_group="big") for i in range(3)]
    small = [mk_pod(f"small-{i}", cpu=500, pod_group="small") for i in range(2)]
    snap = Snapshot(nodes=[mk_node("n0", cpu=1000), mk_node("n1", cpu=1000)],
                    pending_pods=big + small)
    got = run_both(snap)
    assert all(got[f"big-{i}"] is None for i in range(3))
    assert all(got[f"small-{i}"] is not None for i in range(2))


def test_min_member_quorum():
    # minMember 2 of 3: gang sticks even though the third pod can't fit
    pods = [mk_pod(f"g-{i}", cpu=600, pod_group="job") for i in range(3)]
    snap = Snapshot(
        nodes=[mk_node("n0", cpu=1000), mk_node("n1", cpu=700)],
        pending_pods=pods,
        pod_groups={"job": t.PodGroup(name="job", min_member=2)},
    )
    got = run_both(snap)
    assert sum(1 for v in got.values() if v is not None) == 2


def test_gangs_mixed_with_plain_pods():
    rng = random.Random(5)
    pods = [mk_pod(f"plain-{i}", cpu=rng.choice([100, 300])) for i in range(10)]
    pods += [mk_pod(f"gang-{i}", cpu=900, pod_group="heavy") for i in range(4)]
    snap = Snapshot(nodes=[mk_node(f"n{i}", cpu=2000) for i in range(3)], pending_pods=pods)
    run_both(snap)
