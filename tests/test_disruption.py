"""PodDisruptionBudget: disruption-controller status math + PDB-aware
preemption (reference: pkg/controller/disruption — updatePdbStatus;
framework/preemption — filterPodsWithPDBViolation, pickOneNodeForPreemption's
fewest-violations-first criterion)."""

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.disruption import DisruptionController
from kubernetes_tpu.scheduler.plugins.cpu import _split_pdb_violating
from kubernetes_tpu.scheduler.queue import FakeClock

from helpers import mk_node, mk_pod


def mk_pdb(name, min_available=None, max_unavailable=None, **labels):
    return t.PodDisruptionBudget(
        name=name,
        selector=t.LabelSelector.of(**labels),
        min_available=min_available,
        max_unavailable=max_unavailable,
    )


def test_disruption_controller_status_min_available():
    store = ClusterStore()
    store.add_pdb(mk_pdb("web-pdb", min_available=2, app="web"))
    for i in range(3):
        store.add_pod(mk_pod(f"w{i}", labels={"app": "web"}, node_name="n0"))
    store.add_pod(mk_pod("other", labels={"app": "db"}, node_name="n0"))
    (pdb,) = DisruptionController(store).tick()
    assert pdb.expected_pods == 3
    assert pdb.current_healthy == 3
    assert pdb.desired_healthy == 2
    assert pdb.disruptions_allowed == 1


def test_disruption_controller_status_max_unavailable_and_unbound():
    store = ClusterStore()
    store.add_pdb(mk_pdb("web-pdb", max_unavailable=1, app="web"))
    store.add_pod(mk_pod("w0", labels={"app": "web"}, node_name="n0"))
    store.add_pod(mk_pod("w1", labels={"app": "web"}))  # pending: not healthy
    (pdb,) = DisruptionController(store).tick()
    assert pdb.expected_pods == 2
    assert pdb.current_healthy == 1
    assert pdb.desired_healthy == 1  # 2 expected - 1 maxUnavailable
    assert pdb.disruptions_allowed == 0


def test_split_pdb_violating_charges_evictions():
    pdb = mk_pdb("pdb", min_available=1, app="web")
    pdb.disruptions_allowed = 1
    pods = [mk_pod(f"w{i}", labels={"app": "web"}) for i in range(3)]
    violating, non_violating = _split_pdb_violating(pods, [pdb])
    # first eviction consumes the budget; the rest violate
    assert [p.name for p in non_violating] == ["w0"]
    assert [p.name for p in violating] == ["w1", "w2"]


def test_preemption_prefers_node_without_pdb_violation():
    clock = FakeClock()
    store = ClusterStore()
    # two identical one-pod nodes; victim on n0 is PDB-protected
    store.add_node(mk_node("n0", cpu=1000))
    store.add_node(mk_node("n1", cpu=1000))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"), clock=clock)
    store.add_pod(mk_pod("protected", cpu=800, labels={"app": "web"},
                         node_selector={t.LABEL_HOSTNAME: "n0"}))
    store.add_pod(mk_pod("plain", cpu=800, labels={"app": "db"},
                         node_selector={t.LABEL_HOSTNAME: "n1"}))
    sched.run_until_idle()
    pdb = mk_pdb("web-pdb", min_available=1, app="web")
    store.add_pdb(pdb)
    DisruptionController(store).tick()
    assert store.pdbs["default/web-pdb"].disruptions_allowed == 0

    # without PDBs the tie-break would pick n0 (lowest node index); the PDB
    # must steer the victim search to n1's unprotected pod
    store.add_pod(mk_pod("vip", cpu=800, priority=100))
    sched.run_until_idle()
    names = {p.name for p in store.pods.values()}
    assert "protected" in names
    assert "plain" not in names
    clock.step(2.0)
    sched.run_until_idle()
    assert store.pods["default/vip"].node_name == "n1"


def test_preemption_violates_pdb_only_as_last_resort():
    clock = FakeClock()
    store = ClusterStore()
    store.add_node(mk_node("only", cpu=1000))
    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"), clock=clock)
    store.add_pod(mk_pod("protected", cpu=800, labels={"app": "web"}))
    sched.run_until_idle()
    store.add_pdb(mk_pdb("web-pdb", min_available=1, app="web"))
    DisruptionController(store).tick()
    # only candidate violates the PDB; preemption still proceeds (the
    # reference's preemption ignores PDBs as a hard constraint — best effort)
    store.add_pod(mk_pod("vip", cpu=800, priority=100))
    sched.run_until_idle()
    assert "default/protected" not in store.pods
    clock.step(2.0)
    sched.run_until_idle()
    assert store.pods["default/vip"].node_name == "only"
