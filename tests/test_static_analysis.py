"""ktpu-verify (ISSUE 8): the AST invariant analyzer + lock-order checker.

Three layers under test:

  1. per-rule fixtures — one failing and one passing snippet per rule
     (KTPU001..KTPU005) plus the whole-package KTPU006 lock-order pass,
     proving each rule FIRES and each documented exemption holds;
  2. the engine machinery — line-number-free fingerprints, baseline
     suppression (required reasons, stale-entry surfacing, draft workflow),
     and the 0/1/2 exit-code contract shared with bench/regression.py;
  3. the runtime half — CheckedLock order recording, cycle detection from
     single-thread observations, and the acceptance gate: the package
     itself analyzes clean, and a seeded chaos storm run under
     KTPU_LOCK_CHECK=1 reports no lock-order cycle.
"""

import copy
import json
import os
import random
import threading

import pytest

import kubernetes_tpu
from kubernetes_tpu import chaos
from kubernetes_tpu.analysis import CheckedLock, LockOrderViolation, lockcheck
from kubernetes_tpu.analysis.__main__ import default_baseline, main as cli_main
from kubernetes_tpu.analysis.engine import (
    Baseline,
    BaselineError,
    ModuleInfo,
    analyze_package,
    analyze_source,
)
from kubernetes_tpu.analysis.lockorder import LockOrderAnalyzer
from kubernetes_tpu.analysis.rules import (
    ALL_RULES,
    CheapGateRule,
    DeterminismRule,
    DonationAliasingRule,
    KillSafetyRule,
    SnapshotListRule,
)
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration

from helpers import mk_node, mk_pod

ANY = "kubernetes_tpu/scheduler/somefile.py"


def _run(rule, source, relpath=ANY):
    return analyze_source(source, relpath, [rule()])


# --- KTPU001 kill-safety ---
def test_ktpu001_fires_on_bare_except():
    fs = _run(KillSafetyRule, "try:\n    work()\nexcept:\n    pass\n")
    assert len(fs) == 1 and fs[0].rule == "KTPU001"
    assert "swallow ProcessKilled" in fs[0].message


def test_ktpu001_fires_on_nontransparent_baseexception():
    src = "try:\n    work()\nexcept BaseException:\n    log()\n"
    assert len(_run(KillSafetyRule, src)) == 1


def test_ktpu001_transparent_reraise_is_legal():
    # bookkeeping-then-reraise (checkpoint.py's tmp cleanup) stays legal
    src = "try:\n    work()\nexcept BaseException:\n    cleanup()\n    raise\n"
    assert _run(KillSafetyRule, src) == []


def test_ktpu001_raise_as_binding_is_transparent():
    # `raise e` re-raising the handler's own un-rebound `as` binding is the
    # same exception object — ProcessKilled propagates unchanged
    src = ("try:\n    work()\nexcept BaseException as e:\n"
           "    cleanup()\n    raise e\n")
    assert _run(KillSafetyRule, src) == []
    # ...but a REBOUND binding is a conversion
    rebound = ("try:\n    work()\nexcept BaseException as e:\n"
               "    e = RuntimeError('other')\n    raise e\n")
    assert len(_run(KillSafetyRule, rebound)) == 1


def test_ktpu001_raise_conversion_is_not_transparent():
    # a conditional `raise Other(...)` before the final bare raise converts
    # ProcessKilled into a plain Exception that downstream recoveries catch
    src = (
        "try:\n"
        "    work()\n"
        "except BaseException:\n"
        "    if oops:\n"
        "        raise RuntimeError('converted')\n"
        "    raise\n"
    )
    fs = _run(KillSafetyRule, src)
    assert len(fs) == 1 and "swallow ProcessKilled" in fs[0].message


def test_ktpu001_except_exception_is_legal():
    # ProcessKilled is a BaseException BY CONSTRUCTION: Exception handlers
    # are transparent to it — the 21 recovery sites stay untouched
    src = "try:\n    work()\nexcept Exception:\n    recover()\n"
    assert _run(KillSafetyRule, src) == []


def test_ktpu001_fires_on_processkilled_outside_allowlist():
    src = "try:\n    work()\nexcept ProcessKilled:\n    return None\n"
    src = "def f():\n" + "\n".join("    " + l for l in src.splitlines()) + "\n"
    fs = _run(KillSafetyRule, src)
    assert len(fs) == 1 and "restart-driver allowlist" in fs[0].message


def test_ktpu001_allowlisted_restart_driver_may_catch_kill():
    src = (
        "def run_restartable(sched):\n"
        "    try:\n"
        "        sched.run()\n"
        "    except ProcessKilled:\n"
        "        return restart(sched)\n"
    )
    assert _run(KillSafetyRule, src,
                relpath="kubernetes_tpu/scheduler/scheduler.py") == []
    # ...but only in scheduler.py: the same code elsewhere is a finding
    assert len(_run(KillSafetyRule, src)) == 1


def test_ktpu001_streaming_restart_drivers_are_allowlisted():
    # the storm-proof streaming drivers joined the restart-driver family:
    # the stream wave-WAL replay loop and the open-loop HA takeover loop
    # may catch ProcessKilled — in THEIR modules only
    stream = (
        "def run_stream_restartable(waves):\n"
        "    try:\n"
        "        drive()\n"
        "    except ProcessKilled:\n"
        "        return replay_suffix()\n"
    )
    assert _run(KillSafetyRule, stream,
                relpath="kubernetes_tpu/parallel/pipeline.py") == []
    assert len(_run(KillSafetyRule, stream)) == 1
    replay = (
        "def replay_trace(trace):\n"
        "    try:\n"
        "        cycle()\n"
        "    except ProcessKilled:\n"
        "        return takeover()\n"
    )
    assert _run(KillSafetyRule, replay,
                relpath="kubernetes_tpu/bench/loadgen.py") == []
    assert len(_run(KillSafetyRule, replay)) == 1
    # ...and the allowlist entry covers exactly the named driver, nothing
    # else in the same module
    other = replay.replace("replay_trace", "some_helper")
    assert len(_run(KillSafetyRule, other,
                    relpath="kubernetes_tpu/bench/loadgen.py")) == 1


def test_ktpu001_allowlist_does_not_cover_same_named_methods():
    # the exemption is the MODULE-LEVEL driver, not any method that happens
    # to share its name
    src = (
        "class Foo:\n"
        "    def run_restartable(self):\n"
        "        try:\n"
        "            work()\n"
        "        except ProcessKilled:\n"
        "            return None\n"
    )
    assert len(_run(KillSafetyRule, src,
                    relpath="kubernetes_tpu/scheduler/scheduler.py")) == 1


def test_ktpu001_kill_guard_legalizes_following_broad_handler():
    src = (
        "try:\n"
        "    work()\n"
        "except ProcessKilled:\n"
        "    raise\n"
        "except BaseException:\n"
        "    log()\n"
    )
    assert _run(KillSafetyRule, src) == []


def test_ktpu001_fires_on_contextlib_suppress_baseexception():
    src = "with contextlib.suppress(BaseException):\n    work()\n"
    fs = _run(KillSafetyRule, src)
    assert len(fs) == 1 and "suppress" in fs[0].message
    assert _run(
        KillSafetyRule, "with contextlib.suppress(KeyError):\n    work()\n"
    ) == []


# --- KTPU002 snapshot-LIST ---
def test_ktpu002_fires_on_live_dict_values_iteration():
    src = "for p in store.pods.values():\n    use(p)\n"
    fs = _run(SnapshotListRule, src)
    assert len(fs) == 1 and fs[0].rule == "KTPU002"
    assert "list_pods()" in fs[0].message


def test_ktpu002_fires_on_len_and_comprehension():
    assert len(_run(SnapshotListRule, "n = len(self.store.pods)\n")) == 1
    assert len(_run(
        SnapshotListRule, "xs = [p for p in self.store.nodes.items()]\n"
    )) == 1
    assert len(_run(
        SnapshotListRule, "for k in sorted(store.objects['ReplicaSet']):\n"
        "    use(k)\n"
    )) == 1


def test_ktpu002_covers_workload_alias_properties():
    # store.replicasets/.deployments/.jobs alias the SAME live dicts as
    # store.objects[kind] — iterating them races the writers identically
    src = "for rs in store.replicasets.values():\n    use(rs)\n"
    fs = _run(SnapshotListRule, src)
    assert len(fs) == 1 and 'list_objects("ReplicaSet")' in fs[0].message
    assert len(_run(SnapshotListRule,
                    "active = [j for j in store.jobs.values()]\n")) == 1
    # point reads on the alias stay legal
    assert _run(SnapshotListRule, "x = store.jobs.get(key)\n") == []
    assert _run(SnapshotListRule, "ok = key in store.deployments\n") == []


def test_ktpu002_point_reads_and_snapshots_are_legal():
    assert _run(SnapshotListRule, "p = store.pods.get(uid)\n") == []
    assert _run(SnapshotListRule, "for p in store.list_pods():\n    use(p)\n") == []
    assert _run(SnapshotListRule, "ok = uid in store.pods\n") == []


def test_ktpu002_transaction_scope_is_exempt():
    src = (
        "with self.store.transaction():\n"
        "    for p in self.store.pods.values():\n"
        "        use(p)\n"
    )
    assert _run(SnapshotListRule, src) == []


def test_ktpu002_locked_suffix_and_store_py_are_exempt():
    src = (
        "def _scan_locked(store):\n"
        "    return [p for p in store.pods.values()]\n"
    )
    assert _run(SnapshotListRule, src) == []
    live = "for p in store.pods.values():\n    use(p)\n"
    assert _run(SnapshotListRule, live,
                relpath="kubernetes_tpu/scheduler/store.py") == []


# --- KTPU003 donation-aliasing ---
def test_ktpu003_fires_on_resident_buffer_in_donated_position():
    src = "out = schedule_batch_donated(state.inc, pods)\n"
    fs = _run(DonationAliasingRule, src)
    assert len(fs) == 1 and "donated argument 0" in fs[0].message
    assert _run(
        DonationAliasingRule, "out = schedule_batch_donated(dev, pods, inc)\n"
    ) == []


def test_ktpu003_fires_on_hoist_cache_donation():
    src = "r = schedule_batch_ordinals_donated(hoist_cache.resident, w)\n"
    assert len(_run(DonationAliasingRule, src)) == 1


def test_ktpu003_fires_on_new_donation_site_outside_audited_modules():
    src = "f = jax.jit(step, donate_argnums=(0,))\n"
    fs = _run(DonationAliasingRule, src,
              relpath="kubernetes_tpu/parallel/other.py")
    assert len(fs) == 1 and "audited donation modules" in fs[0].message
    # the two audited modules may declare donation wrappers
    assert _run(DonationAliasingRule, src,
                relpath="kubernetes_tpu/ops/assign.py") == []
    # donate_argnums=() donates nothing — legal anywhere
    assert _run(DonationAliasingRule,
                "f = jax.jit(step, donate_argnums=())\n") == []


# --- KTPU004 determinism ---
OPS = "kubernetes_tpu/ops/newkernel.py"


def test_ktpu004_fires_on_wall_clock_in_pure_path():
    fs = _run(DeterminismRule, "t = time.time()\n", relpath=OPS)
    assert len(fs) == 1 and "wall clock" in fs[0].message
    # perf_counter times, it never decides — legal
    assert _run(DeterminismRule, "t = time.perf_counter()\n", relpath=OPS) == []
    # out of scope: the impure layers may read clocks
    assert _run(DeterminismRule, "t = time.time()\n") == []


def test_ktpu004_fires_on_unseeded_rng():
    assert len(_run(DeterminismRule, "x = random.random()\n", relpath=OPS)) == 1
    assert len(_run(DeterminismRule, "x = np.random.rand(3)\n", relpath=OPS)) == 1
    assert _run(DeterminismRule, "rng = random.Random(seed)\n", relpath=OPS) == []
    assert _run(DeterminismRule,
                "rng = np.random.default_rng(seed)\n", relpath=OPS) == []


def test_ktpu004_argless_seeded_ctor_is_not_seeded():
    # Random()/default_rng() without a seed is entropy-seeded — flagged;
    # the same constructors WITH a seed stay legal
    src = "rng = np.random.default_rng()\n"
    fs = _run(DeterminismRule, src, relpath="kubernetes_tpu/ops/assign.py")
    assert len(fs) == 1
    assert _run(DeterminismRule, "rng = np.random.default_rng(7)\n",
                relpath="kubernetes_tpu/ops/assign.py") == []
    assert len(_run(DeterminismRule, "r = random.Random()\n",
                    relpath="kubernetes_tpu/ops/assign.py")) == 1
    assert _run(DeterminismRule, "r = random.Random(seed)\n",
                relpath="kubernetes_tpu/ops/assign.py") == []


def test_ktpu004_fires_on_unordered_set_iteration():
    src = "for n in set(names):\n    place(n)\n"
    fs = _run(DeterminismRule, src, relpath="kubernetes_tpu/api/delta.py")
    assert len(fs) == 1 and "unordered set" in fs[0].message
    assert _run(DeterminismRule, "for n in sorted(set(names)):\n    place(n)\n",
                relpath=OPS) == []


# --- KTPU005 cheap-gate ---
def test_ktpu005_fires_on_ungated_o_p_span_build():
    src = (
        "def emit(self, pods):\n"
        "    self.tracer.record_span('w', t0, uids=[p.uid for p in pods])\n"
    )
    fs = _run(CheapGateRule, src)
    assert len(fs) == 1 and "cheap-gate" in fs[0].message


def test_ktpu005_enclosing_if_gate_is_legal():
    src = (
        "def emit(self, pods):\n"
        "    if self.tracer.enabled:\n"
        "        self.tracer.record_span('w', t0, uids=[p.uid for p in pods])\n"
    )
    assert _run(CheapGateRule, src) == []


def test_ktpu005_early_return_guard_is_legal():
    src = (
        "def emit(self, pods):\n"
        "    if not self.tracer.enabled:\n"
        "        return\n"
        "    self.tracer.record_span('w', t0, uids=[p.uid for p in pods])\n"
    )
    assert _run(CheapGateRule, src) == []


def test_ktpu005_constant_span_is_legal_ungated():
    src = "def emit(self):\n    self.tracer.record_span('w', t0, n=3)\n"
    assert _run(CheapGateRule, src) == []


# --- KTPU006 static lock-order ---
_INVERTED = """
class DataStore:
    def __init__(self):
        self._lock = make_lock("DataStore._lock")
    def get(self):
        with self._lock:
            return 1
    def poke(self, workqueue):
        with self._lock:
            workqueue.push(1)

class WorkQueue:
    def __init__(self):
        self._lock = make_lock("WorkQueue._lock")
    def push(self, x):
        with self._lock:
            pass
    def drain(self, datastore):
        with self._lock:
            datastore.get()
"""


def _lockorder(source, relpath=ANY):
    return LockOrderAnalyzer([ModuleInfo(relpath, source)]).check()


def test_ktpu006_fires_on_lock_order_inversion():
    fs = _lockorder(_INVERTED)
    assert len(fs) == 1 and fs[0].rule == "KTPU006"
    assert "inversion" in fs[0].message
    assert "DataStore._lock" in fs[0].message
    assert "WorkQueue._lock" in fs[0].message


def test_ktpu006_consistent_order_is_clean():
    clean = _INVERTED.replace("datastore.get()", "pass")
    assert _lockorder(clean) == []


def test_ktpu006_self_deadlock_on_plain_lock():
    src = (
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = make_lock('Box._lock')\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self.b()\n"
        "    def b(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    fs = _lockorder(src)
    assert len(fs) == 1 and "self-deadlock" in fs[0].message
    # the same shape over an RLock is a legal re-entrant hold
    assert _lockorder(src.replace("make_lock", "make_rlock")) == []


def test_ktpu006_multi_item_with_is_an_ordering_edge():
    # `with self._x, self._y:` acquires left-to-right — the most idiomatic
    # two-lock form must produce the same edges as nested withs
    src = (
        "class Pair:\n"
        "    def __init__(self):\n"
        "        self._x = make_lock('Pair._x')\n"
        "        self._y = make_lock('Pair._y')\n"
        "    def one(self):\n"
        "        with self._x, self._y:\n"
        "            pass\n"
        "    def two(self):\n"
        "        with self._y, self._x:\n"
        "            pass\n"
    )
    fs = _lockorder(src)
    assert len(fs) == 1 and "inversion" in fs[0].message
    # consistent multi-item order is clean
    consistent = src.replace("with self._y, self._x:", "with self._x, self._y:")
    assert _lockorder(consistent) == []


def test_ktpu006_watch_callback_runs_under_store_lock():
    src = (
        "class Follower:\n"
        "    def __init__(self, store):\n"
        "        self._lock = threading.Lock()\n"
        "        store.watch(self._on_event)\n"
        "    def _on_event(self, ev):\n"
        "        with self._lock:\n"
        "            pass\n"
        "    def scan(self, store):\n"
        "        with self._lock:\n"
        "            with store.transaction():\n"
        "                pass\n"
    )
    # watch edge ClusterStore._lock -> Follower._lock, nesting edge
    # Follower._lock -> ClusterStore._lock (transaction): a cycle
    fs = _lockorder(src)
    assert len(fs) == 1 and "ClusterStore._lock" in fs[0].message


def test_static_lock_graph_of_the_package_is_acyclic():
    root = os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))
    mods = []
    from kubernetes_tpu.analysis.engine import iter_package_files

    for relpath, abspath in iter_package_files(root):
        with open(abspath) as f:
            mods.append(ModuleInfo(relpath, f.read()))
    analyzer = LockOrderAnalyzer(mods)
    assert analyzer.check() == []
    edges, _, _ = analyzer.build_graph()
    # the known edge families exist — the analyzer is looking, not blind
    assert any(a == "ClusterStore._lock" for a in edges)


# --- engine: fingerprints, baseline, exit codes ---
def test_fingerprint_survives_line_shifts():
    src = "for p in store.pods.values():\n    use(p)\n"
    a = _run(SnapshotListRule, src)[0]
    b = _run(SnapshotListRule, "# comment\n\n\n" + src)[0]
    assert a.line != b.line and a.fingerprint == b.fingerprint


def test_baseline_requires_reasons():
    with pytest.raises(BaselineError):
        Baseline([{"fingerprint": "abc", "reason": ""}])
    with pytest.raises(BaselineError):
        Baseline([{"fingerprint": "abc", "reason": "TODO: justify or fix"}])
    with pytest.raises(BaselineError):
        Baseline([{"reason": "no fingerprint"}])


def test_baseline_suppresses_and_surfaces_stale(tmp_path):
    src = "for p in store.pods.values():\n    use(p)\n"
    f = _run(SnapshotListRule, src)[0]
    bl = Baseline([
        {"fingerprint": f.fingerprint, "reason": "audited: single-writer"},
        {"fingerprint": "deadbeefdeadbeef", "reason": "fixed long ago"},
    ])
    assert bl.match(f) == "audited: single-writer"
    stale = bl.unused([f])
    assert [e["fingerprint"] for e in stale] == ["deadbeefdeadbeef"]


def test_draft_baseline_cannot_silently_pass(tmp_path):
    src = "for p in store.pods.values():\n    use(p)\n"
    f = _run(SnapshotListRule, src)[0]
    draft = Baseline.draft([f])
    assert draft["findings"][0]["reason"].startswith("TODO")
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(draft))
    with pytest.raises(BaselineError):
        Baseline.load(str(p))


def test_exit_code_contract(tmp_path):
    # 1: a package dir with one unbaselined finding
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text("for p in store.pods.values():\n    use(p)\n")
    rep = analyze_package(str(pkg))
    assert rep.exit_code == 1 and len(rep.unbaselined) == 1
    # 0: the same finding baselined with a reason
    bl = Baseline([{
        "fingerprint": rep.findings[0].fingerprint,
        "reason": "fixture: suppressed on purpose",
    }])
    assert analyze_package(str(pkg), baseline=bl).exit_code == 0
    # 2: a module that does not parse is an unusable run, never "clean"
    (pkg / "broken.py").write_text("def f(:\n")
    assert analyze_package(str(pkg), baseline=bl).exit_code == 2


def test_exit_code_contract_unreadable_source(tmp_path):
    # a null byte makes ast.parse raise ValueError (not SyntaxError) — still
    # an unusable run (exit 2), never a traceback CI misreads as exit 1
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "nul.py").write_bytes(b"x = 1\n\x00bad")
    rep = analyze_package(str(pkg))
    assert rep.exit_code == 2 and rep.errors


def test_cli_unknown_rule_id_refused(tmp_path):
    # a typoed --rules id must not select zero rules and report clean
    with pytest.raises(SystemExit) as ei:
        cli_main(["--rules", "KTPU999"])
    assert ei.value.code == 2


def test_stale_baseline_ignores_rules_subset(tmp_path):
    # an entry for a rule that did not run is NOT stale — it may still
    # match on a full run, so a subset run must not advise deleting it
    entry = {"fingerprint": "ab" * 8, "rule": "KTPU002", "reason": "live"}
    bl = Baseline([entry])
    assert bl.unused([], ran_rules=["KTPU001"]) == []
    assert bl.unused([], ran_rules=["KTPU001", "KTPU002"]) == [entry]
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text("x = 1\n")
    rep = analyze_package(
        str(pkg), rules=[r for r in (cls() for cls in ALL_RULES)
                         if r.rule_id == "KTPU001"],
        baseline=bl, lockorder=False)
    assert rep.stale_baseline == [] and rep.exit_code == 0


# --- the acceptance gate: the package itself is clean ---
def test_package_analyzes_clean_under_committed_baseline():
    root = os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))
    baseline = Baseline.load(default_baseline())
    rep = analyze_package(root, baseline=baseline)
    assert rep.errors == []
    assert [f.render() for f in rep.unbaselined] == []
    assert rep.stale_baseline == []
    assert rep.exit_code == 0
    assert rep.files_scanned > 50


def test_cli_json_artifact(tmp_path, capsys):
    out = tmp_path / "verify.json"
    rc = cli_main(["--format", "json", "--output", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["tool"] == "ktpu-verify"
    assert doc["exit_code"] == 0
    assert set(doc["rules"]) == {
        "KTPU001", "KTPU002", "KTPU003", "KTPU004", "KTPU005", "KTPU006",
        "KTPU013",
    }
    assert json.loads(capsys.readouterr().out)["n_unbaselined"] == 0


def test_cli_rules_subset_really_subsets(tmp_path, capsys):
    # --rules KTPU002 must not drag the whole-package KTPU006 pass along
    rc = cli_main(["--format", "json", "--rules", "KTPU002"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["rules"] == ["KTPU002"]
    rc = cli_main(["--format", "json", "--rules", "KTPU006"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["rules"] == ["KTPU006"]


def test_write_baseline_refuses_no_baseline():
    # the combination would overwrite the committed file with TODO drafts,
    # discarding every human-written suppression reason
    with pytest.raises(SystemExit):
        cli_main(["--write-baseline", "--no-baseline"])


def test_unreadable_baseline_is_unusable_not_findings(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text("{")  # truncated JSON
    with pytest.raises(BaselineError):
        Baseline.load(str(p))
    rc = cli_main(["--baseline", str(p)])
    assert rc == 2  # unusable, never misread as "findings"


def test_cli_root_reanchors_at_the_package_dir(tmp_path, capsys):
    # --root pointed at a REPO root (containing kubernetes_tpu/) must
    # re-anchor at the package so path-scoped rules keep matching — the
    # repo root must analyze identically to the default package root
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(kubernetes_tpu.__file__)))
    rc = cli_main(["--root", repo_root])
    assert rc == 0
    assert "0 findings" in capsys.readouterr().out
    # fixture roots without the package pass through unchanged
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text("for p in store.pods.values():\n    use(p)\n")
    assert cli_main(["--root", str(pkg), "--no-baseline"]) == 1


def test_write_baseline_refuses_unusable_run(tmp_path):
    # a parse error means incomplete findings: rewriting the baseline would
    # silently drop entries for the unparsed file — refuse, leave it alone
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def f(:\n")
    bl = tmp_path / "bl.json"
    original = ('{"findings": [{"fingerprint": "ab", "rule": "KTPU002", '
                '"reason": "TODO: x"}]}')
    bl.write_text(original)
    rc = cli_main(["--root", str(pkg), "--baseline", str(bl),
                   "--write-baseline"])
    assert rc == 2
    assert bl.read_text() == original  # untouched


def test_write_baseline_redraft_is_not_a_dead_end(tmp_path):
    # a prior draft's TODO reasons must not brick --write-baseline itself:
    # re-drafting loads leniently, drops stale TODO entries, and exits by
    # remaining-TODO count (strict CI runs still refuse TODOs)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text("for p in store.pods.values():\n    use(p)\n")
    bl = tmp_path / "bl.json"
    rc = cli_main(["--root", str(pkg), "--baseline", str(bl),
                   "--write-baseline"])
    assert rc == 1  # a TODO entry was written: unresolved work
    assert "TODO" in bl.read_text()
    assert cli_main(["--root", str(pkg), "--baseline", str(bl)]) == 2
    (pkg / "bad.py").write_text("x = 1\n")  # finding fixed
    rc = cli_main(["--root", str(pkg), "--baseline", str(bl),
                   "--write-baseline"])
    assert rc == 0  # stale TODO dropped, nothing left to justify
    assert json.loads(bl.read_text()) == {"findings": []}


# --- runtime lock checker (KTPU_LOCK_CHECK=1) ---
@pytest.fixture
def clean_lockcheck():
    lockcheck.reset()
    yield
    lockcheck.reset()


def test_checkedlock_detects_inverted_order(clean_lockcheck):
    a, b = CheckedLock("A"), CheckedLock("B")
    with a:
        with b:
            pass
    with b:
        with a:  # closes the cycle: A->B observed, now B->A
            pass
    vs = lockcheck.violations()
    assert len(vs) == 1
    assert "A" in vs[0].cycle and "B" in vs[0].cycle
    assert vs[0].witnesses  # the prior A->B edge is named as evidence
    with pytest.raises(LockOrderViolation):
        lockcheck.assert_clean()


def test_checkedlock_consistent_order_across_threads(clean_lockcheck):
    a, b = CheckedLock("A"), CheckedLock("B")

    def worker():
        with a:
            with b:
                pass

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    with a:
        with b:
            pass
    assert lockcheck.violations() == []
    assert ("A", "B") in lockcheck.order_graph()
    lockcheck.assert_clean()


def test_checkedlock_reentrant_hold_adds_no_edge(clean_lockcheck):
    a = CheckedLock("A", reentrant=True)
    with a:
        with a:
            pass
    assert lockcheck.order_graph() == {}
    lockcheck.assert_clean()


def test_checkedlock_distinct_instances_of_one_name_flagged(clean_lockcheck):
    """Per-object locks (StreamingHist._lock, one per histogram) share a
    name: nesting two DIFFERENT instances is order-ambiguous at the name
    level — the mirror nesting on another thread is an ABBA deadlock, so
    the checker flags it (lockdep's same-class rule) instead of mistaking
    it for a re-entrant hold."""
    a, b = CheckedLock("StreamingHist._lock"), CheckedLock("StreamingHist._lock")
    with a:
        with b:
            pass
    vs = lockcheck.violations()
    assert len(vs) == 1 and vs[0].cycle == [
        "StreamingHist._lock", "StreamingHist._lock",
    ]
    assert "distinct instances" in vs[0].witnesses[0]


def test_checkedlock_records_violation_before_blocking(clean_lockcheck):
    """Lockdep's rule: the ordering edge lands BEFORE the potentially-
    deadlocking wait, so an actual ABBA hang still leaves the violation
    and witnesses in the graph instead of two threads stuck inside
    acquire() with nothing recorded."""
    a, b = CheckedLock("A"), CheckedLock("B")
    with a:
        with b:
            pass  # establishes A -> B
    a.acquire()  # main thread holds A

    def worker():
        b.acquire()
        got = a.acquire(timeout=0.2)  # blocks: main holds A
        if got:
            a.release()
        b.release()

    t = threading.Thread(target=worker, name="worker")
    t.start()
    t.join()
    a.release()
    vs = lockcheck.violations()
    assert vs and set(vs[0].cycle) == {"A", "B"}


def test_checkedlock_cross_thread_release_purges_hold(clean_lockcheck):
    # releasing a plain Lock from a thread other than its acquirer is a
    # legal handoff — the acquirer's hold stack must be purged, else its
    # every later acquisition records a false ordering edge
    a, b = CheckedLock("A"), CheckedLock("B")
    a.acquire()
    t = threading.Thread(target=a.release)
    t.start()
    t.join()
    with b:
        pass  # must NOT record A -> B
    assert lockcheck.order_graph() == {}
    lockcheck.assert_clean()


def test_checkedlock_illegal_release_keeps_checker_state(clean_lockcheck):
    # an illegal cross-thread RLock release raises from the inner lock with
    # the hold records untouched — the true owner's later edges still land
    a, b = CheckedLock("A", reentrant=True), CheckedLock("B")
    a.acquire()

    err: list = []

    def bad_release():
        try:
            a.release()
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=bad_release)
    t.start()
    t.join()
    assert err  # the release itself raised
    with b:
        pass  # main still holds A: edge A -> B must be recorded
    a.release()
    assert ("A", "B") in lockcheck.order_graph()
    lockcheck.assert_clean()


def test_checkedlock_nonreentrant_self_reacquire_recorded(clean_lockcheck):
    # the holder re-acquiring a non-reentrant lock blocks forever — the
    # guaranteed self-deadlock must be on record before the hang
    c = CheckedLock("C")
    c.acquire()
    assert not c.acquire(timeout=0.05)
    c.release()
    vs = lockcheck.violations()
    assert vs and vs[0].cycle == ["C", "C"]


def test_make_lock_reads_env_at_construction(monkeypatch):
    monkeypatch.delenv("KTPU_LOCK_CHECK", raising=False)
    assert not isinstance(lockcheck.make_lock("x"), CheckedLock)
    monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
    lk = lockcheck.make_lock("x")
    assert isinstance(lk, CheckedLock) and not lk.reentrant
    rl = lockcheck.make_rlock("y")
    assert isinstance(rl, CheckedLock) and rl.reentrant
    monkeypatch.setenv("KTPU_LOCK_CHECK", "0")
    assert not isinstance(lockcheck.make_lock("x"), CheckedLock)


def test_lockcheck_report_shape(clean_lockcheck):
    a, b = CheckedLock("A"), CheckedLock("B")
    with a:
        with b:
            pass
    rep = lockcheck.report()
    assert rep["edges"] == ["A -> B"]
    assert rep["violations"] == []


# --- the acceptance storm: seeded chaos churn under KTPU_LOCK_CHECK=1 ---
def _lock_checked_churn(seed):
    store = ClusterStore()
    for i in range(5):
        store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for i in range(20):
        store.add_pod(mk_pod(f"p{i}", cpu=250))
    sched.run_until_idle()
    rng = random.Random(seed)
    for r in range(2):
        bound = sorted(
            (p for p in store.list_pods() if p.node_name), key=lambda p: p.uid
        )
        for v in rng.sample(bound, 6):
            store.delete_pod(v.uid)
            q = copy.copy(v)
            q.name = f"{v.name}-r{r}"
            q.uid = ""
            q.node_name = ""
            q.__post_init__()
            store.add_pod(q)
        sched.run_until_idle()
    return {p.name: p.node_name for p in store.list_pods()}


def test_chaos_storm_under_lock_check_is_cycle_free(monkeypatch, clean_lockcheck):
    """ISSUE 8 acceptance: a seeded chaos storm run with every lock
    instrumented reports no lock-order cycle — and placements stay
    bit-identical to the un-instrumented oracle (the checker observes,
    it never perturbs)."""
    monkeypatch.setenv("KTPU_PIPELINE", "1")
    oracle = _lock_checked_churn(5)  # plain locks (env not yet set)
    monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
    lockcheck.reset()
    plan = chaos.FaultPlan.from_seed(
        0, sites=("scheduler.step", "host.stall"), n_faults=4
    )
    try:
        with chaos.chaos_plan(plan):
            got = _lock_checked_churn(5)
    finally:
        chaos.uninstall()
    assert got == oracle
    lockcheck.assert_clean()
    rep = lockcheck.report()
    # the checker actually observed the hot nesting — not silently off
    assert "ClusterStore._lock -> Scheduler._move_lock" in rep["edges"]
