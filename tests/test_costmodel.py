"""Device cost observatory (ISSUE 14): named-scope sub-phase attribution,
the analytic roofline ledger (analysis/costmodel.py), the measured profile
table (bench/profiling.py), and the KTPU019 gate that joins them.

Ordering note: the parity test spawns one subprocess with
KTPU_NAMED_SCOPES=0 and compares against in-process runs — annotation must
change zero placements and zero TRACE_COUNTS across every route x donation
variant."""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu.analysis import costmodel
from kubernetes_tpu.analysis.devicecheck import RouteTrace
from kubernetes_tpu.analysis.jaxrules import SubphaseLedgerRule
from kubernetes_tpu.bench.profiling import (
    merge_profile_spans,
    parse_hlo_dumps,
    subphase_table,
)
from kubernetes_tpu.ops.scopes import SUBPHASES, subphase, subphase_of


# ---- the scope vocabulary ----

def test_subphase_vocabulary_is_closed():
    with pytest.raises(ValueError):
        subphase("not_a_phase")
    # innermost declared component owns the op — one definition for both
    # observatory halves
    assert subphase_of("jit(f)/jit(main)/round_loop/repair/mul") == "repair"
    assert subphase_of("jit(f)/hoist/dot_general") == "hoist"
    assert subphase_of("jit(f)/transpose/whatever") == ""
    assert subphase_of("") == ""
    assert set(SUBPHASES) >= {"hoist", "round_loop", "speculate", "repair",
                              "commit", "score", "normalize"}


# ---- analytic ledger: exact FLOPs on a known kernel ----

def _known_fn(x, w):
    with subphase("hoist"):
        y = x @ w  # [m, k] @ [k, n]
    with subphase("commit"):
        return y + 1.0


def test_known_flop_kernel_exact_ledger():
    m, k, n = 8, 16, 4
    closed = jax.make_jaxpr(_known_fn)(
        jnp.ones((m, k), jnp.float32), jnp.ones((k, n), jnp.float32)
    )
    led = costmodel.jaxpr_ledger(closed)
    hoist = led["subphases"]["hoist"]
    assert hoist["flops"] == 2 * m * k * n
    # roofline bytes: every operand streams once (in + out)
    assert hoist["hbm_bytes"] == 4 * (m * k + k * n + m * n)
    commit = led["subphases"]["commit"]
    assert commit["flops"] == m * n  # one add per element
    # fractions sum to 1.0 over every charged row
    assert sum(r["fraction"] for r in led["subphases"].values()) == \
        pytest.approx(1.0, abs=0.01)
    assert led["heavy_unowned"] == []
    assert led["round_loop_fraction"] == 0.0


def test_loop_trip_scaling():
    def f(x):
        with subphase("hoist"):
            x = x * 2.0
        with subphase("round_loop"):
            def body(st):
                i, a = st
                with subphase("repair"):
                    a = a @ a
                return i + 1, a
            _, x = jax.lax.while_loop(lambda st: st[0] < 3, body, (0, x))
        with subphase("commit"):
            def sbody(c, _):
                return c + 1.0, ()
            x, _ = jax.lax.scan(sbody, x, None, length=7)
        return x

    closed = jax.make_jaxpr(f)(jnp.ones((4, 4), jnp.float32))
    led5 = costmodel.jaxpr_ledger(closed, while_trip=5)
    led10 = costmodel.jaxpr_ledger(closed, while_trip=10)
    # the while body's dot scales with the assumed trip count
    assert led5["subphases"]["repair"]["flops"] == 5 * 2 * 4 * 4 * 4
    assert led10["subphases"]["repair"]["flops"] == 10 * 2 * 4 * 4 * 4
    # the scan body's add scales with the static length
    assert led5["subphases"]["commit"]["flops"] == 7 * 16
    # repair lives inside the loop: the rollup owns it
    assert led5["round_loop_fraction"] >= led5["subphases"]["repair"]["fraction"]
    assert led5["dominant"] == "round_loop"


# ---- KTPU019: coverage fails closed, reconciliation gates the join ----

def _unannotated_fixture():
    def f(x):
        with subphase("hoist"):
            y = x * 1.5
        return y @ y  # heavy dot OUTSIDE every declared scope

    return RouteTrace.from_callable(
        "fixture/unannotated", f, jnp.ones((32, 32), jnp.float32))


def test_heavy_unowned_eqn_is_a_finding():
    t = _unannotated_fixture()
    assert t.cost is not None  # capture attaches the ledger
    assert t.cost["heavy_unowned"], "the naked dot must show up"
    findings = SubphaseLedgerRule().check([t])
    assert any("unowned" in f.snippet for f in findings)


def test_annotated_fixture_is_clean():
    def f(x):
        with subphase("hoist"):
            y = x * 1.5
        with subphase("score"):
            return y @ y

    t = RouteTrace.from_callable(
        "fixture/annotated", f, jnp.ones((32, 32), jnp.float32))
    assert SubphaseLedgerRule().check([t]) == []


def _loop_fixture():
    def f(x):
        with subphase("hoist"):
            x = x + 1.0
        with subphase("round_loop"):
            def body(st):
                i, a = st
                with subphase("repair"):
                    a = a @ a
                return i + 1, a
            _, x = jax.lax.while_loop(lambda st: st[0] < 3, body, (0, x))
        return x

    return RouteTrace.from_callable(
        "fixture/loop", f, jnp.ones((64, 64), jnp.float32))


def test_reconciliation_pass_and_fail_fixtures():
    t = _loop_fixture()
    analytic_rl = t.cost["round_loop_fraction"]
    assert analytic_rl > 0.9  # the dot-in-loop dominates the model
    # pass: measured agrees
    t.measured_subphases = {"round_loop_fraction": analytic_rl}
    assert SubphaseLedgerRule().check([t]) == []
    # fail: measured says the loop is negligible
    t.measured_subphases = {"round_loop_fraction": 0.06}
    findings = SubphaseLedgerRule().check([t])
    assert any("reconcile" in f.snippet for f in findings)
    # unit contract: floor + ratio semantics
    assert costmodel.reconcile(0.03, 0.04)["ok"]  # both below floor
    assert costmodel.reconcile(0.9, 0.5)["ok"]    # 1.8x < tolerance
    assert not costmodel.reconcile(0.9, 0.05)["ok"]


# ---- measured half: dump parsing + self-time table ----

_FAKE_DUMP = textwrap.dedent("""\
    HloModule jit_kernel, entry_computation_layout={()->f32[4]}

    %fused_computation (p: f32[4]) -> f32[4] {
      ROOT %mul.1 = f32[4] multiply(%p, %p), metadata={op_name="jit(k)/jit(main)/round_loop/repair/mul"}
    }

    ENTRY %main () -> f32[4] {
      %dot.5 = f32[4,4] dot(%a, %a), metadata={op_name="jit(k)/jit(main)/hoist/dot_general"}
      %while.9 = (s32[], f32[4]) while(%tuple.1), condition=%cond, body=%body
      %fusion.2 = f32[4] fusion(%dot.5), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(k)/jit(main)/round_loop/repair/mul"}
      ROOT %add.3 = f32[4] add(%fusion.2, %fusion.2), metadata={op_name="jit(k)/jit(main)/commit/add"}
    }
""")


def _fake_profile(tmp_path):
    hlo = tmp_path / "hlo"
    hlo.mkdir()
    (hlo / "module_0001.jit_kernel.cpu_after_optimizations.txt").write_text(
        _FAKE_DUMP)
    events = [
        {"module": "jit_kernel", "op": "dot.5", "ts_us": 0.0, "dur_us": 10.0},
        {"module": "jit_kernel", "op": "while.9", "ts_us": 10.0,
         "dur_us": 80.0},  # container envelope — must not be charged
        {"module": "jit_kernel", "op": "fusion.2", "ts_us": 12.0,
         "dur_us": 60.0},
        {"module": "jit_kernel", "op": "add.3", "ts_us": 95.0, "dur_us": 30.0},
        {"module": "jit_other", "op": "dot.1", "ts_us": 0.0, "dur_us": 500.0},
    ]
    return str(hlo), events


def test_subphase_table_from_fixture_dump(tmp_path):
    hlo_dir, events = _fake_profile(tmp_path)
    op_map = parse_hlo_dumps(hlo_dir)
    assert op_map["jit_kernel"]["while.9"] is None  # container detected
    # the fused computation's interior line must not shadow entry ops
    table = subphase_table(events, op_map)
    # jit_other has no declared scopes: out of scope entirely
    assert table["kernel_modules"] == ["jit_kernel"]
    subs = table["subphases"]
    total = 10.0 + 60.0 + 30.0  # leaves only; while.9 excluded
    assert subs["hoist"]["fraction"] == pytest.approx(10 / total, abs=1e-3)
    assert subs["repair"]["fraction"] == pytest.approx(60 / total, abs=1e-3)
    assert subs["commit"]["fraction"] == pytest.approx(30 / total, abs=1e-3)
    assert sum(d["fraction"] for d in subs.values()) == \
        pytest.approx(1.0, abs=0.01)
    assert table["round_loop_fraction"] == pytest.approx(60 / total, abs=1e-3)
    assert not table["incomplete"]
    # no events at all -> incomplete, never a vacuous clean table
    assert subphase_table([], op_map)["incomplete"]


def test_merge_profile_spans_nests_under_device_step(tmp_path):
    from kubernetes_tpu.scheduler.tracing import Span, TraceCollector

    hlo_dir, events = _fake_profile(tmp_path)
    op_map = parse_hlo_dumps(hlo_dir)
    col = TraceCollector()
    anchor = Span("device.step", component="pipeline", start=100.0)
    anchor.finish(101.0)
    col.add(anchor)
    n = merge_profile_spans(col, events, op_map)
    assert n == 3  # leaves of the kernel module only
    children = [s for s in col.spans() if s.name.startswith("device.")
                and s.name != "device.step"]
    assert {s.name for s in children} == {
        "device.hoist", "device.repair", "device.commit"}
    assert all(s.parent_id == anchor.span_id for s in children)
    assert all(s.trace_id == anchor.trace_id for s in children)


def test_attribution_nests_device_subphases():
    from kubernetes_tpu.scheduler.attribution import (
        attribute_spans, render_attribution,
    )
    from kubernetes_tpu.scheduler.tracing import Span

    sp = Span("device.step", start=0.0)
    sp.finish(1.0)
    table = {
        "subphases": {"repair": {"seconds": 0.9, "fraction": 0.9},
                      "hoist": {"seconds": 0.1, "fraction": 0.1}},
        "round_loop_fraction": 0.9, "dominant": "round_loop",
        "n_ops": 2, "kernel_modules": ["jit_kernel"], "total_s": 1.0,
        "incomplete": False,
    }
    rep = attribute_spans([sp], spans_dropped=0, device_subphases=table)
    assert rep["device_subphases"] is table
    text = render_attribution(rep)
    # nested under device_kernel, not a separate table
    dk = text.index("device_kernel")
    assert "  . repair" in text and text.index("  . repair") > dk
    assert "round_loop(all)" in text


# ---- queue-pool depth observability (satellite) ----

def test_queue_pool_depths_and_artifact_fields():
    from kubernetes_tpu.bench.harness import queue_fields
    from kubernetes_tpu.scheduler.metrics import Metrics
    from kubernetes_tpu.scheduler.queue import FakeClock, PriorityQueue
    from helpers import mk_pod

    q = PriorityQueue(clock=FakeClock())
    for i in range(3):
        q.add(mk_pod(f"d{i}"))
    p_backoff = q.pop()
    q.add_unschedulable(p_backoff, backoff=True)
    p_parked = q.pop()
    q.add_unschedulable(p_parked, {"Node/Add"}, backoff=True)
    d = q.depths()
    assert d == {"active": 1, "backoff": 1, "unschedulable": 1, "parked": 2}
    m = Metrics()
    for pool, v in d.items():
        m.set(f"queue_pool_{pool}_pods", v)
        m.set_max(f"queue_pool_{pool}_pods_peak", v)
    m.set_max("queue_pool_active_pods_peak", 7)  # a later, deeper sample
    m.set_max("queue_pool_active_pods_peak", 2)  # never lowers
    qf = queue_fields(m)["queue_depths"]
    assert qf["active"] == {"final": 1, "peak": 7}
    assert qf["parked"] == {"final": 2, "peak": 2}


def test_scheduler_samples_queue_depth_gauges():
    from kubernetes_tpu.scheduler import (
        ClusterStore, Scheduler, SchedulerConfiguration,
    )
    from helpers import mk_node, mk_pod

    store = ClusterStore()
    store.add_node(mk_node("n1"))
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    for i in range(4):
        store.add_pod(mk_pod(f"p{i}"))
    sched.run_until_idle()
    _c, gauges, _h = sched.metrics.snapshot()
    assert gauges.get("queue_pool_active_pods_peak", 0) >= 4
    assert gauges.get("queue_pool_active_pods") == 0  # drained at idle


# ---- named-scope parity: annotation changes nothing (satellite) ----

_PARITY_PROG = """
import json, os, sys
os.environ["KTPU_FORCE_CHUNKED"] = "1"
import numpy as np
from kubernetes_tpu.bench import workloads
from kubernetes_tpu.api.delta import DeltaEncoder
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, infer_score_config
from kubernetes_tpu.ops import assign as A
from kubernetes_tpu.ops.incremental import HoistCache

out = {}
for kind in ("chunked", "rounds", "inc"):
    snap = (workloads.spread_affinity(16, 48, seed=5) if kind == "rounds"
            else workloads.heterogeneous(16, 120, seed=5))
    for donate in (False, True):
        enc = DeltaEncoder()
        arr, meta = enc.encode(snap)
        cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
        inc = None
        if kind == "inc":
            inc = HoistCache().ensure(arr, meta, cfg)
        pre = dict(A.TRACE_COUNTS)
        c, u = A.schedule_batch_routed(arr, cfg, donate=donate, inc=inc)
        delta = {k: A.TRACE_COUNTS[k] - pre[k] for k in pre
                 if A.TRACE_COUNTS[k] != pre[k]}
        out[f"{kind}/{donate}"] = {
            "choices": np.asarray(c).tolist(),
            "trace_delta": delta,
        }
print(json.dumps(out))
"""


def test_named_scope_annotation_changes_nothing():
    """KTPU_NAMED_SCOPES=0 vs the default across {chunked, rounds, inc} x
    {donate on/off}: bit-identical placements AND identical TRACE_COUNTS
    route deltas — the scopes are metadata, never program structure.  Both
    settings run in fresh subprocesses (the knob is read at trace time, so
    flipping it against a warm jit cache would be vacuous)."""
    outs = []
    for scopes in ("1", "0"):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   KTPU_NAMED_SCOPES=scopes)
        r = subprocess.run(
            [sys.executable, "-c", _PARITY_PROG], env=env,
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    on, off = outs
    assert on.keys() == off.keys()
    for key in on:
        assert on[key]["choices"] == off[key]["choices"], key
        assert on[key]["trace_delta"] == off[key]["trace_delta"], key


# ---- profile-capture smoke on the forced 8-device CPU platform ----

def test_profile_capture_smoke(tmp_path):
    """`bench.harness --stream 1 --profile` in a fresh subprocess (XLA
    parses dump flags once per process) on the forced 8-device CPU
    platform: the artifact must carry a sub-phase table whose fractions
    sum to 1.0 within device_kernel and a passing reconciliation."""
    prof = tmp_path / "prof"
    out = tmp_path / "out.json"
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", KTPU_STREAM_SHAPE="256x64",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_force_host_platform_device_count=8").strip(),
    )
    r = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.bench.harness",
         "--stream", "1", "--profile", str(prof), "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    table = doc["device_subphases"]
    assert not table["incomplete"]
    assert sum(d["fraction"] for d in table["subphases"].values()) == \
        pytest.approx(1.0, abs=0.02)
    assert doc["subphase_reconciliation"]["ok"], doc["subphase_reconciliation"]
    # the stream routes chunked_inc, where the class-batched commit waves
    # (ISSUE 17) replaced the prefix-commit loop: commit_batch is the story
    # now, and the collapsed round_loop_fraction is the measured proof
    assert doc["round_loop_fraction"] < 0.2, doc["round_loop_fraction"]
    assert table["subphases"]["commit_batch"]["fraction"] > 0.2, table
    assert doc["device_flops"] > 0 and doc["device_hbm_bytes"] > 0


# ---- the production routes carry ledgers (cached single route) ----

def test_traced_route_carries_cost_ledger():
    from kubernetes_tpu.analysis.devicecheck import RouteSpec, trace_route

    os.environ["KTPU_FORCE_CHUNKED"] = "1"
    try:
        t = trace_route(RouteSpec("chunked", False, 1))
    finally:
        os.environ.pop("KTPU_FORCE_CHUNKED", None)
    assert t.cost is not None
    assert t.cost["round_loop_fraction"] > 0.5
    assert t.cost["dominant"] == "round_loop"
    assert t.cost["heavy_unowned"] == []
    assert t.to_dict()["cost"]["total_flops"] > 0
