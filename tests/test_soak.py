"""Threaded full-stack soak: scheduler + controllers + kubelets + proxier
hammering one ClusterStore concurrently.

SURVEY.md §5 race posture: the reference relies on `go test -race`; Python
has no race detector, so the locking story (store RLock + single-writer
components + watch fan-out under the lock) is proven by running every
component in its own thread against a shared store and checking the system
still converges to a consistent state — the disruptive-suite analog.
"""

import random
import threading
import time

import pytest

from kubernetes_tpu.api import cluster as c
from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler.config import SchedulerConfiguration
from kubernetes_tpu.scheduler.controllers import ControllerManager
from kubernetes_tpu.scheduler.kubelet import HollowCluster
from kubernetes_tpu.scheduler.leases import LeaseStore
from kubernetes_tpu.scheduler.network import Proxier
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.store import ClusterStore

N_NODES = 6
N_DEPLOYMENTS = 4
SOAK_SECONDS = 3.0


def _loop(stop, errors, fn, pause=0.002):
    while not stop.is_set():
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — the assertion surface
            errors.append(e)
            return
        time.sleep(pause)


def test_full_stack_soak_converges():
    store = ClusterStore()
    for i in range(N_NODES):
        store.add_node(t.Node(name=f"n{i}", allocatable={t.CPU: 64_000, t.PODS: 200}))
    for d in range(N_DEPLOYMENTS):
        store.add_object(
            "Deployment",
            t.Deployment(
                name=f"app{d}",
                replicas=5,
                selector=t.LabelSelector.of(app=f"app{d}"),
                template=t.Pod(
                    name=f"app{d}",
                    requests={t.CPU: 100},
                    labels={"app": f"app{d}"},
                ),
            ),
        )
        store.add_object(
            "Service",
            c.Service(name=f"svc{d}", selector=(("app", f"app{d}"),),
                      ports=(c.ServicePort(80),), cluster_ip=f"10.96.0.{d + 1}"),
        )

    from kubernetes_tpu.scheduler.auth import TokenAuthenticator

    sched = Scheduler(store, SchedulerConfiguration(mode="cpu"))
    leases = LeaseStore()
    cm = ControllerManager(store, authenticator=TokenAuthenticator())
    fleet = HollowCluster(store, leases)
    proxy = Proxier(store)
    rng = random.Random(7)

    def chaos():
        # delete a random running pod; its controller must replace it
        pods = [p for p in store.pods.values() if p.node_name]
        if pods:
            store.delete_pod(rng.choice(pods).uid)

    stop = threading.Event()
    errors: list = []
    threads = [
        threading.Thread(target=_loop, args=(stop, errors, lambda: sched.run_until_idle(20))),
        threading.Thread(target=_loop, args=(stop, errors, cm.tick)),
        threading.Thread(target=_loop, args=(stop, errors, fleet.tick)),
        threading.Thread(target=_loop, args=(stop, errors, proxy.sync)),
        threading.Thread(target=_loop, args=(stop, errors, chaos, 0.05)),
    ]
    for th in threads:
        th.start()
    time.sleep(SOAK_SECONDS)
    stop.set()
    for th in threads:
        th.join(timeout=30)
        assert not th.is_alive(), "component thread wedged"
    assert errors == [], f"component crashed under concurrency: {errors!r}"

    # quiesce: a few synchronous rounds must converge the survivors
    for _ in range(30):
        cm.tick()
        sched.run_until_idle(50)
        fleet.tick()
    proxy.sync()

    for d in range(N_DEPLOYMENTS):
        running = [
            p for p in store.pods.values()
            if p.labels.get("app") == f"app{d}" and p.node_name
            and p.phase == t.PHASE_RUNNING
        ]
        assert len(running) == 5, (
            f"app{d}: {len(running)} running of 5 after quiesce"
        )
    # pod IPs unique across the cluster (the nodeipam invariant)
    ips = [p.pod_ip for p in store.pods.values() if p.pod_ip]
    assert len(ips) == len(set(ips)), "duplicate pod IPs"
    # every service routes to its running backends
    for d in range(N_DEPLOYMENTS):
        backends = {
            proxy.lookup(f"client-{i}", f"10.96.0.{d + 1}", 80) for i in range(40)
        }
        assert backends and None not in backends
