"""Ring-blockwise matching + all-to-all reshard vs dense references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_tpu.parallel import make_mesh
from kubernetes_tpu.parallel.ring import all_to_all_pods_to_nodes, ring_match


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_ring_match_equals_dense(mesh):
    rng = np.random.default_rng(3)
    S, E, L, P = 16, 2, 24, 64
    sel_mask = (rng.random((S, E, L)) < 0.15).astype(np.float32)
    sel_kind = rng.integers(0, 3, size=(S, E)).astype(np.int32)  # PAD/ANY/NONE
    labels = (rng.random((P, L)) < 0.3).astype(np.float32)

    got = np.asarray(ring_match(jnp.array(sel_mask), jnp.array(sel_kind), jnp.array(labels), mesh))

    counts = np.einsum("sel,pl->sep", sel_mask, labels)
    kind = sel_kind[:, :, None]
    want = np.where(kind == 1, counts > 0, np.where(kind == 2, counts == 0, kind == 0)).all(1)
    np.testing.assert_array_equal(got, want)


def test_all_to_all_reshard_preserves_values(mesh):
    rng = np.random.default_rng(4)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    y = all_to_all_pods_to_nodes(jnp.array(x), mesh)
    np.testing.assert_array_equal(np.asarray(y), x)
    # and it really is node-sharded now
    shard_shapes = {s.data.shape for s in y.addressable_shards}
    assert shard_shapes == {(32, 2)}
