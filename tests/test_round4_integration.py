"""Round-4 soak: the PRODUCTION batch route under forced-chunked routing
(KTPU_FORCE_CHUNKED=1 — the rounds/chunked kernels on the CPU sim, the
round-3 verdict's "production routing predicate is untestable off-TPU"),
with the delta encoder's identity-convention cross-check enabled
(KTPU_DELTA_VERIFY=1 — the round-3 verdict's "debug_verify never runs in
CI").  Waves are sized so the bucketed pod axis reaches >= 128 and the
chunked paths actually engage through Scheduler.schedule_batch, not via
direct kernel calls."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api import types as t
from kubernetes_tpu.scheduler import ClusterStore, Scheduler, SchedulerConfiguration
from kubernetes_tpu.scheduler.queue import FakeClock
from helpers import mk_node, mk_pod


@pytest.mark.parametrize("seed", [11, 23])
def test_round4_forced_chunked_soak_with_delta_verify(seed, monkeypatch):
    from kubernetes_tpu.ops.assign import TRACE_COUNTS
    from kubernetes_tpu.scheduler.config import Profile

    monkeypatch.setenv("KTPU_FORCE_CHUNKED", "1")
    monkeypatch.setenv("KTPU_DELTA_VERIFY", "1")
    traced_before = dict(TRACE_COUNTS)
    rng = random.Random(seed)
    clock = FakeClock()
    store = ClusterStore()
    for i in range(21):
        store.add_node(mk_node(f"n{i}", cpu=16000, pods=40,
                               labels={t.LABEL_ZONE: f"z{i % 3}"}))
    # a one-off hardPodAffinityWeight makes the kernel ScoreConfig — part of
    # the jit cache key — unique to THIS test, so the forced routing cannot
    # be satisfied by a plain-scan trace some earlier test cached for the
    # same bucketed shapes (the env override is read at trace time only)
    cfg = SchedulerConfiguration(
        mode="tpu",
        # unique per SEED too: a second seed reusing the first's bucketed
        # shapes would otherwise hit its jit cache and trace nothing
        profiles=(Profile(hard_pod_affinity_weight=1.0 + seed * 1e-6),),
    )
    sched = Scheduler(store, cfg, clock=clock)

    serial = 0
    for cycle in range(6):
        # a big mixed wave: bucketed P >= 128 so the chunked routing engages
        n_wave = rng.randint(70, 130)
        for _ in range(n_wave):
            kind = rng.random()
            if kind < 0.5:
                p = mk_pod(f"p{serial}", cpu=rng.choice([100, 300, 700]),
                           labels={"app": rng.choice(["web", "db"])})
            elif kind < 0.8:
                p = mk_pod(
                    f"s{serial}", cpu=200,
                    labels={"app": "web"},
                    topology_spread=(
                        t.TopologySpreadConstraint(
                            max_skew=2, topology_key=t.LABEL_ZONE,
                            when_unsatisfiable=t.DO_NOT_SCHEDULE,
                            label_selector=t.LabelSelector.of(app="web"),
                        ),
                    ),
                )
            else:
                p = mk_pod(
                    f"a{serial}", cpu=150, labels={"app": "db"},
                    affinity=t.Affinity(required_pod_anti_affinity=(
                        t.PodAffinityTerm(
                            topology_key=t.LABEL_HOSTNAME,
                            label_selector=t.LabelSelector.of(
                                app=f"solo{serial % 5}"),
                        ),)),
                )
            store.add_pod(p)
            serial += 1
        sched.run_until_idle()
        # churn: complete/delete a slice of bound pods so the next cycle
        # exercises the DELTA path (bind absorb + deletes), which is the
        # path debug_verify cross-checks
        bound = [p for p in store.pods.values() if p.node_name]
        for p in rng.sample(bound, min(len(bound), 30)):
            store.delete_pod(p.uid)
        clock.step(2.0)

        # capacity invariant under the chunked production route
        for nd in store.nodes.values():
            used = sum(
                q.requests.get(t.CPU, 0)
                for q in store.pods.values()
                if q.node_name == nd.name
                and q.phase not in (t.PHASE_SUCCEEDED, t.PHASE_FAILED)
            )
            assert used <= nd.allocatable[t.CPU], (nd.name, used)

    # the forced routing must have actually EXECUTED a chunked kernel
    # through the production route — the trace counters prove a fresh
    # chunked/rounds compilation happened in this process, which the env
    # predicate alone cannot (a warm jit cache would make it vacuous)
    # (either the dense or the incremental variant of the production
    # kernels satisfies the proof — the scheduler routes the _inc form
    # when the equivalence-class cache applies, ops/incremental.py)
    assert any(
        TRACE_COUNTS[k] > traced_before[k]
        for k in ("chunked", "rounds", "chunked_inc", "rounds_inc")
    ), (traced_before, TRACE_COUNTS)
    from kubernetes_tpu.ops.scores import infer_score_config, DEFAULT_SCORE_CONFIG

    assert sched._delta_enc is not None
    snap = sched.cache.update_snapshot()
    # ...and the delta cross-check must have RUN (not just been enabled)
    assert sched._delta_enc.debug_verify
    assert sched._delta_enc.stats["delta"] > 0, sched._delta_enc.stats
    assert sched._delta_enc.stats["verified"] > 0, sched._delta_enc.stats

    # decisions through the resident (delta-synced, verified) encoder match
    # a from-scratch encoder on the final state
    from kubernetes_tpu.api.delta import DeltaEncoder
    from kubernetes_tpu.ops import schedule_batch

    if snap.pending_pods:
        got_arr, gm = sched._delta_enc.encode(snap)
        want_arr, wm = DeltaEncoder().encode(snap)
        cfg = infer_score_config(want_arr, DEFAULT_SCORE_CONFIG)
        g = np.asarray(schedule_batch(got_arr, cfg)[0])[: gm.n_pods]
        w = np.asarray(schedule_batch(want_arr, cfg)[0])[: wm.n_pods]
        np.testing.assert_array_equal(g, w)
