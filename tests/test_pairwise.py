"""PodTopologySpread / InterPodAffinity / NodePorts / preferred-node-affinity
tests — table-driven (reference analog: podtopologyspread/filtering_test.go,
interpodaffinity/filtering_test.go, nodeports/node_ports_test.go)."""

import numpy as np

from kubernetes_tpu.api import types as t
from kubernetes_tpu.api.snapshot import Snapshot, encode_snapshot
from kubernetes_tpu.ops import DEFAULT_SCORE_CONFIG, schedule_batch
from kubernetes_tpu.oracle import oracle_schedule
from helpers import mk_node, mk_pod


def run_both(snap):
    arr, meta = encode_snapshot(snap)
    c = np.asarray(schedule_batch(arr, DEFAULT_SCORE_CONFIG)[0])
    got = [
        (meta.pod_names[k], meta.node_names[c[k]] if c[k] >= 0 else None)
        for k in range(meta.n_pods)
    ]
    want = oracle_schedule(snap)
    assert got == want, f"kernel={got} oracle={want}"
    return dict(got)


def zone_nodes(n_per_zone=2, zones=("a", "b", "c"), cpu=4000):
    out = []
    for z in zones:
        for i in range(n_per_zone):
            out.append(mk_node(f"n-{z}-{i}", cpu=cpu, labels={t.LABEL_ZONE: z}))
    return out


def spread(max_skew=1, key=t.LABEL_ZONE, hard=True, **sel):
    return t.TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=t.DO_NOT_SCHEDULE if hard else t.SCHEDULE_ANYWAY,
        label_selector=t.LabelSelector.of(**sel),
    )


def test_spread_hard_enforces_skew():
    # 3 zones, app pods must spread: 4 pods -> at most 2 in any zone with skew 1
    pods = [
        mk_pod(f"app-{i}", labels={"app": "web"}, topology_spread=(spread(app="web"),))
        for i in range(4)
    ]
    got = run_both(Snapshot(nodes=zone_nodes(), pending_pods=pods))
    zones = [v.split("-")[1] for v in got.values()]
    counts = {z: zones.count(z) for z in "abc"}
    assert max(counts.values()) - min(counts.values()) <= 1


def test_spread_unsatisfiable_when_skew_exceeded():
    # single zone already has 2 matching bound pods; maxSkew 1 vs empty zone b
    nodes = zone_nodes(zones=("a", "b"))
    bound = [
        mk_pod(f"old-{i}", labels={"app": "web"}, node_name="n-a-0") for i in range(2)
    ]
    # zone b nodes are cordoned -> only zone a feasible, but skew would be 3 > 1
    for nd in nodes:
        if "-b-" in nd.name:
            nd.unschedulable = True
    pod = mk_pod("new", labels={"app": "web"}, topology_spread=(spread(app="web"),))
    got = run_both(Snapshot(nodes=nodes, pending_pods=[pod], bound_pods=bound))
    # minMatch counts zone b (eligible by node-affinity terms; cordon is a taint,
    # not affinity) => skew 3 > 1: unschedulable
    assert got["new"] is None


def test_spread_node_missing_key_fails_hard_constraint():
    nodes = [mk_node("zoned", labels={t.LABEL_ZONE: "a"}), mk_node("keyless")]
    pod = mk_pod("p", labels={"app": "x"}, topology_spread=(spread(app="x"),))
    got = run_both(Snapshot(nodes=nodes, pending_pods=[pod]))
    assert got["p"] == "zoned"


def test_required_pod_affinity_first_pod_waiver_and_colocation():
    aff = t.Affinity(
        required_pod_affinity=(
            t.PodAffinityTerm(
                topology_key=t.LABEL_ZONE, label_selector=t.LabelSelector.of(app="db")
            ),
        )
    )
    pods = [
        mk_pod("db-0", labels={"app": "db"}, affinity=aff),  # waiver: self-match
        mk_pod("db-1", labels={"app": "db"}, affinity=aff),  # must join db-0's zone
    ]
    got = run_both(Snapshot(nodes=zone_nodes(), pending_pods=pods))
    z0 = got["db-0"].split("-")[1]
    z1 = got["db-1"].split("-")[1]
    assert z0 == z1


def test_required_affinity_no_match_no_self_is_unschedulable():
    aff = t.Affinity(
        required_pod_affinity=(
            t.PodAffinityTerm(
                topology_key=t.LABEL_ZONE, label_selector=t.LabelSelector.of(app="db")
            ),
        )
    )
    got = run_both(
        Snapshot(nodes=zone_nodes(), pending_pods=[mk_pod("web", labels={"app": "web"}, affinity=aff)])
    )
    assert got["web"] is None


def test_anti_affinity_one_per_zone():
    anti = t.Affinity(
        required_pod_anti_affinity=(
            t.PodAffinityTerm(
                topology_key=t.LABEL_ZONE, label_selector=t.LabelSelector.of(app="zk")
            ),
        )
    )
    pods = [mk_pod(f"zk-{i}", labels={"app": "zk"}, affinity=anti) for i in range(4)]
    got = run_both(Snapshot(nodes=zone_nodes(), pending_pods=pods))
    placed_zones = [v.split("-")[1] for v in got.values() if v]
    assert len(placed_zones) == 3 and len(set(placed_zones)) == 3  # 4th unschedulable
    assert sum(1 for v in got.values() if v is None) == 1


def test_existing_pod_anti_affinity_blocks_incoming():
    anti = t.Affinity(
        required_pod_anti_affinity=(
            t.PodAffinityTerm(
                topology_key=t.LABEL_ZONE, label_selector=t.LabelSelector.of(app="web")
            ),
        )
    )
    nodes = zone_nodes(zones=("a", "b"))
    bound = [mk_pod("lonely", labels={"app": "zk"}, affinity=anti, node_name="n-a-0")]
    got = run_both(
        Snapshot(nodes=nodes, pending_pods=[mk_pod("web", labels={"app": "web"})], bound_pods=bound)
    )
    # zone a is poisoned by lonely's anti-affinity against app=web
    assert got["web"].startswith("n-b-")


def test_host_ports_conflict():
    pods = [
        mk_pod("a", host_ports=(("TCP", 8080),)),
        mk_pod("b", host_ports=(("TCP", 8080),)),
        mk_pod("c", host_ports=(("UDP", 8080),)),  # different proto: no conflict
    ]
    got = run_both(Snapshot(nodes=[mk_node("n0"), mk_node("n1")], pending_pods=pods))
    assert got["a"] != got["b"]
    assert got["c"] is not None


def test_host_ports_conflict_with_bound():
    bound = [mk_pod("old", host_ports=(("TCP", 443),), node_name="n0")]
    got = run_both(
        Snapshot(
            nodes=[mk_node("n0"), mk_node("n1")],
            pending_pods=[mk_pod("new", host_ports=(("TCP", 443),))],
            bound_pods=bound,
        )
    )
    assert got["new"] == "n1"


def test_preferred_node_affinity_steers():
    pref = t.Affinity(
        preferred_node_terms=(
            t.PreferredSchedulingTerm(
                weight=10,
                preference=t.NodeSelectorTerm(
                    match_expressions=(
                        t.NodeSelectorRequirement(key="disktype", operator=t.OP_IN, values=("ssd",)),
                    )
                ),
            ),
        )
    )
    nodes = [
        mk_node("hdd-node", labels={"disktype": "hdd"}),
        mk_node("ssd-node", labels={"disktype": "ssd"}),
    ]
    got = run_both(Snapshot(nodes=nodes, pending_pods=[mk_pod("p", affinity=pref)]))
    assert got["p"] == "ssd-node"


def test_soft_spread_prefers_less_loaded_zone():
    nodes = zone_nodes(zones=("a", "b"))
    bound = [mk_pod(f"w-{i}", labels={"app": "web"}, node_name="n-a-0") for i in range(3)]
    pod = mk_pod(
        "new", labels={"app": "web"}, topology_spread=(spread(hard=False, app="web"),)
    )
    got = run_both(Snapshot(nodes=nodes, pending_pods=[pod], bound_pods=bound))
    assert got["new"].startswith("n-b-")
