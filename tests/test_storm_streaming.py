"""Storm-proof streaming (ISSUE 18): the pipelined batch loop, the open-loop
replay driver, and the admission valve under kill.* chaos.

Four invariants under test:

  1. exactly-once wave publication — a kill at ANY of the four streaming
     kill points (submit/dispatch/collect/drain), answered by
     run_stream_restartable's fresh-loop replay of the uncommitted suffix,
     yields verdicts bit-identical to the chaos-free oracle, with the
     committed prefix never re-published (WAL crc divergence is fatal);
  2. mid-stream leader failover — replay_trace under a kill.* plan resumes
     on a standby from the checkpointed trace cursor and finishes with a
     decision_crc equal to the un-killed replay, restarts and blackout
     recorded in the artifact's ha block;
  3. SLI phase telescoping survives restore — a pod popped pre-kill keeps
     its queue_wait; the takeover blackout lands in wave_wait, and the
     phases still sum to exactly the SLI sample;
  4. overload-graceful admission — the valve parks fair-share per priority
     band, sheds stale parks with CO-honest waits, and the accounting
     identity shed + scheduled + unschedulable == trace arrivals holds.

Seed-stability goldens pin FaultPlan.from_seed output: adding the streaming
kill sites must not reshuffle any pre-existing seeded storm."""

import os
import time
from types import SimpleNamespace

import numpy as np
import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.api.snapshot import Snapshot
from kubernetes_tpu.bench.loadgen import (
    ArrivalEvent,
    ArrivalTrace,
    replay_trace,
    rollout_trace,
)
from kubernetes_tpu.parallel.pipeline import (
    STREAM_WAL,
    PipelinedBatchLoop,
    load_stream_wal,
    run_serial,
    run_stream_restartable,
)
from kubernetes_tpu.scheduler import (
    ClusterStore,
    Scheduler,
    SchedulerConfiguration,
    restart_scheduler,
)
from kubernetes_tpu.scheduler.checkpoint import CheckpointManager
from kubernetes_tpu.scheduler.flightrecorder import (
    FlightRecorder,
    load_flight,
    render_flight,
)
from kubernetes_tpu.scheduler.flowcontrol import ADMISSION_COUNTERS, AdmissionValve
from kubernetes_tpu.scheduler.metrics import Metrics
from kubernetes_tpu.scheduler.tracing import TraceCollector

from helpers import mk_node, mk_pod


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _wave(seed: int, n_nodes: int = 6, n_pods: int = 12) -> Snapshot:
    rng = np.random.default_rng(seed)
    nodes = [mk_node(f"w{seed}-n{i}", cpu=int(rng.integers(2000, 8000)))
             for i in range(n_nodes)]
    pods = [mk_pod(f"w{seed}-p{j}", cpu=int(rng.integers(100, 1500)))
            for j in range(n_pods)]
    return Snapshot(nodes=nodes, pending_pods=pods)


# --- exactly-once across every streaming kill point x {serial, pipelined} ---
@pytest.mark.parametrize("depth", [0, 1])
@pytest.mark.parametrize("site", chaos.STREAM_KILL_SITES)
def test_stream_kill_exactly_once(site, depth, tmp_path):
    """kill -9 at each streaming kill point: the replacement loop replays
    exactly the uncommitted suffix and the full verdict stream is
    bit-identical to the chaos-free oracle."""
    waves = [_wave(s) for s in range(4)]
    oracle = list(run_serial(waves))
    # drain() runs once per incarnation, so only its first invocation can
    # fire; submit/dispatch/collect repeat per wave and use a later ordinal
    # to prove mid-stream (not first-wave) recovery
    at = 0 if site == "kill.drain" else 2
    ckpt = CheckpointManager(str(tmp_path))
    metrics = Metrics()
    with chaos.chaos_plan(chaos.FaultPlan.parse(f"{site}:kill@{at}")):
        inj = chaos.active()
        got, restarts = run_stream_restartable(
            waves,
            lambda commit, wal: PipelinedBatchLoop(
                depth=depth, commit=commit, wal=wal),
            checkpoint=ckpt, metrics=metrics,
        )
        rep = inj.report()
    assert restarts >= 1, f"{site} never fired — kill point unreachable"
    assert got == oracle
    # the chaos report names the streaming site and its recovery action
    assert rep[
        f'framework_fault_injected_total{{action="kill",site="{site}"}}'] >= 1
    assert rep[
        f'framework_fault_recovery_total{{action="stream_restart",site="{site}"}}'
    ] >= 1
    # the HA series the artifact's ha block reads: one blackout per restart
    assert metrics.counters["scheduler_restarts_total"] == restarts
    _p50, p99, n = metrics.hists["failover_duration_seconds"].stats()
    assert n == restarts and p99 > 0
    # the durable ledger holds every wave exactly once
    assert sorted(load_stream_wal(ckpt)) == list(range(len(waves)))


def test_stream_seeded_kill_storm(tmp_path):
    """A seeded storm across the streaming kill family: multiple kills,
    every one answered by a fresh-loop replay, verdicts bit-identical."""
    waves = [_wave(s) for s in range(5)]
    oracle = list(run_serial(waves))
    # horizon 6 keeps ordinals inside the storm's actual invocation counts
    # (poke counts are global across incarnations, so later ordinals are
    # reached by the replays the earlier kills force)
    plan = chaos.FaultPlan.from_seed(
        1, sites=chaos.STREAM_KILL_SITES, n_faults=6, horizon=6)
    assert all(f.site in chaos.STREAM_KILL_SITES and f.action == "kill"
               for f in plan.faults)
    ckpt = CheckpointManager(str(tmp_path))
    with chaos.chaos_plan(plan):
        got, restarts = run_stream_restartable(
            waves,
            lambda commit, wal: PipelinedBatchLoop(
                depth=1, commit=commit, wal=wal),
            checkpoint=ckpt,
        )
    assert restarts >= 2
    assert got == oracle


def test_stream_wal_replay_divergence_is_fatal(tmp_path):
    """A committed wave whose replay produces different verdicts is a real
    double-publication hazard: the driver must hard-error, never silently
    overwrite the committed record."""
    ckpt = CheckpointManager(str(tmp_path))
    ckpt.save(STREAM_WAL, {"committed": {"0": "not-the-real-crc"},
                           "inflight": {}})
    with pytest.raises(RuntimeError, match="refusing to double-publish"):
        run_stream_restartable(
            [_wave(0)],
            lambda commit, wal: PipelinedBatchLoop(
                depth=0, commit=commit, wal=wal),
            checkpoint=ckpt,
        )


def test_stream_restart_budget_is_bounded():
    """A kill point that fires on EVERY incarnation exhausts max_restarts
    and re-raises instead of spinning forever."""
    with chaos.chaos_plan(chaos.FaultPlan.parse(
            ";".join("kill.submit:kill@%d" % k for k in range(8)))):
        with pytest.raises(chaos.ProcessKilled):
            run_stream_restartable(
                [_wave(0)],
                lambda commit, wal: PipelinedBatchLoop(
                    depth=0, commit=commit, wal=wal),
                max_restarts=3,
            )


# --- seed stability: the new sites must not reshuffle existing storms ---
def test_seeded_storm_goldens_are_stable():
    """from_seed draws from the default pool, which excludes ALL kill sites
    (old and new): pinned golden strings prove a seed replays the identical
    plan after the streaming family landed."""
    assert chaos.FaultPlan.from_seed(0).describe() == (
        "seed=0 kubelet.sync:crash@6;sidecar.rpc:hang@7:0.0291;"
        "scheduler.step:nan@7;pipeline.step:error@8;sidecar.health:error@2;"
        "kubelet.sync:crash@9;kubelet.sync:crash@8;host.stall:stall@11:0.0128"
    )
    assert chaos.FaultPlan.from_seed(7).describe() == (
        "seed=7 pipeline.step:error@6;host.stall:stall@8:0.0068;"
        "sidecar.rpc:hang@8:0.0196;sidecar.health:error@1;"
        "scheduler.step:nan@1;sidecar.health:error@8;scheduler.step:error@9;"
        "sidecar.rpc:error@10"
    )
    for seed in range(8):
        plan = chaos.FaultPlan.from_seed(seed)
        assert not any(f.site in chaos.ALL_KILL_SITES for f in plan.faults)


# --- mid-stream leader failover: open-loop decision parity ---
def test_replay_trace_failover_decision_parity(tmp_path, monkeypatch):
    """The tentpole gate: an open-loop replay killed mid-stream resumes on
    a standby leader from the checkpointed trace cursor and finishes with
    a decision_crc bit-identical to the un-killed oracle — blackout in the
    ha block, zero pods lost, accounting identity intact."""
    trace = rollout_trace(seed=2, scale=0.15)
    base, _ = replay_trace(trace)
    monkeypatch.setenv("KTPU_CHECKPOINT_DIR", str(tmp_path))
    plan = chaos.FaultPlan.parse(
        "kill.post_checkpoint:kill@1;kill.post_checkpoint:kill@9")
    with chaos.chaos_plan(plan):
        art, sched = replay_trace(trace)
    assert art["restarts"] >= 1
    assert art["decision_crc"] == base["decision_crc"]
    assert art["scheduled"] == base["scheduled"]
    assert art["shed"] + art["scheduled"] + art["unschedulable"] == art["pods"]
    ha = art["ha"]
    assert ha and ha["scheduler_restarts_total"] >= 1
    assert ha["failover_count"] == art["restarts"]
    # failover percentiles stamped top-level next to sli_p99_ms
    # (regression.py gates them like any latency scalar)
    assert art["failover_p99_ms"] == ha["failover_p99_ms"] > 0
    # the resume cursor is evidence from the dead leader's checkpoint: it
    # names THIS trace and never runs ahead of the live driver
    rc = art["resume_cursor"]
    assert rc and rc["trace_crc"] == art["trace_crc"] == trace.fingerprint()
    assert 0 <= rc["i"] <= art["trace_events"]
    # recovered_waves rides the artifact for the ci.sh regression gate
    assert art["recovered_waves"] == art["restarts"]


def test_replay_trace_without_ha_plane_reraises(monkeypatch):
    """No kill.* fault in the armed plan means no HA plane: a ProcessKilled
    poked from elsewhere must propagate, not be silently absorbed."""
    trace = rollout_trace(seed=2, scale=0.15)
    art, _ = replay_trace(trace)  # non-kill storms replay unchanged
    assert art["restarts"] == 0 and art["ha"] is None
    assert "failover_p99_ms" not in art  # no HA: no stamped percentiles


# --- SLI phase telescoping across restore ---
def test_sli_phase_telescoping_survives_restore(tmp_path):
    """A pod popped into a wave pre-kill keeps its original queue_wait
    through the restore: the takeover blackout lands in wave_wait (where
    the dead time actually passed) and the four phases still telescope to
    exactly the SLI sample."""
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    try:
        metrics = Metrics()
        col = TraceCollector()
        store = ClusterStore()
        store.add_node(mk_node("n0", cpu=3000, pods=16))
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"),
                          metrics=metrics, collector=col)
        store.add_pod(mk_pod("v0", cpu=250))
        with chaos.chaos_plan(
                chaos.FaultPlan.parse("kill.post_checkpoint:kill@0")):
            with pytest.raises(chaos.ProcessKilled):
                sched.run_until_idle()
            time.sleep(0.06)  # the blackout while the leader is "dead"
            chaos.revive()
        sched2 = restart_scheduler(sched)
        sched2.run_until_idle()
        assert store.pods["default/v0"].node_name == "n0"
        worst = sched2.worst_sli_pods()
        assert worst
        w = worst[0]
        total = sum(w["phases_ms"].values())
        assert abs(total - w["sli_ms"]) < 1.0, w  # telescoping invariant
        # the pinned pop stamp keeps queue_wait at its pre-kill value; the
        # >=60ms blackout shows up downstream of the pop, not before it
        assert w["phases_ms"]["queue_wait"] < 25.0, w
        assert (w["phases_ms"]["wave_wait"] + w["phases_ms"]["device_kernel"]
                + w["phases_ms"]["bind"]) >= 40.0, w
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


# --- overload-graceful admission valve ---
def _item(priority=0, t=0.0):
    return SimpleNamespace(priority=priority, t=t)


def test_valve_disabled_is_invisible():
    v = AdmissionValve(watermark=0)
    items = [_item() for _ in range(5)]
    assert v.offer(items, depth=10_000, now=0.0) == items
    assert not v.enabled and v.parked_count == 0


def test_valve_env_knobs(monkeypatch):
    monkeypatch.setenv("KTPU_ADMIT_WATERMARK", "6")
    monkeypatch.setenv("KTPU_ADMIT_MAX_PARK_S", "2.5")
    v = AdmissionValve()
    assert v.enabled and v.watermark == 6 and v.max_park_s == 2.5


def test_valve_fair_share_parks_lowest_bands_first():
    m = Metrics()
    v = AdmissionValve(watermark=4, max_park_s=30.0, metrics=m)
    hi = [_item(priority=100, t=0.0) for _ in range(4)]
    lo = [_item(priority=0, t=0.0) for _ in range(4)]
    # under the watermark the valve is invisible
    assert v.offer(hi[:1], depth=0, now=0.0) == hi[:1]
    # saturated at depth == 2*watermark: budget collapses to the floor
    # (watermark//8 -> 1) and the single slot goes to the highest band FIFO
    admitted = v.offer(hi[1:] + lo, depth=8, now=1.0)
    assert admitted == [hi[1]]
    assert v.parked_count == 6
    assert m.counters["scheduler_admission_parked_total"] == 6
    # pressure eases: budget 2*4-5=3, split ceil(3/2)=2 high + 1 low, FIFO
    admitted = v.offer([], depth=5, now=2.0)
    assert admitted == [hi[2], hi[3], lo[0]]
    assert v.parked_count == 3
    # fully drained once depth falls under the watermark
    assert v.offer([], depth=0, now=3.0) == lo[1:]
    assert v.parked_count == 0
    assert v.shed_total == 0
    assert "scheduler_admission_parked_total" in ADMISSION_COUNTERS
    assert m.counters.get("scheduler_admission_shed_total", 0) == 0


def test_valve_sheds_stale_parks_with_co_honest_waits():
    m = Metrics()
    v = AdmissionValve(watermark=2, max_park_s=5.0, metrics=m)
    a, b = _item(priority=0, t=-2.0), _item(priority=0, t=0.0)
    assert v.offer([a, b], depth=10, now=0.0) == [a]  # floor=1, FIFO
    assert v.parked_count == 1
    # past the staleness bound the park sheds instead of admitting — and
    # the shed wait measures from the arrival's TRACE instant (b.t), not
    # from when the valve got around to deciding
    assert v.offer([], depth=10, now=6.0) == []
    assert v.parked_count == 0 and v.shed_total == 1
    assert m.counters["scheduler_admission_shed_total"] == 1
    _p50, p99, n = m.hists["pod_admission_shed_wait_seconds"].stats()
    assert n == 1 and p99 >= 6.0  # waited from t=0.0 to now=6.0
    assert v.shed_items == [b]


def test_valve_flush_sheds_everything_parked():
    m = Metrics()
    v = AdmissionValve(watermark=2, max_park_s=30.0, metrics=m)
    items = [_item(priority=p, t=0.0) for p in (0, 0, 50)]
    v.offer(items, depth=10, now=0.0)  # floor admits 1, parks 2
    assert v.parked_count == 2
    assert v.flush(now=1.0) == 2
    assert v.parked_count == 0 and v.shed_total == 2
    rep = v.report()
    assert rep["shed_total"] == 2 and rep["parked_now"] == 0
    assert rep["watermark"] == 2


def test_replay_trace_admission_identity_under_overload(monkeypatch):
    """The storm burst through a tight valve: waves shrink, low bands park,
    stale parks shed — and the artifact's accounting identity
    shed + scheduled + unschedulable == trace arrivals still holds, with
    the admission block stamped and decisions still deterministic."""
    monkeypatch.setenv("KTPU_ADMIT_WATERMARK", "4")
    monkeypatch.setenv("KTPU_ADMIT_MAX_PARK_S", "1.0")
    # a capacity-starved trace: one 32-CPU node, forty 8-CPU arrivals —
    # only four ever fit, so the queue depth pins far over the watermark
    # while arrivals keep coming due (the shipped scenarios scale their
    # node count with load and never back up at tier-1 scale).  Uniform
    # priority: a preemption eviction removes its victim from the store —
    # a legitimate fourth exit the admission identity does not model (band
    # fairness is unit-tested above)
    events = [ArrivalEvent(t=round(0.1 * k, 3), name=f"s{k:02d}", cpu_m=8000,
                           mem_mb=256)
              for k in range(40)]
    trace = ArrivalTrace(name="starved", scenario="starved", seed=0,
                         nodes=1, duration_s=4.0, events=events)
    a1, _ = replay_trace(trace)
    a2, _ = replay_trace(trace)
    assert a1["decision_crc"] == a2["decision_crc"]  # valve is deterministic
    adm = a1["admission"]
    assert adm and adm["watermark"] == 4
    assert adm["parked_total"] > 0  # the backlog genuinely overflowed
    assert a1["shed"] > 0  # stale parks genuinely shed
    assert a1["shed"] == adm["shed_total"]
    assert a1["shed"] + a1["scheduled"] + a1["unschedulable"] == a1["pods"]


# --- flight recorder context: where in the trace did it die ---
def test_flight_dump_carries_trace_context(tmp_path):
    rec = FlightRecorder(directory=str(tmp_path))
    rec.annotate(trace_crc="abc123", scenario="rollout",
                 trace_offset=7, v_now=1.75)
    rec.annotate(trace_offset=9)  # the cursor advances; later wins
    rec.record(profile="batch", pods=3, scheduled=3)
    path = rec.dump(reason="kill.post_checkpoint")
    doc = load_flight(path)
    assert doc["context"]["trace_crc"] == "abc123"
    assert doc["context"]["trace_offset"] == 9
    text = render_flight(doc)
    assert "context:" in text
    assert "trace_crc=abc123" in text and "trace_offset=9" in text
