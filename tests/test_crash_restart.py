"""Crash-restart & failover parity (ISSUE 7): a process kill at ANY
enumerated kill point — post-assume/pre-checkpoint, post-checkpoint/pre-bind,
mid-deferred-flush, mid-device-step with buffers in flight — answered by the
restart-from-checkpoint protocol yields final placements bit-identical to the
fault-free serial oracle: zero double-binds, zero lost pods.  Plus: corrupt
checkpoints are quarantined (never silently discarded), the arrival->bind SLI
survives restarts, and an active/standby HAReplica pair completes takeover
within one lease duration with the blackout recorded.

Tier-1 covers every kill point x {pipeline on/off} x {incremental on/off} at
smoke scale; the full seeded kill-storm soak with mesh8 handoff is `slow`."""

import contextlib
import copy
import os
import random
import time

import pytest

from kubernetes_tpu import chaos
from kubernetes_tpu.scheduler import (
    ClusterStore,
    Scheduler,
    SchedulerConfiguration,
    restart_scheduler,
    run_ha_restartable,
    run_restartable,
)
from kubernetes_tpu.scheduler.checkpoint import (
    CheckpointManager,
    load_scheduler_state,
    save_scheduler_state,
)
from kubernetes_tpu.scheduler.leases import HAReplica, LeaseStore
from kubernetes_tpu.scheduler.metrics import Metrics
from kubernetes_tpu.scheduler.queue import FakeClock
from kubernetes_tpu.scheduler.tracing import TraceCollector, Tracer

from helpers import mk_node, mk_pod


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _run(plan=None, ckpt_dir=None, pipeline=True, gang=True,
         incremental=True, collector=None, churn=0, metrics=None):
    """One scheduler lifetime driven through run_restartable: any kill.*
    fault is answered by restart-from-checkpoint and the run resumes on the
    replacement incarnation.  Returns (placements, final sched, restarts)."""
    os.environ["KTPU_PIPELINE"] = "1" if pipeline else "0"
    os.environ["KTPU_INCREMENTAL"] = "1" if incremental else "0"
    if ckpt_dir:
        os.environ["KTPU_CHECKPOINT_DIR"] = str(ckpt_dir)
    gates = () if gang else (("GangScheduling", False),)
    try:
        ctx = (chaos.chaos_plan(plan) if plan is not None
               else contextlib.nullcontext())
        with ctx:
            store = ClusterStore()
            for i in range(5):
                store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
            sched = Scheduler(
                store,
                SchedulerConfiguration(mode="tpu", feature_gates=gates),
                collector=collector, metrics=metrics,
            )
            for i in range(20):
                store.add_pod(mk_pod(f"p{i}", cpu=250))
            restarts = 0
            sched, restarts = run_restartable(sched)
            rng = random.Random(5)
            for r in range(churn):
                bound = sorted(
                    (p for p in store.pods.values() if p.node_name),
                    key=lambda p: p.uid,
                )
                for v in rng.sample(bound, 6):
                    store.delete_pod(v.uid)
                    q = copy.copy(v)
                    q.name = f"{v.name}-r{r}"
                    q.uid = ""
                    q.node_name = ""
                    q.__post_init__()
                    store.add_pod(q)
                sched, more = run_restartable(sched)
                restarts += more
            placements = {p.name: p.node_name for p in store.pods.values()}
            return placements, sched, restarts
    finally:
        os.environ.pop("KTPU_PIPELINE", None)
        os.environ.pop("KTPU_INCREMENTAL", None)
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


# --- kill-point parity: each enumerated point x pipeline x incremental ---
@pytest.mark.parametrize("incremental", [True, False])
@pytest.mark.parametrize("pipeline", [True, False])
@pytest.mark.parametrize("site", chaos.KILL_SITES)
def test_kill_point_parity(site, pipeline, incremental, tmp_path):
    """kill -9 at each enumerated kill point: the restarted incarnation
    replays the checkpoint and finishes with placements bit-identical to
    the fault-free serial oracle — no pod double-bound, none lost."""
    # mid_flush needs the deferred-commit window (non-gang async path with
    # pipelined commits armed — serial loops publish synchronously, so there
    # is no mid-flush to die in); the other sites are exercised on the
    # DEFAULT gang-gated path too
    if site == "kill.mid_flush" and not pipeline:
        pytest.skip("no deferred flush exists without pipelined commits")
    gang = site != "kill.mid_flush"
    oracle, _, _ = _run(pipeline=False, gang=gang, incremental=incremental)
    plan = chaos.FaultPlan.parse(f"{site}:kill@1" if site == "kill.mid_step"
                                 else f"{site}:kill@0")
    got, sched, restarts = _run(
        plan, ckpt_dir=tmp_path, pipeline=pipeline, gang=gang,
        incremental=incremental, churn=1,
    )
    oracle_churn, _, _ = _run(pipeline=False, gang=gang,
                              incremental=incremental, churn=1)
    assert restarts >= 1, f"{site} never fired — kill point unreachable"
    assert got == oracle_churn
    assert all(v for v in got.values())  # zero lost pods
    assert sched.metrics.counters["scheduler_restarts_total"] >= 1


def test_kill_storm_parity_smoke(tmp_path):
    """A seeded storm across ALL kill points (the acceptance schedule) with
    churn: every kill answered by a restart, placements bit-identical."""
    oracle, _, _ = _run(pipeline=False, gang=False, churn=2)
    plan = chaos.FaultPlan.from_seed(7, sites=chaos.KILL_SITES, n_faults=6)
    col = TraceCollector()
    got, sched, restarts = _run(
        plan, ckpt_dir=tmp_path, gang=False, churn=2, collector=col,
    )
    assert restarts >= 2
    assert got == oracle
    # every pod bound exactly once across all incarnations (the shared
    # event sink spans restarts): no double-publication anywhere
    ev = [e for e in sched.events.by_reason("Scheduled")]
    uids = [e.pod for e in ev]
    final_uids = {p.uid for p in
                  (p for p in sched.store.pods.values() if p.node_name)}
    assert final_uids <= set(uids)
    assert col.spans(name="scheduler.restore")


def test_kill_without_checkpoint_dir_is_pure_crash_only(tmp_path):
    """No KTPU_CHECKPOINT_DIR: a killed scheduler still restarts clean —
    everything rebuilds from LIST+WATCH (the crash-only floor)."""
    oracle, _, _ = _run(pipeline=False)
    got, sched, restarts = _run(chaos.FaultPlan.parse("kill.post_assume:kill@0"))
    assert restarts == 1
    assert got == oracle


def test_mid_flush_kill_replays_exactly_the_unpublished_suffix(tmp_path):
    """Kill part-way through the deferred fan-out: the published prefix
    survives in the store, the WAL replays ONLY the unpublished suffix —
    each pod ends with exactly one Scheduled event (exactly-once rule)."""
    plan = chaos.FaultPlan.parse("kill.mid_flush:kill@2")
    got, sched, restarts = _run(plan, ckpt_dir=tmp_path, gang=False)
    assert restarts == 1
    assert all(v for v in got.values())
    ev = sched.events.by_reason("Scheduled")
    assert len(ev) == 20
    uids = [e.pod for e in ev]
    assert len(uids) == len(set(uids))  # no pod published twice


def test_killed_latch_suppresses_dead_instance_teardown(tmp_path):
    """While killed() is latched, the dying instance's drain/flush paths do
    nothing a SIGKILL'd process couldn't — and revive() re-arms them."""
    store = ClusterStore()
    store.add_node(mk_node("n0", cpu=8000, pods=64))
    sched = Scheduler(store, SchedulerConfiguration(
        mode="tpu", feature_gates=(("GangScheduling", False),)))
    p = mk_pod("d0", cpu=100)
    store.add_pod(p)
    sched.cache.assume(p.uid, "n0")
    sched._deferred_binds.append((p, "n0"))
    from kubernetes_tpu.chaos import plan as _plan_mod

    with chaos.chaos_plan(chaos.FaultPlan.parse("kill.post_assume:kill@99")):
        _plan_mod._KILLED = True  # the latch lives in the plan module
        try:
            sched._flush_deferred_binds()  # dead process publishes nothing
            assert sched._deferred_binds  # nothing flushed
            assert store.pods[p.uid].node_name == ""
        finally:
            chaos.revive()
    sched._flush_deferred_binds()
    assert store.pods[p.uid].node_name == "n0"


# --- checkpoint corruption: quarantine, never silence ---
def test_corrupt_checkpoint_is_quarantined_and_counted(tmp_path):
    m = Metrics()
    cm = CheckpointManager(str(tmp_path), metrics=m)
    save_scheduler_state(cm, {"u1": "n0"}, [("u2", "n1")], {"u1": 1.5})
    path = os.path.join(str(tmp_path), "scheduler_state.json")
    with open(path, "w") as f:
        f.write('{"truncated')  # torn write / disk corruption
    assert cm.load("scheduler_state") is None
    assert os.path.exists(path + ".corrupt")  # evidence preserved
    assert not os.path.exists(path)
    assert m.counters["checkpoint_corrupt_total"] == 1


def test_checksum_mismatch_quarantines_too(tmp_path):
    import json

    m = Metrics()
    cm = CheckpointManager(str(tmp_path), metrics=m)
    cm.save("scheduler_state", {"assumed": {"u": "n"}})
    path = os.path.join(str(tmp_path), "scheduler_state.json")
    doc = json.load(open(path))
    doc["data"]["assumed"]["u"] = "evil"  # bit-flip without re-checksum
    json.dump(doc, open(path, "w"))
    assert cm.load("scheduler_state") is None
    assert os.path.exists(path + ".corrupt")
    assert m.counters["checkpoint_corrupt_total"] == 1


def test_absent_checkpoint_is_not_corruption(tmp_path):
    m = Metrics()
    cm = CheckpointManager(str(tmp_path), metrics=m)
    assert cm.load("scheduler_state") is None  # normal first boot
    assert m.counters.get("checkpoint_corrupt_total", 0) == 0
    assert not os.listdir(str(tmp_path))


def test_restore_after_corrupt_checkpoint_rebuilds_clean(tmp_path):
    """A corrupt checkpoint at restore time: quarantined + counted, then a
    pure crash-only rebuild schedules everything correctly anyway."""
    oracle, _, _ = _run(pipeline=False)
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    try:
        store = ClusterStore()
        for i in range(5):
            store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
        for i in range(20):
            store.add_pod(mk_pod(f"p{i}", cpu=250))
        # poison the checkpoint the constructor's dir now holds
        with open(os.path.join(str(tmp_path), "scheduler_state.json"), "w") as f:
            f.write("not json at all")
        report = sched.restore()
        assert report["wal_applied"] == 0
        assert sched.metrics.counters["checkpoint_corrupt_total"] == 1
        sched.run_until_idle()
        got = {p.name: p.node_name for p in store.pods.values()}
        assert got == oracle
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


def test_checkpoint_from_another_cluster_lineage_is_ignored(tmp_path):
    """uids are deterministic (namespace/name), so a checkpoint dir reused
    across clusters — harness rounds share one — must never replay a stale
    WAL into a new store whose uids merely collide; the same store's own
    restart still replays it exactly once."""
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    try:
        store1 = ClusterStore()
        store1.add_node(mk_node("n0", cpu=8000, pods=16))
        s1 = Scheduler(store1, SchedulerConfiguration(mode="tpu"))
        p = mk_pod("same-name", cpu=100)
        store1.add_pod(p)
        s1._deferred_binds.append((p, "n0"))
        s1._checkpoint_state()  # durable WAL entry for p's uid
        # a NEW cluster reusing the dir, with a COLLIDING uid
        store2 = ClusterStore()
        store2.add_node(mk_node("n0", cpu=8000, pods=16))
        s2 = Scheduler(store2, SchedulerConfiguration(mode="tpu"))
        store2.add_pod(mk_pod("same-name", cpu=100))
        report = s2.restore()
        assert report["wal_applied"] == 0  # stale lineage: nothing replayed
        assert store2.pods[p.uid].node_name == ""  # no premature bind
        assert s2.metrics.counters.get("checkpoint_corrupt_total", 0) == 0
        # the SAME store's restart replays its own WAL exactly once
        s1._checkpoint_state()
        s1b = restart_scheduler(s1)
        assert store1.pods[p.uid].node_name == "n0"
        assert s1b.metrics.counters["scheduler_restarts_total"] >= 1
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


# --- SLI continuity across restart ---
def test_arrival_stamps_ride_the_checkpoint(tmp_path):
    """A pod that waited before the crash keeps its served wait after the
    restart: the arrival->bind SLI includes pre-crash queue time instead of
    restarting the clock (failover inflates p99 honestly)."""
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    try:
        store = ClusterStore()
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
        store.add_pod(mk_pod("w0", cpu=100))  # no nodes yet: it waits
        sched._checkpoint_state()
        doc = load_scheduler_state(sched._ckpt)
        uid = next(iter(doc["arrivals"]))
        time.sleep(0.05)  # the wait it serves while the process is "dead"
        sched2 = restart_scheduler(sched)
        store.add_node(mk_node("n0", cpu=8000, pods=16))
        sched2.run_until_idle()
        p50, p99, count = sched2.metrics.hists[
            "pod_scheduling_sli_duration_seconds"
        ].stats()
        assert count == 1
        assert p99 >= 0.05  # the pre-restart wait is in the SLI
        assert store.pods[uid].node_name == "n0"
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


def test_stale_arrival_entries_do_not_seed_the_queue(tmp_path):
    """A checkpointed arrival stamp for a pod the relisted world no longer
    admits must not grow the arrival table unboundedly."""
    store = ClusterStore()
    sched = Scheduler(store, SchedulerConfiguration(mode="tpu"))
    n = sched.queue.restore_arrivals({"ghost-uid": 12.0})
    assert n == 0
    assert "ghost-uid" not in sched.queue._arrival_at


def test_open_loop_arrival_age_survives_kill_restore(tmp_path):
    """Load-observatory continuity: a pod admitted BEFORE a
    kill.post_checkpoint crash keeps its ORIGINAL arrival age through
    run_restartable's restore() — the post-restart bind observes the full
    pre-crash wait on the SHARED Metrics, so an open-loop replay that spans
    a restart still reports coordinated-omission-safe latencies instead of
    restarting every victim's clock at the reincarnation."""
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    try:
        metrics = Metrics()
        store = ClusterStore()
        store.add_node(mk_node("n0", cpu=3000, pods=16))
        sched = Scheduler(store, SchedulerConfiguration(mode="tpu"),
                          metrics=metrics)
        store.add_pod(mk_pod("aged", cpu=250))
        time.sleep(0.08)  # the pre-crash wait the restored SLI must retain
        plan = chaos.FaultPlan.parse("kill.post_checkpoint:kill@0")
        with chaos.chaos_plan(plan):
            sched, restarts = run_restartable(sched)
        assert restarts == 1
        assert store.pods["default/aged"].node_name == "n0"
        p50, p99, count = metrics.hists[
            "pod_scheduling_sli_duration_seconds"
        ].stats()
        assert count == 1
        # a clock restarted at reincarnation would observe ~ms, not 80ms+
        assert p99 >= 0.08
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


# --- active/standby failover ---
def _ha_pair(store, metrics, collector, lease_s=5.0):
    clock = FakeClock()
    leases = LeaseStore(clock=clock)

    def make():
        return Scheduler(store, SchedulerConfiguration(mode="tpu"),
                         metrics=metrics, collector=collector)

    a = HAReplica("sched-a", leases, make, lease_duration_s=lease_s,
                  metrics=metrics)
    b = HAReplica("sched-b", leases, make, lease_duration_s=lease_s,
                  metrics=metrics)
    return a, b, clock


def test_standby_takes_over_within_one_lease_duration(tmp_path):
    """Active dies silently (kill -9: it just stops renewing); the standby's
    first tick past lease expiry wins the CAS, restores, and schedules the
    backlog — blackout recorded in failover_duration_seconds and the
    takeover emits a leader.takeover span."""
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    try:
        metrics = Metrics()
        col = TraceCollector()
        store = ClusterStore()
        for i in range(5):
            store.add_node(mk_node(f"h{i}", cpu=3000, pods=16))
        a, b, clock = _ha_pair(store, metrics, col, lease_s=5.0)
        assert a.tick() is True  # first election
        assert b.tick() is False  # standby stays cold (no scheduler at all)
        assert b.scheduler is None
        for i in range(10):
            store.add_pod(mk_pod(f"q{i}", cpu=200))
        a.scheduler.run_until_idle()
        a.kill()
        # within the lease the standby CANNOT take over (CAS fails) ...
        clock.step(4.9)
        assert b.tick() is False
        # ... one retry period past expiry it must
        clock.step(0.2)
        t0 = metrics.counters.get("leader_election_transitions_total", 0)
        assert b.tick() is True
        assert metrics.counters["leader_election_transitions_total"] == t0 + 1
        for i in range(10, 20):
            store.add_pod(mk_pod(f"q{i}", cpu=200))
        b.scheduler.run_until_idle()
        assert all(p.node_name for p in store.pods.values())
        p50, p99, count = metrics.hists["failover_duration_seconds"].stats()
        assert count >= 1
        spans = col.spans(name="leader.takeover")
        assert spans
        # lease-clock blackout half: the takeover landed 0.1 lease-seconds
        # past expiry — within one lease duration (the pair invariant)
        blackouts = [s.attributes.get("blackout_s", 0.0) for s in spans]
        assert max(blackouts) <= 5.0
        assert metrics.counters["scheduler_restarts_total"] >= 1
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


def test_run_ha_restartable_records_failover_in_metrics(tmp_path):
    """The bench driver's answer to a kill storm (harness chaos rounds):
    every kill fells the leader and a standby's leader-elected takeover
    resumes the run — parity holds, the blackout lands in
    failover_duration_seconds, and ha_fields turns it into the artifact's
    ha block next to the SLI."""
    oracle, _, _ = _run(pipeline=False, gang=False)
    os.environ["KTPU_CHECKPOINT_DIR"] = str(tmp_path)
    lease_s = 0.1
    try:
        plan = chaos.FaultPlan.parse("kill.post_checkpoint:kill@0")
        with chaos.chaos_plan(plan):
            store = ClusterStore()
            for i in range(5):
                store.add_node(mk_node(f"n{i}", cpu=3000, pods=16))
            col = TraceCollector()
            sched = Scheduler(
                store,
                SchedulerConfiguration(
                    mode="tpu", feature_gates=(("GangScheduling", False),)
                ),
                collector=col,
            )
            for i in range(20):
                store.add_pod(mk_pod(f"p{i}", cpu=250))
            sched, restarts = run_ha_restartable(sched, lease_duration_s=lease_s)
            got = {p.name: p.node_name for p in store.pods.values()}
        assert restarts == 1
        assert got == oracle
        m = sched.metrics
        assert m.counters["leader_election_transitions_total"] >= 1
        assert m.counters["scheduler_restarts_total"] >= 1
        _p50, p99, count = m.hists["failover_duration_seconds"].stats()
        assert count == 1
        assert p99 > 0
        # pair invariant: the takeover CAS landed within one lease duration
        # of the dead leader's expiry (the driver renews at the death
        # instant, so blackout_s measures death -> takeover overshoot)
        spans = col.spans(name="leader.takeover")
        assert spans
        assert max(
            s.attributes.get("blackout_s", 0.0) for s in spans
        ) <= lease_s
        from kubernetes_tpu.bench.harness import ha_fields

        out = ha_fields(m)
        assert out["failover_count"] == 1
        assert out["leader_election_transitions_total"] >= 1
    finally:
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)


def test_dead_replica_never_reacquires(tmp_path):
    metrics = Metrics()
    store = ClusterStore()
    store.add_node(mk_node("h0", cpu=3000, pods=16))
    a, b, clock = _ha_pair(store, metrics, TraceCollector(), lease_s=5.0)
    assert a.tick()
    a.kill()
    clock.step(100.0)
    assert a.tick() is False  # dead stays dead
    assert b.tick() is True


# --- chaos-site selection (bench.harness --chaos-sites) ---
def test_sites_matching_globs():
    # kill.* spans BOTH families now: the scheduler's four original kill
    # points plus the streaming loop's (SITE_ACTIONS order)
    assert chaos.sites_matching("kill.*") == chaos.ALL_KILL_SITES
    assert chaos.ALL_KILL_SITES == chaos.KILL_SITES + chaos.STREAM_KILL_SITES
    rest = chaos.sites_matching("*,!kill.*")
    assert not set(rest) & set(chaos.ALL_KILL_SITES)
    assert "sidecar.rpc" in rest
    mixed = chaos.sites_matching("scheduler.*,kill.mid_flush")
    assert "scheduler.step" in mixed and "kill.mid_flush" in mixed
    assert chaos.sites_matching("no.such.*") == ()


def test_seeded_storms_exclude_kill_sites_by_default():
    """Pre-existing seeds must keep producing identical plans: the default
    pool never draws kill.* (only sites_matching('kill.*') storms do)."""
    for seed in range(12):
        plan = chaos.FaultPlan.from_seed(seed)
        assert not any(f.site in chaos.KILL_SITES for f in plan.faults)
    killplan = chaos.FaultPlan.from_seed(0, sites=chaos.KILL_SITES)
    assert all(f.site in chaos.KILL_SITES for f in killplan.faults)
    assert all(f.action == "kill" for f in killplan.faults)


def test_ha_fields_artifact_block():
    from kubernetes_tpu.bench.harness import ha_fields

    m = Metrics()
    assert ha_fields(m) is None  # untouched run keeps its artifact shape
    m.inc("scheduler_restarts_total")
    m.observe("failover_duration_seconds", 0.2)
    out = ha_fields(m)
    assert out["scheduler_restarts_total"] == 1.0
    assert out["failover_count"] == 1
    assert out["failover_p99_ms"] > 0


def test_chaos_sites_flag_requires_chaos():
    from kubernetes_tpu.bench.harness import main

    with pytest.raises(SystemExit):
        main(["--chaos-sites", "kill.*", "--out", "/dev/null"])


# --- the slow soak: seeded kill storm + mesh8 active/standby handoff ---
@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 11])
def test_kill_storm_soak_with_handoff_mesh8(mesh8, seed, tmp_path):
    """Seeded kill-storm soak under the 8-way mesh with an active/standby
    handoff mid-storm: placements stay bit-identical to the never-failed
    oracle and the takeover blackout is recorded."""
    os.environ["KTPU_MESH"] = "8"
    try:
        oracle, _, _ = _run(pipeline=False, gang=False, churn=3)
        plan = chaos.FaultPlan.from_seed(
            seed, sites=chaos.KILL_SITES, n_faults=10, horizon=24,
        )
        got, sched, restarts = _run(
            plan, ckpt_dir=tmp_path, gang=False, churn=3,
        )
        assert got == oracle
        assert restarts >= 1
        # handoff on the surviving store: the standby relists + restores
        metrics = sched.metrics
        col = TraceCollector()
        a, b, clock = _ha_pair(sched.store, metrics, col, lease_s=5.0)
        assert a.tick()
        a.kill()
        clock.step(5.2)
        assert b.tick()
        _, _, count = metrics.hists["failover_duration_seconds"].stats()
        assert count >= 1
        after = {p.name: p.node_name for p in b.scheduler.store.pods.values()}
        assert after == got  # takeover rewrites nothing
    finally:
        os.environ.pop("KTPU_MESH", None)
        os.environ.pop("KTPU_CHECKPOINT_DIR", None)
