#!/usr/bin/env bash
# The CI gate — the exact checks every push must pass, runnable by humans
# too (`./ci.sh`), so CI and a laptop can never disagree about what green
# means.  Seven stages, fail-fast:
#
#   1. tier-1 tests        the ROADMAP.md tier-1 command (not slow, 870 s cap)
#   2. ktpu-verify         AST + device + shard + mem passes (KTPU001–020:
#                          the device cost observatory's KTPU019 sub-phase
#                          ledger AND the HBM telemetry plane's KTPU020
#                          measured-vs-analytic reconciliation — leak
#                          sentinel clean + census==size-model on all
#                          twelve routes, on the forced 8-device platform)
#   3. --profile smoke     the device cost observatory + memwatch end to
#                          end in a fresh process (bench.harness --stream
#                          --profile): sub-phase capture + analytic
#                          reconciliation must pass AND the stream's leak
#                          sentinel must be clean (the harness exits 1 on
#                          any of the three failures)
#   4. open-loop smoke     the load observatory end to end (bench.harness
#                          --open-loop rollout --sli-attribution at reduced
#                          scale): the artifact must stamp a finite headline
#                          SLI with per-phase p99 shares summing to ~1.0
#   5. open-loop storm     the SAME rollout replay under a seeded
#                          kill.post_checkpoint storm (mid-stream leader
#                          failover — bench/loadgen.py): decision_crc must
#                          equal the stage-4 un-killed replay bit-for-bit,
#                          restarts >= 1, and the admission accounting
#                          identity shed + scheduled + unschedulable ==
#                          trace arrivals must hold
#   6. regression gates    bench/regression.py over the BENCH_r*.json
#                          trajectory (same-platform comparison only), plus
#                          the observatory's round_loop_fraction /
#                          device_flops / device_hbm_bytes scalars, the
#                          memwatch plane's measured hbm_peak_bytes from
#                          the stage-3 artifact, the commit-wave
#                          rounds_executed sweep count (class-batched
#                          commit waves — the number the batching
#                          collapses), and the storm stage's
#                          recovered_waves / failover_p99_ms
#   7. autotune smoke      bench/autotune.py end to end: sweep 2 knob
#                          candidates in fresh subprocesses, persist the
#                          winner next to the (smoke) compile cache, and
#                          prove a second process RELOADS it (ops/tuning.py
#                          env > winner > default resolution)
#
# Exit non-zero on the first failing stage.  .github/workflows/ci.yml runs
# exactly this script.
set -uo pipefail
cd "$(dirname "$0")"

echo "=== [1/7] tier-1 tests ==="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  echo "ci: tier-1 tests failed (rc=$rc)" >&2
  exit "$rc"
fi

echo "=== [2/7] ktpu-verify (AST + device + shard + mem, incl. KTPU019/KTPU020) ==="
# packed data plane pinned ON explicitly (its default): the device/shard/mem
# passes must price and reconcile the packed word planes + bf16 score path
# (KTPU007 bf16-accumulation legality, KTPU012/KTPU020 packed size model)
JAX_PLATFORMS=cpu KTPU_PACK_MASKS=1 KTPU_SCORE_DTYPE=bf16 \
  python -m kubernetes_tpu.analysis --device --shard --mem || {
  rc=$?
  echo "ci: ktpu-verify failed (rc=$rc; 1 = unbaselined findings, 2 = unusable)" >&2
  exit "$rc"
}

echo "=== [3/7] device cost observatory + memwatch smoke (--profile) ==="
# fresh process (XLA parses dump flags once); reduced stream shape so the
# smoke prices the capture path, not the full BENCH scale.  The stream's
# artifact also carries the memwatch block: the harness exits 1 when the
# leak sentinel trips, so this stage is the memwatch smoke too.
rm -rf /tmp/ktpu-ci-profile
# --stream 3, not 2: the sentinel needs >= 3 samples (SENTINEL_MIN_SAMPLES)
# before it may call a monotone rise a leak — a 2-wave stream could never
# trip the exit-1 gate this stage exists for
JAX_PLATFORMS=cpu KTPU_STREAM_SHAPE=512x128 \
  python -m kubernetes_tpu.bench.harness --stream 3 \
  --profile /tmp/ktpu-ci-profile --out /tmp/KTPU_CI_PROFILE.json \
  > /dev/null || {
  rc=$?
  echo "ci: --profile/memwatch smoke failed (rc=$rc; capture, reconciliation, or leak sentinel)" >&2
  exit "$rc"
}

echo "=== [4/7] open-loop load observatory smoke ==="
# reduced-scale rollout ramp on the cpu sim: proves the open-loop driver,
# the CO-safe SLI stamping and the phase decomposition end to end.  The
# python step asserts the acceptance contract on the artifact itself.
JAX_PLATFORMS=cpu KTPU_OPEN_LOOP_SCALE=0.5 \
  python -m kubernetes_tpu.bench.harness --open-loop rollout \
  --sli-attribution --out /tmp/KTPU_CI_OPENLOOP.json > /dev/null || {
  rc=$?
  echo "ci: open-loop smoke failed (rc=$rc)" >&2
  exit "$rc"
}
python - <<'PY' || { echo "ci: open-loop artifact contract violated" >&2; exit 1; }
import json, math
art = json.load(open("/tmp/KTPU_CI_OPENLOOP.json"))
assert art["latency_mode"] == "open-loop", art["latency_mode"]
assert art["sli_count"] > 0
for k in ("sli_p50_ms", "sli_p99_ms"):
    assert math.isfinite(art[k]) and art[k] >= 0, (k, art[k])
shares = sum(p["p99_share"] for p in art["sli_phases"].values())
assert abs(shares - 1.0) < 1e-3, art["sli_phases"]
PY

echo "=== [5/7] open-loop storm: mid-stream failover decision parity ==="
# the SAME rollout replay, now under a seeded kill.post_checkpoint storm
# with a checkpoint dir armed: the scheduler must die mid-stream, a
# standby must take over from the checkpointed trace cursor, and the
# final decision_crc must equal the stage-4 un-killed replay BIT FOR BIT
# (blackout moves latency, never placement).  The python step also
# asserts the CO-honest admission accounting identity — every trace
# arrival is scheduled, unschedulable, or honestly counted as shed.
rm -rf /tmp/ktpu-ci-storm-ckpt
JAX_PLATFORMS=cpu KTPU_OPEN_LOOP_SCALE=0.5 \
  KTPU_CHECKPOINT_DIR=/tmp/ktpu-ci-storm-ckpt \
  KTPU_FAULT_PLAN="kill.post_checkpoint:kill@1;kill.post_checkpoint:kill@25" \
  python -m kubernetes_tpu.bench.harness --open-loop rollout \
  --out /tmp/KTPU_CI_STORM.json > /dev/null || {
  rc=$?
  echo "ci: open-loop storm failed (rc=$rc)" >&2
  exit "$rc"
}
python - <<'PY' || { echo "ci: storm artifact contract violated" >&2; exit 1; }
import json
base = json.load(open("/tmp/KTPU_CI_OPENLOOP.json"))
storm = json.load(open("/tmp/KTPU_CI_STORM.json"))
# exactly-once, bit-identical placement across the kill: the crc is over
# every (pod, verdict, node) decision in commit order
assert storm["decision_crc"] == base["decision_crc"], (
    storm["decision_crc"], base["decision_crc"])
assert storm["restarts"] >= 1, storm["restarts"]
assert storm["ha"] and storm["ha"]["failover_p99_ms"] > 0, storm["ha"]
# admission accounting identity: shed + scheduled + unschedulable must
# telescope back to the trace's arrivals — no pod silently dropped
total = storm["shed"] + storm["scheduled"] + storm["unschedulable"]
assert total == storm["pods"], (total, storm["pods"])
PY

echo "=== [6/7] bench regression gates ==="
# exit 2 = no comparable same-platform artifact pair on this runner — the
# gate is advisory there (CI boxes have no BENCH trajectory of their own);
# a real regression (exit 1) still fails the build
run_gate() {
  python -m kubernetes_tpu.bench.regression "$@"
  rc=$?
  if [ "$rc" -eq 2 ]; then
    echo "ci: regression gate ($*) unusable on this runner — skipped"
  elif [ "$rc" -ne 0 ]; then
    echo "ci: bench regression gate ($*) failed (rc=$rc)" >&2
    exit "$rc"
  fi
}
run_gate
run_gate --metric round_loop_fraction --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric device_flops --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric device_hbm_bytes --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric hbm_peak_bytes --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric sli_p99_ms --current /tmp/KTPU_CI_OPENLOOP.json
# the commit-wave sweep count (class-batched commit waves): BENCH_r07+
# stamps rounds_executed; a change that silently reinflates the round
# count fails here even when wall time hides it on a fast box
run_gate --metric rounds_executed
# the packed-data-plane headline (BENCH_r08+): the analytic per-shard HBM
# ceiling must never silently reinflate — a change that unpacks a resident
# plane or widens a score matrix fails here even when wall time hides it
run_gate --metric per_shard_hbm_bytes
# storm-stage gates: recovered_waves must not silently drop (a storm that
# stops restarting stopped testing failover) and the blackout-inclusive
# failover p99 must not regress vs prior storm artifacts on this box
run_gate --metric recovered_waves --higher-is-better --current /tmp/KTPU_CI_STORM.json
run_gate --metric failover_p99_ms --current /tmp/KTPU_CI_STORM.json

echo "=== [7/7] autotune smoke (sweep -> persist -> reload) ==="
# two tiny candidates in fresh subprocesses (the knobs are trace-time
# constants); the second probe must RELOAD the persisted winner with no
# knob env set — proving the ops/tuning.py env > winner > default chain
rm -rf /tmp/ktpu-ci-tuning
# one candidate per packed-plane setting (6-field syntax; the first also
# proves the legacy-default fill for PACK_MASKS/SCORE_DTYPE stays bf16+packed)
JAX_PLATFORMS=cpu KTPU_FORCE_CHUNKED=1 \
  python -m kubernetes_tpu.bench.autotune sweep --nodes 128 --pods 256 \
  --candidates "32:48:12:256,16:32:6:128:0:f32" --tuning-dir /tmp/ktpu-ci-tuning \
  > /tmp/KTPU_CI_AUTOTUNE.json || {
  rc=$?
  echo "ci: autotune sweep failed (rc=$rc)" >&2
  exit "$rc"
}
JAX_PLATFORMS=cpu KTPU_TUNING_DIR=/tmp/ktpu-ci-tuning \
  python -m kubernetes_tpu.bench.autotune probe --nodes 64 --pods 128 \
  > /tmp/KTPU_CI_AUTOTUNE_RELOAD.json || {
  rc=$?
  echo "ci: autotune reload probe failed (rc=$rc)" >&2
  exit "$rc"
}
python - <<'PY' || { echo "ci: autotune winner not reloaded" >&2; exit 1; }
import json
sweep = json.load(open("/tmp/KTPU_CI_AUTOTUNE.json"))
probe = json.load(open("/tmp/KTPU_CI_AUTOTUNE_RELOAD.json"))
assert sweep["winner"], sweep
assert sweep["persisted"], "sweep did not persist a winner file"
# the fresh probe process resolved every tuned knob to the persisted
# winner (no knob env set — only KTPU_TUNING_DIR)
for k, v in sweep["winner"].items():
    assert probe["knobs"][k] == v, (k, probe["knobs"], sweep["winner"])
PY

echo "CI green"
