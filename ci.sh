#!/usr/bin/env bash
# The CI gate — the exact checks every push must pass, runnable by humans
# too (`./ci.sh`), so CI and a laptop can never disagree about what green
# means.  Three stages, fail-fast:
#
#   1. tier-1 tests        the ROADMAP.md tier-1 command (not slow, 870 s cap)
#   2. ktpu-verify         AST + device + shard passes (KTPU001–018) — the
#                          verify stack PRs 8–10 built, gated on every push
#   3. regression gate     bench/regression.py over the BENCH_r*.json
#                          trajectory (same-platform comparison only)
#
# Exit non-zero on the first failing stage.  .github/workflows/ci.yml runs
# exactly this script.
set -uo pipefail
cd "$(dirname "$0")"

echo "=== [1/3] tier-1 tests ==="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  echo "ci: tier-1 tests failed (rc=$rc)" >&2
  exit "$rc"
fi

echo "=== [2/3] ktpu-verify (AST + device + shard) ==="
JAX_PLATFORMS=cpu python -m kubernetes_tpu.analysis --device --shard || {
  rc=$?
  echo "ci: ktpu-verify failed (rc=$rc; 1 = unbaselined findings, 2 = unusable)" >&2
  exit "$rc"
}

echo "=== [3/3] bench regression gate ==="
python -m kubernetes_tpu.bench.regression || {
  rc=$?
  if [ "$rc" -eq 2 ]; then
    # unusable = no comparable same-platform artifact pair on this runner —
    # the gate is advisory there (CI boxes have no BENCH trajectory of
    # their own); a real regression (exit 1) still fails the build
    echo "ci: regression gate unusable on this runner (no comparable artifacts) — skipped"
  else
    echo "ci: bench regression gate failed (rc=$rc)" >&2
    exit "$rc"
  fi
}

echo "CI green"
