#!/usr/bin/env bash
# The CI gate — the exact checks every push must pass, runnable by humans
# too (`./ci.sh`), so CI and a laptop can never disagree about what green
# means.  Four stages, fail-fast:
#
#   1. tier-1 tests        the ROADMAP.md tier-1 command (not slow, 870 s cap)
#   2. ktpu-verify         AST + device + shard + mem passes (KTPU001–020:
#                          the device cost observatory's KTPU019 sub-phase
#                          ledger AND the HBM telemetry plane's KTPU020
#                          measured-vs-analytic reconciliation — leak
#                          sentinel clean + census==size-model on all
#                          twelve routes, on the forced 8-device platform)
#   3. --profile smoke     the device cost observatory + memwatch end to
#                          end in a fresh process (bench.harness --stream
#                          --profile): sub-phase capture + analytic
#                          reconciliation must pass AND the stream's leak
#                          sentinel must be clean (the harness exits 1 on
#                          any of the three failures)
#   4. regression gates    bench/regression.py over the BENCH_r*.json
#                          trajectory (same-platform comparison only), plus
#                          the observatory's round_loop_fraction /
#                          device_flops / device_hbm_bytes scalars and the
#                          memwatch plane's measured hbm_peak_bytes from
#                          the stage-3 artifact
#
# Exit non-zero on the first failing stage.  .github/workflows/ci.yml runs
# exactly this script.
set -uo pipefail
cd "$(dirname "$0")"

echo "=== [1/4] tier-1 tests ==="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  echo "ci: tier-1 tests failed (rc=$rc)" >&2
  exit "$rc"
fi

echo "=== [2/4] ktpu-verify (AST + device + shard + mem, incl. KTPU019/KTPU020) ==="
JAX_PLATFORMS=cpu python -m kubernetes_tpu.analysis --device --shard --mem || {
  rc=$?
  echo "ci: ktpu-verify failed (rc=$rc; 1 = unbaselined findings, 2 = unusable)" >&2
  exit "$rc"
}

echo "=== [3/4] device cost observatory + memwatch smoke (--profile) ==="
# fresh process (XLA parses dump flags once); reduced stream shape so the
# smoke prices the capture path, not the full BENCH scale.  The stream's
# artifact also carries the memwatch block: the harness exits 1 when the
# leak sentinel trips, so this stage is the memwatch smoke too.
rm -rf /tmp/ktpu-ci-profile
# --stream 3, not 2: the sentinel needs >= 3 samples (SENTINEL_MIN_SAMPLES)
# before it may call a monotone rise a leak — a 2-wave stream could never
# trip the exit-1 gate this stage exists for
JAX_PLATFORMS=cpu KTPU_STREAM_SHAPE=512x128 \
  python -m kubernetes_tpu.bench.harness --stream 3 \
  --profile /tmp/ktpu-ci-profile --out /tmp/KTPU_CI_PROFILE.json \
  > /dev/null || {
  rc=$?
  echo "ci: --profile/memwatch smoke failed (rc=$rc; capture, reconciliation, or leak sentinel)" >&2
  exit "$rc"
}

echo "=== [4/4] bench regression gates ==="
# exit 2 = no comparable same-platform artifact pair on this runner — the
# gate is advisory there (CI boxes have no BENCH trajectory of their own);
# a real regression (exit 1) still fails the build
run_gate() {
  python -m kubernetes_tpu.bench.regression "$@"
  rc=$?
  if [ "$rc" -eq 2 ]; then
    echo "ci: regression gate ($*) unusable on this runner — skipped"
  elif [ "$rc" -ne 0 ]; then
    echo "ci: bench regression gate ($*) failed (rc=$rc)" >&2
    exit "$rc"
  fi
}
run_gate
run_gate --metric round_loop_fraction --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric device_flops --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric device_hbm_bytes --current /tmp/KTPU_CI_PROFILE.json
run_gate --metric hbm_peak_bytes --current /tmp/KTPU_CI_PROFILE.json

echo "CI green"
