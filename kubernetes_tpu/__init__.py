"""kubernetes_tpu — a TPU-native scheduling framework.

A brand-new framework with the capabilities of the reference Kubernetes fork
(zizhuo-yan/kubernetes): the kube-scheduler's per-pod Filter/Score fan-out
(reference: pkg/scheduler/schedule_one.go — ScheduleOne) is recast as a batched
constraint-satisfaction problem scored on TPU.  One jitted XLA program evaluates
a (pending-pods x nodes) feasibility + score matrix for the default-profile
plugins, then a `lax.scan` commit pass reproduces the reference's sequential
one-pod-at-a-time semantics exactly.

Plugin coverage so far (kernel + oracle, parity-tested): NodeResourcesFit
(filter + LeastAllocated score), NodeResourcesBalancedAllocation,
TaintToleration (filter + score), NodeAffinity required terms + nodeSelector
(all operators), NodeName, NodeUnschedulable (toleration-aware), SchedulingGates.
In progress (fields exist on the API types but are not yet enforced):
PodTopologySpread, InterPodAffinity, NodePorts, preferred (soft) affinities,
gang scheduling, preemption.

Layout (SURVEY.md §7):
  api/        cluster model: Pod/Node dataclasses + Snapshot -> device arrays (L0)
  ops/        jitted filter/score/assignment kernels (L1-L3)
  parallel/   device-mesh sharding: node-axis DP, ring blockwise affinity (§2.4)
  oracle/     NumPy sequential reference scheduler — the parity oracle (L5)
  bench/      scheduler_perf-style workload harness (L6)
"""

__version__ = "0.1.0"
