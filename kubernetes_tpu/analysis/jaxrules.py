"""ktpu-verify device rules KTPU007..KTPU012 — invariants of the COMPILED
placement kernels.

The AST rules (rules.py) see Python; the invariants that gate the north
star live below it, in the jaxprs and compiled executables of
ops/assign.py / ops/incremental.py / parallel/sharded.py.  Each rule here
checks one machine-readable artifact captured by analysis/devicecheck.py
(a RouteTrace per production kernel route):

  KTPU007 dtype-flow          no f64 promotion anywhere in the traced
                              program; the integer argmax/tie-break lattice
                              is never narrowed through bf16/f16 (the
                              load-bearing precondition for ROADMAP 4's
                              bf16 scores: raw scores may shrink, node ids
                              and usage counts may not)
  KTPU008 donation-honored    declared donate_argnums survive lowering as
                              input_output_aliases / buffer-donor marks —
                              the runtime twin of KTPU003 (a backend that
                              silently ignores donation doubles peak HBM
                              without failing any test)
  KTPU009 collective-sequence under a mesh every shard runs the identical
                              ordered collective sequence — a collective
                              inside one `cond` branch but not the other is
                              a cross-shard deadlock waiting for the first
                              shard-divergent predicate (ROADMAP 3's 2-D
                              mesh raises the stakes)
  KTPU010 recompile-guard     warm cycles must not re-trace or re-lower the
                              cached kernels — a silent recompile erases
                              PR 5's 4.2x warm-cycle win
  KTPU011 transfer-guard      the warm loop runs clean under
                              jax.transfer_guard("disallow"): no implicit
                              host<->device transfers hiding in the hot path
  KTPU012 hbm-estimate        the compiled memory analysis (where the
                              backend exposes it) reconciles with
                              parallel/mesh.shard_hbm_estimate within
                              HBM_TOLERANCE — the PARITY.md scale ceiling
                              is a checked number, not prose
  KTPU019 subphase-ledger     the device cost observatory's join
                              (analysis/costmodel.py): every heavy eqn of
                              every traced route is owned by a declared
                              named-scope sub-phase (ops/scopes.py —
                              unannotated kernels are findings, fail
                              closed like KTPU013), and on routes carrying
                              a measured profile table the analytic
                              round-loop share reconciles with the
                              measured one within SUBPHASE_TOLERANCE

Rules operate on devicecheck.RouteTrace objects (fixture tests build small
synthetic traces with RouteTrace.from_callable), return engine.Finding
lists, and ride the same fingerprint/baseline/exit contract as the AST
pass.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Finding

# dtypes that may never appear in a placement kernel (f64 promotion breaks
# the cross-backend bit-identity contract; complex is nonsense here)
_FORBIDDEN_DTYPES = ("float64", "complex64", "complex128")
# float dtypes too narrow to carry the integer lattice exactly (int -> f32
# is exact below 2^24, the documented invariant; int -> bf16/f16 is not)
_NARROW_FLOATS = ("bfloat16", "float16")
# additive-reduction primitives: their OUTPUT dtype is the accumulator.
# bf16 STORAGE is legal (ops/bitplane.py — KTPU_SCORE_DTYPE), but every
# sum/matmul/prefix-sum must accumulate in f32 — a narrow-float output on
# one of these is silent precision loss, not storage compression.  max/min
# reductions are exact in any width and stay unflagged.
_ADDITIVE_REDUCE_PRIMS = ("reduce_sum", "dot_general", "cumsum")

# collective primitives whose cross-shard ORDER is the deadlock surface
COLLECTIVE_PRIMS = (
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "reduce_scatter", "psum_scatter", "pgather", "all_gather_invariant",
)

# KTPU012: measured-per-shard bytes may exceed the analytic estimate by at
# most this factor before the PARITY.md ceiling is declared prose (stated
# tolerance — the estimate models dominant blocks, not every XLA temp)
HBM_TOLERANCE = 4.0


class DeviceRule:
    """Base: subclasses set rule_id/title and implement check(traces).

    check receives the FULL trace list (KTPU009 compares traces of one
    route group pairwise); single-trace rules iterate it."""

    rule_id = "KTPU000"
    title = ""

    def check(self, traces: Sequence) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def _finding(trace, rule_id: str, message: str, detail: str = "") -> Finding:
    """A device finding anchored at the route, not a source line: the
    fingerprint is rule | route file | route name | detail, so baselines
    survive kernel edits that do not change the violated property."""
    return Finding(
        rule=rule_id, message=message, file=trace.file, line=0,
        func=trace.name, snippet=detail or trace.name,
    )


class DtypeFlowRule(DeviceRule):
    """KTPU007 — walk every eqn output dtype through the jaxpr (sub-jaxprs
    included): no f64/complex anywhere, no integer->{bf16,f16} narrowing,
    no f32->f64 widening, and the kernel outputs the route declares integer
    (assignment, node_used, commit ordinals) stay integer dtypes.

    bf16 LEGALIZATION (the packed data plane): bf16 values flowing through
    elementwise/select/gather ops are LEGAL — that is the storage half of
    the bf16 score path (ops/bitplane.py).  What stays a finding is (a) an
    integer-lattice value narrowed into bf16/f16, and (b) an ADDITIVE
    reduction (sum / dot_general / cumsum) whose accumulator dtype is
    bf16/f16 — the f32-accumulation rule (PARITY.md — packed-plane
    invariants) enforced mechanically."""

    rule_id = "KTPU007"
    title = "dtype-flow: no f64 promotion; integer tie-break lattice exact"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if t.jaxpr is None:
                continue
            seen: Set[str] = set()
            for eqn, aval in _iter_eqn_avals(t.jaxpr.jaxpr):
                name = getattr(getattr(aval, "dtype", None), "name", "")
                if name in _FORBIDDEN_DTYPES:
                    key = f"{eqn.primitive.name}->{name}"
                    if key not in seen:
                        seen.add(key)
                        findings.append(_finding(
                            t, self.rule_id,
                            f"{name} value produced by `{eqn.primitive.name}`"
                            " — f64/complex promotion breaks cross-backend "
                            "bit-identity",
                            key,
                        ))
                if eqn.primitive.name in _ADDITIVE_REDUCE_PRIMS \
                        and name in _NARROW_FLOATS:
                    key = f"{eqn.primitive.name}-acc->{name}"
                    if key not in seen:
                        seen.add(key)
                        findings.append(_finding(
                            t, self.rule_id,
                            f"additive reduction `{eqn.primitive.name}` "
                            f"accumulates in {name} — bf16 is a STORAGE "
                            "dtype; sums/matmuls must accumulate in f32 "
                            "(upcast before reducing)",
                            key,
                        ))
                if eqn.primitive.name == "convert_element_type":
                    src = getattr(
                        getattr(eqn.invars[0], "aval", None), "dtype", None
                    )
                    if src is None:
                        continue
                    src_name = getattr(src, "name", "")
                    if src_name.startswith(("int", "uint", "bool")) \
                            and name in _NARROW_FLOATS:
                        key = f"{src_name}->{name}"
                        if key not in seen:
                            seen.add(key)
                            findings.append(_finding(
                                t, self.rule_id,
                                f"integer lattice narrowed {src_name} -> "
                                f"{name} — tie-breaks/usage counts must "
                                "stay exact (int or f32 below 2^24)",
                                key,
                            ))
            for i in t.integer_out_indices:
                if i >= len(t.out_avals):
                    continue
                name = getattr(
                    getattr(t.out_avals[i], "dtype", None), "name", ""
                )
                if not name.startswith(("int", "uint", "bool")):
                    findings.append(_finding(
                        t, self.rule_id,
                        f"kernel output {i} (declared integer-exact) has "
                        f"dtype {name}",
                        f"out{i}:{name}",
                    ))
        return findings


class DonationHonoredRule(DeviceRule):
    """KTPU008 — routes declaring donation must show it in the lowering:

    * single-device: the node_used->used_final aliasing class must be
      realized — the used output is backed by a donated input buffer of the
      same shape/dtype (`tf.aliasing_output` on some donated argument
      pointing at the used output).  jax aliases ANY shape-matching donated
      leaf, so the check is output-side: the big persistent [N, R] buffer
      must not be a fresh allocation.
    * mesh: the sharded input node_used and the (replicated or resharded)
      used output have different per-device shapes, so an alias is not
      always expressible — the lowering must still carry at least one
      aliasing/donor mark (donation freeing [P, Nl] inputs early is the
      point at scale); zero marks means the backend dropped donation
      silently."""

    rule_id = "KTPU008"
    title = "donation-honored: donate_argnums survive to input_output_aliases"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if not t.donate or t.lowered_text is None:
                continue
            aliased_outs = {out for (_a, out) in t.aliased}
            if t.n_shards == 1:
                if t.alias_required_out is not None \
                        and t.alias_required_out not in aliased_outs:
                    findings.append(_finding(
                        t, self.rule_id,
                        "declared donation did not alias the used-state "
                        f"output (index {t.alias_required_out}) — the "
                        "compiler dropped it; peak HBM doubles silently",
                        f"missing-alias-out{t.alias_required_out}",
                    ))
            elif not t.aliased and not t.donor_args:
                findings.append(_finding(
                    t, self.rule_id,
                    "declared donation left no input_output_aliases or "
                    "buffer-donor marks in the sharded lowering — donation "
                    "was dropped end to end",
                    "no-aliases-no-donors",
                ))
        return findings


class CollectiveSequenceRule(DeviceRule):
    """KTPU009 — mesh routes: (a) the traced program must actually contain
    collectives (a sharded route with none is a routing bug: shards are
    deciding independently); (b) no `cond` whose branches carry different
    collective subsequences (the first shard-divergent predicate deadlocks
    the mesh); (c) every trace of the same (kind, n_shards) group — donate
    on/off — must carry the IDENTICAL ordered sequence (a sequence that
    moves under a donation flag is trace-order nondeterminism).  Groups key
    on the MESH SHAPE, not just the device count: a 1-D mesh8 and a 2-D
    2x4 mesh both hold 8 devices but legitimately lower different sequences
    (the 2-D route prepends the pod-axis entry gathers)."""

    rule_id = "KTPU009"
    title = "collective-sequence: identical ordered collectives per shard"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        groups: Dict[Tuple, List] = {}
        for t in traces:
            if t.n_shards <= 1 or t.jaxpr is None:
                continue
            if not t.collectives:
                findings.append(_finding(
                    t, self.rule_id,
                    "sharded route lowered to ZERO collectives — shards "
                    "cannot be agreeing on placements",
                    "no-collectives",
                ))
            for desc in t.cond_divergences:
                findings.append(_finding(
                    t, self.rule_id,
                    "cond branches carry different collective sequences "
                    f"({desc}) — a shard-divergent predicate deadlocks "
                    "the mesh",
                    f"cond:{desc}",
                ))
            shape = tuple(sorted(getattr(t, "mesh_axes", {}).items())) \
                or (("n_shards", t.n_shards),)
            groups.setdefault((t.kind, shape), []).append(t)
        for (kind, shape), grp in groups.items():
            seqs = {tuple(t.collectives) for t in grp}
            if len(seqs) > 1:
                tag = "x".join(str(v) for _k, v in shape)
                findings.append(_finding(
                    grp[0], self.rule_id,
                    f"route group ({kind}, mesh {dict(shape)}) traced "
                    f"{len(seqs)} distinct collective sequences across "
                    "donate variants — the program order is not a pure "
                    "function of the route",
                    f"group-divergence:{kind}:{tag}",
                ))
        return findings


class RecompileGuardRule(DeviceRule):
    """KTPU010 — the warm loop (two synthetic warm deltas after the cold
    cycle) must ride the jit cache: zero kernel re-traces (TRACE_COUNTS),
    zero cache-entry growth, and the lowering of the warm step must be
    byte-stable across deltas (an unstable lowering means some host value
    is leaking into the cache key — the next shape bump recompiles)."""

    rule_id = "KTPU010"
    title = "recompile-guard: warm deltas never re-trace the cached kernels"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            w = t.warm
            if not w:
                continue
            if w.get("retraces", 0) > 0 or w.get("cache_growth", 0) > 0:
                findings.append(_finding(
                    t, self.rule_id,
                    f"warm cycle re-traced the kernel "
                    f"(retraces={w.get('retraces', 0)}, new cache entries="
                    f"{w.get('cache_growth', 0)}) — a silent recompile "
                    "erases the 4.2x incremental warm-cycle win",
                    "warm-retrace",
                ))
            if w.get("lowered_stable") is False:
                findings.append(_finding(
                    t, self.rule_id,
                    "lowering is not byte-stable across two warm deltas — "
                    "a host value is leaking into the cache key",
                    "unstable-lowering",
                ))
        return findings


class TransferGuardRule(DeviceRule):
    """KTPU011 — the warm loop (hoist ensure + kernel step on explicitly
    placed buffers) ran under jax.transfer_guard_host_to_device("disallow")
    + device_to_device("disallow"); any implicit transfer raised and was
    captured into the trace."""

    rule_id = "KTPU011"
    title = "transfer-guard: warm loop clean under transfer_guard(disallow)"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if t.transfer_violation:
                findings.append(_finding(
                    t, self.rule_id,
                    "implicit host<->device transfer in the warm loop: "
                    f"{t.transfer_violation}",
                    "transfer-violation",
                ))
        return findings


class HbmEstimateRule(DeviceRule):
    """KTPU012 — compiled memory analysis vs the analytic per-shard budget
    (parallel/mesh.shard_hbm_estimate): measured per-shard bytes
    (argument + output + temp + alias) must stay within HBM_TOLERANCE x
    the estimate.  Backends that expose no memory analysis are recorded on
    the route (devicecheck marks memory=None), never silently passed as
    reconciled."""

    rule_id = "KTPU012"
    title = "hbm-estimate: compiled memory reconciles with the PARITY budget"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if t.memory is None or t.est is None:
                continue
            measured = sum(
                t.memory.get(k, 0) for k in
                ("argument_bytes", "output_bytes", "temp_bytes",
                 "alias_bytes")
            )
            per_shard = measured / max(1, t.n_shards)
            budget = t.est.get("total", 0)
            if budget and per_shard > HBM_TOLERANCE * budget:
                findings.append(_finding(
                    t, self.rule_id,
                    f"compiled per-shard memory {int(per_shard)} B exceeds "
                    f"{HBM_TOLERANCE}x the analytic budget {int(budget)} B "
                    "— the PARITY.md scale ceiling no longer holds",
                    f"hbm:{int(per_shard)}>{HBM_TOLERANCE}x{int(budget)}",
                ))
        return findings


def _sub_jaxprs(eqn):
    """Every Jaxpr nested in an eqn's params (pjit/scan/while/cond/
    shard_map/custom_* all stash theirs differently)."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns"):
                yield inner


def _iter_eqn_avals(jaxpr):
    """(eqn, outvar aval) pairs in program order, depth-first through
    sub-jaxprs at the point of their eqn."""
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            aval = getattr(ov, "aval", None)
            if aval is not None:
                yield eqn, aval
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqn_avals(sub)


def collective_walk(jaxpr) -> Tuple[List[str], List[str]]:
    """(ordered collective primitive names, cond-divergence descriptors)
    for a jaxpr — depth-first, so the order is the canonical program order
    every shard executes.  A `cond` contributes its FIRST branch's
    subsequence to the main order (branches are required identical; the
    divergence list reports when they are not)."""
    seq: List[str] = []
    divergences: List[str] = []

    def walk(jx) -> List[str]:
        out: List[str] = []
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "cond":
                branches = [
                    walk(getattr(b, "jaxpr", b))
                    for b in eqn.params.get("branches", ())
                ]
                if branches:
                    if any(b != branches[0] for b in branches[1:]):
                        divergences.append(
                            "/".join(",".join(b) or "-" for b in branches)
                        )
                    out.extend(branches[0])
                continue
            if name in COLLECTIVE_PRIMS:
                out.append(name)
            for sub in _sub_jaxprs(eqn):
                out.extend(walk(sub))
        return out

    seq = walk(jaxpr)
    return seq, divergences


def collective_bytes(jaxpr) -> List[Tuple[str, int]]:
    """Ordered (collective primitive, output bytes) pairs for a jaxpr —
    the measured side of the KTPU017 comm reconciliation
    (analysis/shardcheck.py).  Depth-first in canonical program order, one
    entry per collective EQN (static program bytes: a collective inside a
    scan/while body counts once, matching shard_comm_estimate's
    definition); bytes are the eqn's summed output aval sizes — the
    traffic each shard stitches at that point."""
    out: List[Tuple[str, int]] = []

    def eqn_bytes(eqn) -> int:
        total = 0
        for ov in eqn.outvars:
            aval = getattr(ov, "aval", None)
            size = getattr(aval, "size", None)
            dtype = getattr(aval, "dtype", None)
            if size is not None and dtype is not None:
                total += int(size) * int(dtype.itemsize)
        return total

    def walk(jx) -> None:
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "cond":
                # branches are required identical (KTPU009): count the
                # FIRST branch's subsequence, same rule as collective_walk
                branches = eqn.params.get("branches", ())
                if branches:
                    walk(getattr(branches[0], "jaxpr", branches[0]))
                continue
            if name in COLLECTIVE_PRIMS:
                out.append((name, eqn_bytes(eqn)))
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return out


class SubphaseLedgerRule(DeviceRule):
    """KTPU019 — the device cost observatory's gate (analysis/costmodel.py):

    * COVERAGE (fail closed, the KTPU013 shape): every leaf eqn carrying
      >= costmodel.HEAVY_FRACTION of a traced route's modeled time must be
      owned by a declared named-scope sub-phase (ops/scopes.py).  A heavy
      unowned eqn is a kernel region the observatory cannot attribute —
      exactly the blindness this plane exists to remove.
    * RECONCILIATION: a trace carrying a measured sub-phase table
      (`measured_subphases`, stamped by bench/profiling.py fixtures and
      profiled runs) must agree with the analytic ledger on the round-loop
      rollup share within costmodel.SUBPHASE_TOLERANCE.
    """

    rule_id = "KTPU019"
    title = "subphase-ledger: heavy eqns owned by a sub-phase; analytic vs " \
            "measured round-loop share reconciles"

    def check(self, traces: Sequence) -> List[Finding]:
        from .costmodel import reconcile, route_ledger

        findings: List[Finding] = []
        for t in traces:
            ledger = getattr(t, "cost", None) or route_ledger(t)
            if ledger is None:
                continue
            for h in ledger["heavy_unowned"]:
                findings.append(_finding(
                    t, self.rule_id,
                    f"heavy eqn outside every declared sub-phase scope: "
                    f"{h['eqn']} carries {h['fraction']:.1%} of the route's "
                    "modeled time — annotate it (ops/scopes.py) or the "
                    "observatory under-attributes the kernel",
                    f"unowned:{h['eqn']}",
                ))
            measured = getattr(t, "measured_subphases", None)
            if measured:
                rec = reconcile(
                    ledger["round_loop_fraction"],
                    measured.get("round_loop_fraction", 0.0),
                )
                if not rec["ok"]:
                    findings.append(_finding(
                        t, self.rule_id,
                        "analytic round-loop share "
                        f"{rec['analytic']:.2f} and measured share "
                        f"{rec['measured']:.2f} diverge by "
                        f"{rec['ratio']:.1f}x (> {rec['tolerance']}x) — "
                        "the cost model and the profile disagree about "
                        "where the kernel's time goes",
                        "reconcile:round_loop",
                    ))
        return findings


ALL_DEVICE_RULES = [
    DtypeFlowRule,
    DonationHonoredRule,
    CollectiveSequenceRule,
    RecompileGuardRule,
    TransferGuardRule,
    HbmEstimateRule,
    SubphaseLedgerRule,
]

DEVICE_RULE_IDS = tuple(r.rule_id for r in ALL_DEVICE_RULES)
