"""ktpu-verify shard pass — KTPU014..KTPU018: the sharding-flow gates.

PR 4 sharded the node axis; ROADMAP 3 (2-D pods x nodes mesh, 500k x 100k)
is blocked on sharding plumbing nobody could *check*.  This pass makes the
declarative rule table (parallel/partition_rules.py) enforceable: one AST
rule guarantees the table is the only spec authority, and four trace rules
— riding the SAME twelve-route tracer as the device pass
(analysis/devicecheck.py — collect_traces) and the same
fingerprint/baseline/0-1-2 exit contract — prove every compiled program
obeys what the table declares:

  KTPU014 rule-table-resolution  any NamedSharding / PartitionSpec literal
                                 or device_put(..., sharding=) outside
                                 parallel/partition_rules.py is a finding —
                                 the KTPU003-style "one blessed module"
                                 rule for placement truth
  KTPU015 replicated-giant       a resident buffer whose dims scale with
                                 P, N, or U x N left fully replicated above
                                 an analytic byte threshold (at the
                                 ROADMAP-3 target dims) — today's
                                 replicated pod-axis buffers become tracked
                                 findings with REQUIRED-reason baselines
                                 the 2-D mesh PR burns down, not invisible
                                 debt
  KTPU016 axis-consistency       every PartitionSpec axis name exists in
                                 the mesh; node-scaling dims map to the
                                 node axis (and only them); sharded dims
                                 divide the axis size after padding
  KTPU017 comm-reconciliation    per-route collective bytes measured from
                                 the captured jaxpr reconcile within
                                 COMM_TOLERANCE with the analytic
                                 parallel/mesh.shard_comm_estimate — an
                                 accidental extra all-gather per warm
                                 cycle becomes exit 1
  KTPU018 out-sharding drift     the compiled outputs' shardings match the
                                 table's declared out.* rows — a compiler
                                 decision to replicate a sharded output
                                 cannot silently pass

Entry points: run_shard_pass() (CLI `python -m kubernetes_tpu.analysis
--shard` / `--rules KTPU014,...`, `bench.harness --verify-shard` /
KTPU_VERIFY_SHARD=1); the rules operate on devicecheck.RouteTrace objects
(fixture tests build synthetic ones).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Baseline, Finding, ModuleInfo, Report, Rule, call_name

# anchor for table-derived findings: the rule table IS the fix site
TABLE_FILE = "kubernetes_tpu/parallel/partition_rules.py"

# KTPU015: a replicated resident buffer above this many analytic bytes at
# the ROADMAP-3 target dims (partition_rules.SCALE_DIMS — 500k pods x 100k
# nodes) is a tracked scaling debt.  1 MiB: every multi-byte pod-axis
# vector crosses it at 500k pods; vocabulary-axis tables never do.
REPLICATED_GIANT_BYTES = 1 << 20

# KTPU017: measured static-program collective bytes may exceed the
# analytic shard_comm_estimate by at most this factor (stated tolerance —
# the estimate models the dominant stitches, not every scalar pmax; same
# contract as jaxrules.HBM_TOLERANCE for KTPU012).
COMM_TOLERANCE = 4.0


# --------------------------------------------------------------------------
# KTPU014 — AST: the rule table is the ONLY spec authority
# --------------------------------------------------------------------------


class ShardSpecLiteralRule(Rule):
    """KTPU014 — placement truth lives in parallel/partition_rules.py and
    nowhere else: flags (a) any ``NamedSharding(...)`` or
    ``PartitionSpec(...)`` construction (through any import alias) outside
    the blessed module, (b) any ``device_put(..., sharding=...)`` keyword
    placement outside it.  Call sites receive specs/shardings from the
    table's resolvers (spec_for / sharding_for / clusterarrays_shardings);
    a literal anywhere else is a second spec authority waiting to drift —
    the KTPU003 "audited module" pattern applied to sharding."""

    rule_id = "KTPU014"
    title = "rule-table-resolution: PartitionSpec literals only in the table"

    BLESSED = {TABLE_FILE}
    _SPEC_NAMES = {"NamedSharding", "PartitionSpec", "GSPMDSharding",
                   "PositionalSharding"}

    def _aliases(self, mod: ModuleInfo) -> Set[str]:
        """Module-local names bound to jax sharding constructors via
        ``from jax.sharding import PartitionSpec as P`` style imports."""
        out: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and "sharding" in node.module:
                for alias in node.names:
                    if alias.name in self._SPEC_NAMES:
                        out.add(alias.asname or alias.name)
        return out

    def check(self, mod: ModuleInfo) -> List[Finding]:
        if mod.relpath in self.BLESSED:
            return []
        aliases = self._aliases(mod)
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in self._SPEC_NAMES or name in aliases:
                findings.append(mod.finding(
                    self.rule_id, node,
                    f"{name}(...) literal outside the partition rule table "
                    "— resolve the spec through parallel/partition_rules "
                    "(spec_for/sharding_for); one table, one truth",
                ))
            elif name == "device_put" and any(
                    kw.arg == "sharding" for kw in node.keywords):
                findings.append(mod.finding(
                    self.rule_id, node,
                    "device_put(..., sharding=) outside the partition rule "
                    "table — pass a table-resolved sharding positionally "
                    "from sharding_for/clusterarrays_shardings",
                ))
        return findings


# --------------------------------------------------------------------------
# trace rules (RouteTrace-driven, devicecheck.collect_traces)
# --------------------------------------------------------------------------


class ShardTraceRule:
    """Base for the trace-driven shard rules: check(traces) over the full
    RouteTrace list, same shape as jaxrules.DeviceRule."""

    rule_id = "KTPU000"
    title = ""

    def check(self, traces: Sequence) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def _route_finding(trace, rule_id: str, message: str, detail: str) -> Finding:
    """Route-anchored finding (fingerprint = rule | route file | route name
    | detail — survives kernel edits that keep the violated property)."""
    return Finding(
        rule=rule_id, message=message, file=trace.file, line=0,
        func=trace.name, snippet=detail,
    )


def _field_finding(rule_id: str, qualname: str, message: str,
                   detail: str) -> Finding:
    """Field-anchored finding: keyed to the rule table row, NOT the route —
    one replicated pod-axis buffer is one piece of debt however many routes
    carry it, so one baseline entry covers it."""
    return Finding(
        rule=rule_id, message=message, file=TABLE_FILE, line=0,
        func=qualname, snippet=detail,
    )


def _scaled_bytes(entry: Dict) -> int:
    """Analytic bytes of one shard-report entry at the ROADMAP-3 target
    dims: scale symbols (P/N/U) at SCALE_DIMS, vocabulary symbols at their
    CANONICAL_DIMS size (workload-independent, so the finding set — and
    therefore the committed baseline — never moves with the traced
    workload), unknown symbols at 1."""
    from ..parallel.partition_rules import CANONICAL_DIMS, SCALE_DIMS

    total = int(entry["itemsize"])
    for sym in entry["dims"]:
        total *= SCALE_DIMS.get(sym) or CANONICAL_DIMS.get(sym, 1)
    return total


class ReplicatedGiantRule(ShardTraceRule):
    """KTPU015 — the exact ROADMAP-3a gap, as a gate: any resident buffer
    (arr.* / inc.*) carried FULLY REPLICATED on EVERY multi-shard route
    whose dims scale with P, N, or U, above REPLICATED_GIANT_BYTES at the
    target dims.  TWO-PASS across the route matrix: a field the 2-D
    pods x nodes routes shard is paid-down debt even though the 1-D node
    mesh (correctly, by stripping) still replicates it — only a field no
    mesh shape anywhere shards is a finding.  Deduped per field;
    legitimately-replicated-for-now buffers carry REQUIRED-reason baseline
    entries naming the follow-up, so the debt is enumerated, visible, and
    burnable."""

    rule_id = "KTPU015"
    title = "replicated-giant: no P/N/U-scaling buffer left fully replicated"

    def check(self, traces: Sequence) -> List[Finding]:
        from ..parallel.partition_rules import SCALE_SYMBOLS

        # pass 1: which qualnames does ANY multi-shard route shard?
        sharded_somewhere: Set[str] = set()
        candidates: Dict[str, Dict] = {}
        for t in traces:
            if t.n_shards <= 1:
                continue
            axes = set(getattr(t, "mesh_axes", {}) or ())
            for entry in t.shard_fields:
                q = entry["qualname"]
                spec = tuple(entry["spec"])
                if any(ax is not None and (not axes or ax in axes)
                       for ax in spec):
                    sharded_somewhere.add(q)
                else:
                    candidates.setdefault(q, entry)
        # pass 2: flag only replicated-EVERYWHERE scaling giants
        findings: List[Finding] = []
        for q, entry in sorted(candidates.items()):
            if q in sharded_somewhere:
                continue
            scaling = [s for s in entry["dims"] if s in SCALE_SYMBOLS]
            if not scaling:
                continue  # vocabulary-axis table, bounded by design
            size = _scaled_bytes(entry)
            if size <= REPLICATED_GIANT_BYTES:
                continue
            findings.append(_field_finding(
                self.rule_id, q,
                f"{q} ({'x'.join(entry['dims'])}) is fully replicated "
                f"on every mesh shape at ~{size // (1 << 20)} MiB per "
                "shard (ROADMAP-3 target dims) — shard it or baseline it "
                "with the follow-up that will",
                f"replicated-giant:{q}:{'x'.join(entry['dims'])}",
            ))
        return findings


class AxisConsistencyRule(ShardTraceRule):
    """KTPU016 — the spec/mesh/shape contract, per traced route and PER
    MESH AXIS (the axis universe is partition_rules.AXIS_SCALE — nodes->N,
    pods->P — so the 2-D mesh's pod rows get the same three gates the node
    rows always had): (a) every axis a spec names exists in the mesh;
    (b) each mesh axis shards exactly its scaling dimension (a spec placing
    "nodes" on a vocabulary dim — or "pods" on a node dim — is a silent
    wrong-axis reshard); (c) the sharded dimension divides the axis size
    (padding must have happened before placement)."""

    rule_id = "KTPU016"
    title = "axis-consistency: spec axes exist, map to their dim, and divide"

    def check(self, traces: Sequence) -> List[Finding]:
        from ..parallel.partition_rules import AXIS_SCALE

        findings: List[Finding] = []
        seen: Set[str] = set()

        def once(key: str) -> bool:
            if key in seen:
                return False
            seen.add(key)
            return True

        for t in traces:
            if t.n_shards <= 1 or not t.mesh_axes:
                continue
            for entry in t.shard_fields:
                q = entry["qualname"]
                spec = tuple(entry["spec"])
                shape = tuple(entry["shape"])
                dims = tuple(entry["dims"])
                for axis in spec:
                    if axis is None:
                        continue
                    if axis not in t.mesh_axes and once(f"axis:{q}:{axis}"):
                        findings.append(_route_finding(
                            t, self.rule_id,
                            f"{q}: spec axis {axis!r} does not exist in the "
                            f"mesh (axes: {sorted(t.mesh_axes)}) — the "
                            "placement silently replicates",
                            f"unknown-axis:{q}:{axis}",
                        ))
                for mesh_axis, scale_sym in AXIS_SCALE.items():
                    if mesh_axis not in spec:
                        continue
                    k = spec.index(mesh_axis)
                    if k < len(dims) and dims[k] != scale_sym \
                            and once(f"map:{q}:{mesh_axis}"):
                        findings.append(_route_finding(
                            t, self.rule_id,
                            f"{q}: the {mesh_axis} axis shards dim {k} "
                            f"({dims[k]!r}), not the {scale_sym}-scaling "
                            "dimension — wrong-axis sharding",
                            f"{mesh_axis}-axis-mismap:{q}:{k}",
                        ))
                    n_ax = t.mesh_axes.get(mesh_axis, t.n_shards)
                    if k < len(shape) and shape[k] % max(1, n_ax) \
                            and once(f"div:{q}:{mesh_axis}"):
                        findings.append(_route_finding(
                            t, self.rule_id,
                            f"{q}: sharded dim {k} (size {shape[k]}) does "
                            f"not divide the {mesh_axis} axis size {n_ax} "
                            "— the route ran unpadded",
                            f"indivisible:{q}:{shape[k]}%{n_ax}",
                        ))
        return findings


class CommReconcileRule(ShardTraceRule):
    """KTPU017 — collective traffic is a checked number: the static-program
    collective bytes measured from the captured jaxpr
    (jaxrules.collective_bytes — one entry per collective eqn at its
    output size) must stay within COMM_TOLERANCE x the analytic
    parallel/mesh.shard_comm_estimate for the route.  An accidental extra
    all-gather of the [C, N] score block roughly doubles the measured side
    and breaches the budget — exit 1, not a silent ICI tax."""

    rule_id = "KTPU017"
    title = "comm-reconciliation: collective bytes within the analytic budget"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if t.n_shards <= 1 or t.comm_est is None:
                continue
            budget = int(t.comm_est.get("total", 0))
            measured = int(sum(b for _p, b in t.collective_bytes))
            if budget and measured > COMM_TOLERANCE * budget:
                top = sorted(t.collective_bytes, key=lambda pb: -pb[1])[:3]
                findings.append(_route_finding(
                    t, self.rule_id,
                    f"measured collective bytes {measured} exceed "
                    f"{COMM_TOLERANCE}x the analytic budget {budget} "
                    f"(largest: {', '.join(f'{p}={b}' for p, b in top)}) — "
                    "an unbudgeted collective entered the program",
                    f"comm:{measured}>{COMM_TOLERANCE}x{budget}",
                ))
        return findings


class OutShardingDriftRule(ShardTraceRule):
    """KTPU018 — the compiled executable's output shardings must realize
    the table's out.* rows: GSPMD is free to re-layout internals, but an
    output the table declares node-sharded coming back replicated (or vice
    versa) changes every consumer's transfer profile without failing a
    single test.  Routes whose backend exposes no output shardings are
    recorded unreconciled on the route report — never silently passed."""

    rule_id = "KTPU018"
    title = "out-sharding: compiled outputs match the declared table rows"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if not t.out_sharding_report:
                continue
            for i, entry in enumerate(t.out_sharding_report):
                if entry.get("equivalent") is False:
                    findings.append(_route_finding(
                        t, self.rule_id,
                        f"compiled output {i} drifted from the declared "
                        f"{entry['declared']} spec (compiled: "
                        f"{entry['compiled']}) — the compiler overrode the "
                        "table",
                        f"out-drift:{i}:{entry['declared']}",
                    ))
        return findings


ALL_SHARD_TRACE_RULES = [
    ReplicatedGiantRule,
    AxisConsistencyRule,
    CommReconcileRule,
    OutShardingDriftRule,
]

SHARD_RULE_IDS = ("KTPU014",) + tuple(r.rule_id for r in ALL_SHARD_TRACE_RULES)


# --------------------------------------------------------------------------
# the pass
# --------------------------------------------------------------------------


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_shard_pass(rule_ids: Optional[Sequence[str]] = None,
                   baseline: Optional[Baseline] = None,
                   mesh_size: int = 8,
                   pretraced: Optional[Tuple[list, List[str]]] = None,
                   root: Optional[str] = None) -> Report:
    """Run the (selected) shard rules: the KTPU014 AST scan over the
    package plus the KTPU015..018 trace rules over the twelve production
    routes (devicecheck.collect_traces — shared with the device pass via
    `pretraced`, so `--device --shard` traces once).  Same report/
    fingerprint/baseline/exit contract as the other passes; a route that
    fails to trace is an ERROR (exit 2), never a silent skip."""
    from .engine import apply_baseline, load_modules

    want = (
        {r.upper() for r in rule_ids} if rule_ids is not None
        else set(SHARD_RULE_IDS)
    )
    selected = [r for r in SHARD_RULE_IDS if r in want]
    report = Report(rules=selected)

    if "KTPU014" in want:
        mods, load_errors = load_modules(root or _package_root())
        report.errors.extend(load_errors)
        report.files_scanned = len(mods)
        rule = ShardSpecLiteralRule()
        for mod in mods:
            try:
                report.findings.extend(rule.check(mod))
            except Exception as e:  # a rule bug must not pass as "clean"
                report.errors.append(
                    f"{mod.relpath}: rule KTPU014 crashed: "
                    f"{type(e).__name__}: {e}")

    trace_rules = [cls() for cls in ALL_SHARD_TRACE_RULES
                   if cls.rule_id in want]
    if trace_rules:
        if pretraced is not None:
            traces, trace_errors = pretraced
        else:
            from .devicecheck import collect_traces

            traces, trace_errors = collect_traces(mesh_size)
        report.errors.extend(trace_errors)
        n_traced = sum(1 for t in traces if t.status == "traced")
        report.files_scanned = max(report.files_scanned, n_traced)
        for r in trace_rules:
            try:
                report.findings.extend(r.check(traces))
            except Exception as e:
                report.errors.append(
                    f"shard rule {r.rule_id} crashed: "
                    f"{type(e).__name__}: {e}")
        report.device = {
            "routes": [t.to_dict() for t in traces],
            "n_traced": n_traced,
            "n_skipped": sum(1 for t in traces if t.status == "skipped"),
        }
    apply_baseline(report, baseline)
    return report
