"""ktpu-verify — the project's static-analysis plane (ISSUE 8).

The reference gates every PR behind hack/verify-* + golangci-lint; this
package is the reproduction's analog: `python -m kubernetes_tpu.analysis`
enforces the codebase's own invariants (PARITY.md prose rules turned into
rule ids KTPU001..KTPU006 + KTPU013 at the AST layer, KTPU007..KTPU012 at
the jaxpr/compiled-kernel layer — devicecheck.py/jaxrules.py, and
KTPU014..KTPU018 at the sharding layer — shardcheck.py over the
declarative partition rule table in parallel/partition_rules.py, and
KTPU020 at the device-memory layer — memrules.py over the live ledger in
scheduler/memwatch.py), with a baseline-suppression file and the 0/1/2
exit-code contract.  `--device --shard --mem` is the full verify gate
(one shared 12-route trace).

Only the runtime lock-check factories are exported at package level — the
scheduler's hot modules import them at construction time, so this __init__
must stay dependency-free and cheap (engine/rules/lockorder are imported
by the CLI and tests directly).
"""

from . import lockcheck  # noqa: F401
from .lockcheck import (  # noqa: F401
    CheckedLock,
    LockOrderViolation,
    make_lock,
    make_rlock,
)
