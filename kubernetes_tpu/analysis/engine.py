"""ktpu-verify engine — AST rule registry, baseline suppression, reporting.

The reference gates every PR behind `hack/verify-*` + golangci-lint; this
reproduction's equally sharp invariants (PARITY.md: donation-aliasing,
crash-consistency, the snapshot-LIST rule, the cheap-gate contract) lived in
prose until now.  This engine turns them into enforced findings:

  * every rule (`analysis/rules.py` — KTPU001..005, `analysis/lockorder.py`
    — KTPU006) walks the parsed AST of every module in the package
  * a finding is keyed by a LINE-NUMBER-FREE fingerprint
    (rule | file | enclosing function | normalized source line), so
    baselines survive unrelated edits
  * the baseline file suppresses known findings, each with a REQUIRED
    human reason — `--write-baseline` drafts entries, a reviewer fills in
    the why
  * exit-code contract (bench/regression.py style): 0 clean, 1 unbaselined
    findings, 2 unusable (parse failure, bad baseline) — CI gates on it

`python -m kubernetes_tpu.analysis` is the CLI (`analysis/__main__.py`).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass
class Finding:
    rule: str           # KTPU001...
    message: str        # one-line defect statement
    file: str           # package-relative posix path
    line: int           # 1-based (display only — NOT part of the fingerprint)
    func: str           # enclosing function qualname ("" at module level)
    snippet: str        # stripped source line
    baselined: bool = False
    baseline_reason: str = ""

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity: stable across unrelated edits.  Two
        identical offending lines in one function share a fingerprint — one
        baseline entry deliberately covers both."""
        norm = re.sub(r"\s+", " ", self.snippet).strip()
        raw = f"{self.rule}|{self.file}|{self.func}|{norm}"
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        where = f"{self.file}:{self.line}"
        ctx = f" [{self.func}]" if self.func else ""
        tail = f"  (baselined: {self.baseline_reason})" if self.baselined else ""
        return f"{self.rule} {where}{ctx}: {self.message}{tail}\n    {self.snippet}"

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule, "message": self.message, "file": self.file,
            "line": self.line, "func": self.func, "snippet": self.snippet,
            "fingerprint": self.fingerprint, "baselined": self.baselined,
            "baseline_reason": self.baseline_reason,
        }


class ModuleInfo:
    """One parsed module + the node bookkeeping every rule needs: parent
    links and enclosing-function qualnames."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self._parent: Dict[int, ast.AST] = {}
        self._qual: Dict[int, str] = {}
        self._index(self.tree, None, ())

    def _index(self, node: ast.AST, parent: Optional[ast.AST],
               scope: Tuple[str, ...]) -> None:
        if parent is not None:
            self._parent[id(node)] = parent
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            scope = scope + (node.name,)
        self._qual[id(node)] = ".".join(scope)
        for child in ast.iter_child_nodes(node):
            self._index(child, node, scope)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def qualname(self, node: ast.AST) -> str:
        """Qualname of the scope enclosing `node` (class + nested funcs)."""
        return self._qual.get(id(node), "")

    def line_of(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        if 1 <= ln <= len(self.lines):
            return self.lines[ln - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule, message=message, file=self.relpath,
            line=getattr(node, "lineno", 0), func=self.qualname(node),
            snippet=self.line_of(node),
        )


def call_name(call: ast.AST) -> str:
    """Last-segment name of a call's callee: `contextlib.suppress(...)` ->
    'suppress', `jit(...)` -> 'jit', anything else -> ''.  The one shared
    extraction every rule resolves callees through."""
    if not isinstance(call, ast.Call):
        return ""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


class Rule:
    """Base: subclasses set rule_id/title and implement check(mod)."""

    rule_id = "KTPU000"
    title = ""

    def check(self, mod: ModuleInfo) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


class BaselineError(ValueError):
    """Malformed baseline file — the run is unusable (exit 2), never
    silently ungated."""


class Baseline:
    """The suppression file: JSON list of {fingerprint, rule, file, func,
    snippet, reason}.  Matching is by fingerprint; the rest is for humans
    reading the file.  A reason is REQUIRED — a baseline without a why is
    just a muted alarm."""

    def __init__(self, entries: Optional[List[Dict[str, str]]] = None,
                 lenient: bool = False):
        self.entries: List[Dict[str, str]] = list(entries or [])
        self._by_fp: Dict[str, Dict[str, str]] = {}
        for e in self.entries:
            fp = e.get("fingerprint", "")
            reason = (e.get("reason") or "").strip()
            if not fp:
                raise BaselineError(f"baseline entry missing fingerprint: {e}")
            if not reason or reason.upper().startswith("TODO"):
                if lenient:
                    # --write-baseline re-drafting: a prior draft's TODO
                    # entries must not dead-end the tool — they are kept
                    # (still refused by the strict CI load)
                    self._by_fp[fp] = e
                    continue
                raise BaselineError(
                    f"baseline entry {fp} ({e.get('file', '?')}) has no "
                    "reason — every suppression must say why"
                )
            self._by_fp[fp] = e

    @classmethod
    def load(cls, path: str, lenient: bool = False) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            # an unreadable baseline is an UNUSABLE run (exit 2), never a
            # traceback that CI misreads as "findings" (exit 1)
            raise BaselineError(f"unreadable baseline {path}: {e}")
        if not isinstance(doc, dict) or not isinstance(doc.get("findings"), list):
            raise BaselineError(f"baseline {path} must be {{'findings': [...]}}")
        return cls(doc["findings"], lenient=lenient)

    def match(self, f: Finding) -> Optional[str]:
        e = self._by_fp.get(f.fingerprint)
        return e.get("reason", "") if e is not None else None

    def unused(self, findings: List[Finding],
               ran_rules: Optional[List[str]] = None) -> List[Dict[str, str]]:
        """Entries that matched nothing this run — stale suppressions the
        report surfaces so fixed findings get un-baselined.  Entries for
        rules that did NOT run (a --rules subset) are never stale: they
        may still match on a full run."""
        hit = {f.fingerprint for f in findings}
        ran = set(ran_rules) if ran_rules is not None else None
        return [
            e for e in self.entries
            if e["fingerprint"] not in hit
            and (ran is None or e.get("rule", "") in ran or not e.get("rule"))
        ]

    @staticmethod
    def draft(findings: List[Finding]) -> Dict[str, object]:
        """--write-baseline payload: one entry per unbaselined fingerprint
        with reason left as TODO (load() refuses TODOs, so a drafted
        baseline cannot silently pass CI)."""
        seen: Dict[str, Dict[str, str]] = {}
        for f in findings:
            if f.baselined or f.fingerprint in seen:
                continue
            seen[f.fingerprint] = {
                "fingerprint": f.fingerprint, "rule": f.rule, "file": f.file,
                "func": f.func, "snippet": f.snippet,
                "reason": "TODO: justify or fix",
            }
        return {"findings": list(seen.values())}


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)  # parse/IO failures
    files_scanned: int = 0
    rules: List[str] = field(default_factory=list)
    stale_baseline: List[Dict[str, str]] = field(default_factory=list)
    # device-pass block (analysis/devicecheck.py): every traced kernel
    # route with its status — the no-silent-route-skips ledger.  None for
    # AST-only runs.
    device: Optional[Dict[str, object]] = None

    @property
    def unbaselined(self) -> List[Finding]:
        return [f for f in self.findings if not f.baselined]

    @property
    def exit_code(self) -> int:
        """0 clean / 1 new findings / 2 unusable — bench/regression.py's
        contract, so CI wires both gates identically."""
        if self.errors:
            return 2
        return 1 if self.unbaselined else 0

    def to_dict(self) -> Dict[str, object]:
        return {
            "tool": "ktpu-verify",
            "files_scanned": self.files_scanned,
            "rules": self.rules,
            "findings": [f.to_dict() for f in self.findings],
            "n_findings": len(self.findings),
            "n_unbaselined": len(self.unbaselined),
            "errors": self.errors,
            "stale_baseline": self.stale_baseline,
            "exit_code": self.exit_code,
            **({"device": self.device} if self.device is not None else {}),
        }

    def render_text(self) -> str:
        out: List[str] = []
        for f in sorted(self.findings, key=lambda f: (f.rule, f.file, f.line)):
            out.append(f.render())
        for e in self.errors:
            out.append(f"ERROR {e}")
        for e in self.stale_baseline:
            out.append(
                f"STALE baseline entry {e['fingerprint']} "
                f"({e.get('rule', '?')} {e.get('file', '?')}) matched nothing "
                "— remove it"
            )
        if self.device is not None:
            out.append(
                f"device pass: {self.device.get('n_traced', 0)} routes "
                f"traced, {self.device.get('n_skipped', 0)} skipped"
            )
            for r in self.device.get("routes", []):
                if r.get("status") == "skipped":
                    out.append(
                        f"  SKIPPED {r['name']}: {r.get('skip_reason', '?')}")
        nb = len(self.unbaselined)
        out.append(
            f"ktpu-verify: {self.files_scanned} files, "
            f"{len(self.findings)} findings "
            f"({nb} unbaselined, {len(self.findings) - nb} baselined), "
            f"{len(self.errors)} errors -> exit {self.exit_code}"
        )
        return "\n".join(out)


def iter_package_files(root: str) -> List[Tuple[str, str]]:
    """(relpath, abspath) for every .py under `root`, sorted, pycache
    skipped.  relpath is rooted at the package name (kubernetes_tpu/...)."""
    root = os.path.abspath(root)
    base = os.path.basename(root)
    out: List[Tuple[str, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            ap = os.path.join(dirpath, fn)
            rp = os.path.join(base, os.path.relpath(ap, root)).replace(os.sep, "/")
            out.append((rp, ap))
    return out


def load_modules(root: str) -> Tuple[List[ModuleInfo], List[str]]:
    """Parse every module under `root`: (parsed modules, load errors).  An
    unreadable file (I/O, syntax, null bytes, bad encoding) is an error the
    caller reports — the one loader both analyze_package and the
    --lock-graph dump resolve files through."""
    mods: List[ModuleInfo] = []
    errors: List[str] = []
    for relpath, abspath in iter_package_files(root):
        try:
            with open(abspath) as f:
                source = f.read()
            mods.append(ModuleInfo(relpath, source))
        except (OSError, SyntaxError, ValueError) as e:
            # ValueError covers UnicodeDecodeError and ast.parse's
            # null-byte rejection — any unreadable file is exit 2, never
            # a traceback CI misreads as exit 1
            errors.append(f"{relpath}: {type(e).__name__}: {e}")
    return mods, errors


def analyze_source(source: str, relpath: str, rules: List[Rule]) -> List[Finding]:
    """Run `rules` over one source blob — the fixture-test entry point."""
    mod = ModuleInfo(relpath, source)
    findings: List[Finding] = []
    for r in rules:
        findings.extend(r.check(mod))
    return findings


def apply_baseline(report: Report, baseline: Optional[Baseline]) -> Report:
    """Mark baselined findings + compute stale entries — ONE application
    point shared by analyze_package, the device pass, and the CLI's merged
    AST+device report (applying per-pass would double-report staleness)."""
    if baseline is None:
        return report
    for f in report.findings:
        reason = baseline.match(f)
        if reason is not None:
            f.baselined = True
            f.baseline_reason = reason
    report.stale_baseline = baseline.unused(report.findings,
                                            ran_rules=report.rules)
    return report


def analyze_package(root: str, rules: Optional[List[Rule]] = None,
                    baseline: Optional[Baseline] = None,
                    lockorder: bool = True) -> Report:
    """The full pass: parse every module, run the per-module rules, then the
    whole-package lock-order analysis (KTPU006 — skippable via lockorder=False
    so a --rules subset really runs only what it names), then apply the
    baseline."""
    from .lockorder import LockOrderAnalyzer
    from .rules import ALL_RULES

    if rules is None:
        rules = [cls() for cls in ALL_RULES]
    report = Report(rules=[r.rule_id for r in rules]
                    + (["KTPU006"] if lockorder else []))
    mods, load_errors = load_modules(root)
    report.errors.extend(load_errors)
    report.files_scanned = len(mods)
    for mod in mods:
        for r in rules:
            try:
                report.findings.extend(r.check(mod))
            except Exception as e:  # a rule bug must not pass as "clean"
                report.errors.append(
                    f"{mod.relpath}: rule {r.rule_id} crashed: "
                    f"{type(e).__name__}: {e}"
                )
    # whole-package analysis: the lock-order graph needs every class at once
    if lockorder:
        try:
            report.findings.extend(LockOrderAnalyzer(mods).check())
        except Exception as e:
            report.errors.append(
                f"lock-order analysis crashed: {type(e).__name__}: {e}")
    return apply_baseline(report, baseline)
