"""ktpu-verify CLI — `python -m kubernetes_tpu.analysis`.

The project's hack/verify-* analog: runs every KTPU rule over the package
and gates on the baseline.

  python -m kubernetes_tpu.analysis                      # text, exit 0/1/2
  python -m kubernetes_tpu.analysis --format json        # CI artifact
  python -m kubernetes_tpu.analysis --write-baseline     # draft suppressions
  python -m kubernetes_tpu.analysis --lock-graph         # dump KTPU006 graph
  python -m kubernetes_tpu.analysis --device             # + device pass
  python -m kubernetes_tpu.analysis --shard              # + shard pass
  python -m kubernetes_tpu.analysis --mem                # + mem pass
  python -m kubernetes_tpu.analysis --device --shard --mem
                                                         # the full verify
                                                         # gate (one trace)
  python -m kubernetes_tpu.analysis --rules KTPU007,KTPU008,KTPU009,KTPU010,KTPU011,KTPU012
                                                         # device pass only
  python -m kubernetes_tpu.analysis --rules KTPU014,KTPU015,KTPU016,KTPU017,KTPU018
                                                         # shard pass only
  python -m kubernetes_tpu.analysis --rules KTPU020      # mem pass only

Exit-code contract (bench/regression.py's): 0 clean (all findings
baselined), 1 unbaselined findings, 2 unusable (parse failure, malformed
baseline).  The baseline lives at kubernetes_tpu/analysis/baseline.json;
every entry carries a REQUIRED reason (a drafted TODO reason fails the
load, so --write-baseline output cannot silently pass CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def default_root() -> str:
    """The installed kubernetes_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_baseline() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def resolve_root(root: str) -> str:
    """Re-anchor a repo-root --root at the package directory: every
    path-scoped rule (KTPU001 allowlist, KTPU002 exemptions, KTPU003
    donation modules, KTPU004 scope) matches relpaths rooted at
    `kubernetes_tpu/...` — pointing --root at the repo would otherwise
    produce spurious findings AND silently disable those scopes at once.
    Roots not containing the package (rule fixtures) pass through."""
    root = os.path.abspath(root)
    if os.path.basename(root) != "kubernetes_tpu":
        cand = os.path.join(root, "kubernetes_tpu")
        if os.path.isdir(cand):
            return cand
    return root


def run_verify(root: Optional[str] = None, baseline_path: Optional[str] = None,
               device: bool = False, shard: bool = False, mem: bool = False):
    """The shared gate: load the committed baseline and run the full pass —
    the AST rules, plus the DEVICE pass (KTPU007..012, devicecheck.py)
    when `device` is set, plus the SHARD pass (KTPU014..018, shardcheck.py)
    when `shard` is set, plus the MEM pass (KTPU020, memrules.py) when
    `mem` is set — the trace passes share one 12-route trace.  Used by
    this CLI and by `bench.harness --verify[-device|-shard|-mem]`, so
    every exit follows ONE contract.  Raises BaselineError (exit 2) on an
    unusable baseline."""
    from .engine import Baseline, analyze_package, apply_baseline

    baseline = Baseline.load(baseline_path or default_baseline())
    any_trace = device or shard or mem
    report = analyze_package(resolve_root(root or default_root()),
                             baseline=None if any_trace else baseline)
    if any_trace:
        pretraced = None
        if sum((device, shard, mem)) >= 2:
            from .devicecheck import collect_traces

            pretraced = collect_traces()
        if device:
            from .devicecheck import run_device_pass

            dev = run_device_pass(baseline=None, pretraced=pretraced)
            report.findings.extend(dev.findings)
            report.errors.extend(dev.errors)
            report.rules = report.rules + dev.rules
            report.device = dev.device
        if shard:
            from .shardcheck import run_shard_pass

            shd = run_shard_pass(baseline=None, pretraced=pretraced)
            report.findings.extend(shd.findings)
            report.errors.extend(shd.errors)
            report.rules = report.rules + shd.rules
            if shd.device is not None:
                report.device = shd.device
        if mem:
            from .memrules import run_mem_pass

            mm = run_mem_pass(baseline=None, pretraced=pretraced)
            report.findings.extend(mm.findings)
            report.errors.extend(mm.errors)
            report.rules = report.rules + mm.rules
            if mm.device is not None:
                report.device = mm.device
        report.errors = list(dict.fromkeys(report.errors))
        apply_baseline(report, baseline)
    return report


def main(argv=None) -> int:
    from .engine import Baseline, BaselineError, analyze_package, apply_baseline
    from .jaxrules import DEVICE_RULE_IDS
    from .memrules import MEM_RULE_IDS
    from .rules import ALL_RULES
    from .shardcheck import SHARD_RULE_IDS

    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="ktpu-verify: AST invariant analyzer + lock-order checker",
    )
    ap.add_argument("--root", default=default_root(),
                    help="package directory to analyze (default: the "
                         "installed kubernetes_tpu)")
    ap.add_argument("--baseline", default=default_baseline(),
                    help="baseline suppression file (JSON)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--output", default="",
                    help="also write the JSON report to this path")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run (default: all AST "
                         "rules; naming a KTPU007..012 id also runs the "
                         "device pass for it, a KTPU014..018 id the shard "
                         "pass, KTPU020 the mem pass)")
    ap.add_argument("--device", action="store_true",
                    help="also run the device pass (KTPU007..012 — trace "
                         "every production kernel route and check the "
                         "compiled invariants; compiles kernels, takes "
                         "~1 min on the CPU sim)")
    ap.add_argument("--shard", action="store_true",
                    help="also run the shard pass (KTPU014..018 — the "
                         "partition-rule-table authority scan plus the "
                         "replicated-giant / axis-consistency / "
                         "comm-reconciliation / out-sharding gates over "
                         "the traced routes; shares the route traces with "
                         "--device, so --device --shard traces once)")
    ap.add_argument("--mem", action="store_true",
                    help="also run the mem pass (KTPU020 — the HBM "
                         "telemetry plane's measured-vs-analytic "
                         "reconciliation over the traced routes: live "
                         "peak within tolerance of shard_hbm_estimate, "
                         "resident census == the FIELD_DIMS size model, "
                         "leak sentinel clean; analysis/memrules.py; "
                         "shares the route traces with --device/--shard, "
                         "so --device --shard --mem traces once)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write a draft baseline covering every unbaselined "
                         "finding (reasons left TODO — fill them in)")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the static lock-order graph and exit")
    ap.add_argument("--flight", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="read a decision flight-recorder dump post-mortem "
                         "(scheduler/flightrecorder.py — written to the "
                         "checkpoint dir when a kill.* site or a wave "
                         "recovery fires) and exit; PATH defaults to "
                         "$KTPU_CHECKPOINT_DIR/flight.json.  Exit 0 "
                         "parseable, 2 missing/corrupt")
    args = ap.parse_args(argv)
    if args.write_baseline and args.no_baseline:
        # --no-baseline makes `baseline` None, so the draft merge below
        # would REPLACE the committed file, silently discarding every
        # human-written suppression reason — refuse the combination
        ap.error("--write-baseline cannot combine with --no-baseline "
                 "(the draft merges into the existing baseline)")

    args.root = resolve_root(args.root)

    if args.lock_graph:
        return _dump_lock_graph(args.root)
    if args.flight is not None:
        return _dump_flight(args.flight)

    rules = [cls() for cls in ALL_RULES]
    lockorder = True
    device_ids = list(DEVICE_RULE_IDS) if args.device else []
    shard_ids = list(SHARD_RULE_IDS) if args.shard else []
    mem_ids = list(MEM_RULE_IDS) if args.mem else []
    if args.rules:
        want = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
        known = ({r.rule_id for r in rules} | {"KTPU006"}
                 | set(DEVICE_RULE_IDS) | set(SHARD_RULE_IDS)
                 | set(MEM_RULE_IDS))
        unknown = sorted(want - known)
        if unknown:
            # a typoed id would otherwise select ZERO rules and exit 0 —
            # a CI gate that enforces nothing while reporting clean
            ap.error(f"unknown rule id(s): {', '.join(unknown)} "
                     f"(known: {', '.join(sorted(known))})")
        rules = [r for r in rules if r.rule_id in want]
        lockorder = "KTPU006" in want  # --rules subsets really subset
        # --device/--shard/--mem UNION with a --rules subset: an AST-only
        # subset must not silently drop a pass the flag explicitly requested
        named = [r for r in DEVICE_RULE_IDS if r in want]
        device_ids = named or device_ids
        named_shard = [r for r in SHARD_RULE_IDS if r in want]
        shard_ids = named_shard or shard_ids
        named_mem = [r for r in MEM_RULE_IDS if r in want]
        mem_ids = named_mem or mem_ids

    baseline = None
    if not args.no_baseline:
        try:
            # --write-baseline loads leniently: a prior draft's TODO reasons
            # must not dead-end re-drafting (strict CI runs still refuse them)
            baseline = Baseline.load(args.baseline, lenient=args.write_baseline)
        except BaselineError as e:
            print(f"ktpu-verify: unusable baseline: {e}", file=sys.stderr)
            return 2

    run_ast = bool(rules) or lockorder
    if run_ast:
        report = analyze_package(args.root, rules=rules, baseline=None,
                                 lockorder=lockorder)
    else:
        # a pure device/shard/mem-rule subset (--rules KTPU007,... /
        # KTPU014,... / KTPU020) skips the package AST walk entirely —
        # subsets really subset (KTPU014 scans modules inside its own pass)
        from .engine import Report

        report = Report(rules=[])
    # the trace passes share ONE 12-route trace when two or more will trace
    pretraced = None
    shard_traces = any(r != "KTPU014" for r in shard_ids)
    n_tracing = sum((bool(device_ids), shard_traces, bool(mem_ids)))
    if n_tracing >= 2:
        from .devicecheck import collect_traces

        pretraced = collect_traces()
    if device_ids:
        from .devicecheck import run_device_pass

        if pretraced is not None:
            dev = run_device_pass(rule_ids=device_ids, baseline=None,
                                  pretraced=pretraced)
        else:
            dev = run_device_pass(rule_ids=device_ids, baseline=None)
        report.findings.extend(dev.findings)
        report.errors.extend(dev.errors)
        report.rules = report.rules + dev.rules
        report.files_scanned = max(report.files_scanned, dev.files_scanned)
        report.device = dev.device
    if shard_ids:
        from .shardcheck import run_shard_pass

        shd = run_shard_pass(rule_ids=shard_ids, baseline=None,
                             pretraced=pretraced, root=args.root)
        report.findings.extend(shd.findings)
        report.errors.extend(shd.errors)
        report.rules = report.rules + shd.rules
        report.files_scanned = max(report.files_scanned, shd.files_scanned)
        if shd.device is not None:
            report.device = shd.device
    if mem_ids:
        from .memrules import run_mem_pass

        mm = run_mem_pass(rule_ids=mem_ids, baseline=None,
                          pretraced=pretraced)
        report.findings.extend(mm.findings)
        report.errors.extend(mm.errors)
        report.rules = report.rules + mm.rules
        report.files_scanned = max(report.files_scanned, mm.files_scanned)
        if mm.device is not None:
            report.device = mm.device
    # shared traces surface the same trace errors in every pass — dedupe
    report.errors = list(dict.fromkeys(report.errors))
    report = apply_baseline(report, baseline)

    if args.write_baseline:
        if report.errors:
            # an unusable run has incomplete findings: rewriting the
            # baseline from it would silently drop entries whose file
            # merely failed to parse — refuse to touch the file
            for e in report.errors:
                print(f"ERROR {e}", file=sys.stderr)
            print("ktpu-verify: refusing to rewrite the baseline from an "
                  "unusable run (errors above)", file=sys.stderr)
            return 2
        draft = Baseline.draft(report.unbaselined)
        if baseline is not None:
            # drop TODO entries whose finding was fixed (stale drafts);
            # human-reasoned stale entries stay — the STALE report line
            # tells a reviewer to remove them, drafting never deletes a why
            stale = {e["fingerprint"] for e in report.stale_baseline}
            keep = [
                e for e in baseline.entries
                if not ((e.get("reason") or "").upper().startswith("TODO")
                        and e["fingerprint"] in stale)
            ]
            draft["findings"] = keep + draft["findings"]
        with open(args.baseline, "w") as f:
            json.dump(draft, f, indent=2, sort_keys=True)
            f.write("\n")
        todo = sum(1 for e in draft["findings"]
                   if (e.get("reason") or "").upper().startswith("TODO"))
        print(f"wrote {len(draft['findings'])} baseline entries "
              f"({todo} TODO) to {args.baseline} — fill in every TODO reason")
        return 1 if todo else 0  # TODOs left = unresolved work, not clean

    if args.output:
        with open(args.output, "w") as f:
            json.dump(report.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    return report.exit_code


def _dump_flight(path: str) -> int:
    """Post-mortem reader for the decision flight recorder: render the dump
    a dying scheduler left in its checkpoint dir.  A missing or corrupt
    dump is exit 2 (unusable evidence), matching the shared contract."""
    from ..scheduler.flightrecorder import (
        FLIGHT_FILENAME, load_flight, render_flight,
    )

    if not path:
        ckpt = os.environ.get("KTPU_CHECKPOINT_DIR", "")
        if not ckpt:
            print("ktpu-verify: --flight needs a path or KTPU_CHECKPOINT_DIR",
                  file=sys.stderr)
            return 2
        path = os.path.join(ckpt, FLIGHT_FILENAME)
    try:
        doc = load_flight(path)
        text = render_flight(doc)
    except ValueError as e:
        print(f"ktpu-verify: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 — malformed evidence is exit 2
        # a structurally-valid dump with wrong-typed fields must still be
        # "unusable" (2), never a traceback CI misreads as exit 1
        print(f"ktpu-verify: malformed flight dump {path}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    print(text)
    return 0


def _dump_lock_graph(root: str) -> int:
    from .engine import load_modules
    from .lockorder import LockOrderAnalyzer

    mods, errors = load_modules(root)
    if errors:
        for e in errors:
            print(f"ERROR {e}", file=sys.stderr)
        return 2
    edges, witness, reentrant = LockOrderAnalyzer(mods).build_graph()
    for a in sorted(edges):
        for b in sorted(edges[a]):
            w = witness.get((a, b), ("", 0, ""))
            print(f"{a} -> {b}    # {w[2]} ({w[0]}:{w[1]})")
    locks = sorted(reentrant)
    print(f"# {len(locks)} locks, "
          f"{sum(len(v) for v in edges.values())} edges")
    return 0


if __name__ == "__main__":
    sys.exit(main())
