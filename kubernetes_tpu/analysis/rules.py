"""ktpu-verify rules KTPU001..KTPU005 — the codebase's own invariants.

Each rule is the executable form of a prose rule from PARITY.md / review
memory (the mapping table lives in PARITY.md §"Static analysis"):

  KTPU001 kill-safety        crash-consistency invariant 3: no in-process
                             code may swallow ProcessKilled
  KTPU002 snapshot-LIST      the PR-3 "dict changed size during iteration"
                             rule: ClusterStore live dicts are iterated only
                             via the lock-consistent list_*() snapshots or
                             under store.transaction()
  KTPU003 donation-aliasing  incremental-cache invariant 4: resident
                             IncState/HoistCache buffers never ride a
                             donated argument position
  KTPU004 determinism        placement decisions in the pure paths (ops/,
                             api/delta.py) must not read wall clocks,
                             unseeded RNGs, or unordered-set iteration
  KTPU005 cheap-gate         O(P) builds feeding spans are gated on
                             tracer.enabled (the PR-6 contract)
  KTPU013 knob-drift         every literal KTPU_* env READ has a row in
                             README's "Configuration knobs" table

(KTPU007..KTPU012 — the jaxpr/compiled-kernel device rules — live in
jaxrules.py and are traced by devicecheck.py.)
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import Finding, ModuleInfo, Rule, call_name


# --- shared AST helpers ---
def _names_in(expr: Optional[ast.AST]) -> Set[str]:
    """Last-segment identifiers mentioned in an exception-type expression:
    `chaos.ProcessKilled` -> {'chaos', 'ProcessKilled'}."""
    out: Set[str] = set()
    if expr is None:
        return out
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _walk_no_defs(node: ast.AST) -> Iterable[ast.AST]:
    """Descendants of `node`, not descending into nested function/class
    defs (their control flow is not the enclosing handler's)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from _walk_no_defs(child)


def _stmts_walk(stmts: Sequence[ast.stmt]) -> Iterable[ast.AST]:
    for s in stmts:
        yield s
        yield from _walk_no_defs(s)


def _rebinds(body: Sequence[ast.stmt], name: str) -> bool:
    """Is `name` assigned anywhere in the handler body?  A rebound `as e`
    no longer names the caught exception."""
    for n in _stmts_walk(body):
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr,
                            ast.For)):
            targets = [n.target]
        for tgt in targets:
            for t2 in ast.walk(tgt):
                if isinstance(t2, ast.Name) and t2.id == name:
                    return True
    return False


def _is_transparent(handler: ast.ExceptHandler) -> bool:
    """True when the handler unconditionally re-raises THE SAME exception:
    its LAST top-level statement is a bare `raise` (or `raise e` where `e`
    is the handler's own un-rebound `as` binding — same object, ProcessKilled
    propagates unchanged) and nothing in the body can exit another way
    (return/break/continue) or substitute a different exception
    (`raise Other(...)` converts ProcessKilled into something the downstream
    `except Exception` recoveries will catch) — bookkeeping-then-reraise
    (checkpoint.py's tmp cleanup, _kill_point's dead-latch) stays legal."""
    body = handler.body
    if not body:
        return False

    def reraises_same(r: ast.Raise) -> bool:
        if r.exc is None:
            return True
        return (handler.name is not None
                and isinstance(r.exc, ast.Name)
                and r.exc.id == handler.name
                and not _rebinds(body, handler.name))

    last = body[-1]
    if not (isinstance(last, ast.Raise) and reraises_same(last)):
        return False
    for n in _stmts_walk(body):
        if isinstance(n, (ast.Return, ast.Break, ast.Continue)):
            return False
        if isinstance(n, ast.Raise) and not reraises_same(n):
            return False
    return True


class KillSafetyRule(Rule):
    """KTPU001 — no handler may swallow ProcessKilled.

    ProcessKilled is a BaseException precisely so the 21 `except Exception`
    recovery sites stay transparent to it BY CONSTRUCTION; the holes this
    rule closes are (a) bare `except:` / `except BaseException:` that do not
    unconditionally re-raise, (b) catching ProcessKilled anywhere outside
    the restart drivers, and (c) contextlib.suppress over either."""

    rule_id = "KTPU001"
    title = "kill-safety: ProcessKilled must escape in-process handlers"

    # the restart drivers: the ONLY code allowed to answer a ProcessKilled
    # with something other than propagation (they run the crash-restart /
    # leader-takeover protocol — PARITY.md crash-consistency invariants)
    ALLOWLIST: Set[Tuple[str, str]] = {
        ("kubernetes_tpu/scheduler/scheduler.py", "run_restartable"),
        ("kubernetes_tpu/scheduler/scheduler.py", "run_ha_restartable"),
        # the streaming restart drivers (same protocol, stream shape): the
        # wave-WAL replay loop and the open-loop replay's mid-stream
        # leader failover
        ("kubernetes_tpu/parallel/pipeline.py", "run_stream_restartable"),
        ("kubernetes_tpu/bench/loadgen.py", "replay_trace"),
    }

    def check(self, mod: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Try):
                findings.extend(self._check_try(mod, node))
            elif isinstance(node, ast.Call):
                findings.extend(self._check_suppress(mod, node))
        return findings

    def _allowlisted(self, mod: ModuleInfo, node: ast.AST) -> bool:
        # FULL qualname match: the drivers are module-level functions, so a
        # future `SomeClass.run_restartable` method elsewhere in the file
        # does not inherit the exemption
        return (mod.relpath, mod.qualname(node)) in self.ALLOWLIST

    def _check_try(self, mod: ModuleInfo, node: ast.Try) -> List[Finding]:
        findings: List[Finding] = []
        kill_guarded = False  # an earlier transparent ProcessKilled handler
        for h in node.handlers:
            names = _names_in(h.type)
            bare = h.type is None
            catches_base = bare or "BaseException" in names
            catches_kill = "ProcessKilled" in names
            if catches_kill and _is_transparent(h):
                kill_guarded = True
                continue
            if catches_kill and not self._allowlisted(mod, h):
                findings.append(mod.finding(
                    self.rule_id, h,
                    "catches ProcessKilled outside the restart-driver "
                    "allowlist — only restart drivers may answer a kill",
                ))
                continue
            if catches_base and not kill_guarded and not _is_transparent(h) \
                    and not self._allowlisted(mod, h):
                what = "bare except:" if bare else "except BaseException"
                findings.append(mod.finding(
                    self.rule_id, h,
                    f"{what} can swallow ProcessKilled — re-raise "
                    "unconditionally, narrow to Exception, or guard with a "
                    "transparent `except ProcessKilled: raise` first",
                ))
        return findings

    def _check_suppress(self, mod: ModuleInfo, call: ast.Call) -> List[Finding]:
        if call_name(call) != "suppress":
            return []
        bad = {"BaseException", "ProcessKilled"}
        for arg in call.args:
            if _names_in(arg) & bad and not self._allowlisted(mod, call):
                return [mod.finding(
                    self.rule_id, call,
                    "contextlib.suppress over BaseException/ProcessKilled "
                    "swallows the kill latch",
                )]
        return []


# --- KTPU002 ---
# the workload alias properties (store.replicasets/...) return the SAME live
# dicts as store.objects[kind] — iterating them races the writers identically
_ALIAS_KIND = {"replicasets": "ReplicaSet", "deployments": "Deployment",
               "jobs": "Job"}
_STORE_TABLES = ("pods", "nodes", "pvs", "pvcs", "pdbs") \
    + tuple(_ALIAS_KIND)
_ITER_BUILTINS = {
    "list", "sorted", "set", "tuple", "sum", "any", "all", "max", "min",
    "len", "frozenset", "enumerate", "iter", "dict",
}


def _store_like(e: ast.AST) -> bool:
    """`store` / `self.store` / `self._store` / `x.store` receivers."""
    if isinstance(e, ast.Name):
        return e.id in ("store", "_store")
    if isinstance(e, ast.Attribute):
        return e.attr in ("store", "_store")
    return False


def _store_table(e: ast.AST) -> Optional[str]:
    """The table name when `e` is a ClusterStore live-dict expression:
    store.pods / self.store.nodes / store.objects / store.objects[kind]."""
    if isinstance(e, ast.Attribute) and e.attr in _STORE_TABLES \
            and _store_like(e.value):
        return e.attr
    if isinstance(e, ast.Attribute) and e.attr == "objects" \
            and _store_like(e.value):
        return "objects"
    if isinstance(e, ast.Subscript):
        v = e.value
        if isinstance(v, ast.Attribute) and v.attr == "objects" \
                and _store_like(v.value):
            return "objects[...]"
    return None


class SnapshotListRule(Rule):
    """KTPU002 — no iteration/len over ClusterStore live dicts outside
    store.py: use the lock-consistent list_pods()/list_nodes()/... snapshots
    (or hold store.transaction() for a multi-object read-modify-write).
    Point reads (d.get(k), d[k], `k in d`) stay legal — atomic under
    CPython.  Functions whose name ends in `_locked` are exempt by
    convention: the suffix asserts the caller holds store.transaction()
    (the reference's `...Locked` Go naming).  This is the enforced form of
    the PR-3 fix for the "dictionary changed size during iteration" soak
    race."""

    rule_id = "KTPU002"
    title = "snapshot-LIST: no live-dict iteration over ClusterStore tables"

    EXEMPT_FILES = {"kubernetes_tpu/scheduler/store.py"}

    def check(self, mod: ModuleInfo) -> List[Finding]:
        if mod.relpath in self.EXEMPT_FILES:
            return []
        findings: List[Finding] = []
        flagged: Set[int] = set()

        def flag(node: ast.AST, table: str, how: str) -> None:
            if id(node) in flagged or self._in_transaction(mod, node):
                return
            qual = mod.qualname(node)
            if qual.split(".")[-1].endswith("_locked"):
                return  # convention: caller holds store.transaction()
            flagged.add(id(node))
            if table.startswith("objects"):
                api = "list_objects(kind)"
            elif table in _ALIAS_KIND:
                api = f'list_objects("{_ALIAS_KIND[table]}")'
            else:
                api = f"list_{'node_names' if table == 'nodes' and how == 'len' else table}()"
            findings.append(mod.finding(
                self.rule_id, node,
                f"{how} over live ClusterStore.{table} races the store's "
                f"writers — use the lock-consistent store.{api} snapshot "
                "or hold store.transaction()",
            ))

        for node in ast.walk(mod.tree):
            # E.values()/.items()/.keys(): a live view is only ever built to
            # iterate — flag in ANY context (aliasing included)
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("values", "items", "keys"):
                table = _store_table(node.func.value)
                if table is not None:
                    flag(node, table, f".{node.func.attr}() view")
                continue
            if isinstance(node, ast.For):
                table = _store_table(node.iter)
                if table is not None:
                    flag(node.iter, table, "iteration")
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    table = _store_table(gen.iter)
                    if table is not None:
                        flag(gen.iter, table, "iteration")
            elif isinstance(node, ast.Starred):
                table = _store_table(node.value)
                if table is not None:
                    flag(node.value, table, "unpacking")
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id in _ITER_BUILTINS:
                for arg in node.args:
                    table = _store_table(arg)
                    if table is not None:
                        how = "len" if node.func.id == "len" else \
                            f"{node.func.id}()"
                        flag(arg, table, how)
        return findings

    @staticmethod
    def _in_transaction(mod: ModuleInfo, node: ast.AST) -> bool:
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call) \
                            and isinstance(ce.func, ast.Attribute) \
                            and ce.func.attr == "transaction":
                        return True
        return False


# --- KTPU003 ---
_RESIDENT_RE = re.compile(r"(^|_)(inc|hoist)(_|$)|^IncState$|^HoistCache$")


def _mentions_resident(expr: ast.AST) -> Optional[str]:
    for n in ast.walk(expr):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        if ident and _RESIDENT_RE.search(ident):
            return ident
    return None


class DonationAliasingRule(Rule):
    """KTPU003 — incremental-cache invariant 4 (PARITY.md): the resident
    IncState / HoistCache buffers ride a SEPARATE, never-donated kernel
    argument.  Flags (a) a donated argument position mentioning a resident
    buffer identifier, and (b) any new `donate_argnums` wrapper declared
    outside the two audited donation modules."""

    rule_id = "KTPU003"
    title = "donation-aliasing: resident cache buffers never donate"

    # wrapper -> donated positional indices (ops/assign.py donate_argnums)
    DONATED_WRAPPERS: Dict[str, Tuple[int, ...]] = {
        "schedule_batch_donated": (0,),
        "schedule_batch_ordinals_donated": (0,),
    }
    DONATION_MODULES = {
        "kubernetes_tpu/ops/assign.py",
        "kubernetes_tpu/parallel/sharded.py",
        # the device pass (KTPU008) is the donation audit's runtime twin:
        # its RouteTrace.from_callable re-declares callers' donate_argnums
        # to check the COMPILED aliasing — a tracer of donation, never a
        # new donation site for resident buffers
        "kubernetes_tpu/analysis/devicecheck.py",
    }

    # Donation audit table — modules REVIEWED for donation and found to
    # have none on purpose (recorded here so the audit outcome is code,
    # not PR archaeology):
    #   ops/preempt.py — preempt_eval / preempt_eval_wave once carried a
    #     no-op `donate_argnums=()`; dropped (this PR) instead of donating
    #     for real: the wave's inputs are the SHARED state snapshot
    #     (used_now/victim tables serve every same-priority preemptor and
    #     the host's sequential commit pass re-reads them — snap2
    #     freshness reuse), and `arr` is the encoder's resident
    #     ClusterArrays, which the donation contract forbids consuming.
    AUDITED_NO_DONATE = ("kubernetes_tpu/ops/preempt.py",)

    def check(self, mod: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in self.DONATED_WRAPPERS:
                for idx in self.DONATED_WRAPPERS[name]:
                    if idx < len(node.args):
                        hit = _mentions_resident(node.args[idx])
                        if hit:
                            findings.append(mod.finding(
                                self.rule_id, node,
                                f"donated argument {idx} of {name} mentions "
                                f"resident buffer {hit!r} — the incremental "
                                "cache must ride the separate non-donated "
                                "argument (PARITY.md invariant 4)",
                            ))
            donates = any(
                kw.arg == "donate_argnums"
                and not (isinstance(kw.value, (ast.Tuple, ast.List))
                         and not kw.value.elts)  # =() donates nothing
                for kw in node.keywords
            )
            if donates and mod.relpath not in self.DONATION_MODULES:
                findings.append(mod.finding(
                    self.rule_id, node,
                    "donate_argnums outside the audited donation modules "
                    "(ops/assign.py, parallel/sharded.py) — new donation "
                    "sites must land where the aliasing audit lives",
                ))
        return findings


# --- KTPU004 ---
class DeterminismRule(Rule):
    """KTPU004 — the pure placement paths (ops/, api/delta.py) must be a
    function of the encoded cluster alone: no wall clocks, no unseeded
    global RNGs, no iteration over unordered set expressions feeding
    decisions.  (Spans/benchmarks use perf_counter, which stays legal —
    it times, it never decides.)"""

    rule_id = "KTPU004"
    title = "determinism: pure paths read no clocks/unseeded RNG/set order"

    SCOPE_PREFIXES = ("kubernetes_tpu/ops/",)
    SCOPE_FILES = {"kubernetes_tpu/api/delta.py"}
    SEEDED_OK = {"Random", "default_rng", "PRNGKey", "key"}

    def _in_scope(self, relpath: str) -> bool:
        return relpath in self.SCOPE_FILES or any(
            relpath.startswith(p) for p in self.SCOPE_PREFIXES
        )

    def _seeded(self, node: ast.Call, fn: ast.Attribute) -> bool:
        """A seedable constructor is only legal WITH a seed: an argless
        `Random()` / `default_rng()` is entropy-seeded — nondeterministic."""
        return fn.attr in self.SEEDED_OK and bool(node.args or node.keywords)

    def check(self, mod: ModuleInfo) -> List[Finding]:
        if not self._in_scope(mod.relpath):
            return []
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                fn = node.func
                recv = fn.value
                if isinstance(recv, ast.Name) and recv.id == "time" \
                        and fn.attr in ("time", "time_ns"):
                    findings.append(mod.finding(
                        self.rule_id, node,
                        "wall clock in a pure path — decisions must not "
                        "depend on time.time()",
                    ))
                if isinstance(recv, ast.Name) and recv.id == "random" \
                        and not self._seeded(node, fn):
                    findings.append(mod.finding(
                        self.rule_id, node,
                        f"unseeded global random.{fn.attr}() in a pure path "
                        "— use a seeded random.Random(seed) instance",
                    ))
                if isinstance(recv, ast.Attribute) and recv.attr == "random" \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id in ("np", "numpy") \
                        and not self._seeded(node, fn):
                    findings.append(mod.finding(
                        self.rule_id, node,
                        f"global np.random.{fn.attr}() in a pure path — "
                        "use a seeded Generator (np.random.default_rng(seed))",
                    ))
            iters: List[ast.AST] = []
            if isinstance(node, ast.For):
                iters = [node.iter]
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters = [g.iter for g in node.generators]
            for it in iters:
                if isinstance(it, (ast.Set, ast.SetComp)) or (
                    isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                    and it.func.id in ("set", "frozenset")
                ):
                    findings.append(mod.finding(
                        self.rule_id, it,
                        "iterating an unordered set expression in a pure "
                        "path — wrap in sorted() so placement order is "
                        "deterministic",
                    ))
        return findings


# --- KTPU005 ---
def _mentions_gate(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr == "enabled":
            return True
        if isinstance(n, ast.Name) and (
            "enabled" in n.id or "trac" in n.id
        ):
            return True
    return False


class CheapGateRule(Rule):
    """KTPU005 — the PR-6 cheap-gate contract: an O(P) comprehension built
    inside a tracer call (record_span and friends) must sit under a
    `tracer.enabled` gate — an enclosing `if`, a conditional expression, or
    a function-level early-return guard — so tracing-off runs never pay a
    per-pod build."""

    rule_id = "KTPU005"
    title = "cheap-gate: O(P) span builds gated on tracer.enabled"

    TRACER_METHODS = {"record_span", "span", "span_for_pod"}

    def check(self, mod: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.TRACER_METHODS
                    and self._tracer_recv(node.func.value)):
                continue
            has_comp = any(
                isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp))
                for arg in (list(node.args)
                            + [kw.value for kw in node.keywords])
                for n in ast.walk(arg)
            )
            if has_comp and not self._gated(mod, node):
                findings.append(mod.finding(
                    self.rule_id, node,
                    "O(P) comprehension built inside a tracer call without "
                    "a tracer.enabled gate — tracing-off runs pay it "
                    "(PR-6 cheap-gate contract)",
                ))
        return findings

    @staticmethod
    def _tracer_recv(recv: ast.AST) -> bool:
        for n in ast.walk(recv):
            ident = n.id if isinstance(n, ast.Name) else (
                n.attr if isinstance(n, ast.Attribute) else "")
            if ident and ("tracer" in ident or ident == "tr"):
                return True
        return False

    def _gated(self, mod: ModuleInfo, node: ast.AST) -> bool:
        # enclosing if/while/ternary whose test mentions an enabled gate
        enclosing_fn: Optional[ast.AST] = None
        for anc in mod.ancestors(node):
            if isinstance(anc, (ast.If, ast.While, ast.IfExp)) \
                    and _mentions_gate(anc.test):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and enclosing_fn is None:
                enclosing_fn = anc
        # function-level early-return guard before this call:
        #   if not self.tracer.enabled: return ...
        if enclosing_fn is not None:
            for stmt in enclosing_fn.body:
                if getattr(stmt, "lineno", 10**9) >= getattr(node, "lineno", 0):
                    break
                if isinstance(stmt, ast.If) and _mentions_gate(stmt.test) \
                        and stmt.body \
                        and isinstance(stmt.body[-1], (ast.Return, ast.Raise)):
                    return True
        return False


# --- KTPU013 ---
class KnobDriftRule(Rule):
    """KTPU013 — knob drift: every `os.environ.get("KTPU_*")` /
    `os.getenv("KTPU_*")` / `os.environ["KTPU_*"]` READ in the package must
    have a matching row in README's "Configuration knobs" table.  An
    undocumented knob is a behavior switch operators cannot discover and
    reviewers cannot audit; a documented-but-unread knob is a row the
    stale-baseline report equivalent of this rule's inverse would flag —
    here only the read side gates (doc-only rows may describe harness
    FLAGS).  Writes (`os.environ[...] = ...`), `pop`, and non-literal
    names (loops over knob tuples) are not reads and never flag."""

    rule_id = "KTPU013"
    title = "knob-drift: every KTPU_* env read has a README knob row"

    SECTION = "## Configuration knobs"

    def __init__(self, known_knobs: Optional[Set[str]] = None):
        # fixture tests inject the documented set; the real pass reads the
        # README next to the package directory
        self._known = known_knobs

    def _documented(self) -> Set[str]:
        if self._known is not None:
            return self._known
        import os as _os

        pkg = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        readme = _os.path.join(_os.path.dirname(pkg), "README.md")
        try:
            with open(readme) as f:
                text = f.read()
        except OSError:
            return set()
        # scope to the knobs table: a knob mentioned only in prose
        # elsewhere is not a reference row.  FAIL CLOSED on a missing /
        # renamed heading — treating the whole README as the table would
        # silently degrade this gate to near-vacuous (any prose mention
        # passes); an empty documented set instead flags every read loudly
        start = text.find(self.SECTION)
        if start < 0:
            self._known = set()
            return self._known
        end = text.find("\n## ", start + len(self.SECTION))
        text = text[start:end if end >= 0 else len(text)]
        self._known = set(re.findall(r"KTPU_[A-Z0-9_]+", text))
        return self._known

    @staticmethod
    def _knob_name(node: ast.AST) -> Optional[str]:
        """The literal KTPU_* name a node READS from the process env, or
        None."""
        def lit(e: ast.AST) -> Optional[str]:
            if isinstance(e, ast.Constant) and isinstance(e.value, str) \
                    and e.value.startswith("KTPU_") and len(e.value) > 5:
                return e.value
            return None

        def is_environ(e: ast.AST) -> bool:
            return isinstance(e, ast.Attribute) and e.attr == "environ"

        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "get" \
                    and is_environ(fn.value) and node.args:
                return lit(node.args[0])
            if isinstance(fn, ast.Attribute) and fn.attr == "getenv" \
                    and node.args:
                return lit(node.args[0])
        if isinstance(node, ast.Subscript) and is_environ(node.value) \
                and isinstance(node.ctx, ast.Load):
            return lit(node.slice)
        return None

    def check(self, mod: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        for node in ast.walk(mod.tree):
            name = self._knob_name(node)
            if name is None or name in self._documented():
                continue
            key = (mod.qualname(node), name)
            if key in seen:
                continue
            seen.add(key)
            findings.append(mod.finding(
                self.rule_id, node,
                f"env knob {name} is read here but has no row in README's "
                '"Configuration knobs" table — document it or delete the '
                "read",
            ))
        return findings


ALL_RULES = [
    KillSafetyRule,
    SnapshotListRule,
    DonationAliasingRule,
    DeterminismRule,
    CheapGateRule,
    KnobDriftRule,
]
