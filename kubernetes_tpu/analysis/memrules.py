"""ktpu-verify mem pass — KTPU020: measured-vs-analytic HBM reconciliation.

KTPU012 reconciles the COMPILED memory analysis against the analytic
per-shard budget; this pass reconciles the MEASURED side — the live
device-memory ledger (scheduler/memwatch.py) sampled across each traced
route's warm loop — against the same budget, and gates the ledger's own
invariants:

  KTPU020 mem-reconcile   every traced route must carry a memory block
                          (fail closed — a route the ledger could not
                          meter is lost coverage, the KTPU013 shape), its
                          resident-buffer census must equal the
                          FIELD_DIMS size model per buffer (the ledger
                          and shard_hbm_estimate share one model — a
                          mismatch is drift), its measured live peak must
                          stay within MEM_TOLERANCE x the analytic
                          shard_hbm_estimate budget, and its leak
                          sentinel must be clean (unaccounted live bytes
                          rising monotonically across the warm cycles —
                          a retained retired buffer — is exit 1).
                          memory_stats-less backends are recorded on the
                          route block (source: live_arrays), never
                          silently passed as a device measurement.

Rides the twelve-route tracer (analysis/devicecheck.py — collect_traces;
`--device --shard --mem` unions share ONE trace) and the engine's
fingerprint/baseline/0-1-2 exit contract.  Fixture tests build synthetic
RouteTrace mem blocks (an injected leak, a census drift) and pin exit 1.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .engine import Baseline, Finding, Report

# measured live peak may exceed the analytic per-route budget by at most
# this factor (stated tolerance — the budget models the dominant blocks;
# same contract as jaxrules.HBM_TOLERANCE / shardcheck.COMM_TOLERANCE)
MEM_TOLERANCE = 4.0


class MemTraceRule:
    """Base shape shared with jaxrules.DeviceRule / shardcheck trace rules:
    check(traces) over the full RouteTrace list."""

    rule_id = "KTPU000"
    title = ""

    def check(self, traces: Sequence) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def _route_finding(trace, rule_id: str, message: str, detail: str) -> Finding:
    """Route-anchored finding (fingerprint = rule | route file | route name
    | detail — survives kernel edits that keep the violated property)."""
    return Finding(
        rule=rule_id, message=message, file=trace.file, line=0,
        func=trace.name, snippet=detail,
    )


class MemReconcileRule(MemTraceRule):
    """KTPU020 — the four gates, per traced route (see module docstring):
    block present, census == FIELD_DIMS model, measured peak within
    MEM_TOLERANCE x the analytic budget, sentinel clean."""

    rule_id = "KTPU020"
    title = "mem-reconcile: measured HBM peak within the analytic budget; " \
            "census matches the size model; leak sentinel clean"

    def check(self, traces: Sequence) -> List[Finding]:
        findings: List[Finding] = []
        for t in traces:
            if t.status != "traced":
                continue
            mem = getattr(t, "mem", None)
            if not mem:
                # fail CLOSED: a traced route without a memory block means
                # the ledger never metered it — lost coverage, not a pass
                findings.append(_route_finding(
                    t, self.rule_id,
                    "traced route carries no memory block — the device-"
                    "memory ledger did not meter it (lost coverage, the "
                    "KTPU013 fail-closed shape)",
                    "no-mem-block",
                ))
                continue
            census = mem.get("census") or {}
            if census.get("matched") is False:
                bad = [e["qualname"] for e in census.get("entries", [])
                       if not e.get("matched")]
                findings.append(_route_finding(
                    t, self.rule_id,
                    "resident-buffer census diverged from the FIELD_DIMS "
                    f"size model ({', '.join(bad[:4]) or '?'}) — the "
                    "ledger and shard_hbm_estimate no longer share one "
                    "size model",
                    "census-model-drift",
                ))
            measured = int(mem.get("measured_peak_bytes") or 0)
            budget = int(mem.get("analytic_budget_bytes") or 0)
            if budget and measured > MEM_TOLERANCE * budget:
                findings.append(_route_finding(
                    t, self.rule_id,
                    f"measured live-memory peak {measured} B exceeds "
                    f"{MEM_TOLERANCE}x the analytic budget {budget} B "
                    f"(source: {mem.get('source', '?')}) — the measured "
                    "HBM ceiling no longer reconciles with "
                    "shard_hbm_estimate",
                    f"mem:{measured}>{MEM_TOLERANCE}x{budget}",
                ))
            sentinel = mem.get("sentinel") or {}
            if sentinel.get("leaking"):
                findings.append(_route_finding(
                    t, self.rule_id,
                    "leak sentinel: unaccounted live device bytes grew "
                    "monotonically across the warm cycles "
                    f"(growth {sentinel.get('growth_bytes', '?')} B > "
                    f"slack {sentinel.get('slack_bytes', '?')} B) — a "
                    "retired buffer is being retained",
                    "sentinel-leak",
                ))
        return findings


ALL_MEM_RULES = [MemReconcileRule]

MEM_RULE_IDS = tuple(r.rule_id for r in ALL_MEM_RULES)


def run_mem_pass(rule_ids: Optional[Sequence[str]] = None,
                 baseline: Optional[Baseline] = None,
                 mesh_size: int = 8,
                 pretraced: Optional[Tuple[list, List[str]]] = None,
                 ) -> Report:
    """Run the (selected) mem rules over the twelve production routes
    (devicecheck.collect_traces — shared with the device/shard passes via
    `pretraced`, so `--device --shard --mem` traces once).  Same report/
    fingerprint/baseline/exit contract as the other passes; a route that
    fails to trace is an ERROR (exit 2), never a silent skip."""
    from .engine import apply_baseline

    rules = [cls() for cls in ALL_MEM_RULES]
    if rule_ids is not None:
        want = {r.upper() for r in rule_ids}
        rules = [r for r in rules if r.rule_id in want]
    report = Report(rules=[r.rule_id for r in rules])
    if pretraced is not None:
        traces, trace_errors = pretraced
    else:
        from .devicecheck import collect_traces

        traces, trace_errors = collect_traces(mesh_size)
    report.errors.extend(trace_errors)
    n_traced = sum(1 for t in traces if t.status == "traced")
    report.files_scanned = n_traced
    for r in rules:
        try:
            report.findings.extend(r.check(traces))
        except Exception as e:  # a rule bug must not pass as "clean"
            report.errors.append(
                f"mem rule {r.rule_id} crashed: {type(e).__name__}: {e}")
    report.device = {
        "routes": [t.to_dict() for t in traces],
        "n_traced": n_traced,
        "n_skipped": sum(1 for t in traces if t.status == "skipped"),
    }
    apply_baseline(report, baseline)
    return report
