"""Runtime lock-order checker — the KTPU_LOCK_CHECK=1 instrumented locks.

The static half of ktpu-verify (`analysis/lockorder.py`) extracts the
lock-acquisition graph from the AST; this is the dynamic half: every lock in
the package is constructed through `make_lock(name)` / `make_rlock(name)`,
which return plain `threading.Lock`/`RLock` objects unless KTPU_LOCK_CHECK
is set — in which case they return a `CheckedLock` that records, per thread,
the stack of held locks and folds every observed (held -> acquired) pair
into a process-wide order graph.  An acquisition that closes a cycle in that
graph is a potential deadlock (two threads interleaving the two paths hang),
recorded as a `LockOrderViolation` with both witness stacks.

This is the runtime analog of golang's lock-order annotations / the kernel's
lockdep: cycles are detected from SINGLE-thread observations, so one
tier-1 run or chaos storm under KTPU_LOCK_CHECK=1 is enough to flag an
inversion that would only hang under a rare two-thread interleaving.

Zero-cost when off: `make_lock` reads the env once per construction and
hands back a bare threading primitive — the hot paths never see a wrapper.

Usage (tests/test_static_analysis.py, the chaos storm smoke):

    monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
    lockcheck.reset()
    ... run the workload ...
    lockcheck.assert_clean()   # raises with witnesses on any cycle
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple


def enabled() -> bool:
    return os.environ.get("KTPU_LOCK_CHECK", "") not in ("", "0")


# --- process-wide order graph (guarded by its own plain lock) ---
_graph_lock = threading.Lock()
# edge (held -> acquired) -> witness: (thread name, held-stack at observation)
_edges: Dict[Tuple[str, str], Tuple[str, Tuple[str, ...]]] = {}
_violations: List["LockOrderViolation"] = []
# per-thread stacks of held locks, keyed by thread ident and guarded by
# _graph_lock (NOT threading.local: a plain Lock may legally be released by
# a thread other than its acquirer — lock handoff — and the release must
# purge the hold from the ACQUIRER's stack, else every later acquisition on
# that thread records false ordering edges)
_holds: Dict[int, List["CheckedLock"]] = {}


class LockOrderViolation(RuntimeError):
    """An acquisition order that closes a cycle in the observed graph."""

    def __init__(self, cycle: List[str], thread: str,
                 stack: Tuple[str, ...], witnesses: List[str]):
        self.cycle = cycle
        self.thread = thread
        self.stack = stack
        self.witnesses = witnesses
        super().__init__(
            "lock-order cycle " + " -> ".join(cycle)
            + f" (thread {thread!r} holding {list(stack)})\n  prior edges:\n  "
            + "\n  ".join(witnesses)
        )


def _stack() -> List["CheckedLock"]:
    """Current thread's hold stack (callers hold _graph_lock)."""
    return _holds.setdefault(threading.get_ident(), [])


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over the edge set: a path src ~> dst (callers hold _graph_lock)."""
    seen: Set[str] = {src}
    path = [src]

    def walk(cur: str) -> bool:
        if cur == dst:
            return True
        for (a, b) in _edges:
            if a == cur and b not in seen:
                seen.add(b)
                path.append(b)
                if walk(b):
                    return True
                path.pop()
        return False

    return path if walk(src) else None


def _note_intent(lock: "CheckedLock") -> None:
    """Fold the (held -> lock) ordering edges into the graph BEFORE the
    potentially-blocking acquire, lockdep-style: when the flagged
    interleaving actually deadlocks, the violation and witnesses are already
    recorded instead of both threads hanging inside acquire() with an empty
    graph."""
    with _graph_lock:
        st = _stack()
        # holds are tracked PER INSTANCE: only re-acquiring this exact lock
        # is a re-entrant hold (no new ordering information).  Two
        # *different* instances sharing a name (per-object locks like
        # StreamingHist._lock) must NOT collapse into one hold — their
        # nesting is real ordering.
        if any(x is lock for x in st):
            if not lock.reentrant:
                # the holder re-acquiring a non-reentrant lock blocks
                # forever — record the guaranteed self-deadlock first
                _violations.append(LockOrderViolation(
                    [lock.name, lock.name], threading.current_thread().name,
                    tuple(dict.fromkeys(x.name for x in st)),
                    [f"{lock.name} re-acquired by its own holder "
                     "(non-reentrant)"]))
            return
        name = lock.name
        held = tuple(dict.fromkeys(x.name for x in st))  # unique, ordered
        if not held:
            return
        tname = threading.current_thread().name
        for h in held:
            edge = (h, name)
            if edge in _edges:
                continue
            if h == name:
                # two distinct instances of one named lock nested: no
                # name-level order can serialize them — the mirror
                # nesting on another thread is an ABBA deadlock
                # (lockdep's same-class rule; annotate a true hierarchy
                # by giving the levels distinct names)
                _violations.append(LockOrderViolation(
                    [name, name], tname, held,
                    [f"{h} -> {name} (distinct instances of one name)"]))
                _edges[edge] = (tname, held)
                continue
            # does name ~> h already exist?  Then h -> name closes
            # a cycle: some earlier acquisition path orders name
            # before h, this one orders h before name.
            back = _find_path(name, h)
            if back is not None:
                cycle = back + [name]
                witnesses = [
                    f"{a} -> {b} (thread {w[0]!r}, holding {list(w[1])})"
                    for (a, b), w in _edges.items()
                    if a in cycle and b in cycle
                ]
                _violations.append(LockOrderViolation(
                    cycle, tname, held, witnesses))
            _edges[edge] = (tname, held)


def _push_hold(lock: "CheckedLock") -> None:
    with _graph_lock:
        _stack().append(lock)


def _record_release(lock: "CheckedLock") -> None:
    with _graph_lock:
        st = _stack()
        # release the most recent hold of `lock` (with-blocks unwind LIFO,
        # but explicit acquire/release pairs may interleave)
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return
        # not held by this thread: a plain Lock released by a thread other
        # than its acquirer (lock handoff — legal for threading.Lock).
        # Purge the hold from the acquirer's stack, else that thread
        # records a false (lock -> X) edge on every later acquisition.
        for other in _holds.values():
            for i in range(len(other) - 1, -1, -1):
                if other[i] is lock:
                    del other[i]
                    return


class CheckedLock:
    """A Lock/RLock wrapper recording acquisition order per thread.

    Violations are RECORDED, not raised at the acquire site — raising inside
    arbitrary lock-holding code would corrupt the very invariants under
    test; the harness/test asserts `violations()` is empty at the end
    (`assert_clean`)."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            _note_intent(self)  # edges land before a deadlock can hang us
            got = self._inner.acquire(True, timeout)
        else:
            # a trylock cannot block, so it creates no deadlock ordering
            # until it SUCCEEDS (lockdep's trylock rule)
            got = self._inner.acquire(False)
            if got:
                _note_intent(self)
        if got:
            _push_hold(self)
        return got

    def release(self) -> None:
        # inner release FIRST: an illegal release (e.g. cross-thread RLock
        # release) raises here with the checker's hold records untouched —
        # recording first would purge the true owner's hold and silently
        # blind the checker to that thread's later ordering edges
        self._inner.release()
        _record_release(self)

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else False

    def __repr__(self) -> str:
        kind = "RLock" if self.reentrant else "Lock"
        return f"CheckedLock({self.name!r}, {kind})"


def make_lock(name: str):
    """A mutex for `name` (e.g. "ClusterStore._lock"): plain threading.Lock
    unless KTPU_LOCK_CHECK is set."""
    if enabled():
        return CheckedLock(name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    """Re-entrant variant of make_lock."""
    if enabled():
        return CheckedLock(name, reentrant=True)
    return threading.RLock()


# --- reporting ---
def violations() -> List[LockOrderViolation]:
    with _graph_lock:
        return list(_violations)


def order_graph() -> Dict[Tuple[str, str], Tuple[str, Tuple[str, ...]]]:
    """The observed (held -> acquired) edges with their first witnesses."""
    with _graph_lock:
        return dict(_edges)


def reset() -> None:
    """Clear the order graph and violation list (test isolation).  Does not
    touch per-thread hold stacks — live threads keep their true holds."""
    with _graph_lock:
        _edges.clear()
        _violations.clear()


def assert_clean() -> None:
    """Raise the first recorded violation (with its witnesses), if any."""
    vs = violations()
    if vs:
        raise vs[0]


def report() -> Dict[str, object]:
    """Machine-readable summary for bench artifacts (harness lock_check
    block)."""
    with _graph_lock:
        return {
            "enabled": enabled(),
            "edges": sorted(f"{a} -> {b}" for a, b in _edges),
            "violations": [
                {"cycle": v.cycle, "thread": v.thread, "stack": list(v.stack)}
                for v in _violations
            ],
        }
