"""KTPU006 — static lock-order analysis over the whole package.

Extracts the lock-acquisition graph from the AST:

  1. **Lock inventory**: every `self.X = threading.Lock()/RLock()` (or the
     instrumented `make_lock/make_rlock` factories — analysis/lockcheck.py)
     inside a class body registers lock node `Class.X`, remembering
     re-entrancy.
  2. **Direct nesting**: inside one function, `with self.A:` containing
     `with self.B:` yields edge A -> B.  `with store.transaction():` counts
     as acquiring `ClusterStore._lock` (store.py documents transaction()
     as the store's re-entrant lock).
  3. **One-level call propagation**: a call made while holding lock A, to a
     method m resolvable to a lock-owning class (receiver-name heuristic:
     `store` -> ClusterStore, `queue` -> PriorityQueue, ... — the receiver
     identifier must be a substring of a candidate class name), yields
     A -> every lock m acquires directly (same-class `self.m()` calls are
     closed transitively first).
  4. **Watch fan-out**: `store.watch(self._on_event)` registers a callback
     the store invokes UNDER its lock (`_emit` runs inside `with
     self._lock`), so ClusterStore._lock gains an edge to every lock the
     callback acquires — the edge family behind the PR-3 snapshot-LIST
     race and the documented update_snapshot() ABBA comment.

A cycle in the resulting digraph is a potential deadlock: two threads
interleaving the two witness paths hang.  A self-edge on a NON-re-entrant
lock is a guaranteed one.  The dynamic twin (KTPU_LOCK_CHECK=1 —
analysis/lockcheck.py) validates the same property from observed runtime
acquisition order; this static pass fires at analysis time, before any
soak.  Heuristic resolution is deliberately conservative — a spurious edge
is baselined with a reason, a missed deadlock is a 3 a.m. page.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import Finding, ModuleInfo, call_name

_LOCK_FACTORIES = {"Lock": False, "RLock": True,
                   "make_lock": False, "make_rlock": True}


class _ClassInfo:
    def __init__(self, name: str, relpath: str, node: ast.ClassDef):
        self.name = name
        self.relpath = relpath
        self.node = node
        self.locks: Dict[str, bool] = {}  # attr -> reentrant
        # method -> locks it acquires directly (attr names)
        self.method_locks: Dict[str, Set[str]] = {}
        # method -> same-class methods it calls (for transitive closure)
        self.self_calls: Dict[str, Set[str]] = {}
        # (held lock attr) -> [(receiver ident, method, lineno)]
        self.calls_under: Dict[str, List[Tuple[str, str, int]]] = {}
        # direct `with A:` containing `with B:` — (held attr, acquired attr,
        # lineno), attrs as _scan_method records them ("@store_transaction"
        # for store.transaction())
        self.nested: List[Tuple[str, str, int]] = []
        # callbacks handed to <store-like>.watch(...): method names
        self.watch_callbacks: Set[str] = set()


def _lock_ctor(call: ast.AST) -> Optional[bool]:
    """reentrant flag when `call` constructs a lock, else None."""
    return _LOCK_FACTORIES.get(call_name(call))


def _self_lock_attr(expr: ast.AST, locks: Dict[str, bool]) -> Optional[str]:
    """`self.X` where X is a registered lock attr of this class."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and expr.attr in locks:
        return expr.attr
    return None


def _is_transaction_call(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) \
        and isinstance(expr.func, ast.Attribute) \
        and expr.func.attr == "transaction"


def _recv_ident(expr: ast.AST) -> str:
    """Last identifier of a call receiver: self.store.foo() -> 'store';
    queue.push() -> 'queue'; self.meth() -> 'self'."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


class LockOrderAnalyzer:
    STORE_CLASS = "ClusterStore"
    STORE_LOCK = "ClusterStore._lock"

    def __init__(self, mods: List[ModuleInfo]):
        self.mods = mods
        self.classes: Dict[str, _ClassInfo] = {}

    # --- pass 1+2: per-class inventory ---
    def _collect(self) -> None:
        for mod in self.mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    ci = _ClassInfo(node.name, mod.relpath, node)
                    self._scan_class(ci)
                    if ci.locks:
                        # later definition with the same name wins nothing —
                        # keep the first lock-owning one (names are unique
                        # in this package)
                        self.classes.setdefault(ci.name, ci)

    def _scan_class(self, ci: _ClassInfo) -> None:
        for item in ci.node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # lock attributes (any method may lazily create one)
            for n in ast.walk(item):
                if isinstance(n, ast.Assign) and len(n.targets) == 1:
                    attr = _self_lock_attr_any(n.targets[0])
                    if attr is not None:
                        reent = _lock_ctor(n.value)
                        if reent is not None:
                            ci.locks[attr] = reent
        for item in ci.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_method(ci, item)

    def _scan_method(self, ci: _ClassInfo, fn: ast.AST) -> None:
        name = fn.name
        ci.method_locks.setdefault(name, set())
        ci.self_calls.setdefault(name, set())

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, ast.With):
                # items acquire left-to-right, so `with self._x, self._y:`
                # is the same ordering edge as nested withs — extend `held`
                # progressively per item, not once per statement
                new_held = held
                for item in node.items:
                    attr = _self_lock_attr(item.context_expr, ci.locks)
                    if attr is None and _is_transaction_call(item.context_expr):
                        attr = "@store_transaction"
                    if attr is not None:
                        ci.method_locks[name].add(attr)
                        for h in new_held:  # the nesting IS the ordering edge
                            ci.nested.append(
                                (h, attr, getattr(node, "lineno", 0)))
                        new_held = new_held + (attr,)
                for b in node.body:
                    walk(b, new_held)
                return
            if isinstance(node, ast.Call):
                fn_expr = node.func
                if isinstance(fn_expr, ast.Attribute):
                    recv = _recv_ident(fn_expr.value)
                    meth = fn_expr.attr
                    if recv == "self":
                        ci.self_calls[name].add(meth)
                    if meth == "watch" and recv in ("store", "_store"):
                        for arg in node.args:
                            if isinstance(arg, ast.Attribute) \
                                    and isinstance(arg.value, ast.Name) \
                                    and arg.value.id == "self":
                                ci.watch_callbacks.add(arg.attr)
                    if held:
                        for h in held:
                            ci.calls_under.setdefault(h, []).append(
                                (recv, meth, getattr(node, "lineno", 0)))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue  # nested defs run later, not under this hold
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, ())

    # --- pass 3: same-class transitive closure of method_locks ---
    def _close_self_calls(self) -> None:
        for ci in self.classes.values():
            changed = True
            rounds = 0
            while changed and rounds < 16:
                changed = False
                rounds += 1
                for m, calls in ci.self_calls.items():
                    for callee in calls:
                        extra = ci.method_locks.get(callee, set())
                        if extra - ci.method_locks[m]:
                            ci.method_locks[m] |= extra
                            changed = True

    # --- receiver resolution ---
    def _candidates(self, recv: str, meth: str) -> List[_ClassInfo]:
        recv_l = recv.lstrip("_").lower()
        if not recv_l or recv_l == "self":
            return []
        out = []
        for ci in self.classes.values():
            if recv_l in ci.name.lower() and (
                meth in ci.method_locks or meth == "transaction"
            ):
                out.append(ci)
        return out

    def _lock_id(self, ci: _ClassInfo, attr: str) -> str:
        if attr == "@store_transaction":
            return self.STORE_LOCK
        return f"{ci.name}.{attr}"

    # --- pass 4: global edge set ---
    def build_graph(self) -> Tuple[
        Dict[str, Set[str]],
        Dict[Tuple[str, str], Tuple[str, int, str]],
        Dict[str, bool],
    ]:
        """(edges, witness per edge (relpath, line, description),
        reentrancy per lock id)."""
        self._collect()
        self._close_self_calls()
        reentrant: Dict[str, bool] = {self.STORE_LOCK: True}
        for ci in self.classes.values():
            for attr, reent in ci.locks.items():
                reentrant[f"{ci.name}.{attr}"] = reent
        edges: Dict[str, Set[str]] = {}
        witness: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def add(a: str, b: str, relpath: str, line: int, desc: str) -> None:
            if a == b:
                if reentrant.get(a, False):
                    return  # re-entrant self-hold is legal
            edges.setdefault(a, set()).add(b)
            witness.setdefault((a, b), (relpath, line, desc))

        for ci in self.classes.values():
            for held, calls in ci.calls_under.items():
                a = self._lock_id(ci, held)
                for recv, meth, line in calls:
                    if recv == "self":
                        for attr in ci.method_locks.get(meth, set()):
                            add(a, self._lock_id(ci, attr), ci.relpath, line,
                                f"{ci.name}.{meth}() under {a}")
                        continue
                    for target in self._candidates(recv, meth):
                        if meth == "transaction":
                            add(a, self.STORE_LOCK, ci.relpath, line,
                                f"{recv}.transaction() under {a}")
                            continue
                        for attr in target.method_locks.get(meth, set()):
                            add(a, self._lock_id(target, attr), ci.relpath,
                                line, f"{recv}.{meth}() -> "
                                      f"{target.name}.{meth} under {a}")
            # direct nesting: with A: ... with B: — recorded by the SAME
            # walk that built calls_under (_scan_method), so one traversal
            # serves both edge families
            for h, a, line in ci.nested:
                add(self._lock_id(ci, h), self._lock_id(ci, a),
                    ci.relpath, line, f"nested with in {ci.name}")
        self._watch_edges(add)
        return edges, witness, reentrant

    def _watch_edges(self, add) -> None:
        store = self.classes.get(self.STORE_CLASS)
        for ci in self.classes.values():
            for cb in ci.watch_callbacks:
                for attr in ci.method_locks.get(cb, set()):
                    add(self.STORE_LOCK, self._lock_id(ci, attr),
                        ci.relpath, getattr(ci.node, "lineno", 0),
                        f"store.watch({ci.name}.{cb}) runs under the store "
                        "lock (_emit)")
        # store watch REPLAY also invokes the callback under the lock —
        # covered by the same edge; nothing extra needed
        _ = store

    # --- cycles -> findings ---
    def check(self) -> List[Finding]:
        edges, witness, reentrant = self.build_graph()
        findings: List[Finding] = []
        for cyc in _cycles(edges):
            if len(cyc) == 1:
                a = cyc[0]
                w = witness.get((a, a), ("", 0, ""))
                findings.append(Finding(
                    rule="KTPU006",
                    message=f"non-reentrant lock {a} acquired while already "
                            f"held ({w[2]}) — guaranteed self-deadlock",
                    file=w[0], line=w[1], func="",
                    snippet=f"self-cycle {a}",
                ))
                continue
            pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
            wit = [witness.get(p) for p in pairs if p in witness]
            loc = wit[0] if wit else ("", 0, "")
            desc = "; ".join(
                f"{a}->{b} ({witness[(a, b)][2]} at "
                f"{witness[(a, b)][0]}:{witness[(a, b)][1]})"
                for a, b in pairs if (a, b) in witness
            )
            findings.append(Finding(
                rule="KTPU006",
                message="potential lock-order inversion: "
                        + " -> ".join(cyc + [cyc[0]]) + " — " + desc,
                file=loc[0], line=loc[1], func="",
                snippet="cycle " + " -> ".join(sorted(cyc)),
            ))
        return findings


def _self_lock_attr_any(expr: ast.AST) -> Optional[str]:
    """`self.X` target of an assignment (lock inventory)."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycle representatives: one per SCC with >1 node (a
    shortest cycle through its lexically-first node), plus self-loops.
    Deterministic output order."""
    nodes = sorted(set(edges) | {b for bs in edges.values() for b in bs})
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        work = [(v, iter(sorted(edges.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            cur, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[cur] = min(low[cur], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                p = work[-1][0]
                low[p] = min(low[p], low[cur])
            if low[cur] == index[cur]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == cur:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in nodes:
        if v not in index:
            strong(v)

    out: List[List[str]] = []
    for v in nodes:  # self-loops
        if v in edges.get(v, ()):
            out.append([v])
    for comp in sccs:
        cyc = _shortest_cycle(comp[0], set(comp), edges)
        if cyc:
            out.append(cyc)
    return out


def _shortest_cycle(start: str, comp: Set[str],
                    edges: Dict[str, Set[str]]) -> Optional[List[str]]:
    """BFS back to `start` inside its SCC."""
    from collections import deque

    q = deque([(start, [start])])
    seen = {start}
    while q:
        cur, path = q.popleft()
        for nxt in sorted(edges.get(cur, ())):
            if nxt not in comp:
                continue
            if nxt == start:
                return path
            if nxt not in seen:
                seen.add(nxt)
                q.append((nxt, path + [nxt]))
    return None
