"""Analytic kernel-interior cost model — the roofline ledger half of the
device cost observatory (KTPU019's evidence).

bench/profiling.py MEASURES where the device step's time goes by mapping
profiler ops back to their owning `jax.named_scope` sub-phase
(ops/scopes.py).  This module predicts the same breakdown from first
principles: it walks the jaxprs the twelve-route tracer
(analysis/devicecheck.collect_traces) already captures and charges every
LEAF eqn's FLOPs and HBM bytes to the same owning sub-phase — the innermost
declared scope on its name stack, `ops.scopes.subphase_of`, so an op can
never be owned by two different sub-phases across the two halves.

Cost model (deliberately simple — dominant blocks, not every XLA temp,
exactly the shard_hbm_estimate / shard_comm_estimate philosophy whose
KTPU012/KTPU017 tolerances absorb the rest):

  FLOPs      dot_general = 2 x out_size x contraction_size; reductions /
             cumulative ops = input size; sort/top_k = n log2 n; everything
             else = output size (one op per element)
  HBM bytes  sum of input + output aval bytes per eqn (the roofline
             convention: every operand streams once)
  comm bytes collective eqns' output bytes — the same definition
             jaxrules.collective_bytes measures, so the three estimators
             share one field model
  loops      a `scan` body multiplies by its static `length`; a `while`
             body by KTPU_COST_ROUNDS (the prefix-commit round loop's trip
             count is data-dependent; the default is the measured
             rounds/chunk mean from BENCH_ROUNDS_PROOF_r05) — static
             program cost scaled to expected dynamic cost

Roofline classification: per sub-phase, modeled time is
max(flops/peak_flops, hbm/peak_hbm, comm/peak_ici) and the binding resource
names the bound (compute / memory / comm).  Peaks are knobs
(KTPU_PEAK_FLOPS / KTPU_PEAK_HBM_BPS / KTPU_PEAK_ICI_BPS, defaulting to
TPU v5e-ish numbers); on the CPU sim the absolute seconds are fiction but
the SHARES are what KTPU019 reconciles, and shares only need the relative
cost model.

`round_loop_fraction` is a ROLLUP: the share of modeled time on eqns whose
scope path passes through `round_loop` at any depth (the loop's interior
speculate/repair/commit included) — ROADMAP-1's target as one number, the
same rollup bench/profiling.py computes on the measured side.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..ops.scopes import SUBPHASES, subphase_of

# an unowned eqn is "heavy" (a KTPU019 finding) when it carries at least
# this fraction of the route's total modeled time — scale-free, so tiny
# glue (reshapes, converts, loop counters) never flags while any real
# block outside the declared scopes does
HEAVY_FRACTION = 0.01

# KTPU019 reconciliation tolerance: the analytic and measured round-loop
# shares must agree within this FACTOR (ratio of the larger to the smaller,
# after a 0.05 absolute floor so two "negligible" shares always reconcile).
# Stated tolerance, same contract shape as jaxrules.HBM_TOLERANCE: the
# model prices dominant blocks against assumed peaks, not the machine.
SUBPHASE_TOLERANCE = 4.0

_ROLLUP = "round_loop"


def assumed_rounds() -> int:
    """KTPU_COST_ROUNDS — the while-loop trip count the analytic ledger
    charges per prefix-commit round loop (data-dependent at runtime;
    default 9 ≈ the north-star rounds/chunk mean, BENCH_ROUNDS_PROOF_r05
    "8.7 rounds/chunk at north-star scale")."""
    return int(os.environ.get("KTPU_COST_ROUNDS", "9"))


@dataclass(frozen=True)
class Roofline:
    """Peak numbers the ledger classifies against (bytes/s, flop/s)."""

    peak_flops: float
    peak_hbm_bps: float
    peak_ici_bps: float

    @classmethod
    def from_env(cls) -> "Roofline":
        """KTPU_PEAK_FLOPS / KTPU_PEAK_HBM_BPS / KTPU_PEAK_ICI_BPS, with
        TPU v5e-flavored defaults (f32 MXU ~98 TFLOP/s, HBM ~819 GB/s, ICI
        ~4.5e10 B/s per link).  Operators profiling other hardware set the
        knobs; shares (what KTPU019 gates) are peak-insensitive whenever
        one resource binds uniformly."""
        return cls(
            peak_flops=float(os.environ.get("KTPU_PEAK_FLOPS", "9.8e13")),
            peak_hbm_bps=float(os.environ.get("KTPU_PEAK_HBM_BPS", "8.19e11")),
            peak_ici_bps=float(os.environ.get("KTPU_PEAK_ICI_BPS", "4.5e10")),
        )


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * int(dtype.itemsize)


def _out_size(eqn) -> int:
    return sum(
        int(getattr(getattr(ov, "aval", None), "size", 0) or 0)
        for ov in eqn.outvars
    )


def _in_size(eqn) -> int:
    return sum(
        int(getattr(getattr(iv, "aval", None), "size", 0) or 0)
        for iv in eqn.invars
    )


_REDUCE_PRIMS = (
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "reduce_window_sum", "reduce_window",
    "reduce_window_max", "cumsum", "cummax", "cummin", "reduce_precision",
)
_SORT_PRIMS = ("sort", "top_k", "approx_top_k")


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        lhs_shape = getattr(getattr(eqn.invars[0], "aval", None), "shape", ())
        contract = 1
        for d in lhs_c:
            contract *= int(lhs_shape[d]) if d < len(lhs_shape) else 1
        return 2.0 * _out_size(eqn) * max(1, contract)
    if name in _REDUCE_PRIMS:
        return float(_in_size(eqn))
    if name in _SORT_PRIMS:
        shape = getattr(getattr(eqn.invars[0], "aval", None), "shape", (1,))
        n = int(shape[-1]) if shape else 1
        return float(_in_size(eqn)) * math.log2(max(2, n))
    return float(_out_size(eqn))


def _leaf_costs(jaxpr, prefix: str = "", mult: float = 1.0,
                while_trip: Optional[float] = None):
    """Yield (scope_path, prim_name, flops, hbm_bytes, comm_bytes) per LEAF
    eqn, scaled by the product of enclosing loop trip counts.  Containers
    (scan / while / cond / pjit / custom_*) are never charged themselves —
    their interiors are, under the container's scope prefix (interior name
    stacks are relative to their container)."""
    from .jaxrules import COLLECTIVE_PRIMS, _sub_jaxprs

    if while_trip is None:
        while_trip = float(assumed_rounds())
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ns = str(getattr(eqn.source_info, "name_stack", "") or "")
        path = f"{prefix}/{ns}" if prefix and ns else (prefix or ns)
        if name == "scan":
            inner = eqn.params["jaxpr"]
            inner = getattr(inner, "jaxpr", inner)
            length = float(eqn.params.get("length", 1) or 1)
            yield from _leaf_costs(inner, path, mult * length, while_trip)
            continue
        if name == "while":
            for key, m in (("cond_jaxpr", 1.0), ("body_jaxpr", while_trip)):
                inner = eqn.params[key]
                inner = getattr(inner, "jaxpr", inner)
                yield from _leaf_costs(inner, path, mult * m, while_trip)
            continue
        if name == "cond":
            branches = eqn.params.get("branches", ())
            if branches:  # KTPU009 requires identical branches: charge one
                inner = getattr(branches[0], "jaxpr", branches[0])
                yield from _leaf_costs(inner, path, mult, while_trip)
            continue
        subs = list(_sub_jaxprs(eqn))
        if subs:  # pjit / custom_* / shard_map wrappers: transparent
            for sub in subs:
                yield from _leaf_costs(sub, path, mult, while_trip)
            continue
        hbm = sum(_aval_bytes(v) for v in (*eqn.invars, *eqn.outvars))
        comm = 0
        if name in COLLECTIVE_PRIMS:
            comm = sum(_aval_bytes(ov) for ov in eqn.outvars)
        yield (path, name, mult * _eqn_flops(eqn), mult * hbm, mult * comm)


def _bound_of(flops: float, hbm: float, comm: float,
              roof: Roofline) -> Tuple[float, str]:
    times = {
        "compute": flops / roof.peak_flops,
        "memory": hbm / roof.peak_hbm_bps,
        "comm": comm / roof.peak_ici_bps,
    }
    bound = max(times, key=times.get)
    return times[bound], (bound if times[bound] > 0 else "memory")


def dominant_phase(self_fractions: Dict[str, float],
                   rollup: float) -> Optional[str]:
    """The table's dominant sub-phase: the round-loop ROLLUP competes
    against the phases outside the loop (the loop's interior
    speculate/repair rows are part of the rollup, not rivals to it).  One
    definition shared by the analytic (this module) and measured
    (bench/profiling.py) halves."""
    outside = {
        p: f for p, f in self_fractions.items()
        if p not in (_ROLLUP, "speculate", "repair")
    }
    outside[_ROLLUP] = rollup
    return max(outside, key=outside.get) if outside else None


def in_round_loop(path: str) -> bool:
    """Whether a scope path passes through the round loop at any depth —
    the rollup membership test both halves share."""
    return f"/{_ROLLUP}" in f"/{path}" or path == _ROLLUP


def jaxpr_ledger(closed_jaxpr, while_trip: Optional[float] = None,
                 roofline: Optional[Roofline] = None) -> Dict[str, Any]:
    """The per-sub-phase analytic ledger of one traced program.

    Returns {"subphases": {phase: {flops, hbm_bytes, comm_bytes, intensity,
    bound, modeled_s, fraction}}, "total_*", "round_loop_fraction",
    "dominant", "heavy_unowned": [...]}.  `fraction` is modeled-time share
    over ALL leaf eqns ('' = unowned rows sum under the "unowned" key), so
    the fractions sum to 1.0 by construction; `round_loop_fraction` is the
    rollup over every eqn whose path passes through `round_loop`."""
    roof = roofline or Roofline.from_env()
    jx = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    acc: Dict[str, List[float]] = {}
    rollup = [0.0, 0.0, 0.0]
    unowned: Dict[str, List[float]] = {}  # path/prim -> [flops, hbm, comm]
    for path, prim, flops, hbm, comm in _leaf_costs(
            jx, while_trip=while_trip):
        phase = subphase_of(path) or "unowned"
        a = acc.setdefault(phase, [0.0, 0.0, 0.0, 0.0])
        a[0] += flops
        a[1] += hbm
        a[2] += comm
        a[3] += 1
        if in_round_loop(path):
            rollup[0] += flops
            rollup[1] += hbm
            rollup[2] += comm
        if phase == "unowned":
            u = unowned.setdefault(f"{prim}@{path or '<top>'}",
                                   [0.0, 0.0, 0.0])
            u[0] += flops
            u[1] += hbm
            u[2] += comm
    total_s = 0.0
    rows: Dict[str, Dict[str, Any]] = {}
    for phase, (flops, hbm, comm, n) in acc.items():
        t, bound = _bound_of(flops, hbm, comm, roof)
        rows[phase] = {
            "flops": round(flops),
            "hbm_bytes": round(hbm),
            "comm_bytes": round(comm),
            "n_eqns": int(n),
            "intensity": round(flops / hbm, 4) if hbm else 0.0,
            "bound": bound,
            "modeled_s": t,
        }
        total_s += t
    for phase, row in rows.items():
        row["fraction"] = round(row["modeled_s"] / total_s, 4) if total_s else 0.0
        row["modeled_s"] = round(row["modeled_s"], 9)
    rl_t, _ = _bound_of(*rollup, roof)
    rl_frac = round(rl_t / total_s, 4) if total_s else 0.0
    dominant = dominant_phase(
        {p: r["fraction"] for p, r in rows.items()}, rl_frac
    )
    heavy = []
    for key, (flops, hbm, comm) in unowned.items():
        t, _ = _bound_of(flops, hbm, comm, roof)
        frac = t / total_s if total_s else 0.0
        if frac >= HEAVY_FRACTION:
            heavy.append({"eqn": key, "fraction": round(frac, 4)})
    heavy.sort(key=lambda h: -h["fraction"])
    return {
        "subphases": {p: rows[p] for p in (*SUBPHASES, "unowned") if p in rows},
        "total_flops": round(sum(r["flops"] for r in rows.values())),
        "total_hbm_bytes": round(sum(r["hbm_bytes"] for r in rows.values())),
        "total_comm_bytes": round(sum(r["comm_bytes"] for r in rows.values())),
        "round_loop_fraction": rl_frac,
        "dominant": dominant,
        "assumed_rounds": while_trip if while_trip is not None
        else assumed_rounds(),
        "heavy_unowned": heavy,
    }


def route_ledger(trace, while_trip: Optional[float] = None,
                 roofline: Optional[Roofline] = None) -> Optional[Dict]:
    """The ledger of one devicecheck.RouteTrace (None when the route was
    skipped / carries no jaxpr)."""
    if getattr(trace, "jaxpr", None) is None:
        return None
    return jaxpr_ledger(trace.jaxpr, while_trip=while_trip,
                        roofline=roofline)


def reconcile(analytic_rl: float, measured_rl: float,
              tolerance: float = SUBPHASE_TOLERANCE) -> Dict[str, Any]:
    """The KTPU019 join: analytic vs measured round-loop share.  Shares
    below the 0.05 floor reconcile vacuously (both halves call the loop
    negligible); otherwise the larger/smaller ratio must stay within
    `tolerance`."""
    a = max(float(analytic_rl), 0.0)
    m = max(float(measured_rl), 0.0)
    floor = 0.05
    if a < floor and m < floor:
        return {"ok": True, "analytic": a, "measured": m, "ratio": 1.0,
                "tolerance": tolerance, "note": "both shares below floor"}
    lo, hi = min(a, m), max(a, m)
    ratio = hi / max(lo, floor)
    return {
        "ok": ratio <= tolerance,
        "analytic": round(a, 4), "measured": round(m, 4),
        "ratio": round(ratio, 4), "tolerance": tolerance,
    }
