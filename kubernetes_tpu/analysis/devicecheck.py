"""ktpu-verify device pass — traces the production placement kernels and
feeds the captured artifacts to the KTPU007..KTPU012 rules (jaxrules.py).

WHAT IS TRACED.  Every production kernel route the batch scheduler can
take: {chunked, rounds, inc} x {donate on/off} x {single-device, mesh8} —
twelve routes, each exercised exactly the way parallel/pipeline.py and
scheduler.py drive it (DeltaEncoder encode -> HoistCache.ensure ->
schedule_batch_routed / sharded_schedule_batch_routed), at a deliberately
tiny deterministic scale (the invariants checked are properties of the
PROGRAM — dtype flow, aliasing, collective order, cache keys — not of the
workload size).  The report lists every route with its status; a route
that fails to trace is an ERROR (exit 2), never a silent skip.

WHAT IS CAPTURED per route (RouteTrace):

  * the jaxpr (jax.make_jaxpr) — dtype flow + collective order walks
  * the StableHLO lowering text — donation aliasing / buffer-donor marks
  * compiled memory analysis (donate=off variant; backends may expose
    none — recorded as unavailable, not reconciled)
  * a 3-cycle warm loop (cold + two synthetic warm deltas: bind a few
    placed pods, re-pend the rest under fresh names — the encoder's delta
    path and the HoistCache patch path both engage): kernel re-trace and
    jit-cache growth counts, lowering byte-stability, and a transfer-guard
    run (cycles 2-3 execute under
    jax.transfer_guard_host_to_device/device_to_device("disallow") with
    every input explicitly placed)

The pass is read-only with respect to kernel behavior: it saves/restores
the routing env and ops.assign.TRACE_COUNTS, never donates a resident
buffer, and tests/test_devicecheck.py pins analyzed-vs-unanalyzed runs
bit-identical.

Entry points: run_device_pass() (CLI `python -m kubernetes_tpu.analysis
--rules KTPU007,...` / `--device`, and `bench.harness --verify-device`),
RouteTrace.from_callable() (fixture tests build synthetic traces).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .engine import Baseline, Report

# kernel-route anchor for findings/fingerprints (the kernels under test)
ROUTE_FILE = "kubernetes_tpu/ops/assign.py"

_ALIAS_RE = re.compile(
    r"%arg(\d+):[^{)]*\{[^}]*tf\.aliasing_output = (\d+)")
_DONOR_RE = re.compile(r"%arg(\d+):[^{)]*\{[^}]*jax\.buffer_donor")


def ensure_devices(n: int = 8) -> None:
    """Force an n-device virtual CPU platform so the mesh routes trace
    without TPU hardware.  XLA_FLAGS is read at BACKEND INITIALIZATION,
    not at jax import, so this works until the first backend use; a
    process whose backend is already up keeps its platform (the skipped
    mesh routes are then listed with the reason — never silently)."""
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                return
        except Exception:
            return  # cannot tell — do not disturb a live backend
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


@dataclass
class RouteTrace:
    """Captured artifacts of one traced kernel route — what jaxrules.py
    checks.  Fixture tests build small synthetic ones via from_callable."""

    name: str                 # e.g. "chunked/donate/mesh8"
    kind: str                 # chunked | rounds | inc (fixtures: free-form)
    donate: bool
    n_shards: int
    file: str = ROUTE_FILE    # finding anchor
    status: str = "traced"    # traced | skipped
    skip_reason: str = ""
    jaxpr: Any = None         # ClosedJaxpr
    out_avals: Tuple = ()
    integer_out_indices: Tuple[int, ...] = ()
    lowered_text: Optional[str] = None
    aliased: List[Tuple[int, int]] = field(default_factory=list)
    donor_args: int = 0
    alias_required_out: Optional[int] = None
    collectives: List[str] = field(default_factory=list)
    cond_divergences: List[str] = field(default_factory=list)
    warm: Dict[str, Any] = field(default_factory=dict)
    transfer_violation: Optional[str] = None
    memory: Optional[Dict[str, int]] = None
    est: Optional[Dict[str, int]] = None
    workload: Dict[str, Any] = field(default_factory=dict)
    # ---- shard-pass capture (analysis/shardcheck.py, KTPU015..018) ----
    # per resident buffer entering the program: {qualname, shape, itemsize,
    # spec, dims} — resolved through the partition rule table
    shard_fields: List[Dict[str, Any]] = field(default_factory=list)
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    # ordered (collective prim, output bytes) pairs from the jaxpr walk —
    # the measured side of the KTPU017 comm reconciliation
    collective_bytes: List[Tuple[str, int]] = field(default_factory=list)
    comm_est: Optional[Dict[str, int]] = None
    # per kernel output: {declared, compiled, equivalent} — compiled
    # shardings vs the table's out.* rows (KTPU018); None = not captured
    # (single-device route, or backend exposing no output shardings)
    out_sharding_report: Optional[List[Dict[str, Any]]] = None
    # ---- device cost observatory (analysis/costmodel.py, KTPU019) ----
    # the per-sub-phase analytic roofline ledger of the traced program
    cost: Optional[Dict[str, Any]] = None
    # a measured sub-phase table (bench/profiling.py) when one exists for
    # this route — KTPU019 reconciles the two round-loop shares
    measured_subphases: Optional[Dict[str, Any]] = None
    # ---- HBM telemetry plane (scheduler/memwatch.py, KTPU020) ----
    # the per-route memory block: measured live peak vs the analytic
    # budget, the resident-buffer census vs the FIELD_DIMS model, the
    # leak-sentinel verdict across the warm loop, memory_stats
    # availability.  Every traced route must carry one (fail closed).
    mem: Optional[Dict[str, Any]] = None

    def capture(self, jaxpr_fn, jaxpr_args, jitted_fn, lower_args):
        """Fill the program-capture fields — jaxpr + collective walk,
        lowering text, donation alias/donor marks — from ONE extraction
        path shared by trace_route (real kernels) and from_callable
        (fixtures), so the fixture tests and the production pass can never
        check different parsing logic.  Returns the Lowered for optional
        memory analysis."""
        import jax

        from .jaxrules import collective_bytes, collective_walk

        closed = jax.make_jaxpr(jaxpr_fn)(*jaxpr_args)
        self.jaxpr = closed
        self.out_avals = tuple(closed.out_avals)
        self.collectives, self.cond_divergences = collective_walk(
            closed.jaxpr)
        self.collective_bytes = collective_bytes(closed.jaxpr)
        # the analytic per-sub-phase roofline ledger (costmodel.py): ONE
        # extraction path, so fixtures and the production pass can never
        # check different cost logic
        from .costmodel import route_ledger

        self.cost = route_ledger(self)
        with _quiet_donation():
            lowered = jitted_fn.lower(*lower_args)
        self.lowered_text = lowered.as_text()
        self.aliased = [(int(a), int(o))
                        for a, o in _ALIAS_RE.findall(self.lowered_text)]
        self.donor_args = len(_DONOR_RE.findall(self.lowered_text))
        return lowered

    @classmethod
    def from_callable(cls, name: str, fn, *args, donate_argnums=(),
                      integer_out_indices=(), alias_required_out=None,
                      n_shards: int = 1, kind: str = "fixture",
                      compile_memory: bool = False) -> "RouteTrace":
        """Trace an arbitrary callable into a RouteTrace — the fixture-test
        entry (a deliberately f64-promoting kernel, a dropped donation, a
        shard-divergent collective); capture() is the shared extraction."""
        import jax

        t = cls(name=name, kind=kind, donate=bool(donate_argnums),
                n_shards=n_shards,
                integer_out_indices=tuple(integer_out_indices),
                alias_required_out=alias_required_out)
        lowered = t.capture(
            fn, args, jax.jit(fn, donate_argnums=donate_argnums), args)
        if compile_memory:
            t.memory = _memory_stats(lowered)
        return t

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "kind": self.kind, "donate": self.donate,
            "n_shards": self.n_shards, "status": self.status,
            "skip_reason": self.skip_reason,
            "collectives": list(self.collectives),
            "cond_divergences": list(self.cond_divergences),
            "n_aliased": len(self.aliased), "donor_args": self.donor_args,
            "warm": dict(self.warm),
            "transfer_violation": self.transfer_violation,
            "memory": self.memory, "est": self.est,
            "workload": dict(self.workload),
            # the per-route shard report (KTPU015..018 artifacts)
            "shard": {
                "n_fields": len(self.shard_fields),
                "mesh_axes": dict(self.mesh_axes),
                "collective_bytes": [
                    [p, int(b)] for p, b in self.collective_bytes],
                "comm_bytes_measured": int(
                    sum(b for _p, b in self.collective_bytes)),
                "comm_est": self.comm_est,
                "out_shardings": self.out_sharding_report,
            },
            # the analytic roofline ledger (costmodel.py — the KTPU019
            # evidence; every traced route must carry one)
            "cost": self.cost,
            # the HBM telemetry block (memwatch.py — the KTPU020
            # evidence; every traced route must carry one)
            "mem": self.mem,
        }


@dataclass(frozen=True)
class RouteSpec:
    kind: str        # chunked | rounds | inc
    donate: bool
    n_shards: int    # TOTAL device count (pods x nodes on a 2-D mesh)
    # 2-D pods x nodes mesh shape; None = 1-D node-only mesh (or single)
    mesh_shape: Optional[Tuple[int, int]] = None

    @property
    def name(self) -> str:
        if self.mesh_shape is not None:
            tag = f"mesh{self.mesh_shape[0]}x{self.mesh_shape[1]}"
        elif self.n_shards > 1:
            tag = f"mesh{self.n_shards}"
        else:
            tag = "single"
        return f"{self.kind}/{'donate' if self.donate else 'nodonate'}/{tag}"

    @property
    def axis_shards(self) -> Tuple[int, int]:
        """(pod_shards, node_shards) this route runs at."""
        if self.mesh_shape is not None:
            return (int(self.mesh_shape[0]), int(self.mesh_shape[1]))
        return (1, max(1, self.n_shards))


def enumerate_routes(mesh_size: int = 8) -> List[RouteSpec]:
    """The production route matrix: {chunked, rounds, inc} x {donate
    on/off} x {single-device, 1-D node mesh, 2-D pods x nodes mesh} —
    eighteen routes.  The 2-D shape folds the same device count as the 1-D
    mesh (pods x nodes = mesh_size) so both shard layers trace on the same
    virtual platform."""
    shape_2d = (2, mesh_size // 2) if mesh_size >= 4 else None
    meshes: List[Tuple[int, Optional[Tuple[int, int]]]] = [
        (1, None), (mesh_size, None)]
    if shape_2d is not None:
        meshes.append((mesh_size, shape_2d))
    return [
        RouteSpec(kind, donate, ns, shape)
        for kind in ("chunked", "rounds", "inc")
        for donate in (False, True)
        for ns, shape in meshes
    ]


@contextlib.contextmanager
def _quiet_donation():
    """The 'Some donated buffers were not usable' warning is expected
    noise on whole-ClusterArrays donation (schedule_batch_routed suppresses
    it identically)."""
    import warnings

    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def _memory_stats(lowered) -> Optional[Dict[str, int]]:
    """CompiledMemoryStats -> plain dict, or None when the backend exposes
    no memory analysis (KTPU012 records the route as unreconciled instead
    of guessing)."""
    try:
        return _memory_of_compiled(lowered.compile())
    except Exception:
        return None


def _memory_of_compiled(compiled) -> Optional[Dict[str, int]]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    try:
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
    except AttributeError:
        return None


def _out_sharding_report(compiled, mesh, declared, out_ndims) -> Optional[list]:
    """Per-output {declared, compiled, equivalent} — the KTPU018 capture.
    `declared` is the ordered list of out.* table qualnames for this
    route's outputs; `out_ndims` their ranks (from the captured out_avals).
    Backends/jax versions exposing no output shardings record None
    (reported unreconciled, never silently passed)."""
    from ..parallel.partition_rules import sharding_for

    try:
        outs = list(compiled.output_shardings)
    except Exception:
        return None
    report = []
    for qualname, sh, ndim in zip(declared, outs, out_ndims):
        want = sharding_for(mesh, qualname)
        try:
            eq = bool(sh.is_equivalent_to(want, ndim))
        except Exception:
            eq = None
        report.append({
            "declared": qualname,
            "compiled": repr(sh),
            "equivalent": eq,
        })
    return report


def _shard_field_report(arr, inc, image_sharded: bool,
                        pod_sharded: bool = False) -> list:
    """Per resident buffer: qualname, concrete shape, itemsize, resolved
    spec (through the partition rule table), dims symbols — what KTPU015
    (replicated-giant) and KTPU016 (axis-consistency) check per route.
    Specs are the EFFECTIVE per-route placements: on a 1-D node mesh the
    table's pods-axis rows strip to replicated (what the devices actually
    hold), so KTPU015's replicated-on-every-route pass sees the truth per
    mesh shape rather than the table's 2-D declaration."""
    import dataclasses as _dc

    import numpy as np

    from ..parallel.partition_rules import (
        FIELD_DIMS, MESH_AXES, NODE_AXIS, clusterarrays_specs, spec_for,
        strip_spec,
    )

    keep = MESH_AXES if pod_sharded else (NODE_AXIS,)
    out = []
    specs = clusterarrays_specs(image_sharded, pod_sharded=pod_sharded)
    missing = [
        f"arr.{f.name}" for f in _dc.fields(type(arr))
        if f"arr.{f.name}" not in FIELD_DIMS
    ]
    if missing:
        # fail CLOSED, matching spec_for: a resident field outside the
        # size model would silently escape KTPU015/016 — make it a trace
        # error (exit 2), not a quiet coverage hole
        raise ValueError(
            f"resident field(s) missing from partition_rules.FIELD_DIMS: "
            f"{missing} — add dims/itemsize rows next to the field's "
            "partition rule"
        )
    for f in _dc.fields(type(arr)):
        q = f"arr.{f.name}"
        a = np.asarray(getattr(arr, f.name))
        dims = FIELD_DIMS[q][0]
        if f.name == "image_score" and not image_sharded:
            # the [P, 1] broadcast form: the node dim is a constant 1, not
            # an N-scaling axis (the real [P, N] matrix shards on nodes)
            dims = ("P", "_1")
        out.append({
            "qualname": q,
            "shape": tuple(int(s) for s in a.shape),
            "itemsize": int(a.dtype.itemsize),
            "spec": tuple(getattr(specs, f.name)),
            "dims": dims,
        })
    if inc is not None:
        for name in inc._fields:
            v = getattr(inc, name)
            if v is None:
                continue
            q = f"inc.{name}"
            out.append({
                "qualname": q,
                "shape": tuple(int(s) for s in v.shape),
                "itemsize": int(v.dtype.itemsize),
                "spec": tuple(strip_spec(spec_for(q), keep)),
                "dims": FIELD_DIMS[q][0],
            })
    return out


def _route_snapshot(kind: str):
    """Deterministic tiny workload per route kind.  heterogeneous is the
    north-star shape (fit+balanced only -> chunked routing, template-
    stamped specs -> real equivalence classes for inc); spread_affinity
    carries pairwise terms -> rounds routing."""
    from ..bench import workloads

    if kind in ("chunked", "inc"):
        return workloads.heterogeneous(16, 120, seed=5)
    return workloads.spread_affinity(16, 48, seed=5)


def _bind_warm_delta(snap, meta, choices, cycle: int, k: int = 4):
    """The synthetic warm delta: k placed pods become bound (spec objects
    shared — template stamping keeps the class set identity-stable), the
    rest re-pend under fresh names.  Mirrors the warm churn the pipeline
    sees between cycles."""
    import numpy as np

    from ..api.snapshot import Snapshot

    ch = np.asarray(choices)
    by_name = {p.name: p for p in snap.pending_pods}
    bound = list(snap.bound_pods)
    n_bound = 0
    for i in range(meta.n_pods):
        if int(ch[i]) >= 0 and n_bound < k:
            pod = by_name[meta.pod_names[i]]
            bound.append(dataclasses.replace(
                pod, node_name=meta.node_names[int(ch[i])]))
            n_bound += 1
    pend = [
        dataclasses.replace(p, name=f"w{cycle}-{p.name}", uid="")
        for p in snap.pending_pods
    ]
    return Snapshot(nodes=snap.nodes, pending_pods=pend, bound_pods=bound)


def _place(arr, mesh):
    """EXPLICIT device placement of a host ClusterArrays — what
    api/delta.py encode_device does on the production path, so the warm
    loop's transfer guard only sees intended transfers."""
    import jax

    if mesh is None:
        return jax.tree_util.tree_map(jax.device_put, arr)
    from ..parallel.sharded import field_shardings

    img = arr.image_score.shape[1] == arr.N
    sh = field_shardings(mesh, img)
    return dataclasses.replace(arr, **{
        name: jax.device_put(getattr(arr, name), s)
        for name, s in sh.items()
    })


@contextlib.contextmanager
def _no_implicit_transfers():
    import jax

    with jax.transfer_guard_host_to_device("disallow"), \
            jax.transfer_guard_device_to_device("disallow"):
        yield


def _single_fns(donate: bool):
    from ..ops import assign as A

    return A.schedule_batch_donated if donate else A.schedule_batch


def _sharded_fn(mesh, arr, cfg, donate, inc):
    """The exact lru-cached jit parallel/sharded.py routes this call to —
    fetching it through the same key means _cache_size() watches the
    production cache entry, not a twin."""
    from ..ops import assign as A
    from ..parallel import sharded as S

    if A._chunk_routed(arr, cfg):
        kind = "chunked"
    elif A._rounds_routed(arr, cfg):
        kind = "rounds"
    else:
        kind = "scan"
    inc = A.inc_applicable(arr, cfg, inc) if kind != "scan" else None
    inc_sig = None
    if inc is not None:
        inc_sig = (inc.elig_u is not None, inc.traw_u is not None,
                   inc.naraw_u is not None, inc.img_u is not None)
    fn = S._sharded_routed_fn(
        mesh, arr.image_score.shape[1] == arr.N, kind, cfg, False, donate,
        inc_sig,
    )
    return fn, inc, kind


def _cache_size(fn) -> int:
    try:
        return int(fn._cache_size())
    except Exception:
        return 0


def trace_route(spec: RouteSpec) -> RouteTrace:
    """Trace ONE production route end to end (see module docstring for the
    capture list).  Raises on any failure — run_device_pass converts that
    into a report ERROR (exit 2): a route that cannot trace is lost
    coverage, not a clean pass."""
    import jax
    import numpy as np

    from ..api.delta import DeltaEncoder
    from ..ops import DEFAULT_SCORE_CONFIG, infer_score_config
    from ..ops import assign as A
    from ..ops.incremental import HoistCache
    from ..parallel.mesh import make_mesh, shard_hbm_estimate

    t = RouteTrace(name=spec.name, kind=spec.kind, donate=spec.donate,
                   n_shards=spec.n_shards,
                   integer_out_indices=(0, 1), alias_required_out=1)
    if spec.n_shards > 1 and len(jax.devices()) < spec.n_shards:
        t.status = "skipped"
        t.skip_reason = (f"{spec.n_shards}-device mesh needs "
                         f">= {spec.n_shards} devices "
                         f"(have {len(jax.devices())})")
        return t

    if spec.mesh_shape is not None:
        mesh = make_mesh(shape=spec.mesh_shape)
    elif spec.n_shards > 1:
        mesh = make_mesh(spec.n_shards)
    else:
        mesh = None
    pod_shards, node_shards = spec.axis_shards
    snap = _route_snapshot(spec.kind)
    enc = DeltaEncoder()
    cache = HoistCache(mesh=mesh) if spec.kind == "inc" else None

    # the HBM telemetry ledger (scheduler/memwatch.py): baseline the
    # measured side BEFORE this route allocates anything, so earlier
    # routes' leftovers never count against it; cycle samples land after
    # the cold step and each warm step, and the assembled per-route `mem`
    # block is what KTPU020 (analysis/memrules.py) reconciles.  The
    # tracer deliberately ignores KTPU_MEMWATCH (the RUNTIME plane's kill
    # switch): KTPU020 fails closed on a route without a memory block, so
    # a verify run must always meter — lost coverage is never a pass.
    from ..scheduler.memwatch import DeviceMemoryLedger

    ledger = DeviceMemoryLedger(mesh=mesh)
    ledger.baseline()
    mem_samples: List[Dict[str, Any]] = []

    arr, meta = enc.encode(snap)
    cfg = infer_score_config(arr, DEFAULT_SCORE_CONFIG)
    want_chunked = spec.kind in ("chunked", "inc")
    if want_chunked != A._chunk_routed(arr, cfg) or (
            spec.kind == "rounds" and not A._rounds_routed(arr, cfg)):
        raise RuntimeError(
            f"{spec.name}: workload did not route the {spec.kind} kernel "
            "(routing predicates moved?)")

    inc = cache.ensure(arr, meta, cfg) if cache is not None else None
    if spec.kind == "inc" and inc is None:
        raise RuntimeError(f"{spec.name}: HoistCache.ensure declined — no "
                           "incremental route to trace")

    arr_dev = _place(arr, mesh)

    # ---- program capture: jaxpr, lowering, donation marks, memory ----
    if mesh is None:
        fn = _single_fns(spec.donate)
        lower_args = (arr_dev, cfg, inc)
        if spec.kind == "inc":
            jaxpr_fn = lambda a, i: A.schedule_batch_impl(a, cfg, i)  # noqa: E731
            jaxpr_args = (arr_dev, inc)
        else:
            jaxpr_fn = lambda a: A.schedule_batch_impl(a, cfg, None)  # noqa: E731
            jaxpr_args = (arr_dev,)
    else:
        fn, inc_eff, _routed_kind = _sharded_fn(
            mesh, arr_dev, cfg, spec.donate, inc)
        lower_args = (arr_dev,) if inc_eff is None else (arr_dev, inc_eff)
        jaxpr_fn, jaxpr_args = fn, lower_args
    lowered = t.capture(jaxpr_fn, jaxpr_args, fn, lower_args)
    if not spec.donate:
        try:
            compiled = lowered.compile()
        except Exception:
            compiled = None
        if compiled is not None:
            t.memory = _memory_of_compiled(compiled)
            if mesh is not None:
                # KTPU018: the compiled outputs vs the table's out.* rows
                declared = ["out.assignment", "out.node_used"]
                t.out_sharding_report = _out_sharding_report(
                    compiled, mesh, declared,
                    [len(a.shape) for a in t.out_avals],
                )

    chunk = {"chunked": A._CHUNK, "inc": A._INC_CHUNK,
             "rounds": A._RCHUNK}[spec.kind]
    u1 = int(inc.req_u.shape[0]) if inc is not None else None
    t.est = shard_hbm_estimate(
        arr.P, arr.N, node_shards, n_res=arr.R,
        n_terms=arr.term_counts0.shape[0], chunk=chunk,
        u_classes=u1, pod_shards=pod_shards,
    )
    # ---- shard-pass capture: resident-buffer report + comm budget ----
    from ..parallel.mesh import shard_comm_estimate

    img = arr.image_score.shape[1] == arr.N
    t.shard_fields = _shard_field_report(arr, inc, img,
                                         pod_sharded=pod_shards > 1)
    t.mesh_axes = (
        {str(k): int(v) for k, v in mesh.shape.items()}
        if mesh is not None else {}
    )
    if mesh is not None:
        t.comm_est = shard_comm_estimate(
            arr.P, arr.N, node_shards, n_res=arr.R,
            n_terms=arr.term_counts0.shape[0], chunk=chunk,
            u_classes=u1, kind=spec.kind, pod_shards=pod_shards,
        )
    t.workload = {
        "P": int(arr.P), "N": int(arr.N), "R": int(arr.R),
        "T": int(arr.term_counts0.shape[0]), "chunk": int(chunk),
        "U1": u1,
    }

    # ---- warm loop: cold cycle + two guarded warm deltas ----
    def call(a_dev, cfg_c, inc_state):
        # cfg_c is the CYCLE's inferred config: a warm delta that moves it
        # churns the jit cache key, which must show up as a retrace below
        # (KTPU010) — never be masked by reusing the cold cfg closure
        return A.schedule_batch_routed(
            a_dev, cfg_c, donate=spec.donate, mesh=mesh, inc=inc_state)

    choices, _used = call(arr_dev, cfg, inc)
    mem_samples.append(ledger.cycle_sample(
        arr=arr_dev, inc=inc, hoist=cache, label="cold"))
    size0 = _cache_size(fn)
    warm_texts: List[str] = []
    retraces = 0
    last_size = size0
    cur = _bind_warm_delta(snap, meta, choices, 1)
    for cyc in (2, 3):
        arr_w, meta_w = enc.encode(cur)
        cfg_w = infer_score_config(arr_w, DEFAULT_SCORE_CONFIG)
        violated = False
        with _no_implicit_transfers():
            try:
                inc_w = (cache.ensure(arr_w, meta_w, cfg_w)
                         if cache is not None else None)
                aw_dev = _place(arr_w, mesh)
            except Exception as e:  # noqa: BLE001 — guard violations surface
                if "transfer" not in str(e).lower() \
                        and "disallow" not in str(e).lower():
                    raise
                t.transfer_violation = t.transfer_violation or \
                    f"cycle {cyc} (hoist/placement): {e}"
                violated = True
        if violated:
            # re-run unguarded so the warm-delta chain stays intact
            inc_w = (cache.ensure(arr_w, meta_w, cfg_w)
                     if cache is not None else None)
            aw_dev = _place(arr_w, mesh)
        # lowering capture BEFORE the call: donated buffers are consumed
        # by it, and lower() re-traces (which must not count as a kernel
        # re-trace below)
        if mesh is None:
            fn_w, largs = fn, (aw_dev, cfg_w, inc_w)
        else:
            fn_w, inc_eff_w, _k = _sharded_fn(
                mesh, aw_dev, cfg_w, spec.donate, inc_w)
            largs = (aw_dev,) if inc_eff_w is None else (aw_dev, inc_eff_w)
        with _quiet_donation():
            warm_texts.append(fn_w.lower(*largs).as_text())
        pre_counts = dict(A.TRACE_COUNTS)
        try:
            with _no_implicit_transfers():
                out = call(aw_dev, cfg_w, inc_w)
                jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001
            if "transfer" not in str(e).lower() \
                    and "disallow" not in str(e).lower():
                raise
            t.transfer_violation = t.transfer_violation or \
                f"cycle {cyc} (step): {e}"
            aw_dev = _place(arr_w, mesh)
            out = call(aw_dev, cfg_w, inc_w)
        retraces += sum(
            A.TRACE_COUNTS[k] - pre_counts[k] for k in pre_counts)
        last_size = _cache_size(fn_w)
        # cycle-boundary memory sample (outside the transfer guard — the
        # ledger only reads buffer metadata, never values): donated waves'
        # consumed inputs drop out of the census here, which is exactly
        # the "donation retires the buffer" invariant the sentinel checks
        mem_samples.append(ledger.cycle_sample(
            arr=aw_dev, inc=inc_w, hoist=cache, label=f"warm{cyc}"))
        choices_w = np.asarray(out[0])
        cur = _bind_warm_delta(cur, meta_w, choices_w, cyc)
    t.warm = {
        "cycles": 3,
        "retraces": retraces,
        "cache_growth": max(0, last_size - size0),
        "lowered_stable": warm_texts[0] == warm_texts[1],
    }
    # ---- the per-route memory block (KTPU020's evidence) ----
    # measured: the ledger's live high-water delta (memory_stats peak on
    # backends exposing it, live-array bytes otherwise — the source is
    # recorded either way, never silently substituted); analytic: the
    # SAME shard_hbm_estimate budget KTPU012 reconciles, globalized
    # (per-shard total x shards — the live-array measure is process-
    # global logical bytes).  The census ships totals + any UNMATCHED
    # entries (matched ones need no enumeration in the artifact).
    census = ledger.last_census or {}
    t.mem = {
        "measured_peak_bytes": ledger.hbm_peak_bytes(),
        "analytic_budget_bytes": int(
            (t.est or {}).get("total", 0)) * max(1, spec.n_shards),
        "source": ledger.source(),
        "memory_stats_available": ledger.memory_stats_available,
        "census": {
            "matched": ledger.census_matched,
            "resident_bytes": census.get("resident_bytes", 0),
            "per_shard_bytes": census.get("per_shard_bytes", 0),
            "model_bytes": census.get("model_bytes", 0),
            "n_buffers": census.get("n_buffers", 0),
            # every unmatched entry SEEN ACROSS THE RUN (matched is an
            # AND over all samples — a transient cold-cycle drift must
            # ship its offending qualname, not an empty list)
            "entries": list(ledger.census_unmatched.values()),
        },
        "sentinel": ledger.sentinel.verdict(),
        "samples": mem_samples,
    }
    return t


@contextlib.contextmanager
def _pass_env():
    """Force the production routing for the pass, restore EVERYTHING after
    (env + TRACE_COUNTS) — the no-mutation contract the parity test pins."""
    from ..ops import assign as A

    saved_env = {k: os.environ.get(k)
                 for k in ("KTPU_FORCE_CHUNKED", "KTPU_INCREMENTAL")}
    saved_counts = dict(A.TRACE_COUNTS)
    os.environ["KTPU_FORCE_CHUNKED"] = "1"
    os.environ.pop("KTPU_INCREMENTAL", None)
    try:
        yield
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        A.TRACE_COUNTS.clear()
        A.TRACE_COUNTS.update(saved_counts)


def collect_traces(mesh_size: int = 8) -> Tuple[List[RouteTrace], List[str]]:
    """Trace every production route once: (traces, errors).  The one trace
    collector the device pass (KTPU007..012) and the shard pass
    (KTPU014..018, analysis/shardcheck.py) share — `--device --shard` pays
    a single 12-route trace, and the two passes can never check different
    captures."""
    ensure_devices(mesh_size)
    traces: List[RouteTrace] = []
    errors: List[str] = []
    with _pass_env():
        for spec in enumerate_routes(mesh_size):
            try:
                traces.append(trace_route(spec))
            except Exception as e:  # noqa: BLE001 — lost coverage = exit 2
                errors.append(
                    f"{spec.name}: trace failed: {type(e).__name__}: {e}")
    return traces, errors


def run_device_pass(rule_ids: Optional[Sequence[str]] = None,
                    baseline: Optional[Baseline] = None,
                    mesh_size: int = 8,
                    pretraced: Optional[Tuple[List[RouteTrace], List[str]]] = None,
                    ) -> Report:
    """Trace every production route and run the (selected) device rules.

    Returns an engine.Report (same fingerprint/baseline/exit contract as
    the AST pass) whose `device` block lists EVERY route with its status —
    no silent route skips.  A route that raises is an ERROR (exit 2).
    `pretraced` reuses a collect_traces() result (the CLI's shared-trace
    path when --device and --shard both run)."""
    from .jaxrules import ALL_DEVICE_RULES

    rules = [cls() for cls in ALL_DEVICE_RULES]
    if rule_ids is not None:
        want = {r.upper() for r in rule_ids}
        rules = [r for r in rules if r.rule_id in want]
    report = Report(rules=[r.rule_id for r in rules])
    traces, trace_errors = (
        pretraced if pretraced is not None else collect_traces(mesh_size)
    )
    report.errors.extend(trace_errors)
    report.files_scanned = len([t for t in traces if t.status == "traced"])
    for r in rules:
        try:
            report.findings.extend(r.check(traces))
        except Exception as e:  # a rule bug must not pass as "clean"
            report.errors.append(
                f"device rule {r.rule_id} crashed: {type(e).__name__}: {e}")
    from .engine import apply_baseline

    apply_baseline(report, baseline)
    report.device = {
        "routes": [t.to_dict() for t in traces],
        "n_traced": sum(1 for t in traces if t.status == "traced"),
        "n_skipped": sum(1 for t in traces if t.status == "skipped"),
    }
    return report
