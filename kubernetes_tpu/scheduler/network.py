"""Service networking: EndpointSlice controller + kube-proxy analog.

reference:
  pkg/controller/endpointslice — reconcile EndpointSlices for each Service
  from the pods its selector matches (ready = Running with an IP), slices
  capped at maxEndpointsPerSlice (default 100), owned by the Service (GC'd
  with it).
  pkg/proxy — the proxier pattern: watch Service/EndpointSlice, rebuild the
  kernel ruleset in one syncProxyRules pass.  Here the "kernel ruleset" is an
  in-memory VIP table: (clusterIP, port) -> ordered backend list; lookup()
  plays the iptables -j DNAT chain walk with random backend choice and
  ClientIP session affinity (the two balancing modes iptables mode supports).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from ..api import cluster as c
from ..api import types as t
from .store import ClusterStore

MAX_ENDPOINTS_PER_SLICE = 100


class EndpointSliceController:
    """pkg/controller/endpointslice — endpoint_slice_controller.go:
    syncService per tick; full reconcile (level-triggered, same trade as the
    other controllers here)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def _endpoints_for(self, svc: c.Service) -> List[c.Endpoint]:
        eps = []
        for pod in self.store.list_pods():
            if not svc.selects(pod):
                continue
            if not pod.node_name:
                continue  # unscheduled pods are never endpoints
            # serving readiness = Running AND the Ready condition the
            # kubelet's prober maintains (False while a readiness probe has
            # not yet passed, or after failure_threshold failures)
            ready = pod.phase in ("", t.PHASE_RUNNING) and pod.ready
            if pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED):
                continue
            address = pod.pod_ip or f"?:{pod.uid}"  # IP pending -> not ready
            if not pod.pod_ip:
                ready = False
            eps.append(
                c.Endpoint(address=address, pod_uid=pod.uid,
                           node_name=pod.node_name, ready=ready)
            )
        eps.sort(key=lambda e: e.address)
        return eps

    def sync_service(self, svc: c.Service) -> None:
        want = self._endpoints_for(svc)
        # chunk into slices of MAX_ENDPOINTS_PER_SLICE
        chunks = [
            tuple(want[i : i + MAX_ENDPOINTS_PER_SLICE])
            for i in range(0, len(want), MAX_ENDPOINTS_PER_SLICE)
        ] or [()]
        existing = {
            s.name: s
            for s in self.store.list_objects("EndpointSlice", svc.namespace)
            if s.service_name == svc.name
        }
        owner = (t.OwnerReference(kind="Service", name=svc.name, uid=svc.uid),)
        wanted_names = {f"{svc.name}-{i}" for i in range(len(chunks))}
        for i, chunk in enumerate(chunks):
            name = f"{svc.name}-{i}"
            current = existing.get(name)
            desired = c.EndpointSlice(
                name=name, namespace=svc.namespace, service_name=svc.name,
                endpoints=chunk, ports=svc.ports, owner_references=owner,
            )
            if current is None:
                self.store.add_object("EndpointSlice", desired)
            elif current.endpoints != chunk or current.ports != svc.ports:
                desired.uid = current.uid
                self.store.update_object("EndpointSlice", desired)
        # delete by name-set membership (a positional sort would misfire past
        # 10 slices: "web-10" < "web-2" lexicographically)
        for s in existing.values():
            if s.name not in wanted_names:
                self.store.delete_object("EndpointSlice", s.key)

    def tick(self) -> None:
        services = self.store.list_objects("Service")
        names = {(s.namespace, s.name) for s in services}
        for svc in services:
            self.sync_service(svc)
        # slices for deleted services (when GC hasn't collected them yet)
        for s in self.store.list_objects("EndpointSlice"):
            if s.service_name and (s.namespace, s.service_name) not in names:
                self.store.delete_object("EndpointSlice", s.key)


@dataclass(frozen=True)
class Rule:
    """One VIP:port service entry in the synced "ruleset"."""

    cluster_ip: str
    port: int
    protocol: str
    session_affinity: str
    backends: Tuple[Tuple[str, int], ...]  # (pod ip, target port), ready only


class Proxier:
    """pkg/proxy/iptables/proxier.go — syncProxyRules reduced to its
    semantics: full rebuild of the VIP table from the watched state, then
    O(1) lookups with per-service probability-chain (random) balancing and
    ClientIP affinity stickiness."""

    def __init__(self, store: ClusterStore, seed: int = 0):
        self.store = store
        self.rules: Dict[Tuple[str, int], Rule] = {}
        self._rng = random.Random(seed)
        self._affinity: Dict[Tuple[str, str, int], Tuple[str, int]] = {}
        self.sync_count = 0

    def sync(self) -> None:
        """One syncProxyRules pass."""
        rules: Dict[Tuple[str, int], Rule] = {}
        slices_by_svc: Dict[Tuple[str, str], List[c.EndpointSlice]] = {}
        for s in self.store.list_objects("EndpointSlice"):
            slices_by_svc.setdefault((s.namespace, s.service_name), []).append(s)
        for svc in self.store.list_objects("Service"):
            if not svc.cluster_ip:
                continue
            eps: List[c.Endpoint] = []
            for s in slices_by_svc.get((svc.namespace, svc.name), []):
                eps.extend(e for e in s.endpoints if e.ready)
            eps.sort(key=lambda e: e.address)
            for port in svc.ports:
                rules[(svc.cluster_ip, port.port)] = Rule(
                    cluster_ip=svc.cluster_ip,
                    port=port.port,
                    protocol=port.protocol,
                    session_affinity=svc.session_affinity,
                    backends=tuple((e.address, port.backend_port) for e in eps),
                )
        self.rules = rules
        # drop affinity entries whose backend vanished (conntrack cleanup)
        self._affinity = {
            k: v
            for k, v in self._affinity.items()
            if any(v in r.backends for r in rules.values())
        }
        self.sync_count += 1

    def lookup(self, client_ip: str, vip: str, port: int) -> Optional[Tuple[str, int]]:
        """Route one connection: -> (pod ip, port) or None (REJECT: no
        endpoints — iptables' -j REJECT for empty services)."""
        rule = self.rules.get((vip, port))
        if rule is None or not rule.backends:
            return None
        if rule.session_affinity == "ClientIP":
            key = (client_ip, vip, port)
            prev = self._affinity.get(key)
            if prev is not None and prev in rule.backends:
                return prev
            chosen = self._rng.choice(rule.backends)
            self._affinity[key] = chosen
            return chosen
        return self._rng.choice(rule.backends)
