"""Scheduling queue: activeQ + backoffQ + unschedulablePods.

Analog of pkg/scheduler/backend/queue/scheduling_queue.go — PriorityQueue:

  - activeQ: heap ordered by the queue-sort plugin's Less (priority desc, then
    arrival — PrioritySort)
  - backoffQ: pods recently failed, re-activated after an exponential backoff
    (1s initial, doubling, 10s cap — DefaultPodInitialBackoffDuration /
    DefaultPodMaxBackoffDuration)
  - unschedulablePods: pods that failed with no backoff pending; moved back to
    activeQ/backoffQ when a cluster event that might make them schedulable
    arrives (MoveAllToActiveOrBackoffQueue), filtered through per-plugin
    QueueingHint callbacks — each registered plugin's (event, obj, old, pod)
    -> Queue/Skip hint, so irrelevant churn (e.g. a Node update that shrinks
    allocatable) wakes nobody (isSchedulableAfterNodeChange analogs)

A injectable clock makes backoff deterministic in tests (the reference uses
k8s.io/utils/clock/testing the same way — SURVEY.md §4).
"""

from __future__ import annotations

import functools
import heapq
import itertools
import random
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api import types as t
from ..analysis.lockcheck import make_rlock

INITIAL_BACKOFF_S = 1.0
MAX_BACKOFF_S = 10.0

# Cluster event kinds (framework/types.go — ClusterEvent); plugins that fail a
# pod register which events may resolve the failure (EventsToRegister).
EV_NODE_ADD = "Node/Add"
EV_NODE_UPDATE = "Node/Update"
EV_POD_DELETE = "Pod/Delete"
EV_POD_ADD = "Pod/Add"
EV_ALL = "*"


class Clock:
    def now(self) -> float:
        return _time.monotonic()


class FakeClock(Clock):
    def __init__(self, start: float = 0.0):
        self.t = start

    def now(self) -> float:
        return self.t

    def step(self, dt: float) -> None:
        self.t += dt


@dataclass(order=True)
class _Item:
    sort_key: Tuple
    pod: t.Pod = field(compare=False)


def _locked(fn):
    """Run the method under the queue's re-entrant lock."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


class PriorityQueue:
    """Thread-safe: binding-cycle workers requeue/denominate concurrently with
    the scheduling thread's pop (the reference's queue takes its own lock —
    scheduling_queue.go guards activeQ/backoffQ with sync.Cond)."""

    def __init__(self, clock: Optional[Clock] = None, tracer=None,
                 initial_backoff_s: float = INITIAL_BACKOFF_S,
                 max_backoff_s: float = MAX_BACKOFF_S,
                 backoff_jitter: float = 0.0, jitter_seed: int = 0):
        self._lock = make_rlock("PriorityQueue._lock")
        self.clock = clock or Clock()
        # exponential backoff base/cap (podInitialBackoffSeconds /
        # podMaxBackoffSeconds — wired from SchedulerConfiguration), plus a
        # multiplicative jitter fraction: each push matures at
        # duration * (1 + U[0, jitter)).  A FIXED backoff synchronizes the
        # retry storm after a correlated failure (e.g. a sidecar outage
        # parks a whole wave at once, and 1 s later the whole wave retries
        # in one thundering cycle); jitter de-correlates the retries.  The
        # RNG is seeded so runs are reproducible — backoff_duration() stays
        # the pure base for tests/introspection, jitter applies at push.
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.backoff_jitter = backoff_jitter
        self._jitter_rng = random.Random(jitter_seed)
        # queue-wait spans (enqueue -> pop) per pod, joining the pod's trace
        # (scheduler/tracing.py); timestamps are real perf_counter values —
        # span time is wall attribution, independent of the injectable
        # backoff clock
        self._tracer = tracer
        self._enq_at: Dict[str, float] = {}
        # uid -> first-admission perf_counter instant, kept across retries:
        # the arrival half of the pod_scheduling_sli_duration_seconds SLI
        # (metrics.go — arrival -> bind).  Unlike _enq_at this is stamped
        # UNCONDITIONALLY (the SLI is metrics-first, not gated on tracing)
        # and consumed/popped at bind publication (take_arrival) or delete,
        # so the table stays bounded by in-flight pods.
        self._arrival_at: Dict[str, float] = {}
        # uid -> latest activeQ-pop instant (tracer-gated, like _enq_at):
        # the queue_wait/wave_wait boundary of the per-pod SLI phase
        # decomposition (pod_sli_phase_duration_seconds — scheduler.py
        # _observe_sli_phases).  Consumed at bind publication (take_popped)
        # or delete, so the table stays bounded like _arrival_at.
        self._popped_at: Dict[str, float] = {}
        # uids whose pop stamp was restored from a checkpoint
        # (restore_popped): the post-restore re-pop must NOT overwrite it —
        # the pod already left the queue once, in the dead leader, and its
        # queue_wait ended there; everything after (blackout included) is
        # wave_wait.  Cleared with the stamp at take_popped/delete.
        self._popped_pinned: Set[str] = set()
        self._seq = itertools.count()
        self._active: List[_Item] = []  # heap
        self._active_uids: Set[str] = set()
        self._backoff: List[Tuple[float, int, t.Pod]] = []  # (ready_at, seq, pod)
        # uid -> (pod, events, hints); hints: event kind -> [(obj, old, pod)
        # -> bool] callbacks (QueueingHintFn — scheduling_queue.go: a parked
        # pod wakes on a registered event only if SOME failing plugin's hint
        # answers Queue; hintless kinds wake unconditionally)
        self._unschedulable: Dict[str, Tuple[t.Pod, Set[str], Dict]] = {}
        self._attempts: Dict[str, int] = {}
        self._arrival: Dict[str, int] = {}
        self._nominated: Dict[str, Tuple[t.Pod, str]] = {}  # uid -> (pod, node)
        # uid -> count of STALE backoff entries to swallow (set at delete();
        # entries pushed before the delete mature earlier than any pushed
        # after a re-add, so draining by count pairs them correctly)
        self._gone: Dict[str, int] = {}
        self._in_backoff: Dict[str, int] = {}  # uid -> live backoff entries
        self._parked_at: Dict[str, float] = {}  # uid -> when parked unschedulable
        # gate-parked pods (backoff=False, e.g. SchedulingGates) wait for
        # their re-add event only — the leftover flush must NOT resurrect
        # them past PreEnqueue
        self._no_flush: Set[str] = set()
        # bumps on every move_all_to_active_or_backoff: schedulers compare
        # against their cycle-start value to detect a move that fired while
        # the cycle ran (the reference's moveRequestCycle guard)
        self.move_seq = 0
        # flushUnschedulablePodsLeftover: parked pods whose events never fire
        # retry anyway after this long (podMaxInUnschedulablePodsDuration, 5m)
        self.max_unschedulable_s = 300.0

    @_locked
    def __len__(self) -> int:
        self._flush_backoff()
        return len(self._active)

    @property
    @_locked
    def pending_total(self) -> int:
        return len(self._active) + len(self._backoff) + len(self._unschedulable)

    @_locked
    def depths(self) -> Dict[str, int]:
        """Per-pool depths in ONE lock acquisition — the queue-pool
        observability sample the batch cycle stamps onto /metrics at each
        cycle boundary (scheduler.py — _sample_queue_depths).  `parked` is
        the backoff+unschedulable union the deferred-commit gate keys on;
        the pools are reported separately so an operator can tell a retry
        storm (backoff) from an event-starved park (unschedulable).
        Matured backoff entries flush first (the __len__/pop convention) —
        a pod whose backoff just expired is activeQ work THIS cycle, and
        reporting it as backoff would under-count the peak at exactly the
        retry-storm moment these gauges diagnose."""
        self._flush_backoff()
        return {
            "active": len(self._active_uids),
            "backoff": len(self._backoff),
            "unschedulable": len(self._unschedulable),
            "parked": len(self._backoff) + len(self._unschedulable),
        }

    @property
    @_locked
    def parked_total(self) -> int:
        """Pods waiting OUTSIDE the activeQ (backoff + unschedulable) — the
        set a cluster-event move could wake.  The batch cycle's deferred
        commit fan-out (scheduler.py — _flush_deferred_binds) is exactly
        serial-equivalent only when this is 0: with nobody parked, the
        deferred binds' AssignedPodAdd moves are no-ops, so delaying them
        into the next device step's window cannot change any queue state."""
        return len(self._backoff) + len(self._unschedulable)

    def _key(self, pod: t.Pod) -> Tuple:
        # PrioritySort.Less: higher priority first, then FIFO by first arrival
        arr = self._arrival.setdefault(pod.uid, next(self._seq))
        return (-pod.priority, arr)

    @_locked
    def add(self, pod: t.Pod) -> None:
        if pod.uid in self._active_uids:
            return
        # a re-added pod supersedes any parked copy (AddUnschedulableIfNotPresent
        # dedupe — without this the leftover flush could resurrect a stale copy)
        self._unschedulable.pop(pod.uid, None)
        self._parked_at.pop(pod.uid, None)
        self._no_flush.discard(pod.uid)
        heapq.heappush(self._active, _Item(self._key(pod), pod))
        self._active_uids.add(pod.uid)
        # first admission wins across retries: arrival -> bind is the SLI
        self._arrival_at.setdefault(pod.uid, _time.perf_counter())
        if self._tracer is not None and self._tracer.enabled:
            # first activation wins: a superseding re-add keeps the original
            # enqueue instant (the wait the pod actually experienced)
            self._enq_at.setdefault(pod.uid, _time.perf_counter())

    def _flush_backoff(self) -> None:
        now = self.clock.now()
        # flushUnschedulablePodsLeftover: event-parked pods retry eventually
        # even if their registered events never fire
        for uid, since in list(self._parked_at.items()):
            if uid not in self._unschedulable:
                del self._parked_at[uid]
            elif uid in self._no_flush:
                continue  # gated: only its registered event may move it
            elif now - since >= self.max_unschedulable_s:
                pod = self._unschedulable.pop(uid)[0]
                del self._parked_at[uid]
                self._push_backoff(pod)
        while self._backoff and self._backoff[0][0] <= now:
            _, _, pod = heapq.heappop(self._backoff)
            left = self._in_backoff.get(pod.uid, 1) - 1
            if left > 0:
                self._in_backoff[pod.uid] = left
            else:
                self._in_backoff.pop(pod.uid, None)
            stale = self._gone.get(pod.uid, 0)
            if stale > 0:
                if stale > 1:
                    self._gone[pod.uid] = stale - 1
                else:
                    del self._gone[pod.uid]
                continue
            self.add(pod)

    def _pop_one(self) -> Optional[t.Pod]:
        """Heap-drain step shared by pop()/pop_all() (caller holds the lock):
        skip superseded entries, bump the attempt counter."""
        while self._active:
            item = heapq.heappop(self._active)
            if item.pod.uid in self._active_uids:
                self._active_uids.discard(item.pod.uid)
                self._attempts[item.pod.uid] = self._attempts.get(item.pod.uid, 0) + 1
                tr = self._tracer
                if tr is not None and tr.enabled:
                    # latest pop wins: after a retry the wait that counts
                    # toward queue_wait is everything up to the pop that
                    # finally led to the bind — EXCEPT a checkpoint-restored
                    # stamp (pinned): the pod's queue_wait ended in the dead
                    # leader, and the re-pop is wave replay, not queueing
                    if item.pod.uid not in self._popped_pinned:
                        self._popped_at[item.pod.uid] = _time.perf_counter()
                    t0 = self._enq_at.pop(item.pod.uid, None)
                    if t0 is not None:
                        # enqueue -> pop as a finished span on the pod's
                        # trace chain (attempt = retry ordinal)
                        tr.record_span(
                            "queue.wait", start=t0, pod_uid=item.pod.uid,
                            pod=item.pod.uid,
                            attempt=self._attempts[item.pod.uid],
                        )
                return item.pod
        return None

    @_locked
    def pop(self) -> Optional[t.Pod]:
        """Next pod in activeQ order, or None if activeQ is empty
        (scheduling_queue.go — Pop; non-blocking variant)."""
        self._flush_backoff()
        return self._pop_one()

    @_locked
    def pop_all(self) -> List[t.Pod]:
        """Drain the activeQ in pop order under ONE lock acquisition — the
        batch cycle's bulk Pop (the reference pops one pod per cycle; the
        batched path would otherwise pay P lock round-trips per cycle)."""
        self._flush_backoff()
        out: List[t.Pod] = []
        while True:
            pod = self._pop_one()
            if pod is None:
                return out
            out.append(pod)

    @_locked
    def backoff_duration(self, pod_uid: str) -> float:
        n = max(0, self._attempts.get(pod_uid, 1) - 1)
        return min(self.max_backoff_s, self.initial_backoff_s * (2**n))

    def _push_backoff(self, pod: t.Pod) -> None:
        """Enter the backoffQ (caller holds the lock): jittered maturity —
        duration * (1 + U[0, jitter)), base already capped at
        max_backoff_s — so correlated failures fan their retries out
        instead of re-arriving as one storm."""
        d = self.backoff_duration(pod.uid)
        if self.backoff_jitter > 0.0:
            d *= 1.0 + self._jitter_rng.random() * self.backoff_jitter
        heapq.heappush(self._backoff, (self.clock.now() + d, next(self._seq), pod))
        self._in_backoff[pod.uid] = self._in_backoff.get(pod.uid, 0) + 1

    @_locked
    def add_unschedulable(self, pod: t.Pod, events: Optional[Set[str]] = None,
                          backoff: bool = True,
                          cycle_move_seq: Optional[int] = None,
                          hints: Optional[Dict] = None) -> None:
        """AddUnschedulableIfNotPresent.  With SPECIFIC events (QueueingHint
        registrations from the failing plugins) the pod parks in
        unschedulablePods until a matching cluster event moves it (through
        backoff) or the leftover flush expires; without them (or with only
        the wildcard) it takes the plain backoff retry path.

        cycle_move_seq is the caller's cycle-start move_seq: compared against
        the live value HERE, under the queue lock (the reference's
        moveRequestCycle guard inside AddUnschedulableIfNotPresent) — a move
        that fired during the cycle means the pod's wake event may already be
        gone, so it takes the plain backoff path instead of parking."""
        if cycle_move_seq is not None and self.move_seq != cycle_move_seq:
            events = None
        # gate-parked pods (backoff=False) enter here without ever passing
        # add(): their SLI clock starts at first admission too
        self._arrival_at.setdefault(pod.uid, _time.perf_counter())
        if events and EV_ALL not in events and backoff:
            self._unschedulable[pod.uid] = (pod, set(events), hints or {})
            self._parked_at[pod.uid] = self.clock.now()
        elif backoff:
            self._push_backoff(pod)
        else:
            self._unschedulable[pod.uid] = (pod, events or {EV_ALL}, hints or {})
            self._parked_at[pod.uid] = self.clock.now()
            self._no_flush.add(pod.uid)

    @_locked
    def move_all_to_active_or_backoff(self, event: str, obj=None, old=None) -> int:
        """MoveAllToActiveOrBackoffQueue on a cluster event; returns #moved.

        With the event OBJECT available, a parked pod's per-plugin
        QueueingHint callbacks decide Queue vs Skip (isPodWorthRequeuing);
        without it (obj None — e.g. a coalesced batch flush) matching event
        kinds wake unconditionally, the pre-hint conservative behavior."""
        self.move_seq += 1
        moved = []
        for uid, (pod, events, hints) in list(self._unschedulable.items()):
            if EV_ALL in events or event in events:
                fns = hints.get(event)
                if obj is not None and fns:
                    try:
                        if not any(fn(obj, old, pod) for fn in fns):
                            continue  # every failing plugin answered Skip
                    except Exception:  # noqa: BLE001 — hint bugs must not strand pods
                        pass
                moved.append(uid)
                del self._unschedulable[uid]
                self._parked_at.pop(uid, None)
                self._no_flush.discard(uid)
                self._push_backoff(pod)
        return len(moved)

    @_locked
    def take_arrival(self, pod_uid: str) -> Optional[float]:
        """Pop and return the pod's first-admission instant — called at
        bind publication so the SLI table never outlives the pods it
        tracks (a later re-add of the same uid restarts the clock)."""
        return self._arrival_at.pop(pod_uid, None)

    @_locked
    def take_popped(self, pod_uid: str) -> Optional[float]:
        """Pop and return the pod's latest activeQ-pop instant — the
        queue_wait/wave_wait boundary of the SLI phase decomposition.
        None when tracing was off or the pod never popped (same lifecycle
        as the queue.wait span it pairs with)."""
        self._popped_pinned.discard(pod_uid)
        return self._popped_at.pop(pod_uid, None)

    @_locked
    def stamp_arrival(self, pod_uid: str, at: float) -> None:
        """Override the pod's first-admission instant with an EXTERNAL
        arrival timestamp (perf_counter domain, possibly in the past) —
        the open-loop replay's coordinated-omission-safe clock
        (bench/loadgen.py): SLI age is measured from the TRACE arrival
        instant, never from send time, so a stalled replay cycle inflates
        p99 honestly instead of hiding the backlog.  Earliest stamp wins,
        matching add()'s first-admission-wins contract in either call
        order."""
        cur = self._arrival_at.get(pod_uid)
        if cur is None or at < cur:
            self._arrival_at[pod_uid] = at

    # --- crash-restart SLI continuity (scheduler/checkpoint.py) ---
    @_locked
    def export_arrivals(self) -> Dict[str, float]:
        """Per-pod first-admission AGE (seconds waited so far) for the
        checkpoint: ages are relative, so the restoring process's
        perf_counter base never needs to match the dead one's."""
        now = _time.perf_counter()
        return {uid: now - t for uid, t in self._arrival_at.items()}

    @_locked
    def restore_arrivals(self, ages: Dict[str, float]) -> int:
        """Re-base checkpointed admission ages onto this process's clock —
        a requeued pod's arrival->bind SLI keeps the wait it already
        served (failover inflates p99 honestly instead of restarting the
        clock).  Only pods the watch replay re-admitted are touched: a
        stale checkpoint entry for a pod that no longer exists must not
        seed an unbounded table.  Returns #restored."""
        now = _time.perf_counter()
        n = 0
        for uid, age in ages.items():
            if uid in self._arrival_at:
                self._arrival_at[uid] = now - max(0.0, float(age))
                n += 1
        return n

    @_locked
    def export_popped(self) -> Dict[str, float]:
        """Per-pod latest activeQ-pop AGE for the checkpoint — the
        queue_wait/wave_wait SLI-phase boundary rides the crash-restart
        state (same age-relative convention as export_arrivals).  Empty
        when tracing is off (the table is tracer-gated)."""
        now = _time.perf_counter()
        return {uid: now - t for uid, t in self._popped_at.items()}

    @_locked
    def restore_popped(self, ages: Dict[str, float]) -> int:
        """Re-base checkpointed pop stamps onto this process's clock and
        PIN them: a pod popped into a wave pre-kill keeps its original
        queue_wait — the takeover blackout and the replay re-pop both land
        in wave_wait, where the dead time actually passed (the telescoping
        invariant tests/test_storm_streaming.py asserts).  Gated like
        restore_arrivals on the watch replay having re-admitted the pod.
        Returns #restored."""
        now = _time.perf_counter()
        n = 0
        for uid, age in ages.items():
            if uid in self._arrival_at:
                self._popped_at[uid] = now - max(0.0, float(age))
                self._popped_pinned.add(uid)
                n += 1
        return n

    @_locked
    def delete(self, pod_uid: str) -> None:
        self._active_uids.discard(pod_uid)
        self._enq_at.pop(pod_uid, None)
        self._arrival_at.pop(pod_uid, None)
        self._popped_at.pop(pod_uid, None)
        self._popped_pinned.discard(pod_uid)
        self._unschedulable.pop(pod_uid, None)
        self._parked_at.pop(pod_uid, None)
        self._no_flush.discard(pod_uid)
        self._nominated.pop(pod_uid, None)
        if self._in_backoff.get(pod_uid):
            # every entry currently in backoff predates this delete: all stale
            self._gone[pod_uid] = self._in_backoff[pod_uid]

    # --- nominator (scheduling_queue.go — nominator: AddNominatedPod /
    # DeleteNominatedPodIfExists / NominatedPodsForNode) ---
    @_locked
    def add_nominated(self, pod: t.Pod, node_name: str) -> None:
        self._nominated[pod.uid] = (pod, node_name)

    @_locked
    def delete_nominated(self, pod_uid: str) -> None:
        self._nominated.pop(pod_uid, None)

    @_locked
    def nominated_pods_for_node(self, node_name: str) -> List[t.Pod]:
        return [p for p, n in self._nominated.values() if n == node_name]

    @property
    @_locked
    def nominated(self) -> Dict[str, Tuple[t.Pod, str]]:
        return dict(self._nominated)
