"""Checkpoint manager — fsynced JSON + checksum files.

reference: pkg/kubelet/checkpointmanager (file-based, checksummed state that
survives restarts) as used by cm/devicemanager; here it checkpoints the
scheduler's assumed-pod ledger so a restarted scheduler doesn't double-place
in-flight binds before its watch catches up (SURVEY.md §5 checkpoint note:
"device-allocation-style checkpoint only for the assumed-pod ledger").
Everything else is crash-only: caches rebuild from LIST+WATCH.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, Optional


class CheckpointManager:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.json")

    def save(self, name: str, data: Dict) -> None:
        payload = json.dumps(data, sort_keys=True)
        doc = json.dumps(
            {"checksum": hashlib.sha256(payload.encode()).hexdigest(), "data": data},
            sort_keys=True,
        )
        fd, tmp = tempfile.mkstemp(dir=self.directory)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(doc)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(name))  # atomic
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def load(self, name: str) -> Optional[Dict]:
        """None when absent or corrupt (a corrupt checkpoint is discarded —
        crash-only: the caller rebuilds from the watch)."""
        try:
            with open(self._path(name)) as f:
                doc = json.load(f)
            payload = json.dumps(doc["data"], sort_keys=True)
            if hashlib.sha256(payload.encode()).hexdigest() != doc["checksum"]:
                return None
            return doc["data"]
        except (OSError, ValueError, KeyError):
            return None


def save_assumed(cm: CheckpointManager, assumed: Dict[str, str]) -> None:
    cm.save("assumed_pods", {"assumed": assumed})


def load_assumed(cm: CheckpointManager) -> Dict[str, str]:
    doc = cm.load("assumed_pods")
    return dict(doc["assumed"]) if doc else {}
