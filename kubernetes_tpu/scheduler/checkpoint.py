"""Checkpoint manager — fsynced JSON + checksum files.

reference: pkg/kubelet/checkpointmanager (file-based, checksummed state that
survives restarts) as used by cm/devicemanager; here it checkpoints the
scheduler's crash-restart state so a restarted scheduler doesn't double-place
in-flight binds before its watch catches up (SURVEY.md §5 checkpoint note:
"device-allocation-style checkpoint only for the assumed-pod ledger").
Everything else is crash-only: caches rebuild from LIST+WATCH.

The scheduler's checkpoint (save_scheduler_state / load_scheduler_state,
wired through Scheduler._checkpoint_state) carries exactly the state the
watch CANNOT reconstruct:

  assumed    the assumed-pod ledger (uid -> node): reservations whose bind
             publication may not have landed — restore() reconciles each
             against the store (bound: retired; unbound: requeued)
  wal        write-ahead record of in-flight deferred commits
             [(uid, node), ...]: a verdict that was durably decided but
             whose store publication rides the next cycle's device window.
             Replay is idempotent by construction (an already-bound entry
             is skipped), which with the append-before-publish ordering
             gives exactly-once application across any kill point.
  arrivals   per-pod first-admission AGE (uid -> seconds since admission at
             save time): the arrival half of the arrival->bind SLI rides
             the checkpoint, so a failover inflates p99 honestly instead of
             restarting the clock for requeued pods
  saved_at   host perf_counter at save (provenance/debugging only — ages
             are relative so clock bases never need to match)

A corrupt or truncated checkpoint is QUARANTINED, not silently discarded:
load() renames the bad file to `<name>.json.corrupt`, klogs a warning and
bumps `checkpoint_corrupt_total`, then returns None so the caller rebuilds
crash-only — operators get evidence, the scheduler gets a clean slate.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple


class CheckpointManager:
    def __init__(self, directory: str, metrics=None, logger=None):
        self.directory = directory
        # observability is optional: a bare CheckpointManager stays usable
        # (devicemanager-style callers), the scheduler threads its own
        self.metrics = metrics
        self.log = logger
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.json")

    def save(self, name: str, data: Dict) -> None:
        payload = json.dumps(data, sort_keys=True)
        doc = json.dumps(
            {"checksum": hashlib.sha256(payload.encode()).hexdigest(), "data": data},
            sort_keys=True,
        )
        fd, tmp = tempfile.mkstemp(dir=self.directory)
        try:
            with os.fdopen(fd, "w") as f:
                f.write(doc)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(name))  # atomic
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def _quarantine(self, name: str, reason: str) -> None:
        """A checkpoint that fails to parse or verify is EVIDENCE: move it
        aside as <name>.json.corrupt (overwriting an older quarantine —
        the newest corpse is the useful one), warn, and count it."""
        path = self._path(name)
        try:
            os.replace(path, path + ".corrupt")
            moved = True
        except OSError:
            moved = False  # raced away / unreadable dir: nothing to keep
        if self.metrics is not None:
            self.metrics.inc("checkpoint_corrupt_total")
        if self.log is not None:
            self.log.V(0).error(
                "Corrupt checkpoint quarantined; rebuilding crash-only",
                checkpoint=name, reason=reason,
                quarantine=(path + ".corrupt") if moved else "",
            )

    def load(self, name: str) -> Optional[Dict]:
        """None when absent or corrupt (the caller rebuilds from the watch —
        crash-only); a corrupt file is quarantined as <name>.json.corrupt
        with a klog warning + checkpoint_corrupt_total bump, never silently
        swallowed."""
        try:
            with open(self._path(name)) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None  # absent is the normal first boot, not corruption
        except OSError as e:
            # transient READ failure (EIO, EACCES, ...): the file may be a
            # perfectly valid checkpoint — leave it in place for a retry or
            # an operator, never destroy the WAL over an I/O hiccup
            if self.log is not None:
                self.log.V(0).error(
                    "Checkpoint unreadable, left in place; rebuilding "
                    "crash-only", checkpoint=name, reason=str(e),
                )
            return None
        except ValueError as e:  # json parse (UnicodeDecodeError included)
            self._quarantine(name, f"{type(e).__name__}: {e}")
            return None
        try:
            payload = json.dumps(doc["data"], sort_keys=True)
            if hashlib.sha256(payload.encode()).hexdigest() != doc["checksum"]:
                self._quarantine(name, "checksum mismatch")
                return None
            return doc["data"]
        except (ValueError, KeyError, TypeError) as e:
            self._quarantine(name, f"{type(e).__name__}: {e}")
            return None


def save_assumed(cm: CheckpointManager, assumed: Dict[str, str]) -> None:
    cm.save("assumed_pods", {"assumed": assumed})


def load_assumed(cm: CheckpointManager) -> Dict[str, str]:
    doc = cm.load("assumed_pods")
    return dict(doc["assumed"]) if doc else {}


# --- the scheduler's crash-restart checkpoint (one file, one fsync) ---
SCHEDULER_STATE = "scheduler_state"


def save_scheduler_state(
    cm: CheckpointManager,
    assumed: Dict[str, str],
    wal: List[Tuple[str, str]],
    arrivals: Dict[str, float],
    lineage: str = "",
    wave: Optional[Dict] = None,
    cursor: Optional[Dict] = None,
    popped: Optional[Dict[str, float]] = None,
) -> None:
    cm.save(
        SCHEDULER_STATE,
        {
            # cluster lineage (store.py — ClusterStore.lineage): uids are
            # deterministic, so restore() must refuse to replay this state
            # into a DIFFERENT cluster whose uids merely collide
            "lineage": str(lineage),
            "assumed": dict(assumed),
            "wal": [[uid, node] for uid, node in wal],
            "arrivals": dict(arrivals),
            # wave WAL (streaming crash-consistency): the in-flight commit
            # wave's membership + verdict crc ({"uids": [...],
            # "verdict_crc": str}), present only while a wave is between
            # verdict and full publication — restore() splits it into the
            # published prefix (store shows the bind), the durable suffix
            # (deferred-bind wal above) and the requeued remainder
            "wave": dict(wave) if wave else None,
            # open-loop replay cursor ({"v_now", "i", "trace_crc",
            # "scenario"}): the arrival trace's virtual clock + event offset
            # ride the checkpoint so a standby resumes the replay at the
            # exact trace position the leader died at (bench/loadgen.py)
            "cursor": dict(cursor) if cursor else None,
            # per-pod latest activeQ-pop AGE (uid -> seconds): the
            # queue_wait/wave_wait SLI boundary — restored so a pod popped
            # into a wave pre-kill keeps its original queue_wait and the
            # blackout lands in wave_wait, not queue_wait
            "popped": dict(popped) if popped else {},
            "saved_at": time.perf_counter(),
            # wall clock of the save: restore adds (now_wall - saved_wall)
            # to every arrival age so the BLACKOUT — the dead time between
            # the last checkpoint and the takeover — counts toward the SLI
            # (ages alone would silently forgive it)
            "saved_wall": time.time(),
        },
    )


def load_scheduler_state(cm: CheckpointManager) -> Optional[Dict]:
    """The checkpoint doc with every field defaulted, or None when absent/
    corrupt (corruption was quarantined + counted by load())."""
    doc = cm.load(SCHEDULER_STATE)
    if doc is None:
        return None
    wave = doc.get("wave") or None
    cursor = doc.get("cursor") or None
    return {
        "lineage": str(doc.get("lineage") or ""),
        "assumed": dict(doc.get("assumed") or {}),
        "wal": [(str(u), str(n)) for u, n in (doc.get("wal") or [])],
        "arrivals": {
            str(k): float(v) for k, v in (doc.get("arrivals") or {}).items()
        },
        "wave": {
            "uids": [str(u) for u in (wave.get("uids") or [])],
            "verdict_crc": str(wave.get("verdict_crc") or ""),
        } if isinstance(wave, dict) else None,
        "cursor": dict(cursor) if isinstance(cursor, dict) else None,
        "popped": {
            str(k): float(v) for k, v in (doc.get("popped") or {}).items()
        },
        "saved_at": float(doc.get("saved_at") or 0.0),
        "saved_wall": float(doc.get("saved_wall") or 0.0),
    }
