from .framework import Framework, Status, CycleState  # noqa: F401
from .config import SchedulerConfiguration, Profile  # noqa: F401
from .scheduler import (  # noqa: F401
    Scheduler,
    reincarnate,
    restart_scheduler,
    run_ha_restartable,
    run_restartable,
)
from .store import ClusterStore  # noqa: F401
from .controllers import ControllerManager  # noqa: F401
from .kubelet import HollowCluster, HollowKubelet  # noqa: F401
from .disruption import DisruptionController  # noqa: F401
