"""Workload controllers + garbage collector — the kube-controller-manager
analog (SURVEY.md §2.3: "each controller = informer→workqueue→sync loop").

Representative set per the reference's pkg/controller/*:

  ReplicaSetController   replica_set.go — syncReplicaSet/manageReplicas:
                         diff desired vs actual owned pods, create/delete
  DeploymentController   deployment/ — rollout via template-hashed ReplicaSets
                         (RollingUpdate with maxSurge/maxUnavailable)
  JobController          job/ — run pods to completion (completions/parallelism)
  GarbageCollector       garbagecollector/ — cascading delete of orphans whose
                         controller ownerReference points at a vanished owner

The workqueue is collapsed to a full reconcile pass per tick() — the same
level-triggered semantics (sync is idempotent, diff-driven), minus the
per-key scheduling, which only matters for fairness at scale.
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from ..api import types as t
from .store import ClusterStore, _key_of


def _is_finished(pod: t.Pod) -> bool:
    return pod.phase in (t.PHASE_SUCCEEDED, t.PHASE_FAILED)


def _is_ready(pod: t.Pod) -> bool:
    """Bound and running ("" phase = harness objects without lifecycle)."""
    return bool(pod.node_name) and pod.phase in ("", t.PHASE_RUNNING)


def _controller_of(pod: t.Pod) -> Optional[t.OwnerReference]:
    for ref in pod.owner_references:
        if ref.controller:
            return ref
    return None


def _stamp(template: t.Pod, name: str, namespace: str, owner: t.OwnerReference) -> t.Pod:
    import copy

    q = copy.copy(template)
    q.name = name
    q.namespace = namespace
    q.node_name = ""
    q.phase = t.PHASE_PENDING
    q.owner_references = (owner,)
    q.uid = f"{namespace}/{name}"
    q.labels = dict(template.labels)
    return q


class ReplicaSetController:
    """replica_set.go — syncReplicaSet: adopt matching orphans, then
    manageReplicas (create the shortfall / delete the excess, preferring
    pending and unready pods for deletion — getPodsToDelete's ranking)."""

    def __init__(self, store: ClusterStore):
        self.store = store
        self._seq = itertools.count()

    def _owned(self, rs: t.ReplicaSet) -> List[t.Pod]:
        out = []
        for pod in self.store.pods.values():
            if pod.namespace != rs.namespace:
                continue
            ctrl = _controller_of(pod)
            if ctrl is not None:
                if ctrl.uid == rs.uid:
                    out.append(pod)
            elif rs.selector is not None and rs.selector.matches(pod.labels):
                # adoption: matching orphan gains the controller ref
                import copy

                q = copy.copy(pod)
                q.owner_references = (
                    t.OwnerReference(kind="ReplicaSet", name=rs.name, uid=rs.uid),
                )
                self.store.update_pod(q)
                out.append(q)
        return out

    def sync(self, rs: t.ReplicaSet) -> None:
        owned = self._owned(rs)
        active = [p for p in owned if not _is_finished(p)]
        diff = rs.replicas - len(active)
        if diff > 0:
            owner = t.OwnerReference(kind="ReplicaSet", name=rs.name, uid=rs.uid)
            for _ in range(diff):
                name = f"{rs.name}-{next(self._seq):05d}"
                self.store.add_pod(
                    _stamp(rs.template or t.Pod(name="x"), name, rs.namespace, owner)
                )
        elif diff < 0:
            # delete excess: pending (unscheduled) first, then unready, then by name
            ranked = sorted(
                active,
                key=lambda p: (bool(p.node_name), _is_ready(p), p.name),
            )
            doomed = ranked[: -rs.replicas] if rs.replicas else ranked
            for p in doomed:
                self.store.delete_pod(p.uid)
            gone = {p.uid for p in doomed}
            active = [p for p in active if p.uid not in gone]
        ready = sum(1 for p in active if _is_ready(p))
        if ready != rs.ready_replicas:
            self.store.update_workload("ReplicaSet", replace(rs, ready_replicas=ready))

    def tick(self) -> None:
        for rs in list(self.store.replicasets.values()):
            self.sync(rs)


def _template_hash(template: Optional[t.Pod]) -> str:
    """pod-template-hash: stable digest of the rollout-relevant template
    fields (deployment_util.go — ComputeHash)."""
    if template is None:
        return "0"
    h = hashlib.sha256()
    h.update(repr((
        sorted(template.requests.items()),
        sorted(template.labels.items()),
        template.tolerations,
        template.node_selector,
        template.affinity,
        template.topology_spread,
        template.priority,
        template.host_ports,
        template.pvcs,
        template.resource_claims,
        template.scheduling_gates,
        template.images,
        template.run_seconds,
    )).encode())
    return h.hexdigest()[:10]


class DeploymentController:
    """deployment/sync.go — getAllReplicaSetsAndSyncRevision + the rolling
    update loop (rolling.go — reconcileNewReplicaSet/reconcileOldReplicaSets):
    scale the template-hashed new RS up within maxSurge, old RSes down within
    maxUnavailable, delete old RSes once drained."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def sync(self, d: t.Deployment) -> None:
        hash_ = _template_hash(d.template)
        new_name = f"{d.name}-{hash_}"
        mine = [
            rs
            for rs in self.store.replicasets.values()
            if rs.namespace == d.namespace
            and any(r.uid == d.uid for r in rs.owner_references)
        ]
        new_rs = next((rs for rs in mine if rs.name == new_name), None)
        if new_rs is None:
            tmpl = None
            if d.template is not None:
                import copy

                tmpl = copy.copy(d.template)
                tmpl.labels = {**d.template.labels, "pod-template-hash": hash_}
            sel = d.selector or (
                t.LabelSelector.of(**d.template.labels) if d.template else None
            )
            new_rs = t.ReplicaSet(
                name=new_name,
                namespace=d.namespace,
                replicas=0,
                selector=sel,
                template=tmpl,
                owner_references=(
                    t.OwnerReference(kind="Deployment", name=d.name, uid=d.uid),
                ),
            )
            self.store.add_workload("ReplicaSet", new_rs)
        old = [rs for rs in mine if rs.name != new_name]

        total = new_rs.replicas + sum(rs.replicas for rs in old)
        ready_total = new_rs.ready_replicas + sum(rs.ready_replicas for rs in old)
        if new_rs.replicas > d.replicas:
            # the Deployment itself was scaled down: shrink the new RS directly
            self.store.update_workload(
                "ReplicaSet", replace(new_rs, replicas=d.replicas)
            )
        else:
            # scale new RS up within the surge budget
            allowed = d.replicas + d.max_surge - total
            if allowed > 0 and new_rs.replicas < d.replicas:
                grown = min(d.replicas, new_rs.replicas + allowed)
                self.store.update_workload(
                    "ReplicaSet", replace(new_rs, replicas=grown)
                )
        # scale old RSes down within the availability budget
        can_remove = ready_total - (d.replicas - d.max_unavailable)
        for rs in sorted(old, key=lambda r: r.name):
            if can_remove <= 0:
                break
            if rs.replicas > 0:
                drop = min(rs.replicas, can_remove)
                self.store.update_workload(
                    "ReplicaSet", replace(rs, replicas=rs.replicas - drop)
                )
                can_remove -= drop
        for rs in old:
            if rs.replicas == 0 and rs.ready_replicas == 0 and rs.key in self.store.replicasets:
                self.store.delete_workload("ReplicaSet", rs.key)

    def tick(self) -> None:
        for d in list(self.store.deployments.values()):
            self.sync(d)


class JobController:
    """job_controller.go — syncJob: keep min(parallelism, remaining) pods
    active until `completions` pods have succeeded."""

    def __init__(self, store: ClusterStore):
        self.store = store
        self._seq = itertools.count()

    def sync(self, job: t.Job) -> None:
        owned = [
            p
            for p in self.store.pods.values()
            if p.namespace == job.namespace
            and any(r.uid == job.uid for r in p.owner_references)
        ]
        succeeded = sum(1 for p in owned if p.phase == t.PHASE_SUCCEEDED)
        active = [p for p in owned if not _is_finished(p)]
        want_active = min(job.parallelism, max(0, job.completions - succeeded))
        owner = t.OwnerReference(kind="Job", name=job.name, uid=job.uid)
        for _ in range(want_active - len(active)):
            name = f"{job.name}-{next(self._seq):05d}"
            tmpl = job.template or t.Pod(name="x", run_seconds=1.0)
            self.store.add_pod(_stamp(tmpl, name, job.namespace, owner))
        for p in active[want_active:] if want_active < len(active) else []:
            self.store.delete_pod(p.uid)
        if succeeded != job.succeeded or len(active) != job.active:
            self.store.update_workload(
                "Job", replace(job, succeeded=succeeded, active=len(active))
            )

    def tick(self) -> None:
        for job in list(self.store.jobs.values()):
            self.sync(job)


class GarbageCollector:
    """garbagecollector/ — the dependency graph reduced to one cascading rule:
    an object whose controller ownerReference names a vanished uid is deleted.
    Covers Deployment→ReplicaSet→Pod and Job→Pod chains transitively (a pass
    per level; tick until quiescent for full cascades)."""

    def __init__(self, store: ClusterStore):
        self.store = store

    def _live_uids(self) -> set:
        live = set()
        for table in self.store.objects.values():
            for obj in table.values():
                uid = getattr(obj, "uid", "")
                if uid:
                    live.add(uid)
        # pods and nodes can own objects too (EndpointSlice<-Service is the
        # common case, but Pod- and Node-owned objects exist in the reference)
        for pod in self.store.pods.values():
            live.add(pod.uid)
        for name in self.store.nodes:
            live.add(f"node/{name}")
        return live

    def tick(self) -> int:
        """One pass; returns number of objects deleted.  Covers every
        registered kind (CRDs included) whose objects carry owner_references,
        then pods — the reference GC's dependency graph walks all GVRs the
        same way (garbagecollector/graph_builder.go monitors every
        deletable resource)."""
        deleted = 0
        live = self._live_uids()
        for kind in list(self.store.objects):
            for obj in list(self.store.objects[kind].values()):
                refs = getattr(obj, "owner_references", ())
                ctrl = next((r for r in refs if r.controller), None)
                if ctrl is not None and ctrl.uid not in live:
                    self.store.delete_object(kind, _key_of(obj))
                    deleted += 1
        live = self._live_uids()
        for pod in list(self.store.pods.values()):
            ctrl = _controller_of(pod)
            if ctrl is not None and ctrl.uid not in live:
                self.store.delete_pod(pod.uid)
                deleted += 1
        return deleted


class ControllerManager:
    """cmd/kube-controller-manager — runs the controller set; tick() is one
    reconcile round across all of them (deployment before replicaset so a
    rollout's RS scaling lands in the same round)."""

    def __init__(self, store: ClusterStore):
        self.store = store
        self.deployments = DeploymentController(store)
        self.replicasets = ReplicaSetController(store)
        self.jobs = JobController(store)
        self.gc = GarbageCollector(store)

    def tick(self) -> None:
        self.deployments.tick()
        self.replicasets.tick()
        self.jobs.tick()
        self.gc.tick()

    def tick_until_quiescent(self, max_rounds: int = 20) -> None:
        for _ in range(max_rounds):
            before = self.store._rv
            self.tick()
            if self.store._rv == before:
                return
